#!/usr/bin/env python
"""JSON-lines front for the stencil server (stdio or TCP socket).

One long-lived process hosts a :class:`yask_tpu.serve.StencilServer`;
clients speak newline-delimited JSON.  Every request line is an object
with an ``op`` and an optional client-chosen ``id`` echoed back on the
response line; responses carry ``ok: true`` or ``ok: false`` +
``error``.

Ops::

    {"op": "open", "stencil": "iso3dfd", "radius": 2, "g": 16,
     "mode": "jit", "wf": 2, "options": "", "session": null}
        -> {"ok": true, "sid": "s0000"}
    {"op": "fill", "sid": ..., "var": "vel", "value": 0.5}
    {"op": "fill", "sid": ..., "var": "pressure",
     "first": [0,0,0,0], "last": [0,15,15,15],
     "data": [...flat...], "shape": [1,16,16,16], "dtype": "float32"}
    {"op": "read", "sid": ..., "var": ..., "first": [...], "last": [...]}
    {"op": "init", "sid": ...}          # init_solution_vars
    {"op": "prewarm", "sid": ..., "steps": 8}
    {"op": "run", "sid": ..., "first": 0, "last": 3, "outputs": [],
     "flush_every": 0, "stream_outputs": false}
    {"op": "run_many", "requests": [{"sid":..., "first":..., "last":...,
                                     "outputs": []}, ...]}
        # submit-all-then-wait-all: the shape that actually exercises
        # the micro-batching window
    {"op": "metrics"} / {"op": "flush_metrics"} / {"op": "cache_stats"}
    {"op": "ping"}                      # liveness heartbeat (fleet
                                        # supervision; cheap, no device
                                        # work)
    {"op": "snapshot", "sid": ...}      # interior-coordinate checkpoint
        -> {"ok": true, "meta": {...}, "state": {var: [slot...]}}
    {"op": "restore", "sid": ..., "meta": {...}, "state": {...}}
    {"op": "close", "sid": ...}
    {"op": "shutdown"}

``open`` takes an optional ``bucket`` (true/false/null = the
``YT_SERVE_BUCKETING`` default) — shape-bucket co-batching per
``yask_tpu/serve/buckets.py``.

**Streaming**: a ``run``/``run_many`` with ``flush_every > 0`` emits
interleaved ``{"stream": true, "id": ..., "sid": ..., "step": ...}``
lines on the SAME connection as each chunk boundary flushes (with the
partial interiors when ``stream_outputs`` is set), BEFORE the final
response line.  Clients must collect/skip ``stream`` lines until a
line without ``"stream"`` arrives — ``tools/serve_client.py`` does.

Arrays cross the wire as ``{"shape": [...], "dtype": "float32",
"data": [flat row-major floats]}``.  float32 values round-trip EXACTLY
through JSON doubles, so the bit-identity self-checks in
``tools/serve_client.py`` / ``examples/serve_sweep_main.py`` hold
across the process boundary.

This front performs no device work itself — every op is a
``StencilServer`` method call (the guarded sites live inside the
serve package), which is also what keeps the BARE-DEVICE-CALL lint
closure clean here.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _encode_array(a) -> dict:
    a = np.asarray(a)
    return {"shape": list(a.shape), "dtype": str(a.dtype),
            "data": [float(x) for x in a.ravel().tolist()]}


def _decode_array(d: dict):
    return np.asarray(d["data"],
                      dtype=np.dtype(d.get("dtype", "float32"))
                      ).reshape(d.get("shape", [-1]))


def _worker_chaos() -> None:
    """YT_FAULT_PLAN chaos hooks for the fleet supervision tests.  An
    injected ``worker_dead`` at site ``fleet.kill_worker`` hard-exits
    the worker process (SIGKILL semantics: no cleanup, no reply on the
    pipe — exactly what a crashed worker looks like to the front); a
    ``hang`` at ``fleet.hang_worker`` stalls it past the front's
    liveness deadline.  Probed at op entry, at every chunk-boundary
    stream flush (so a kill can land MID-run), and on ``ping``."""
    from yask_tpu.resilience.faults import WorkerDead, fault_point
    try:
        fault_point("fleet.kill_worker")
    except WorkerDead:
        os._exit(17)
    fault_point("fleet.hang_worker")


def _encode_stream_event(ev: dict) -> dict:
    out = {"step": ev.get("step")}
    if "outputs" in ev:
        out["outputs"] = {k: _encode_array(v)
                          for k, v in ev["outputs"].items()}
    return out


def _encode_response(resp) -> dict:
    out = {"ok": resp.ok, "rid": resp.rid, "session": resp.session,
           "status": resp.status, "batch": resp.batch,
           "batched": resp.batched, "mode": resp.mode,
           "degraded": resp.degraded,
           "queue_secs": resp.queue_secs, "run_secs": resp.run_secs,
           "compile_secs": resp.compile_secs,
           "cache_hit": resp.cache_hit,
           "outputs": {k: _encode_array(v)
                       for k, v in resp.outputs.items()}}
    if resp.error:
        out["error"] = resp.error
    if resp.anomaly:
        out["anomaly"] = resp.anomaly
    if resp.bucket:
        out["bucket"] = resp.bucket
    if resp.preempted:
        out["preempted"] = int(resp.preempted)
    if resp.streams:
        out["streams"] = [_encode_stream_event(e) for e in resp.streams]
    if resp.trace:
        out["trace"] = resp.trace
    return out


class ServeFront:
    """Dispatch table from wire ops to server methods."""

    #: ops that may emit interleaved ``{"stream": true}`` lines.
    _STREAMING_OPS = ("run", "run_many")

    def __init__(self, server):
        self.server = server
        self.closing = threading.Event()

    def handle(self, msg: dict, emit=None) -> dict:
        op = msg.get("op")
        fn = getattr(self, f"op_{op}", None)
        if fn is None:
            return {"ok": False, "error": f"unknown op {op!r}"}
        from yask_tpu.obs.tracer import activate
        try:
            # a front-stamped trace id rides the wire msg; activating it
            # here makes every journal row / span this op produces join
            # the SAME end-to-end trace ("" = no-op passthrough)
            with activate(msg.get("trace", "")):
                out = fn(msg, emit) if op in self._STREAMING_OPS \
                    else fn(msg)
        except Exception as e:  # noqa: BLE001 - the front must answer
            out = {"ok": False,
                   "error": f"{type(e).__name__}: {e}"}
        if "id" in msg:
            out["id"] = msg["id"]
        return out

    def op_open(self, msg):
        from yask_tpu.serve.api import Overloaded
        try:
            sid = self.server.open_session(
                stencil=msg["stencil"], radius=msg.get("radius"),
                g=msg.get("g", 16), mode=msg.get("mode", "jit"),
                wf=int(msg.get("wf", 2)),
                options=msg.get("options", ""),
                session=msg.get("session"), bucket=msg.get("bucket"))
        except Overloaded as e:
            # brownout tier 2 / saturation: a STRUCTURED rejection —
            # clients key on "overloaded" and honor the Retry-After
            # hint instead of parsing the error string
            return {"ok": False, "error": f"Overloaded: {e}",
                    "overloaded": True,
                    "retry_after": float(e.retry_after)}
        return {"ok": True, "sid": sid}

    def op_fill(self, msg):
        if "value" in msg:
            self.server.set_var(msg["sid"], msg["var"],
                                float(msg["value"]))
            return {"ok": True}
        n = self.server.set_var_slice(
            msg["sid"], msg["var"], _decode_array(msg),
            msg["first"], msg["last"])
        return {"ok": True, "elements": int(n)}

    def op_read(self, msg):
        buf = self.server.get_var_slice(msg["sid"], msg["var"],
                                        msg["first"], msg["last"])
        return {"ok": True, **_encode_array(buf)}

    def op_init(self, msg):
        self.server.init_vars(msg["sid"])
        return {"ok": True}

    def op_prewarm(self, msg):
        n = self.server.prewarm(msg["sid"], int(msg.get("steps", 1)))
        return {"ok": True, "chunks": int(n)}

    def _req(self, m):
        from yask_tpu.obs.tracer import current_trace_id
        from yask_tpu.serve import ServeRequest
        return ServeRequest(session=m["sid"],
                            first_step=int(m["first"]),
                            last_step=(None if m.get("last") is None
                                       else int(m["last"])),
                            outputs=tuple(m.get("outputs", ())),
                            deadline_secs=float(m.get("deadline", 0.0)),
                            flush_every=int(m.get("flush_every", 0)),
                            stream_outputs=bool(
                                m.get("stream_outputs", False)),
                            trace=m.get("trace")
                            or current_trace_id())

    @staticmethod
    def _stream_hook(emit, sid, rid):
        """The per-request flush hook: push one ``{"stream": true}``
        line.  Defensive — a dropped client must cost the beacon, not
        the run (the scheduler's flush policy, extended to the wire)."""
        def push(ev):
            _worker_chaos()  # a chaos kill lands at a chunk boundary
            line = {"stream": True, "sid": sid,
                    **_encode_stream_event(ev)}
            if rid is not None:
                line["id"] = rid
            try:
                emit(line)
            except Exception:  # noqa: BLE001
                pass
        return push

    def op_run(self, msg, emit=None):
        _worker_chaos()
        req = self._req(msg)
        hook = None
        if emit is not None and req.flush_every > 0:
            hook = self._stream_hook(emit, req.session, msg.get("id"))
        h = self.server.submit(req, on_stream=hook)
        return _encode_response(
            self.server.wait(h, timeout=msg.get("timeout")))

    def op_run_many(self, msg, emit=None):
        # submit EVERYTHING before waiting on anything — this is what
        # lands compatible requests inside one batching window
        handles = []
        for m in msg["requests"]:
            req = self._req(m)
            hook = None
            if emit is not None and req.flush_every > 0:
                hook = self._stream_hook(emit, req.session,
                                         msg.get("id"))
            handles.append(self.server.submit(req, on_stream=hook))
        resps = [self.server.wait(h, timeout=msg.get("timeout"))
                 for h in handles]
        return {"ok": True,
                "responses": [_encode_response(r) for r in resps]}

    def op_ping(self, msg):
        _worker_chaos()
        return {"ok": True, "pid": os.getpid(),
                "sessions": len(self.server.registry.sessions())}

    def op_snapshot(self, msg):
        snap = self.server.snapshot(msg["sid"])
        return {"ok": True, "meta": snap["meta"],
                "state": {k: [_encode_array(a) for a in ring]
                          for k, ring in snap["state"].items()}}

    def op_restore(self, msg):
        snap = {"meta": msg["meta"],
                "state": {k: [_decode_array(d) for d in ring]
                          for k, ring in msg["state"].items()}}
        ok = self.server.restore(msg["sid"], snap)
        out = {"ok": bool(ok)}
        if not ok:
            out["error"] = "snapshot did not apply (identity mismatch)"
        return out

    def op_metrics(self, msg):
        return {"ok": True, "metrics": self.server.metrics()}

    def op_metrics_snapshot(self, msg):
        # the fleet front's telemetry poll: the full registry snapshot
        # (raw histogram windows included — the merge pools samples,
        # it never averages percentiles) + occupancy/cache/journal/SLO
        return {"ok": True, "snapshot": self.server.metrics_snapshot()}

    def op_cache_stats(self, msg):
        from yask_tpu.cache import cache_dir, stats
        return {"ok": True, "stats": stats(),
                "cache_dir": cache_dir()}

    def op_flush_metrics(self, msg):
        rows = self.server.flush_metrics()
        return {"ok": True, "rows": len(rows)}

    def op_close(self, msg):
        self.server.close_session(msg["sid"])
        return {"ok": True}

    def op_shutdown(self, msg):
        self.closing.set()
        return {"ok": True}


def _serve_stream(front: ServeFront, rfile, wfile) -> None:
    """One JSON-lines conversation (stdio, or one socket client).
    Stream events fire from the scheduler's worker thread while this
    thread blocks in ``wait``, so all writes go through one lock."""
    wlock = threading.Lock()

    def emit(obj: dict) -> None:
        with wlock:
            wfile.write(json.dumps(obj, sort_keys=True) + "\n")
            wfile.flush()

    for line in rfile:
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
        except ValueError as e:
            out = {"ok": False, "error": f"bad JSON: {e}"}
        else:
            out = front.handle(msg, emit=emit)
        emit(out)
        if front.closing.is_set():
            return


def _serve_socket(front: ServeFront, host: str, port: int) -> None:
    srv = socket.create_server((host, port))
    srv.settimeout(0.5)
    sys.stderr.write(f"serve: listening on {host}:{srv.getsockname()[1]}\n")
    sys.stderr.flush()
    threads = []
    try:
        while not front.closing.is_set():
            try:
                conn, _addr = srv.accept()
            except socket.timeout:
                continue
            rfile = conn.makefile("r", encoding="utf-8")
            wfile = conn.makefile("w", encoding="utf-8")
            t = threading.Thread(target=_serve_stream,
                                 args=(front, rfile, wfile),
                                 daemon=True)
            t.start()
            threads.append(t)
    finally:
        srv.close()
        for t in threads:
            t.join(timeout=2.0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="JSON-lines stencil-serving front")
    ap.add_argument("--port", type=int, default=None,
                    help="listen on a TCP port (default: stdio)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--window_ms", type=float, default=None,
                    help="batching window override (YT_SERVE_WINDOW_MS)")
    ap.add_argument("--max_batch", type=int, default=None,
                    help="occupancy cap override (YT_SERVE_MAX_BATCH)")
    ap.add_argument("--journal", default=None,
                    help="serve journal path (YT_SERVE_JOURNAL)")
    ap.add_argument("--no-preflight", action="store_true",
                    help="skip the checker's serve pass on open_session")
    args = ap.parse_args(argv)

    from yask_tpu.serve import StencilServer
    server = StencilServer(
        journal_path=args.journal,
        window_secs=(None if args.window_ms is None
                     else args.window_ms / 1000.0),
        max_batch=args.max_batch,
        preflight=not args.no_preflight)
    front = ServeFront(server)
    try:
        if args.port is not None:
            _serve_socket(front, args.host, args.port)
        else:
            sys.stderr.write("serve: ready (stdio)\n")
            sys.stderr.flush()
            _serve_stream(front, sys.stdin, sys.stdout)
    finally:
        server.flush_metrics()
        server.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
