#!/usr/bin/env python
"""Render TRACE_EVENTS.jsonl: per-phase breakdown + Perfetto export.

Reads the span rows the obs tracer appends (schema ``yask_tpu.trace/1``,
see ``yask_tpu/obs/tracer.py``) and answers the two questions a trace
exists for:

* **Where did the time go?**  The terminal report buckets spans by
  phase using SELF-TIME attribution — each span's duration minus the
  durations of its direct children in the same trace — so nested spans
  (``guard:run.chunk`` inside ``serve.chunk`` inside
  ``run.supervised``) are not double-counted, and queue-wait and
  exchange show up as their own lines instead of hiding inside
  compute.  Retroactive ``halo.share`` spans (the measured exchange
  fraction of a fused program call — the exchange runs INSIDE the
  jitted scan, so it cannot be a nested child) are additionally moved
  out of the compute bucket.  Halo-calibration instability
  (``halo_cal`` spans with ``unstable: true``) is surfaced in the
  table — an unstable split means the exchange line is noise, not a
  datum.
* **What did it look like?**  ``--perfetto OUT`` writes Chrome
  trace-event JSON (``{"traceEvents": [...]}``, ``ph: "X"`` complete
  events, µs timestamps): load it in ui.perfetto.dev or
  chrome://tracing.  One lane per (pid, tid) — the fleet front, each
  worker process, and the scheduler's device thread land on separate
  rows, aligned on wall-clock ``ts``.

Usage::

    python tools/obs_report.py                      # latest trace
    python tools/obs_report.py --trace t4f2ab...    # one trace
    python tools/obs_report.py --trace all          # everything
    python tools/obs_report.py --perfetto out.json  # + Perfetto dump
    python tools/obs_report.py --attribution        # measured-vs-
                                                    #   modeled table
    python tools/obs_report.py --bank               # bank one
                                                    #   attribution row
    python -m yask_tpu.tools.log_to_csv --traces    # flat CSV instead

The span math (``pick_trace`` / ``self_times`` / ``phase_breakdown`` /
``halo_cal_status``) lives in ``yask_tpu.obs.attribution`` and is
re-exported here — one implementation for the terminal report, the CSV
exporter, and the attribution ledger rows.

No device work, no jax import — safe to run anywhere, any time.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yask_tpu.obs.attribution import (  # noqa: F401  (re-exports)
    halo_cal_status,
    phase_breakdown,
    pick_trace,
    self_times,
)
from yask_tpu.obs.tracer import PHASES, default_trace_path, read_spans


def report(rows: List[Dict], top: int = 10, out=None) -> None:
    out = out or sys.stdout
    if not rows:
        out.write("no spans\n")
        return
    traces = sorted({r.get("trace", "") for r in rows})
    pids = sorted({r.get("pid", 0) for r in rows})
    t0 = min(float(r.get("ts", 0.0)) for r in rows)
    t1 = max(float(r.get("ts", 0.0)) + float(r.get("dur", 0.0))
             for r in rows)
    out.write(f"trace: {', '.join(traces)}\n")
    out.write(f"spans: {len(rows)}  processes: {len(pids)}  "
              f"wall: {t1 - t0:.4f} s\n\n")

    bk = phase_breakdown(rows)
    total = sum(b["secs"] for b in bk.values()) or 1.0
    order = [p for p in PHASES if p in bk] \
        + sorted(set(bk) - set(PHASES))
    out.write(f"{'phase':<12} {'self-time':>10} {'%':>6} {'spans':>6}\n")
    for ph in order:
        b = bk[ph]
        out.write(f"{ph:<12} {b['secs']:>9.4f}s "
                  f"{100.0 * b['secs'] / total:>5.1f}% "
                  f"{b['count']:>6}\n")
    moved = bk.get("compute", {}).get("halo_share_moved", 0.0)
    if moved:
        out.write(f"  (exchange evidence: {moved:.4f}s halo.share "
                  "moved out of compute)\n")
    hc = halo_cal_status(rows)
    if hc["count"]:
        flag = (f"UNSTABLE x{hc['unstable']}" if hc["unstable"]
                else "stable")
        out.write(f"halo-cal: {flag}  reps={hc['reps']} "
                  f"max_spread={hc['max_spread']:.3f}\n")

    out.write(f"\ntop {min(top, len(rows))} spans by duration:\n")
    for r in sorted(rows, key=lambda r: -float(r.get("dur", 0.0)))[:top]:
        attrs = json.dumps(r.get("attrs", {}), sort_keys=True)
        if len(attrs) > 60:
            attrs = attrs[:57] + "..."
        out.write(f"  {float(r.get('dur', 0.0)):>9.4f}s "
                  f"{(r.get('phase') or '-'):<10} "
                  f"{r.get('name', '?'):<24} {attrs}\n")


def counter_events(rows: List[Dict]) -> List[Dict]:
    """Counter tracks (``ph: "C"``) derived from the span stream, so
    Perfetto shows LOAD on the same timeline as latency:

    * ``serve.batch_occupancy`` — each ``serve.chunk`` span's ``batch``
      attr, raised at the chunk start and dropped back to 0 at its end;
    * ``serve.queue_depth`` — the number of concurrently open
      ``serve.queue_wait`` intervals, stepped at each edge.

    Both are per-pid tracks (a fleet trace gets one pair per worker)."""
    events: List[Dict] = []
    for r in rows:
        if r.get("name") != "serve.chunk":
            continue
        ts = float(r.get("ts", 0.0)) * 1e6
        dur = float(r.get("dur", 0.0)) * 1e6
        pid = r.get("pid", 0)
        occ = r.get("attrs", {}).get("batch", 1)
        events.append({"ph": "C", "name": "serve.batch_occupancy",
                       "ts": ts, "pid": pid, "tid": 0,
                       "args": {"occupancy": occ}})
        events.append({"ph": "C", "name": "serve.batch_occupancy",
                       "ts": ts + dur, "pid": pid, "tid": 0,
                       "args": {"occupancy": 0}})
    edges: List[tuple] = []
    for r in rows:
        if r.get("name") != "serve.queue_wait":
            continue
        ts = float(r.get("ts", 0.0)) * 1e6
        pid = r.get("pid", 0)
        edges.append((ts, 1, pid))
        edges.append((ts + float(r.get("dur", 0.0)) * 1e6, -1, pid))
    depth: Dict[int, int] = {}
    for ts, d, pid in sorted(edges):
        depth[pid] = depth.get(pid, 0) + d
        events.append({"ph": "C", "name": "serve.queue_depth",
                       "ts": ts, "pid": pid, "tid": 0,
                       "args": {"depth": depth[pid]}})
    return events


def to_perfetto(rows: List[Dict]) -> Dict:
    """Chrome trace-event JSON: ``ph: "X"`` complete events in µs on
    the wall clock, one lane per (pid, tid), phase as the category,
    span/trace ids + attrs in ``args``; plus the derived ``ph: "C"``
    load counter tracks (:func:`counter_events`)."""
    events: List[Dict] = []
    for pid in sorted({r.get("pid", 0) for r in rows}):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0,
                       "args": {"name": f"yask_tpu pid {pid}"}})
    for r in rows:
        events.append({
            "ph": "X",
            "name": r.get("name", "?"),
            "cat": r.get("phase") or "other",
            "ts": float(r.get("ts", 0.0)) * 1e6,
            "dur": float(r.get("dur", 0.0)) * 1e6,
            "pid": r.get("pid", 0),
            "tid": r.get("tid", 0),
            "args": {"trace": r.get("trace", ""),
                     "span": r.get("span", ""),
                     "parent": r.get("parent", ""),
                     **r.get("attrs", {})},
        })
    events.extend(counter_events(rows))
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"schema": "yask_tpu.trace/1"}}


def attribution_report(ledger_rows: List[Dict], top: int = 10,
                       out=None) -> int:
    """Render the ``source: "attribution"`` ledger rows as a
    measured-vs-modeled table, worst-efficiency phases first.
    Quarantined and halo-cal-unstable rows are excluded (their wall
    time attributes nothing / their exchange split is noise).  Returns
    the number of attribution rows rendered."""
    out = out or sys.stdout
    rows = [r for r in ledger_rows
            if r.get("source") == "attribution"
            and not r.get("quarantined")]
    kept = [r for r in rows
            if not (r.get("extra") or {}).get("halo_cal_unstable")]
    if not kept:
        out.write("no attribution rows\n")
        return 0
    entries = []
    for r in kept:
        ex = r.get("extra") or {}
        for ph, d in sorted((ex.get("phases") or {}).items()):
            entries.append((d.get("efficiency"), r, ph, d))
    # worst efficiency first; phases with no model sort last
    entries.sort(key=lambda t: (t[0] is None, t[0] or 0.0))
    out.write(f"{'key':<28} {'phase':<12} {'measured':>10} "
              f"{'modeled':>10} {'eff':>6} {'share':>6}\n")
    for eff, r, ph, d in entries[:top]:
        drift = (r.get("guard") or {}).get("status") == "drift"
        out.write(f"{r.get('key', '?')[:28]:<28} {ph:<12} "
                  f"{d.get('measured_secs', 0.0):>9.4f}s "
                  f"{('%9.4fs' % d['modeled_secs']) if 'modeled_secs' in d else '        -':>10} "
                  f"{('%5.2f' % eff) if eff is not None else '    -':>6} "
                  f"{d.get('share', 0.0):>6.2f}"
                  f"{'  DRIFT' if drift else ''}\n")
    skipped = len(rows) - len(kept)
    if skipped:
        out.write(f"({skipped} halo-cal-unstable row(s) excluded)\n")
    return len(kept)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="per-phase breakdown + Perfetto export of the "
                    "obs span trace")
    ap.add_argument("--path", default=None,
                    help="trace file (default: YT_TRACE_EVENTS or "
                         "repo-root TRACE_EVENTS.jsonl)")
    ap.add_argument("--trace", default="",
                    help="trace id to report ('all' = every trace; "
                         "default: the latest)")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest-span list length")
    ap.add_argument("--perfetto", default=None, metavar="OUT",
                    help="also write Chrome/Perfetto trace-event JSON")
    ap.add_argument("--attribution", action="store_true",
                    help="render the measured-vs-modeled attribution "
                         "table from the perf ledger instead of the "
                         "span report")
    ap.add_argument("--bank", action="store_true",
                    help="join the trace against its perf-ledger row "
                         "and bank one source:'attribution' row first")
    ap.add_argument("--ledger", default=None,
                    help="perf ledger path (default: YT_PERF_LEDGER "
                         "or repo-root PERF_LEDGER.jsonl)")
    args = ap.parse_args(argv)

    if args.bank:
        from yask_tpu.obs.attribution import attribute_and_bank
        row = attribute_and_bank(trace=("" if args.trace == "all"
                                        else args.trace),
                                 events_path=args.path,
                                 ledger_path=args.ledger)
        if row is None:
            sys.stdout.write("attribution: nothing banked (empty "
                             "trace or quarantined perf row)\n")
        else:
            sys.stdout.write(f"attribution: banked {row['key']!r} "
                             f"trace={row['extra']['trace']}\n")
    if args.attribution:
        from yask_tpu.perflab.ledger import read_rows
        n = attribution_report(read_rows(path=args.ledger),
                               top=args.top)
        return 0 if n else 1

    rows = pick_trace(read_spans(args.path or default_trace_path()),
                      args.trace)
    report(rows, top=args.top)
    if args.perfetto:
        with open(args.perfetto, "w") as f:
            json.dump(to_perfetto(rows), f, sort_keys=True)
        sys.stdout.write(f"\nperfetto: {args.perfetto} "
                         f"({len(rows)} events)\n")
    return 0 if rows else 1


if __name__ == "__main__":
    sys.exit(main())
