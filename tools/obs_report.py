#!/usr/bin/env python
"""Render TRACE_EVENTS.jsonl: per-phase breakdown + Perfetto export.

Reads the span rows the obs tracer appends (schema ``yask_tpu.trace/1``,
see ``yask_tpu/obs/tracer.py``) and answers the two questions a trace
exists for:

* **Where did the time go?**  The terminal report buckets spans by
  phase using SELF-TIME attribution — each span's duration minus the
  durations of its direct children in the same trace — so nested spans
  (``guard:run.chunk`` inside ``serve.chunk`` inside
  ``run.supervised``) are not double-counted, and queue-wait and
  exchange show up as their own lines instead of hiding inside
  compute.  Retroactive ``halo.share`` spans (the measured exchange
  fraction of a fused program call — the exchange runs INSIDE the
  jitted scan, so it cannot be a nested child) are additionally moved
  out of the compute bucket.  Halo-calibration instability
  (``halo_cal`` spans with ``unstable: true``) is surfaced in the
  table — an unstable split means the exchange line is noise, not a
  datum.
* **What did it look like?**  ``--perfetto OUT`` writes Chrome
  trace-event JSON (``{"traceEvents": [...]}``, ``ph: "X"`` complete
  events, µs timestamps): load it in ui.perfetto.dev or
  chrome://tracing.  One lane per (pid, tid) — the fleet front, each
  worker process, and the scheduler's device thread land on separate
  rows, aligned on wall-clock ``ts``.

Usage::

    python tools/obs_report.py                      # latest trace
    python tools/obs_report.py --trace t4f2ab...    # one trace
    python tools/obs_report.py --trace all          # everything
    python tools/obs_report.py --perfetto out.json  # + Perfetto dump
    python -m yask_tpu.tools.log_to_csv --traces    # flat CSV instead

No device work, no jax import — safe to run anywhere, any time.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yask_tpu.obs.tracer import PHASES, default_trace_path, read_spans


def pick_trace(rows: List[Dict], trace: str = "") -> List[Dict]:
    """Filter rows to one trace id; default = the LATEST trace (the one
    whose newest span has the greatest wall ts); ``"all"`` keeps every
    row."""
    if trace == "all":
        return list(rows)
    if not trace:
        latest: Dict[str, float] = {}
        for r in rows:
            t = r.get("trace", "")
            latest[t] = max(latest.get(t, 0.0), float(r.get("ts", 0.0)))
        if not latest:
            return []
        trace = max(latest, key=lambda t: latest[t])
    return [r for r in rows if r.get("trace") == trace]


def self_times(rows: List[Dict]) -> Dict[str, float]:
    """span id → duration minus direct children's durations (floored
    at 0 — children on other threads can overlap their parent)."""
    child_dur: Dict[str, float] = {}
    for r in rows:
        p = r.get("parent", "")
        if p:
            child_dur[p] = child_dur.get(p, 0.0) + float(r.get("dur", 0.0))
    return {r["span"]: max(0.0, float(r.get("dur", 0.0))
                           - child_dur.get(r.get("span", ""), 0.0))
            for r in rows if "span" in r}


def phase_breakdown(rows: List[Dict]) -> Dict[str, Dict]:
    """Per-phase ``{secs, count}`` from self-times, with ``halo.share``
    exchange evidence moved out of the compute bucket (it measures a
    slice of a compute span's interval, not a nested child)."""
    selfs = self_times(rows)
    out: Dict[str, Dict] = {}
    halo_share = 0.0
    for r in rows:
        ph = r.get("phase") or "other"
        b = out.setdefault(ph, {"secs": 0.0, "count": 0})
        b["secs"] += selfs.get(r.get("span", ""), 0.0)
        b["count"] += 1
        if r.get("name") == "halo.share":
            halo_share += float(r.get("dur", 0.0))
    if halo_share > 0 and "compute" in out:
        out["compute"]["secs"] = max(
            0.0, out["compute"]["secs"] - halo_share)
        out["compute"]["halo_share_moved"] = halo_share
    return out


def halo_cal_status(rows: List[Dict]) -> Dict:
    """Aggregate the halo-calibration spans: rep/spread evidence plus
    whether any calibration came out UNSTABLE (ledger parity — an
    unstable split is noise, not a halo datum)."""
    cals = [r for r in rows if r.get("name") == "halo_cal"]
    att = [r.get("attrs", {}) for r in cals]
    return {
        "count": len(cals),
        "reps": sum(int(a.get("reps", 0) or 0) for a in att),
        "max_spread": max([float(a.get("spread", 0.0) or 0.0)
                           for a in att] or [0.0]),
        "unstable": sum(1 for a in att if a.get("unstable")),
    }


def report(rows: List[Dict], top: int = 10, out=None) -> None:
    out = out or sys.stdout
    if not rows:
        out.write("no spans\n")
        return
    traces = sorted({r.get("trace", "") for r in rows})
    pids = sorted({r.get("pid", 0) for r in rows})
    t0 = min(float(r.get("ts", 0.0)) for r in rows)
    t1 = max(float(r.get("ts", 0.0)) + float(r.get("dur", 0.0))
             for r in rows)
    out.write(f"trace: {', '.join(traces)}\n")
    out.write(f"spans: {len(rows)}  processes: {len(pids)}  "
              f"wall: {t1 - t0:.4f} s\n\n")

    bk = phase_breakdown(rows)
    total = sum(b["secs"] for b in bk.values()) or 1.0
    order = [p for p in PHASES if p in bk] \
        + sorted(set(bk) - set(PHASES))
    out.write(f"{'phase':<12} {'self-time':>10} {'%':>6} {'spans':>6}\n")
    for ph in order:
        b = bk[ph]
        out.write(f"{ph:<12} {b['secs']:>9.4f}s "
                  f"{100.0 * b['secs'] / total:>5.1f}% "
                  f"{b['count']:>6}\n")
    moved = bk.get("compute", {}).get("halo_share_moved", 0.0)
    if moved:
        out.write(f"  (exchange evidence: {moved:.4f}s halo.share "
                  "moved out of compute)\n")
    hc = halo_cal_status(rows)
    if hc["count"]:
        flag = (f"UNSTABLE x{hc['unstable']}" if hc["unstable"]
                else "stable")
        out.write(f"halo-cal: {flag}  reps={hc['reps']} "
                  f"max_spread={hc['max_spread']:.3f}\n")

    out.write(f"\ntop {min(top, len(rows))} spans by duration:\n")
    for r in sorted(rows, key=lambda r: -float(r.get("dur", 0.0)))[:top]:
        attrs = json.dumps(r.get("attrs", {}), sort_keys=True)
        if len(attrs) > 60:
            attrs = attrs[:57] + "..."
        out.write(f"  {float(r.get('dur', 0.0)):>9.4f}s "
                  f"{(r.get('phase') or '-'):<10} "
                  f"{r.get('name', '?'):<24} {attrs}\n")


def to_perfetto(rows: List[Dict]) -> Dict:
    """Chrome trace-event JSON: ``ph: "X"`` complete events in µs on
    the wall clock, one lane per (pid, tid), phase as the category,
    span/trace ids + attrs in ``args``."""
    events: List[Dict] = []
    for pid in sorted({r.get("pid", 0) for r in rows}):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0,
                       "args": {"name": f"yask_tpu pid {pid}"}})
    for r in rows:
        events.append({
            "ph": "X",
            "name": r.get("name", "?"),
            "cat": r.get("phase") or "other",
            "ts": float(r.get("ts", 0.0)) * 1e6,
            "dur": float(r.get("dur", 0.0)) * 1e6,
            "pid": r.get("pid", 0),
            "tid": r.get("tid", 0),
            "args": {"trace": r.get("trace", ""),
                     "span": r.get("span", ""),
                     "parent": r.get("parent", ""),
                     **r.get("attrs", {})},
        })
    return {"traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"schema": "yask_tpu.trace/1"}}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="per-phase breakdown + Perfetto export of the "
                    "obs span trace")
    ap.add_argument("--path", default=None,
                    help="trace file (default: YT_TRACE_EVENTS or "
                         "repo-root TRACE_EVENTS.jsonl)")
    ap.add_argument("--trace", default="",
                    help="trace id to report ('all' = every trace; "
                         "default: the latest)")
    ap.add_argument("--top", type=int, default=10,
                    help="slowest-span list length")
    ap.add_argument("--perfetto", default=None, metavar="OUT",
                    help="also write Chrome/Perfetto trace-event JSON")
    args = ap.parse_args(argv)

    rows = pick_trace(read_spans(args.path or default_trace_path()),
                      args.trace)
    report(rows, top=args.top)
    if args.perfetto:
        with open(args.perfetto, "w") as f:
            json.dump(to_perfetto(rows), f, sort_keys=True)
        sys.stdout.write(f"\nperfetto: {args.perfetto} "
                         f"({len(rows)} events)\n")
    return 0 if rows else 1


if __name__ == "__main__":
    sys.exit(main())
