#!/bin/bash
# Probe the axon TPU relay every ~3 min; run the first-session protocol
# the moment it answers (the relay window has been short all round —
# CLAUDE.md "Environment gotchas").  One-shot: exits after one session.
LOG=${1:-/tmp/tpu_session_auto.log}
while true; do
    if timeout 100 python - <<'EOF' >/dev/null 2>&1
import subprocess, sys
# require the axon/TPU backend, not a CPU fallback — otherwise the
# one-shot session would be burned on CPU (bench.py _probe_platform
# does the same check)
r = subprocess.run(
    [sys.executable, "-c",
     "import jax; import sys; sys.exit(0 if jax.default_backend() in "
     "('axon', 'tpu') else 3)"],
    capture_output=True, timeout=90)
sys.exit(r.returncode)
EOF
    then
        echo "$(date -u +%H:%M:%S) relay UP - running session" >> "$LOG"
        python tools/tpu_session.py -g 512 --quick >> "$LOG" 2>&1
        echo "$(date -u +%H:%M:%S) session exit $?" >> "$LOG"
        exit 0
    fi
    echo "$(date -u +%H:%M:%S) relay down" >> "$LOG"
    sleep 170
done
