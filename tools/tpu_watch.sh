#!/bin/bash
# Thin wrapper over yask_tpu.resilience.watch (the testable port of the
# old inline loop): probe the axon TPU relay every ~3 min; run the
# session protocol on EVERY window it answers (the relay windows have
# been short and rare — CLAUDE.md "Environment gotchas").  First window
# runs --quick to bank a number fast; later windows run the full
# validation matrix; windows after a drop resume from the session
# journal.  Each session's artifacts are committed IMMEDIATELY (round 3
# lost its hardware numbers by waiting for round end).
cd "$(dirname "$0")/.." || exit 1
LOG=${1:-/tmp/tpu_session_auto.log}
mkdir -p tools/logs
exec python -m yask_tpu.resilience.watch -g 512 >> "$LOG" 2>&1
