#!/bin/bash
# Probe the axon TPU relay every ~3 min; run the session protocol on
# EVERY window it answers (the relay windows have been short and rare —
# CLAUDE.md "Environment gotchas").  First window runs --quick to bank
# a number fast; later windows run the full validation matrix.  Each
# session's artifacts are committed IMMEDIATELY (round 3 lost its
# hardware numbers by waiting for round end).
cd "$(dirname "$0")/.." || exit 1
LOG=${1:-/tmp/tpu_session_auto.log}
mkdir -p tools/logs
N=0
while true; do
    if timeout 100 python - <<'EOF' >/dev/null 2>&1
import subprocess, sys
# require the axon/TPU backend, not a CPU fallback — otherwise the
# session would be burned on CPU (bench.py _probe_platform does the
# same check)
r = subprocess.run(
    [sys.executable, "-c",
     "import jax; import sys; sys.exit(0 if jax.default_backend() in "
     "('axon', 'tpu') else 3)"],
    capture_output=True, timeout=90)
sys.exit(r.returncode)
EOF
    then
        N=$((N+1))
        ARGS="-g 512 --quick"
        [ "$N" -gt 1 ] && ARGS="-g 512"
        SLOG="tools/logs/tpu_session_$(date -u +%m%d_%H%M%S).log"
        echo "$(date -u +%H:%M:%S) relay UP - session $N ($ARGS)" >> "$LOG"
        timeout 3000 python tools/tpu_session.py $ARGS > "$SLOG" 2>&1
        echo "$(date -u +%H:%M:%S) session $N exit $?" >> "$LOG"
        # Commit hardware artifacts the moment they exist.  Only the
        # session-owned paths are staged so an in-progress working tree
        # is never swept up; each pathspec is guarded (a missing
        # TPU_RESULTS.jsonl — relay dropped before the first bench line
        # — must not abort staging the session log); a transient
        # index.lock just defers the commit to the next window.
        PATHS="tools/logs"
        [ -f TPU_RESULTS.jsonl ] && PATHS="$PATHS TPU_RESULTS.jsonl"
        [ -f BENCH_suite_latest.json ] && PATHS="$PATHS BENCH_suite_latest.json"
        git add -f $PATHS 2>/dev/null
        git commit -m "TPU session $N artifacts (auto-committed by tpu_watch)" \
            --only $PATHS >/dev/null 2>&1
        sleep 60
    else
        echo "$(date -u +%H:%M:%S) relay down" >> "$LOG"
        sleep 170
    fi
done
