#!/usr/bin/env python
"""Chaos traffic-replay load harness for the serving fleet.

Drives an in-process :class:`tools.serve_fleet.ServeFleet` with an
OPEN-LOOP arrival process (arrivals fire on the wall clock whether or
not earlier requests answered — the shape that actually builds queues)
and banks the latency/goodput evidence as ``PERF_LEDGER`` rows:

* **Arrival processes** (``--arrivals``): seeded ``poisson`` /
  ``uniform`` (deterministic gaps) / ``step`` (rate doubles at the
  midpoint) / ``spike`` (a ``--spike-mult`` burst through the middle
  third).  Across tenants the loop is open; PER tenant it is closed
  (one in-flight request per session — the scheduler serializes a
  session's requests anyway, and step ranges must stay contiguous).
* **Replay** (``--replay PATH``): re-drives a recorded
  ``SERVE_JOURNAL`` — the ``received`` rows' original tenant mix and
  inter-arrival gaps (scaled by ``--replay-speed``) become the
  schedule, so a production trace reproduces under test.
* **Chaos soak** (``--soak``): one seeded ``YT_FAULT_PLAN`` composes a
  ``load.arrival`` load spike with worker-side ``fleet.kill_worker``,
  ``fleet.hang_worker`` and ``serve.respond`` zero-output corruption,
  all concurrent with the offered load.  The acceptance gate is NOT
  throughput: every completed (``ok``) response must be bit-identical
  to a solo in-process ``StencilServer`` oracle at the same chunk
  boundary, corrupted outputs may only surface quarantined
  (``status == "anomaly"``), every applied step range is applied
  exactly once (contiguous per-tenant coverage + at most one
  journaled ``retry`` per idempotency key).
* **Loadcheck** (``--check``): the seeded, deterministic CPU-mesh
  scenario ``make loadcheck`` gates on — a latency-SLO burn spike
  trips the autoscaler (journaled ``scale_up`` joined to the breach
  trace, warm spawn with zero lowerings), the queue drains, admission
  recovers, idle ticks drain + retire the extra worker with zero lost
  sessions.

Ledger keys: ``load-p50-ms`` / ``load-p99-ms`` (ms — unguarded by
design), ``load-goodput`` (ok/offered, unit "x", guarded by the
provisional ``load-goodput-floor`` sentinel rule).  Soak rows bank
under ``load-soak-*`` keys the floor pattern deliberately does not
match (injected kills are SUPPOSED to dent goodput).

The harness performs no device work itself: every request is a fleet
``handle()`` call (guarded sites live in the workers), and the oracle
runs through the serve package's own guarded scheduler.
"""

from __future__ import annotations

import argparse
import calendar
import json
import os
import random
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_PROFILE = {"stencil": "iso3dfd", "radius": 1, "g": 8, "wf": 2}


# ---------------------------------------------------------- schedules

def arrivals(kind: str, rate: float, duration: float,
             rng: random.Random, spike_mult: float = 4.0) -> List[float]:
    """Arrival offsets (seconds from t0) for one open-loop process."""
    rate = max(rate, 1e-9)
    if kind == "uniform":
        gap = 1.0 / rate
        n = int(duration * rate)
        return [i * gap for i in range(n)]
    if kind == "poisson":
        out, t = [], 0.0
        while True:
            t += rng.expovariate(rate)
            if t >= duration:
                return out
            out.append(t)
    if kind == "step":
        half = duration / 2.0
        lo = arrivals("poisson", rate, half, rng)
        hi = arrivals("poisson", rate * spike_mult,
                      duration - half, rng)
        return lo + [half + t for t in hi]
    if kind == "spike":
        third = duration / 3.0
        base = arrivals("poisson", rate, duration, rng)
        burst = arrivals("poisson", rate * spike_mult, third, rng)
        return sorted(base + [third + t for t in burst])
    raise ValueError(f"unknown arrival process {kind!r}")


def replay_arrivals(journal_path: str, speed: float = 1.0) \
        -> List[Tuple[float, str]]:
    """(offset, tenant) pairs from a recorded serve journal's
    ``received`` rows — the original tenant mix and gaps (journal ts
    resolution is 1 s; ``speed`` > 1 compresses the gaps)."""
    speed = max(speed, 1e-9)
    rows: List[Tuple[float, str]] = []
    t0: Optional[float] = None
    with open(journal_path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or '"received"' not in line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if row.get("event") != "received":
                continue
            try:
                ts = calendar.timegm(time.strptime(
                    row.get("ts", ""), "%Y-%m-%dT%H:%M:%SZ"))
            except ValueError:
                continue
            if t0 is None:
                t0 = float(ts)
            rows.append(((ts - t0) / speed,
                         str(row.get("session", "tenant-0"))))
    return rows


# ------------------------------------------------------------ harness

class LoadHarness:
    """Open-loop driver over an in-process fleet front."""

    def __init__(self, fleet, tenants: int = 2, steps: int = 2,
                 flush_every: int = 0, deadline: float = 0.0,
                 spike_burst: int = 8, profile: Optional[Dict] = None,
                 rng: Optional[random.Random] = None):
        self.fleet = fleet
        self.steps = max(1, int(steps))
        self.flush_every = int(flush_every)
        self.deadline = float(deadline)
        self.spike_burst = max(0, int(spike_burst))
        self.profile = dict(profile or DEFAULT_PROFILE)
        self.rng = rng or random.Random(0)
        self.results: List[Dict] = []
        self._rlock = threading.Lock()
        self.sids: Dict[str, str] = {}           # tenant -> fleet sid
        self._next_step: Dict[str, int] = {}
        self._tlocks: Dict[str, threading.Lock] = {}
        self.offered = 0
        self._tenant_names = [f"tenant-{i}" for i in range(max(1, tenants))]

    def open_tenants(self) -> None:
        for name in self._tenant_names:
            out = self.fleet.handle({"op": "open", **self.profile})
            if not out.get("ok"):
                raise RuntimeError(f"open failed for {name}: {out}")
            sid = out["sid"]
            ini = self.fleet.handle({"op": "init", "sid": sid})
            if not ini.get("ok"):
                raise RuntimeError(f"init failed for {name}: {ini}")
            self.sids[name] = sid
            self._next_step[name] = 0
            self._tlocks[name] = threading.Lock()

    # one request: closed-loop per tenant (contiguous step ranges),
    # open-loop across tenants (the dispatcher never waits on this)
    def _issue(self, tenant: str) -> None:
        with self._tlocks[tenant]:
            first = self._next_step[tenant]
            last = first + self.steps - 1
            msg = {"op": "run", "sid": self.sids[tenant],
                   "first": first, "last": last}
            if self.flush_every > 0:
                msg["flush_every"] = self.flush_every
            if self.deadline > 0:
                msg["deadline"] = self.deadline
            t0 = time.perf_counter()
            try:
                out = self.fleet.handle(msg)
            except Exception as e:  # noqa: BLE001 - a lost answer is a
                # data point, not a harness crash
                out = {"ok": False,
                       "error": f"{type(e).__name__}: {e}"}
            ms = (time.perf_counter() - t0) * 1000.0
            status = str(out.get("status", ""))
            ok = bool(out.get("ok"))
            if not status:
                status = "ok" if ok else "error"
            # ok AND anomaly both ran to completion server-side: the
            # session advanced, so the next range follows contiguously
            if ok or status == "anomaly":
                self._next_step[tenant] = last + 1
            rec = {"tenant": tenant, "sid": self.sids[tenant],
                   "first": first, "last": last, "ok": ok,
                   "status": status, "latency_ms": ms,
                   "overloaded": bool(out.get("overloaded")),
                   "retry_after": out.get("retry_after"),
                   "error": str(out.get("error", ""))[:200],
                   "trace": str(out.get("trace", ""))}
            if ok:
                rec["outputs"] = out.get("outputs") or {}
            if out.get("anomaly"):
                rec["anomaly"] = out["anomaly"]
            with self._rlock:
                self.results.append(rec)

    def drive(self, schedule: List) -> int:
        """Run one schedule: floats (round-robin tenants) or
        (offset, tenant) pairs (replay).  Each arrival probes the
        ``load.arrival`` chaos site — an injected LoadSpike answers
        with an immediate burst of ``spike_burst`` extra arrivals.
        Returns the offered-request count (burst included)."""
        from yask_tpu.resilience.faults import Fault, LoadSpike, \
            fault_point
        threads: List[threading.Thread] = []
        names = list(self.sids)
        t0 = time.perf_counter()

        def launch(tenant: str) -> None:
            th = threading.Thread(target=self._issue, args=(tenant,),
                                  daemon=True)
            th.start()
            threads.append(th)
            self.offered += 1

        for i, item in enumerate(schedule):
            off, tenant = item if isinstance(item, tuple) \
                else (item, names[i % len(names)])
            if tenant not in self.sids:
                tenant = names[i % len(names)]
            delay = t0 + float(off) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            burst = 0
            try:
                fault_point("load.arrival")
            except LoadSpike:
                burst = self.spike_burst
            except Fault:
                continue  # any other injected fault drops the arrival
            launch(tenant)
            for j in range(burst):
                launch(names[(i + 1 + j) % len(names)])
        for th in threads:
            th.join(timeout=600.0)
        return self.offered

    # ------------------------------------------------------- metrics

    def summary(self) -> Dict:
        lat = sorted(r["latency_ms"] for r in self.results if r["ok"])

        def pct(p: float) -> float:
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1, int(p * (len(lat) - 1)))]

        n_ok = sum(1 for r in self.results if r["ok"])
        n_anom = sum(1 for r in self.results
                     if r["status"] == "anomaly")
        n_shed = sum(1 for r in self.results if r["overloaded"])
        offered = max(1, self.offered)
        return {"offered": self.offered, "completed": len(self.results),
                "ok": n_ok, "anomaly": n_anom, "overloaded": n_shed,
                "goodput": n_ok / offered,
                "p50_ms": pct(0.50), "p99_ms": pct(0.99)}

    def bank(self, prefix: str = "load", extra: Optional[Dict] = None,
             path: Optional[str] = None) -> List[Dict]:
        """PERF_LEDGER rows: p50/p99 (ms, unguarded) + goodput (unit
        "x", sentinel-guarded for ``load-goodput``; soak prefixes bank
        outside the floor pattern on purpose)."""
        from yask_tpu.perflab.provenance import capture_provenance
        from yask_tpu.perflab.sentinel import guard_and_append
        s = self.summary()
        prov = capture_provenance(platform="cpu", calibrate=False)
        meta = {"offered": s["offered"], "ok": s["ok"],
                "anomaly": s["anomaly"], "overloaded": s["overloaded"],
                **(extra or {})}
        rows = []
        for key, val, unit in ((f"{prefix}-p50-ms", s["p50_ms"], "ms"),
                               (f"{prefix}-p99-ms", s["p99_ms"], "ms"),
                               (f"{prefix}-goodput", s["goodput"], "x")):
            rows.append(guard_and_append(
                key, float(val), unit, "cpu", "load", prov,
                extra=meta, path=path))
        return rows

    # -------------------------------------------------------- audits

    def oracle_outputs(self, journal_path: str) -> Dict[int, Dict]:
        """Solo oracle: one in-process StencilServer runs the SAME
        profile through the SAME chunk boundaries (all tenants share
        the profile and deterministic init, so expected outputs depend
        only on the chunk's last step).  Runs with faults cleared —
        the oracle must be the uninjected twin."""
        import numpy as np
        from yask_tpu.serve import ServeRequest, StencilServer
        bounds = sorted({(r["first"], r["last"])
                         for r in self.results
                         if r["ok"] or r["status"] == "anomaly"})
        srv = StencilServer(journal_path=journal_path, preflight=False)
        self.oracle_anomalies = set()
        try:
            sid = srv.open_session(**self.profile)
            srv.init_vars(sid)
            out: Dict[int, Dict] = {}
            for first, last in bounds:
                h = srv.submit(ServeRequest(session=sid,
                                            first_step=first,
                                            last_step=last))
                r = srv.wait(h)
                if r.status == "anomaly":
                    # the UNINJECTED twin flags this boundary too:
                    # genuine physics (the undamped test profile grows
                    # to nonfinite past enough steps), not corruption —
                    # fleet answers here must ALSO be quarantined
                    self.oracle_anomalies.add(last)
                elif r.status != "ok":
                    raise RuntimeError(
                        f"oracle run [{first},{last}] not ok: "
                        f"{r.status} {r.error}")
                out[last] = {k: np.asarray(v)
                             for k, v in (r.outputs or {}).items()}
            return out
        finally:
            srv.shutdown()

    def audit(self, oracle: Optional[Dict[int, Dict]] = None,
              fleet_journal_rows: Optional[List[Dict]] = None) -> Dict:
        """The soak acceptance gate.  Raises AssertionError on any
        violation; returns the audit tally."""
        import numpy as np
        from tools.serve_client import decode_array
        compared = 0
        anom_bounds = getattr(self, "oracle_anomalies", set())
        for r in self.results:
            if r["status"] == "anomaly":
                # corrupted outputs may only surface quarantined —
                # never as a clean ok answer
                assert not r["ok"], f"anomaly released as ok: {r}"
                assert r.get("anomaly"), \
                    f"anomaly row without a structured verdict: {r}"
                continue
            if not r["ok"] or oracle is None:
                continue
            # sanity consistency: a boundary the uninjected oracle
            # quarantines can never be released clean by the fleet
            assert r["last"] not in anom_bounds, \
                f"oracle flags step {r['last']} anomalous but the " \
                f"fleet released it clean: {r}"
            exp = oracle.get(r["last"])
            assert exp is not None, \
                f"oracle has no boundary for step {r['last']}"
            for name, enc in (r.get("outputs") or {}).items():
                got = decode_array(enc)
                assert np.array_equal(got, np.asarray(exp[name])), \
                    f"{r['tenant']} [{r['first']},{r['last']}] " \
                    f"{name}: completed response diverged from the " \
                    f"solo oracle"
                compared += 1
        # exactly-once: per tenant, applied ranges tile [0, hi] with
        # no gap and no overlap
        for tenant in self.sids:
            done = sorted((r["first"], r["last"])
                          for r in self.results
                          if r["tenant"] == tenant
                          and (r["ok"] or r["status"] == "anomaly"))
            expect = 0
            for first, last in done:
                assert first == expect, \
                    f"{tenant}: step range [{first},{last}] applied " \
                    f"out of sequence (expected first={expect} — a " \
                    f"duplicate or lost application)"
                expect = last + 1
        # at most ONE journaled retry per idempotency key
        if fleet_journal_rows is not None:
            seen: Dict[str, int] = {}
            for row in fleet_journal_rows:
                if row.get("event") != "retry":
                    continue
                idem = str((row.get("detail") or {}).get("idem", ""))
                seen[idem] = seen.get(idem, 0) + 1
            dup = {k: v for k, v in seen.items() if v > 1}
            assert not dup, f"idempotency keys retried twice: {dup}"
        return {"bit_identical_arrays": compared,
                "oracle_anomalies": len(anom_bounds),
                "tenants": len(self.sids),
                "retries": 0 if fleet_journal_rows is None else sum(
                    1 for row in fleet_journal_rows
                    if row.get("event") == "retry")}


# ------------------------------------------------------------ helpers

def _fleet_env(workdir: str) -> Dict[str, str]:
    """Process-env defaults every harness mode needs: CPU platform
    (the relay dial can hang for minutes), a scratch perf ledger so
    worker shutdown flushes stay out of the tracked one."""
    env = {"JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS") or "cpu",
           "PALLAS_AXON_POOL_IPS":
               os.environ.get("PALLAS_AXON_POOL_IPS", ""),
           "YT_PERF_LEDGER": os.environ.get("YT_PERF_LEDGER")
               or os.path.join(workdir, "ledger.jsonl")}
    os.environ.update(env)
    return env


def _make_fleet(workdir: str, workers: int, autoscale=None):
    from tools.serve_fleet import ServeFleet
    return ServeFleet(
        n_workers=workers,
        cache_dir=os.path.join(workdir, "cache"),
        journal_dir=workdir,
        worker_args=["--no-preflight", "--window_ms", "5"],
        hb_secs=0.0, autoscale=autoscale)


def _fleet_rows(workdir: str) -> List[Dict]:
    from yask_tpu.serve.journal import ServeJournal
    return ServeJournal(os.path.join(
        workdir, "SERVE_JOURNAL.fleet.jsonl")).rows()


# -------------------------------------------------------------- modes

def run_load(args, workdir: str) -> int:
    """Plain load run (or replay): drive, audit against the oracle,
    bank the curve."""
    _fleet_env(workdir)
    rng = random.Random(args.seed)
    fleet = _make_fleet(workdir, args.workers)
    try:
        h = LoadHarness(fleet, tenants=args.tenants, steps=args.steps,
                        flush_every=args.flush_every,
                        deadline=args.deadline, rng=rng)
        h.open_tenants()
        if args.replay:
            sched = replay_arrivals(args.replay, args.replay_speed)
            # re-map recorded tenants onto our sessions, preserving
            # the mix: distinct recorded names -> round-robin tenants
            names = sorted({t for _o, t in sched})
            ours = list(h.sids)
            remap = {n: ours[i % len(ours)]
                     for i, n in enumerate(names)}
            sched = [(o, remap[t]) for o, t in sched]
        else:
            sched = arrivals(args.arrivals, args.rate, args.duration,
                             rng, spike_mult=args.spike_mult)
        h.drive(sched)
        s = h.summary()
        oracle = None
        if not args.no_oracle:
            oracle = h.oracle_outputs(os.path.join(
                workdir, "SERVE_JOURNAL.oracle.jsonl"))
        tally = h.audit(oracle, _fleet_rows(workdir))
        if args.bank:
            h.bank(prefix="load-replay" if args.replay else "load",
                   extra={"arrivals": "replay" if args.replay
                          else args.arrivals, "seed": args.seed})
        print(json.dumps({"summary": s, "audit": tally},
                         sort_keys=True))
        return 0
    finally:
        fleet.close()


def run_soak(args, workdir: str) -> int:
    """Seeded chaos soak: load spike + worker kill + hang + zero
    output, all under one YT_FAULT_PLAN, gated on exactly-once +
    bit-identity (docs/resilience.md)."""
    from yask_tpu.resilience.faults import reset_faults
    _fleet_env(workdir)
    plan = [
        {"site": "load.arrival", "kind": "load_spike",
         "times": 2, "after": 3},
        {"site": "fleet.kill_worker", "kind": "worker_dead",
         "times": 1, "after": 5},
        {"site": "fleet.hang_worker", "kind": "hang",
         "secs": 0.3, "times": 1, "after": 9},
        {"site": "serve.respond", "kind": "zero_output",
         "times": 1, "after": 4},
    ]
    os.environ["YT_FAULT_PLAN"] = json.dumps(plan)
    reset_faults()
    rng = random.Random(args.seed)
    fleet = _make_fleet(workdir, max(2, args.workers))
    # replacements for chaos-killed workers must spawn CLEAN — the
    # injected plan applies to the first generation only
    fleet._base_env.pop("YT_FAULT_PLAN", None)
    try:
        h = LoadHarness(fleet, tenants=args.tenants, steps=args.steps,
                        flush_every=args.flush_every, spike_burst=4,
                        rng=rng)
        h.open_tenants()
        sched = arrivals("spike", args.rate, args.duration, rng,
                         spike_mult=args.spike_mult)
        h.drive(sched)
        # the oracle is the uninjected twin: clear the plan first
        os.environ.pop("YT_FAULT_PLAN", None)
        reset_faults()
        oracle = h.oracle_outputs(os.path.join(
            workdir, "SERVE_JOURNAL.oracle.jsonl"))
        tally = h.audit(oracle, _fleet_rows(workdir))
        s = h.summary()
        if args.bank:
            h.bank(prefix="load-soak",
                   extra={"arrivals": "spike", "seed": args.seed,
                          "fault_plan": plan})
        print(json.dumps({"summary": s, "audit": tally},
                         sort_keys=True))
        return 0
    finally:
        os.environ.pop("YT_FAULT_PLAN", None)
        reset_faults()
        fleet.close()


def run_check(args, workdir: str) -> int:
    """``make loadcheck``: the seeded closed-loop elastic scenario.
    Deterministic by construction (manual supervision ticks, burn
    thresholds, zero cooldown); a few CPU-timing-free assertions:

    1. a latency-burn spike trips a journaled ``scale_up`` (signal
       attached) and the fleet grows to 2 workers;
    2. the new worker warm-starts: first run answers with ZERO
       lowerings off the shared compile cache;
    3. admission recovers (a fresh open + run succeeds, queue empty);
    4. idle ticks drain the tail worker: ``scale_down`` row with the
       session migrated (zero lost), and the migrated session keeps
       serving contiguous steps.
    """
    saved = {k: os.environ.get(k) for k in (
        "YT_SLO_P99_MS", "YT_SLO_WINDOWS", "YT_FLEET_SCALE_UP_BURN",
        "YT_FLEET_SCALE_UP_QUEUE", "YT_FLEET_MIN_WORKERS",
        "YT_FLEET_MAX_WORKERS", "YT_FLEET_SCALE_COOLDOWN",
        "YT_FLEET_SCALE_DOWN_IDLE")}
    os.environ.update({
        "YT_SLO_P99_MS": "0.001",       # every request breaches
        "YT_SLO_WINDOWS": "2",          # short window: burn decays fast
        "YT_FLEET_SCALE_UP_BURN": "1.0",
        "YT_FLEET_SCALE_UP_QUEUE": "0",  # burn is the only trigger
        "YT_FLEET_MIN_WORKERS": "1",
        "YT_FLEET_MAX_WORKERS": "2",
        "YT_FLEET_SCALE_COOLDOWN": "0",
        "YT_FLEET_SCALE_DOWN_IDLE": "2",
    })
    _fleet_env(workdir)
    rng = random.Random(args.seed)
    fleet = _make_fleet(workdir, 1, autoscale=True)
    try:
        h = LoadHarness(fleet, tenants=2, steps=1, rng=rng)
        h.open_tenants()
        h.drive(arrivals("spike", 10.0, 1.0, rng, spike_mult=4.0))
        assert h.summary()["ok"] > 0, h.summary()

        # (1) the burn spike scales the fleet up, journaled
        fleet.supervise_tick()
        assert len(fleet.workers) == 2, \
            f"burn spike did not scale up ({len(fleet.workers)} workers)"
        ups = [r for r in _fleet_rows(workdir)
               if r.get("event") == "scale_up"]
        assert ups and "signal" in ups[-1].get("detail", {}), ups
        assert ups[-1]["detail"]["signal"]["max_burn"] >= 1.0, ups[-1]

        # (2) warm spawn: the new worker's first run = zero lowerings
        s = fleet.handle({"op": "open", **DEFAULT_PROFILE})
        assert s.get("ok") and s.get("worker") == 1, s
        ini = fleet.handle({"op": "init", "sid": s["sid"]})
        assert ini.get("ok"), ini
        r = fleet.handle({"op": "run", "sid": s["sid"],
                          "first": 0, "last": 0})
        assert r.get("ok"), r
        cs = fleet.handle({"op": "cache_stats"})["stats"]["1"]
        assert cs["lowerings"] == 0 and cs["disk_hits"] > 0, \
            f"scale-up worker re-lowered instead of warm-starting: {cs}"

        # (3) admission recovered: queue empty, fresh work flows
        m = fleet.handle({"op": "metrics"})["metrics"]
        assert m["queue_depth"] == 0, m
        r2 = fleet.handle({"op": "run", "sid": s["sid"],
                           "first": 1, "last": 1})
        assert r2.get("ok"), r2

        # (4) burn decays, idle ticks drain + retire the tail worker
        time.sleep(2.2)
        for _ in range(4):
            if len(fleet.workers) == 1:
                break
            fleet.supervise_tick()
        assert len(fleet.workers) == 1, "idle fleet did not scale down"
        downs = [r for r in _fleet_rows(workdir)
                 if r.get("event") == "scale_down"]
        assert downs, "no scale_down journal row"
        det = downs[-1].get("detail", {})
        assert s["sid"] in det.get("migrated", []), det
        assert det.get("lost") == [], det
        # the migrated session keeps serving, contiguous steps intact
        r3 = fleet.handle({"op": "run", "sid": s["sid"],
                           "first": 2, "last": 2})
        assert r3.get("ok"), f"migrated session lost after drain: {r3}"
        print(json.dumps({"loadcheck": "ok",
                          "scale_up": ups[-1]["detail"],
                          "scale_down": det}, sort_keys=True))
        return 0
    finally:
        fleet.close()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="open-loop / replay / chaos load harness for the "
                    "serving fleet")
    ap.add_argument("--arrivals", default="poisson",
                    choices=("poisson", "uniform", "step", "spike"))
    ap.add_argument("--rate", type=float, default=10.0,
                    help="offered arrivals per second")
    ap.add_argument("--duration", type=float, default=2.0)
    ap.add_argument("--spike-mult", type=float, default=4.0)
    ap.add_argument("--tenants", type=int, default=2)
    ap.add_argument("--steps", type=int, default=2,
                    help="steps per request")
    ap.add_argument("--flush-every", type=int, default=0)
    ap.add_argument("--deadline", type=float, default=0.0,
                    help="per-request queue+run deadline seconds")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--replay", default=None,
                    help="re-drive a recorded SERVE_JOURNAL's "
                         "received rows (original tenant mix)")
    ap.add_argument("--replay-speed", type=float, default=1.0)
    ap.add_argument("--soak", action="store_true",
                    help="seeded chaos soak (load spike + worker "
                         "kill + hang + zero output)")
    ap.add_argument("--check", action="store_true",
                    help="deterministic loadcheck scenario (make "
                         "loadcheck)")
    ap.add_argument("--no-oracle", action="store_true",
                    help="skip the solo bit-identity oracle")
    ap.add_argument("--no-bank", dest="bank", action="store_false",
                    help="do not append PERF_LEDGER rows")
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: a fresh temp dir)")
    args = ap.parse_args(argv)

    if args.workdir:
        workdir = args.workdir
        os.makedirs(workdir, exist_ok=True)
    else:
        import tempfile
        workdir = tempfile.mkdtemp(prefix="yt_load_")
    try:
        if args.check:
            return run_check(args, workdir)
        if args.soak:
            return run_soak(args, workdir)
        return run_load(args, workdir)
    except AssertionError as e:
        print(f"load_harness: FAIL: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
