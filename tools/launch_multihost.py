#!/usr/bin/env python
"""Multi-host (multi-process) launch path for the shard modes.

The reference scales across nodes by launching one MPI rank per node
(``setup.cpp:51-90``); the JAX analog is one *process* per host joined
into a cluster via ``jax.distributed``, after which ``jax.devices()``
spans every host and the ONE mesh factory (``parallel.mesh.make_mesh``)
lays the solution's rank grid over the global device list — ICI within
a slice, DCN across hosts.  The CommPlan classifies each mesh axis
(``mesh_axis_kinds``) and orders the DCN axes first, so the launch tool
only has to build the same solution on every process and run; there is
no per-axis code here.

Run the SAME command on every host, varying only ``--process_id``::

    python tools/launch_multihost.py \
        --coordinator host0:8476 --num_processes 2 --process_id 0 \
        -stencil iso3dfd -radius 8 -g 256 -mode shard_pallas \
        -ranks x=2,y=2 -steps 32

With ``--num_processes 1`` (the default) no cluster is formed and the
tool is a single-host driver — the CPU-testable path
(``tests/test_comm_schedule.py``).

Device work routes through ``guarded_call`` (repo_lint's
BARE-DEVICE-CALL closure) with fault sites ``multihost.prepare`` /
``multihost.run`` so the resilience injection harness reaches this
driver like every other one.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yask_tpu.resilience.faults import fault_point    # noqa: E402
from yask_tpu.resilience.guard import guarded_call    # noqa: E402


def parse_args(argv):
    ap = argparse.ArgumentParser(
        description="multi-process shard-mode launcher")
    ap.add_argument("--coordinator", default="",
                    help="coordinator address host:port "
                         "(required when --num_processes > 1)")
    ap.add_argument("--num_processes", type=int, default=1)
    ap.add_argument("--process_id", type=int, default=0)
    ap.add_argument("-stencil", default="iso3dfd")
    ap.add_argument("-radius", type=int, default=8)
    ap.add_argument("-g", type=int, default=128,
                    help="global cube edge")
    ap.add_argument("-mode", default="shard_map",
                    choices=["sharded", "shard_map", "shard_pallas"])
    ap.add_argument("-ranks", default="x=2",
                    help="mesh axes, e.g. x=2,y=2")
    ap.add_argument("-steps", type=int, default=8)
    ap.add_argument("-wf_steps", type=int, default=1)
    ap.add_argument("-comm_order", default="",
                    help="explicit exchange order (default: cost model)")
    ap.add_argument("-coalesce", default="auto",
                    choices=["auto", "on", "off"])
    ap.add_argument("--deadline", type=float, default=900.0,
                    help="per-phase guard deadline (secs)")
    return ap.parse_args(argv)


def build_context(args):
    """Configured, prepared context over the (possibly global) device
    list — called on every process; XLA keeps the SPMD programs in
    lockstep because each builds the identical mesh from the identical
    global list."""
    from yask_tpu import yk_factory
    from yask_tpu.runtime.init_utils import init_solution_vars

    fac = yk_factory()
    env = fac.new_env()
    ctx = fac.new_solution(env, stencil=args.stencil, radius=args.radius)
    opt = f"-g {args.g}"
    if args.comm_order:
        opt += f" -comm_order {args.comm_order}"
    opt += f" -coalesce {args.coalesce}"
    ctx.apply_command_line_options(opt)
    s = ctx.get_settings()
    s.mode = args.mode
    s.wf_steps = args.wf_steps
    for part in args.ranks.split(","):
        d, _, n = part.partition("=")
        ctx.set_num_ranks(d.strip(), int(n))
    fault_point("multihost.prepare")
    ctx.prepare_solution()
    init_solution_vars(ctx)
    return ctx


def main(argv=None) -> int:
    args = parse_args(sys.argv[1:] if argv is None else argv)
    if args.num_processes > 1:
        if not args.coordinator:
            print("--coordinator is required for --num_processes > 1",
                  file=sys.stderr)
            return 2
        from yask_tpu.runtime.env import yk_env
        yk_env.init_distributed(args.coordinator, args.num_processes,
                                args.process_id)

    ctx = guarded_call(build_context, args, site="multihost.prepare",
                       deadline_secs=args.deadline)

    # the schedule every process will execute — identical by
    # construction (same geometry, same global mesh)
    plan = ctx.comm_plan()
    if args.process_id == 0:
        print("comm plan:", json.dumps(plan.record(), indent=2))

    def run():
        fault_point("multihost.run")
        t0 = time.perf_counter()
        ctx.run_solution(0, args.steps - 1)
        return time.perf_counter() - t0

    secs = guarded_call(run, site="multihost.run",
                        deadline_secs=args.deadline)
    st = ctx.get_stats()
    if args.process_id == 0:
        print(st.format())
        print(f"proc {args.process_id}/{args.num_processes}: "
              f"{args.steps} steps in {secs:.3f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
