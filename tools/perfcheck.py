"""Quick sentinel gate: measure the fast bench rows, fail on an
unexplained breach.

``make perfcheck`` runs the suite's CPU-proxy rows (small domains, a
tight time budget) through ``yask_tpu.perflab``: every row gets
provenance, a ledger append, and a guard verdict with one automatic
re-measure on breach.  The exit code is the point —

* 0: every row is ``ok`` / ``no_history`` / ``noise`` (a breach that
  cleared on re-measure is load noise, explained in the row itself);
* 1: some row's verdict is ``regression`` or ``breach`` (breached twice,
  or breached with no re-measure hook), or a section crashed, or no
  rows were produced at all.

This replaces eyeballing BENCH JSON between rounds: a real perf slide
turns red here first, with the trailing-median baseline and both samples
recorded in ``PERF_LEDGER.jsonl``.

Run: ``make perfcheck``  (or ``python tools/perfcheck.py [budget_secs]``)
"""

from __future__ import annotations

import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

#: verdicts that fail the gate (everything else is ok or self-explained)
FAIL_STATUSES = ("breach", "regression")


def run(budget_secs: float = 300.0, out=None) -> int:
    out = out or sys.stdout
    from yask_tpu import yk_factory
    from tools.bench_suite import run_suite
    fac = yk_factory()
    env = fac.new_env()
    rows = run_suite(fac, env, budget_secs=budget_secs)
    bad = []
    for r in rows:
        st = r.get("guard", {}).get("status", "")
        if st in FAIL_STATUSES or r.get("unit") == "error":
            bad.append(r)
    ok = [r for r in rows if r not in bad]
    out.write(f"perfcheck: {len(rows)} row(s), {len(ok)} clean, "
              f"{len(bad)} failing\n")
    for r in bad:
        out.write("FAIL " + json.dumps(
            {k: r.get(k) for k in ("metric", "value", "unit", "guard",
                                   "error") if k in r}) + "\n")
    if not rows:
        out.write("perfcheck: no rows produced\n")
        return 1
    return 1 if bad else 0


def main() -> int:
    try:
        budget = float(sys.argv[1]) if len(sys.argv) > 1 else 300.0
    except ValueError:
        return 2
    return run(budget)


if __name__ == "__main__":
    sys.exit(main())
