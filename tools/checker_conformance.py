#!/usr/bin/env python
"""Differential checker-soundness harness (``make conformance``).

The static checker (``yask_tpu.checker``) promises that its verdict on
a configured solution predicts what the runtime will do WITHOUT
executing anything.  This harness tests that promise differentially:
for each seed it generates a random solution + configuration, asks the
checker for a static verdict, then actually runs the pallas path
against the jit oracle, and compares the two answers.

A **disagreement** is either direction of drift:

* ``unsound``    — the checker reported NO errors, but the pallas
  build/run raised, or the run's output mismatched the jit oracle
  beyond the field-tolerance policy (``compare_data(...,
  field_epsilon=1e-4)`` — fused in-tile evaluation legitimately
  reassociates long sums, so isolated field-ulp differences are not
  corruption; see ``docs/checking.md``).
* ``overstrict`` — the checker reported an error, yet the identical
  configuration built, ran, and matched the oracle.

Anything else is agreement: clean+match, or error+raise (the checker
predicted the refusal), or error+mismatch (the checker predicted the
corruption).  The jit oracle itself failing on a checker-clean config
also counts as ``unsound`` — the races pass exists precisely to flag
solutions the core analysis rejects.

The generated space covers the structures the checker rules are about:
2-D/3-D domains, radius 1..4, ring depth 1..2, multi-stage chains,
same-point-read written vars (the r21 skew-carry regression class),
IF_DOMAIN condition bands, misc-index coefficient vars, scratch
intermediates, partial-dim read vars WITH the minor dim (legal) and
WITHOUT it (the Mosaic lane-alignment refusal), reverse time, random
block sizes (including below skew carry floors), wf_steps 1..3, and
explicit VMEM budgets (shared by both arms, so the checker's
TPU-default budget and the interpret host's looser default cannot
disagree about which budget is being judged).

On a disagreement the failing configuration is greedily minimized
(features dropped one at a time while the disagreement persists) and
written as a replayable JSON repro under ``tools/logs/`` — rerun with
``--replay tools/logs/conformance_<seed>.json``.

Usage::

    python tools/checker_conformance.py              # 200 seeds
    python tools/checker_conformance.py --seeds 500 --base 1000
    python tools/checker_conformance.py --quick      # the 16-seed
                                                     # tier-1 subset
    python tools/checker_conformance.py --replay tools/logs/....json

Exit status is nonzero iff any disagreement survived.  Always runs on
the CPU interpret host — a differential sweep must never burn (or hang
on) a TPU relay window.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
from typing import Dict, List, Optional, Tuple

# A differential sweep is CPU work by definition: force the interpret
# host BEFORE jax can load, so an importing shell can never dial the
# axon relay and hang (CLAUDE.md environment rules).
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCHEMA = "yask_tpu.conformance/1"

#: the oracle-match policy: fused in-tile evaluation reassociates long
#: staggered sums (different FMA contraction than XLA's fusion), which
#: shows up as isolated field-ulp differences — NOT corruption.  A real
#: geometry bug produces O(field) errors and fails this by orders of
#: magnitude (the pre-fix awp skew carry: 52k+ points past it).
FIELD_EPSILON = 1e-4

#: per-case wall clock before the resilience guard kills the case
DEADLINE_ENV = "YT_CONFORMANCE_DEADLINE"

#: the tier-1 quick subset (tests/test_conformance.py): seeds chosen
#: 0..N so the covered feature mix is stable run to run
QUICK_SEEDS = 16

_FEATURES = ("two_stage", "same_point_chain", "condition", "misc_var",
             "scratch", "partial_minor", "partial_no_minor", "reverse")


# ---------------------------------------------------------------- gen
def gen_config(seed: int) -> dict:
    """One random-but-reproducible configuration.  Pure function of the
    seed (``random.Random(seed)``), JSON-round-trippable, replayable."""
    rng = random.Random(seed)
    ndims = rng.choice((2, 3))
    r = rng.choice((1, 1, 2, 2, 3, 4))
    wf = rng.choice((1, 1, 2, 2, 3))
    ring = rng.choice((1, 1, 2))
    g = rng.choice((16, 20, 24) if ndims == 3 else (24, 32, 48))
    feats = {
        "two_stage": rng.random() < 0.35,
        "same_point_chain": rng.random() < 0.30,
        "condition": rng.random() < 0.30,
        "misc_var": rng.random() < 0.25,
        "scratch": rng.random() < 0.25,
        "partial_minor": rng.random() < 0.20,
        "partial_no_minor": rng.random() < 0.15,
        "reverse": rng.random() < 0.10,
    }
    # reverse time + deep ring both change the write target; keep the
    # generator in the space the oracle covers (reverse uses ring 1)
    if feats["reverse"]:
        ring = 1
    # block sizes over the LEAD dims only (the minor dim always tiles
    # full-lane); None = let the planner choose.  Occasionally tiny, to
    # walk the skew fallback ladder.
    lead = ndims - 1
    block: Dict[str, Optional[int]] = {}
    for i, d in enumerate("xyz"[:lead]):
        block[d] = rng.choice((None, None, 8, 16, 16, g))
    skew = rng.choice((None, None, None, True, False))
    vmem_mb = rng.choice((0, 0, 0, 64, 100))
    steps = max(2, wf * 2)
    return {"schema": SCHEMA, "seed": seed, "ndims": ndims, "g": g,
            "r": r, "wf": wf, "ring": ring, "block": block,
            "skew": skew, "vmem_mb": vmem_mb, "steps": steps,
            "features": feats}


def build_solution(cfg: dict):
    """A ``yc_solution_base`` from a config — the same front-end path
    user stencils take, so the checker sees nothing special."""
    from yask_tpu.compiler.solution_base import yc_solution_base

    feats = cfg["features"]
    ndims = cfg["ndims"]
    r = cfg["r"]
    ring = cfg["ring"]
    rng = random.Random(cfg["seed"] ^ 0x5EED)
    coef = [round(rng.uniform(0.01, 0.2), 4) for _ in range(r + 1)]

    class _Gen(yc_solution_base):
        def __init__(self):
            super().__init__(f"conf_{cfg['seed']}")

        def define(self):
            t = self.new_step_index("t")
            dims = [self.new_domain_index(d) for d in "xyz"[:ndims]]
            u = self.new_var("U", [t] + dims)

            def at(var, tt, **off):
                args = [dims[i] + off.get("xyz"[i], 0)
                        for i in range(ndims)]
                return var(tt, *args)

            # the core star stencil: ± offsets up to r in every dim
            e = at(u, t) * coef[0]
            for i in range(1, r + 1):
                for d in "xyz"[:ndims]:
                    e = e + (at(u, t, **{d: i})
                             + at(u, t, **{d: -i})) * coef[i]
            if ring == 2:
                e = e + at(u, t - 1) * 0.05

            if feats["misc_var"]:
                im = self.new_misc_index("i")
                c = self.new_var("C", [im])
                e = e * c(0) + c(1)

            if feats["scratch"]:
                s = self.new_scratch_var("S", dims)
                s(*dims).EQUALS(at(u, t) + at(u, t, x=1) * 0.5)
                e = e + s(*[dims[0] - 1] + dims[1:]) * 0.25

            if feats["partial_minor"]:
                # read-only var that DOES include the minor dim: legal
                p = self.new_var("P", dims[1:] if ndims > 1 else dims)
                e = e + p(*(dims[1:] if ndims > 1 else dims)) * 0.1

            if feats["partial_no_minor"]:
                # read-only var MISSING the minor dim: no lane-aligned
                # Mosaic DMA window exists — the checker must flag it
                # and the pallas mode must refuse
                q = self.new_var("Q", dims[:-1])
                e = e + q(*dims[:-1]) * 0.1

            m = None
            if feats["same_point_chain"]:
                # written var read ONLY at zero spatial offset (the awp
                # anelastic mem pattern — the r21 skew-carry class)
                m = self.new_var("M", [t] + dims)
                e = e + at(m, t) * 0.2

            tw = t - 1 if feats["reverse"] else t + 1
            lhs = at(u, tw)
            if feats["condition"]:
                first = self.first_domain_index(dims[0])
                last = self.last_domain_index(dims[0])
                band = ((dims[0] >= first + r + 1)
                        & (dims[0] <= last - (r + 1)))
                lhs.EQUALS(e).IF_DOMAIN(band)
                at(u, tw).EQUALS(at(u, t) * 0.5).IF_DOMAIN(~band)
            else:
                lhs.EQUALS(e)

            if m is not None:
                at(m, tw).EQUALS(at(m, t) * 0.5 + at(u, tw) * 0.1)

            if feats["two_stage"]:
                v = self.new_var("V", [t] + dims)
                ev = at(v, t) * 0.9
                for d in "xyz"[:ndims]:
                    ev = ev + (at(u, tw, **{d: 1})
                               + at(u, tw, **{d: -1})) * 0.05
                at(v, tw).EQUALS(ev)

    return _Gen()


# ---------------------------------------------------------------- run
def _make_ctx(env, cfg: dict, mode: str, wf: int = 1):
    from yask_tpu import yk_factory
    ctx = yk_factory().new_solution(env, build_solution(cfg))
    ctx.apply_command_line_options(f"-g {cfg['g']}")
    o = ctx.get_settings()
    o.mode = mode
    o.wf_steps = wf
    if cfg.get("vmem_mb"):
        o.vmem_budget_mb = cfg["vmem_mb"]
    if cfg.get("skew") is not None:
        o.skew_wavefront = cfg["skew"]
    for d, b in (cfg.get("block") or {}).items():
        if b:
            ctx.set_block_size(d, b)
    return ctx


def static_verdict(env, cfg: dict) -> dict:
    """The checker's answer, WITHOUT executing: the legality passes
    over an unprepared context (pure geometry planning)."""
    from yask_tpu.checker import run_checks
    try:
        ctx = _make_ctx(env, cfg, "pallas", wf=cfg["wf"])
        report = run_checks(ctx, passes=("mosaic", "vmem", "races",
                                         "explain"))
    except Exception as e:   # the checker must NEVER raise — itself a
        return {"clean": False, "checker_raised": True,   # finding
                "error": f"{type(e).__name__}: {e}", "rules": []}
    errs = report.errors
    return {"clean": not errs, "checker_raised": False,
            "rules": sorted({d.rule for d in errs}),
            "messages": [d.message[:200] for d in errs[:4]]}


def _run_one(ctx, cfg: dict):
    from yask_tpu.runtime.init_utils import init_solution_vars
    ctx.prepare_solution()
    init_solution_vars(ctx)
    if cfg["features"]["reverse"]:
        ctx.run_solution(cfg["steps"], 0)
    else:
        ctx.run_solution(0, cfg["steps"] - 1)
    return ctx


def dynamic_verdict(env, cfg: dict) -> dict:
    """What actually happens: jit oracle, then the pallas arm, then the
    field-tolerant comparison."""
    from yask_tpu.utils.exceptions import YaskException
    try:
        ref = _run_one(_make_ctx(env, cfg, "jit"), cfg)
    except YaskException as e:
        return {"oracle_ok": False, "ran": False,
                "error": f"oracle: {e}"}
    try:
        p = _run_one(_make_ctx(env, cfg, "pallas", wf=cfg["wf"]), cfg)
    except YaskException as e:
        return {"oracle_ok": True, "ran": False, "error": str(e)[:300]}
    bad = p.compare_data(ref, field_epsilon=FIELD_EPSILON)
    return {"oracle_ok": True, "ran": True, "match": bad == 0,
            "mismatches": int(bad)}


def classify(static: dict, dynamic: dict) -> str:
    """Agreement taxonomy — see the module docstring."""
    if static.get("checker_raised"):
        return "unsound"          # run_checks may never raise
    if static["clean"]:
        if not dynamic["oracle_ok"]:
            return "unsound"      # core analysis rejected a clean cfg
        if not dynamic["ran"]:
            return "unsound"      # missed infeasibility
        return "agree-clean" if dynamic["match"] else "unsound"
    # checker reported errors:
    if dynamic["oracle_ok"] and dynamic["ran"] and dynamic["match"]:
        return "overstrict"       # predicted failure never happened
    return "agree-error"


def run_case(env, cfg: dict) -> dict:
    """One differential case under the resilience guard (deadline +
    fault classification — tools never hang unattended)."""
    from yask_tpu.resilience.guard import guarded_call

    def _case():
        st = static_verdict(env, cfg)
        dy = dynamic_verdict(env, cfg)
        return {"cfg": cfg, "static": st, "dynamic": dy,
                "verdict": classify(st, dy)}

    deadline = float(os.environ.get(DEADLINE_ENV, "300"))
    try:
        return guarded_call(_case,
                            site=f"suite.conformance.{cfg['seed']}",
                            deadline_secs=deadline)
    except Exception as e:
        # a hang/crash on a case the checker passed is itself a
        # soundness datum; one it flagged is agreement
        st = static_verdict(env, cfg)
        return {"cfg": cfg, "static": st,
                "dynamic": {"oracle_ok": True, "ran": False,
                            "error": f"{type(e).__name__}: {e}"},
                "verdict": "agree-error" if not st["clean"]
                           else "unsound"}


# ------------------------------------------------------------ minimize
def minimize(env, cfg: dict, verdict: str) -> dict:
    """Greedy 1-feature-at-a-time shrink: drop each enabled feature and
    keep the drop while the same disagreement class persists."""
    cur = json.loads(json.dumps(cfg))
    changed = True
    while changed:
        changed = False
        for f in _FEATURES:
            if not cur["features"].get(f):
                continue
            trial = json.loads(json.dumps(cur))
            trial["features"][f] = False
            if run_case(env, trial)["verdict"] == verdict:
                cur = trial
                changed = True
    return cur


def write_repro(out_dir: str, result: dict) -> str:
    os.makedirs(out_dir, exist_ok=True)
    seed = result["cfg"]["seed"]
    path = os.path.join(out_dir, f"conformance_{seed}.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
    return path


# ---------------------------------------------------------------- main
def sweep(seeds, out_dir: str,
          progress=None) -> Tuple[Dict[str, int], List[dict]]:
    """Run the differential sweep; returns (verdict counts,
    disagreement results with minimized repro configs attached)."""
    from yask_tpu import yk_factory
    env = yk_factory().new_env()
    counts: Dict[str, int] = {}
    bad: List[dict] = []
    for seed in seeds:
        res = run_case(env, gen_config(seed))
        v = res["verdict"]
        counts[v] = counts.get(v, 0) + 1
        if v in ("unsound", "overstrict"):
            res["minimized"] = minimize(env, res["cfg"], v)
            res["repro"] = write_repro(out_dir, res)
            bad.append(res)
        if progress:
            progress(seed, res)
    return counts, bad


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=200)
    ap.add_argument("--base", type=int, default=0)
    ap.add_argument("--quick", action="store_true",
                    help=f"the {QUICK_SEEDS}-seed tier-1 subset")
    ap.add_argument("--replay", metavar="JSON",
                    help="re-run one repro (or raw config) file")
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "logs"))
    args = ap.parse_args(argv)

    if args.replay:
        with open(args.replay) as f:
            blob = json.load(f)
        cfg = blob.get("minimized") or blob.get("cfg") or blob
        from yask_tpu import yk_factory
        env = yk_factory().new_env()
        res = run_case(env, cfg)
        print(json.dumps({k: res[k] for k in
                          ("static", "dynamic", "verdict")}, indent=2))
        return 0 if res["verdict"].startswith("agree") else 1

    n = QUICK_SEEDS if args.quick else args.seeds
    seeds = range(args.base, args.base + n)

    def _progress(seed, res):
        tag = res["verdict"]
        if tag in ("unsound", "overstrict"):
            print(f"seed {seed}: {tag.upper()} — repro {res['repro']}")
        elif (seed - args.base + 1) % 25 == 0:
            print(f"...{seed - args.base + 1}/{n}")

    counts, bad = sweep(seeds, args.out, progress=_progress)
    print("conformance:", json.dumps(counts, sort_keys=True))
    for res in bad:
        mini = res["minimized"]
        print(f"  seed {res['cfg']['seed']} {res['verdict']}: "
              f"features={[f for f, on in mini['features'].items() if on]} "
              f"static={res['static']['rules']} "
              f"dynamic={res['dynamic']}")
    print(f"conformance: {len(bad)} disagreement(s) over {n} seeds")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
