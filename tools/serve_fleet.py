#!/usr/bin/env python
"""A warm-cache serving FLEET: N ``tools/serve.py`` workers behind one
JSON-lines front.

Topology::

    client  ──stdio/TCP──  serve_fleet.py  ──stdio pipes──  worker 0
                                 │                          worker 1
                                 │                          ...
                           (routing table: sid -> worker)

* **Workers** are plain ``tools/serve.py`` stdio children
  (``tools/serve_client.py`` transport), each with its OWN journal
  (``SERVE_JOURNAL.w<i>.jsonl`` — per-worker lifecycle evidence, and
  how the affinity test proves a session never migrated) and a SHARED
  on-disk compile cache (``YT_COMPILE_CACHE``): worker 0's compiles
  land in the cache, so worker 1+'s first request deserializes with
  ZERO lowerings (``cache_stats``) — the fleet's scale-out contract.
* **Session affinity**: ``open`` places a session on one worker
  (admission control below) and every later op for that sid routes to
  the same worker — session state lives in worker memory, migration
  would lose it.  The fleet namespaces session ids (``f0000...``) so
  two workers can never hand out colliding ids.
* **Admission control**: placement reads each worker's live metrics
  (queue depth, open sessions — the same numbers the journal
  occupancy rows carry); the least-loaded worker wins, and when every
  worker's queue is past ``YT_FLEET_MAX_QUEUE`` (default 64) the op
  is rejected instead of queued — saturation answers fast, it does
  not time out slowly.  Routing decisions pass the ``fleet.route``
  fault point (``YT_FAULT_PLAN`` injectable; a classified fault
  rejects that op, it never kills the fleet).
* **Streaming** passes through: a worker's interleaved
  ``{"stream": true}`` lines are re-emitted to the fleet's client as
  they arrive (per-worker pipes are serialized by a lock, so a
  stream line can only belong to the in-flight call on that worker).
* **Supervision** (fleet failover): workers are spawned in their own
  process group and health-checked — a dead process, a missed
  ``ping`` heartbeat past the liveness deadline
  (``YT_FLEET_HB_DEADLINE``, consecutive-miss threshold
  ``YT_FLEET_HB_MISSES``), or an EOF mid-op all declare the worker
  dead.  The front SIGKILLs the whole group (``run_deadlined``
  semantics), spawns a replacement that warm-starts from the shared
  compile cache, and FAILS THE SESSIONS OVER: each routed session is
  re-opened on the replacement (``session=sid``), restored from the
  last banked checkpoint (the ``snapshot``/``restore`` worker ops —
  r14 interior-coordinate snapshots, banked at a
  ``YT_FLEET_CKPT_EVERY``-step cadence on op boundaries), and the
  state-mutating ops since that committed boundary are replayed in
  order.  The recovered state is bit-identical to an uninterrupted
  twin (the r14 kill-resume contract at fleet scope).  An op in
  flight on the dead worker is re-issued EXACTLY ONCE under its
  idempotency key (``idem``, front-stamped on every forwarded op):
  the retry happens only when no response was delivered, against
  state rolled back to the last committed boundary, so its effects
  apply once.  Already-emitted ``{"stream": true}`` lines may repeat
  on a retried streaming run (streams are at-least-once; the final
  response is exactly-once).  Every migration is journaled
  (``SERVE_JOURNAL.fleet.jsonl``: ``worker_dead`` → ``failover`` with
  the dead worker id, snapshot step and replayed step ranges →
  ``retry``).

* **Elasticity** (``--autoscale`` / ``YT_FLEET_AUTOSCALE=1``): an
  SLO-driven policy loop (``yask_tpu/serve/autoscale.py``) rides the
  supervision cadence — scale UP warm-spawns a worker from the shared
  compile cache (first request: zero lowerings), scale DOWN drains
  the tail worker (stop admitting, in-flight runs finish, live
  sessions snapshot + migrate through the failover path) before the
  kill.  Every decision is a journaled ``scale_up`` / ``drain`` /
  ``scale_down`` row carrying the triggering signal; decisions read
  ONLY fresh telemetry (stale per-worker blocks are excluded — the
  autoscaler never scales on dead data).  Saturation rejections are
  structured: ``{"overloaded": true, "retry_after": ...}``
  (worker-side brownout tiers live in the scheduler; see
  ``docs/serving.md``).

The fleet front performs no device work itself — every op is a
forwarded worker call over pipes; the guarded device sites live in the
workers' serve package.  Chaos injection: ``fleet.route`` (front),
``fleet.heartbeat`` (front, a dropped heartbeat), ``fleet.scale`` /
``fleet.drain`` (front, an aborted scaling action), and the
worker-side ``fleet.kill_worker`` / ``fleet.hang_worker`` sites in
``tools/serve.py``.

Usage::

    python tools/serve_fleet.py --workers 2 --cache-dir /tmp/ytcache
    # then speak the tools/serve.py JSON-lines protocol on stdio, or
    # --port for TCP.  Extra ops: {"op": "fleet_stats"} and
    # {"op": "metrics_snapshot"} — the latter answers the merged
    # fleet-wide telemetry snapshot (yask_tpu.obs.telemetry: histogram
    # sample windows pooled and re-ranked, never averaged percentiles);
    # the heartbeat loop banks the same snapshot every tick.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.serve_client import ServeClient, ServeClientError


def fleet_max_queue() -> int:
    try:
        return max(1, int(os.environ.get("YT_FLEET_MAX_QUEUE", "")
                          or 64))
    except ValueError:
        return 64


def _env_num(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def fleet_ckpt_every() -> int:
    """Checkpoint cadence in steps (``YT_FLEET_CKPT_EVERY``, default
    8): after a session accumulates this many run steps past its last
    banked snapshot, the front banks a fresh one at the next op
    boundary."""
    return max(1, int(_env_num("YT_FLEET_CKPT_EVERY", 8)))


def fleet_hb_deadline() -> float:
    """Heartbeat liveness deadline in seconds (``YT_FLEET_HB_DEADLINE``,
    default 10): a ``ping`` that has not answered by then is a miss."""
    return max(0.1, _env_num("YT_FLEET_HB_DEADLINE", 10.0))


def fleet_hb_misses() -> int:
    """Consecutive heartbeat misses before a worker is declared
    unhealthy and replaced (``YT_FLEET_HB_MISSES``, default 2)."""
    return max(1, int(_env_num("YT_FLEET_HB_MISSES", 2)))


class FleetWorker:
    """One spawned serve.py child + its pipe lock and journal path."""

    def __init__(self, idx: int, client: ServeClient,
                 journal_path: str, gen: int = 0):
        self.idx = idx
        self.gen = gen  # bumped on every replacement spawn
        self.client = client
        self.journal_path = journal_path
        self.lock = threading.Lock()  # serializes this worker's pipe
        self.sessions: set = set()
        self.hb_misses = 0
        #: set by the autoscaler ahead of retirement: a draining
        #: worker admits NO new sessions; in-flight work finishes and
        #: live sessions migrate before the kill.
        self.draining = False

    def alive(self) -> bool:
        """Process liveness (with a short grace for the EOF→exit
        race).  Socket-transport clients are assumed alive — only the
        spawned-worker topology is supervised."""
        p = self.client._proc
        if p is None:
            return True
        try:
            p.wait(timeout=1.0)
            return False
        except subprocess.TimeoutExpired:
            return True

    def call(self, op: str, on_stream=None, **fields) -> Dict:
        with self.lock:
            prev = self.client.on_stream
            self.client.on_stream = on_stream
            try:
                out = self.client.call(op, **fields)
            finally:
                self.client.on_stream = prev
        # the pipe-level request id is this worker-client's own; the
        # fleet front re-stamps its client's id in handle()
        out.pop("id", None)
        return out

    def occupancy(self) -> Dict:
        """Live load numbers for admission (falls back to the local
        session count when the worker cannot answer)."""
        try:
            m = self.call("metrics")["metrics"]
            return {"queue_depth": int(m.get("queue_depth", 0)),
                    "sessions": int(m.get("sessions", 0)),
                    "completed": int(m.get("completed", 0))}
        except (ServeClientError, OSError, ValueError):
            return {"queue_depth": 0, "sessions": len(self.sessions),
                    "completed": -1}


class ServeFleet:
    """The routing front: spawns the workers, owns the sid->worker
    table, forwards ops."""

    def __init__(self, n_workers: int = 2,
                 cache_dir: Optional[str] = None,
                 journal_dir: Optional[str] = None,
                 worker_args: List[str] = (),
                 env: Optional[Dict[str, str]] = None,
                 hb_secs: Optional[float] = None,
                 autoscale=None):
        from yask_tpu.serve.journal import ServeJournal
        self.closing = threading.Event()
        self._route_table: Dict[str, FleetWorker] = {}
        self._lock = threading.RLock()
        self._next_sid = 0
        self._next_idem = 0
        #: per-sid failover bank: stored open fields, the last banked
        #: checkpoint (raw wire form — passed back to ``restore``
        #: verbatim), and the state-mutating ops since that boundary.
        self._bank: Dict[str, Dict] = {}
        self._jdir = journal_dir or os.getcwd()
        base_env = dict(os.environ if env is None else env)
        if cache_dir:
            base_env["YT_COMPILE_CACHE"] = cache_dir
        self.cache_dir = base_env.get("YT_COMPILE_CACHE", "")
        self._base_env = base_env
        self._worker_args = list(worker_args)
        #: the front's own lifecycle journal (worker_dead / snapshot /
        #: failover / retry — the auditable migration trail).
        self.journal = ServeJournal(os.path.join(
            self._jdir, "SERVE_JOURNAL.fleet.jsonl"))
        #: last merged telemetry snapshot (banked by the heartbeat
        #: loop / refreshed by ``op metrics_snapshot``).
        self._telemetry: Optional[Dict] = None
        #: per-worker-idx last GOOD snapshot poll: {"ts", "snap",
        #: "gen"}.  A busy worker's block is carried forward from here
        #: stamped with its age; past the staleness horizon it is
        #: flagged ``stale`` and excluded from the merged fold — the
        #: autoscaler must not scale on dead data.
        self._snap_bank: Dict[int, Dict] = {}
        #: the autoscaling policy loop (None = fixed-size fleet).
        #: ``autoscale`` may be True (env-tuned policy), an
        #: AutoscalePolicy instance (tests), or None → the
        #: YT_FLEET_AUTOSCALE master switch decides.
        self._autoscaler = None
        if autoscale is None:
            from yask_tpu.serve.autoscale import fleet_autoscale_enabled
            autoscale = fleet_autoscale_enabled()
        if autoscale:
            from yask_tpu.serve.autoscale import AutoscalePolicy
            self._autoscaler = autoscale \
                if isinstance(autoscale, AutoscalePolicy) \
                else AutoscalePolicy.from_env()
        self.workers: List[FleetWorker] = []
        for i in range(max(1, int(n_workers))):
            self.workers.append(self._spawn_worker(i))
        self._hb_secs = _env_num("YT_FLEET_HB_SECS", 0.0) \
            if hb_secs is None else float(hb_secs)
        self._hb_thread = None
        if self._hb_secs > 0:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True)
            self._hb_thread.start()

    def _spawn_worker(self, idx: int, gen: int = 0) -> FleetWorker:
        """Spawn worker ``idx`` (its own process group so an unhealthy
        one dies whole via killpg; replacements reuse the journal path
        and warm-start from the shared compile cache)."""
        jpath = os.path.join(self._jdir, f"SERVE_JOURNAL.w{idx}.jsonl")
        wenv = dict(self._base_env)
        wenv["YT_SERVE_JOURNAL"] = jpath
        client = ServeClient.spawn(
            extra_args=list(self._worker_args),
            env=wenv, stderr=subprocess.DEVNULL,
            start_new_session=True)
        return FleetWorker(idx, client, jpath, gen=gen)

    # --------------------------------------------------------- routing

    def _worker_at(self, idx: int) -> Optional[FleetWorker]:
        """Bounds-safe slot lookup (caller need not hold the lock for
        a racy identity probe).  After a scale-down pops the tail, a
        stale worker ref's idx can exceed the list — that worker was
        retired, not replaced, and the answer is None."""
        with self._lock:
            if 0 <= idx < len(self.workers):
                return self.workers[idx]
        return None

    def _route(self, sid: str) -> FleetWorker:
        """Affinity: the worker that owns this session."""
        from yask_tpu.resilience.faults import fault_point
        fault_point("fleet.route")
        with self._lock:
            w = self._route_table.get(str(sid))
        if w is None:
            raise ServeClientError(
                f"unknown fleet session {sid!r} (not opened through "
                "this fleet, or already closed)")
        return w

    def _admit(self) -> FleetWorker:
        """Placement for a new session: least-loaded NON-DRAINING
        worker by live queue depth then session count; reject with a
        structured :class:`Overloaded` (Retry-After hint, journaled
        ``overloaded`` row) when the whole fleet is past the queue
        bound — saturation answers fast, it does not time out
        slowly."""
        from yask_tpu.resilience.faults import fault_point
        from yask_tpu.serve.api import Overloaded, serve_retry_after
        fault_point("fleet.route")
        cands = [w for w in list(self.workers) if not w.draining] \
            or list(self.workers)
        occ = [(w, w.occupancy()) for w in cands]
        bound = fleet_max_queue()
        if all(o["queue_depth"] >= bound for _w, o in occ):
            ra = serve_retry_after()
            self.journal.record(
                "-", "-", "overloaded", tier=2, retry_after=ra,
                queue_bound=bound, workers=len(occ))
            raise Overloaded(
                f"fleet saturated: every worker's queue depth >= "
                f"{bound} (YT_FLEET_MAX_QUEUE)", retry_after=ra)
        occ.sort(key=lambda t: (t[1]["queue_depth"],
                                t[1]["sessions"], t[0].idx))
        return occ[0][0]

    # --------------------------------------------------- supervision

    def _hb_loop(self) -> None:
        while not self.closing.wait(self._hb_secs):
            try:
                self.supervise_tick()
            except Exception:  # noqa: BLE001 - supervision must not
                pass           # take the front down

    def supervise_tick(self) -> None:
        """One synchronous health pass over the fleet (the background
        loop calls this every ``hb_secs``; tests call it directly).
        A dead process fails over immediately; an idle worker gets a
        ``ping`` under the liveness deadline — ``YT_FLEET_HB_MISSES``
        consecutive misses declare it unhealthy.  Busy workers are
        skipped: the in-flight call path detects death by EOF."""
        for w in list(self.workers):
            if self._worker_at(w.idx) is not w:
                continue  # replaced or retired since we listed
            if not w.alive():
                self._failover(w, cause="worker process exited")
                continue
            if not w.lock.acquire(blocking=False):
                continue
            try:
                ok = self._ping_deadlined(w)
            finally:
                w.lock.release()
            if ok:
                w.hb_misses = 0
                continue
            w.hb_misses += 1
            if w.hb_misses >= fleet_hb_misses():
                self._failover(
                    w, cause=f"missed {w.hb_misses} heartbeats "
                             f"(deadline {fleet_hb_deadline()}s)")
        # telemetry rides the same cadence: bank one merged fleet
        # snapshot per tick (busy workers are skipped, not queued
        # behind — a stale per-worker block beats a stalled heartbeat)
        try:
            self.collect_telemetry(block=False)
        except Exception:  # noqa: BLE001 - telemetry must not take
            pass           # supervision down
        # elastic sizing rides the same cadence, AFTER the telemetry
        # bank so decisions read this tick's freshness stamps
        try:
            self.autoscale_tick()
        except Exception:  # noqa: BLE001 - scaling must not take
            pass           # supervision down

    def _ping_deadlined(self, w: FleetWorker) -> bool:
        """One heartbeat under the liveness deadline.  Caller holds
        ``w.lock``.  ``fleet.heartbeat`` is the front-side chaos site:
        an injected fault here IS a dropped heartbeat.  The ping runs
        on a helper thread because a hung worker never answers — a
        blocked pipe read must cost the deadline, not the supervisor
        (``run_deadlined``'s contract without the subprocess)."""
        from yask_tpu.resilience.faults import Fault, fault_point
        try:
            fault_point("fleet.heartbeat")
        except Fault:
            return False
        result: Dict = {}

        def do_ping():
            try:
                result["out"] = w.client.call("ping")
            except Exception as e:  # noqa: BLE001
                result["err"] = e

        t = threading.Thread(target=do_ping, daemon=True)
        t.start()
        t.join(fleet_hb_deadline())
        return (not t.is_alive()) and "out" in result

    def _stale_after(self) -> float:
        """The staleness horizon: a per-worker block older than 3
        heartbeat intervals is dead data (3 missed polls ≈ the worker
        is hung or the loop is wedged).  Falls back to the liveness
        deadline when no background loop runs (tests tick manually)."""
        base = self._hb_secs if self._hb_secs > 0 \
            else fleet_hb_deadline()
        return 3.0 * base

    def collect_telemetry(self, block: bool = True) -> Dict:
        """Poll every worker's ``metrics_snapshot`` and merge into ONE
        fleet snapshot (``yask_tpu.obs.telemetry.merge_snapshots`` —
        histogram sample windows pooled and re-ranked; counters/gauges
        summed; per-worker blocks kept).  ``block=False`` is the
        heartbeat path: a busy worker is skipped rather than queued
        behind its in-flight op — its LAST GOOD block is carried
        forward instead, stamped with ``poll_age_secs``, and flagged
        ``stale`` past :meth:`_stale_after` (``merge_snapshots``
        excludes flagged blocks from the fold and lists them in
        ``stale_workers``).  A replacement worker never inherits its
        predecessor's bank: carried blocks are gen-checked.  The
        merged snapshot is banked on the fleet for ``fleet_stats`` /
        ``op metrics_snapshot`` to answer from."""
        import time
        from yask_tpu.obs.telemetry import merge_snapshots
        now = time.time()
        horizon = self._stale_after()
        per: Dict[str, Dict] = {}
        for w in list(self.workers):
            wid = f"w{w.idx}"
            snap: Optional[Dict] = None
            err = ""
            if block:
                try:
                    out = w.call("metrics_snapshot")
                    snap = dict(out.get("snapshot") or {})
                except Exception as e:  # noqa: BLE001
                    err = f"{type(e).__name__}: {e}"
            elif w.lock.acquire(blocking=False):
                try:
                    out = w.client.call("metrics_snapshot")
                    snap = dict(out.get("snapshot") or {})
                except Exception:  # noqa: BLE001
                    snap = None
                finally:
                    w.lock.release()
            if snap is not None:
                snap["gen"] = w.gen
                snap["poll_age_secs"] = 0.0
                with self._lock:
                    self._snap_bank[w.idx] = {
                        "ts": now, "snap": dict(snap), "gen": w.gen}
                per[wid] = snap
                continue
            # busy or failed poll: carry the banked block forward,
            # honestly aged — never a block from an older generation
            with self._lock:
                b = self._snap_bank.get(w.idx)
            if b is not None and b["gen"] == w.gen:
                age = max(0.0, now - b["ts"])
                snap = dict(b["snap"])
                snap["poll_age_secs"] = age
                if age > horizon:
                    snap["stale"] = True
                per[wid] = snap
            elif err:
                per[wid] = {"error": err}
        merged = merge_snapshots(per, ts=now)
        with self._lock:
            self._telemetry = merged
        return merged

    def _failover(self, w: FleetWorker, cause="") -> FleetWorker:
        """Replace a dead/unhealthy worker and fail its sessions over.
        Idempotent per worker object: concurrent detectors (heartbeat
        loop, in-flight EOF) race to the fleet lock and the losers see
        the replacement already installed."""
        with self._lock:
            cur = self._worker_at(w.idx)
            if cur is not w:
                return cur if cur is not None else w
            self.journal.record(
                f"w{w.idx}.g{w.gen}", "-", "worker_dead",
                worker=w.idx, gen=w.gen, cause=str(cause)[:200],
                sessions=sorted(w.sessions))
            self._kill_worker(w)
            repl = self._spawn_worker(w.idx, gen=w.gen + 1)
            self.workers[w.idx] = repl
            self._recover_sessions(w, repl)
            return repl

    @staticmethod
    def _kill_worker(w: FleetWorker) -> None:
        """SIGKILL the worker's whole process group (it was spawned
        with ``start_new_session=True``) and drop the pipes — the
        ``run_deadlined`` semantics applied to a worker."""
        import signal
        p = w.client._proc
        if p is not None:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                try:
                    p.kill()
                except (OSError, ProcessLookupError):
                    pass
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        for f in (w.client._w, w.client._r):
            try:
                f.close()
            except Exception:  # noqa: BLE001
                pass

    def _recover_sessions(self, dead: FleetWorker,
                          repl: FleetWorker) -> None:
        """Re-open every session routed to the dead worker on the
        replacement, restore the banked checkpoint, and replay the
        state-mutating ops past that committed boundary (deterministic
        — the r14 contract makes the result bit-identical to an
        uninterrupted run).  Caller holds the fleet lock."""
        for sid in sorted(dead.sessions):
            self._recover_one(sid, dead, repl)

    def _recover_one(self, sid: str, src: FleetWorker,
                     dst: FleetWorker, cause: str = "failover") -> bool:
        """Migrate ONE session ``src`` → ``dst`` through the banked
        checkpoint + replay-log path; journals a ``failover`` row
        either way (``cause`` distinguishes a death from an autoscaler
        drain).  An unrecoverable session is dropped from routing so
        it cannot block the rest of the fleet."""
        b = self._bank.get(sid)
        try:
            if b is None:
                raise ServeClientError("no banked open fields")
            dst.call("open", **b["open"])
            snap_step = None
            if b["snapshot"] is not None:
                out = dst.call("restore", sid=sid,
                               meta=b["snapshot"]["meta"],
                               state=b["snapshot"]["state"])
                if not out.get("ok"):
                    raise ServeClientError(
                        "banked snapshot did not apply")
                snap_step = int(
                    b["snapshot"]["meta"].get("cur_step", 0))
            replayed = []
            for m in b["log"]:
                dst.call(m["op"], **{k: v for k, v in m.items()
                                     if k not in ("op", "id")})
                if m["op"] == "run":
                    replayed.append(
                        [int(m.get("first", 0)),
                         m.get("last")])
            with self._lock:
                self._route_table[sid] = dst
                dst.sessions.add(sid)
                src.sessions.discard(sid)
            self.journal.record(
                sid, sid, "failover", dead_worker=src.idx,
                dead_gen=src.gen, to_worker=dst.idx,
                to_gen=dst.gen, snapshot_step=snap_step,
                replayed=replayed, cause=cause)
            return True
        except Exception as e:  # noqa: BLE001 - an unrecoverable
            # session must not block the rest of the fleet
            with self._lock:
                self._route_table.pop(sid, None)
                src.sessions.discard(sid)
            self.journal.record(
                sid, sid, "failover", dead_worker=src.idx,
                dead_gen=src.gen, recovered=False, cause=cause,
                error=f"{type(e).__name__}: {e}")
            return False

    # ---------------------------------------------------- autoscaling

    def autoscale_tick(self) -> None:
        """One autoscaler pass (rides the supervision cadence, after
        the telemetry bank).  No-op on a fixed-size fleet.  The policy
        (yask_tpu/serve/autoscale.py) decides; this method is the
        mechanism: warm spawn from the shared compile cache on UP,
        drain + migrate + retire on DOWN."""
        if self._autoscaler is None:
            return
        from yask_tpu.serve.autoscale import signals_from_snapshot
        with self._lock:
            merged = self._telemetry
            n = len(self.workers)
            nd = sum(1 for w in self.workers if w.draining)
        sig = signals_from_snapshot(merged, n, nd)
        dec = self._autoscaler.decide(sig)
        if dec is None:
            return
        if dec.action == "up":
            self._scale_up(dec)
        elif dec.action == "down":
            self._scale_down(dec)

    def _scale_up(self, dec) -> Optional[FleetWorker]:
        """Append one worker (warm spawn: the shared YT_COMPILE_CACHE
        means its first request deserializes with zero lowerings) and
        journal the decision joined to the triggering trace."""
        from yask_tpu.resilience.faults import Fault, fault_point
        try:
            fault_point("fleet.scale")
        except Fault as e:
            self.journal.record(
                "-", "-", "fault", site="fleet.scale", kind=e.kind,
                error=str(e)[:200])
            return None
        with self._lock:
            idx = len(self.workers)
            w = self._spawn_worker(idx)
            self.workers.append(w)
        self.journal.record(
            f"w{idx}.g0", "-", "scale_up",
            trace_id=self._latest_breach_trace(),
            worker=idx, reason=dec.reason, signal=dec.signal,
            cache_dir=self.cache_dir)
        return w

    def _scale_down(self, dec) -> None:
        """Retire the tail worker: journal ``drain``, stop admitting
        (``draining`` flag), migrate every live session through the
        checkpoint path, then kill and pop.  Only the TAIL is ever
        retired so ``idx == list position`` stays invariant."""
        from yask_tpu.resilience.faults import Fault, fault_point
        with self._lock:
            if len(self.workers) <= 1:
                return
            w = self.workers[-1]
            if w.draining:
                return  # a prior drain is still in flight
            w.draining = True
        self.journal.record(
            f"w{w.idx}.g{w.gen}", "-", "drain", worker=w.idx,
            gen=w.gen, reason=dec.reason, signal=dec.signal,
            sessions=sorted(w.sessions))
        try:
            fault_point("fleet.drain")
        except Fault as e:
            with self._lock:
                w.draining = False  # aborted: keep serving
            self.journal.record(
                "-", "-", "fault", site="fleet.drain", kind=e.kind,
                error=str(e)[:200])
            return
        self._drain_worker(w, dec)

    def _drain_worker(self, w: FleetWorker, dec) -> None:
        """The mechanism behind a ``scale_down``: snapshot each live
        session at the drain boundary (fresh checkpoint → zero
        replay), migrate it to the least-loaded surviving worker, then
        retire the drained worker.  Waiting on the worker lock inside
        ``snapshot`` naturally lets in-flight (chunked) runs finish
        first — nothing in flight is abandoned."""
        migrated: List[str] = []
        lost: List[str] = []
        for sid in sorted(w.sessions):
            self._bank_snapshot(sid)
            dst = self._pick_target(exclude=w)
            if dst is None:
                with self._lock:
                    self._route_table.pop(sid, None)
                    w.sessions.discard(sid)
                lost.append(sid)
                continue
            ok = self._recover_one(sid, w, dst, cause="drain")
            (migrated if ok else lost).append(sid)
        with self._lock:
            if self.workers and self.workers[-1] is w:
                self.workers.pop()
            self._snap_bank.pop(w.idx, None)
        self._kill_worker(w)
        self.journal.record(
            f"w{w.idx}.g{w.gen}", "-", "scale_down", worker=w.idx,
            gen=w.gen, reason=dec.reason, signal=dec.signal,
            migrated=migrated, lost=lost)

    def _pick_target(self, exclude: FleetWorker) \
            -> Optional[FleetWorker]:
        """Least-loaded live, non-draining worker other than
        ``exclude`` (the migration destination during a drain)."""
        cands = [w for w in list(self.workers)
                 if w is not exclude and not w.draining and w.alive()]
        if not cands:
            return None
        occ = [(w, w.occupancy()) for w in cands]
        occ.sort(key=lambda t: (t[1]["queue_depth"],
                                t[1]["sessions"], t[0].idx))
        return occ[0][0]

    def _latest_breach_trace(self) -> str:
        """The newest journaled ``slo_breach`` row's trace id across
        the worker journals — the join key a ``scale_up`` row carries
        back to the request that tripped the burn-rate signal (""
        when no breach was ever journaled or tracing is off)."""
        best_ts, best = "", ""
        for w in list(self.workers):
            try:
                with open(w.journal_path, "r",
                          encoding="utf-8") as f:
                    for line in f:
                        if '"slo_breach"' not in line:
                            continue
                        try:
                            row = json.loads(line)
                        except ValueError:
                            continue
                        if row.get("event") != "slo_breach":
                            continue
                        ts = str(row.get("ts", ""))
                        if ts >= best_ts:  # ISO-8601 sorts by time
                            best_ts = ts
                            best = str(row.get("trace_id", "") or "")
            except OSError:
                continue
        return best

    # -------------------------------------------------- checkpointing

    def _stamp_idem(self, msg: dict) -> str:
        """Front-generated idempotency key, stamped onto every
        forwarded op (workers ignore unknown fields).  A retry after
        failover carries the SAME key, and the journal ``retry`` row
        records it — the exactly-once audit trail."""
        with self._lock:
            idem = msg.get("idem") or f"i{self._next_idem:06d}"
            self._next_idem += 1
        msg["idem"] = idem
        return idem

    @staticmethod
    def _stamp_trace(msg: dict) -> str:
        """Front-stamped end-to-end trace id (same shape as
        ``_stamp_idem``): one id per client op, riding the forwarded
        wire msg so the worker's journal/ledger rows and a failover
        replay (the banked msg is re-issued verbatim, gen+1 included)
        all join the SAME trace.  No-op unless ``YT_TRACE`` is on —
        the msg is untouched and "" comes back."""
        from yask_tpu.obs.tracer import new_trace_id, trace_enabled
        tid = str(msg.get("trace", "") or "")
        if not tid and trace_enabled():
            tid = new_trace_id()
            msg["trace"] = tid
        return tid

    @staticmethod
    def _mutates(op: str) -> bool:
        return op in ("fill", "init", "run", "restore")

    def _note_ok(self, sid: str, msg: dict) -> None:
        """Bookkeeping after a successful forwarded op: log state
        mutations for replay; bank a fresh checkpoint once the
        session has run ``YT_FLEET_CKPT_EVERY`` steps past the last
        committed boundary."""
        op = msg.get("op", "")
        if not self._mutates(op):
            return
        with self._lock:
            b = self._bank.get(sid)
            if b is None:
                return
            b["log"].append(dict(msg))
            if op == "run":
                first = int(msg.get("first", 0))
                last = msg.get("last")
                b["steps"] += (1 if last is None
                               else max(1, int(last) - first + 1))
                due = b["steps"] >= fleet_ckpt_every()
            else:
                due = False
        if due:
            self._bank_snapshot(sid)

    def _bank_snapshot(self, sid: str) -> bool:
        """Pull a checkpoint from the owning worker and bank it as the
        session's committed boundary (clears the replay log).  Banked
        in raw wire form — ``restore`` gets it back verbatim, so the
        front never decodes arrays.  A failed snapshot just keeps the
        longer replay log: correctness does not depend on cadence."""
        try:
            w = self._route(sid)
            out = w.call("snapshot", sid=sid)
        except Exception:  # noqa: BLE001
            return False
        if not out.get("ok"):
            return False
        with self._lock:
            b = self._bank.get(sid)
            if b is None:
                return False
            b["snapshot"] = {"meta": out["meta"],
                             "state": out["state"]}
            b["log"] = []
            b["steps"] = 0
        self.journal.record(
            sid, sid, "snapshot",
            step=int(out["meta"].get("cur_step", 0)), worker=w.idx)
        return True

    def _maybe_snapshot_before_run(self, sid: str) -> None:
        """Pre-run commit point: bank a checkpoint when none exists
        yet or when un-snapshotted fills/inits are in the log (state
        writes are cheaper to bank once than to hold for replay
        forever)."""
        with self._lock:
            b = self._bank.get(sid)
            need = b is not None and (
                b["snapshot"] is None
                or any(m.get("op") != "run" for m in b["log"]))
        if need:
            self._bank_snapshot(sid)

    # ------------------------------------------------------------- ops

    def handle(self, msg: dict, emit=None) -> dict:
        op = msg.get("op")
        fn = getattr(self, f"op_{op}", None)
        from yask_tpu.obs.tracer import activate, span
        from yask_tpu.serve.api import Overloaded
        tid = self._stamp_trace(msg)
        try:
            with activate(tid), \
                    span(f"fleet.{op}", phase="front", trace=tid,
                         sid=msg.get("sid", "")):
                if fn is not None:
                    out = fn(msg, emit)
                elif "sid" in msg:
                    # any other session-scoped op: pure affinity forward
                    out = self._forward(msg, emit)
                else:
                    out = {"ok": False, "error": f"unknown op {op!r}"}
        except Overloaded as e:
            # structured rejection: clients key on "overloaded" and
            # honor the Retry-After hint, no error-string parsing
            out = {"ok": False, "error": f"Overloaded: {e}",
                   "overloaded": True,
                   "retry_after": float(e.retry_after)}
        except Exception as e:  # noqa: BLE001 - the front must answer
            out = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        if "id" in msg:
            out["id"] = msg["id"]
        if tid and "trace" not in out:
            out["trace"] = tid
        return out

    def _forward(self, msg: dict, emit=None) -> dict:
        sid = msg["sid"]
        if msg.get("op") == "run":
            self._maybe_snapshot_before_run(sid)
        out = self._call_with_failover(msg, emit, sids=(sid,))
        # anomaly runs DID execute (sanity quarantined the outputs but
        # worker state advanced) — they must enter the replay log or a
        # later failover restores a state missing those steps.
        if out.get("ok") or out.get("status") == "anomaly":
            self._note_ok(sid, msg)
        return out

    def _call_with_failover(self, msg: dict, emit=None,
                            sids=()) -> dict:
        """Forward to the owning worker; when the worker DIED mid-op
        (EOF/broken pipe + process gone), fail over and re-issue the
        op exactly once under its idempotency key.  Application errors
        from a live worker re-raise untouched — only a lost answer is
        retryable."""
        idem = self._stamp_idem(msg)
        w = self._route(msg["sid"] if "sid" in msg else sids[0])
        try:
            return self._worker_call(w, msg, emit)
        except (ServeClientError, OSError) as e:
            replaced = self._worker_at(w.idx) is not w
            if not replaced and w.alive():
                raise  # the worker answered; not a death
            self._failover(w, cause=e)
            sid0 = msg.get("sid") or (sids[0] if sids else "")
            w2 = self._route(sid0)  # raises when not recovered
            self.journal.record(idem, sid0, "retry", idem=idem,
                                op=msg.get("op", ""), worker=w2.idx,
                                gen=w2.gen)
            return self._worker_call(w2, msg, emit)

    @staticmethod
    def _worker_call(w: FleetWorker, msg: dict, emit=None) -> dict:
        hook = None
        if emit is not None:
            def hook(ev):  # re-emit worker stream lines to our client
                try:
                    from tools.serve_client import encode_array
                    line = dict(ev)
                    if "outputs" in line:
                        line["outputs"] = {
                            k: encode_array(v)
                            for k, v in line["outputs"].items()}
                    if "id" in msg:
                        line["id"] = msg["id"]
                    emit(line)
                except Exception:  # noqa: BLE001 - beacon only
                    pass
        fields = {k: v for k, v in msg.items() if k not in ("op", "id")}
        try:
            return w.call(msg["op"], on_stream=hook, **fields)
        except ServeClientError as e:
            resp = getattr(e, "response", None)
            if isinstance(resp, dict):
                # the worker ANSWERED ok:false (rejected / anomaly /
                # app error): pass the STRUCTURED response through —
                # clients key on status/anomaly fields, and failover
                # must never re-run an op a live worker executed.
                out = dict(resp)
                out.pop("id", None)  # handle() re-stamps ours
                return out
            raise

    def op_open(self, msg, emit=None):
        w = self._admit()
        with self._lock:
            sid = msg.get("session") or f"f{self._next_sid:04d}"
            self._next_sid += 1
            if sid in self._route_table:
                return {"ok": False,
                        "error": f"fleet session {sid!r} already open"}
        fields = {k: v for k, v in msg.items() if k not in ("op", "id")}
        fields["session"] = sid
        try:
            out = w.call("open", **fields)
        except (ServeClientError, OSError) as e:
            resp = getattr(e, "response", None)
            if isinstance(resp, dict) and resp.get("overloaded"):
                # worker-level brownout (tier 2): the structured
                # rejection + Retry-After hint rides through the fleet
                out2 = dict(resp)
                out2.pop("id", None)
                return out2
            replaced = self._worker_at(w.idx) is not w
            if not replaced and w.alive():
                raise
            self._failover(w, cause=e)
            w = self._admit()  # re-place on a live worker, once
            out = w.call("open", **fields)
        with self._lock:
            self._route_table[out["sid"]] = w
            w.sessions.add(out["sid"])
            self._bank[out["sid"]] = {"open": dict(fields),
                                      "snapshot": None,
                                      "log": [], "steps": 0}
        out["worker"] = w.idx
        return out

    def op_close(self, msg, emit=None):
        w = self._route(msg["sid"])
        out = w.call("close", sid=msg["sid"])
        with self._lock:
            self._route_table.pop(msg["sid"], None)
            self._bank.pop(msg["sid"], None)
            w.sessions.discard(msg["sid"])
        return out

    def op_run_many(self, msg, emit=None):
        """Split by owning worker, forward each shard concurrently
        (submit-all-then-wait-all must reach each worker as one op to
        land inside its batching window), reassemble in order."""
        reqs = msg["requests"]
        shards: Dict[int, List[int]] = {}
        for i, m in enumerate(reqs):
            w = self._route(m["sid"])
            shards.setdefault(w.idx, []).append(i)
        results: List[Optional[dict]] = [None] * len(reqs)
        errs: List[str] = []

        def run_shard(widx: int, idxs: List[int]) -> None:
            shard_sids = [reqs[i]["sid"] for i in idxs]
            for sid in dict.fromkeys(shard_sids):
                self._maybe_snapshot_before_run(sid)
            sub = {"op": "run_many",
                   "requests": [reqs[i] for i in idxs]}
            if msg.get("trace"):
                sub["trace"] = msg["trace"]
            if "timeout" in msg:
                sub["timeout"] = msg["timeout"]
            if "id" in msg:
                sub["id"] = msg["id"]
            try:
                out = self._call_with_failover(sub, emit,
                                               sids=shard_sids)
                for i, r in zip(idxs, out["responses"]):
                    results[i] = r
                for i in idxs:
                    self._note_ok(reqs[i]["sid"],
                                  {"op": "run", **reqs[i]})
            except Exception as e:  # noqa: BLE001
                errs.append(f"worker {widx}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=run_shard, args=(wi, ix))
                   for wi, ix in shards.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            return {"ok": False, "error": "; ".join(errs)}
        return {"ok": True, "responses": results}

    def op_metrics(self, msg, emit=None):
        """Aggregated fleet metrics + the per-worker breakdown."""
        per = []
        for w in self.workers:
            try:
                m = w.call("metrics")["metrics"]
            except Exception as e:  # noqa: BLE001
                m = {"error": f"{type(e).__name__}: {e}"}
            m["worker"] = w.idx
            per.append(m)
        agg = {"queue_depth": sum(m.get("queue_depth", 0) for m in per),
               "sessions": sum(m.get("sessions", 0) for m in per),
               "completed": sum(m.get("completed", 0) for m in per),
               "workers": per}
        return {"ok": True, "metrics": agg}

    def op_fleet_stats(self, msg, emit=None):
        rows = []
        slo_breaches = 0
        for w in self.workers:
            row = {"worker": w.idx, "journal": w.journal_path,
                   "sessions": sorted(w.sessions),
                   **w.occupancy()}
            try:
                cs = w.call("cache_stats")
                row["cache"] = cs.get("stats", {})
                row["cache_dir"] = cs.get("cache_dir")
            except Exception as e:  # noqa: BLE001
                row["cache"] = {"error": f"{type(e).__name__}: {e}"}
            # SLO surfacing: the worker's monitor state + journaled
            # breach count (None slo = no YT_SLO_* knobs set)
            try:
                snap = w.call("metrics_snapshot").get("snapshot", {})
                row["slo"] = snap.get("slo")
                n = int((snap.get("journal") or {})
                        .get("slo_breaches", 0))
                row["slo_breaches"] = n
                slo_breaches += n
            except Exception as e:  # noqa: BLE001
                row["slo"] = {"error": f"{type(e).__name__}: {e}"}
            rows.append(row)
        out = {"ok": True, "cache_dir": self.cache_dir,
               "slo_breaches": slo_breaches, "workers": rows,
               "autoscale": self._autoscaler is not None,
               "draining": [w.idx for w in self.workers
                            if w.draining]}
        with self._lock:
            if self._telemetry is not None:
                out["telemetry_ts"] = self._telemetry.get("ts")
                out["stale_workers"] = list(
                    self._telemetry.get("stale_workers") or [])
        return out

    def op_metrics_snapshot(self, msg, emit=None):
        """The merged fleet-wide telemetry snapshot (fresh poll; the
        heartbeat loop banks the same shape every tick)."""
        return {"ok": True, "telemetry": self.collect_telemetry()}

    def op_cache_stats(self, msg, emit=None):
        """Per-worker compile-cache counters (warm-start evidence)."""
        out = {}
        for w in self.workers:
            try:
                out[str(w.idx)] = w.call("cache_stats").get("stats", {})
            except Exception as e:  # noqa: BLE001
                out[str(w.idx)] = {"error": f"{type(e).__name__}: {e}"}
        return {"ok": True, "stats": out}

    def op_flush_metrics(self, msg, emit=None):
        n = 0
        for w in self.workers:
            try:
                n += int(w.call("flush_metrics").get("rows", 0))
            except Exception:  # noqa: BLE001
                pass
        return {"ok": True, "rows": n}

    def op_shutdown(self, msg, emit=None):
        self.closing.set()
        return {"ok": True}

    # ------------------------------------------------------- lifecycle

    def close(self) -> None:
        self.closing.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
        for w in self.workers:
            try:
                with w.lock:
                    w.client.close()
            except Exception:  # noqa: BLE001
                pass


def _serve_stream(fleet: ServeFleet, rfile, wfile) -> None:
    wlock = threading.Lock()

    def emit(obj: dict) -> None:
        with wlock:
            wfile.write(json.dumps(obj, sort_keys=True) + "\n")
            wfile.flush()

    for line in rfile:
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
        except ValueError as e:
            out = {"ok": False, "error": f"bad JSON: {e}"}
        else:
            out = fleet.handle(msg, emit=emit)
        emit(out)
        if fleet.closing.is_set():
            return


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="N-worker stencil-serving fleet front")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--cache-dir", default=None,
                    help="shared compile cache (YT_COMPILE_CACHE; "
                         "workers 1+ warm-start from worker 0's "
                         "compiles)")
    ap.add_argument("--journal-dir", default=None,
                    help="directory for per-worker journals "
                         "(SERVE_JOURNAL.w<i>.jsonl; default: cwd)")
    ap.add_argument("--port", type=int, default=None,
                    help="listen on TCP (default: stdio)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--window_ms", type=float, default=None)
    ap.add_argument("--max_batch", type=int, default=None)
    ap.add_argument("--no-preflight", action="store_true")
    ap.add_argument("--hb_secs", type=float, default=5.0,
                    help="heartbeat supervision interval; 0 disables "
                         "the background health loop "
                         "(YT_FLEET_HB_SECS overrides when unset)")
    ap.add_argument("--autoscale", action="store_true",
                    help="enable the SLO-driven autoscaler "
                         "(YT_FLEET_MIN/MAX_WORKERS bounds, "
                         "YT_FLEET_SCALE_* thresholds; also "
                         "switchable via YT_FLEET_AUTOSCALE=1)")
    args = ap.parse_args(argv)

    wargs: List[str] = []
    if args.window_ms is not None:
        wargs += ["--window_ms", str(args.window_ms)]
    if args.max_batch is not None:
        wargs += ["--max_batch", str(args.max_batch)]
    if args.no_preflight:
        wargs += ["--no-preflight"]

    fleet = ServeFleet(n_workers=args.workers,
                       cache_dir=args.cache_dir,
                       journal_dir=args.journal_dir,
                       worker_args=wargs,
                       hb_secs=args.hb_secs,
                       autoscale=True if args.autoscale else None)
    try:
        if args.port is not None:
            import socket
            srv = socket.create_server((args.host, args.port))
            srv.settimeout(0.5)
            sys.stderr.write(
                f"serve_fleet: {len(fleet.workers)} workers on "
                f"{args.host}:{srv.getsockname()[1]}\n")
            sys.stderr.flush()
            threads = []
            try:
                while not fleet.closing.is_set():
                    try:
                        conn, _addr = srv.accept()
                    except socket.timeout:
                        continue
                    t = threading.Thread(
                        target=_serve_stream,
                        args=(fleet, conn.makefile("r", encoding="utf-8"),
                              conn.makefile("w", encoding="utf-8")),
                        daemon=True)
                    t.start()
                    threads.append(t)
            finally:
                srv.close()
                for t in threads:
                    t.join(timeout=2.0)
        else:
            sys.stderr.write(
                f"serve_fleet: {len(fleet.workers)} workers ready "
                "(stdio)\n")
            sys.stderr.flush()
            _serve_stream(fleet, sys.stdin, sys.stdout)
    finally:
        fleet.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
