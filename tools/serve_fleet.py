#!/usr/bin/env python
"""A warm-cache serving FLEET: N ``tools/serve.py`` workers behind one
JSON-lines front.

Topology::

    client  ──stdio/TCP──  serve_fleet.py  ──stdio pipes──  worker 0
                                 │                          worker 1
                                 │                          ...
                           (routing table: sid -> worker)

* **Workers** are plain ``tools/serve.py`` stdio children
  (``tools/serve_client.py`` transport), each with its OWN journal
  (``SERVE_JOURNAL.w<i>.jsonl`` — per-worker lifecycle evidence, and
  how the affinity test proves a session never migrated) and a SHARED
  on-disk compile cache (``YT_COMPILE_CACHE``): worker 0's compiles
  land in the cache, so worker 1+'s first request deserializes with
  ZERO lowerings (``cache_stats``) — the fleet's scale-out contract.
* **Session affinity**: ``open`` places a session on one worker
  (admission control below) and every later op for that sid routes to
  the same worker — session state lives in worker memory, migration
  would lose it.  The fleet namespaces session ids (``f0000...``) so
  two workers can never hand out colliding ids.
* **Admission control**: placement reads each worker's live metrics
  (queue depth, open sessions — the same numbers the journal
  occupancy rows carry); the least-loaded worker wins, and when every
  worker's queue is past ``YT_FLEET_MAX_QUEUE`` (default 64) the op
  is rejected instead of queued — saturation answers fast, it does
  not time out slowly.  Routing decisions pass the ``fleet.route``
  fault point (``YT_FAULT_PLAN`` injectable; a classified fault
  rejects that op, it never kills the fleet).
* **Streaming** passes through: a worker's interleaved
  ``{"stream": true}`` lines are re-emitted to the fleet's client as
  they arrive (per-worker pipes are serialized by a lock, so a
  stream line can only belong to the in-flight call on that worker).

The fleet front performs no device work itself — every op is a
forwarded worker call over pipes; the guarded device sites live in the
workers' serve package.

Usage::

    python tools/serve_fleet.py --workers 2 --cache-dir /tmp/ytcache
    # then speak the tools/serve.py JSON-lines protocol on stdio, or
    # --port for TCP.  Extra op: {"op": "fleet_stats"}.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.serve_client import ServeClient, ServeClientError


def fleet_max_queue() -> int:
    try:
        return max(1, int(os.environ.get("YT_FLEET_MAX_QUEUE", "")
                          or 64))
    except ValueError:
        return 64


class FleetWorker:
    """One spawned serve.py child + its pipe lock and journal path."""

    def __init__(self, idx: int, client: ServeClient,
                 journal_path: str):
        self.idx = idx
        self.client = client
        self.journal_path = journal_path
        self.lock = threading.Lock()  # serializes this worker's pipe
        self.sessions: set = set()

    def call(self, op: str, on_stream=None, **fields) -> Dict:
        with self.lock:
            prev = self.client.on_stream
            self.client.on_stream = on_stream
            try:
                out = self.client.call(op, **fields)
            finally:
                self.client.on_stream = prev
        # the pipe-level request id is this worker-client's own; the
        # fleet front re-stamps its client's id in handle()
        out.pop("id", None)
        return out

    def occupancy(self) -> Dict:
        """Live load numbers for admission (falls back to the local
        session count when the worker cannot answer)."""
        try:
            m = self.call("metrics")["metrics"]
            return {"queue_depth": int(m.get("queue_depth", 0)),
                    "sessions": int(m.get("sessions", 0)),
                    "completed": int(m.get("completed", 0))}
        except (ServeClientError, OSError, ValueError):
            return {"queue_depth": 0, "sessions": len(self.sessions),
                    "completed": -1}


class ServeFleet:
    """The routing front: spawns the workers, owns the sid->worker
    table, forwards ops."""

    def __init__(self, n_workers: int = 2,
                 cache_dir: Optional[str] = None,
                 journal_dir: Optional[str] = None,
                 worker_args: List[str] = (),
                 env: Optional[Dict[str, str]] = None):
        self.closing = threading.Event()
        self._route_table: Dict[str, FleetWorker] = {}
        self._lock = threading.RLock()
        self._next_sid = 0
        jdir = journal_dir or os.getcwd()
        base_env = dict(os.environ if env is None else env)
        if cache_dir:
            base_env["YT_COMPILE_CACHE"] = cache_dir
        self.cache_dir = base_env.get("YT_COMPILE_CACHE", "")
        self.workers: List[FleetWorker] = []
        for i in range(max(1, int(n_workers))):
            jpath = os.path.join(jdir, f"SERVE_JOURNAL.w{i}.jsonl")
            wenv = dict(base_env)
            wenv["YT_SERVE_JOURNAL"] = jpath
            client = ServeClient.spawn(
                extra_args=list(worker_args),
                env=wenv, stderr=subprocess.DEVNULL)
            self.workers.append(FleetWorker(i, client, jpath))

    # --------------------------------------------------------- routing

    def _route(self, sid: str) -> FleetWorker:
        """Affinity: the worker that owns this session."""
        from yask_tpu.resilience.faults import fault_point
        fault_point("fleet.route")
        with self._lock:
            w = self._route_table.get(str(sid))
        if w is None:
            raise ServeClientError(
                f"unknown fleet session {sid!r} (not opened through "
                "this fleet, or already closed)")
        return w

    def _admit(self) -> FleetWorker:
        """Placement for a new session: least-loaded worker by live
        queue depth then session count; reject when the whole fleet is
        past the queue bound (saturation answers fast)."""
        from yask_tpu.resilience.faults import fault_point
        fault_point("fleet.route")
        occ = [(w, w.occupancy()) for w in self.workers]
        bound = fleet_max_queue()
        if all(o["queue_depth"] >= bound for _w, o in occ):
            raise ServeClientError(
                f"fleet saturated: every worker's queue depth >= "
                f"{bound} (YT_FLEET_MAX_QUEUE)")
        occ.sort(key=lambda t: (t[1]["queue_depth"],
                                t[1]["sessions"], t[0].idx))
        return occ[0][0]

    # ------------------------------------------------------------- ops

    def handle(self, msg: dict, emit=None) -> dict:
        op = msg.get("op")
        fn = getattr(self, f"op_{op}", None)
        try:
            if fn is not None:
                out = fn(msg, emit)
            elif "sid" in msg:
                # any other session-scoped op: pure affinity forward
                out = self._forward(msg, emit)
            else:
                out = {"ok": False, "error": f"unknown op {op!r}"}
        except Exception as e:  # noqa: BLE001 - the front must answer
            out = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        if "id" in msg:
            out["id"] = msg["id"]
        return out

    def _forward(self, msg: dict, emit=None) -> dict:
        w = self._route(msg["sid"])
        return self._worker_call(w, msg, emit)

    @staticmethod
    def _worker_call(w: FleetWorker, msg: dict, emit=None) -> dict:
        hook = None
        if emit is not None:
            def hook(ev):  # re-emit worker stream lines to our client
                try:
                    from tools.serve_client import encode_array
                    line = dict(ev)
                    if "outputs" in line:
                        line["outputs"] = {
                            k: encode_array(v)
                            for k, v in line["outputs"].items()}
                    if "id" in msg:
                        line["id"] = msg["id"]
                    emit(line)
                except Exception:  # noqa: BLE001 - beacon only
                    pass
        fields = {k: v for k, v in msg.items() if k not in ("op", "id")}
        return w.call(msg["op"], on_stream=hook, **fields)

    def op_open(self, msg, emit=None):
        w = self._admit()
        with self._lock:
            sid = msg.get("session") or f"f{self._next_sid:04d}"
            self._next_sid += 1
            if sid in self._route_table:
                return {"ok": False,
                        "error": f"fleet session {sid!r} already open"}
        fields = {k: v for k, v in msg.items() if k not in ("op", "id")}
        fields["session"] = sid
        out = w.call("open", **fields)
        with self._lock:
            self._route_table[out["sid"]] = w
            w.sessions.add(out["sid"])
        out["worker"] = w.idx
        return out

    def op_close(self, msg, emit=None):
        w = self._route(msg["sid"])
        out = w.call("close", sid=msg["sid"])
        with self._lock:
            self._route_table.pop(msg["sid"], None)
            w.sessions.discard(msg["sid"])
        return out

    def op_run_many(self, msg, emit=None):
        """Split by owning worker, forward each shard concurrently
        (submit-all-then-wait-all must reach each worker as one op to
        land inside its batching window), reassemble in order."""
        reqs = msg["requests"]
        shards: Dict[int, List[int]] = {}
        for i, m in enumerate(reqs):
            w = self._route(m["sid"])
            shards.setdefault(w.idx, []).append(i)
        results: List[Optional[dict]] = [None] * len(reqs)
        errs: List[str] = []

        def run_shard(widx: int, idxs: List[int]) -> None:
            w = self.workers[widx]
            sub = {"op": "run_many",
                   "requests": [reqs[i] for i in idxs]}
            if "timeout" in msg:
                sub["timeout"] = msg["timeout"]
            if "id" in msg:
                sub["id"] = msg["id"]
            try:
                out = self._worker_call(w, sub, emit)
                for i, r in zip(idxs, out["responses"]):
                    results[i] = r
            except Exception as e:  # noqa: BLE001
                errs.append(f"worker {widx}: {type(e).__name__}: {e}")

        threads = [threading.Thread(target=run_shard, args=(wi, ix))
                   for wi, ix in shards.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            return {"ok": False, "error": "; ".join(errs)}
        return {"ok": True, "responses": results}

    def op_metrics(self, msg, emit=None):
        """Aggregated fleet metrics + the per-worker breakdown."""
        per = []
        for w in self.workers:
            try:
                m = w.call("metrics")["metrics"]
            except Exception as e:  # noqa: BLE001
                m = {"error": f"{type(e).__name__}: {e}"}
            m["worker"] = w.idx
            per.append(m)
        agg = {"queue_depth": sum(m.get("queue_depth", 0) for m in per),
               "sessions": sum(m.get("sessions", 0) for m in per),
               "completed": sum(m.get("completed", 0) for m in per),
               "workers": per}
        return {"ok": True, "metrics": agg}

    def op_fleet_stats(self, msg, emit=None):
        rows = []
        for w in self.workers:
            row = {"worker": w.idx, "journal": w.journal_path,
                   "sessions": sorted(w.sessions),
                   **w.occupancy()}
            try:
                cs = w.call("cache_stats")
                row["cache"] = cs.get("stats", {})
                row["cache_dir"] = cs.get("cache_dir")
            except Exception as e:  # noqa: BLE001
                row["cache"] = {"error": f"{type(e).__name__}: {e}"}
            rows.append(row)
        return {"ok": True, "cache_dir": self.cache_dir,
                "workers": rows}

    def op_cache_stats(self, msg, emit=None):
        """Per-worker compile-cache counters (warm-start evidence)."""
        out = {}
        for w in self.workers:
            try:
                out[str(w.idx)] = w.call("cache_stats").get("stats", {})
            except Exception as e:  # noqa: BLE001
                out[str(w.idx)] = {"error": f"{type(e).__name__}: {e}"}
        return {"ok": True, "stats": out}

    def op_flush_metrics(self, msg, emit=None):
        n = 0
        for w in self.workers:
            try:
                n += int(w.call("flush_metrics").get("rows", 0))
            except Exception:  # noqa: BLE001
                pass
        return {"ok": True, "rows": n}

    def op_shutdown(self, msg, emit=None):
        self.closing.set()
        return {"ok": True}

    # ------------------------------------------------------- lifecycle

    def close(self) -> None:
        for w in self.workers:
            try:
                with w.lock:
                    w.client.close()
            except Exception:  # noqa: BLE001
                pass


def _serve_stream(fleet: ServeFleet, rfile, wfile) -> None:
    wlock = threading.Lock()

    def emit(obj: dict) -> None:
        with wlock:
            wfile.write(json.dumps(obj, sort_keys=True) + "\n")
            wfile.flush()

    for line in rfile:
        line = line.strip()
        if not line:
            continue
        try:
            msg = json.loads(line)
        except ValueError as e:
            out = {"ok": False, "error": f"bad JSON: {e}"}
        else:
            out = fleet.handle(msg, emit=emit)
        emit(out)
        if fleet.closing.is_set():
            return


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="N-worker stencil-serving fleet front")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--cache-dir", default=None,
                    help="shared compile cache (YT_COMPILE_CACHE; "
                         "workers 1+ warm-start from worker 0's "
                         "compiles)")
    ap.add_argument("--journal-dir", default=None,
                    help="directory for per-worker journals "
                         "(SERVE_JOURNAL.w<i>.jsonl; default: cwd)")
    ap.add_argument("--port", type=int, default=None,
                    help="listen on TCP (default: stdio)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--window_ms", type=float, default=None)
    ap.add_argument("--max_batch", type=int, default=None)
    ap.add_argument("--no-preflight", action="store_true")
    args = ap.parse_args(argv)

    wargs: List[str] = []
    if args.window_ms is not None:
        wargs += ["--window_ms", str(args.window_ms)]
    if args.max_batch is not None:
        wargs += ["--max_batch", str(args.max_batch)]
    if args.no_preflight:
        wargs += ["--no-preflight"]

    fleet = ServeFleet(n_workers=args.workers,
                       cache_dir=args.cache_dir,
                       journal_dir=args.journal_dir,
                       worker_args=wargs)
    try:
        if args.port is not None:
            import socket
            srv = socket.create_server((args.host, args.port))
            srv.settimeout(0.5)
            sys.stderr.write(
                f"serve_fleet: {len(fleet.workers)} workers on "
                f"{args.host}:{srv.getsockname()[1]}\n")
            sys.stderr.flush()
            threads = []
            try:
                while not fleet.closing.is_set():
                    try:
                        conn, _addr = srv.accept()
                    except socket.timeout:
                        continue
                    t = threading.Thread(
                        target=_serve_stream,
                        args=(fleet, conn.makefile("r", encoding="utf-8"),
                              conn.makefile("w", encoding="utf-8")),
                        daemon=True)
                    t.start()
                    threads.append(t)
            finally:
                srv.close()
                for t in threads:
                    t.join(timeout=2.0)
        else:
            sys.stderr.write(
                f"serve_fleet: {len(fleet.workers)} workers ready "
                "(stdio)\n")
            sys.stderr.flush()
            _serve_stream(fleet, sys.stdin, sys.stdout)
    finally:
        fleet.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
