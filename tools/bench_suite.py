"""BASELINE-table benchmark suite: one JSON line per headline config.

Covers the target rows in BASELINE.md beyond the single-number contract
of ``bench.py``:

* iso3dfd order-16, single device (jit vs validated fused pallas);
* cube 27-point with temporal wave-front fusion (wavefront speedup =
  fused K=4 over K=1);
* ssg staggered elastic (multi-var);
* iso3dfd in bf16 on the validated pallas path (HBM roofline lever);
* iso3dfd small-radius trapezoid-vs-skew A/B (the two-phase
  parallel-grid tiling, correctness-gated, TPU-scoped sentinel floor);
* awp, domain-decomposed with measured halo fraction (multi-device);
* ensemble batched-vs-sequential A/B (N instances as one vmapped
  program vs N fresh contexts each paying its own compile — the
  parameter-sweep regime; bit-identity gated per member);
* serving-layer A/Bs: same-geometry micro-batching, cross-profile
  shape-bucket co-batching (mixed geometries on one ladder rung,
  masked sub-domain runs bit-identical to solo), and the
  streaming/preemption short-request p99 win under mixed traffic.

Every section is independent (a failure emits an error line and the
suite continues), pallas numbers are correctness-gated against the jit
path first, and the relay-down case falls back to CPU via bench.py's
probe. Sizes shrink automatically off-TPU so the suite stays runnable
on the virtual CPU mesh.

Every row goes through ``yask_tpu.perflab``: it carries measurement
provenance (load average, CPU model, git SHA, calibration rate — the
context whose absence made the r5 across-the-board proxy slide
uninvestigable), a sentinel guard verdict (trailing clean median +
absolute floors, one automatic re-measure on breach deciding
noise-vs-regression), roofline context where a traffic model exists,
and is appended to ``PERF_LEDGER.jsonl``.  There are no ad-hoc guards
left here — the old cube-wavefront floor is now a sentinel rule.

Run: ``python tools/bench_suite.py``
"""

from __future__ import annotations

import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from yask_tpu.resilience import (Fault, anomaly_fields,  # noqa: E402
                                 check_output, guarded_call,
                                 maybe_corrupt)

#: sanity verdicts accumulated by measure() since the last emit() —
#: a row built from several measurements (speedup ratios) is
#: quarantined when ANY of them failed the guards.
_SANITY: list = []


def measure(ctx, g_pts, steps, trials=3):
    rates = []
    t = ctx._cur_step
    ctx.run_solution(t, t + steps - 1)   # warm
    t += steps
    for _ in range(trials):
        t0 = time.perf_counter()
        ctx.run_solution(t, t + steps - 1)
        dt = time.perf_counter() - t0
        t += steps
        rates.append(g_pts * steps / dt / 1e9)
    # result-sanity gate: wall-clock throughput of a diverged or
    # all-zero field is noise.  The interior slice around the domain
    # center (seeded nonzero by init_solution_vars) goes through the
    # shared guards; the verdict is accumulated for emit() to
    # quarantine the row rather than raising — the measurement is
    # recorded as a structured ANOMALY, not lost.
    name = ctx.get_var_names()[0]
    v = ctx.get_var(name)
    mid = [s // 2 for s in
           (ctx.get_settings().global_domain_sizes[d]
            for d in ctx.get_domain_dim_names())]
    s = v.get_elements_in_slice([t] + [c - 1 for c in mid],
                                [t] + [c + 1 for c in mid])
    s = maybe_corrupt("suite.result", s)
    verdict = check_output(s)
    if not verdict["ok"]:
        _SANITY.append(verdict)
    rates.sort()
    return rates[len(rates) // 2]


def build(fac, env, name, radius, g, mode, wf=0, ranks=(),
          measure_halo=False, elem_bytes=None, extra_opts=""):
    from yask_tpu.runtime.init_utils import init_solution_vars
    if elem_bytes:
        from yask_tpu.compiler.solution_base import create_solution
        sb = create_solution(name, radius=radius)
        sb.get_soln().set_element_bytes(elem_bytes)
        ctx = fac.new_solution(env, sb)
    else:
        ctx = fac.new_solution(env, stencil=name, radius=radius)
    opts = f"-g {g} -wf_steps {wf}"
    if measure_halo:
        opts += " -measure_halo"
    if extra_opts:
        opts += " " + extra_opts
    ctx.apply_command_line_options(opts)
    ctx.get_settings().mode = mode
    for d, r in ranks:
        ctx.set_num_ranks(d, r)
    ctx.prepare_solution()
    init_solution_vars(ctx)
    return ctx


def validated_pallas(fac, env, name, radius, wf, gv=24, steps=4,
                     elem_bytes=None, epsilon=1e-3, abs_epsilon=1e-4):
    """Correctness gate: the fused path must match jit on a small domain
    before any timing is trusted (same policy as bench.py)."""
    ref = build(fac, env, name, radius, gv, "jit", elem_bytes=elem_bytes)
    ref.run_solution(0, steps - 1)
    p = build(fac, env, name, radius, gv, "pallas", wf=wf,
              elem_bytes=elem_bytes)
    p.run_solution(0, steps - 1)
    bad = p.compare_data(ref, epsilon=epsilon, abs_epsilon=abs_epsilon)
    if bad:
        raise RuntimeError(f"pallas K={wf} mismatches jit at {gv}^3: {bad}")


#: rows emitted by the current run_suite invocation (bench.py persists
#: them into the round artifact alongside its contract line).
ROWS = []

#: set by run_suite: (platform, device_kind) for per-row provenance.
_ENV_INFO = {"platform": "", "device_kind": ""}


def emit(metric, value, unit, remeasure=None, roofline=None, **extra):
    """Record one suite row: provenance + sentinel verdict + ledger
    append, then the legacy-shaped JSON line (bench.py re-prints these
    and the driver parser reads them — `metric`/`value`/`unit` keys stay
    stable, provenance/guard ride along as extra fields).

    Sanity verdicts accumulated by measure() since the previous emit
    quarantine the row: it still prints and lands in the ledger, but as
    a structured ANOMALY the sentinel never baselines on."""
    from yask_tpu.perflab import capture_provenance, guard_and_append
    value = round(value, 4)
    sanity = None
    if _SANITY:
        sanity = {"ok": False,
                  "anomalies": sorted({a for v in _SANITY
                                       for a in v["anomalies"]}),
                  **{k: _SANITY[-1][k]
                     for k in ("zero_frac", "nonfinite_frac", "max_abs")
                     if k in _SANITY[-1]}}
        _SANITY.clear()
    prov = capture_provenance(platform=_ENV_INFO["platform"],
                              device_kind=_ENV_INFO["device_kind"])
    try:
        lrow = guard_and_append(metric, value, unit,
                                _ENV_INFO["platform"] or "cpu", "suite",
                                prov, remeasure=remeasure,
                                roofline=roofline, extra=extra or None,
                                sanity=sanity)
        guard = lrow["guard"]
    except Exception as e:  # ledger I/O must never kill a bench section
        guard = {"status": "unrecorded", "error": str(e)[:120]}
    row = {"metric": metric, "value": value, "unit": unit, **extra,
           "provenance": prov, "guard": guard}
    if sanity is not None:
        row.update(anomaly_fields(sanity))
    if roofline:
        row.update({k: v for k, v in roofline.items() if v is not None})
    ROWS.append(row)
    print(json.dumps(row), flush=True)


def section(fn, budget_t0=None, budget_secs=None):
    """Run one headline row; a failure emits an error line, not a crash.
    Sections past the time budget are skipped (bench.py embeds the suite
    under the driver's overall timeout — a partial suite beats no
    contract line at all).  Sections run through guarded_call, so
    injected faults fire at ``suite.<name>`` and real backend failures
    are recorded with their classified kind."""
    if budget_t0 is not None and budget_secs is not None \
            and time.perf_counter() - budget_t0 > budget_secs:
        emit(fn.__name__, 0.0, "skipped", reason="suite time budget")
        return
    try:
        guarded_call(fn, site=f"suite.{fn.__name__}")
    except Fault as f:
        _SANITY.clear()   # a failed section's verdicts die with it
        emit(fn.__name__, 0.0, "error", error=str(f)[:160],
             fault=f.kind)
    except Exception as e:
        _SANITY.clear()
        emit(fn.__name__, 0.0, "error", error=str(e)[:160])


def run_suite(fac, env, budget_secs=None):
    """All BASELINE rows (beyond bench.py's single contract line) for
    the given environment; returns the emitted row dicts. Importable by
    bench.py so the round artifact records the suite, not one number
    (VERDICT r2 weak 6)."""
    plat = env.get_platform()
    on_tpu = plat == "tpu"
    ndev = env.get_num_ranks()
    ROWS.clear()
    _ENV_INFO["platform"] = plat
    _ENV_INFO["device_kind"] = (getattr(env.get_devices()[0],
                                        "device_kind", "")
                                if env.get_devices() else "")
    t0 = time.perf_counter()

    steps = 12 if on_tpu else 4   # multiple of 4: clean K=4 fusion groups

    from yask_tpu.perflab.roofline import ctx_roofline

    def iso3dfd_jit():
        for g in ((512, 384, 256) if on_tpu else (48,)):
            try:
                ctx = build(fac, env, "iso3dfd", 8, g, "jit")
                rate = measure(ctx, g ** 3, steps)
                emit(f"iso3dfd r=8 {g}^3 {plat} jit", rate, "GPts/s",
                     remeasure=lambda: measure(ctx, g ** 3, steps),
                     roofline=ctx_roofline(ctx, env, rate))
                del ctx
                return
            except Exception:
                if g == (256 if on_tpu else 48):
                    raise

    def _tiling_of(ctx):
        """The tiling the built kernel ACTUALLY chose, for row
        provenance (skew / pipelining can auto-fall-back)."""
        for t in ctx._pallas_tiling.values():
            if t:
                return {k: t[k] for k in ("skew", "skew_dims",
                                          "trapezoid", "trap_dims",
                                          "pipeline_dmas",
                                          "pipeline_out",
                                          "overlap_exchange",
                                          "overlap_core",
                                          "margin_overhead") if k in t}
        return {}

    def _comm_of(ctx):
        """Comm-schedule row fields (mesh shape, per-axis kB, collective
        rounds — measured when halo-cal ran); {} on single-device
        paths or any plan failure (row fields must not kill a
        section)."""
        from yask_tpu.parallel.comm_plan import comm_ledger_fields
        try:
            return comm_ledger_fields(ctx)
        except Exception:
            return {}

    def iso3dfd_pallas():
        validated_pallas(fac, env, "iso3dfd", 8, wf=2)
        g = 512 if on_tpu else 48
        ctx = build(fac, env, "iso3dfd", 8, g, "pallas", wf=2)
        rate = measure(ctx, g ** 3, steps)
        emit(f"iso3dfd r=8 {g}^3 {plat} pallas-K2", rate, "GPts/s",
             remeasure=lambda: measure(ctx, g ** 3, steps),
             roofline=ctx_roofline(ctx, env, rate), **_tiling_of(ctx))
        del ctx

    def cube_wavefront():
        # The K=4-over-K=1 fusion speedup.  The old ad-hoc 1.5× floor
        # (VERDICT r4 item 3: the r4 proxy silently halved when skew
        # mis-engaged at r=1) is now the sentinel's cube-wavefront rule;
        # on a breach the guard re-measures the ratio once and records
        # noise-vs-regression in the row itself.
        validated_pallas(fac, env, "cube", 1, wf=4)
        gc = 256 if on_tpu else 32
        c1 = build(fac, env, "cube", 1, gc, "pallas", wf=1)
        base = measure(c1, gc ** 3, steps)
        c4 = build(fac, env, "cube", 1, gc, "pallas", wf=4)
        fused = measure(c4, gc ** 3, steps)

        def remeasure_speedup():
            return (measure(c4, gc ** 3, steps)
                    / max(measure(c1, gc ** 3, steps), 1e-12))

        emit(f"cube 27pt {gc}^3 {plat} wavefront-speedup",
             fused / max(base, 1e-12), "x",
             remeasure=remeasure_speedup,
             k1_gpts=round(base, 4), k4_gpts=round(fused, 4),
             **_tiling_of(c4))
        del c1, c4

    def iso3dfd_skew2d():
        # 1-D vs 2-D skew A/B via the -skew_dims knob: the second
        # (outer-dim, E=0) carry trades its row buffer for another
        # 2·K·r → (K+1)·r margin drop — track the payoff as a ratio so
        # the sentinel sees mis-engagement (the r4 cube lesson, one
        # dim up).
        g = 512 if on_tpu else 48
        c1 = build(fac, env, "iso3dfd", 8, g, "pallas", wf=2,
                   extra_opts="-skew_dims 1")
        r1 = measure(c1, g ** 3, steps)
        c2 = build(fac, env, "iso3dfd", 8, g, "pallas", wf=2)
        r2 = measure(c2, g ** 3, steps)

        def remeasure_ratio():
            return (measure(c2, g ** 3, steps)
                    / max(measure(c1, g ** 3, steps), 1e-12))

        emit(f"iso3dfd r=8 {g}^3 {plat} skew2d-speedup",
             r2 / max(r1, 1e-12), "x", remeasure=remeasure_ratio,
             skew1d_gpts=round(r1, 4), skew2d_gpts=round(r2, 4),
             **_tiling_of(c2))
        del c1, c2

    def iso3dfd_trapezoid():
        # Trapezoid-vs-skew A/B at the config the profit gate engages
        # on (small radius, K=4 — see docs/performance.md gate table):
        # -trapezoid arms the gate (pads sized at prepare), the off arm
        # is the same config on the skew/uniform path.  The correctness
        # gate asserts BIT-equality against the uniform pallas schedule
        # (same contract as pipeline_ab: a tiling variant reorders the
        # sweep, never the per-cell arithmetic — jit is the wrong oracle
        # here since XLA's fusion reassociates and drifts ~1e-3 after a
        # few steps regardless of tiling).  The provisional 0.9
        # TRAP_SPEEDUP_FLOOR is TPU-scoped (the CPU proxy has no
        # megacore and serializes the diamond fill passes, so its ratio
        # sits below 1 by construction); the row's tiling block says
        # whether the gate actually engaged.
        # 64 is the smallest cube where the gate engages trapezoid for
        # this stencil (at 48 the planner's 16^2 blocks keep skew ahead;
        # at 64..384 trapezoid wins the cost model — see the probe table
        # in docs/performance.md).
        g = 384 if on_tpu else 64
        ref = build(fac, env, "iso3dfd", 2, 24, "pallas", wf=4)
        ref.run_solution(0, 3)
        chk = build(fac, env, "iso3dfd", 2, 24, "pallas", wf=4,
                    extra_opts="-trapezoid")
        chk.run_solution(0, 3)
        bad = chk.compare_data(ref, epsilon=0.0, abs_epsilon=0.0)
        if bad:
            raise RuntimeError(
                f"trapezoid K=4 not bit-equal to uniform pallas: {bad}")
        del ref, chk
        c_off = build(fac, env, "iso3dfd", 2, g, "pallas", wf=4)
        r_off = measure(c_off, g ** 3, steps)
        c_on = build(fac, env, "iso3dfd", 2, g, "pallas", wf=4,
                     extra_opts="-trapezoid")
        r_on = measure(c_on, g ** 3, steps)
        if not _tiling_of(c_on).get("trapezoid"):
            # both arms ran the same plan — a vacuous A/B must error
            # loudly, not bank a noise ratio as "trap-speedup" (the
            # tiling only materializes at first chunk build, hence the
            # post-measure check)
            raise RuntimeError(
                f"trapezoid gate did not engage at {g}^3: "
                f"{_tiling_of(c_on)}")

        def remeasure_ratio():
            return (measure(c_on, g ** 3, steps)
                    / max(measure(c_off, g ** 3, steps), 1e-12))

        emit(f"iso3dfd r=2 {g}^3 {plat} trap-speedup",
             r_on / max(r_off, 1e-12), "x", remeasure=remeasure_ratio,
             base_gpts=round(r_off, 4), trap_gpts=round(r_on, 4),
             base_tiling=_tiling_of(c_off), **_tiling_of(c_on))
        del c_on, c_off

    def ssg_elastic():
        gs = 256 if on_tpu else 32
        ctx = build(fac, env, "ssg", 2, gs, "jit")
        rate = measure(ctx, gs ** 3, steps)
        emit(f"ssg r=2 {gs}^3 {plat} jit", rate, "GPts/s",
             remeasure=lambda: measure(ctx, gs ** 3, steps),
             roofline=ctx_roofline(ctx, env, rate))
        del ctx

    def iso3dfd_bf16():
        # bf16 halves HBM bytes/point — the bandwidth-roofline lever on
        # TPU (reference real_bytes=4|8 builds have no half-precision
        # analog; bf16 is the TPU-native one).  Validation gate compares
        # bf16 pallas against bf16 jit with bf16-appropriate epsilons.
        validated_pallas(fac, env, "iso3dfd", 8, wf=2, elem_bytes=2,
                         epsilon=3e-2, abs_epsilon=3e-2)
        g = 512 if on_tpu else 48
        ctx = build(fac, env, "iso3dfd", 8, g, "pallas", wf=2,
                    elem_bytes=2)
        rate = measure(ctx, g ** 3, steps)
        emit(f"iso3dfd r=8 {g}^3 {plat} pallas-K2 bf16", rate, "GPts/s",
             remeasure=lambda: measure(ctx, g ** 3, steps),
             roofline=ctx_roofline(ctx, env, rate))
        del ctx

    def awp_decomposed():
        if ndev <= 1:
            return
        ga = 256 if on_tpu else 32
        ctx = build(fac, env, "awp", None, ga, "shard_map",
                    ranks=[("x", ndev)], measure_halo=True)
        rate = measure(ctx, ga ** 3, steps)
        st = ctx.get_stats()
        # twice-unstable calibration banks NO split: halo_pct is null
        # (the row still carries total throughput + halo_cal_unstable)
        halo_pct = None
        if not st.get_halo_cal_unstable():
            halo_pct = round(100.0 * st.get_halo_secs()
                             / max(st.get_elapsed_secs(), 1e-12), 2)
        emit(f"awp {ga}^3 {plat} x{ndev} shard_map", rate, "GPts/s",
             remeasure=lambda: measure(ctx, ga ** 3, steps),
             roofline=ctx_roofline(ctx, env, rate),
             halo_pct=halo_pct, **_comm_of(ctx))
        del ctx

    def sm_coalesce():
        # Message-coalescing A/B on a 2-D mesh (the shape where slabs
        # per axis multiply): one packed ppermute per (axis, direction)
        # vs one per buffer slab, same geometry — the CommPlan's
        # headline lever.  Rows carry measured collective counts
        # (comm_rounds_measured, from the traced exchange twin) so the
        # ledger shows the round reduction, not just the rate delta.
        if ndev < 4:
            return
        g = 256 if on_tpu else 32
        c_off = build(fac, env, "ssg", 2, g, "shard_map",
                      ranks=[("x", 2), ("y", 2)], measure_halo=True,
                      extra_opts="-coalesce off")
        r_off = measure(c_off, g ** 3, steps)
        c_on = build(fac, env, "ssg", 2, g, "shard_map",
                     ranks=[("x", 2), ("y", 2)], measure_halo=True,
                     extra_opts="-coalesce on")
        r_on = measure(c_on, g ** 3, steps)

        def remeasure_ratio():
            return (measure(c_on, g ** 3, steps)
                    / max(measure(c_off, g ** 3, steps), 1e-12))

        emit(f"ssg r=2 {g}^3 {plat} x2y2 sm-coalesce-speedup",
             r_on / max(r_off, 1e-12), "x", remeasure=remeasure_ratio,
             serial_gpts=round(r_off, 4), coalesced_gpts=round(r_on, 4),
             serial_rounds=_comm_of(c_off).get("comm_rounds_measured"),
             **_comm_of(c_on))
        del c_on, c_off

    def sp_overlap():
        # Overlapped halo exchange A/B on the flagship multi-chip path:
        # the core/shell split of the fused K-group (-overlap_x on)
        # against the serial chunk→exchange schedule.  Forcing "on"
        # (rather than auto) makes the ratio's meaning unconditional —
        # an infeasible geometry errors the section instead of silently
        # comparing serial to serial.  The provisional 0.95 sentinel
        # floor is TPU-scoped (the CPU proxy pays the split's extra
        # launches with no collective latency to hide, ~0.7-0.8x by
        # construction — trailing-median guards that arm); re-base on
        # hardware.
        if ndev <= 1:
            return
        g = 256 if on_tpu else 48
        rx = min(ndev, 4)
        c_off = build(fac, env, "iso3dfd", 2, g, "shard_pallas", wf=2,
                      ranks=[("x", rx)], measure_halo=True,
                      extra_opts="-overlap_x off")
        r_off = measure(c_off, g ** 3, steps)
        eff_off = c_off.get_stats().get_halo_overlap_eff()
        c_on = build(fac, env, "iso3dfd", 2, g, "shard_pallas", wf=2,
                     ranks=[("x", rx)], measure_halo=True,
                     extra_opts="-overlap_x on")
        r_on = measure(c_on, g ** 3, steps)
        eff_on = c_on.get_stats().get_halo_overlap_eff()

        def remeasure_ratio():
            return (measure(c_on, g ** 3, steps)
                    / max(measure(c_off, g ** 3, steps), 1e-12))

        emit(f"iso3dfd r=2 {g}^3 {plat} x{rx} sp-overlap-speedup",
             r_on / max(r_off, 1e-12), "x", remeasure=remeasure_ratio,
             serial_gpts=round(r_off, 4), overlap_gpts=round(r_on, 4),
             overlap_eff=round(eff_on, 4),
             serial_eff=round(eff_off, 4), **_tiling_of(c_on),
             **_comm_of(c_on))
        del c_on, c_off

    def ensemble_ab():
        # Batched-vs-sequential ensemble A/B at the parameter-sweep
        # point (N=8 at 64³ off-TPU): the sequential arm is N FRESH
        # contexts each paying its own trace+lower+compile — today's
        # aggregate cost of a sweep — with the compile-cache memo
        # cleared per member and disk persistence off, so the
        # chokepoint cannot quietly share compiles between arms.  The
        # batched arm is ONE context + new_ensemble(N): one vmapped
        # compile, one fused run.  Correctness gate: every member must
        # be BIT-identical (all vars, all ring slots) to its
        # sequential twin — vmap adds a leading axis, never changes
        # per-lane arithmetic.  The ≥2× ENSEMBLE_SPEEDUP_FLOOR is
        # CPU-scoped (compile dominates at 64³ on the proxy; re-base
        # on hardware where the chip-saturation win takes over).
        import numpy as np
        from yask_tpu import cache as ccache
        try:
            N = int(os.environ.get("YT_BENCH_ENSEMBLE", "8"))
        except ValueError:
            N = 8
        if N < 2:
            return
        g = 128 if on_tpu else 64

        def seed(ctx, i):
            rng = np.random.RandomState(1000 + i)
            arr = (rng.rand(g, g, g).astype(np.float32) - 0.5) * 0.1
            ctx.get_var("pressure").set_elements_in_slice(
                arr, [0, 0, 0, 0], [0, g - 1, g - 1, g - 1])

        # Both arms time ONLY the runs: context build + initial
        # conditions are identical per-member host work (numpy fills)
        # that would dilute the signal equally on both sides.  The
        # first run_solution/ens.run still pays trace+lower+compile —
        # that asymmetry (N compiles vs one vmapped compile) is the
        # thing being measured.
        def seq_arm():
            ctxs = []
            for i in range(N):
                ctx = build(fac, env, "iso3dfd", 8, g, "jit")
                seed(ctx, i)
                ctxs.append(ctx)
            t0s = time.perf_counter()
            for ctx in ctxs:
                # identical geometry ⇒ identical persistent key: the
                # memo would hand member 2..N member 1's executable
                # and measure a sweep that paid one compile, not N
                ccache.clear_memo()
                ctx.run_solution(0, steps - 1)
            t = time.perf_counter() - t0s
            finals = [{n: [np.asarray(a) for a in ring]
                       for n, ring in ctx._state.items()}
                      for ctx in ctxs]
            del ctxs
            return t, finals

        def bat_arm():
            from yask_tpu.runtime.init_utils import init_solution_vars
            ctx = build(fac, env, "iso3dfd", 8, g, "jit")
            ens = ctx.new_ensemble(N)
            for i in range(N):
                with ens.member(i) as c:
                    if i:   # member 0 was initialized by build();
                            # fresh members need the same baseline
                        init_solution_vars(c)
                    seed(c, i)
            ccache.clear_memo()
            t0b = time.perf_counter()
            ens.run(0, steps - 1)
            return time.perf_counter() - t0b, ctx, ens

        saved = os.environ.pop("YT_COMPILE_CACHE", None)
        try:
            t_seq, finals = seq_arm()
            t_bat, ctx, ens = bat_arm()
        finally:
            if saved is not None:
                os.environ["YT_COMPILE_CACHE"] = saved
        for i in range(N):
            with ens.member(i) as c:
                for n, ring in finals[i].items():
                    for s, a in enumerate(ring):
                        b = np.asarray(c._state[n][s])
                        if not np.array_equal(a, b):
                            raise RuntimeError(
                                f"ensemble member {i} var {n} slot {s} "
                                "not bit-identical to its sequential "
                                f"twin (maxdiff {np.abs(a - b).max()})")

        def remeasure_ratio():
            sv = os.environ.pop("YT_COMPILE_CACHE", None)
            try:
                ts, _ = seq_arm()
                tb, c2, e2 = bat_arm()
                del c2, e2
                return ts / max(tb, 1e-12)
            finally:
                if sv is not None:
                    os.environ["YT_COMPILE_CACHE"] = sv

        emit(f"iso3dfd r=8 {g}^3 {plat} ensemble{N}-speedup",
             t_seq / max(t_bat, 1e-12), "x", remeasure=remeasure_ratio,
             ensemble=N, seq_secs=round(t_seq, 3),
             batched_secs=round(t_bat, 3),
             compile_ms=round(ctx._compile_secs * 1000.0, 1),
             cache_hit=ctx._last_cache_hit or "cold",
             batched_reason=ens.batched_reason)
        del ctx, ens

    def serve_batch_ab():
        # Serving-layer A/B at the same sweep point as ensemble_ab
        # (N=8 at 64³ off-TPU): the sequential arm is N fresh solo
        # contexts each paying its own compile (memo cleared per
        # member, disk cache off) — the no-server cost of answering N
        # tenants.  The serve arm is ONE StencilServer: N sessions on
        # one profile, submit-all-then-wait-all, so the batching
        # window groups them into one vmapped execution — PLUS the
        # server's honest overheads (worker handoff, pre-request
        # snapshots, journal rows, sanity gating).  Correctness gate:
        # every response bit-identical to its sequential twin's
        # written interiors.  The SERVE_BATCH_SPEEDUP_FLOOR (1.5×) is
        # CPU-scoped and deliberately below the 2× ensemble floor:
        # the serving machinery's per-request tax is part of what this
        # row tracks.
        import numpy as np
        from yask_tpu import cache as ccache
        from yask_tpu.serve import StencilServer
        from yask_tpu.serve.scheduler import extract_outputs
        try:
            N = int(os.environ.get("YT_BENCH_ENSEMBLE", "8"))
        except ValueError:
            N = 8
        if N < 2:
            return
        g = 128 if on_tpu else 64

        def seed_arr(i):
            rng = np.random.RandomState(1000 + i)
            return (rng.rand(1, g, g, g).astype(np.float32) - 0.5) * 0.1

        def seq_arm():
            ctxs = []
            for i in range(N):
                ctx = build(fac, env, "iso3dfd", 8, g, "jit")
                ctx.get_var("pressure").set_elements_in_slice(
                    seed_arr(i), [0, 0, 0, 0],
                    [0, g - 1, g - 1, g - 1])
                ctxs.append(ctx)
            t0s = time.perf_counter()
            for ctx in ctxs:
                ccache.clear_memo()  # N tenants, N compiles — the
                ctx.run_solution(0, steps - 1)   # cost being beaten
            t = time.perf_counter() - t0s
            outs = [extract_outputs(ctx) for ctx in ctxs]
            del ctxs
            return t, outs

        def serve_arm():
            srv = StencilServer(window_secs=0.1, max_batch=N,
                                preflight=False)
            sids = []
            for i in range(N):
                sid = srv.open_session(stencil="iso3dfd", radius=8,
                                       g=g, mode="jit", wf=2)
                srv.init_vars(sid)
                with srv.scheduler.session_ctx(sid) as c:
                    c.get_var("pressure").set_elements_in_slice(
                        seed_arr(i), [0, 0, 0, 0],
                        [0, g - 1, g - 1, g - 1])
                sids.append(sid)
            ccache.clear_memo()
            t0b = time.perf_counter()
            handles = [srv.submit_run(sid, 0, steps - 1)
                       for sid in sids]
            resps = [srv.wait(h, timeout=600) for h in handles]
            t = time.perf_counter() - t0b
            occ = max((r.batch for r in resps), default=0)
            srv.shutdown()
            for r in resps:
                if not r.ok:
                    raise RuntimeError(
                        f"serve arm request {r.rid}: {r.status} "
                        f"{r.error}")
            return t, resps, occ

        saved = os.environ.pop("YT_COMPILE_CACHE", None)
        try:
            t_seq, seq_outs = seq_arm()
            t_srv, resps, occ = serve_arm()
        finally:
            if saved is not None:
                os.environ["YT_COMPILE_CACHE"] = saved
        for i, (want, r) in enumerate(zip(seq_outs, resps)):
            for n, a in want.items():
                b = r.outputs[n]
                if not np.array_equal(a, b):
                    raise RuntimeError(
                        f"serve tenant {i} var {n} not bit-identical "
                        "to its sequential twin "
                        f"(maxdiff {np.abs(a - b).max()})")

        def remeasure_ratio():
            sv = os.environ.pop("YT_COMPILE_CACHE", None)
            try:
                ts, _ = seq_arm()
                tb, _, _ = serve_arm()
                return ts / max(tb, 1e-12)
            finally:
                if sv is not None:
                    os.environ["YT_COMPILE_CACHE"] = sv

        emit(f"iso3dfd r=8 {g}^3 {plat} serve-batch{N}-speedup",
             t_seq / max(t_srv, 1e-12), "x", remeasure=remeasure_ratio,
             tenants=N, occupancy=occ, seq_secs=round(t_seq, 3),
             serve_secs=round(t_srv, 3))

    def serve_bucket_ab():
        # Cross-PROFILE serving A/B: N tenants on >=3 DISTINCT
        # geometries, all mapping to ONE bucket-ladder rung.  The
        # sequential arm is N fresh solo contexts — each geometry its
        # own prepared context, each member its own compile (memo
        # cleared, disk cache off): the no-server cost of a
        # mixed-geometry tenant population, and the bit-identity
        # oracle.  The serve arm opens every session with
        # ``bucket=True``: the planner hosts each tenant as a masked
        # sub-domain of the shared rung profile and the scheduler
        # rides ALL of them as one vmapped EnsembleRun — one compile,
        # occupancy N, despite no two tenants necessarily sharing a
        # geometry.  Gate: every tenant's outputs bit-identical to its
        # solo twin over its OWN domain (extract_outputs slices the
        # sub-domain back out of the bucket state).  The
        # SERVE_BUCKET_SPEEDUP_FLOOR (1.5x) sentinel rule is
        # CPU-scoped.
        import numpy as np
        from yask_tpu import cache as ccache
        from yask_tpu.serve import StencilServer, bucket_for
        from yask_tpu.serve.scheduler import extract_outputs
        try:
            N = int(os.environ.get("YT_BENCH_ENSEMBLE", "8"))
        except ValueError:
            N = 8
        if N < 2:
            return
        # three distinct geometries on one rung (24 off-TPU, 48 on):
        # the ladder's 8-multiples keep every sub-domain
        # sublane-aligned for free.
        gs_cycle = (40, 44, 48) if on_tpu else (20, 22, 24)
        gs = [gs_cycle[i % len(gs_cycle)] for i in range(N)]
        rung = bucket_for(max(gs))

        def seed_arr(i, gi):
            rng = np.random.RandomState(3000 + i)
            return (rng.rand(1, gi, gi, gi).astype(np.float32)
                    - 0.5) * 0.1

        def solo_arm():
            ctxs = []
            for i, gi in enumerate(gs):
                ctx = build(fac, env, "iso3dfd", 2, gi, "jit")
                ctx.get_var("pressure").set_elements_in_slice(
                    seed_arr(i, gi), [0, 0, 0, 0],
                    [0, gi - 1, gi - 1, gi - 1])
                ctxs.append(ctx)
            t0s = time.perf_counter()
            for ctx in ctxs:
                ccache.clear_memo()   # each geometry+member: own compile
                ctx.run_solution(0, steps - 1)
            t = time.perf_counter() - t0s
            outs = [extract_outputs(ctx) for ctx in ctxs]
            del ctxs
            return t, outs

        def bucket_arm():
            srv = StencilServer(window_secs=0.1, max_batch=N,
                                preflight=False)
            sids = []
            for i, gi in enumerate(gs):
                sid = srv.open_session(stencil="iso3dfd", radius=2,
                                       g=gi, mode="jit", wf=2,
                                       bucket=True)
                b = srv.session_bucket(sid)
                if b.get("decision") != "bucketed":
                    raise RuntimeError(
                        f"tenant {i} g={gi} did not bucket: {b}")
                srv.init_vars(sid)
                with srv.scheduler.session_ctx(sid) as c:
                    c.get_var("pressure").set_elements_in_slice(
                        seed_arr(i, gi), [0, 0, 0, 0],
                        [0, gi - 1, gi - 1, gi - 1])
                sids.append(sid)
            ccache.clear_memo()
            t0b = time.perf_counter()
            handles = [srv.submit_run(sid, 0, steps - 1)
                       for sid in sids]
            resps = [srv.wait(h, timeout=600) for h in handles]
            t = time.perf_counter() - t0b
            occ = max((r.batch for r in resps), default=0)
            srv.shutdown()
            for r in resps:
                if not r.ok:
                    raise RuntimeError(
                        f"bucket arm request {r.rid}: {r.status} "
                        f"{r.error}")
            return t, resps, occ

        saved = os.environ.pop("YT_COMPILE_CACHE", None)
        try:
            t_solo, solo_outs = solo_arm()
            t_bkt, resps, occ = bucket_arm()
        finally:
            if saved is not None:
                os.environ["YT_COMPILE_CACHE"] = saved
        if occ < N:
            raise RuntimeError(
                f"bucketed tenants did not co-batch: occupancy {occ} "
                f"< {N} (geometries {sorted(set(gs))} on rung {rung})")
        # batch= alone is the INTENDED width; batched= proves the
        # vmapped executable really ran (a missing batching rule
        # degrades to sequential members and must not bank a speedup)
        if not all(r.batched for r in resps):
            raise RuntimeError(
                "bucket arm degraded to sequential members — "
                "speedup row withheld")
        for i, (want, r) in enumerate(zip(solo_outs, resps)):
            for n, a in want.items():
                b = r.outputs[n]
                if a.shape != b.shape or not np.array_equal(a, b):
                    raise RuntimeError(
                        f"bucketed tenant {i} (g={gs[i]}) var {n} not "
                        "bit-identical to its solo twin")

        def remeasure_ratio():
            sv = os.environ.pop("YT_COMPILE_CACHE", None)
            try:
                ts, _ = solo_arm()
                tb, _, _ = bucket_arm()
                return ts / max(tb, 1e-12)
            finally:
                if sv is not None:
                    os.environ["YT_COMPILE_CACHE"] = sv

        emit(f"iso3dfd r=2 mixed-g {plat} serve-bucket{N}-speedup",
             t_solo / max(t_bkt, 1e-12), "x",
             remeasure=remeasure_ratio, tenants=N,
             geometries=sorted(set(gs)), rung=rung, occupancy=occ,
             solo_secs=round(t_solo, 3), bucket_secs=round(t_bkt, 3))

    def serve_stream_ab():
        # Streaming/preemption A/B under MIXED traffic: one long run
        # plus a burst of 1-step requests submitted while it is in
        # flight.  Blocking arm (flush_every=0): the shorts wait out
        # the whole long run — their latency IS the long run.
        # Streaming arm (flush_every=steps): the scheduler executes
        # the long run in guarded chunks, preempts it at a chunk
        # boundary when the shorts are pending, runs them, then
        # re-queues the continuation — short-request p99 collapses to
        # about one chunk.  Both arms are pre-warmed (compile excluded
        # on both sides; the row tracks scheduling latency, not
        # amortization) and the long run's final state must be
        # BIT-identical across arms: jit chunked execution equals the
        # whole-range run exactly, preemption included.  No sentinel
        # floor — the pass criterion rides in the row.
        import numpy as np
        from yask_tpu.serve import StencilServer
        # 3axis is a pure neighbor average — unconditionally stable,
        # so the long run stays finite for hundreds of steps (iso3dfd
        # amplifies and overflows fp32 within ~40 steps).
        g = 96 if on_tpu else 64
        T = 150 * steps         # long enough to dominate the window
        nshort = 3

        srv = StencilServer(window_secs=0.02, max_batch=8,
                            preflight=False)

        def mk():
            sid = srv.open_session(stencil="3axis", radius=4, g=g,
                                   mode="jit", wf=2)
            srv.init_vars(sid)
            return sid

        # warm every chunk shape both arms will run (whole-range,
        # cadence chunks, 1-step shorts)
        srv.run(mk(), 0, T - 1, timeout=600)
        srv.run(mk(), 0, T - 1, flush_every=steps, timeout=600)
        srv.run(mk(), 0, 0, timeout=600)

        def arm(flush):
            long_sid = mk()
            shorts = [mk() for _ in range(nshort)]
            h_long = srv.submit_run(long_sid, 0, T - 1,
                                    flush_every=flush)
            time.sleep(0.05)   # window elapses; long run is in flight
            hs = [srv.submit_run(s, 0, 0) for s in shorts]
            rs = [srv.wait(h, timeout=600) for h in hs]
            r_long = srv.wait(h_long, timeout=600)
            for r in list(rs) + [r_long]:
                if not r.ok:
                    raise RuntimeError(
                        f"stream arm request {r.rid}: {r.status} "
                        f"{r.error}")
            lat = [r.queue_secs + r.run_secs for r in rs]
            return max(lat), r_long

        p99_block, r_block = arm(0)
        p99_stream, r_stream = arm(steps)
        srv.shutdown()
        if r_stream.preempted < 1:
            raise RuntimeError(
                "streaming arm was never preempted — the shorts did "
                "not interleave (long run too fast for the window?)")
        for n, a in r_block.outputs.items():
            b = r_stream.outputs[n]
            if not np.array_equal(a, b):
                raise RuntimeError(
                    f"preempted chunked long run diverged from the "
                    f"blocking run on {n}")

        def remeasure_ratio():
            pb, _ = arm(0)
            ps, _ = arm(steps)
            return pb / max(ps, 1e-12)

        emit(f"3axis r=4 {g}^3 {plat} serve-stream-p99-win",
             p99_block / max(p99_stream, 1e-12), "x",
             remeasure=remeasure_ratio,
             criterion="short-request p99 with streaming+preemption "
                       "< blocking p99",
             criterion_met=bool(p99_stream < p99_block),
             p99_block_ms=round(p99_block * 1e3, 1),
             p99_stream_ms=round(p99_stream * 1e3, 1),
             shorts=nshort, long_steps=T, flush_every=steps,
             preempts=r_stream.preempted,
             stream_events=len(r_stream.streams))

    def pipeline_fusion_ab():
        # Cross-solution pipeline-fusion A/B on the 3-stage RTM chain
        # (forward iso wave -> imaging correlation -> 3-point
        # smoothing): the fused arm is ONE merged program (bound vars
        # never round-trip HBM; the model says 2× traffic for this
        # chain), the chained arm is the host-chained oracle — per
        # step, per stage, each binding pushed through host slice
        # copies.  Correctness gate: every written var of every stage
        # BIT-identical between arms — both arms run the same jit
        # temporal schedule, where the merge is exact (the pallas K>1
        # chunked schedule is only tolerance-equal to stepwise runs,
        # a pre-existing property of temporal chunking, so the perf
        # headline for that path lives in tpu_session, not here).
        # Timing excludes the warmup/compile window on both sides:
        # unlike the ensemble row, the fusion win being tracked is
        # steady-state traffic + dispatch + push tax, not compile
        # amortization.  PIPELINE_FUSION_FLOOR (1.2×) is CPU-scoped.
        import numpy as np
        from yask_tpu.ops.pipeline import (SolutionPipeline, rtm_chain,
                                           pipeline_hbm_model)
        g = 64 if on_tpu else 32

        def mk(fuse):
            stages, bindings = rtm_chain(radius=2)
            pipe = SolutionPipeline(env, stages, bindings)
            pipe.apply_command_line_options(f"-g {g} -mode jit "
                                            "-wf_steps 2")
            pipe.prepare(fuse=fuse)
            v = pipe.get_var("fwd", "pressure")
            rng = np.random.RandomState(7)
            arr = (rng.rand(g, g, g).astype(np.float32) - 0.5) * 0.1
            for t in range(v.get_first_valid_step_index(),
                           v.get_last_valid_step_index() + 1):
                v.set_elements_in_slice(arr, [t, 0, 0, 0],
                                        [t, g - 1, g - 1, g - 1])
            return pipe

        fused, chained = mk(True), mk(False)
        # warmup window pays trace+lower+compile on both sides AND
        # feeds the bit-equality gate
        fused.run(0, steps - 1)
        chained.run(0, steps - 1)
        bad = fused.compare(chained)
        if bad:
            raise RuntimeError(
                f"pipeline fusion not bit-identical to the "
                f"host-chained oracle ({bad} mismatching elements)")

        def arms(lo, hi):
            t0f = time.perf_counter()
            fused.run(lo, hi)
            tf = time.perf_counter() - t0f
            t0c = time.perf_counter()
            chained.run(lo, hi)
            return tf, time.perf_counter() - t0c

        t_fused = t_chain = 0.0
        trials = 3
        for i in range(trials):
            tf, tc = arms((i + 1) * steps, (i + 2) * steps - 1)
            t_fused += tf
            t_chain += tc
        bad = fused.compare(chained)
        if bad:
            raise RuntimeError(
                f"pipeline fusion diverged from the host-chained "
                f"oracle during timed steps ({bad} mismatches)")

        def remeasure_ratio():
            tf, tc = arms((trials + 1) * steps,
                          (trials + 2) * steps - 1)
            return tc / max(tf, 1e-12)

        hbm = pipeline_hbm_model(fused)
        emit(f"rtm3 r=2 {g}^3 {plat} pipeline-fusion-speedup",
             t_chain / max(t_fused, 1e-12), "x",
             remeasure=remeasure_ratio, stages=len(fused.stage_names),
             fused=fused.fused, chained_secs=round(t_chain, 3),
             fused_secs=round(t_fused, 3), hbm_bytes_model=hbm)
        fused.end()
        chained.end()

    def pipeline_push_ab():
        # Push-memory tile-graph fusion A/B on the PURE rtm chain
        # (rtm_img_pure: no img(t) self-read, so the merged image var's
        # only reader is the smoother at +step — the push flagship):
        # three arms at the same pallas K=1 temporal schedule, where
        # the merge and the push are both bit-exact vs the host-chained
        # oracle.  push = fused with the image tile consumed in-VMEM
        # (no input DMA, no write-back — the var leaves HBM entirely);
        # nopush = the r16 source-fused arm (bound reads eliminated,
        # the image still round-trips HBM); chained = the oracle.
        # Bit-equality gates run BEFORE and AFTER the timed windows on
        # both fused arms.  The headline is push vs source-fused (the
        # r16 baseline); the hbm model's chained/fused/fused_push
        # bytes-per-point ride the row with each arm's achieved
        # bandwidth so the modeled traffic drop is a ledger number.
        import numpy as np
        from yask_tpu.ops.pipeline import (SolutionPipeline, rtm_chain,
                                           pipeline_hbm_model)
        g = 64 if on_tpu else 32

        def mk(fuse, push_cli):
            stages, bindings = rtm_chain(radius=2, accumulate=False)
            pipe = SolutionPipeline(env, stages, bindings)
            pipe.apply_command_line_options(
                f"-g {g} -mode pallas -wf_steps 1 {push_cli}")
            pipe.prepare(fuse=fuse)
            v = pipe.get_var("fwd", "pressure")
            rng = np.random.RandomState(7)
            arr = (rng.rand(g, g, g).astype(np.float32) - 0.5) * 0.1
            for t in range(v.get_first_valid_step_index(),
                           v.get_last_valid_step_index() + 1):
                v.set_elements_in_slice(arr, [t, 0, 0, 0],
                                        [t, g - 1, g - 1, g - 1])
            return pipe

        push = mk(True, "-push on")
        nopush = mk(True, "-push off")
        chained = mk(False, "-push off")
        pal = (push.plan().get("pallas") or {})
        if not pal.get("push"):
            raise RuntimeError(
                f"push arm did not engage: {push.plan()['reasons']}")

        def gate(tag):
            bad = push.compare(chained) + nopush.compare(chained)
            if bad:
                raise RuntimeError(
                    f"push fusion not bit-identical to the "
                    f"host-chained oracle {tag} ({bad} mismatches)")

        # warmup pays trace+compile on all arms AND feeds the bit gate
        push.run(0, steps - 1)
        nopush.run(0, steps - 1)
        chained.run(0, steps - 1)
        gate("before timed windows")

        def arm(pipe, lo, hi):
            t0 = time.perf_counter()
            pipe.run(lo, hi)
            return time.perf_counter() - t0

        t_push = t_nopush = t_chain = 0.0
        trials = 3
        for i in range(trials):
            lo, hi = (i + 1) * steps, (i + 2) * steps - 1
            t_push += arm(push, lo, hi)
            t_nopush += arm(nopush, lo, hi)
            t_chain += arm(chained, lo, hi)
        gate("after timed windows")

        def remeasure_ratio():
            lo = (trials + 1) * steps
            hi = (trials + 2) * steps - 1
            return arm(nopush, lo, hi) / max(arm(push, lo, hi), 1e-12)

        hbm = pipeline_hbm_model(push, push_vars=pal.get("push_vars"))
        n_steps = trials * steps
        pts = g ** 3 * n_steps

        def gbs(bpp, secs):
            return round(bpp * pts / max(secs, 1e-12) / 1e9, 3)

        emit(f"rtm3-pure r=2 {g}^3 {plat} pipeline-push-speedup",
             t_nopush / max(t_push, 1e-12), "x",
             remeasure=remeasure_ratio,
             criterion="push arm >= source-fused arm",
             criterion_met=bool(t_push <= t_nopush),
             push_vars=pal.get("push_vars"),
             hbm_bytes_model=hbm,
             push_secs=round(t_push, 3),
             fused_secs=round(t_nopush, 3),
             chained_secs=round(t_chain, 3),
             achieved_gbs_push=gbs(hbm["fused_push_bytes_pp"], t_push),
             achieved_gbs_fused=gbs(hbm["fused_bytes_pp"], t_nopush),
             achieved_gbs_chained=gbs(hbm["chained_bytes_pp"], t_chain),
             chained_over_push=round(
                 t_chain / max(t_push, 1e-12), 4))
        push.end()
        nopush.end()
        chained.end()

    def serve_resident_ab():
        # Device-resident bulk serving A/B: the SAME work list — 4
        # sessions x 4 single-step items — drained through the
        # resident executor (one device-lock hold, one end-of-queue
        # sync, one extraction per session) vs per-request dispatch
        # through the scheduler (queue + batching window + rollback
        # snapshot + host extraction per item).  Responses bit-gated
        # identical across arms before the row is trusted; profile is
        # shared and pre-warmed so neither arm pays compile.
        import numpy as np
        from yask_tpu.serve.registry import SessionRegistry
        from yask_tpu.serve.scheduler import BatchScheduler
        from yask_tpu.serve.resident import run_per_request
        g, occupancy, nsteps = 16, 4, 4
        rng = np.random.RandomState(11)
        arr = (rng.rand(g, g, g).astype(np.float32) - 0.5) * 0.1

        reg = SessionRegistry(fac, env)
        prof = reg.get_profile("iso3dfd", 2, str(g), mode="jit", wf=1)
        sched = BatchScheduler(reg, window_secs=0.0)

        def open_sessions():
            sids = []
            for i in range(occupancy):
                s = reg.open_session(prof)
                sids.append(s.sid)
                with sched.session_ctx(s.sid) as ctx:
                    v = ctx.get_var("pressure")
                    for t in range(v.get_first_valid_step_index(),
                                   v.get_last_valid_step_index() + 1):
                        v.set_elements_in_slice(
                            arr * (i + 1), [t, 0, 0, 0],
                            [t, g - 1, g - 1, g - 1])
            return sids

        def work(sids):
            return [(sid, t, t) for t in range(nsteps)
                    for sid in sids]

        # warm the shared profile's compile outside both timed arms
        warm = open_sessions()
        sched.run_resident(work(warm)[:1])
        for sid in warm:
            reg.close_session(sid)

        sids_r = open_sessions()
        t0 = time.perf_counter()
        res = sched.run_resident(work(sids_r))
        t_resident = time.perf_counter() - t0

        sids_p = open_sessions()
        t0 = time.perf_counter()
        base = run_per_request(sched, work(sids_p))
        t_per_req = time.perf_counter() - t0

        for sr, sp in zip(sids_r, sids_p):
            for name, a in res[sr]["outputs"].items():
                if not np.array_equal(a, base[sp]["outputs"][name]):
                    raise RuntimeError(
                        f"resident arm diverged from per-request "
                        f"dispatch on {name}")

        def remeasure_ratio():
            s1, s2 = open_sessions(), open_sessions()
            t0 = time.perf_counter()
            sched.run_resident(work(s1))
            tr = time.perf_counter() - t0
            t0 = time.perf_counter()
            run_per_request(sched, work(s2))
            return (time.perf_counter() - t0) / max(tr, 1e-12)

        emit(f"iso3dfd r=2 {g}^3 {plat} serve-resident-speedup",
             t_per_req / max(t_resident, 1e-12), "x",
             remeasure=remeasure_ratio,
             criterion=f"resident arm strictly faster at "
                       f"occupancy {occupancy}",
             criterion_met=bool(t_resident < t_per_req),
             occupancy=occupancy, items=occupancy * nsteps,
             resident_secs=round(t_resident, 4),
             per_request_secs=round(t_per_req, 4))
        sched.shutdown()

    # explicit section(...) calls (not a loop over a tuple): repo_lint's
    # BARE-DEVICE-CALL closure sanctions device work lexically, from
    # the names passed into the guard invokers
    section(iso3dfd_jit, t0, budget_secs)
    section(iso3dfd_pallas, t0, budget_secs)
    section(cube_wavefront, t0, budget_secs)
    section(iso3dfd_skew2d, t0, budget_secs)
    section(iso3dfd_trapezoid, t0, budget_secs)
    section(ssg_elastic, t0, budget_secs)
    section(iso3dfd_bf16, t0, budget_secs)
    section(awp_decomposed, t0, budget_secs)
    section(sm_coalesce, t0, budget_secs)
    section(sp_overlap, t0, budget_secs)
    section(ensemble_ab, t0, budget_secs)
    section(serve_batch_ab, t0, budget_secs)
    section(serve_bucket_ab, t0, budget_secs)
    section(serve_stream_ab, t0, budget_secs)
    section(pipeline_fusion_ab, t0, budget_secs)
    section(pipeline_push_ab, t0, budget_secs)
    section(serve_resident_ab, t0, budget_secs)
    return list(ROWS)


def main() -> int:
    # relay-down protection (the bench's subprocess probe + CPU fallback)
    try:
        import bench
        if bench._probe_platform() is None:
            bench._force_cpu_env()
    except ImportError:
        pass

    from yask_tpu import yk_factory
    fac = yk_factory()
    env = fac.new_env()
    # graceful section-skip margin inside bench.py's hard-kill budget,
    # so the artifact is written and sections are skipped, not killed
    try:
        budget = float(os.environ.get("YT_SUITE_BUDGET", "900"))
    except ValueError:
        budget = 900.0
    rows = run_suite(fac, env, budget_secs=max(budget - 60.0, 30.0))
    out = os.path.join(_ROOT, "BENCH_suite_latest.json")
    try:
        with open(out, "w") as f:
            json.dump({"platform": env.get_platform(), "rows": rows}, f,
                      indent=1)
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
