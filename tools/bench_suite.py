"""BASELINE-table benchmark suite: one JSON line per headline config.

Covers the target rows in BASELINE.md beyond the single-number contract
of ``bench.py``:

* iso3dfd order-16, single device (jit vs tuned pallas);
* cube/9axis 27-point with temporal wave-front fusion (wavefront
  speedup = fused K>1 over K=1);
* ssg staggered elastic (multi-var);
* awp, domain-decomposed with measured halo fraction (multi-device).

Sizes shrink automatically off-TPU so the suite stays runnable on the
virtual CPU mesh for plumbing validation.

Run: ``python tools/bench_suite.py``
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(ctx, g_pts, steps, trials=3):
    rates = []
    t = ctx._cur_step
    ctx.run_solution(t, t + steps - 1)   # warm
    t += steps
    for _ in range(trials):
        t0 = time.perf_counter()
        ctx.run_solution(t, t + steps - 1)
        dt = time.perf_counter() - t0
        t += steps
        rates.append(g_pts * steps / dt / 1e9)
    rates.sort()
    return rates[len(rates) // 2]


def build(fac, env, name, radius, g, mode, wf=0, ranks=(), measure_halo=False):
    from yask_tpu.runtime.init_utils import init_solution_vars
    ctx = fac.new_solution(env, stencil=name, radius=radius)
    opts = f"-g {g} -wf_steps {wf}"
    if measure_halo:
        opts += " -measure_halo"
    ctx.apply_command_line_options(opts)
    ctx.get_settings().mode = mode
    for d, r in ranks:
        ctx.set_num_ranks(d, r)
    ctx.prepare_solution()
    init_solution_vars(ctx)
    return ctx


def emit(metric, value, unit, **extra):
    print(json.dumps({"metric": metric, "value": round(value, 4),
                      "unit": unit, **extra}), flush=True)


def main() -> int:
    from yask_tpu import yk_factory
    fac = yk_factory()
    env = fac.new_env()
    plat = env.get_platform()
    on_tpu = plat == "tpu"
    ndev = env.get_num_ranks()

    g = 512 if on_tpu else 48
    steps = 10 if on_tpu else 2

    # 1) iso3dfd order-16 single device: jit, then pallas
    ctx = build(fac, env, "iso3dfd", 8, g, "jit")
    rate = measure(ctx, g ** 3, steps)
    emit(f"iso3dfd r=8 {g}^3 {plat} jit", rate, "GPts/s")
    try:
        p = build(fac, env, "iso3dfd", 8, g, "pallas", wf=2)
        rate_p = measure(p, g ** 3, steps)
        emit(f"iso3dfd r=8 {g}^3 {plat} pallas-K2", rate_p, "GPts/s")
    except Exception as e:
        emit(f"iso3dfd r=8 {g}^3 {plat} pallas-K2", 0.0, "GPts/s",
             error=str(e)[:120])

    # 2) cube 27-pt wave-front speedup (fused K4 over K1)
    gc = 256 if on_tpu else 32
    try:
        base = measure(build(fac, env, "cube", 1, gc, "pallas", wf=1),
                       gc ** 3, steps)
        fused = measure(build(fac, env, "cube", 1, gc, "pallas", wf=4),
                        gc ** 3, steps)
        emit(f"cube 27pt {gc}^3 {plat} wavefront-speedup",
             fused / max(base, 1e-12), "x", k1_gpts=round(base, 4),
             k4_gpts=round(fused, 4))
    except Exception as e:
        emit(f"cube 27pt {gc}^3 {plat} wavefront-speedup", 0.0, "x",
             error=str(e)[:120])

    # 3) ssg staggered elastic
    gs = 256 if on_tpu else 32
    ctx = build(fac, env, "ssg", 2, gs, "jit")
    emit(f"ssg r=2 {gs}^3 {plat} jit", measure(ctx, gs ** 3, steps),
         "GPts/s")

    # 4) awp domain-decomposed + halo fraction (needs >1 device)
    if ndev > 1:
        ga = 256 if on_tpu else 32
        ctx = build(fac, env, "awp", None, ga, "shard_map",
                    ranks=[("x", ndev)], measure_halo=True)
        rate = measure(ctx, ga ** 3, steps)
        st = ctx.get_stats()
        halo_pct = (100.0 * st.get_halo_secs()
                    / max(st.get_elapsed_secs(), 1e-12))
        emit(f"awp {ga}^3 {plat} x{ndev} shard_map", rate, "GPts/s",
             halo_pct=round(halo_pct, 2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
