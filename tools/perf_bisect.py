"""Replay one perf-ledger row-key across a git revision range.

The round-5 verdict's unanswerable question — "is the −24 % slide the
code or the machine?" — becomes a command: take the row key exactly as
it appears in ``PERF_LEDGER.jsonl``, check out each candidate revision
into a throwaway worktree under ``.perf_bisect/``, re-measure the SAME
configuration in each, and print one table.  All replays run back to
back on the same host with fresh load + calibration context per row, so
a value that moves only with the revision is code, and one that moves
with ``calib_gpts`` is the machine.

Row keys understood (the suite/bench naming scheme):

* ``<stencil> r=<R> <G>^3 <plat> <mode>[-K<k>][ bf16]`` — throughput
  replay (``iso3dfd r=8 128^3 fp32 cpu throughput (jit)`` and the
  harness' ``... harness (jit)`` spellings are parsed too);
* ``<stencil> <tag> <G>^3 <plat> wavefront-speedup`` — fused K=4 over
  K=1 pallas ratio (the cube residue row).

Each replay result is appended to the ledger with ``source="bisect"``
and the revision in ``extra`` (the sentinel excludes bisect rows from
guard baselines — historical revisions must not shift the median).

Usage::

    python tools/perf_bisect.py "iso3dfd r=8 128^3 fp32 cpu throughput (jit)" \
        47f415b HEAD [-trials 3] [-steps 4] [--keep]
"""

from __future__ import annotations

import json
import os
import re
import shutil
import subprocess
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

_WT_DIR = os.path.join(_ROOT, ".perf_bisect")

#: the per-revision replay, run with cwd=<worktree> so it imports THAT
#: revision's yask_tpu.  Only the oldest-stable API surface is used
#: (yk_factory / apply_command_line_options / run_solution), so specs
#: replay across every round boundary.
_REPLAY = r"""
import json, sys, time
spec = json.loads(sys.argv[1])

from yask_tpu import yk_factory
from yask_tpu.runtime.init_utils import init_solution_vars

fac = yk_factory()
env = fac.new_env()

def build(mode, wf):
    ctx = fac.new_solution(env, stencil=spec["stencil"],
                           radius=spec["radius"] or None)
    ctx.apply_command_line_options(f"-g {spec['g']} -wf_steps {wf}")
    ctx.get_settings().mode = mode
    ctx.prepare_solution()
    init_solution_vars(ctx)
    return ctx

def measure(ctx):
    g, steps, trials = spec["g"], spec["steps"], spec["trials"]
    npts = g ** len(ctx.get_domain_dim_names())
    t = 0
    ctx.run_solution(t, t + steps - 1)   # warm (compile)
    t += steps
    rates = []
    for _ in range(trials):
        t0 = time.perf_counter()
        ctx.run_solution(t, t + steps - 1)
        dt = time.perf_counter() - t0
        t += steps
        rates.append(npts * steps / dt / 1e9)
    rates.sort()
    return rates[len(rates) // 2]

if spec["kind"] == "wavefront-speedup":
    base = measure(build("pallas", 1))
    fused = measure(build("pallas", 4))
    out = {"value": round(fused / max(base, 1e-12), 4), "unit": "x",
           "k1_gpts": round(base, 4), "k4_gpts": round(fused, 4)}
else:
    out = {"value": round(measure(build(spec["mode"], spec["wf"])), 4),
           "unit": "GPts/s"}
print("PERF_BISECT_RESULT " + json.dumps(out))
"""


def parse_key(key: str) -> dict:
    """Row key → replay spec; raises ValueError on an unknown shape."""
    m = re.search(r"(\d+)\^3", key)
    if m:
        g = int(m.group(1))
    else:
        # the harness' cube spelling: g=64x64x64
        hm = re.search(r"g=(\d+(?:x\d+)+)", key)
        if not hm or len(set(hm.group(1).split("x"))) != 1:
            raise ValueError(f"no cubic domain size in row key: {key!r}")
        g = int(hm.group(1).split("x")[0])
    stencil = key.split()[0]
    rm = re.search(r"\br=(\d+)", key)
    radius = int(rm.group(1)) if rm else 0
    if "wavefront-speedup" in key:
        return {"kind": "wavefront-speedup", "stencil": stencil,
                "radius": radius, "g": g}
    # mode: "(jit)" / "(pallas-K2)" contract+harness style, or the
    # suite's trailing "jit" / "pallas-K2" token
    mode, wf = "jit", 1
    pm = re.search(r"\(?\b(jit|pallas(?:-K(\d+))?)\)?(?:\s+bf16)?\s*$",
                   key) or re.search(r"\((jit|pallas(?:-K(\d+))?)\)", key)
    if pm:
        mode = "pallas" if pm.group(1).startswith("pallas") else "jit"
        wf = int(pm.group(2)) if pm.group(2) else 1
    return {"kind": "throughput", "stencil": stencil, "radius": radius,
            "g": g, "mode": mode, "wf": wf}


def _git(*args: str, cwd: str = _ROOT) -> str:
    return subprocess.run(["git", *args], cwd=cwd, text=True,
                          stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT).stdout.strip()


def replay_at(rev: str, spec: dict, timeout: float = 600.0) -> dict:
    """Measure the spec at one revision (throwaway worktree)."""
    sha = _git("rev-parse", "--short", rev)
    wt = os.path.join(_WT_DIR, sha)
    if not os.path.isdir(wt):
        out = _git("worktree", "add", "--detach", wt, rev)
        if not os.path.isdir(wt):
            return {"rev": rev, "error": f"worktree add failed: {out[:200]}"}
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
                "PYTHONPATH": wt})
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _REPLAY, json.dumps(spec)],
            cwd=wt, env=env, text=True, timeout=timeout,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    except subprocess.TimeoutExpired:
        return {"rev": rev, "sha": sha, "error": "timeout"}
    for ln in proc.stdout.splitlines():
        if ln.startswith("PERF_BISECT_RESULT "):
            res = json.loads(ln[len("PERF_BISECT_RESULT "):])
            return {"rev": rev, "sha": sha, **res}
    return {"rev": rev, "sha": sha,
            "error": (proc.stderr.strip().splitlines() or ["no output"])
            [-1][:200]}


def cleanup() -> None:
    if not os.path.isdir(_WT_DIR):
        return
    for name in os.listdir(_WT_DIR):
        _git("worktree", "remove", "--force",
             os.path.join(_WT_DIR, name))
    _git("worktree", "prune")
    shutil.rmtree(_WT_DIR, ignore_errors=True)


def bisect(key: str, revs, trials: int = 3, steps: int = 4,
           keep: bool = False, ledger: bool = True, out=None):
    out = out or sys.stdout
    spec = dict(parse_key(key), trials=trials, steps=steps)
    out.write(f"replaying {spec} at {len(revs)} revision(s)\n")
    results = []
    try:
        for rev in revs:
            from yask_tpu.perflab import capture_provenance
            res = replay_at(rev, spec)
            # per-replay calibration: same-host noise yardstick riding
            # next to each value in the table AND the ledger row
            prov = capture_provenance(platform="cpu", device_kind="cpu")
            res["calib_gpts"] = prov.get("calib_gpts")
            results.append(res)
            out.write(json.dumps(res) + "\n")
            if ledger and "error" not in res:
                from yask_tpu.perflab.sentinel import guard_and_append
                guard_and_append(
                    key, res["value"], res["unit"], "cpu", "bisect",
                    prov, extra={"rev": res.get("sha", rev),
                                 **{k: v for k, v in res.items()
                                    if k in ("k1_gpts", "k4_gpts")}})
    finally:
        if not keep:
            cleanup()
    ok = [r for r in results if "error" not in r]
    if len(ok) >= 2:
        first, last = ok[0], ok[-1]
        ratio = last["value"] / max(first["value"], 1e-12)
        out.write(f"{first.get('sha')} -> {last.get('sha')}: "
                  f"{first['value']} -> {last['value']} {last['unit']} "
                  f"({ratio:.3f}x; calib "
                  f"{first['calib_gpts']} -> {last['calib_gpts']})\n")
    return results


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    trials, steps, keep, ledger = 3, 4, False, True
    pos = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "-trials":
            trials = int(argv[i + 1]); i += 2
        elif a == "-steps":
            steps = int(argv[i + 1]); i += 2
        elif a == "--keep":
            keep = True; i += 1
        elif a == "--no-ledger":
            ledger = False; i += 1
        else:
            pos.append(a); i += 1
    if len(pos) < 3:
        sys.stderr.write(__doc__ + "\n")
        return 2
    key, revs = pos[0], pos[1:]
    results = bisect(key, revs, trials=trials, steps=steps, keep=keep,
                     ledger=ledger)
    return 0 if all("error" not in r for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
