#!/usr/bin/env python
"""Prometheus text exposition for yask_tpu telemetry snapshots.

Renders a metrics snapshot — a single server's
``StencilServer.metrics_snapshot()`` or a fleet's merged
``op metrics_snapshot`` reply — as Prometheus text exposition
(``yask_tpu.obs.telemetry.to_prometheus``): counters and gauges get
``# TYPE`` lines plus per-worker ``{worker="w0"}`` labels on fleet
snapshots; histograms export as summaries (``quantile="0.5"|"0.99"``,
``_count``/``_sum``/``_max``).  Names derive mechanically from registry
names (``serve.total_ms`` → ``yt_serve_total_ms``) — the stable set is
pinned by ``tests/test_telemetry.py``.

Two sources::

    python tools/obs_export.py --snapshot snap.json     # a saved reply
    python tools/obs_export.py --port 7421              # a live front

``--port`` speaks the JSON-lines protocol to a running ``serve.py`` /
``serve_fleet.py`` front, sends one ``{"op": "metrics_snapshot"}``, and
renders the answer — the shape a node-exporter-style scrape wrapper
would loop on.  No device work, no jax import.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

import os

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from yask_tpu.obs.telemetry import to_prometheus


def _unwrap(doc: Dict) -> Dict:
    """Accept any of: a raw snapshot, a ``{"snapshot": ...}`` serve
    reply, or a ``{"telemetry": ...}`` fleet reply."""
    if not isinstance(doc, dict):
        return {}
    for key in ("telemetry", "snapshot"):
        if isinstance(doc.get(key), dict):
            return doc[key]
    return doc


def export_snapshot(doc: Dict, prefix: str = "yt") -> str:
    return to_prometheus(_unwrap(doc), prefix=prefix)


def fetch_live(host: str, port: int) -> Dict:
    """One ``metrics_snapshot`` round-trip against a live front."""
    from tools.serve_client import ServeClient
    client = ServeClient.connect(host=host, port=port)
    try:
        return client.call("metrics_snapshot")
    finally:
        client.close()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Prometheus text exposition of a telemetry "
                    "snapshot")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--snapshot", metavar="FILE",
                     help="a saved snapshot / op-reply JSON file "
                          "('-' = stdin)")
    src.add_argument("--port", type=int,
                     help="poll a live serve/serve_fleet front on TCP")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--prefix", default="yt",
                    help="metric name prefix (default: yt)")
    args = ap.parse_args(argv)

    if args.snapshot:
        raw = (sys.stdin.read() if args.snapshot == "-"
               else open(args.snapshot).read())
        doc = json.loads(raw)
    else:
        doc = fetch_live(args.host, args.port)
    text = export_snapshot(doc, prefix=args.prefix)
    sys.stdout.write(text)
    return 0 if text else 1


if __name__ == "__main__":
    sys.exit(main())
