#!/usr/bin/env python
"""Client for the JSON-lines serving front (``tools/serve.py``).

Two transports:

* :meth:`ServeClient.spawn` — launch ``tools/serve.py`` as a child
  process and talk over its stdio pipes (the examples' shape: no
  ports, dies with the parent);
* :meth:`ServeClient.connect` — TCP to a ``--port`` server.

Arrays cross the wire as flat float lists + shape + dtype
(float32 round-trips exactly through JSON doubles), so a client-side
comparison against a local oracle can demand bit-identity.

Usage::

    with ServeClient.spawn() as c:
        sid = c.open(stencil="iso3dfd", radius=2, g=16, mode="jit")
        c.fill(sid, "vel", 0.5)
        c.fill_slice(sid, "pressure", arr, [0,0,0,0], [0,15,15,15])
        resps = c.run_many([(sid, 0, 3)])     # batches on the server
        out = resps[0]["outputs"]["pressure"] # numpy, decoded
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
SERVE_PY = os.path.join(_HERE, "serve.py")


def encode_array(a) -> Dict:
    a = np.asarray(a)
    return {"shape": list(a.shape), "dtype": str(a.dtype),
            "data": [float(x) for x in a.ravel().tolist()]}


def decode_array(d: Dict):
    return np.asarray(d["data"],
                      dtype=np.dtype(d.get("dtype", "float32"))
                      ).reshape(d.get("shape", [-1]))


class ServeClientError(RuntimeError):
    """Raised on a transport drop or an ``ok: false`` answer.  When
    the server ANSWERED (as opposed to dying mid-op), the structured
    response dict rides on :attr:`response` so callers — the fleet
    front in particular — can pass status/anomaly fields through
    instead of flattening them into an error string."""
    response: Optional[Dict] = None


class ServeClient:
    def __init__(self, rfile, wfile, proc: Optional[subprocess.Popen] = None,
                 sock: Optional[socket.socket] = None):
        self._r = rfile
        self._w = wfile
        self._proc = proc
        self._sock = sock
        self._next_id = 0
        #: interleaved ``{"stream": true}`` lines collected during
        #: streaming run/run_many calls (decoded), oldest first.
        self.stream_events: List[Dict] = []
        #: optional callable(event) fired as each stream line arrives.
        self.on_stream = None

    # ------------------------------------------------------ transports

    @classmethod
    def spawn(cls, extra_args: Sequence[str] = (),
              env: Optional[Dict[str, str]] = None,
              stderr=None, start_new_session: bool = False
              ) -> "ServeClient":
        """Launch ``tools/serve.py`` as a stdio child.  The child
        inherits this interpreter and environment (callers set
        ``JAX_PLATFORMS``/``PALLAS_AXON_POOL_IPS`` as the situation
        demands — the examples force the CPU path).  The fleet
        supervisor passes ``start_new_session=True`` so an unhealthy
        worker can be taken down whole with ``os.killpg`` — the
        ``run_deadlined`` SIGKILL semantics, applied to workers."""
        e = dict(os.environ if env is None else env)
        proc = subprocess.Popen(
            [sys.executable, SERVE_PY, *extra_args],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=stderr, env=e, text=True,
            start_new_session=bool(start_new_session))
        return cls(proc.stdout, proc.stdin, proc=proc)

    @classmethod
    def connect(cls, host: str = "127.0.0.1",
                port: int = 0) -> "ServeClient":
        sock = socket.create_connection((host, port))
        return cls(sock.makefile("r", encoding="utf-8"),
                   sock.makefile("w", encoding="utf-8"), sock=sock)

    # ------------------------------------------------------------ wire

    def call(self, op: str, **fields) -> Dict:
        """One op round-trip; raises :class:`ServeClientError` on a
        transport drop or an ``ok: false`` answer.  Interleaved
        ``{"stream": true}`` lines (streaming runs) are collected
        onto :attr:`stream_events` — and forwarded to
        :attr:`on_stream` — until the final response line arrives."""
        msg = {"op": op, "id": self._next_id, **fields}
        self._next_id += 1
        self._w.write(json.dumps(msg) + "\n")
        self._w.flush()
        while True:
            line = self._r.readline()
            if not line:
                raise ServeClientError(
                    f"server closed the stream during op {op!r}")
            out = json.loads(line)
            if out.get("stream"):
                if "outputs" in out:
                    out["outputs"] = {k: decode_array(v)
                                      for k, v in out["outputs"].items()}
                self.stream_events.append(out)
                cb = self.on_stream
                if cb is not None:
                    cb(out)
                continue
            break
        if not out.get("ok"):
            err = ServeClientError(
                out.get("error") or f"op {op!r} failed: {out}")
            err.response = out
            raise err
        return out

    # ------------------------------------------------------------- ops

    def open(self, stencil: str, radius: Optional[int] = None, g=16,
             mode: str = "jit", wf: int = 2, options: str = "",
             session: Optional[str] = None,
             bucket: Optional[bool] = None) -> str:
        return self.call("open", stencil=stencil, radius=radius, g=g,
                         mode=mode, wf=wf, options=options,
                         session=session, bucket=bucket)["sid"]

    def fill(self, sid: str, var: str, value: float) -> None:
        self.call("fill", sid=sid, var=var, value=float(value))

    def fill_slice(self, sid: str, var: str, buf, first, last) -> int:
        return self.call("fill", sid=sid, var=var,
                         first=list(first), last=list(last),
                         **encode_array(buf))["elements"]

    def read_slice(self, sid: str, var: str, first, last):
        return decode_array(self.call("read", sid=sid, var=var,
                                      first=list(first),
                                      last=list(last)))

    def init_vars(self, sid: str) -> None:
        self.call("init", sid=sid)

    def prewarm(self, sid: str, steps: int) -> int:
        return self.call("prewarm", sid=sid, steps=steps)["chunks"]

    def run(self, sid: str, first: int, last: Optional[int] = None,
            outputs: Sequence[str] = (),
            timeout: Optional[float] = None,
            flush_every: int = 0, stream_outputs: bool = False) -> Dict:
        out = self.call("run", sid=sid, first=first, last=last,
                        outputs=list(outputs), timeout=timeout,
                        flush_every=int(flush_every),
                        stream_outputs=bool(stream_outputs))
        return self._decode_resp(out)

    def run_many(self, requests: Sequence[Tuple],
                 outputs: Sequence[str] = (),
                 timeout: Optional[float] = None) -> List[Dict]:
        """Submit-all-then-wait-all; ``requests`` is a sequence of
        ``(sid, first, last)`` or ``(sid, first, last, extra)``
        tuples (``extra`` = dict of per-request fields like
        ``flush_every`` / ``stream_outputs``).  Compatible requests
        co-batch inside the server's window."""
        reqs = []
        for r in requests:
            sid, first, last = r[0], r[1], r[2]
            m = {"sid": sid, "first": first, "last": last,
                 "outputs": list(outputs)}
            if len(r) > 3 and r[3]:
                m.update(r[3])
            reqs.append(m)
        out = self.call("run_many", requests=reqs, timeout=timeout)
        return [self._decode_resp(r) for r in out["responses"]]

    @staticmethod
    def _decode_resp(out: Dict) -> Dict:
        out["outputs"] = {k: decode_array(v)
                          for k, v in out.get("outputs", {}).items()}
        for ev in out.get("streams", ()):
            if "outputs" in ev:
                ev["outputs"] = {k: decode_array(v)
                                 for k, v in ev["outputs"].items()}
        return out

    def ping(self) -> Dict:
        """Liveness heartbeat (fleet supervision)."""
        return self.call("ping")

    def metrics(self) -> Dict:
        return self.call("metrics")["metrics"]

    def cache_stats(self) -> Dict:
        """The worker's process-wide compile-cache counters
        (``yask_tpu.cache.stats()``) — ``lowerings == 0`` on a
        warm-started worker is the fleet acceptance probe."""
        return self.call("cache_stats")

    def flush_metrics(self) -> int:
        return self.call("flush_metrics")["rows"]

    def close_session(self, sid: str) -> None:
        self.call("close", sid=sid)

    # ------------------------------------------------------- lifecycle

    def shutdown(self) -> None:
        try:
            self.call("shutdown")
        except ServeClientError:
            pass  # already gone

    def close(self) -> None:
        try:
            self.shutdown()
        finally:
            for f in (self._w, self._r):
                try:
                    f.close()
                except Exception:  # noqa: BLE001
                    pass
            if self._sock is not None:
                self._sock.close()
            if self._proc is not None:
                try:
                    self._proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
                    self._proc.wait()

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
