"""TPU-session protocol, as one command.

Round 3 ran the Pallas backend on real Mosaic (v5e) for the first time:
22/26 validation-matrix cases matched the jit oracle and the
pipeline_dmas A/B measured 1.75× before the relay dropped.  This script
is the staged validation + tuning session to (re)run whenever hardware
is reachable — the remaining goals are 26/26 validation, the skew A/B,
a completed joint tune, and a tuned bench number (VERDICT r3 items
1-3):

1. smoke: iso3dfd on the XLA path (device sanity);
2. validate: the pallas equivalence matrix ON DEVICE (interpret=False,
   real Mosaic lowering) against the jit path — runs FIRST on full
   sessions, but AFTER the perf stages on ``--quick`` first-window
   sessions (round 3 lost its hardware numbers to a relay drop while
   validation compiles were still grinding);
3. A/B: pipeline_dmas / skew / misaligned-E_sk / bf16 chunk variants
   (bit-equality cross-checks + timing on real DMA engines) plus the
   shard_pallas overlapped-exchange arms when >1 device is attached;
4. tune: joint (K, block) auto-tuner walk on iso3dfd at the bench size;
5. report: a BENCH-style JSON line per stage (each perf row is
   persisted to TPU_RESULTS.jsonl the moment it is measured);
6. compile_cache_ab: cold-vs-warm AOT compile through the persistent
   cache (the warm rebuild must show ZERO lowerings on the cache's
   trace counter — a disk round-trip of a serialized executable on the
   real backend) and ensemble_ab: N-member batched-vs-sequential run
   with per-member bit-identity; then
7. compile-time A/B of the ``max_vinstr`` tile cap on ssg/swe2d.

Every stage is crash-isolated AND journaled (yask_tpu.resilience):
each case appends its outcome to SESSION_JOURNAL.jsonl the moment it
is known, ``--resume`` completes only the cases a dropped relay left
unfinished (and, with ``YT_CKPT_DIR`` set, restarts MID-case from the
supervision cadence's last checkpoint instead of re-running the whole
case), a consecutive-fault breaker (persisted across watcher restarts)
aborts the session loudly when the relay dies mid-run, and every
measured row passes the result-sanity guards (an all-zero field is banked as a quarantined ANOMALY
row, never a clean number — the round-3 quick-matrix incident).

Run: ``python tools/tpu_session.py [-g 512] [--quick] [--resume |
--fresh] [--stages smoke,validate,...] [-no-trace]``
(needs the real backend: do NOT set JAX_PLATFORMS=cpu).
``YT_SESSION_MATRIX="name:radius,..."`` ("-" = default radius)
overrides the validation matrix; ``YT_SESSION_JOURNAL`` relocates the
journal; ``YT_SESSION_BANK=1`` banks rows off-TPU (tests).

Tracing is ON by default here (``-trace``/``-no-trace``; an explicit
``YT_TRACE`` env wins): hardware windows are the scarce resource, and a
span timeline that joins the session journal / ledger rows is exactly
the evidence a post-mortem of a dropped relay window needs.  See
docs/observability.md.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from yask_tpu.resilience import (Breaker, Fault, SessionJournal,
                                 TERMINAL_OUTCOMES, anomaly_fields,
                                 check_output, guarded_call,
                                 maybe_corrupt)

MATRIX = [
    ("3axis", 1), ("cube", 1), ("iso3dfd", 2), ("iso3dfd_sponge", 2),
    ("ssg", 2), ("awp", None), ("tti", 2), ("swe2d", None),
    ("box", None), ("test_scratch_3d", None), ("test_stream_3d", None),
    ("test_boundary_3d", None), ("test_misc_2d", None),
]

STAGES = ("smoke", "validate", "chunk_abs", "tune_bench",
          "compile_cache_ab", "ensemble_ab", "pipeline_fusion_ab",
          "push_ab", "serving", "serving_bucket", "serve_resident_ab",
          "compile_time")


def matrix_cases():
    """The validation matrix, overridable via YT_SESSION_MATRIX
    ("name:radius,..." with "-" for the stencil's default radius) —
    the resume acceptance test runs a 2-stencil matrix on the CPU
    mesh instead of burning minutes on all 13."""
    raw = os.environ.get("YT_SESSION_MATRIX", "").strip()
    if not raw:
        return list(MATRIX)
    out = []
    for part in raw.split(","):
        name, _, rad = part.strip().partition(":")
        out.append((name, None if rad in ("", "-") else int(rad)))
    return out


def log(stage, **kv):
    print(json.dumps({"stage": stage, **kv}), flush=True)


def bank_row(plat, env, line, roofline=None, sanity=None):
    """Persist one measured TPU row twice: bench.py's TPU_RESULTS.jsonl
    (the ``last_tpu_measured`` contract fallback) and the unified perf
    ledger (source ``tpu_session``) with provenance + a sentinel
    verdict — relay windows are short, so every row is banked the
    moment it exists.  A failed ``sanity`` verdict quarantines the row
    in BOTH artifacts (structured ANOMALY, excluded from sentinel
    baselines and from ``last_tpu_measured``)."""
    line = dict(line)
    if sanity and not sanity.get("ok", True):
        line.update(anomaly_fields(sanity))
    try:
        from bench import _record_tpu_result
        _record_tpu_result(line)
    except Exception:  # noqa: BLE001
        pass
    try:
        from yask_tpu.perflab import capture_provenance
        from yask_tpu.perflab.sentinel import guard_and_append
        prov = capture_provenance(
            platform=plat,
            device_kind=(getattr(env.get_devices()[0], "device_kind",
                                 "") if env.get_devices() else ""))
        extra = {k: v for k, v in line.items()
                 if k not in ("metric", "value", "unit", "platform",
                              "quarantined", "anomaly")}
        guard_and_append(line["metric"], line["value"], line["unit"],
                         plat, "tpu_session", prov,
                         roofline=roofline, extra=extra or None,
                         sanity=sanity)
    except Exception as e:  # noqa: BLE001
        log("ledger", error=str(e)[:160])
    return line


def build(fac, env, name, mode, g, radius, wf=1, block=None, tune=False,
          tune_max=None):
    from yask_tpu.runtime.init_utils import init_solution_vars
    ctx = fac.new_solution(env, stencil=name, radius=radius)
    ctx.apply_command_line_options(f"-g {g} -wf_steps {wf}")
    ctx.get_settings().mode = mode
    if tune:
        # Must be set BEFORE prepare: pallas pads are then planned for
        # tune_max_wf_steps so the joint walk can grow K, not only
        # shrink it (K-doubling candidates would otherwise all fail pad
        # validation and cache as inf).
        ctx.get_settings().do_auto_tune = True
        if tune_max:
            ctx.get_settings().tune_max_wf_steps = tune_max
    if block:
        for d, b in block.items():
            ctx.set_block_size(d, b)
    # static preflight (default-on): catch statically-infeasible configs
    # (the round-3 VMEM-spill class) BEFORE spending relay-window time
    # on a compile; findings are logged, the stage still proceeds so a
    # checker false-positive cannot cost a hardware window
    from yask_tpu.checker import preflight
    if not preflight(ctx):
        log("preflight", name=name, mode=mode, ok=False)
    ctx.prepare_solution()
    init_solution_vars(ctx)
    return ctx


def interior_slice(ctx):
    """A small interior slice of the first var around the domain center
    (seeded nonzero by init_solution_vars) — the sanity-guard probe."""
    name = ctx.get_var_names()[0]
    v = ctx.get_var(name)
    t = ctx._cur_step
    mid = [s // 2 for s in
           (ctx.get_settings().global_domain_sizes[d]
            for d in ctx.get_domain_dim_names())]
    return v.get_elements_in_slice([t] + [c - 1 for c in mid],
                                   [t] + [c + 1 for c in mid])


class SessionRunner:
    """Journal + breaker wiring around every stage/case: outcomes are
    durable the moment they are known, ``--resume`` skips journaled
    terminal cases, and ``breaker.threshold`` consecutive classified
    faults abort the whole session (a dead relay must end it loudly,
    not grind every remaining case against nothing)."""

    def __init__(self, journal: SessionJournal, resume: bool,
                 breaker: Breaker):
        self.journal = journal
        self.resume = resume
        self.breaker = breaker
        self.last_status = ""   # "skipped"|"fault"|terminal outcome

    def pending(self, stage, cases):
        if not self.resume:
            return list(cases)
        return self.journal.pending(stage, list(cases))

    def run_case(self, stage, case, fn):
        """One journaled case.  ``fn`` returning ``{"outcome":
        "anomaly"|"skip", ...}`` selects a non-ok terminal outcome
        (details journaled); any other return is outcome ``ok``."""
        if self.resume and self.journal.completed(stage, case):
            self.last_status = "skipped"
            log(stage, case=case, skipped="journaled complete")
            return None
        attempt = self.journal.attempts(stage, case) + 1
        self.journal.record(stage, case, "started", attempt=attempt)
        site = f"session.{stage}" + (f".{case}" if case else "")
        try:
            out = guarded_call(fn, site=site, breaker=self.breaker)
        except Fault as f:
            self.last_status = "fault"
            self.journal.record(stage, case, "fault", attempt=attempt,
                                kind=f.kind, error=str(f)[:160])
            log(stage, case=case, fault=f.kind, error=str(f)[:200])
            if self.breaker.tripped:
                self.journal.record(
                    "session", "", "aborted",
                    reason=f"{self.breaker.consecutive} consecutive "
                           f"faults (last: {f.kind})")
                raise
            return None
        except Exception as e:  # noqa: BLE001 - stage isolation
            self.last_status = "fault"
            self.journal.record(stage, case, "fault", attempt=attempt,
                                error=str(e)[:160])
            log(stage, case=case, error=str(e)[:200])
            return None
        outcome, detail = "ok", {}
        if isinstance(out, dict) and out.get("outcome") \
                in TERMINAL_OUTCOMES:
            outcome = out["outcome"]
            detail = {k: v for k, v in out.items() if k != "outcome"}
        self.last_status = outcome
        self.journal.record(stage, case, outcome, attempt=attempt,
                            **detail)
        return out


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    g_bench = 512
    quick = False
    resume = False
    trace = True
    stages = list(STAGES)
    journal_path = None
    i = 0
    while i < len(argv):
        if argv[i] == "-g":
            g_bench = int(argv[i + 1]); i += 2
        elif argv[i] == "--quick":
            quick = True; i += 1
        elif argv[i] == "--resume":
            resume = True; i += 1
        elif argv[i] in ("-trace", "--trace"):
            trace = True; i += 1
        elif argv[i] in ("-no-trace", "--no-trace"):
            trace = False; i += 1
        elif argv[i] == "--fresh":
            resume = False
            try:
                os.remove(SessionJournal().path)
            except OSError:
                pass
            i += 1
        elif argv[i] == "--stages":
            stages = [s.strip() for s in argv[i + 1].split(",")
                      if s.strip()]
            i += 2
        else:
            print(__doc__)
            return 2

    # span tracing defaults ON for hardware sessions (an explicit
    # YT_TRACE env wins either way; -no-trace opts out): the trace is
    # the post-mortem record of a scarce relay window
    if trace:
        os.environ.setdefault("YT_TRACE", "1")

    from yask_tpu import yk_factory
    fac = yk_factory()
    env = fac.new_env()
    plat = env.get_platform()
    log("env", platform=plat, devices=env.get_num_ranks())
    if plat != "tpu" and os.environ.get("YT_TPU_SESSION_FORCE") != "1":
        log("env", error="not on TPU — this protocol needs real hardware "
            "(YT_TPU_SESSION_FORCE=1 dry-runs the logic in interpret "
            "mode)")
        return 1
    should_bank = (plat == "tpu"
                   or os.environ.get("YT_SESSION_BANK") == "1")

    journal = SessionJournal(journal_path)
    # growth bound: month-long watch loops append every probe window;
    # past YT_JOURNAL_MAX_BYTES (8 MiB default) compact at session open
    dropped = journal.compact_if_large()
    if dropped:
        log("journal", compacted_rows=dropped)
    # the breaker is PERSISTENT: a tpu_watch.sh restart must not reset
    # an open breaker (the relay is still dead); watch_loop resets it
    # on fresh successful-probe evidence
    from yask_tpu.resilience import default_breaker_path
    runner = SessionRunner(
        journal, resume,
        Breaker(threshold=3, path=default_breaker_path()))
    journal.record("session", "", "started", quick=quick,
                   resume=resume, g=g_bench, stages=stages)

    def record(line, roofline=None, sanity=None):
        return bank_row(plat, env, line, roofline=roofline,
                        sanity=sanity)

    def run_span(ctx, first, last, tag):
        """Checkpointed span (forward time): with YT_CKPT_DIR set the
        supervision cadence snapshots every step into a per-case
        subdirectory, and under ``--resume`` a mid-case checkpoint
        restores and only the REMAINING steps run — a dropped relay
        costs the un-checkpointed tail, not the whole case."""
        base = os.environ.get("YT_CKPT_DIR", "")
        if not base:
            ctx.run_solution(first, last)
            return
        o = ctx.get_settings()
        o.ckpt_dir = os.path.join(base, tag.replace("/", "_"))
        if o.ckpt_every <= 0:
            o.ckpt_every = 1   # session cases are short; per-step
        if resume:
            from yask_tpu.resilience import restore_checkpoint
            path = os.path.join(o.ckpt_dir,
                                f"{ctx.get_name()}.ckpt.npz")
            if guarded_call(restore_checkpoint, ctx, path,
                            site="ckpt.restore"):
                done = ctx._cur_step   # next step the run continues at
                log("ckpt", case=tag, resumed_at=int(done))
                if done > last:
                    return
                first = max(first, done)
        ctx.run_solution(first, last)

    # 1) smoke
    def smoke():
        ctx = build(fac, env, "iso3dfd", "jit", 128, 2)
        run_span(ctx, 0, 4, "smoke")
        log("smoke", ok=True)

    def run_matrix():
        # on-device pallas validation matrix
        failures = []
        cases = matrix_cases()
        if quick and not os.environ.get("YT_SESSION_MATRIX"):
            cases = cases[:4]

        def one_case(name, radius):
            def body():
                ref = build(fac, env, name, "jit", 32, radius)
                run_span(ref, 0, 3, f"validate.{name}.ref")
                # oracle-sanity: an all-zero reference makes every
                # comparison vacuous (zero stays zero under the linear
                # homogeneous stencils) — the round-3 all-zero matrix
                # "matched" exactly this way
                overdict = check_output(
                    maybe_corrupt("session.validate.oracle",
                                  interior_slice(ref)))
                case_bad = 0
                anom = list(overdict["anomalies"])
                for wf in (1, 2):
                    p = build(fac, env, name, "pallas", 32, radius,
                              wf=wf)
                    run_span(p, 0, 3, f"validate.{name}.K{wf}")
                    verdict = check_output(
                        maybe_corrupt("session.validate.result",
                                      interior_slice(p)))
                    bad = p.compare_data(ref, epsilon=1e-3,
                                         abs_epsilon=1e-4)
                    log("validate", stencil=name, K=wf,
                        mismatches=int(bad),
                        **({"anomalies": verdict["anomalies"]}
                           if not verdict["ok"] else {}))
                    anom += verdict["anomalies"]
                    if bad:
                        case_bad += int(bad)
                        failures.append((name, wf, int(bad)))
                if anom:
                    failures.append((name, "anomaly",
                                     ",".join(sorted(set(anom)))))
                    return {"outcome": "anomaly",
                            "anomalies": sorted(set(anom))}
                return {"mismatches": case_bad}
            return body

        radii = dict(cases)
        for name in runner.pending("validate", [n for n, _ in cases]):
            out = runner.run_case("validate", name,
                                  one_case(name, radii[name]))
            if out is None and runner.last_status == "fault":
                failures.append((name, "fault", ""))
        if failures:
            log("validate", summary="FAILURES", detail=failures)
        else:
            log("validate", summary="all pallas cases match jit on "
                "device")

    def chunk_ab_stages() -> None:
        """Stage 3 (chunk A/Bs), setup included.  Crash-isolated from
        stages 4-5: the tune/bench build their own context, so a
        failure planning the flagship chunk must not cost the
        session's headline hardware number (round-3 failure mode)."""
        ab_cases = ["pipeline_ab", "skew_ab.K2", "skew_ab.K4",
                    "vmem_ladder", "esk_ab", "trapezoid_ab", "bf16_ab",
                    "overlap_ab"]
        if not runner.pending("chunk_abs", ab_cases):
            log("chunk_abs", skipped="all cases journaled complete")
            return
        # 3) pipeline + skew A/Bs (timing on real DMA engines).  Each stage
        #    is isolated: a Mosaic failure in one A/B must not cost the rest
        #    of the session (the relay window may be short).
        from yask_tpu.ops.pallas_stencil import build_pallas_chunk
        from yask_tpu.utils.idx_tuple import IdxTuple
        from yask_tpu.compiler.solution_base import create_solution
        import jax
        gi = min(g_bench, 256)
        prog = create_solution("iso3dfd", radius=8).get_soln().compile().plan(
            IdxTuple(x=gi, y=gi, z=gi),
            extra_pad={"x": (32, 32), "y": (32, 32), "z": (0, 0)})

        # Seed INTERIORS (pads must stay zero — the ghost-zero invariant):
        # a zero state would make every A/B cross-check vacuous, since
        # iso3dfd is linear homogeneous and zero stays zero.
        def seeded_init(prog_=None):
            prog_ = prog_ or prog
            rng = np.random.RandomState(7)
            init = {}
            for name, g in prog_.geoms.items():
                if g.is_scratch:
                    continue
                a = np.zeros(tuple(g.shape), np.float32)
                idx = tuple(
                    slice(g.origin[dn], g.origin[dn] + prog_.sizes[dn])
                    if kind == "domain" else slice(None)
                    for dn, kind in g.axes)
                shape = a[idx].shape
                if name == "vel":
                    a[idx] = 0.0005 + rng.rand(*shape).astype(np.float32) \
                        * 0.0005
                else:
                    a[idx] = (rng.rand(*shape).astype(np.float32) - 0.5) * 0.1
                init[name] = np.asarray(a, dtype=prog_.dtype)
            return init

        state = prog.alloc_state(init=seeded_init())
        interp = plat != "tpu"   # only under YT_TPU_SESSION_FORCE
        from yask_tpu.ops.pallas_stencil import default_vmem_budget
        budget = default_vmem_budget(plat)
        case_anomalies = []   # verdicts since the current case began

        def time_chunk(tag, prog_=None, state_=None, metric=None,
                       npts=None, **kw):
            """Time one chunk variant; returns its one-chunk output state
            (or None on failure/anomaly) so A/B stages can cross-validate.
            The default (prog, state) pair is the fp32 flagship; the bf16
            stage passes its own so the timing/recording protocol stays
            single-definition.  Outputs pass the sanity guards: an
            all-zero/NaN chunk result banks a QUARANTINED row and is
            withheld from the bit-equality cross-checks (two corrupt arms
            matching proves nothing)."""
            prog_ = prog_ or prog
            state_ = state_ if state_ is not None else state
            vb = kw.pop("vmem_budget", budget)
            time_chunk.gpts = None   # last successful rate, for ratio rows
            try:
                chunk, tb = build_pallas_chunk(prog_, interpret=interp,
                                               vmem_budget=vb, **kw)
                from yask_tpu.cache import aot_compile
                fn = chunk if interp else \
                    aot_compile(chunk, (state_, 0), platform=plat).fn
                st1 = fn(state_, 0)
                jax.block_until_ready(st1)
                st = st1
                t0 = time.perf_counter()
                for _ in range(5):
                    st = fn(st, 0)
                jax.block_until_ready(st)
                dt = (time.perf_counter() - t0) / 5
                k = kw.get("fuse_steps", 1)
                gpts = round((npts or gi ** 3) * k / dt / 1e9, 2)
                st1 = maybe_corrupt("session.chunk_result", st1)
                sanity = check_output(st1)
                log(tag, **{k2: v for k2, v in kw.items()},
                    tile_mib=round(tb / 2**20, 2),
                    secs_per_chunk=round(dt, 5), gpts=gpts,
                    **({"anomalies": sanity["anomalies"]}
                       if not sanity["ok"] else {}))
                if should_bank:
                    record({
                        "metric": metric or (f"iso3dfd r=8 {gi}^3 fp32 tpu "
                                             f"pallas chunk ({tag} {kw})"),
                        "value": gpts, "unit": "GPts/s", "platform": plat,
                        "vs_baseline": round(gpts / 500.0, 4)},
                        sanity=sanity)
                if not sanity["ok"]:
                    case_anomalies.extend(sanity["anomalies"])
                    return None
                time_chunk.gpts = gpts
                return st1
            except Exception as e:  # noqa: BLE001
                log(tag, error=str(e)[:300], **kw)
                return None

        def max_abs_diff(a, b):
            m = 0.0
            for n in a:
                for x, y in zip(a[n], b[n]):
                    m = max(m, float(jax.numpy.max(jax.numpy.abs(x - y))))
            return m

        def case_outcome():
            """Terminal-outcome dict for run_case from the verdicts the
            case's time_chunk calls accumulated."""
            if case_anomalies:
                out = {"outcome": "anomaly",
                       "anomalies": sorted(set(case_anomalies))}
                case_anomalies.clear()
                return out
            return {}

        def pipeline_case():
            unpiped = time_chunk("pipeline_ab", fuse_steps=2,
                                 pipeline_dmas=False, skew=False)
            piped = time_chunk("pipeline_ab", fuse_steps=2,
                               pipeline_dmas=True, skew=False)
            if unpiped is not None and piped is not None:
                # bit-equality promised by the protocol: double-buffering
                # must not change values (the aliasing hazard CLAUDE.md
                # documents)
                log("pipeline_ab", fuse_steps=2,
                    max_abs_diff=float(max_abs_diff(unpiped, piped)))
            return case_outcome()

        def skew_case(k):
            # skew A/B: uniform shrink vs streaming skewed wavefront,
            # growing K; the two tilings must agree numerically on real
            # Mosaic (first hardware execution of the carry machinery)
            def body():
                uni = time_chunk("skew_ab", fuse_steps=k, skew=False)
                skw = time_chunk("skew_ab", fuse_steps=k, skew=True)
                if uni is not None and skw is not None:
                    log("skew_ab", fuse_steps=k,
                        max_abs_diff=float(max_abs_diff(uni, skw)))
                # 1-D vs 2-D: force BOTH lead dims (the multi-dim carry's
                # first hardware execution) and bit-compare against the
                # 1-D arm — the second dim's row carry + diagonal corner
                # propagation must agree exactly on real Mosaic
                sk2 = time_chunk("skew2d_ab", fuse_steps=k,
                                 metric=(f"iso3dfd r=8 {gi}^3 fp32 tpu "
                                         f"pallas chunk (skew2d K{k})"),
                                 skew=["x", "y"])
                if skw is not None and sk2 is not None:
                    log("skew2d_ab", fuse_steps=k,
                        max_abs_diff=float(max_abs_diff(skw, sk2)))
                return case_outcome()
            return body

        def vmem_ladder_case():
            # 3a3) vmem-budget ladder, measured directly: the joint
            #      tuner's outer axis (64 MiB pins 8×32 blocks at the
            #      512^3 flagship; 96 MiB admits 16×32 — the r5 open
            #      item).  Each rung is its own ledger row so the sweep
            #      is comparable across sessions.
            for mb in (64, 96, 120):
                time_chunk("vmem_ladder", fuse_steps=2,
                           metric=(f"iso3dfd r=8 {gi}^3 fp32 tpu pallas "
                                   f"chunk (vmem {mb} MiB)"),
                           vmem_budget=mb * 2 ** 20)
            return case_outcome()

        def esk_case():
            # 3a2) misaligned-radius skew (E_sk window widening,
            #      r % sublane != 0): the sublane-rounded write windows +
            #      widened regions have only ever run in interpret mode —
            #      force skew on a cube r=1 K=4 chunk and bit-compare
            #      against uniform.
            gq = min(gi, 128)
            progc = create_solution("cube", radius=1).get_soln().compile() \
                .plan(IdxTuple(x=gq, y=gq, z=gq),
                      extra_pad={"x": (32, 32), "y": (32, 32), "z": (0, 0)})
            statec = progc.alloc_state(init=seeded_init(progc))
            uni_c = time_chunk(
                "esk_ab", prog_=progc, state_=statec, npts=gq ** 3,
                metric=f"cube r=1 {gq}^3 tpu pallas chunk (esk_ab uniform)",
                fuse_steps=4, skew=False)
            skw_c = time_chunk(
                "esk_ab", prog_=progc, state_=statec, npts=gq ** 3,
                metric=f"cube r=1 {gq}^3 tpu pallas chunk (esk_ab skew)",
                fuse_steps=4, skew=True)
            if uni_c is not None and skw_c is not None:
                log("esk_ab", fuse_steps=4,
                    max_abs_diff=float(max_abs_diff(uni_c, skw_c)))
            return case_outcome()

        def trapezoid_case():
            # 3a4) trapezoid/diamond two-phase A/B: first hardware
            #      execution of the parallel-grid claim (both phases run
            #      with every grid dim "parallel" — the megacore
            #      partitioning the cost model credits).  The forced
            #      trapezoid arm must be BIT-equal to the uniform arm
            #      (same contract as the bench_suite gate: a tiling
            #      variant reorders the sweep, never the per-cell
            #      arithmetic); the speedup row feeds the TPU-scoped
            #      trap-speedup sentinel floor.  r=2 K=4 is the gate's
            #      engagement regime (small radius, deep fusion).
            from yask_tpu.ops.pallas_stencil import trapezoid_pad_need
            gq = min(gi, 128)
            pad = trapezoid_pad_need(np.float32, 2, 4)
            progt = create_solution("iso3dfd", radius=2).get_soln() \
                .compile().plan(
                    IdxTuple(x=gq, y=gq, z=gq),
                    extra_pad={"x": (pad, pad), "y": (pad, pad),
                               "z": (0, 0)})
            statet = progt.alloc_state(init=seeded_init(progt))
            uni_t = time_chunk(
                "trapezoid_ab", prog_=progt, state_=statet, npts=gq ** 3,
                metric=(f"iso3dfd r=2 {gq}^3 fp32 tpu pallas chunk "
                        f"(trapezoid_ab uniform)"),
                fuse_steps=4, skew=False)
            g_off = time_chunk.gpts
            trp = time_chunk(
                "trapezoid_ab", prog_=progt, state_=statet, npts=gq ** 3,
                metric=(f"iso3dfd r=2 {gq}^3 fp32 tpu pallas chunk "
                        f"(trapezoid_ab trap)"),
                fuse_steps=4, trapezoid=True)
            g_on = time_chunk.gpts
            if uni_t is not None and trp is not None:
                mad = float(max_abs_diff(uni_t, trp))
                log("trapezoid_ab", fuse_steps=4, max_abs_diff=mad)
                if should_bank and g_off and g_on:
                    record({"metric": (f"iso3dfd r=2 {gq}^3 {plat} "
                                       f"trap-speedup"),
                            "value": round(g_on / g_off, 4), "unit": "x",
                            "platform": plat, "uniform_gpts": g_off,
                            "trap_gpts": g_on, "max_abs_diff": mad})
                if mad != 0.0:
                    case_anomalies.append(f"trapezoid-mismatch:{mad}")
            return case_outcome()

        def bf16_case():
            # 3b) bf16 A/B: the half-traffic roofline lever.  The CPU
            #     proxy inverts (bf16 is software-emulated off-TPU) so
            #     only this hardware row can confirm the >=1.5x target;
            #     sublane-16 geometry is exercised by the same chunk
            #     builder, and the timing/recording protocol is
            #     time_chunk's single definition.
            sb16 = create_solution("iso3dfd", radius=8)
            sb16.get_soln().set_element_bytes(2)
            prog16 = sb16.get_soln().compile().plan(
                IdxTuple(x=gi, y=gi, z=gi),
                extra_pad={"x": (32, 32), "y": (32, 32), "z": (0, 0)})
            state16 = prog16.alloc_state(init=seeded_init(prog16))
            time_chunk("bf16_ab", prog_=prog16, state_=state16,
                       metric=f"iso3dfd r=8 {gi}^3 bf16 tpu pallas chunk K2",
                       fuse_steps=2)
            return case_outcome()

        def overlap_ab_case():
            # 3c) overlapped halo exchange A/B: first hardware execution
            #     of the shard_pallas core/shell split.  The serial and
            #     overlapped arms must be bit-identical (corrupt arms
            #     are withheld from the comparison — two corrupt arms
            #     matching proves nothing); the speedup row feeds the
            #     TPU-scoped sp-overlap-speedup sentinel floor, and
            #     each arm's measured overlap efficiency is banked so
            #     hardware finally answers how much collective cost
            #     the split hides.
            ndev = env.get_num_ranks()
            if ndev <= 1:
                log("overlap_ab", skipped="single device")
                return {"outcome": "skip", "reason": "single device"}
            from yask_tpu.runtime.init_utils import init_solution_vars
            from yask_tpu.utils.exceptions import YaskException
            go = min(g_bench, 256)
            steps = 8

            def mk(ovx):
                c = fac.new_solution(env, stencil="iso3dfd", radius=8)
                c.apply_command_line_options(
                    f"-g {go} -wf_steps 2 -mode shard_pallas "
                    f"-measure_halo -overlap_x {ovx} -nr_x {ndev}")
                c.prepare_solution()
                init_solution_vars(c)
                return c

            def run_arm(ovx):
                try:
                    c = mk(ovx)
                    c.run_solution(0, 3)       # warmup (compiles; a
                    #   forced-on split that cannot engage raises HERE,
                    #   at the first chunk build)
                except YaskException as e:
                    return None, None, str(e)[:200]
                t0 = time.perf_counter()
                c.run_solution(4, 4 + steps - 1)
                dt = time.perf_counter() - t0
                gpts = round(go ** 3 * steps / dt / 1e9, 3)
                sanity = check_output(
                    maybe_corrupt("session.overlap.result",
                                  interior_slice(c)))
                eff = round(c.get_stats().get_halo_overlap_eff(), 4)
                log("overlap_ab", arm=ovx, gpts=gpts, overlap_eff=eff,
                    **({"anomalies": sanity["anomalies"]}
                       if not sanity["ok"] else {}))
                if should_bank:
                    record({"metric": (f"iso3dfd r=8 {go}^3 {plat} "
                                       f"x{ndev} shard_pallas "
                                       f"(overlap {ovx})"),
                            "value": gpts, "unit": "GPts/s",
                            "platform": plat, "overlap_eff": eff},
                           sanity=sanity)
                if not sanity["ok"]:
                    case_anomalies.extend(sanity["anomalies"])
                    return None, gpts, None
                return c, gpts, None

            c_off, g_off, err = run_arm("off")
            if err:
                log("overlap_ab", error=err)
                return {"outcome": "skip", "reason": err}
            c_on, g_on, err = run_arm("on")
            if err:
                # forced "on" raised: the geometry cannot split (e.g.
                # rank domains < 2·hK at this device count) — a
                # journaled skip, not a failure
                log("overlap_ab", skipped=f"overlap infeasible: {err}")
                return {"outcome": "skip", "reason": err}
            if c_off is not None and c_on is not None:
                bad = int(c_on.compare_data(c_off, epsilon=0.0,
                                            abs_epsilon=0.0))
                log("overlap_ab", mismatches=bad)
                if should_bank and g_off and g_on:
                    record({"metric": (f"iso3dfd r=8 {go}^3 {plat} "
                                       f"x{ndev} sp-overlap-speedup"),
                            "value": round(g_on / g_off, 4),
                            "unit": "x", "platform": plat,
                            "serial_gpts": g_off, "overlap_gpts": g_on,
                            "mismatches": bad})
                if bad:
                    case_anomalies.append(f"overlap-mismatch:{bad}")
            return case_outcome()

        def comm_ab_case():
            # 3d) message-coalescing A/B: first hardware execution of
            #     the packed per-(axis,direction) ppermute schedule.
            #     ppermute only moves bytes, so the arms must be
            #     bit-identical (corrupt arms withheld — two corrupt
            #     arms matching proves nothing); each arm banks its
            #     measured collectives-per-round (traced, not modeled)
            #     so the round reduction is a hardware datum.
            ndev = env.get_num_ranks()
            if ndev <= 1:
                log("comm_ab", skipped="single device")
                return {"outcome": "skip", "reason": "single device"}
            from yask_tpu.parallel.comm_plan import comm_ledger_fields
            from yask_tpu.runtime.init_utils import init_solution_vars
            from yask_tpu.utils.exceptions import YaskException
            go = min(g_bench, 256)
            steps = 8
            ranks = ("-nr_x 2 -nr_y 2" if ndev >= 4 and ndev % 4 == 0
                     else f"-nr_x {ndev}")

            def mk(coal):
                c = fac.new_solution(env, stencil="iso3dfd", radius=8)
                c.apply_command_line_options(
                    f"-g {go} -mode shard_map -measure_halo "
                    f"-coalesce {coal} {ranks}")
                c.prepare_solution()
                init_solution_vars(c)
                return c

            def run_arm(coal):
                try:
                    c = mk(coal)
                    c.run_solution(0, 3)        # warmup + compile
                except YaskException as e:
                    return None, None, str(e)[:200]
                t0 = time.perf_counter()
                c.run_solution(4, 4 + steps - 1)
                dt = time.perf_counter() - t0
                gpts = round(go ** 3 * steps / dt / 1e9, 3)
                sanity = check_output(
                    maybe_corrupt("session.comm.result",
                                  interior_slice(c)))
                comm = comm_ledger_fields(c)
                log("comm_ab", arm=coal, gpts=gpts,
                    rounds=comm.get("comm_rounds_measured"),
                    **({"anomalies": sanity["anomalies"]}
                       if not sanity["ok"] else {}))
                if should_bank:
                    record({"metric": (f"iso3dfd r=8 {go}^3 {plat} "
                                       f"shard_map (coalesce {coal})"),
                            "value": gpts, "unit": "GPts/s",
                            "platform": plat, **comm},
                           sanity=sanity)
                if not sanity["ok"]:
                    case_anomalies.extend(sanity["anomalies"])
                    return None, gpts, None
                return c, gpts, None

            c_off, g_off, err = run_arm("off")
            if err:
                log("comm_ab", error=err)
                return {"outcome": "skip", "reason": err}
            c_on, g_on, err = run_arm("on")
            if err:
                log("comm_ab", error=err)
                return {"outcome": "skip", "reason": err}
            if c_off is not None and c_on is not None:
                bad = int(c_on.compare_data(c_off, epsilon=0.0,
                                            abs_epsilon=0.0))
                rounds_on = comm_ledger_fields(c_on).get(
                    "comm_rounds_measured")
                rounds_off = comm_ledger_fields(c_off).get(
                    "comm_rounds_measured")
                log("comm_ab", mismatches=bad, rounds_on=rounds_on,
                    rounds_off=rounds_off)
                if should_bank and g_off and g_on:
                    record({"metric": (f"iso3dfd r=8 {go}^3 {plat} "
                                       "sm-coalesce-speedup"),
                            "value": round(g_on / g_off, 4),
                            "unit": "x", "platform": plat,
                            "serial_gpts": g_off,
                            "coalesced_gpts": g_on,
                            "rounds_on": rounds_on,
                            "rounds_off": rounds_off,
                            "mismatches": bad})
                if bad:
                    case_anomalies.append(f"comm-mismatch:{bad}")
            return case_outcome()

        runner.run_case("chunk_abs", "pipeline_ab", pipeline_case)
        for k in (2, 4):
            runner.run_case("chunk_abs", f"skew_ab.K{k}", skew_case(k))
        runner.run_case("chunk_abs", "vmem_ladder", vmem_ladder_case)
        runner.run_case("chunk_abs", "esk_ab", esk_case)
        runner.run_case("chunk_abs", "trapezoid_ab", trapezoid_case)
        runner.run_case("chunk_abs", "bf16_ab", bf16_case)
        runner.run_case("chunk_abs", "overlap_ab", overlap_ab_case)
        runner.run_case("chunk_abs", "comm_ab", comm_ab_case)

    def tune_bench_stages():
        """Stages 4-5 (joint tune + tuned bench): independent context,
        crash-isolated from the chunk A/Bs.  One journaled unit — a
        resumed bench without its tune would measure the untuned
        config."""
        # 4) joint auto-tune at the bench size.  tune_max_wf_steps stays
        #    small: pads are planned for radius × the cap, so 16 would
        #    inflate every state array (784^3 for 512^3 at r=8) and make
        #    each candidate compile minutes long.
        from yask_tpu.runtime.auto_tuner import AutoTuner
        ctx = build(fac, env, "iso3dfd", "pallas", g_bench, 8, wf=2,
                    tune=True, tune_max=4)
        ctx.get_settings().auto_tune_trial_secs = 0.5
        try:
            tuner = AutoTuner(ctx)
            best_k = tuner.run_auto_tuner_now()
            s = ctx.get_settings()
            log("tune", wf_steps=best_k,
                blocks={d: s.block_sizes[d] for d in ("x", "y")},
                vmem_mb=s.vmem_budget_mb,   # ladder-chosen rung (0=auto)
                candidates=len(tuner.results))
        except Exception as e:  # noqa: BLE001
            log("tune", error=str(e)[:300])

        # 5) tuned bench
        steps = 4 if quick else 20
        ctx.run_solution(0, steps - 1)   # warm
        ctx.clear_stats()
        ctx.run_solution(steps, 2 * steps - 1)
        st = ctx.get_stats()
        rate = st.get_pts_per_sec() / 1e9
        sanity = check_output(
            maybe_corrupt("session.bench_result", interior_slice(ctx)))
        # roofline fraction via the shared perflab model (the
        # MFU-style number the performance doc's table wants per
        # VERDICT r4 item 1) — one definition across the harness,
        # bench, suite, and this session
        from yask_tpu.perflab.roofline import ctx_roofline
        roof = ctx_roofline(ctx, env, rate)
        line = dict(
            metric=f"iso3dfd r=8 {g_bench}^3 fp32 tpu pallas-tuned",
            value=round(rate, 3), unit="GPts/s", platform=plat,
            hbm_bytes_pp=roof["hbm_bytes_pp"],
            roofline_frac=roof["roofline_frac"] or 0.0,
            vs_baseline=round(rate / 500.0, 4))
        log("bench", **line,
            **({"anomalies": sanity["anomalies"]}
               if not sanity["ok"] else {}))
        if should_bank:
            record(line, roofline=roof, sanity=sanity)
        if not sanity["ok"]:
            return {"outcome": "anomaly",
                    "anomalies": sanity["anomalies"]}
        return {}

    def compile_cache_case():
        """Cold-vs-warm AOT compile through the persistent cache on the
        real backend: build+run the flagship jit config twice with the
        in-memory memo cleared in between, so the second build can ONLY
        come from a deserialized disk entry.  The warm rebuild must
        show ZERO lowerings on the cache's trace counter — the
        serialized-executable round-trip has never run against real
        Mosaic output, only CPU executables."""
        import tempfile
        from yask_tpu import cache as ccache
        saved = os.environ.get("YT_COMPILE_CACHE")
        cdir = saved or os.path.join(tempfile.gettempdir(),
                                     "yt_session_compile_cache")
        os.environ["YT_COMPILE_CACHE"] = cdir
        try:
            ccache.clear_memo()
            s0 = ccache.stats()
            c1 = build(fac, env, "iso3dfd", "jit", 64, 8, wf=2)
            c1.run_solution(0, 1)
            s1 = ccache.stats()
            cold_ms = round(c1._compile_secs * 1000.0, 1)
            cold_hit = c1._last_cache_hit
            del c1
            # memo off: the warm build must round-trip through DISK
            ccache.clear_memo()
            c2 = build(fac, env, "iso3dfd", "jit", 64, 8, wf=2)
            c2.run_solution(0, 1)
            s2 = ccache.stats()
            warm_ms = round(c2._compile_secs * 1000.0, 1)
            warm_lowerings = s2["lowerings"] - s1["lowerings"]
            sanity = check_output(
                maybe_corrupt("session.cache_result",
                              interior_slice(c2)))
            line = {"metric": f"iso3dfd r=8 64^3 {plat} "
                              "compile-cache-warm-ms",
                    "value": warm_ms, "unit": "ms", "platform": plat,
                    "cold_ms": cold_ms, "cold_hit": cold_hit or "cold",
                    "warm_hit": c2._last_cache_hit,
                    "warm_lowerings": warm_lowerings,
                    "disk_hits": s2["disk_hits"] - s1["disk_hits"],
                    "stores": s1["stores"] - s0["stores"],
                    "load_failures": (s2["load_failures"]
                                      - s0["load_failures"])}
            log("compile_cache_ab", **line,
                **({"anomalies": sanity["anomalies"]}
                   if not sanity["ok"] else {}))
            if should_bank:
                record(line, sanity=sanity)
            if not sanity["ok"]:
                return {"outcome": "anomaly",
                        "anomalies": sanity["anomalies"]}
            if warm_lowerings:
                return {"outcome": "anomaly",
                        "anomalies": [f"warm-lowerings:"
                                      f"{warm_lowerings}"]}
            return {}
        finally:
            if saved is None:
                os.environ.pop("YT_COMPILE_CACHE", None)
            else:
                os.environ["YT_COMPILE_CACHE"] = saved

    def ensemble_case():
        """Batched-vs-sequential ensemble on the real backend: the
        CPU-proxy win is compile amortization; on hardware the
        chip-saturation leg (one fused program over N small domains)
        is measured for the first time.  Per-member bit-identity is
        the gate; a corrupt arm (sanity guards) is withheld from the
        comparison and banks quarantined."""
        from yask_tpu import cache as ccache
        from yask_tpu.runtime.init_utils import init_solution_vars
        N = 4
        ge = 128 if plat == "tpu" else 32
        steps_e = 4

        def seed(c, i):
            rng = np.random.RandomState(500 + i)
            arr = (rng.rand(ge, ge, ge).astype(np.float32) - 0.5) * 0.1
            c.get_var("pressure").set_elements_in_slice(
                arr, [0, 0, 0, 0], [0, ge - 1, ge - 1, ge - 1])

        # disk cache off for the A/B: a warm entry from the
        # compile_cache_ab stage would hand the sequential arm its
        # compiles for free and invert the ratio's meaning
        saved = os.environ.pop("YT_COMPILE_CACHE", None)
        try:
            ctxs = []
            for i in range(N):
                c = build(fac, env, "iso3dfd", "jit", ge, 8, wf=2)
                seed(c, i)
                ctxs.append(c)
            t0s = time.perf_counter()
            for c in ctxs:
                ccache.clear_memo()   # identical keys: no memo sharing
                c.run_solution(0, steps_e - 1)
            t_seq = time.perf_counter() - t0s
            finals = [{n: [np.asarray(a) for a in ring]
                       for n, ring in c._state.items()} for c in ctxs]
            del ctxs

            c = build(fac, env, "iso3dfd", "jit", ge, 8, wf=2)
            ens = c.new_ensemble(N)
            for i in range(N):
                with ens.member(i) as m:
                    if i:
                        init_solution_vars(m)
                    seed(m, i)
            ccache.clear_memo()
            t0b = time.perf_counter()
            ens.run(0, steps_e - 1)
            t_bat = time.perf_counter() - t0b
        finally:
            if saved is not None:
                os.environ["YT_COMPILE_CACHE"] = saved

        with ens.member(0):
            sanity = check_output(
                maybe_corrupt("session.ensemble_result",
                              interior_slice(c)))
        mismatches = 0
        if sanity["ok"]:   # corrupt batched arm: comparison withheld
            for i in range(N):
                with ens.member(i) as m:
                    for n, ring in finals[i].items():
                        for s, a in enumerate(ring):
                            if not np.array_equal(
                                    a, np.asarray(m._state[n][s])):
                                mismatches += 1
        line = {"metric": f"iso3dfd r=8 {ge}^3 {plat} "
                          f"ensemble{N}-speedup",
                "value": round(t_seq / max(t_bat, 1e-12), 4),
                "unit": "x", "platform": plat, "ensemble": N,
                "seq_secs": round(t_seq, 3),
                "batched_secs": round(t_bat, 3),
                "compile_ms": round(c._compile_secs * 1000.0, 1),
                "cache_hit": c._last_cache_hit or "cold",
                "batched_reason": ens.batched_reason,
                "mismatches": mismatches}
        log("ensemble_ab", **line,
            **({"anomalies": sanity["anomalies"]}
               if not sanity["ok"] else {}))
        if should_bank:
            record(line, sanity=sanity)
        if not sanity["ok"]:
            return {"outcome": "anomaly",
                    "anomalies": sanity["anomalies"]}
        if mismatches:
            return {"outcome": "anomaly",
                    "anomalies": [f"ensemble-mismatch:{mismatches}"]}
        return {}

    def pipeline_fusion_case():
        """Cross-solution pipeline fusion on the real backend: the
        3-stage RTM chain as ONE merged pallas program vs the
        host-chained oracle.  The bit-equality gate runs BOTH arms on
        matched temporal schedules (stepwise — the repo's K>1 chunked
        schedule is only tolerance-equal to stepwise runs, a
        pre-existing FMA-reassociation property of temporal chunking,
        not a fusion defect); the perf ratio then times the fused arm
        at K=2 chunks against the per-step chained schedule — the
        composed cross-solution + temporal fusion win this PR ships.
        A corrupt arm (sanity guards) is withheld from the comparison
        and banks quarantined."""
        from yask_tpu.ops.pipeline import (SolutionPipeline, rtm_chain,
                                           pipeline_hbm_model)
        gp = 128 if plat == "tpu" else 32
        steps_p = 4

        def mk(fuse, wf):
            stages_, bindings = rtm_chain(radius=2)
            pipe = SolutionPipeline(env, stages_, bindings)
            pipe.apply_command_line_options(
                f"-g {gp} -mode pallas -wf_steps {wf}")
            pipe.prepare(fuse=fuse)
            v = pipe.get_var("fwd", "pressure")
            rng = np.random.RandomState(11)
            arr = (rng.rand(gp, gp, gp).astype(np.float32) - 0.5) * 0.1
            for t in range(v.get_first_valid_step_index(),
                           v.get_last_valid_step_index() + 1):
                v.set_elements_in_slice(arr, [t, 0, 0, 0],
                                        [t, gp - 1, gp - 1, gp - 1])
            return pipe

        # bit-equality gate on matched schedules: fused stepwise vs
        # the (intrinsically stepwise) chained oracle
        fused1, chained = mk(True, 1), mk(False, 1)
        for t in range(steps_p):
            fused1.run(t, t)
        chained.run(0, steps_p - 1)
        vlast = fused1.get_var("smooth", "smooth")
        sanity = check_output(
            maybe_corrupt("session.pipeline_result",
                          fused1._interior(
                              "smooth", "smooth",
                              vlast.get_last_valid_step_index())))
        mismatches = 0
        if sanity["ok"]:   # corrupt arm: comparison withheld
            mismatches = int(fused1.compare(chained))
        fused1.end()

        # perf arms: fused K=2 chunks vs the per-step chained schedule
        fused2 = mk(True, 2)
        fused2.run(0, steps_p - 1)      # warm (compile)
        t0f = time.perf_counter()
        fused2.run(steps_p, 2 * steps_p - 1)
        t_fused = time.perf_counter() - t0f
        t0c = time.perf_counter()
        chained.run(steps_p, 2 * steps_p - 1)
        t_chain = time.perf_counter() - t0c

        line = {"metric": f"rtm3 r=2 {gp}^3 {plat} "
                          "pipeline-fusion-speedup",
                "value": round(t_chain / max(t_fused, 1e-12), 4),
                "unit": "x", "platform": plat,
                "stages": len(fused2.stage_names),
                "fused": fused2.fused, "wf": 2,
                "chained_secs": round(t_chain, 3),
                "fused_secs": round(t_fused, 3),
                "hbm_bytes_model": pipeline_hbm_model(fused2),
                "mismatches": mismatches}
        log("pipeline_fusion_ab", **line,
            **({"anomalies": sanity["anomalies"]}
               if not sanity["ok"] else {}))
        if should_bank:
            record(line, sanity=sanity)
        fused2.end()
        chained.end()
        if not sanity["ok"]:
            return {"outcome": "anomaly",
                    "anomalies": sanity["anomalies"]}
        if mismatches:
            return {"outcome": "anomaly",
                    "anomalies": [f"pipeline-mismatch:{mismatches}"]}
        return {}

    def push_ab_case():
        """Push-memory tile-graph fusion on the real backend: the PURE
        rtm chain (img has no self-read, so the merged image var's VMEM
        tile is consumed in-grid-step and leaves BOTH HBM paths) with
        push ON vs the same fused program with push OFF.  Bit gate:
        both fused arms stepwise (K=1, exact on Mosaic) vs the
        host-chained oracle; perf ratio then times push vs source-fused
        at K=2 chunks — the HBM-traffic halving this stage exists to
        measure on hardware (the CPU proxy realizes only part of it).
        A corrupt arm is withheld from the comparison and banks
        quarantined."""
        from yask_tpu.ops.pipeline import (SolutionPipeline, rtm_chain,
                                           pipeline_hbm_model)
        gp = 128 if plat == "tpu" else 32
        steps_p = 4

        def mk(fuse, wf, push_cli):
            stages_, bindings = rtm_chain(radius=2, accumulate=False)
            pipe = SolutionPipeline(env, stages_, bindings)
            pipe.apply_command_line_options(
                f"-g {gp} -mode pallas -wf_steps {wf} {push_cli}")
            pipe.prepare(fuse=fuse)
            v = pipe.get_var("fwd", "pressure")
            rng = np.random.RandomState(11)
            arr = (rng.rand(gp, gp, gp).astype(np.float32) - 0.5) * 0.1
            for t in range(v.get_first_valid_step_index(),
                           v.get_last_valid_step_index() + 1):
                v.set_elements_in_slice(arr, [t, 0, 0, 0],
                                        [t, gp - 1, gp - 1, gp - 1])
            return pipe

        # bit-equality gate on matched stepwise schedules
        push1, chained = mk(True, 1, "-push on"), mk(False, 1, "-push off")
        pal = (push1.plan().get("pallas") or {})
        if not pal.get("push"):
            raise RuntimeError(
                f"push did not engage on the pure chain: "
                f"{push1.plan()['reasons']}")
        for t in range(steps_p):
            push1.run(t, t)
        chained.run(0, steps_p - 1)
        vlast = push1.get_var("smooth", "smooth")
        sanity = check_output(
            maybe_corrupt("session.push_result",
                          push1._interior(
                              "smooth", "smooth",
                              vlast.get_last_valid_step_index())))
        mismatches = 0
        if sanity["ok"]:   # corrupt arm: comparison withheld
            mismatches = int(push1.compare(chained))
        push1.end()
        chained.end()

        # perf arms: push vs source-fused, both K=2 chunks
        push2 = mk(True, 2, "-push on")
        nopush2 = mk(True, 2, "-push off")
        push2.run(0, steps_p - 1)       # warm (compile)
        nopush2.run(0, steps_p - 1)
        t0p = time.perf_counter()
        push2.run(steps_p, 2 * steps_p - 1)
        t_push = time.perf_counter() - t0p
        t0n = time.perf_counter()
        nopush2.run(steps_p, 2 * steps_p - 1)
        t_nopush = time.perf_counter() - t0n

        hbm = pipeline_hbm_model(push2,
                                 push_vars=push2.pushed_vars())
        line = {"metric": f"rtm3-pure r=2 {gp}^3 {plat} "
                          "pipeline-push-speedup",
                "value": round(t_nopush / max(t_push, 1e-12), 4),
                "unit": "x", "platform": plat,
                "push_vars": sorted(push2.pushed_vars()), "wf": 2,
                "push_secs": round(t_push, 3),
                "fused_secs": round(t_nopush, 3),
                "hbm_bytes_model": hbm,
                "mismatches": mismatches}
        log("push_ab", **line,
            **({"anomalies": sanity["anomalies"]}
               if not sanity["ok"] else {}))
        if should_bank:
            record(line, sanity=sanity)
        push2.end()
        nopush2.end()
        if not sanity["ok"]:
            return {"outcome": "anomaly",
                    "anomalies": sanity["anomalies"]}
        if mismatches:
            return {"outcome": "anomaly",
                    "anomalies": [f"push-mismatch:{mismatches}"]}
        return {}

    def serve_resident_case():
        """Device-resident bulk serving on the real backend: the same
        4-session x 4-item work list through ResidentExecutor.run_queue
        (one device-lock hold, one end-of-queue sync, one extraction
        per session) vs per-request scheduler dispatch.  The resident
        arm's outputs pass the sanity guards (its maybe_corrupt site is
        serve.resident, inside run_queue); a corrupt arm is withheld
        from the bit-equality gate and banks quarantined."""
        from yask_tpu.serve.registry import SessionRegistry
        from yask_tpu.serve.scheduler import BatchScheduler
        from yask_tpu.serve.resident import run_per_request
        gs = 64 if plat == "tpu" else 16
        occupancy, nsteps = 4, 4
        rng = np.random.RandomState(17)
        arr = (rng.rand(gs, gs, gs).astype(np.float32) - 0.5) * 0.1

        reg = SessionRegistry(fac, env)
        prof = reg.get_profile("iso3dfd", 2, str(gs), mode="jit", wf=1)
        sched = BatchScheduler(reg, window_secs=0.0)

        def open_sessions():
            sids = []
            for i in range(occupancy):
                s = reg.open_session(prof)
                sids.append(s.sid)
                with sched.session_ctx(s.sid) as c:
                    v = c.get_var("pressure")
                    for t in range(v.get_first_valid_step_index(),
                                   v.get_last_valid_step_index() + 1):
                        v.set_elements_in_slice(
                            arr * (i + 1), [t, 0, 0, 0],
                            [t, gs - 1, gs - 1, gs - 1])
            return sids

        def work(sids):
            return [(sid, t, t) for t in range(nsteps)
                    for sid in sids]

        warm = open_sessions()
        sched.run_resident(work(warm)[:1])     # compile outside timing
        for sid in warm:
            reg.close_session(sid)

        sids_r = open_sessions()
        t0r = time.perf_counter()
        res = sched.run_resident(work(sids_r))
        t_resident = time.perf_counter() - t0r

        sids_p = open_sessions()
        t0q = time.perf_counter()
        base = run_per_request(sched, work(sids_p))
        t_per_req = time.perf_counter() - t0q
        sched.shutdown()

        sanity = check_output(res[sids_r[0]]["outputs"]["pressure"])
        mismatches = 0
        if sanity["ok"]:   # corrupt resident arm: comparison withheld
            for sr, sp in zip(sids_r, sids_p):
                for name, a in res[sr]["outputs"].items():
                    if not np.array_equal(a, base[sp]["outputs"][name]):
                        mismatches += 1

        line = {"metric": f"iso3dfd r=2 {gs}^3 {plat} "
                          "serve-resident-speedup",
                "value": round(t_per_req / max(t_resident, 1e-12), 4),
                "unit": "x", "platform": plat,
                "occupancy": occupancy, "items": occupancy * nsteps,
                "resident_secs": round(t_resident, 4),
                "per_request_secs": round(t_per_req, 4),
                "mismatches": mismatches}
        log("serve_resident_ab", **line,
            **({"anomalies": sanity["anomalies"]}
               if not sanity["ok"] else {}))
        if should_bank:
            record(line, sanity=sanity)
        if not sanity["ok"]:
            return {"outcome": "anomaly",
                    "anomalies": sanity["anomalies"]}
        if mismatches:
            return {"outcome": "anomaly",
                    "anomalies": [f"resident-mismatch:{mismatches}"]}
        return {}

    def serving_case():
        """Serving-layer batched A/B on the real backend (the serving
        stage the round-10 ROADMAP left unwritten): N tenants through
        ONE StencilServer — submit-all-then-wait-all so the batching
        window co-batches them — vs N fresh solo contexts each paying
        its own compile.  Response bit-identity to the sequential
        twins is the gate; a corrupt serve arm is withheld from the
        comparison and banks quarantined."""
        from yask_tpu import cache as ccache
        from yask_tpu.serve import StencilServer
        from yask_tpu.serve.scheduler import extract_outputs
        N = 4
        gs = 128 if plat == "tpu" else 32
        steps_s = 4

        def seed_arr(i):
            rng = np.random.RandomState(700 + i)
            return (rng.rand(1, gs, gs, gs).astype(np.float32)
                    - 0.5) * 0.1

        saved = os.environ.pop("YT_COMPILE_CACHE", None)
        try:
            ctxs = []
            for i in range(N):
                c = build(fac, env, "iso3dfd", "jit", gs, 8, wf=2)
                c.get_var("pressure").set_elements_in_slice(
                    seed_arr(i), [0, 0, 0, 0],
                    [0, gs - 1, gs - 1, gs - 1])
                ctxs.append(c)
            t0s = time.perf_counter()
            for c in ctxs:
                ccache.clear_memo()   # N tenants, N compiles
                c.run_solution(0, steps_s - 1)
            t_seq = time.perf_counter() - t0s
            seq_outs = [extract_outputs(c) for c in ctxs]
            del ctxs

            srv = StencilServer(window_secs=0.1, max_batch=N,
                                preflight=False)
            sids = []
            for i in range(N):
                sid = srv.open_session(stencil="iso3dfd", radius=8,
                                       g=gs, mode="jit", wf=2)
                srv.init_vars(sid)
                with srv.scheduler.session_ctx(sid) as c:
                    c.get_var("pressure").set_elements_in_slice(
                        seed_arr(i), [0, 0, 0, 0],
                        [0, gs - 1, gs - 1, gs - 1])
                sids.append(sid)
            ccache.clear_memo()
            t0b = time.perf_counter()
            handles = [srv.submit_run(sid, 0, steps_s - 1)
                       for sid in sids]
            resps = [srv.wait(h, timeout=600) for h in handles]
            t_srv = time.perf_counter() - t0b
            occ = max((r.batch for r in resps), default=0)
            srv.shutdown()
        finally:
            if saved is not None:
                os.environ["YT_COMPILE_CACHE"] = saved
        bad_resps = [r.rid for r in resps if not r.ok]
        first = next((r for r in resps if r.ok), None)
        probe = (next(iter(first.outputs.values()))
                 if first and first.outputs else np.zeros(1))
        sanity = check_output(
            maybe_corrupt("session.serve_result", np.asarray(probe)))
        mismatches = 0
        if sanity["ok"]:   # corrupt serve arm: comparison withheld
            for i, (want, r) in enumerate(zip(seq_outs, resps)):
                if not r.ok:
                    continue
                for n, a in want.items():
                    if not np.array_equal(a, r.outputs[n]):
                        mismatches += 1
        line = {"metric": f"iso3dfd r=8 {gs}^3 {plat} "
                          f"serve-batch{N}-speedup",
                "value": round(t_seq / max(t_srv, 1e-12), 4),
                "unit": "x", "platform": plat, "tenants": N,
                "occupancy": occ, "seq_secs": round(t_seq, 3),
                "serve_secs": round(t_srv, 3),
                "failed": len(bad_resps), "mismatches": mismatches}
        log("serving", **line,
            **({"anomalies": sanity["anomalies"]}
               if not sanity["ok"] else {}))
        if should_bank:
            record(line, sanity=sanity)
        if not sanity["ok"]:
            return {"outcome": "anomaly",
                    "anomalies": sanity["anomalies"]}
        if bad_resps or mismatches:
            return {"outcome": "anomaly",
                    "anomalies": ([f"serve-failed:{len(bad_resps)}"]
                                  if bad_resps else [])
                    + ([f"serve-mismatch:{mismatches}"]
                       if mismatches else [])}
        return {}

    def serving_bucket_case():
        """Cross-profile bucketed co-batch A/B on the real backend:
        tenants on THREE different geometries ride one ladder rung as
        masked sub-domains of a shared bucket profile (one vmapped
        ensemble execution) vs per-tenant solo contexts each paying
        their own compile.  Bit-identity of every tenant to its solo
        twin is the gate — the masked step runs as a chained pair of
        select-free executables exactly so this holds on any backend;
        this stage is that claim's first trial on real Mosaic-adjacent
        XLA:TPU.  A degrade to sequential members (batched=False)
        banks as an anomaly, never as a speedup."""
        from yask_tpu import cache as ccache
        from yask_tpu.serve import StencilServer
        from yask_tpu.serve.buckets import bucket_for
        from yask_tpu.serve.scheduler import extract_outputs
        N = 4
        # three distinct geometries that share ONE ladder rung (128 /
        # 32) — mixed rungs group into separate bucket profiles and
        # the A/B would measure two half-batches instead of one
        cycle = (120, 124, 128) if plat == "tpu" else (28, 30, 32)
        gs = [cycle[i % len(cycle)] for i in range(N)]
        rung = bucket_for(max(gs))
        steps_s = 4

        def seed_arr(i, gi):
            rng = np.random.RandomState(800 + i)
            return (rng.rand(1, gi, gi, gi).astype(np.float32)
                    - 0.5) * 0.1

        saved = os.environ.pop("YT_COMPILE_CACHE", None)
        try:
            seq_outs = []
            t0s = time.perf_counter()
            for i, gi in enumerate(gs):
                c = build(fac, env, "iso3dfd", "jit", gi, 2, wf=2)
                c.get_var("pressure").set_elements_in_slice(
                    seed_arr(i, gi), [0, 0, 0, 0],
                    [0, gi - 1, gi - 1, gi - 1])
                ccache.clear_memo()   # each geometry = its own compile
                c.run_solution(0, steps_s - 1)
                seq_outs.append(extract_outputs(c))
                del c
            t_seq = time.perf_counter() - t0s

            srv = StencilServer(window_secs=0.1, max_batch=N,
                                preflight=False)
            sids = []
            for i, gi in enumerate(gs):
                sid = srv.open_session(stencil="iso3dfd", radius=2,
                                       g=gi, mode="jit", wf=2,
                                       bucket=True)
                b = srv.session_bucket(sid)
                if b["decision"] != "bucketed":
                    raise RuntimeError(
                        f"g={gi} not bucketed: {b}")
                srv.init_vars(sid)
                with srv.scheduler.session_ctx(sid) as c:
                    c.get_var("pressure").set_elements_in_slice(
                        seed_arr(i, gi), [0, 0, 0, 0],
                        [0, gi - 1, gi - 1, gi - 1])
                sids.append(sid)
            ccache.clear_memo()
            t0b = time.perf_counter()
            handles = [srv.submit_run(sid, 0, steps_s - 1)
                       for sid in sids]
            resps = [srv.wait(h, timeout=600) for h in handles]
            t_bkt = time.perf_counter() - t0b
            occ = max((r.batch for r in resps), default=0)
            degraded = sum(1 for r in resps
                           if r.ok and r.batch > 1 and not r.batched)
            srv.shutdown()
        finally:
            if saved is not None:
                os.environ["YT_COMPILE_CACHE"] = saved
        bad_resps = [r.rid for r in resps if not r.ok]
        first = next((r for r in resps if r.ok), None)
        probe = (next(iter(first.outputs.values()))
                 if first and first.outputs else np.zeros(1))
        sanity = check_output(
            maybe_corrupt("session.serve_bucket_result",
                          np.asarray(probe)))
        mismatches = 0
        if sanity["ok"]:   # corrupt serve arm: comparison withheld
            for want, r in zip(seq_outs, resps):
                if not r.ok:
                    continue
                for n, a in want.items():
                    if (a.shape != r.outputs[n].shape
                            or not np.array_equal(a, r.outputs[n])):
                        mismatches += 1
        line = {"metric": f"iso3dfd r=2 mixed-g {plat} "
                          f"serve-bucket{N}-speedup",
                "value": round(t_seq / max(t_bkt, 1e-12), 4),
                "unit": "x", "platform": plat, "tenants": N,
                "geometries": sorted(set(gs)), "rung": rung,
                "occupancy": occ, "degraded": degraded,
                "seq_secs": round(t_seq, 3),
                "bucket_secs": round(t_bkt, 3),
                "failed": len(bad_resps), "mismatches": mismatches}
        log("serving_bucket", **line,
            **({"anomalies": sanity["anomalies"]}
               if not sanity["ok"] else {}))
        if should_bank:
            record(line, sanity=sanity)
        if not sanity["ok"]:
            return {"outcome": "anomaly",
                    "anomalies": sanity["anomalies"]}
        anomalies = []
        if bad_resps:
            anomalies.append(f"serve-failed:{len(bad_resps)}")
        if mismatches:
            anomalies.append(f"bucket-mismatch:{mismatches}")
        if occ < N:
            anomalies.append(f"no-cobatch:occupancy-{occ}")
        if degraded:
            anomalies.append(f"degraded-sequential:{degraded}")
        if anomalies:
            return {"outcome": "anomaly", "anomalies": anomalies}
        return {}

    rc = 0
    try:
        if "smoke" in stages:
            runner.run_case("smoke", "", smoke)

        # 2) validation matrix ordering: on a --quick (first-window)
        #    session the PERF stages run first — round 3 lost its
        #    hardware numbers because the relay dropped while
        #    validation compiles were still grinding; the A/B
        #    cross-checks below give internal consistency and the
        #    matrix still runs afterwards if the window holds.  Full
        #    sessions validate first (VERDICT r4 item 4).
        if not quick and "validate" in stages:
            run_matrix()

        if "chunk_abs" in stages:
            try:
                chunk_ab_stages()
            except Fault:
                raise
            except Exception as e:  # noqa: BLE001
                log("chunk_abs", error=str(e)[:300])
                rc = 1
        if "tune_bench" in stages:
            runner.run_case("tune_bench", "", tune_bench_stages)
            if runner.last_status == "fault":
                rc = 1

        # 6) persistent-cache + ensemble A/Bs: cheap (64³/128³ jit) and
        #    banked before the quick-session validation matrix can
        #    burn the relay window
        if "compile_cache_ab" in stages:
            runner.run_case("compile_cache_ab", "", compile_cache_case)
        if "ensemble_ab" in stages:
            runner.run_case("ensemble_ab", "", ensemble_case)
        # 6b) pipeline fusion + serving A/Bs: same cheap-and-banked
        #     policy as the cache/ensemble rows
        if "pipeline_fusion_ab" in stages:
            runner.run_case("pipeline_fusion_ab", "",
                            pipeline_fusion_case)
        if "push_ab" in stages:
            runner.run_case("push_ab", "", push_ab_case)
        if "serving" in stages:
            runner.run_case("serving", "", serving_case)
        if "serving_bucket" in stages:
            runner.run_case("serving_bucket", "", serving_bucket_case)
        if "serve_resident_ab" in stages:
            runner.run_case("serve_resident_ab", "",
                            serve_resident_case)

        # 5b) quick sessions validate AFTER the perf stages are banked
        if quick and "validate" in stages:
            run_matrix()

        # 6) Mosaic compile-time pathology check (LAST: mid-r3 saw
        #    ssg-K2 / swe2d compiles >15 min; a hang here must not cost
        #    the session).  A/B the default tile-planner vinstr cap
        #    against a tight one so the r5 `max_vinstr` knob is
        #    validated on real Mosaic.
        if "compile_time" in stages:
            def ct_case(name, radius, cap):
                def body():
                    t0 = time.perf_counter()
                    c = build(fac, env, name, "pallas", 32, radius, wf=2)
                    c.get_settings().max_tile_vinstr = cap
                    c.run_solution(0, 1)
                    log("compile_time", stencil=name, max_vinstr=cap,
                        secs=round(time.perf_counter() - t0, 1))
                return body
            for name, radius in (("ssg", 2), ("swe2d", None)):
                for cap in (300_000, 64_000):
                    runner.run_case("compile_time", f"{name}.{cap}",
                                    ct_case(name, radius, cap))
    except Fault as f:
        # breaker tripped inside run_case: the session is over — the
        # journal already holds the abort marker and every banked case
        log("session", aborted=True, fault=f.kind, error=str(f)[:200])
        return 1

    journal.record("session", "", "ok", rc=rc)
    return rc


if __name__ == "__main__":
    sys.exit(main())
