#!/usr/bin/env python
"""Repo-specific AST lint — the rules generic linters cannot know.

Rules (see docs/checking.md for the catalog):

* ``EXPR-EQ`` / ``EXPR-NE`` — Python ``==`` / ``!=`` on expression-AST
  values.  ``Expr.__eq__`` BUILDS an ``EqualsExpr`` comparison node
  (that is the DSL), so boolean comparison of two Expr objects is
  always a bug outside ``compiler/expr.py`` itself — use ``.same()``
  for structural identity or compare ``.skey()`` strings.
* ``EXPR-KEY`` — expression nodes used as dict keys / subscripts.
  With ``__eq__`` overloaded, dict lookup degenerates to identity-ish
  hash behavior; key memo tables by ``.skey()`` instead.
* ``BARE-DEVICES`` — ``jax.devices()`` / ``jax.default_backend()``
  outside the sanctioned probe helpers.  A bare backend query dials
  the axon TPU relay and can hang a driver artifact for minutes; only
  the killable-subprocess probes (``_probe_platform``, ``_ready``) and
  explicitly pragma'd TPU-session tools may touch it.
* ``MESH-DIRECT`` — ``Mesh(...)`` construction outside the single
  factory (``yask_tpu/parallel/mesh.py``, ``make_mesh``).  The mesh is
  where the backend becomes config (device list + axis map): scattered
  constructions fork that decision and break the multi-host launch
  path, which hands a ``jax.distributed`` global device list to the
  one factory.
* ``COMPILE-DIRECT`` — a chained ``.lower(...).compile()`` executable
  build, or a ``jax.experimental.serialize_executable`` import,
  outside ``yask_tpu/cache/``.  Every executable must be built through
  the one chokepoint (``yask_tpu.cache.aot_compile``): it owns the
  trace counter, the compile-time accounting, and the persistent
  on-disk cache — a bypassed build silently loses all three.
  Detection is the chain (receiver of ``.compile()`` is itself a
  ``.lower(...)`` call), so ``str.lower()`` and the front-end's
  ``yc_solution.compile(dtype=...)`` never false-positive.
* ``BARE-DEVICE-CALL`` — device WORK (``run_solution`` /
  ``block_until_ready`` / ``compare_data`` / ``run_auto_tuner_now``)
  in a driver artifact (``bench.py``, ``tools/*.py``) outside any
  resilience guard.  A relay that dies mid-run hangs such a call with
  nothing to kill it; driver tools must route device work through
  ``guarded_call`` / ``run_deadlined`` (or the suite/session wrappers
  ``section`` / ``run_case`` that call them).  Sanctioning is a
  transitive call-graph closure from the functions passed into those
  invokers, so helpers like ``measure`` stay clean without pragmas.
  Library code (``yask_tpu/``) is out of scope — the rule is about
  unattended driver artifacts, not the API.
* ``CKPT-UNGUARDED`` — checkpoint I/O (``save_checkpoint`` /
  ``load_checkpoint`` / ``restore_checkpoint``) in a driver artifact
  outside any resilience guard.  Same mechanics and scope as
  ``BARE-DEVICE-CALL``: a checkpoint save pulls device state to host
  (a device hang can strand it) and its fault-injection sites
  (``ckpt.save`` / ``ckpt.restore``) only classify when the call runs
  under ``guarded_call``; new run-loops that write checkpoints must
  route them through a guard.
* ``TRACE-ID`` — a JSONL append site (a function with an append-mode
  ``open`` plus a ``json.dumps``) that never references
  ``stamp_trace`` / ``trace_id``.  Every journal/ledger-style row
  must be joinable against TRACE_EVENTS.jsonl when a trace is active
  (``yask_tpu/obs/tracer.py``); a new appender that forgets the stamp
  silently drops its artifact out of the end-to-end correlation
  spine.  Out of scope in ``tests/`` (fixture writers); the tracer's
  own row writer is pragma'd — it IS the trace.
* ``PHASE-SITE`` — a ``guarded_call``/``fault_point``/``maybe_corrupt``
  site id that falls through ``phase_for_site``'s prefix table to the
  default ``"guard"`` phase.  Guard spans are named after their sites,
  so an unmapped site dumps its time into the catch-all bucket of
  every obs_report/attribution breakdown instead of the phase it
  belongs to; new device-facing sites must either match an existing
  prefix or extend ``_SITE_PHASES`` (``yask_tpu/obs/tracer.py``) —
  that is the drift this rule pins.  Lexically-resolvable ids only
  (string literals and f-string prefixes); out of scope in ``tests/``
  (throwaway unit-test sites).

* ``CAP-CONST`` — a raw backend-legality literal (lane-tile ``128``,
  a sublane alignment ``% 8`` / ``// 8`` (or 16/32), a
  sublane-by-itemsize dict map, or a constant-MiB VMEM byte value
  ``N * 2**20``) re-appearing in the modules that must read those
  facts from the backend capability table
  (``yask_tpu/backend/capability.py``): VarGeom/lowering, the tile
  planner, the pallas build, and the checker passes.  A re-baked
  constant is exactly the drift the table exists to kill — the static
  checker would keep modeling a rule the runtime no longer enforces
  (or vice versa).  Go through ``get_capability()`` /
  ``tpu_tile_dims`` / ``sublane_count`` / ``vmem_limit_bytes``
  instead.  Dict KEYS are exempt (itemsize→dtype maps key on element
  bytes, which is data, not a layout fact).

Detection of "an Expr value" is lexical (this is a linter, not a type
checker): names ``expr``/``lhs``/``rhs``/``eq``, the ``*_expr``
suffix, and attribute access ``.lhs`` / ``.rhs``.  Escape hatch: put
``# lint: <rule>-ok`` on the flagged line (rule tokens: ``expr-eq``,
``expr-key``, ``devices``, ``mesh``, ``compile-direct``,
``bare-device-call``, ``ckpt-unguarded``, ``trace-id``,
``phase-site``, ``cap-const``).

Usage: ``python tools/repo_lint.py [paths...]`` — defaults to the
repo root; exit 1 when anything fires.
"""

from __future__ import annotations

import ast
import json
import os
import sys
from typing import List, Optional

# the PHASE-SITE rule imports the REAL phase table (drift check)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SKIP_DIRS = {".git", ".perf_bisect", "__pycache__", ".claude",
             ".pytest_cache", "build"}
# expr.py defines the overloaded operators — == is the DSL there
EXPR_RULE_EXEMPT = {os.path.join("yask_tpu", "compiler", "expr.py")}
# mesh.py hosts make_mesh — THE sanctioned Mesh construction site
MESH_RULE_EXEMPT = {os.path.join("yask_tpu", "parallel", "mesh.py")}
# yask_tpu/cache/ hosts aot_compile — THE sanctioned executable-build
# and executable-(de)serialization site
COMPILE_RULE_EXEMPT_DIR = os.path.join("yask_tpu", "cache") + os.sep

_SUSPECT_NAMES = {"expr", "lhs", "rhs", "eq"}
_SUSPECT_ATTRS = {"lhs", "rhs"}
_PROBE_FUNCS = {"_probe_platform", "_ready"}

# ---- BARE-DEVICE-CALL ----------------------------------------------------
#: methods/functions that put work on the device (and therefore hang
#: when the relay dies mid-run)
_DEVICE_WORK = {"run_solution", "block_until_ready", "compare_data",
                "run_auto_tuner_now"}
#: resilience entry points: a function passed (by name, or as a
#: ``factory(...)`` call) into one of these runs under a deadline /
#: classified-fault guard, and so does everything it calls
_GUARD_INVOKERS = {"guarded_call", "run_deadlined", "section",
                   "run_case", "run_stage", "guarded"}
#: checkpoint I/O in a driver artifact needs the same guarding as
#: device work: the save pulls device state to host, and the
#: ckpt.save/ckpt.restore injection sites only classify under a guard
_CKPT_WORK = {"save_checkpoint", "load_checkpoint",
              "restore_checkpoint"}


def _device_rule_in_scope(relpath: str) -> bool:
    """Driver artifacts plus the serving layer: bench.py and the
    tools/ scripts run unattended against the relay, and
    yask_tpu/serve/ answers tenants long after any human is watching
    — both must reach device work only through a guard.  Other
    library code is exercised under the callers' guards."""
    return (relpath == "bench.py"
            or relpath.startswith("tools" + os.sep)
            or relpath.startswith(
                os.path.join("yask_tpu", "serve") + os.sep))


def _is_expr_operand(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        n = node.id
        return n in _SUSPECT_NAMES or n.endswith("_expr")
    if isinstance(node, ast.Attribute):
        return node.attr in _SUSPECT_ATTRS or node.attr.endswith("_expr")
    return False


def _is_backend_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute)
            and f.attr in ("devices", "default_backend")
            and isinstance(f.value, ast.Name) and f.value.id == "jax")


def _is_compile_chain(node: ast.Call) -> bool:
    """``<anything>.lower(...).compile(...)`` — the receiver of
    ``.compile`` is itself a ``.lower(...)`` call.  Chain detection is
    what keeps ``"x".lower()`` and ``yc_solution.compile(dtype=...)``
    out: neither is both links at once."""
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "compile"
            and isinstance(f.value, ast.Call)
            and isinstance(f.value.func, ast.Attribute)
            and f.value.func.attr == "lower")


def _is_mesh_ctor(node: ast.Call) -> bool:
    """``Mesh(...)`` / ``jax.sharding.Mesh(...)`` — lexical, like every
    rule here; names ending in ``Mesh`` other than the jax class are
    not flagged."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == "Mesh"
    if isinstance(f, ast.Attribute):
        return f.attr == "Mesh"
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, relpath: str, lines: List[str]):
        self.relpath = relpath
        self.lines = lines
        self.findings: List[dict] = []
        self._func_stack: List[str] = []

    def _pragma(self, lineno: int, token: str) -> bool:
        line = self.lines[lineno - 1] if lineno - 1 < len(self.lines) else ""
        return f"# lint: {token}-ok" in line

    def _add(self, rule: str, node: ast.AST, msg: str) -> None:
        self.findings.append({"rule": rule, "path": self.relpath,
                              "line": node.lineno, "message": msg})

    # ---- scope tracking -------------------------------------------------
    def visit_FunctionDef(self, node):
        self._func_stack.append(node.name)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    # ---- rules ----------------------------------------------------------
    def visit_Compare(self, node: ast.Compare):
        if self.relpath not in EXPR_RULE_EXEMPT:
            operands = [node.left] + list(node.comparators)
            for op in node.ops:
                if isinstance(op, (ast.Eq, ast.NotEq)):
                    hit = next((o for o in operands
                                if _is_expr_operand(o)), None)
                    if hit is not None and not self._pragma(
                            node.lineno, "expr-eq"):
                        rule = ("EXPR-EQ" if isinstance(op, ast.Eq)
                                else "EXPR-NE")
                        self._add(
                            rule, node,
                            f"Python {'==' if rule == 'EXPR-EQ' else '!='} "
                            "on an expression node builds an AST "
                            "comparison, not a bool — use .same() / "
                            ".skey()")
                        break
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript):
        if (self.relpath not in EXPR_RULE_EXEMPT
                and _is_expr_operand(node.slice)
                and not self._pragma(node.lineno, "expr-key")):
            self._add("EXPR-KEY", node,
                      "expression node used as a dict/table key — "
                      "__eq__ is overloaded; key by .skey()")
        self.generic_visit(node)

    def visit_Dict(self, node: ast.Dict):
        if self.relpath not in EXPR_RULE_EXEMPT:
            for k in node.keys:
                if k is not None and _is_expr_operand(k) \
                        and not self._pragma(k.lineno, "expr-key"):
                    self._add("EXPR-KEY", k,
                              "expression node used as a dict key — "
                              "__eq__ is overloaded; key by .skey()")
        self.generic_visit(node)

    def _import_hits_serialize(self, names) -> bool:
        return any("serialize_executable" in (n or "") for n in names)

    def visit_Import(self, node: ast.Import):
        if (self._import_hits_serialize(a.name for a in node.names)
                and not self.relpath.startswith(COMPILE_RULE_EXEMPT_DIR)
                and not self._pragma(node.lineno, "compile-direct")):
            self._add(
                "COMPILE-DIRECT", node,
                "executable (de)serialization outside yask_tpu/cache/ "
                "— cache entries are written/read only by the "
                "aot_compile chokepoint")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        names = [node.module or ""] + [a.name for a in node.names]
        if (self._import_hits_serialize(names)
                and not self.relpath.startswith(COMPILE_RULE_EXEMPT_DIR)
                and not self._pragma(node.lineno, "compile-direct")):
            self._add(
                "COMPILE-DIRECT", node,
                "executable (de)serialization outside yask_tpu/cache/ "
                "— cache entries are written/read only by the "
                "aot_compile chokepoint")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        if (_is_compile_chain(node)
                and not self.relpath.startswith(COMPILE_RULE_EXEMPT_DIR)
                and not self._pragma(node.lineno, "compile-direct")
                and not self._pragma(getattr(node, "end_lineno",
                                             node.lineno),
                                     "compile-direct")):
            self._add(
                "COMPILE-DIRECT", node,
                "chained .lower().compile() executable build outside "
                "yask_tpu/cache/ — route through "
                "yask_tpu.cache.aot_compile (trace counter, compile "
                "accounting, and the persistent cache all live there)")
        if _is_backend_call(node):
            sanctioned = any(f in _PROBE_FUNCS for f in self._func_stack)
            if not sanctioned and not self._pragma(node.lineno, "devices"):
                self._add(
                    "BARE-DEVICES", node,
                    "bare jax backend query outside a sanctioned probe "
                    "helper — this dials the TPU relay and can hang; "
                    "route through _probe_platform/env, or pragma a "
                    "deliberate TPU-session tool")
        if (_is_mesh_ctor(node)
                and self.relpath not in MESH_RULE_EXEMPT
                and not self._pragma(node.lineno, "mesh")):
            self._add(
                "MESH-DIRECT", node,
                "direct Mesh(...) construction outside the "
                "parallel.mesh.make_mesh factory — the mesh is config "
                "(device list + axis map), and forking its construction "
                "breaks the multi-host launch path; call make_mesh")
        self.generic_visit(node)


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


class _DeviceCallPass(ast.NodeVisitor):
    """Two-phase BARE-DEVICE-CALL check: collect the module call graph,
    the guard roots (names passed into guard invokers), and every
    device-work call site; then sanction sites whose lexically
    enclosing function is reachable from a root through the call
    graph.  Lexical and name-based — a linter, not a type checker —
    but that is exactly how the driver tools are shaped (nested
    section/case closures handed to ``run_case``/``section``)."""

    def __init__(self, work=None):
        self.work = work if work is not None else _DEVICE_WORK
        self.calls: dict = {}      # enclosing func name -> called names
        self.roots: set = set()    # names passed into guard invokers
        self.sites: List[tuple] = []   # (node, enclosing-func stack)
        self._stack: List[str] = []

    def visit_FunctionDef(self, node):
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        name = _call_name(node)
        if name:
            if self._stack:
                self.calls.setdefault(self._stack[-1], set()).add(name)
            if name in _GUARD_INVOKERS:
                for a in list(node.args) + [kw.value
                                            for kw in node.keywords]:
                    if isinstance(a, ast.Name):
                        self.roots.add(a.id)
                    elif (isinstance(a, ast.Call)
                          and isinstance(a.func, ast.Name)):
                        # case factory: run_case(st, c, make_body(...))
                        # — the factory's nested body runs guarded
                        self.roots.add(a.func.id)
            if name in self.work:
                self.sites.append((node, tuple(self._stack)))
        self.generic_visit(node)

    def guarded_funcs(self) -> set:
        guarded = set(self.roots)
        changed = True
        while changed:
            changed = False
            for f in list(guarded):
                for g in self.calls.get(f, ()):
                    if g not in guarded:
                        guarded.add(g)
                        changed = True
        return guarded


def _lint_guarded_work(tree: ast.AST, relpath: str, lines: List[str],
                       work, rule: str, pragma: str,
                       message: str) -> List[dict]:
    """Shared reachability check behind BARE-DEVICE-CALL and
    CKPT-UNGUARDED: flag direct ``work`` invocations whose enclosing
    function is not reachable from any guard root."""
    p = _DeviceCallPass(work=work)
    p.visit(tree)
    guarded = p.guarded_funcs()
    findings = []
    for node, stack in p.sites:
        if any(f in guarded for f in stack):
            continue
        line = (lines[node.lineno - 1]
                if node.lineno - 1 < len(lines) else "")
        if f"# lint: {pragma}-ok" in line:
            continue
        findings.append({
            "rule": rule, "path": relpath, "line": node.lineno,
            "message": f"{message.format(name=_call_name(node))}"})
    return findings


def _lint_device_calls(tree: ast.AST, relpath: str,
                       lines: List[str]) -> List[dict]:
    findings = _lint_guarded_work(
        tree, relpath, lines, _DEVICE_WORK, "BARE-DEVICE-CALL",
        "bare-device-call",
        "device work ({name}) in a driver artifact outside any "
        "resilience guard — a dying relay hangs it with nothing to "
        "kill it; route through guarded_call/run_deadlined (or a "
        "section/run_case wrapper), or pragma a deliberate exception")
    findings.extend(_lint_guarded_work(
        tree, relpath, lines, _CKPT_WORK, "CKPT-UNGUARDED",
        "ckpt-unguarded",
        "checkpoint I/O ({name}) in a driver artifact outside any "
        "resilience guard — the ckpt.save/ckpt.restore fault sites "
        "only classify under guarded_call; route the save/restore "
        "through a guard, or pragma a deliberate exception"))
    return findings


# ---- TRACE-ID ------------------------------------------------------------
_TRACE_REFS = {"stamp_trace", "trace_id"}


def _trace_rule_in_scope(relpath: str) -> bool:
    """Everything but tests/ — test fixtures legitimately write raw
    JSONL; production journal/ledger appenders must stamp."""
    return not relpath.startswith("tests" + os.sep)


def _is_append_open(node: ast.Call) -> bool:
    if not (isinstance(node.func, ast.Name) and node.func.id == "open"):
        return False
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    return (isinstance(mode, ast.Constant)
            and isinstance(mode.value, str)
            and mode.value.startswith("a"))


def _shallow_nodes(scope: ast.AST):
    """The nodes of one function (or module) body WITHOUT descending
    into nested function scopes — each scope answers for its own
    append sites."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            stack.extend(ast.iter_child_nodes(n))


def _lint_trace_id(tree: ast.AST, relpath: str,
                   lines: List[str]) -> List[dict]:
    """Flag JSONL append sites (append-mode ``open`` + ``json.dumps``
    in one scope) with no ``stamp_trace`` / ``trace_id`` reference."""
    findings = []
    scopes = [tree] + [n for n in ast.walk(tree)
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for scope in scopes:
        opens: List[ast.Call] = []
        has_dumps = False
        has_ref = False
        for n in _shallow_nodes(scope):
            if isinstance(n, ast.Call):
                if _is_append_open(n):
                    opens.append(n)
                elif _call_name(n) == "dumps":
                    has_dumps = True
                has_ref = has_ref or any(
                    kw.arg in _TRACE_REFS for kw in n.keywords)
            elif isinstance(n, ast.Name) and n.id in _TRACE_REFS:
                has_ref = True
            elif isinstance(n, ast.Attribute) and n.attr in _TRACE_REFS:
                has_ref = True
            elif isinstance(n, ast.Constant) and n.value == "trace_id":
                has_ref = True
            elif isinstance(n, ast.alias) and n.name in _TRACE_REFS:
                has_ref = True
        if not opens or not has_dumps or has_ref:
            continue
        for node in opens:
            line = (lines[node.lineno - 1]
                    if node.lineno - 1 < len(lines) else "")
            if "# lint: trace-id-ok" in line:
                continue
            findings.append({
                "rule": "TRACE-ID", "path": relpath,
                "line": node.lineno,
                "message": "JSONL append site without a stamp_trace/"
                           "trace_id reference — rows written here "
                           "cannot join TRACE_EVENTS.jsonl; call "
                           "yask_tpu.obs.tracer.stamp_trace(row) (or "
                           "pragma a deliberately untraced artifact)"})
    return findings


# ---- PHASE-SITE ----------------------------------------------------------
#: calls whose first positional argument IS a site id
_SITE_CALLS = {"fault_point", "maybe_corrupt"}


def _phase_site_in_scope(relpath: str) -> bool:
    """Everything but tests/ — unit tests mint throwaway site ids;
    production sites must land in a real phase bucket."""
    return not relpath.startswith("tests" + os.sep)


def _site_literal(node: ast.AST) -> Optional[str]:
    """The lexically resolvable site id: a string constant, or the
    leading constant of an f-string (``phase_for_site`` matches on
    prefixes, so the static head of ``f"suite.{name}"`` resolves the
    same as the full id).  None = dynamic, not checkable here."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr) and node.values:
        first = node.values[0]
        if isinstance(first, ast.Constant) \
                and isinstance(first.value, str):
            return first.value
    return None


def _lint_phase_sites(tree: ast.AST, relpath: str,
                      lines: List[str]) -> List[dict]:
    """Flag site ids that resolve to the default "guard" phase — the
    prefix-table drift check (the REAL ``phase_for_site`` is imported,
    so the rule and the runtime can never disagree)."""
    from yask_tpu.obs.tracer import phase_for_site
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        site = None
        if name in _SITE_CALLS and node.args:
            site = _site_literal(node.args[0])
        elif name == "guarded_call":
            for kw in node.keywords:
                if kw.arg == "site":
                    site = _site_literal(kw.value)
        if site is None or phase_for_site(site) != "guard":
            continue
        line = (lines[node.lineno - 1]
                if node.lineno - 1 < len(lines) else "")
        if "# lint: phase-site-ok" in line:
            continue
        findings.append({
            "rule": "PHASE-SITE", "path": relpath, "line": node.lineno,
            "message": f"site {site!r} falls through phase_for_site to "
                       "the default 'guard' phase — its span time lands "
                       "in the catch-all bucket of every breakdown; "
                       "match an existing prefix or extend _SITE_PHASES "
                       "(yask_tpu/obs/tracer.py), or pragma a "
                       "deliberately unphased site"})
    return findings


# ---- CAP-CONST -----------------------------------------------------------
#: the lane-tile extent — unmistakable wherever it appears in scope
_CAP_LANE = 128
#: sublane fold/tile extents by dtype — only flagged in alignment
#: arithmetic (``x % 8`` / ``x // 8``) and itemsize→sublane dict maps,
#: where they are layout facts; a bare ``8`` elsewhere is usually a
#: loop bound or heuristic and stays legal
_CAP_SUBLANES = {8, 16, 32}
_MIB = 2 ** 20


def _cap_const_in_scope(relpath: str) -> bool:
    """The single-source-of-truth perimeter: geometry (VarGeom/
    lowering), the planner, the pallas build, and the checker —
    everything that would let the static model and the runtime drift if
    they each kept a private copy of the probed rules.  The capability
    table itself is the sanctioned home."""
    if relpath.startswith(os.path.join("yask_tpu", "backend") + os.sep):
        return False
    return (relpath in (os.path.join("yask_tpu", "compiler",
                                     "lowering.py"),
                        os.path.join("yask_tpu", "ops",
                                     "tile_planner.py"),
                        os.path.join("yask_tpu", "ops",
                                     "pallas_stencil.py"))
            or relpath.startswith(
                os.path.join("yask_tpu", "checker") + os.sep))


def _is_mib_pow(node: ast.AST) -> bool:
    """``2 ** 20`` or the literal 1048576."""
    if isinstance(node, ast.Constant) and node.value == _MIB:
        return True
    return (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Pow)
            and isinstance(node.left, ast.Constant)
            and node.left.value == 2
            and isinstance(node.right, ast.Constant)
            and node.right.value == 20)


def _lint_cap_consts(tree: ast.AST, relpath: str,
                     lines: List[str]) -> List[dict]:
    findings = []
    # dict KEYS are exempt: itemsize→dtype maps key on element bytes
    dict_keys = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Dict):
            for k in n.keys:
                if k is not None:
                    dict_keys.add(id(k))

    def _add(node, what: str) -> None:
        line = (lines[node.lineno - 1]
                if node.lineno - 1 < len(lines) else "")
        if "# lint: cap-const-ok" in line:
            return
        findings.append({
            "rule": "CAP-CONST", "path": relpath, "line": node.lineno,
            "message": f"{what} — backend legality facts live in "
                       "yask_tpu/backend/capability.py; read them "
                       "through get_capability()/tpu_tile_dims/"
                       "sublane_count/vmem_limit_bytes (or pragma a "
                       "genuinely backend-independent constant)"})

    for n in ast.walk(tree):
        if (isinstance(n, ast.Constant) and n.value == _CAP_LANE
                and id(n) not in dict_keys):
            _add(n, f"raw lane-tile literal {_CAP_LANE}")
        elif isinstance(n, ast.BinOp):
            if (isinstance(n.op, (ast.Mod, ast.FloorDiv))
                    and isinstance(n.right, ast.Constant)
                    and n.right.value in _CAP_SUBLANES):
                _add(n, f"sublane alignment arithmetic on raw "
                        f"{n.right.value}")
            elif isinstance(n.op, ast.Mult):
                for a, b in ((n.left, n.right), (n.right, n.left)):
                    if (isinstance(a, ast.Constant)
                            and isinstance(a.value, int)
                            and _is_mib_pow(b)):
                        _add(n, f"constant VMEM byte value "
                                f"{a.value} MiB")
                        break
        elif isinstance(n, ast.Dict):
            subs = [v for v in n.values
                    if isinstance(v, ast.Constant)
                    and v.value in _CAP_SUBLANES]
            if len(subs) >= 2:
                _add(n, "itemsize→sublane dict map")
    return findings


def lint_file(path: str, root: str) -> List[dict]:
    relpath = os.path.relpath(path, root)
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [{"rule": "PARSE-ERROR", "path": relpath,
                 "line": e.lineno or 0, "message": str(e.msg)}]
    lines = src.splitlines()
    linter = _Linter(relpath, lines)
    linter.visit(tree)
    findings = linter.findings
    if _device_rule_in_scope(relpath):
        findings.extend(_lint_device_calls(tree, relpath, lines))
    if _trace_rule_in_scope(relpath):
        findings.extend(_lint_trace_id(tree, relpath, lines))
    if _phase_site_in_scope(relpath):
        findings.extend(_lint_phase_sites(tree, relpath, lines))
    if _cap_const_in_scope(relpath):
        findings.extend(_lint_cap_consts(tree, relpath, lines))
    return findings


def iter_py_files(paths: List[str], root: str):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d not in SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def run_lint(paths: Optional[List[str]] = None,
             root: Optional[str] = None) -> List[dict]:
    root = root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = paths or [root]
    findings: List[dict] = []
    for path in iter_py_files(paths, root):
        findings.extend(lint_file(path, root))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in argv
    argv = [a for a in argv if a != "--json"]
    findings = run_lint(argv or None)
    if as_json:
        print(json.dumps(findings, indent=2))
    else:
        for f in findings:
            print(f"{f['path']}:{f['line']}: {f['rule']}: {f['message']}")
        print(f"repo_lint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
