"""Simple axis/plane/cube stencils.

Counterpart of the reference's ``src/stencils/SimpleStencils.cpp:115-267``:
MiniGhost-style radius-parameterized averages over neighbor sets. Same
solution names and equation shapes; equations are built through the DSL,
not copied — the reference file documents WHAT each stencil averages.
"""

from __future__ import annotations

from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_with_radius_base,
)


@register_solution
class AxisStencil(yc_solution_with_radius_base):
    """'3axis': average of the center point and its neighbors out to
    ``radius`` along each axis (a (6r+1)-point star; r=1 is the classic
    7-point heat stencil)."""

    def __init__(self, name: str = "3axis", radius: int = 2):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        A = self.new_var("A", [t, x, y, z])
        r = self.get_radius()
        terms = [A(t, x, y, z)]
        for i in range(1, r + 1):
            terms += [A(t, x - i, y, z), A(t, x + i, y, z),
                      A(t, x, y - i, z), A(t, x, y + i, z),
                      A(t, x, y, z - i), A(t, x, y, z + i)]
        npts = float(len(terms))
        expr = terms[0]
        for term in terms[1:]:
            expr = expr + term
        A(t + 1, x, y, z).EQUALS(expr / npts)


@register_solution
class DiagStencil(yc_solution_with_radius_base):
    """'3axis_with_diags': the 3axis star plus corner-diagonal neighbors
    (reference ``DiagStencil``)."""

    def __init__(self, name: str = "3axis_with_diags", radius: int = 2):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        A = self.new_var("A", [t, x, y, z])
        r = self.get_radius()
        terms = [A(t, x, y, z)]
        for i in range(1, r + 1):
            terms += [A(t, x - i, y, z), A(t, x + i, y, z),
                      A(t, x, y - i, z), A(t, x, y + i, z),
                      A(t, x, y, z - i), A(t, x, y, z + i)]
            # 12 in-plane diagonals at distance i: 4 per coordinate plane
            # (the reference's DiagStencil adds x-y, x-z, and y-z plane
            # diagonals, not space corners).
            for si, sj in ((-i, -i), (-i, i), (i, -i), (i, i)):
                terms.append(A(t, x + si, y + sj, z))
                terms.append(A(t, x + si, y, z + sj))
                terms.append(A(t, x, y + si, z + sj))
        expr = terms[0]
        for term in terms[1:]:
            expr = expr + term
        A(t + 1, x, y, z).EQUALS(expr / float(len(terms)))


@register_solution
class PlaneStencil(yc_solution_with_radius_base):
    """'3plane': average over in-plane neighbors of the three coordinate
    planes (reference ``PlaneStencil``)."""

    def __init__(self, name: str = "3plane", radius: int = 1):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        A = self.new_var("A", [t, x, y, z])
        r = self.get_radius()
        # Distinct points of the union of the xy, xz, and yz planes within
        # radius r (center and on-axis points appear once each).
        offsets = set()
        for i in range(-r, r + 1):
            for j in range(-r, r + 1):
                offsets.add((i, j, 0))
                offsets.add((i, 0, j))
                offsets.add((0, i, j))
        terms = [A(t, x + dx, y + dy, z + dz)
                 for dx, dy, dz in sorted(offsets)]
        expr = terms[0]
        for term in terms[1:]:
            expr = expr + term
        A(t + 1, x, y, z).EQUALS(expr / float(len(terms)))


@register_solution
class CubeStencil(yc_solution_with_radius_base):
    """'cube': average over the full (2r+1)³ box (reference
    ``CubeStencil``; r=1 is the 27-point stencil)."""

    def __init__(self, name: str = "cube", radius: int = 1):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        A = self.new_var("A", [t, x, y, z])
        r = self.get_radius()
        terms = []
        for i in range(-r, r + 1):
            for j in range(-r, r + 1):
                for k in range(-r, r + 1):
                    terms.append(A(t, x + i, y + j, z + k))
        expr = terms[0]
        for term in terms[1:]:
            expr = expr + term
        A(t + 1, x, y, z).EQUALS(expr / float(len(terms)))


@register_solution
class NineAxisStencil(yc_solution_with_radius_base):
    """'9axis': average along the 3 axes and 6 space diagonals out to
    ``radius`` (reference ``...`` 9-axis variant of the Simple family)."""

    def __init__(self, name: str = "9axis", radius: int = 2):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        A = self.new_var("A", [t, x, y, z])
        r = self.get_radius()
        terms = [A(t, x, y, z)]
        dirs = [(1, 0, 0), (0, 1, 0), (0, 0, 1),
                (1, 1, 1), (1, 1, -1), (1, -1, 1), (1, -1, -1)]
        for i in range(1, r + 1):
            for dx, dy, dz in dirs:
                terms.append(A(t, x + i * dx, y + i * dy, z + i * dz))
                terms.append(A(t, x - i * dx, y - i * dy, z - i * dz))
        expr = terms[0]
        for term in terms[1:]:
            expr = expr + term
        A(t + 1, x, y, z).EQUALS(expr / float(len(terms)))
