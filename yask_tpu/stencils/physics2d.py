"""2-D physics stencils: wave equation and shallow-water equations.

Counterparts of the reference's ``wave2d`` (``Wave2dStencil.cpp:211``) and
``swe2d`` (``SWE2dStencil.cpp:498``). The SWE uses conservative form with
Lax–Friedrichs fluxes built in *scratch vars* — exercising the scratch-chain
machinery the reference's SWE exercises.
"""

from __future__ import annotations

from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_base,
    yc_solution_with_radius_base,
)


@register_solution
class Wave2dStencil(yc_solution_with_radius_base):
    """'wave2d': 2-D second-order wave equation, order-2r Laplacian."""

    def __init__(self, name: str = "wave2d", radius: int = 1):
        super().__init__(name, radius)

    def define(self):
        from yask_tpu.utils.fd_coeff import get_center_fd_coefficients
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        u = self.new_var("u", [t, x, y])
        c2 = self.new_var("c2", [x, y])   # (c·dt/h)² per cell

        r = self.get_radius()
        c = get_center_fd_coefficients(2, r)
        lap = 2.0 * c[r] * u(t, x, y)
        for i in range(1, r + 1):
            lap = lap + c[r + i] * (u(t, x - i, y) + u(t, x + i, y)
                                    + u(t, x, y - i) + u(t, x, y + i))
        u(t + 1, x, y).EQUALS(
            2.0 * u(t, x, y) - u(t - 1, x, y) + c2(x, y) * lap)


@register_solution
class SWE2dStencil(yc_solution_base):
    """'swe2d': conservative shallow-water equations (h, hu, hv) with
    Lax–Friedrichs numerical fluxes computed into scratch vars."""

    def __init__(self, name: str = "swe2d"):
        super().__init__(name)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        h = self.new_var("h", [t, x, y])     # water depth
        hu = self.new_var("hu", [t, x, y])   # x-momentum
        hv = self.new_var("hv", [t, x, y])   # y-momentum
        # dt/dx ratio and gravity baked into coefficient vars
        lam = self.new_var("lam", [])        # dt/dx
        grav = self.new_var("grav", [])      # g

        # Physical fluxes per cell, in scratch vars (computed over the
        # domain + write-halo, consumed at ±1 → exercises scratch chains).
        f_h = self.new_scratch_var("f_h", [x, y])    # = hu
        f_hu = self.new_scratch_var("f_hu", [x, y])  # = hu²/h + g h²/2
        f_hv = self.new_scratch_var("f_hv", [x, y])  # = hu·hv/h
        g_h = self.new_scratch_var("g_h", [x, y])    # = hv
        g_hu = self.new_scratch_var("g_hu", [x, y])  # = hu·hv/h
        g_hv = self.new_scratch_var("g_hv", [x, y])  # = hv²/h + g h²/2

        from yask_tpu.compiler.expr import max_fn
        H = h(t, x, y)
        U = hu(t, x, y)
        V = hv(t, x, y)
        g_ = grav()
        # Guarded depth: ghost cells outside the domain hold h = 0 and
        # would otherwise produce 0/0 in the momentum fluxes; the floor
        # makes boundary fluxes vanish smoothly (open-boundary behavior).
        Hs = max_fn(H, 1.0e-3)
        f_h(x, y).EQUALS(U)
        f_hu(x, y).EQUALS(U * U / Hs + 0.5 * g_ * H * H)
        f_hv(x, y).EQUALS(U * V / Hs)
        g_h(x, y).EQUALS(V)
        g_hu(x, y).EQUALS(U * V / Hs)
        g_hv(x, y).EQUALS(V * V / Hs + 0.5 * g_ * H * H)

        l = lam()

        def lxf(q, fx, gy):
            """Lax–Friedrichs update: average of neighbors minus flux
            differences (the standard conservative LxF form)."""
            avg = 0.25 * (q(t, x - 1, y) + q(t, x + 1, y)
                          + q(t, x, y - 1) + q(t, x, y + 1))
            return (avg
                    - 0.5 * l * (fx(x + 1, y) - fx(x - 1, y))
                    - 0.5 * l * (gy(x, y + 1) - gy(x, y - 1)))

        h(t + 1, x, y).EQUALS(lxf(h, f_h, g_h))
        hu(t + 1, x, y).EQUALS(lxf(hu, f_hu, g_hu))
        hv(t + 1, x, y).EQUALS(lxf(hv, f_hv, g_hv))

        # Reflective walls as sub-domain boundary overrides (the IF_DOMAIN
        # feature the reference's SWE/boundary stencils exercise). The
        # mirror uses the *previous-step* interior neighbor (lagged
        # reflection): same-step mirrors would make boundary equations
        # mutually dependent at var granularity, which the dependency
        # checker rightly rejects as a cycle.
        x0, x1 = self.first_domain_index(x), self.last_domain_index(x)
        y0, y1 = self.first_domain_index(y), self.last_domain_index(y)
        h(t + 1, x, y).EQUALS(h(t, x + 1, y)).IF_DOMAIN(x == x0)
        h(t + 1, x, y).EQUALS(h(t, x - 1, y)).IF_DOMAIN(x == x1)
        hu(t + 1, x, y).EQUALS(-hu(t, x + 1, y)).IF_DOMAIN(x == x0)
        hu(t + 1, x, y).EQUALS(-hu(t, x - 1, y)).IF_DOMAIN(x == x1)
        hv(t + 1, x, y).EQUALS(hv(t, x + 1, y)).IF_DOMAIN(x == x0)
        hv(t + 1, x, y).EQUALS(hv(t, x - 1, y)).IF_DOMAIN(x == x1)
        h(t + 1, x, y).EQUALS(h(t, x, y + 1)).IF_DOMAIN(y == y0)
        h(t + 1, x, y).EQUALS(h(t, x, y - 1)).IF_DOMAIN(y == y1)
        hv(t + 1, x, y).EQUALS(-hv(t, x, y + 1)).IF_DOMAIN(y == y0)
        hv(t + 1, x, y).EQUALS(-hv(t, x, y - 1)).IF_DOMAIN(y == y1)
        hu(t + 1, x, y).EQUALS(hu(t, x, y + 1)).IF_DOMAIN(y == y0)
        hu(t + 1, x, y).EQUALS(hu(t, x, y - 1)).IF_DOMAIN(y == y1)
