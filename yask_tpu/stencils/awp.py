"""AWP-ODC seismic stencils ('awp', 'awp_abc', 'awp_elastic', 'awp_elastic_abc').

Counterpart of the reference's AWP family (``src/stencils/AwpStencil.cpp:
627-876``): staggered velocity–stress seismic propagation with

* Cerjan sponge damping via a 3-D sponge var (the reference supports a
  3-D sponge var or 1-D factors, ``AwpStencil.cpp:34-100`` — the 3-D form
  is the TPU-native layout: separable tapers fold into it at init, and a
  full-dim coefficient rides lane-aligned DMA slabs),
* free-surface boundary equations at the top of the domain expressed as
  ``IF_DOMAIN`` sub-domain conditions (the feature the reference's AWP
  exercises hardest),
* an anelastic ('awp') vs purely elastic ('awp_elastic') stress update —
  the anelastic form adds memory-variable relaxation.
"""

from __future__ import annotations

from yask_tpu.utils.fd_coeff import get_arbitrary_fd_coefficients
from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_base,
)


class AwpBase(yc_solution_base):
    """Shared AWP machinery: staggered 4th-order derivatives + sponge."""

    _ABC = False      # apply Cerjan sponge factors
    _ANELASTIC = True  # include memory-variable relaxation

    def _c(self):
        # 4th-order staggered weights at half points (9/8, -1/24 pattern).
        return get_arbitrary_fd_coefficients(
            1, 0.0, [-1.5, -0.5, 0.5, 1.5])

    def _d(self, var, t, idxs, dim_pos, shift):
        c = self._c()
        expr = None
        for k in range(4):
            off = k - 2 + shift
            args = list(idxs)
            args[dim_pos] = args[dim_pos] + off
            term = c[k] * var(t, *args)
            expr = term if expr is None else expr + term
        return expr

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        d = (x, y, z)
        ax = {"x": 0, "y": 1, "z": 2}

        v = {c: self.new_var(f"vel_{c}", [t, x, y, z]) for c in "xyz"}
        s = {c: self.new_var(f"stress_{c}", [t, x, y, z])
             for c in ("xx", "yy", "zz", "xy", "xz", "yz")}
        rho = self.new_var("rho", [x, y, z])
        lam = self.new_var("lambda_", [x, y, z])
        mu = self.new_var("mu", [x, y, z])
        h = self.new_var("h", [])  # (dt/h) scalar-like var, no domain dims

        if self._ABC:
            sp = self.new_var("sponge", [x, y, z])

            def damp(e):
                return e * sp(x, y, z)
        else:
            def damp(e):
                return e

        if self._ANELASTIC:
            # Memory variables for anelastic attenuation (one per normal
            # stress), relaxed toward the elastic strain each step.
            r_v = {c: self.new_var(f"mem_{c}", [t, x, y, z])
                   for c in ("xx", "yy", "zz")}
            qp = self.new_var("qp", [x, y, z])   # attenuation factor

        dth = h()

        # --- stage 1: velocities -------------------------------------
        for c in "xyz":
            i = ax[c]
            names = {"x": ("xx", "xy", "xz"),
                     "y": ("xy", "yy", "yz"),
                     "z": ("xz", "yz", "zz")}[c]
            div = self._d(s[names[0]], t, d, 0, 1 if c == "x" else 0)
            div = div + self._d(s[names[1]], t, d, 1, 1 if c == "y" else 0)
            div = div + self._d(s[names[2]], t, d, 2, 1 if c == "z" else 0)
            upd = v[c](t, x, y, z) + dth / rho(x, y, z) * div
            v[c](t + 1, x, y, z).EQUALS(damp(upd))

        # --- stage 2: stresses ---------------------------------------
        e = {}
        for c in "xyz":
            for j in "xyz":
                shift = 0 if c == j else 1
                e[(c, j)] = self._d(v[c], t + 1, d, ax[j], shift)
        tr = e[("x", "x")] + e[("y", "y")] + e[("z", "z")]

        # Free-surface boundary at the top z planes (reference free-surface
        # eqs, AwpStencil.cpp:627-876): stresses involving z vanish on the
        # surface; bulk updates apply on the disjoint interior sub-domain.
        last_z = self.last_domain_index(z)

        for c in "xyz":
            cc = c + c
            el = (lam(x, y, z) * tr + 2.0 * mu(x, y, z) * e[(c, c)])
            if self._ANELASTIC:
                # Memory-variable relaxation: r(t+1) = q·(r(t) + el),
                # stress gains (el − r(t+1)) — a standard coarse-grained
                # attenuation form.
                r_v[cc](t + 1, x, y, z).EQUALS(
                    qp(x, y, z) * (r_v[cc](t, x, y, z) + el))
                el = el - r_v[cc](t + 1, x, y, z)
            upd = s[cc](t, x, y, z) + dth * el
            if cc == "zz":
                s[cc](t + 1, x, y, z).EQUALS(damp(upd)) \
                    .IF_DOMAIN(z < last_z)
                s[cc](t + 1, x, y, z).EQUALS(0.0).IF_DOMAIN(z == last_z)
            else:
                s[cc](t + 1, x, y, z).EQUALS(damp(upd))

        for a, b in (("x", "y"), ("x", "z"), ("y", "z")):
            nm = a + b
            upd = (s[nm](t, x, y, z)
                   + dth * mu(x, y, z) * (e[(a, b)] + e[(b, a)]))
            if "z" in nm:
                s[nm](t + 1, x, y, z).EQUALS(damp(upd)) \
                    .IF_DOMAIN(z < last_z - 1)
                s[nm](t + 1, x, y, z).EQUALS(0.0) \
                    .IF_DOMAIN(z >= last_z - 1)
            else:
                s[nm](t + 1, x, y, z).EQUALS(damp(upd))


@register_solution
class AwpStencil(AwpBase):
    _ABC = False
    _ANELASTIC = True

    def __init__(self):
        super().__init__("awp")


@register_solution
class AwpAbcStencil(AwpBase):
    _ABC = True
    _ANELASTIC = True

    def __init__(self):
        super().__init__("awp_abc")


@register_solution
class AwpElasticStencil(AwpBase):
    _ABC = False
    _ANELASTIC = False

    def __init__(self):
        super().__init__("awp_elastic")


@register_solution
class AwpElasticAbcStencil(AwpBase):
    _ABC = True
    _ANELASTIC = False

    def __init__(self):
        super().__init__("awp_elastic_abc")
