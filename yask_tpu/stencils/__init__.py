"""Stencil library: every solution family from the reference's
``src/stencils`` re-expressed in the Python DSL (same names, same equations,
same radius parameterization) so users of the reference find each solution
here (SURVEY §2.6 inventory).

Importing this package registers all solutions (the analog of the
``REGISTER_SOLUTION`` static objects linking into the compiler binary).
"""

from yask_tpu.stencils import simple  # noqa: F401
from yask_tpu.stencils import iso3dfd  # noqa: F401
from yask_tpu.stencils import elastic  # noqa: F401
from yask_tpu.stencils import awp  # noqa: F401
from yask_tpu.stencils import tti  # noqa: F401
from yask_tpu.stencils import physics2d  # noqa: F401
from yask_tpu.stencils import filters  # noqa: F401
from yask_tpu.stencils import rtm  # noqa: F401
from yask_tpu.stencils import test_stencils  # noqa: F401
