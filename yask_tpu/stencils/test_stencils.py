"""Feature-coverage test stencils.

Counterpart of the reference's ``src/stencils/TestStencils.cpp:200-1035``:
one small solution per DSL feature, used as the primary correctness
fixtures (dimensionality 1-D…4-D, misc dims, scratch chains, multi-stage
dependencies, sub-domain boundaries, step conditions, reverse time,
memory-bound streams, math functions).
"""

from __future__ import annotations

from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_base,
    yc_solution_with_radius_base,
)


def _def_t1d(r, V, t0, x, off, le, re):
    """Radius-sized 1-D sample at step t0, extended per side (reference
    ``TestBase::def_t1d``): left/right halos differ, pinning asymmetric
    halo analysis. ``off`` shifts the whole neighborhood."""
    v = None
    for i in range(-r - le, r + re + 1):
        term = V(t0, x + (off + i))
        v = term if v is None else v + term
    return 2.0 + v


def _def_1d(r, V, x, off, le, re):
    v = None
    for i in range(-r - le, r + re + 1):
        term = V(x + (off + i))
        v = term if v is None else v + term
    return 3.0 + v


def _def_t2d(r, V, t0, x, xle, xre, y, yle, yre):
    v = None
    for i in (-r - xle, 0, r + xre):
        for j in (-r - yle, 0, r + yre):
            term = V(t0, x + i, y + j)
            v = term if v is None else v + term
    return 4.0 + v


def _def_2d(r, V, x, xle, xre, y, yle, yre):
    v = None
    for i in (-r - xle, 0, r + xre):
        for j in (-r - yle, 0, r + yre):
            term = V(x + i, y + j)
            v = term if v is None else v + term
    return 5.0 + v


def _def_t3d(r, V, t0, x, xle, xre, y, yle, yre, z, zle, zre):
    v = V(t0, x, y, z)
    for i in (-r - xle, r + xre):
        for j in (-r - yle, r + yre):
            for k in (-r - zle, r + zre):
                v = v + V(t0, x + i, y + j, z + k)
    return v


def _def_3d(r, V, x, xle, xre, y, yle, yre, z, zle, zre):
    v = V(x, y, z)
    for i in (-r - xle, r + xre):
        for j in (-r - yle, r + yre):
            for k in (-r - zle, r + zre):
                v = v + V(x + i, y + j, z + k)
    return v


class _NdTest(yc_solution_with_radius_base):
    """N-D sum over an asymmetric neighborhood (reference
    ``Test1dStencil…Test4dStencil``, ``TestStencils.cpp:177-280``: the
    per-side extents make left/right halos differ per dim)."""

    DIMS = ("x",)
    EXTS = {"x": (0, 2)}    # per-dim (left_ext, right_ext)

    def define(self):
        t = self.new_step_index("t")
        idxs = [self.new_domain_index(d) for d in self.DIMS]
        u = self.new_var("u", [t] + idxs)
        r = self.get_radius()
        if len(idxs) == 1:
            le, re = self.EXTS["x"]
            expr = _def_t1d(r, u, t, idxs[0], 0, le, re)
        else:
            # center plus the corners of the extended polytope
            expr = u(t, *idxs)
            ranges = [(-r - self.EXTS[d][0], r + self.EXTS[d][1])
                      for d in self.DIMS]
            import itertools
            for corner in itertools.product(*ranges):
                pt = [idx + off for idx, off in zip(idxs, corner)]
                expr = expr + u(t, *pt)
        n = float(1 + 2 ** len(idxs)) if len(idxs) > 1 \
            else float(1 + 2 * r + self.EXTS["x"][0] + self.EXTS["x"][1])
        u(t + 1, *idxs).EQUALS(expr / n)


@register_solution
class Test1d(_NdTest):
    DIMS = ("x",)
    EXTS = {"x": (0, 2)}

    def __init__(self):
        super().__init__("test_1d", radius=1)


@register_solution
class Test2d(_NdTest):
    DIMS = ("x", "y")
    EXTS = {"x": (0, 2), "y": (4, 3)}

    def __init__(self):
        super().__init__("test_2d", radius=1)


@register_solution
class Test3d(_NdTest):
    DIMS = ("x", "y", "z")
    EXTS = {"x": (0, 2), "y": (4, 3), "z": (2, 1)}

    def __init__(self):
        super().__init__("test_3d", radius=1)


@register_solution
class Test4d(_NdTest):
    DIMS = ("w", "x", "y", "z")
    EXTS = {"w": (1, 2), "x": (0, 2), "y": (2, 1), "z": (1, 0)}

    def __init__(self):
        super().__init__("test_4d", radius=1)


@register_solution
class TestMisc2d(yc_solution_with_radius_base):
    """Misc indices interleaved between domain dims, negative misc
    values, misc-only and step+misc vars (reference
    ``TestMisc2dStencil``, ``TestStencils.cpp:330``)."""

    def __init__(self):
        super().__init__("test_misc_2d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        am = self.new_misc_index("a")
        bm = self.new_misc_index("b")
        cm = self.new_misc_index("c")
        r = self.get_radius()
        a = self.new_var("A", [t, x, am, y, bm, cm])
        b = self.new_var("B", [cm, bm])
        c = self.new_var("C", [t, bm, am])
        v = a(t, x, 0, y, 1, 2) + 1.0
        for i in range(1, r + 1):
            v = v + a(t, x + i, 3, y, 0, 3)
        for i in range(1, r + 2):
            v = v + a(t, x - i, 4, y, 2, 2)
        for i in range(1, r + 3):
            v = v + a(t, x, -2, y + i, 2, 2)
        for i in range(1, r + 4):
            v = v + a(t, x, 0, y - i, 0, 3)
        v = v + c(t, 1, 2)
        a(t + 1, x, 1, y, 2, 3).EQUALS(v + b(-2, 3) - b(4, -2))


@register_solution
class TestMiscValue2d(yc_solution_with_radius_base):
    """Misc index used as a VALUE (test_misc_value_2d): each equation's
    RHS reads the misc index it pins on the LHS — the per-equation
    constant the reference's generated code inlines. Exercises the
    per-equation eval-memo scoping in every backend (a shared memo
    would leak one equation's binding into its siblings)."""

    def __init__(self):
        super().__init__("test_misc_value_2d", radius=1)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        im = self.new_misc_index("i")
        r = self.get_radius()
        a = self.new_var("A", [t, x, y, im])
        for i in range(3):
            v = a(t, x, y, i) * 0.5 + im * 0.25
            for k in range(1, r + 1):
                v = v + (a(t, x + k, y, i) - a(t, x - k, y, i)) \
                    * (im + 1.0)
            a(t + 1, x, y, i).EQUALS(v)


@register_solution
class TestScratch1d(yc_solution_with_radius_base):
    """Scratch var read at far offsets from the write point (reference
    ``TestScratchStencil1``, ``TestStencils.cpp:626``: reads around
    ``x-4`` and ``x+6`` force a wide, asymmetric scratch halo)."""

    def __init__(self):
        super().__init__("test_scratch_1d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        r = self.get_radius()
        a = self.new_var("A", [t, x])
        b = self.new_scratch_var("B", [x])
        b(x).EQUALS(_def_t1d(r, a, t, x, 0, 1, 0))
        a(t + 1, x).EQUALS(_def_1d(r, b, x, -4, 2, 3)
                           + _def_1d(r, b, x, 6, 0, 1))


@register_solution
class TestStages2d(yc_solution_with_radius_base):
    """Three-stage dependency chain: B(t+1) reads A(t+1), C(t+1) reads
    B(t+1) at an offset (reference ``TestDepStencil2``,
    ``TestStencils.cpp:560``)."""

    def __init__(self):
        super().__init__("test_stages_2d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        r = self.get_radius()
        a = self.new_var("A", [t, x, y])
        b = self.new_var("B", [t, x, y])
        c = self.new_var("C", [t, x, y])
        a(t + 1, x, y).EQUALS(
            a(t, x, y) - _def_t2d(r, b, t, x, 0, 1, y, 2, 1))
        b(t + 1, x, y).EQUALS(
            b(t, x, y) - _def_t2d(r, a, t + 1, x, 3, 2, y, 0, 1))
        c(t + 1, x, y).EQUALS(b(t + 1, x - 1, y + 2))


@register_solution
class TestBoundary1d(yc_solution_base):
    """Sub-domain conditions with first/last_domain_index
    (test_boundary_*)."""

    def __init__(self):
        super().__init__("test_boundary_1d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        u = self.new_var("A", [t, x])
        first = self.first_domain_index(x)
        last = self.last_domain_index(x)
        sd0 = (x >= first + 5) & (x <= last - 3)
        v = _def_t1d(2, u, t, x, 0, 0, 1)
        u(t + 1, x).EQUALS(v).IF_DOMAIN(sd0)
        u(t + 1, x).EQUALS(-v).IF_DOMAIN(~sd0)


@register_solution
class TestStepCond1d(yc_solution_base):
    """Step conditions: different update on even/odd steps
    (test_step_cond_1d)."""

    def __init__(self):
        super().__init__("test_step_cond_1d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        b_ = self.new_misc_index("b")
        r = 2
        a = self.new_var("A", [t, x])
        bv = self.new_var("B", [b_])
        # step-parity condition and a condition on misc-var CONTENTS
        # (reference ``TestStepCondStencil1``, ``TestStencils.cpp:874``)
        tc0 = (t % 2) == 0
        vc0 = bv(0) > bv(1)
        a(t + 1, x).EQUALS(_def_t1d(r, a, t, x, 0, 0, 0)).IF_STEP(tc0)
        a(t + 1, x).EQUALS(
            _def_t1d(r, a, t, x, 0, 1, 2)).IF_STEP(~tc0 & vc0)
        # combined step + domain condition on one equation
        a(t + 1, x).EQUALS(
            _def_t1d(r, a, t, x, 0, 2, 0)).IF_STEP(~tc0 & ~vc0).IF_DOMAIN(
                x > self.first_domain_index(x) + 5)


@register_solution
class TestReverse2d(yc_solution_base):
    """Reverse-time stepping (test_reverse_2d): writes t-1 from t."""

    def __init__(self):
        super().__init__("test_reverse_2d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        u = self.new_var("A", [t, x, y])
        u(t - 1, x, y).EQUALS(
            _def_t2d(2, u, t, x, 0, 2, y, 4, 3) / 10.0)


class _StreamNd(yc_solution_with_radius_base):
    """Memory-bound stream reading ``radius`` past steps (reference
    ``StreamStencil1/2/3``, ``TestStencils.cpp:387-477``): exercises
    ring allocations deeper than 2."""

    DIMS = ("x",)

    def define(self):
        t = self.new_step_index("t")
        idxs = [self.new_domain_index(d) for d in self.DIMS]
        a = self.new_var("A", [t] + idxs)
        v = None
        for r in range(self.get_radius()):
            term = a(t - r, *idxs)
            v = term if v is None else v + term
        a(t + 1, *idxs).EQUALS(v + 1.0)


@register_solution
class TestStream3d(_StreamNd):
    """Memory-bound stream reading ``radius`` past steps (reference
    ``StreamStencil3``, ``TestStencils.cpp:461``)."""

    DIMS = ("x", "y", "z")

    def __init__(self):
        super().__init__("test_stream_3d", radius=2)


@register_solution
class TestFunc1d(yc_solution_base):
    """Math-function nodes (test_func_1d)."""

    def __init__(self):
        super().__init__("test_func_1d")

    def define(self):
        from yask_tpu.compiler.expr import sin, cos, atan, cbrt, max_fn
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        r = 1
        a = self.new_var("A", [t, x])
        b = self.new_var("B", [t, x])
        c = self.new_var("C", [t, x])
        a(t + 1, x).EQUALS(cos(a(t, x)) - 2.0 * sin(a(t, x)))
        b(t + 1, x).EQUALS(max_fn(_def_t1d(r, b, t, x, 0, 0, 1), 2.5))
        # C depends on A(t+1): math funcs ACROSS a stage boundary
        # (reference ``TestFuncStencil1``, ``TestStencils.cpp:967``)
        # +2 keeps the denominator away from cbrt(0) under zero-filled
        # boundary ghosts (0/0 → nan would poison the comparison)
        c(t + 1, x).EQUALS(
            atan(_def_t1d(r, a, t + 1, x, 0, 1, 0)
                 / cbrt(c(t, x + 1) + 2.0)))


@register_solution
class TestPartial3d(yc_solution_base):
    """Vars spanning subsets of the domain dims, in different orders
    (test_partial_3d): exercises axis alignment in lowering."""

    def __init__(self):
        super().__init__("test_partial_3d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        r = 2
        a = self.new_var("A", [t, x, y, z])
        b = self.new_var("B", [x])
        c = self.new_var("C", [y])
        d = self.new_var("D", [z])
        e = self.new_var("E", [x, y])
        f = self.new_var("F", [y, z])
        g = self.new_var("G", [z, y])       # reversed declaration order
        h = self.new_var("H", [y, z, x])    # 3-D in different order
        i_ = self.new_var("I", [])          # scalar
        j = self.new_var("J", [t])          # step-only
        k = self.new_var("K", [t, y])       # step + 1-D
        el = self.new_var("L", [t, y, z])   # step + 2-D
        a(t + 1, x, y, z).EQUALS(
            _def_t3d(r, a, t, x, 0, 2, y, 4, 3, z, 2, 1)
            + _def_1d(r, b, x, 0, 0, 1)
            + _def_1d(r, c, y, 0, 1, 0)
            + _def_1d(r, d, z, 0, 0, 0)
            + _def_2d(r, e, x, 0, 0, y, 1, 0)
            + _def_2d(r, f, y, 0, 1, z, 0, 0)
            + _def_2d(r, g, z, 1, 0, y, 0, 1)
            + _def_3d(r, h, y, 1, 0, z, 0, 1, x, 1, 0)
            + i_()
            + j(t)
            + _def_t1d(r, k, t, y, 0, 0, 1)
            + _def_t2d(r, el, t, y, 1, 0, z, 0, 1))


class _TestHelpers(yc_solution_with_radius_base):
    """Asymmetric-extent stencil builders shared by the fixture family.

    Counterpart of the reference ``TestBase`` helpers
    (``TestStencils.cpp:38-176``): each samples a radius-sized
    neighborhood extended by per-side ``*_ext`` amounts, so left/right
    halos differ — the corner the dependency/halo analysis must pin.
    ``off`` shifts the whole neighborhood (the reference passes shifted
    index expressions like ``x-4`` directly).
    """

    def def_t1d(self, V, t0, x, off, le, re):
        return _def_t1d(self.get_radius(), V, t0, x, off, le, re)

    def def_1d(self, V, x, off, le, re):
        return _def_1d(self.get_radius(), V, x, off, le, re)

    def def_t2d(self, V, t0, x, xle, xre, y, yle, yre):
        return _def_t2d(self.get_radius(), V, t0, x, xle, xre, y, yle, yre)

    def def_2d(self, V, x, xle, xre, y, yle, yre):
        return _def_2d(self.get_radius(), V, x, xle, xre, y, yle, yre)

    def def_t3d(self, V, t0, x, xle, xre, y, yle, yre, z, zle, zre):
        return _def_t3d(self.get_radius(), V, t0, x, xle, xre,
                        y, yle, yre, z, zle, zre)

    def def_3d(self, V, x, xle, xre, y, yle, yre, z, zle, zre):
        return _def_3d(self.get_radius(), V, x, xle, xre,
                       y, yle, yre, z, zle, zre)


@register_solution
class TestStages1d(_TestHelpers):
    """1-D dependency chain: C(t+1) reads A(t+1) → a 2nd stage
    (reference ``TestDepStencil1``, ``TestStencils.cpp:529``)."""

    def __init__(self):
        super().__init__("test_stages_1d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        a = self.new_var("A", [t, x])
        b = self.new_var("B", [t, x])
        c = self.new_var("C", [t, x])
        a(t + 1, x).EQUALS(-2.0 * a(t, x))
        b(t + 1, x).EQUALS(self.def_t1d(b, t, x, 0, 0, 1))
        c(t + 1, x).EQUALS(self.def_t1d(a, t + 1, x, 0, 1, 0) + c(t, x + 1))


@register_solution
class TestStages3d(_TestHelpers):
    """3-D two-stage chain: B(t+1) reads A(t+1) with its own asymmetric
    halo (reference ``TestDepStencil3``, ``TestStencils.cpp:593``)."""

    def __init__(self):
        super().__init__("test_stages_3d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        a = self.new_var("A", [t, x, y, z])
        b = self.new_var("B", [t, x, y, z])
        a(t + 1, x, y, z).EQUALS(
            a(t, x, y, z) - self.def_t3d(b, t, x, 0, 1, y, 2, 1, z, 1, 0))
        b(t + 1, x, y, z).EQUALS(
            b(t, x, y, z) - self.def_t3d(a, t + 1, x, 1, 0, y, 0, 1,
                                         z, 2, 1))


@register_solution
class TestScratch2d(_TestHelpers):
    """Three-level scratch chain with slot reuse potential (reference
    ``TestScratchStencil2``, ``TestStencils.cpp:657``)."""

    def __init__(self):
        super().__init__("test_scratch_2d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        a = self.new_var("A", [t, x, y])
        t1 = self.new_scratch_var("t1", [x, y])
        t2 = self.new_scratch_var("t2", [x, y])
        t3 = self.new_scratch_var("t3", [x, y])
        t1(x, y).EQUALS(self.def_t2d(a, t, x, 0, 1, y, 2, 1))
        t2(x, y).EQUALS(t1(x, y + 1))
        t3(x, y).EQUALS(t2(x + 1, y))
        a(t + 1, x, y).EQUALS(
            a(t, x, y) + self.def_2d(t2, x, 2, 0, y, 1, 0)
            + self.def_2d(t3, x, 1, 0, y, 0, 1))


@register_solution
class TestScratch3d(_TestHelpers):
    """Diamond scratch dependencies: t3 reads two independent scratch
    vars (reference ``TestScratchStencil3``, ``TestStencils.cpp:699``)."""

    def __init__(self):
        super().__init__("test_scratch_3d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        a = self.new_var("A", [t, x, y, z])
        t1 = self.new_scratch_var("t1", [x, y, z])
        t2 = self.new_scratch_var("t2", [x, y, z])
        t3 = self.new_scratch_var("t3", [x, y, z])
        t1(x, y, z).EQUALS(self.def_t3d(a, t, x, 0, 1, y, 2, 1, z, 1, 0))
        t2(x, y, z).EQUALS(self.def_t3d(a, t, x, 1, 0, y, 0, 2, z, 0, 1))
        t3(x, y, z).EQUALS(t1(x - 1, y + 1, z) + t2(x, y, z - 1))
        a(t + 1, x, y, z).EQUALS(
            a(t, x, y, z) + self.def_3d(t1, x, 2, 0, y, 0, 1, z, 1, 0)
            + self.def_3d(t3, x, 1, 0, y, 0, 1, z, 0, 2))


@register_solution
class TestScratchStages1d(_TestHelpers):
    """Scratch vars split across stages, defined out of assignment
    order; C carries a large one-sided scratch halo and D reads another
    stage's t+1 output (reference ``TestScratchStagesStencil1``,
    ``TestStencils.cpp:740``)."""

    def __init__(self):
        super().__init__("test_scratch_stages_1d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        a = self.new_var("A", [t, x])
        b = self.new_var("B", [t, x])
        c = self.new_scratch_var("C", [x])
        d = self.new_scratch_var("D", [x])
        e = self.new_scratch_var("E", [x])
        a(t + 1, x).EQUALS(self.def_1d(c, x, 0, 1, 0))
        c(x).EQUALS(self.def_1d(d, x, 0, 0, 8))   # large RHS scratch halo
        d(x).EQUALS(self.def_t1d(b, t + 1, x, 0, 1, 0))
        b(t + 1, x).EQUALS(self.def_1d(e, x, 0, 0, 1))
        e(x).EQUALS(self.def_t1d(a, t, x, 0, 1, 0))


@register_solution
class TestBoundary2d(_TestHelpers):
    """Rectangle-interior sub-domain with different stencils inside and
    outside (reference ``TestBoundaryStencil2``,
    ``TestStencils.cpp:810``)."""

    def __init__(self):
        super().__init__("test_boundary_2d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        a = self.new_var("A", [t, x, y])
        sd0 = ((x >= self.first_domain_index(x) + 5)
               & (x <= self.last_domain_index(x) - 3)
               & (y >= self.first_domain_index(y) + 4)
               & (y <= self.last_domain_index(y) - 6))
        a(t + 1, x, y).EQUALS(
            self.def_t2d(a, t, x, 0, 2, y, 1, 0)).IF_DOMAIN(sd0)
        a(t + 1, x, y).EQUALS(
            self.def_t2d(a, t, x, 1, 0, y, 0, 2)).IF_DOMAIN(~sd0)


@register_solution
class TestBoundary3d(_TestHelpers):
    """3-D box-interior sub-domain (reference ``TestBoundaryStencil3``,
    ``TestStencils.cpp:841``)."""

    def __init__(self):
        super().__init__("test_boundary_3d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        a = self.new_var("A", [t, x, y, z])
        sd0 = ((x >= self.first_domain_index(x) + 5)
               & (x <= self.last_domain_index(x) - 3)
               & (y >= self.first_domain_index(y) + 4)
               & (y <= self.last_domain_index(y) - 6)
               & (z >= self.first_domain_index(z) + 6)
               & (z <= self.last_domain_index(z) - 4))
        a(t + 1, x, y, z).EQUALS(
            self.def_t3d(a, t, x, 0, 2, y, 1, 0, z, 0, 1)).IF_DOMAIN(sd0)
        a(t + 1, x, y, z).EQUALS(
            self.def_t3d(a, t, x, 1, 0, y, 0, 2, z, 1, 0)).IF_DOMAIN(~sd0)


@register_solution
class TestScratchBoundary1d(_TestHelpers):
    """Conditional scratch writes + far-offset scratch reads (reference
    ``TestScratchBoundaryStencil1``, ``TestStencils.cpp:925``)."""

    def __init__(self):
        super().__init__("test_scratch_boundary_1d", radius=2)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        a = self.new_var("A", [t, x])
        t1 = self.new_scratch_var("T1", [x])
        sd0 = ((x >= self.first_domain_index(x) + 5)
               & (x <= self.last_domain_index(x) - 3))
        sd1 = ((x >= self.first_domain_index(x) + 3)
               & (x <= self.last_domain_index(x) - 2))
        b0 = self.def_t1d(a, t, x, 0, 1, 0)
        t1(x).EQUALS(b0).IF_DOMAIN(sd0)
        t1(x).EQUALS(-b0).IF_DOMAIN(~sd0)
        a1 = (self.def_1d(t1, x, -6, 2, 3)
              - self.def_1d(t1, x, 7, 0, 2))
        a(t + 1, x).EQUALS(a1).IF_DOMAIN(sd1)
        a(t + 1, x).EQUALS(-a1).IF_DOMAIN(~sd1)


@register_solution
class TestEmpty(_TestHelpers):
    """No vars, no equations (reference ``TestEmptyStencil0``,
    ``TestStencils.cpp:999``): the runtime must prepare and step a
    solution that does nothing."""

    def __init__(self):
        super().__init__("test_empty", radius=1)

    def define(self):
        self.new_step_index("t")
        self.new_domain_index("x")


@register_solution
class TestEmpty2d(_TestHelpers):
    """Vars but no equations (reference ``TestEmptyStencil2``)."""

    def __init__(self):
        super().__init__("test_empty_2d", radius=1)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        self.new_var("A", [t, x, y])


@register_solution
class TestStream1d(_StreamNd):
    DIMS = ("x",)

    def __init__(self):
        super().__init__("test_stream_1d", radius=2)


@register_solution
class TestStream2d(_StreamNd):
    DIMS = ("x", "y")

    def __init__(self):
        super().__init__("test_stream_2d", radius=2)
