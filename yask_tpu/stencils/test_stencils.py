"""Feature-coverage test stencils.

Counterpart of the reference's ``src/stencils/TestStencils.cpp:200-1035``:
one small solution per DSL feature, used as the primary correctness
fixtures (dimensionality 1-D…4-D, misc dims, scratch chains, multi-stage
dependencies, sub-domain boundaries, step conditions, reverse time,
memory-bound streams, math functions).
"""

from __future__ import annotations

from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_base,
    yc_solution_with_radius_base,
)


class _NdTest(yc_solution_with_radius_base):
    DIMS = ("x",)

    def define(self):
        t = self.new_step_index("t")
        idxs = [self.new_domain_index(d) for d in self.DIMS]
        u = self.new_var("u", [t] + idxs)
        r = self.get_radius()
        expr = u(t, *idxs)
        for ax in range(len(idxs)):
            for i in range(1, r + 1):
                lo = list(idxs)
                hi = list(idxs)
                lo[ax] = idxs[ax] - i
                hi[ax] = idxs[ax] + i
                expr = expr + u(t, *lo) + u(t, *hi)
        n = float(1 + 2 * r * len(idxs))
        u(t + 1, *idxs).EQUALS(expr / n)


@register_solution
class Test1d(_NdTest):
    DIMS = ("x",)

    def __init__(self):
        super().__init__("test_1d", radius=1)


@register_solution
class Test2d(_NdTest):
    DIMS = ("x", "y")

    def __init__(self):
        super().__init__("test_2d", radius=1)


@register_solution
class Test3d(_NdTest):
    DIMS = ("x", "y", "z")

    def __init__(self):
        super().__init__("test_3d", radius=1)


@register_solution
class Test4d(_NdTest):
    DIMS = ("w", "x", "y", "z")

    def __init__(self):
        super().__init__("test_4d", radius=1)


@register_solution
class TestMisc2d(yc_solution_base):
    """Misc dims with negative first index (reference test_misc_2d)."""

    def __init__(self):
        super().__init__("test_misc_2d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        m = self.new_misc_index("m")
        u = self.new_var("u", [t, x, y])
        k = self.new_var("k", [m, x, y])
        u(t + 1, x, y).EQUALS(
            k(-1, x, y) * u(t, x - 1, y)
            + k(0, x, y) * u(t, x, y)
            + k(1, x, y) * u(t, x + 1, y))


@register_solution
class TestScratch1d(yc_solution_base):
    """Two-level scratch chain (reference test_scratch_* family)."""

    def __init__(self):
        super().__init__("test_scratch_1d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        u = self.new_var("u", [t, x])
        s1 = self.new_scratch_var("s1", [x])
        s2 = self.new_scratch_var("s2", [x])
        s1(x).EQUALS(u(t, x - 1) + u(t, x + 1))
        s2(x).EQUALS(s1(x - 1) * 0.5 + s1(x + 1) * 0.5)
        u(t + 1, x).EQUALS(u(t, x) + 0.1 * s2(x))


@register_solution
class TestStages2d(yc_solution_base):
    """Same-step dependency chain → multiple stages (test_stages_*)."""

    def __init__(self):
        super().__init__("test_stages_2d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        a = self.new_var("a", [t, x, y])
        b = self.new_var("b", [t, x, y])
        a(t + 1, x, y).EQUALS(
            0.25 * (a(t, x - 1, y) + a(t, x + 1, y)
                    + b(t, x, y - 1) + b(t, x, y + 1)))
        b(t + 1, x, y).EQUALS(b(t, x, y) + 0.5 * a(t + 1, x - 1, y))


@register_solution
class TestBoundary1d(yc_solution_base):
    """Sub-domain conditions with first/last_domain_index
    (test_boundary_*)."""

    def __init__(self):
        super().__init__("test_boundary_1d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        u = self.new_var("u", [t, x])
        first = self.first_domain_index(x)
        last = self.last_domain_index(x)
        interior = (x > first + 0) & (x < last - 0)
        u(t + 1, x).EQUALS(
            0.5 * (u(t, x - 1) + u(t, x + 1))).IF_DOMAIN(
                (x > first) & (x < last))
        u(t + 1, x).EQUALS(0.0).IF_DOMAIN((x == first) | (x == last))


@register_solution
class TestStepCond1d(yc_solution_base):
    """Step conditions: different update on even/odd steps
    (test_step_cond_1d)."""

    def __init__(self):
        super().__init__("test_step_cond_1d")

    def define(self):
        from yask_tpu.compiler.expr import IndexExpr, IndexType
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        u = self.new_var("u", [t, x])
        even = (t % 2) == 0
        odd = (t % 2) == 1
        u(t + 1, x).EQUALS(u(t, x) + 1.0).IF_STEP(even)
        u(t + 1, x).EQUALS(u(t, x) * 2.0).IF_STEP(odd)


@register_solution
class TestReverse2d(yc_solution_base):
    """Reverse-time stepping (test_reverse_2d): writes t-1 from t."""

    def __init__(self):
        super().__init__("test_reverse_2d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        u = self.new_var("u", [t, x, y])
        u(t - 1, x, y).EQUALS(
            (u(t, x, y) + u(t, x - 1, y) + u(t, x, y + 1)) / 3.0)


@register_solution
class TestStream3d(yc_solution_base):
    """Memory-bound stream: many vars, trivial compute (test_stream_*)."""

    def __init__(self):
        super().__init__("test_stream_3d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        vs = [self.new_var(f"v{i}", [t, x, y, z]) for i in range(4)]
        for i, v in enumerate(vs):
            src = vs[(i + 1) % len(vs)]
            v(t + 1, x, y, z).EQUALS(
                0.5 * v(t, x, y, z) + 0.5 * src(t, x, y, z))


@register_solution
class TestFunc1d(yc_solution_base):
    """Math-function nodes (test_func_1d)."""

    def __init__(self):
        super().__init__("test_func_1d")

    def define(self):
        from yask_tpu.compiler.expr import sqrt, fabs, exp, sin, cos, max_fn
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        u = self.new_var("u", [t, x])
        u(t + 1, x).EQUALS(
            0.5 * sin(u(t, x)) * cos(u(t, x))
            + 0.1 * sqrt(fabs(u(t, x - 1)))
            + 0.01 * exp(-fabs(u(t, x + 1)))
            + max_fn(u(t, x), 0.0) * 0.01)


@register_solution
class TestPartial3d(yc_solution_base):
    """Vars spanning subsets of the domain dims, in different orders
    (test_partial_3d): exercises axis alignment in lowering."""

    def __init__(self):
        super().__init__("test_partial_3d")

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        u = self.new_var("u", [t, x, y, z])
        cx = self.new_var("cx", [x])
        cyz = self.new_var("cyz", [z, y])   # reversed declaration order
        u(t + 1, x, y, z).EQUALS(
            u(t, x, y, z) * cx(x) + u(t, x - 1, y, z) * cyz(z, y))
