"""Image filters with a misc (channel) dim.

Counterpart of the reference's ``box``/``gaussian`` stencils
(``src/stencils/ImageFilters.cpp:76,123``), which exist to exercise
misc-dim (channel) indexing in the DSL: the image is ``(t, c, x, y)`` with
``c`` a misc dim indexed by constants.
"""

from __future__ import annotations

import math

from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_with_radius_base,
)

NUM_CHANNELS = 3


@register_solution
class BoxFilter(yc_solution_with_radius_base):
    """'box': per-channel (2r+1)² moving average, repeated each step."""

    def __init__(self, name: str = "box", radius: int = 1):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        c = self.new_misc_index("c")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        img = self.new_var("img", [t, c, x, y])
        r = self.get_radius()
        n = float((2 * r + 1) ** 2)
        for ch in range(NUM_CHANNELS):
            expr = None
            for i in range(-r, r + 1):
                for j in range(-r, r + 1):
                    term = img(t, ch, x + i, y + j)
                    expr = term if expr is None else expr + term
            img(t + 1, ch, x, y).EQUALS(expr / n)


@register_solution
class GaussianFilter(yc_solution_with_radius_base):
    """'gaussian': separable-weight Gaussian blur per channel."""

    def __init__(self, name: str = "gaussian", radius: int = 1):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        c = self.new_misc_index("c")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        img = self.new_var("img", [t, c, x, y])
        r = self.get_radius()
        sigma = max(r / 2.0, 0.5)
        w1 = [math.exp(-(i * i) / (2 * sigma * sigma))
              for i in range(-r, r + 1)]
        s = sum(w1)
        w1 = [w / s for w in w1]
        for ch in range(NUM_CHANNELS):
            expr = None
            for i in range(-r, r + 1):
                for j in range(-r, r + 1):
                    term = (w1[i + r] * w1[j + r]) * img(t, ch, x + i, y + j)
                    expr = term if expr is None else expr + term
            img(t + 1, ch, x, y).EQUALS(expr)
