"""Isotropic acoustic finite-difference stencils ('iso3dfd').

Counterpart of the reference's historical flagship benchmark
(``src/stencils/Iso3dfdStencil.cpp:210,249``): order-``2*radius`` in space,
order-2 in time acoustic wave propagation —

    p(t+1) = 2·p(t) − p(t−1) + v(x,y,z)·∇²p(t)

with the Laplacian built from center FD coefficients
(``get_center_fd_coefficients``, the same public API the reference stencil
calls), and a sponge variant damping reflections near the boundary.
"""

from __future__ import annotations

from yask_tpu.utils.fd_coeff import get_center_fd_coefficients
from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_with_radius_base,
)


class Iso3dfdBase(yc_solution_with_radius_base):
    def _laplacian(self, p, t, x, y, z):
        """Order-2r Laplacian via 2nd-derivative center FD coefficients."""
        r = self.get_radius()
        c = get_center_fd_coefficients(2, r)  # 2r+1 coeffs, c[r] is center
        expr = 3.0 * c[r] * p(t, x, y, z)
        for i in range(1, r + 1):
            ci = c[r + i]  # symmetric: c[r-i] == c[r+i]
            expr = expr + ci * (p(t, x - i, y, z) + p(t, x + i, y, z)
                                + p(t, x, y - i, z) + p(t, x, y + i, z)
                                + p(t, x, y, z - i) + p(t, x, y, z + i))
        return expr


@register_solution
class Iso3dfdStencil(Iso3dfdBase):
    """'iso3dfd': plain second-order-in-time acoustic update."""

    def __init__(self, name: str = "iso3dfd", radius: int = 8):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        p = self.new_var("pressure", [t, x, y, z])
        vel = self.new_var("vel", [x, y, z])

        lap = self._laplacian(p, t, x, y, z)
        p(t + 1, x, y, z).EQUALS(
            2.0 * p(t, x, y, z) - p(t - 1, x, y, z)
            + vel(x, y, z) * lap)


@register_solution
class Iso3dfdSpongeStencil(Iso3dfdBase):
    """'iso3dfd_sponge': the same update multiplied by an absorbing-layer
    coefficient (the reference's sponge variant,
    ``Iso3dfdStencil.cpp:249``). The reference supports either 1-D
    per-dim factors or a full 3-D sponge var (``AwpStencil.cpp:34-100``);
    the TPU-native layout is the 3-D form — separable 1-D profiles fold
    into it at init time, and a full-dim coefficient rides the same
    lane-aligned DMA slabs as the field vars instead of forcing a
    pid-dependent lane gather that Mosaic cannot lower."""

    def __init__(self, name: str = "iso3dfd_sponge", radius: int = 8):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        p = self.new_var("pressure", [t, x, y, z])
        vel = self.new_var("vel", [x, y, z])
        # Absorbing coefficient (≤1 near boundaries, 1 inside); holds the
        # product of any separable per-dim tapers.
        sp = self.new_var("sponge", [x, y, z])

        lap = self._laplacian(p, t, x, y, z)
        nxt = (2.0 * p(t, x, y, z) - p(t - 1, x, y, z)
               + vel(x, y, z) * lap)
        p(t + 1, x, y, z).EQUALS(nxt * sp(x, y, z))
