"""Tilted-transverse-isotropy (TTI) seismic stencil — full formulation.

Counterpart of the reference's largest stencil
(``src/stencils/TTIStencil.cpp:37-62,1944``, the Devito-generated TTI from
the Fletcher–Du–Fowler pseudo-acoustic scheme): two coupled wavefields
``u``/``v`` second-order in time, square-slowness ``m``, boundary damping
``damp``, per-cell dip/azimuth angles ``theta``/``phi``, and Thomsen
parameters ``epsilon``/``delta``.

Where the reference hoists the per-cell trig into precomputed input vars
``ti0..ti3`` and inlines the twice-applied rotated derivative into ~2000
lines of generated expressions, this definition keeps the same computation
*generatively*:

* scratch vars ``ti0..ti3`` hold the per-cell trig (sin/cos of dip and
  azimuth), recomputed by the framework like any scratch stage;
* the rotated first derivative along the symmetry axis
  ``G(f) = sinθ·cosφ·Dx(f) + sinθ·sinφ·Dy(f) + cosθ·Dz(f)``
  is materialized into scratch vars ``gu``/``gv`` and applied twice
  (``Hz = G(G(f))``, reading the scratch with a full halo — the
  scratch-chain-with-halo pattern the reference's generated code walks);
* ``H0 = ∇² − Hz`` (the standard rotated-Laplacian split).

Time update (damped 2nd-order, the reference's ``temp6``/``temp10`` form
with dt = 0.88588, grid spacing h = 20 — derived, not transcribed):

  ``u+·(2m + damp·dt) = (damp·dt − 2m)·u− + 4m·u0
                        + 2dt²·((1+2ε)·H0(u) + √(1+2δ)·Hz(v))``
  ``v+·(2m + damp·dt) = (damp·dt − 2m)·v− + 4m·v0
                        + 2dt²·(√(1+2δ)·H0(u) + Hz(v))``

Supports any radius ≥ 1 (the reference hardcodes spatial order 4 and 8).
"""

from __future__ import annotations

from yask_tpu.utils.fd_coeff import get_center_fd_coefficients
from yask_tpu.compiler.expr import sin, cos, sqrt
from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_with_radius_base,
)

#: Devito-default discretization constants recovered from the reference's
#: generated coefficients (TTIStencil.cpp:289: 1/(0.8858…·damp + 2m);
#: first-derivative weight 2.5e-2 = 1/(2h) ⇒ h = 20).
DT = 0.8858795678228
H = 20.0


@register_solution
class TTIStencil(yc_solution_with_radius_base):
    def __init__(self, name: str = "tti", radius: int = 2):
        super().__init__(name, radius)

    # -- FD building blocks ---------------------------------------------

    def _d1(self, f, pt, dim):
        """Centered first derivative along one axis, order 2r, 1/h."""
        r = self.get_radius()
        c = get_center_fd_coefficients(1, r)
        expr = None
        for i in range(-r, r + 1):
            w = c[r + i] / H
            if w == 0.0:
                continue
            a = dict(pt)
            a[dim] = pt[dim] + i
            term = w * f(*a.values())
            expr = term if expr is None else expr + term
        return expr

    def _d2(self, f, pt, dim):
        """Centered second derivative along one axis, order 2r, 1/h²."""
        r = self.get_radius()
        c = get_center_fd_coefficients(2, r)
        expr = None
        for i in range(-r, r + 1):
            w = c[r + i] / (H * H)
            a = dict(pt)
            a[dim] = pt[dim] + i
            term = w * f(*a.values())
            expr = term if expr is None else expr + term
        return expr

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")

        u = self.new_var("u", [t, x, y, z])
        v = self.new_var("v", [t, x, y, z])
        m = self.new_var("m", [x, y, z])          # square slowness
        damp = self.new_var("damp", [x, y, z])    # boundary damping
        phi = self.new_var("phi", [x, y, z])      # azimuth
        theta = self.new_var("theta", [x, y, z])  # dip
        dlt = self.new_var("delta", [x, y, z])    # Thomsen δ
        eps = self.new_var("epsilon", [x, y, z])  # Thomsen ε

        # Per-cell trig of the tilt, as scratch temporaries (the
        # reference's hoisted ti0..ti3, TTIStencil.cpp:59-62: ti0=sinθ,
        # ti1=cosφ, ti2=cosθ, ti3=sinφ — recovered from the rotated-
        # derivative pattern ti0·ti1·Dx + ti0·ti3·Dy + ti2·Dz).
        ti0 = self.new_scratch_var("ti0", [x, y, z])
        ti1 = self.new_scratch_var("ti1", [x, y, z])
        ti2 = self.new_scratch_var("ti2", [x, y, z])
        ti3 = self.new_scratch_var("ti3", [x, y, z])
        ti0(x, y, z).EQUALS(sin(theta(x, y, z)))
        ti1(x, y, z).EQUALS(cos(phi(x, y, z)))
        ti2(x, y, z).EQUALS(cos(theta(x, y, z)))
        ti3(x, y, z).EQUALS(sin(phi(x, y, z)))

        pt_t = {"t": t, "x": x, "y": y, "z": z}
        pt = {"x": x, "y": y, "z": z}

        def G_of_field(f):
            """Rotated first derivative of a step var at time t."""
            return (ti0(x, y, z) * ti1(x, y, z) * self._d1(f, pt_t, "x")
                    + ti0(x, y, z) * ti3(x, y, z) * self._d1(f, pt_t, "y")
                    + ti2(x, y, z) * self._d1(f, pt_t, "z"))

        def G_of_scratch(g):
            """Second application: rotated derivative of the scratch
            holding the first application (read with full halo)."""
            return (ti0(x, y, z) * ti1(x, y, z) * self._d1(g, pt, "x")
                    + ti0(x, y, z) * ti3(x, y, z) * self._d1(g, pt, "y")
                    + ti2(x, y, z) * self._d1(g, pt, "z"))

        gu = self.new_scratch_var("gu", [x, y, z])
        gv = self.new_scratch_var("gv", [x, y, z])
        gu(x, y, z).EQUALS(G_of_field(u))
        gv(x, y, z).EQUALS(G_of_field(v))

        def lap(f):
            return (self._d2(f, pt_t, "x") + self._d2(f, pt_t, "y")
                    + self._d2(f, pt_t, "z"))

        hz_u = G_of_scratch(gu)
        hz_v = G_of_scratch(gv)
        h0_u = lap(u) - hz_u

        mm = m(x, y, z)
        dd = damp(x, y, z)
        e = eps(x, y, z)
        sq_d = sqrt(1.0 + 2.0 * dlt(x, y, z))
        inv = 1.0 / (2.0 * mm + dd * DT)
        back = dd * DT - 2.0 * mm
        two_dt2 = 2.0 * DT * DT

        u(t + 1, x, y, z).EQUALS(inv * (
            back * u(t - 1, x, y, z) + 4.0 * mm * u(t, x, y, z)
            + two_dt2 * ((1.0 + 2.0 * e) * h0_u + sq_d * hz_v)))
        v(t + 1, x, y, z).EQUALS(inv * (
            back * v(t - 1, x, y, z) + 4.0 * mm * v(t, x, y, z)
            + two_dt2 * (sq_d * h0_u + hz_v)))
