"""Tilted-transverse-isotropy (TTI) seismic stencil.

Counterpart of the reference's largest stencil
(``src/stencils/TTIStencil.cpp:1942``, ~1.9 kLoC): acoustic wave propagation
in tilted transversely isotropic media. This implementation uses the
standard coupled two-wavefield scheme (Fletcher–Du–Fowler-style): fields
``p`` and ``q`` advanced with rotated differential operators built from all
six second derivatives (xx, yy, zz, xy, xz, yz) combined through per-cell
direction cosines of the symmetry axis (dip ``theta``, azimuth ``phi``),
with Thomsen parameters ``epsilon``/``delta`` and velocity per cell.

Exercises what the reference's TTI exercises: very large expression trees,
cross-derivatives (diagonal halos), and many coefficient vars.
"""

from __future__ import annotations

from yask_tpu.utils.fd_coeff import get_center_fd_coefficients
from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_with_radius_base,
)


@register_solution
class TTIStencil(yc_solution_with_radius_base):
    def __init__(self, name: str = "tti", radius: int = 2):
        super().__init__(name, radius)

    # -- differential operators -----------------------------------------

    def _d2(self, f, t, x, y, z, dim):
        """Second derivative along one axis (center FD, order 2r)."""
        r = self.get_radius()
        c = get_center_fd_coefficients(2, r)
        args = {"x": x, "y": y, "z": z}
        expr = c[r] * f(t, x, y, z)
        for i in range(1, r + 1):
            lo = dict(args)
            hi = dict(args)
            lo[dim] = args[dim] - i
            hi[dim] = args[dim] + i
            expr = expr + c[r + i] * (f(t, lo["x"], lo["y"], lo["z"])
                                      + f(t, hi["x"], hi["y"], hi["z"]))
        return expr

    def _dcross(self, f, t, x, y, z, d1, d2):
        """Cross second derivative ∂²/∂d1∂d2 via the tensor product of
        first-derivative center coefficients (the reference builds its
        rotated operators from the same 6 second-derivative family)."""
        r = self.get_radius()
        c1 = get_center_fd_coefficients(1, r)
        args = {"x": x, "y": y, "z": z}
        expr = None
        for i in range(-r, r + 1):
            if c1[r + i] == 0.0:
                continue
            for j in range(-r, r + 1):
                if c1[r + j] == 0.0:
                    continue
                a = dict(args)
                a[d1] = args[d1] + i
                a[d2] = args[d2] + j
                term = (c1[r + i] * c1[r + j]) * f(t, a["x"], a["y"], a["z"])
                expr = term if expr is None else expr + term
        return expr

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")

        p = self.new_var("p", [t, x, y, z])
        q = self.new_var("q", [t, x, y, z])
        vel2 = self.new_var("vel2", [x, y, z])      # (v·dt)²
        eps = self.new_var("epsilon_", [x, y, z])   # Thomsen ε
        dlt = self.new_var("delta_", [x, y, z])     # Thomsen δ (as √(1+2δ))
        # direction cosines of the symmetry axis (precomputed from θ, φ —
        # the reference likewise consumes trig of the tilt per cell)
        ax_ = self.new_var("axis_x", [x, y, z])
        ay_ = self.new_var("axis_y", [x, y, z])
        az_ = self.new_var("axis_z", [x, y, z])

        def rotated_ops(f):
            """(H_perp, H_axis): Laplacian split into the component along
            the tilted symmetry axis and the orthogonal plane."""
            dxx = self._d2(f, t, x, y, z, "x")
            dyy = self._d2(f, t, x, y, z, "y")
            dzz = self._d2(f, t, x, y, z, "z")
            dxy = self._dcross(f, t, x, y, z, "x", "y")
            dxz = self._dcross(f, t, x, y, z, "x", "z")
            dyz = self._dcross(f, t, x, y, z, "y", "z")
            a, b, c = ax_(x, y, z), ay_(x, y, z), az_(x, y, z)
            h_axis = (a * a * dxx + b * b * dyy + c * c * dzz
                      + 2.0 * (a * b * dxy + a * c * dxz + b * c * dyz))
            lap = dxx + dyy + dzz
            return lap - h_axis, h_axis

        hp_perp, hp_axis = rotated_ops(p)
        hq_perp, hq_axis = rotated_ops(q)

        v2 = vel2(x, y, z)
        e = eps(x, y, z)
        d = dlt(x, y, z)

        p(t + 1, x, y, z).EQUALS(
            2.0 * p(t, x, y, z) - p(t - 1, x, y, z)
            + v2 * ((1.0 + 2.0 * e) * hp_perp + d * hq_axis))
        q(t + 1, x, y, z).EQUALS(
            2.0 * q(t, x, y, z) - q(t - 1, x, y, z)
            + v2 * (d * hp_perp + hq_axis))
