"""RTM-like pipeline stages (forward → imaging condition → smoothing).

The yask reference has no cross-solution composition at all — each
``REGISTER_SOLUTION`` stencil is a closed world.  Real RTM/FWI drivers
(ROADMAP items 2 and 4) chain several solutions per time step: a
forward wavefield propagator, an imaging-condition correlation that
accumulates ``p²`` (the zero-lag autocorrelation proxy used when the
receiver wavefield is the same shot), and a spatial smoothing filter
over the image.  These three stages are the headline chain for
``yask_tpu.ops.pipeline`` — each is an ordinary registered solution
runnable standalone, and the consumer stages declare their upstream
input as a *step-free read-only var* (``fwd_in`` / ``img_in``) that a
:class:`~yask_tpu.ops.pipeline.SolutionPipeline` binding replaces with
the producer's freshly-written field.

Stage shapes (all share ordered domain dims ``x, y, z`` and step ``t``):

* ``rtm_fwd``    — iso3dfd-style order-2r acoustic update (default
  radius 2 keeps the fused chain's margins small); per-stage read
  width r.
* ``rtm_img``    — pointwise ``img += fwd_in²``; read width 0.
* ``rtm_smooth`` — 3-point (radius-1) box average of ``img_in`` per
  dim; read width 1.

Fused analysis of the merged chain therefore has 3 stages with
per-stage widths ``(r, 0, 1)`` and ``fused_step_radius == r + 1``.
"""

from __future__ import annotations

from yask_tpu.utils.fd_coeff import get_center_fd_coefficients
from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_base,
    yc_solution_with_radius_base,
)


@register_solution
class RtmForwardStencil(yc_solution_with_radius_base):
    """'rtm_fwd': acoustic forward propagator (iso3dfd form, small
    default radius — the pipeline flagship wants cheap margins)."""

    def __init__(self, name: str = "rtm_fwd", radius: int = 2):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        p = self.new_var("pressure", [t, x, y, z])
        vel = self.new_var("vel", [x, y, z])

        r = self.get_radius()
        c = get_center_fd_coefficients(2, r)  # 2r+1 coeffs, c[r] center
        lap = 3.0 * c[r] * p(t, x, y, z)
        for i in range(1, r + 1):
            ci = c[r + i]
            lap = lap + ci * (p(t, x - i, y, z) + p(t, x + i, y, z)
                              + p(t, x, y - i, z) + p(t, x, y + i, z)
                              + p(t, x, y, z - i) + p(t, x, y, z + i))
        p(t + 1, x, y, z).EQUALS(
            2.0 * p(t, x, y, z) - p(t - 1, x, y, z)
            + vel(x, y, z) * lap)


@register_solution
class RtmImagingStencil(yc_solution_base):
    """'rtm_img': zero-lag imaging condition — accumulate the squared
    source wavefield into the image.  ``fwd_in`` has no step dim: it is
    the pipeline input slot a binding rewires to the producer's
    ``pressure``; standalone it is just a constant field."""

    def __init__(self, name: str = "rtm_img"):
        super().__init__(name)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        img = self.new_var("img", [t, x, y, z])
        fwd = self.new_var("fwd_in", [x, y, z])

        img(t + 1, x, y, z).EQUALS(
            img(t, x, y, z) + fwd(x, y, z) * fwd(x, y, z))


@register_solution
class RtmImagingPureStencil(yc_solution_base):
    """'rtm_img_pure': NON-accumulating imaging condition — the image
    is the squared source wavefield of the current shot step, with no
    ``img(t)`` self-read.  This is the push-memory flagship variant:
    in the merged chain every read of ``img__img`` is the smoothing
    stage's ``+1`` read, so the fused kernel can PUSH the image tile
    straight into the smoother and skip its HBM round-trip entirely
    (the accumulating ``rtm_img`` ring-reads itself and must keep its
    HBM state).  Physically this is the per-shot correlation before
    stacking — drivers that stack host-side use exactly this form."""

    def __init__(self, name: str = "rtm_img_pure"):
        super().__init__(name)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        img = self.new_var("img", [t, x, y, z])
        fwd = self.new_var("fwd_in", [x, y, z])

        img(t + 1, x, y, z).EQUALS(fwd(x, y, z) * fwd(x, y, z))


@register_solution
class RtmSmoothStencil(yc_solution_base):
    """'rtm_smooth': 3-point box average of the image per dim (the
    post-imaging low-pass every RTM driver applies).  ``img_in`` is the
    pipeline input slot for the imaging stage's ``img``."""

    def __init__(self, name: str = "rtm_smooth"):
        super().__init__(name)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        sm = self.new_var("smooth", [t, x, y, z])
        img = self.new_var("img_in", [x, y, z])

        expr = None
        for dx in (-1, 0, 1):
            for dy in (-1, 0, 1):
                for dz in (-1, 0, 1):
                    term = img(x + dx, y + dy, z + dz)
                    expr = term if expr is None else expr + term
        sm(t + 1, x, y, z).EQUALS(expr / 27.0)
