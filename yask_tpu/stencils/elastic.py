"""Staggered-grid elastic wave stencils ('ssg', 'fsg').

Counterpart of the reference's elastic families
(``src/stencils/SSGElasticStencil.cpp:195``, ``FSGElasticStencil.cpp:562``,
shared bases in ``ElasticStencil/*.hpp``): velocity–stress formulation on a
staggered grid, two stages per step (stress reads the velocities updated in
the same step — the same-step dependency that forces stage ordering), with
density interpolation at staggered positions.

Derivative weights at half-grid points come from
``get_arbitrary_fd_coefficients`` (Fornberg at x0=0 with samples at
±(k−½)) — the generic form of the reference's hard-coded 9/8, −1/24
staggered coefficients (recovered exactly at radius 2).
"""

from __future__ import annotations

from yask_tpu.utils.fd_coeff import get_arbitrary_fd_coefficients
from yask_tpu.compiler.solution_base import (
    register_solution,
    yc_solution_with_radius_base,
)


class ElasticBase(yc_solution_with_radius_base):
    """Shared helpers (reference ``ElasticStencilBase``)."""

    def _stag_coeffs(self):
        r = self.get_radius()
        pts = [i + 0.5 for i in range(-r, r)]
        return get_arbitrary_fd_coefficients(1, 0.0, pts)

    def _dstag(self, v, t, idxs, dim_pos, shift):
        """Staggered first derivative of var access ``v(t, *idxs)`` along
        the ``dim_pos``-th domain index; ``shift``∈{0,1} selects the
        half-point side (forward-staggered when 1)."""
        c = self._stag_coeffs()
        r = self.get_radius()
        expr = None
        for k in range(2 * r):
            off = k - r + shift  # samples at ±(k-1/2) relative to target
            args = list(idxs)
            args[dim_pos] = args[dim_pos] + off
            term = c[k] * v(t, *args)
            expr = term if expr is None else expr + term
        return expr

    def _avg2(self, m, idxs, dim_pos):
        a = list(idxs)
        a[dim_pos] = a[dim_pos] + 1
        return 0.5 * (m(*idxs) + m(*a))


@register_solution
class SSGElasticStencil(ElasticBase):
    """'ssg': standard staggered-grid isotropic elastic (velocity + 6
    stresses, Lamé parameters λ, μ and density ρ)."""

    def __init__(self, name: str = "ssg", radius: int = 2):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        d = (x, y, z)

        v = {c: self.new_var(f"v_{c}", [t, x, y, z]) for c in "xyz"}
        s = {c: self.new_var(f"s_{c}", [t, x, y, z])
             for c in ("xx", "yy", "zz", "xy", "xz", "yz")}
        rho = self.new_var("rho", [x, y, z])
        lam = self.new_var("lambda_", [x, y, z])
        mu = self.new_var("mu", [x, y, z])
        # Time step × grid spacing ratio baked to 1 like the reference
        # (delta_t/h handled by the user scaling the material vars).

        ax = {"x": 0, "y": 1, "z": 2}

        # Stage 1: velocity update v(t+1) = v(t) + (1/ρ̄)·div σ(t).
        # Each velocity component lives at a different staggered position;
        # density is interpolated there (reference interp helpers).
        for c in "xyz":
            i = ax[c]
            buoy = 1.0 / self._avg2(rho, d, i)
            names = {"x": ("xx", "xy", "xz"),
                     "y": ("xy", "yy", "yz"),
                     "z": ("xz", "yz", "zz")}[c]
            div = self._dstag(s[names[0]], t, d, 0, 1 if c == "x" else 0)
            div = div + self._dstag(s[names[1]], t, d, 1,
                                    1 if c == "y" else 0)
            div = div + self._dstag(s[names[2]], t, d, 2,
                                    1 if c == "z" else 0)
            v[c](t + 1, x, y, z).EQUALS(v[c](t, x, y, z) + buoy * div)

        # Stage 2: stress update from strain rates of v(t+1).
        dvv = {}
        for c in "xyz":
            for j in "xyz":
                # derivative of v_c along axis j at the stress position.
                shift = 0 if c == j else 1
                dvv[(c, j)] = self._dstag(v[c], t + 1, d, ax[j], shift)

        tr = dvv[("x", "x")] + dvv[("y", "y")] + dvv[("z", "z")]
        for c in "xyz":
            cc = c + c
            s[cc](t + 1, x, y, z).EQUALS(
                s[cc](t, x, y, z) + lam(x, y, z) * tr
                + 2.0 * mu(x, y, z) * dvv[(c, c)])
        for a, b in (("x", "y"), ("x", "z"), ("y", "z")):
            nm = a + b
            mu_i = self._avg2(mu, d, ax[a])
            s[nm](t + 1, x, y, z).EQUALS(
                s[nm](t, x, y, z)
                + mu_i * (dvv[(a, b)] + dvv[(b, a)]))


@register_solution
class SSG2ElasticStencil(SSGElasticStencil):
    """'ssg2': the reference's v2-base variant of the SSG solution
    (``SSGElastic2Stencil.cpp:160``); same physics, registered separately
    so command lines using either name work."""

    def __init__(self):
        super().__init__("ssg2", radius=2)


@register_solution
class SSGMergedElasticStencil(SSGElasticStencil):
    """'ssg_merged': the merged-equation variant
    (``SSGElastic2Stencil.cpp:169``). On TPU the distinction is moot —
    XLA fuses either form into the same kernels — so this registers the
    same equations under the merged name for CLI parity."""

    def __init__(self):
        super().__init__("ssg_merged", radius=2)


@register_solution
class FSGElasticStencil(ElasticBase):
    """'fsg': fully-staggered anisotropic elastic with an orthorhombic
    stiffness tensor (c11…c66 material vars), the structural analog of the
    reference's FSG family (``FSGElasticStencil.cpp``)."""

    def __init__(self, name: str = "fsg", radius: int = 2):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        d = (x, y, z)
        ax = {"x": 0, "y": 1, "z": 2}

        v = {c: self.new_var(f"v_{c}", [t, x, y, z]) for c in "xyz"}
        s = {c: self.new_var(f"s_{c}", [t, x, y, z])
             for c in ("xx", "yy", "zz", "xy", "xz", "yz")}
        rho = self.new_var("rho", [x, y, z])
        C = {nm: self.new_var(f"c{nm}", [x, y, z])
             for nm in ("11", "12", "13", "22", "23", "33",
                        "44", "55", "66")}

        for c in "xyz":
            i = ax[c]
            buoy = 1.0 / self._avg2(rho, d, i)
            names = {"x": ("xx", "xy", "xz"),
                     "y": ("xy", "yy", "yz"),
                     "z": ("xz", "yz", "zz")}[c]
            div = self._dstag(s[names[0]], t, d, 0, 1 if c == "x" else 0)
            div = div + self._dstag(s[names[1]], t, d, 1,
                                    1 if c == "y" else 0)
            div = div + self._dstag(s[names[2]], t, d, 2,
                                    1 if c == "z" else 0)
            v[c](t + 1, x, y, z).EQUALS(v[c](t, x, y, z) + buoy * div)

        e = {}
        for c in "xyz":
            for j in "xyz":
                shift = 0 if c == j else 1
                e[(c, j)] = self._dstag(v[c], t + 1, d, ax[j], shift)

        exx, eyy, ezz = e[("x", "x")], e[("y", "y")], e[("z", "z")]
        s["xx"](t + 1, x, y, z).EQUALS(
            s["xx"](t, x, y, z) + C["11"](x, y, z) * exx
            + C["12"](x, y, z) * eyy + C["13"](x, y, z) * ezz)
        s["yy"](t + 1, x, y, z).EQUALS(
            s["yy"](t, x, y, z) + C["12"](x, y, z) * exx
            + C["22"](x, y, z) * eyy + C["23"](x, y, z) * ezz)
        s["zz"](t + 1, x, y, z).EQUALS(
            s["zz"](t, x, y, z) + C["13"](x, y, z) * exx
            + C["23"](x, y, z) * eyy + C["33"](x, y, z) * ezz)
        s["yz"](t + 1, x, y, z).EQUALS(
            s["yz"](t, x, y, z)
            + C["44"](x, y, z) * (e[("y", "z")] + e[("z", "y")]))
        s["xz"](t + 1, x, y, z).EQUALS(
            s["xz"](t, x, y, z)
            + C["55"](x, y, z) * (e[("x", "z")] + e[("z", "x")]))
        s["xy"](t + 1, x, y, z).EQUALS(
            s["xy"](t, x, y, z)
            + C["66"](x, y, z) * (e[("x", "y")] + e[("y", "x")]))


@register_solution
class FSG2ElasticStencil(FSGElasticStencil):
    """'fsg2': v2-base variant name of the FSG solution
    (``FSGElastic2Stencil.cpp:502``)."""

    def __init__(self):
        super().__init__("fsg2", radius=2)


@register_solution
class FSGElasticABCStencil(ElasticBase):
    """'fsg_abc': FSG with an absorbing-boundary damping coefficient (3-D
    sponge var, the reference's ``AwpStencil.cpp:34-100`` alternative
    form; separable per-dim tapers fold into it at init — the TPU-native
    layout, since a full-dim coefficient rides lane-aligned DMA slabs)."""

    def __init__(self, name: str = "fsg_abc", radius: int = 2):
        super().__init__(name, radius)

    def define(self):
        t = self.new_step_index("t")
        x = self.new_domain_index("x")
        y = self.new_domain_index("y")
        z = self.new_domain_index("z")
        d = (x, y, z)
        ax = {"x": 0, "y": 1, "z": 2}

        v = {c: self.new_var(f"v_{c}", [t, x, y, z]) for c in "xyz"}
        s = {c: self.new_var(f"s_{c}", [t, x, y, z])
             for c in ("xx", "yy", "zz", "xy", "xz", "yz")}
        rho = self.new_var("rho", [x, y, z])
        C = {nm: self.new_var(f"c{nm}", [x, y, z])
             for nm in ("11", "12", "13", "22", "23", "33",
                        "44", "55", "66")}
        sp = self.new_var("sponge", [x, y, z])

        def damp(expr):
            return expr * sp(x, y, z)

        for c in "xyz":
            i = ax[c]
            buoy = 1.0 / self._avg2(rho, d, i)
            names = {"x": ("xx", "xy", "xz"),
                     "y": ("xy", "yy", "yz"),
                     "z": ("xz", "yz", "zz")}[c]
            div = self._dstag(s[names[0]], t, d, 0, 1 if c == "x" else 0)
            div = div + self._dstag(s[names[1]], t, d, 1,
                                    1 if c == "y" else 0)
            div = div + self._dstag(s[names[2]], t, d, 2,
                                    1 if c == "z" else 0)
            v[c](t + 1, x, y, z).EQUALS(
                damp(v[c](t, x, y, z) + buoy * div))

        e = {}
        for c in "xyz":
            for j in "xyz":
                shift = 0 if c == j else 1
                e[(c, j)] = self._dstag(v[c], t + 1, d, ax[j], shift)

        exx, eyy, ezz = e[("x", "x")], e[("y", "y")], e[("z", "z")]
        s["xx"](t + 1, x, y, z).EQUALS(
            s["xx"](t, x, y, z) + C["11"](x, y, z) * exx
            + C["12"](x, y, z) * eyy + C["13"](x, y, z) * ezz)
        s["yy"](t + 1, x, y, z).EQUALS(
            s["yy"](t, x, y, z) + C["12"](x, y, z) * exx
            + C["22"](x, y, z) * eyy + C["23"](x, y, z) * ezz)
        s["zz"](t + 1, x, y, z).EQUALS(
            s["zz"](t, x, y, z) + C["13"](x, y, z) * exx
            + C["23"](x, y, z) * eyy + C["33"](x, y, z) * ezz)
        s["yz"](t + 1, x, y, z).EQUALS(
            s["yz"](t, x, y, z)
            + C["44"](x, y, z) * (e[("y", "z")] + e[("z", "y")]))
        s["xz"](t + 1, x, y, z).EQUALS(
            s["xz"](t, x, y, z)
            + C["55"](x, y, z) * (e[("x", "z")] + e[("z", "x")]))
        s["xy"](t + 1, x, y, z).EQUALS(
            s["xy"](t, x, y, z)
            + C["66"](x, y, z) * (e[("x", "y")] + e[("y", "x")]))


@register_solution
class FSG2ElasticABCStencil(FSGElasticABCStencil):
    """'fsg2_abc': v2-base name of the FSG ABC variant."""

    def __init__(self):
        super().__init__("fsg2_abc", radius=2)


@register_solution
class FSGMergedElasticStencil(FSG2ElasticStencil):
    """Back-compat alias of fsg2 (reference ``FSGElasticMStencil``,
    ``FSGElastic2Stencil.cpp:510``)."""

    def __init__(self):
        super().__init__()
        self._soln._name = "fsg_merged"


@register_solution
class FSGMergedABCElasticStencil(FSG2ElasticABCStencil):
    """Back-compat alias of fsg2_abc (reference ``FSGABCElasticMStencil``,
    ``FSGElastic2Stencil.cpp:517``)."""

    def __init__(self):
        super().__init__()
        self._soln._name = "fsg_merged_abc"
