"""Portable, atomic solution checkpoints + the mode-degradation ladder.

A checkpoint is a schema-versioned snapshot (``yask_tpu.checkpoint/1``)
of the FULL ring state of a prepared solution, saved by INTERIOR
coordinates.  That rides the same invariant ``set_elements_in_seq`` /
``init_solution_vars`` ride: physical-boundary ghost cells are
identically ZERO in every execution mode, and differently-padded
contexts that agree on interiors are the same simulation.  So a
snapshot taken under one mode/padding restores bit-identically into
any other — save under ``jit``, resume under ``shard_pallas`` — which
is what makes the in-run degradation ladder
(:meth:`StencilContext._run_supervised`) and cross-process kill-resume
possible at all.

Two layers:

* in-memory: :func:`extract_snapshot` / :func:`apply_snapshot` — the
  supervision loop's rollback target (no disk I/O on the fault path
  beyond what the cadence already paid);
* on disk: :func:`save_checkpoint` / :func:`restore_checkpoint` — an
  atomic ``.npz`` (written to a tmp file + ``os.replace``, so a dying
  process can only ever leave the previous complete checkpoint or a
  stray tmp, never a torn one under the real name).  ``restore``
  returns ``False`` on ANY problem — missing, torn, corrupt, stale
  schema, wrong solution/geometry — because the fallback is always a
  fresh run, never a crash (fault sites ``ckpt.save`` /
  ``ckpt.restore`` inject exactly these failures in tests).

The npz payload is one array per ring slot (``{var}__slot{i}``,
oldest→newest) plus ``__meta__``, the JSON header as uint8 bytes.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np

from yask_tpu.resilience.faults import fault_point

__all__ = [
    "CKPT_SCHEMA", "extract_snapshot", "apply_snapshot",
    "save_checkpoint", "restore_checkpoint", "peek_checkpoint",
    "snapshot_mismatches", "default_ckpt_dir", "degradation_ladder",
]

CKPT_SCHEMA = "yask_tpu.checkpoint/1"

#: On a classified fault, retry the run under progressively simpler
#: modes: the distributed-fused flagship first sheds fusion, then the
#: mesh; single-device pallas sheds Mosaic for plain XLA.  ``jit`` is
#: the floor (and ``ref`` is an oracle, not a production mode — it
#: never degrades).
_LADDER = {
    "shard_pallas": ("shard_map", "jit"),
    "shard_map": ("jit",),
    "sharded": ("jit",),
    "pallas": ("jit",),
}


def degradation_ladder(mode: str) -> List[str]:
    """Fallback modes to try, in order, when ``mode`` faults mid-run."""
    return list(_LADDER.get(mode, ()))


def default_ckpt_dir() -> str:
    """Checkpoint directory from ``YT_CKPT_DIR`` ("" = no default)."""
    return os.environ.get("YT_CKPT_DIR", "")


def _interior_index(g, gsz):
    """Index tuple selecting the interior of one padded slot array
    (domain axes clipped to the global sizes, misc/step axes whole) —
    the same geometry walk ``compare_data`` and the trace dumps use."""
    return tuple(
        slice(g.origin[dn], g.origin[dn] + gsz[dn])
        if kind == "domain" else slice(None)
        for dn, kind in g.axes)


def extract_snapshot(ctx) -> Dict:
    """Host-side snapshot of ``ctx``'s full ring state by interior
    coordinates: ``{"meta": {...}, "state": {var: [slot, ...]}}``.
    The context must be prepared; device/resident state is materialized
    first."""
    ctx._check_prepared()
    ctx._materialize_state()
    gsz = ctx._opts.global_domain_sizes
    meta = {
        "schema": CKPT_SCHEMA,
        "solution": ctx.get_name(),
        "dtype": str(np.dtype(ctx._program.dtype).name),
        "domain": {d: int(gsz[d]) for d in ctx.get_domain_dim_names()},
        "rings": {},
        "axes": {},
        "cur_step": int(ctx._cur_step),
        "steps_done": int(ctx._steps_done),
    }
    state = {}
    for name, ring in ctx._state.items():
        g = ctx._program.geoms[name]
        idx = _interior_index(g, gsz)
        meta["rings"][name] = len(ring)
        meta["axes"][name] = [dn for dn, _ in g.axes]
        state[name] = [np.ascontiguousarray(np.asarray(a)[idx])
                       for a in ring]
    return {"meta": meta, "state": state}


def apply_snapshot(ctx, snap: Dict) -> bool:
    """Restore a snapshot into a prepared context — possibly one with a
    DIFFERENT mode/padding than the snapshot was taken under.  Each
    slot is rebuilt as a zero padded array (the ghost-zero invariant)
    with the snapshot interior set against the context's CURRENT
    geometry, then pushed to device through the normal path (shardings
    apply automatically).  Returns ``False`` — never raises — on any
    identity mismatch (schema, solution, dtype, domain sizes, ring
    depths, axis order): the caller's fallback is a fresh run."""
    try:
        meta, state = snap["meta"], snap["state"]
        if meta.get("schema") != CKPT_SCHEMA:
            return False
        ctx._check_prepared()
        if meta.get("solution") != ctx.get_name():
            return False
        dtype = ctx._program.dtype
        if meta.get("dtype") != str(np.dtype(dtype).name):
            return False
        gsz = ctx._opts.global_domain_sizes
        dom = meta.get("domain", {})
        for d in ctx.get_domain_dim_names():
            if int(dom.get(d, -1)) != int(gsz[d]):
                return False
        ctx._materialize_state()
        rings = meta.get("rings", {})
        if set(rings) != set(ctx._state):
            return False
        new_state = {}
        for name, ring in ctx._state.items():
            g = ctx._program.geoms[name]
            if int(rings[name]) != len(ring):
                return False
            if meta.get("axes", {}).get(name) != [dn for dn, _ in g.axes]:
                return False
            idx = _interior_index(g, gsz)
            slots = []
            for i in range(len(ring)):
                a = np.asarray(state[name][i])
                dst = np.zeros(tuple(g.shape), dtype=dtype)
                if a.shape != dst[idx].shape:
                    return False
                dst[idx] = a
                slots.append(dst)
            new_state[name] = slots
    except Exception:  # noqa: BLE001 - any malformed snapshot → False
        return False
    ctx._state = new_state
    ctx._state_on_device = False
    ctx._state_to_device()
    ctx._cur_step = int(meta.get("cur_step", 0))
    ctx._steps_done = int(meta.get("steps_done", 0))
    return True


def save_checkpoint(ctx, path: str) -> str:
    """Atomically write ``ctx``'s snapshot to ``path``.  The npz is
    written to ``path + ".tmp"`` through an open file object (so numpy
    cannot append ``.npz`` and break atomicity) and renamed into place.
    Fault site ``ckpt.save``; span ``ckpt.save`` (phase
    ``checkpoint``)."""
    from yask_tpu.obs.tracer import span
    with span("ckpt.save", phase="checkpoint", path=path) as sp:
        fault_point("ckpt.save")
        snap = extract_snapshot(ctx)
        payload = {"__meta__": np.frombuffer(
            json.dumps(snap["meta"], sort_keys=True).encode(),
            dtype=np.uint8)}
        for name, ring in snap["state"].items():
            for i, a in enumerate(ring):
                payload[f"{name}__slot{i}"] = a
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        sp.set(step=int(snap["meta"].get("cur_step", 0)),
               nvars=len(snap["state"]))
    return path


def peek_checkpoint(path: str) -> Optional[Dict]:
    """Read just the meta header of a checkpoint file; ``None`` when the
    file is missing, unreadable, or not this schema."""
    try:
        with np.load(path) as data:
            meta = json.loads(bytes(data["__meta__"]).decode())
    except Exception:  # noqa: BLE001
        return None
    if not isinstance(meta, dict) or meta.get("schema") != CKPT_SCHEMA:
        return None
    return meta


def restore_checkpoint(ctx, path: str) -> bool:
    """Load ``path`` and apply it to ``ctx``.  Returns ``False`` — never
    raises — when the file is missing/torn/corrupt, carries a stale
    schema, or does not match the context's identity: the caller falls
    back to a fresh run.  Fault site ``ckpt.restore``; span
    ``ckpt.restore`` (phase ``checkpoint``)."""
    from yask_tpu.obs.tracer import span
    with span("ckpt.restore", phase="checkpoint", path=path) as sp:
        fault_point("ckpt.restore")
        try:
            with np.load(path) as data:
                meta = json.loads(bytes(data["__meta__"]).decode())
                if not isinstance(meta, dict) \
                        or meta.get("schema") != CKPT_SCHEMA:
                    sp.set(ok=False)
                    return False
                state = {}
                for name, nslots in meta.get("rings", {}).items():
                    state[name] = [np.array(data[f"{name}__slot{i}"])
                                   for i in range(int(nslots))]
        except Exception:  # noqa: BLE001 - torn/corrupt → fresh run
            sp.set(ok=False)
            return False
        ok = apply_snapshot(ctx, {"meta": meta, "state": state})
        sp.set(ok=bool(ok))
        return ok


def snapshot_mismatches(a: Dict, b: Dict, epsilon: float = 1e-4,
                        abs_epsilon: float = 1e-7) -> int:
    """Count mismatching interior points between two snapshots with
    ``compare_data``'s mixed tolerance (|x−y| > abs_eps +
    eps·max(|x|,|y|)); shape/var-set disagreements count every point.
    The cross-process acceptance tests compare a resumed child run's
    final snapshot against an uninterrupted twin with this."""
    bad = 0
    sa, sb = a.get("state", {}), b.get("state", {})
    for name in set(sa) | set(sb):
        ra, rb = sa.get(name, []), sb.get(name, [])
        if len(ra) != len(rb):
            bad += sum(int(np.asarray(x).size) for x in ra + rb)
            continue
        for x, y in zip(ra, rb):
            x = np.asarray(x, dtype=np.float64)
            y = np.asarray(y, dtype=np.float64)
            if x.shape != y.shape:
                bad += x.size + y.size
                continue
            tol = abs_epsilon + epsilon * np.maximum(np.abs(x), np.abs(y))
            bad += int((np.abs(x - y) > tol).sum())
    return bad
