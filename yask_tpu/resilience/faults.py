"""Fault taxonomy, classification, outage breaker, and fault injection.

The TPU relay is flaky and hardware windows are short (CLAUDE.md
"Environment gotchas"; round 3 lost a 26-case matrix mid-run and
crashed the joint tuner on a Mosaic OOM).  Every device-facing producer
used to reinvent its own failure handling — ``bench._probe_platform``'s
killable subprocess, the auto-tuner's message-sniffing 3-failure
breaker, per-stage ``except Exception`` blocks in ``tpu_session``.
This module is the one shared policy:

* a small closed **taxonomy** of :class:`Fault` subclasses
  (:class:`RelayDown`, :class:`DeviceHang`, :class:`CompilerOOM`,
  :class:`CompileFailed`, :class:`ResultAnomaly`);
* :func:`classify` mapping raw backend exceptions onto it (the message
  signatures were probed on real v5e sessions — see the auto-tuner's
  round-3 OOM postmortem);
* :class:`Breaker` — the consecutive-failure circuit breaker (a dead
  relay makes EVERY attempt fail; three in a row must stay loud
  instead of silently striking out the whole walk/matrix);
* **fault injection** via the ``YT_FAULT_PLAN`` environment variable:
  named call sites invoke :func:`fault_point` / :func:`maybe_corrupt`
  so hangs, relay drops, compiler OOMs, and corrupted (all-zero/NaN)
  outputs can be driven by fast CPU tests — the machinery that guards
  rare hardware windows must itself be testable without hardware.

``YT_FAULT_PLAN`` accepts JSON (``[{"site": "session.validate.*",
"kind": "relay_drop", "after": 2, "times": 99}]``) or the compact form
``site:kind[:times[:after]]`` with ``;`` between entries.  ``site``
patterns are :mod:`fnmatch` globs against the site names listed in
``docs/resilience.md``.  Each entry fires on hits ``after < n <=
after + times`` of a matching site, counted per process.
"""

from __future__ import annotations

import fnmatch
import json
import os
import time
from typing import Dict, List, Optional

__all__ = [
    "Fault", "RelayDown", "DeviceHang", "CompilerOOM", "CompileFailed",
    "ResultAnomaly", "WorkerDead", "WorkerUnhealthy", "LoadSpike",
    "FAULT_KINDS",
    "classify", "classify_message", "Breaker", "default_breaker_path",
    "fault_point", "maybe_corrupt", "reset_faults", "active_plan",
]


class Fault(Exception):
    """Base of the closed fault taxonomy.  Carries the site that raised
    it and (when classified from a raw exception) the original cause."""

    kind = "fault"

    def __init__(self, msg: str, site: Optional[str] = None,
                 cause: Optional[BaseException] = None):
        super().__init__(msg)
        self.site = site
        self.cause = cause


class RelayDown(Fault):
    """The TPU relay (or transport to it) is unreachable: connection
    resets, RST_STREAM terminations, gRPC UNAVAILABLE/DEADLINE errors.
    Retryable — the relay comes and goes in windows."""
    kind = "relay_down"


class DeviceHang(Fault):
    """Work exceeded its deadline (backend init or a compile/dispatch
    that never returns).  Retryable once; repeated hangs mean the
    window is gone."""
    kind = "device_hang"


class CompilerOOM(Fault):
    """Mosaic VMEM exhaustion (register-allocator spill slots over
    ``vmem_limit_bytes`` — the round-3 crash class).  NOT retryable and
    never an outage signal: the candidate is genuinely infeasible."""
    kind = "compiler_oom"


class CompileFailed(Fault):
    """Backend/Mosaic compile failure without a VMEM signature.  Not
    retryable per-candidate, but consecutive failures feed the outage
    breaker (a dead relay surfaces as INTERNAL compile errors)."""
    kind = "compile_failed"


class ResultAnomaly(Fault):
    """Device work returned values that fail the sanity guards
    (all-zero field, NaN/Inf, oracle mismatch — the round-3 all-zero
    quick-matrix incident)."""
    kind = "result_anomaly"


class WorkerDead(Fault):
    """A serve-fleet worker process exited (crash, OOM-kill, injected
    chaos kill).  Not retryable against the dead worker; the fleet
    supervisor fails the routed sessions over to a replacement."""
    kind = "worker_dead"


class WorkerUnhealthy(Fault):
    """A serve-fleet worker missed its heartbeat/liveness deadline
    (hung pipe, wedged backend) without exiting.  The supervisor
    SIGKILLs the process group and treats it as :class:`WorkerDead`."""
    kind = "worker_unhealthy"


class LoadSpike(Fault):
    """An injected traffic burst: the load harness probes
    ``fault_point("load.arrival")`` before each open-loop arrival and
    answers a raised LoadSpike with an immediate burst of extra
    requests.  Unlike the device faults this is demand-side chaos —
    nothing is broken, the offered load just jumped — so it is never
    retryable and never feeds the outage breaker."""
    kind = "load_spike"


FAULT_KINDS = {cls.kind: cls for cls in
               (RelayDown, DeviceHang, CompilerOOM, CompileFailed,
                ResultAnomaly, WorkerDead, WorkerUnhealthy, LoadSpike)}

# Message signatures, most specific first.  A Mosaic OOM message also
# matches the INTERNAL/compile signs, so the OOM test must win (the
# auto-tuner's round-3 postmortem ordering).
_OOM_SIGNS = ("RESOURCE_EXHAUSTED",)
_OOM_SIGNS_LOWER = ("vmem",)
_RELAY_SIGNS = ("DEADLINE_EXCEEDED", "UNAVAILABLE", "RST_STREAM",
                "stream terminated", "failed to connect",
                "Connection reset", "Socket closed", "socket closed",
                "relay")
_COMPILE_SIGNS = ("Mosaic", "INTERNAL", "tpu_compile")


def classify_message(msg: str) -> Optional[type]:
    """Map an exception message onto a Fault class (None = unknown)."""
    low = msg.lower()
    if any(s in msg for s in _OOM_SIGNS) \
            or any(s in low for s in _OOM_SIGNS_LOWER):
        return CompilerOOM
    if any(s in msg for s in _RELAY_SIGNS):
        return RelayDown
    if any(s in msg for s in _COMPILE_SIGNS):
        return CompileFailed
    return None


def classify(exc: BaseException,
             site: Optional[str] = None) -> Optional[Fault]:
    """Classify a raw exception into the taxonomy.

    Fault instances pass through unchanged (injection raises them
    directly); anything else is classified by message signature.
    Returns None for exceptions that are not a device/relay failure —
    callers must re-raise those (a ``KeyError`` in our own code must
    never be retried as if the relay blinked)."""
    if isinstance(exc, Fault):
        return exc
    cls = classify_message(f"{type(exc).__name__}: {exc}")
    if cls is None:
        return None
    f = cls(f"{type(exc).__name__}: {exc}", site=site, cause=exc)
    return f


def default_breaker_path() -> str:
    """Sidecar file for persistent breaker state (``YT_BREAKER_STATE``
    overrides; default ``BREAKER_STATE.json`` next to the journal)."""
    explicit = os.environ.get("YT_BREAKER_STATE")
    if explicit:
        return explicit
    from yask_tpu.resilience.journal import repo_root
    return os.path.join(repo_root(), "BREAKER_STATE.json")


class Breaker:
    """Consecutive-failure circuit breaker (the auto-tuner's 3-failure
    rule, hoisted to one shared definition).  ``record`` faults as they
    happen and ``reset`` on any success; once ``tripped``, the caller
    should abort the enclosing walk/session — every further attempt is
    burning a hardware window against a dead relay.

    With ``path`` set, state (count + last fault kind) persists to an
    atomic JSON sidecar and is reloaded on construction, so a
    ``tpu_watch.sh`` restart does not reset an open breaker and
    immediately re-burn a relay window.  A fresh successful relay
    probe is the legitimate reset (the watcher calls ``reset()`` then).
    Sidecar I/O failures are swallowed: persistence is a convenience,
    never a new failure mode."""

    def __init__(self, threshold: int = 3, path: Optional[str] = None):
        self.threshold = threshold
        self.path = path
        self.consecutive = 0
        self.last: Optional[Fault] = None
        if path:
            self._load()

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                d = json.load(f)
            self.consecutive = max(0, int(d.get("consecutive", 0)))
            cls = FAULT_KINDS.get(d.get("last_kind", ""))
            if cls is not None:
                self.last = cls(str(d.get("last_msg", "")))
        except (OSError, ValueError, TypeError):
            pass

    def _persist(self) -> None:
        if not self.path:
            return
        try:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"consecutive": self.consecutive,
                           "threshold": self.threshold,
                           "tripped": self.tripped,
                           "last_kind": getattr(self.last, "kind", None),
                           "last_msg": (str(self.last)[:200]
                                        if self.last else ""),
                           "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                               time.gmtime())}, f)
            os.replace(tmp, self.path)
        except OSError:
            pass

    def record(self, fault: Fault) -> bool:
        """Count one fault; returns whether the breaker is now open."""
        self.consecutive += 1
        self.last = fault
        self._persist()
        return self.tripped

    def reset(self) -> None:
        self.consecutive = 0
        self._persist()

    @property
    def tripped(self) -> bool:
        return self.consecutive >= self.threshold


# ---------------------------------------------------------------------------
# fault injection (YT_FAULT_PLAN)

#: corruption kinds understood by maybe_corrupt (everything else raises
#: at fault_point).
_CORRUPT_KINDS = ("zero_output", "nan_output")

_STATE: Dict = {"raw": None, "entries": []}


def _parse_plan(raw: str) -> List[Dict]:
    raw = raw.strip()
    if not raw:
        return []
    if raw.startswith("["):
        entries = json.loads(raw)
    else:
        entries = []
        for part in raw.split(";"):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) < 2:
                raise ValueError(
                    f"YT_FAULT_PLAN entry {part!r}: want site:kind"
                    "[:times[:after]]")
            e = {"site": bits[0], "kind": bits[1]}
            if len(bits) > 2:
                e["times"] = int(bits[2])
            if len(bits) > 3:
                e["after"] = int(bits[3])
            entries.append(e)
    out = []
    for e in entries:
        kind = e.get("kind", "")
        if kind not in FAULT_KINDS and kind not in _CORRUPT_KINDS \
                and kind not in ("exception", "hang"):
            raise ValueError(f"YT_FAULT_PLAN: unknown fault kind "
                             f"{kind!r}")
        out.append({"site": e.get("site", "*"), "kind": kind,
                    "times": int(e.get("times", 1)),
                    "after": int(e.get("after", 0)),
                    "secs": float(e.get("secs", 3600.0)),
                    "_seen": 0})
    return out


def _entries() -> List[Dict]:
    raw = os.environ.get("YT_FAULT_PLAN", "")
    if raw != _STATE["raw"]:
        _STATE["raw"] = raw
        _STATE["entries"] = _parse_plan(raw)
    return _STATE["entries"]


def reset_faults() -> None:
    """Forget parsed plan + hit counters (test isolation helper)."""
    _STATE["raw"] = None
    _STATE["entries"] = []


def active_plan() -> List[Dict]:
    """The parsed injection entries (empty without YT_FAULT_PLAN)."""
    return list(_entries())


def _firing(site: str, kinds=None) -> Optional[Dict]:
    for e in _entries():
        if kinds is not None and e["kind"] not in kinds:
            continue
        if not fnmatch.fnmatch(site, e["site"]):
            continue
        e["_seen"] += 1
        if e["after"] < e["_seen"] <= e["after"] + e["times"]:
            return e
    return None


def fault_point(site: str) -> None:
    """Raise (or hang on) the planned fault at a named site.  A no-op
    without a matching ``YT_FAULT_PLAN`` entry — every call is cheap
    enough to leave in production paths."""
    e = _firing(site, kinds=set(FAULT_KINDS) | {"exception", "hang"})
    if e is None:
        return
    kind = e["kind"]
    if kind == "hang":
        # an interruptible stall: the deadline machinery (guard.py)
        # must convert this into a DeviceHang
        time.sleep(e["secs"])
        return
    if kind == "exception":
        raise RuntimeError(f"injected exception at {site}")
    if kind == "relay_down":
        raise RelayDown(f"injected relay drop at {site} "
                        "(UNAVAILABLE: failed to connect)", site=site)
    if kind == "device_hang":
        raise DeviceHang(f"injected hang at {site}", site=site)
    if kind == "compiler_oom":
        raise CompilerOOM(
            f"injected OOM at {site} (RESOURCE_EXHAUSTED: Ran out of "
            "memory in memory space vmem)", site=site)
    if kind == "compile_failed":
        raise CompileFailed(f"injected Mosaic compile failure at "
                            f"{site}", site=site)
    if kind == "result_anomaly":
        raise ResultAnomaly(f"injected result anomaly at {site}",
                            site=site)
    if kind == "worker_dead":
        raise WorkerDead(f"injected worker death at {site}", site=site)
    if kind == "worker_unhealthy":
        raise WorkerUnhealthy(f"injected unhealthy worker at {site}",
                              site=site)
    if kind == "load_spike":
        raise LoadSpike(f"injected load spike at {site}", site=site)


def maybe_corrupt(site: str, value):
    """Return ``value`` (an ndarray, or a var→ring-of-arrays state
    dict) corrupted per the plan — all-zero or NaN — or unchanged.
    Producers call this on outputs right before the sanity guards, so
    the round-3 all-zero incident is replayable end to end."""
    e = _firing(site, kinds=set(_CORRUPT_KINDS))
    if e is None:
        return value
    import numpy as np

    def corrupt(a):
        a = np.array(a, copy=True)
        a[...] = 0.0 if e["kind"] == "zero_output" else np.nan
        return a

    if isinstance(value, dict):
        return {k: [corrupt(a) for a in ring]
                for k, ring in value.items()}
    return corrupt(value)
