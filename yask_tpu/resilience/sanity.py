"""Result-sanity guards: validate outputs before they reach any ledger.

Round 3 banked an all-zero quick-matrix from real hardware as if it
were a clean result — vacuously "matching" because the oracle was zero
too.  These guards run on every produced row's backing data and turn
that class of incident into a structured ``ANOMALY``:

* **all-zero** — a zero fraction above :data:`ZERO_FRAC_MAX` on data
  that was seeded nonzero means the device returned nothing;
* **non-finite** — NaN/Inf anywhere (divergence or corrupt DMA);
* **oracle mismatch** — relative L2 error against a cheap CPU
  reference beyond tolerance, where one is available.

A failed verdict never silently drops the measurement: producers
attach it to the row (``quarantined: true`` + the ``anomaly`` field)
so the artifact records WHAT happened, and the perflab sentinel
excludes quarantined rows from its baselines
(:func:`yask_tpu.perflab.sentinel.is_clean`).
"""

from __future__ import annotations

from typing import Dict, List, Optional

#: zero fraction at/above which seeded data counts as "came back
#: all-zero".  High enough that legitimately sparse fields (an impulse
#: a few steps old is checked via an interior slice, not the full
#: domain) never trip it.
ZERO_FRAC_MAX = 0.999

#: default relative-L2 tolerance against a CPU oracle.
ORACLE_REL_TOL = 0.05


def _as_arrays(data) -> List:
    """Flatten an ndarray / list of ndarrays / var→ring state dict into
    a list of numpy arrays."""
    import numpy as np
    if isinstance(data, dict):
        out = []
        for ring in data.values():
            for a in (ring if isinstance(ring, (list, tuple))
                      else [ring]):
                out.append(np.asarray(a))
        return out
    if isinstance(data, (list, tuple)):
        return [np.asarray(a) for a in data]
    return [np.asarray(data)]


def array_stats(data) -> Dict:
    """Aggregate {n, zero_frac, nonfinite_frac, max_abs} over arrays /
    state dicts (device arrays are pulled to host via asarray)."""
    import numpy as np
    n = zeros = nonfinite = 0
    max_abs = 0.0
    for a in _as_arrays(data):
        if a.size == 0:
            continue
        a = np.asarray(a, dtype=np.float64)
        n += a.size
        finite = np.isfinite(a)
        nonfinite += int(a.size - int(finite.sum()))
        zeros += int((a == 0.0).sum())
        if finite.any():
            max_abs = max(max_abs, float(np.abs(a[finite]).max()))
    return {"n": n,
            "zero_frac": (zeros / n) if n else 0.0,
            "nonfinite_frac": (nonfinite / n) if n else 0.0,
            "max_abs": max_abs}


def check_output(data, oracle=None, rel_tol: float = ORACLE_REL_TOL,
                 zero_frac_max: float = ZERO_FRAC_MAX) -> Dict:
    """The sanity verdict for one measurement's backing data.

    Returns ``{"ok": bool, "anomalies": [...], **array_stats}`` (plus
    ``oracle_rel_err`` when an oracle was supplied).  ``data`` and
    ``oracle`` accept an ndarray, a list of ndarrays, or a var→ring
    state dict."""
    import numpy as np
    stats = array_stats(data)
    anomalies: List[str] = []
    if stats["n"] and stats["nonfinite_frac"] > 0.0:
        anomalies.append("nonfinite")
    if stats["n"] and stats["zero_frac"] >= zero_frac_max:
        anomalies.append("all_zero")
    verdict = {"anomalies": anomalies, **stats}
    if oracle is not None:
        got = np.concatenate([np.asarray(a, dtype=np.float64).ravel()
                              for a in _as_arrays(data)])
        want = np.concatenate([np.asarray(a, dtype=np.float64).ravel()
                               for a in _as_arrays(oracle)])
        if got.shape == want.shape and want.size:
            denom = float(np.linalg.norm(want))
            err = float(np.linalg.norm(got - want)) / max(denom, 1e-30)
            verdict["oracle_rel_err"] = round(err, 6)
            if not np.isfinite(err) or err > rel_tol:
                anomalies.append("oracle_mismatch")
        else:
            anomalies.append("oracle_shape_mismatch")
    verdict["ok"] = not anomalies
    return verdict


def check_state(state, **kw) -> Dict:
    """:func:`check_output` over a runtime state dict (var → ring of
    padded device arrays)."""
    return check_output(state, **kw)


def anomaly_fields(verdict: Dict) -> Dict:
    """The row fields a quarantined measurement carries — spliced into
    ledger / TPU_RESULTS rows by the producers."""
    return {"quarantined": True,
            "anomaly": {"classification": "ANOMALY",
                        "anomalies": list(verdict.get("anomalies", [])),
                        **{k: round(verdict[k], 6)
                           for k in ("zero_frac", "nonfinite_frac",
                                     "max_abs", "oracle_rel_err")
                           if k in verdict}}}
