"""Journaled resume: the append-only ``SESSION_JOURNAL.jsonl``.

Round 3 lost a 26-case validation matrix when the relay dropped
mid-session — the next window restarted from stage 1 and re-burned the
banked cases.  The journal makes session progress durable: every stage
/ case appends one row the moment its outcome is known, and the next
window resumes from the first incomplete case.

Row schema (``yask_tpu.session/1``)::

    {"v": "yask_tpu.session/1",
     "stage":   "validate",            # stage name
     "case":    "iso3dfd.K2",          # "" for stage-level rows
     "attempt": 1,
     "outcome": "started|ok|anomaly|skip|fault|aborted",
     "ts":      "2026-08-05T12:00:00Z",
     "detail":  {...}}                 # outcome-specific (mismatches,
                                       # fault kind, gpts, ...)

``ok``/``anomaly``/``skip`` are terminal (``anomaly`` = the case ran to
completion but its output was quarantined — rerunning it burns a
window for data another guard already rejected); ``started``/``fault``
mean the case still needs hardware.  The file is append-only during a
session; :meth:`SessionJournal.compact` (run between windows by the
watcher) atomically rewrites it to one row per (stage, case).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

SCHEMA = "yask_tpu.session/1"
JOURNAL_BASENAME = "SESSION_JOURNAL.jsonl"

#: outcomes after which a case need not rerun.
TERMINAL_OUTCOMES = ("ok", "anomaly", "skip")

#: growth bound for month-long watch loops (YT_JOURNAL_MAX_BYTES
#: overrides): past this, session open compacts before appending.
DEFAULT_MAX_BYTES = 8 * 2 ** 20


def max_journal_bytes() -> int:
    try:
        return int(os.environ.get("YT_JOURNAL_MAX_BYTES", "")
                   or DEFAULT_MAX_BYTES)
    except ValueError:
        return DEFAULT_MAX_BYTES


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_journal_path() -> str:
    return os.environ.get("YT_SESSION_JOURNAL") or os.path.join(
        repo_root(), JOURNAL_BASENAME)


def _utc_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class SessionJournal:
    def __init__(self, path: Optional[str] = None):
        self.path = path or default_journal_path()

    # ---------------------------------------------------------- write
    def record(self, stage: str, case: str = "", outcome: str = "ok",
               attempt: int = 1, **detail) -> Dict:
        """Append one row; never fatal to the caller's own work is NOT
        the contract here — journal I/O failures raise, because a
        session that cannot journal cannot promise resume.  Rows
        inherit the thread's active trace id (``stamp_trace``) so a
        traced run's journal evidence joins TRACE_EVENTS.jsonl."""
        from yask_tpu.obs.tracer import stamp_trace
        row = {"v": SCHEMA, "stage": str(stage), "case": str(case),
               "attempt": int(attempt), "outcome": str(outcome),
               "ts": _utc_now()}
        stamp_trace(row)
        if detail:
            row["detail"] = detail
        with open(self.path, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
        return row

    # ----------------------------------------------------------- read
    def rows(self) -> List[Dict]:
        """All rows, file order == time order; malformed lines are
        skipped (a kill mid-write must not poison resume)."""
        out: List[Dict] = []
        try:
            with open(self.path) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        row = json.loads(ln)
                    except ValueError:
                        continue
                    if isinstance(row, dict) and row.get("v") == SCHEMA:
                        out.append(row)
        except OSError:
            pass
        return out

    def last_outcomes(self) -> Dict[Tuple[str, str], Dict]:
        """Latest row per (stage, case)."""
        out: Dict[Tuple[str, str], Dict] = {}
        for row in self.rows():
            out[(row["stage"], row["case"])] = row
        return out

    def completed(self, stage: str, case: str = "") -> bool:
        row = self.last_outcomes().get((str(stage), str(case)))
        return row is not None and row["outcome"] in TERMINAL_OUTCOMES

    def attempts(self, stage: str, case: str = "") -> int:
        """Highest attempt number journaled for this case (0 = never
        started)."""
        best = 0
        for row in self.rows():
            if row["stage"] == stage and row["case"] == case:
                best = max(best, int(row.get("attempt", 1)))
        return best

    def pending(self, stage: str, cases: List[str]) -> List[str]:
        """The resume point: cases (in given order) without a terminal
        outcome — what the next relay window still owes."""
        done = self.last_outcomes()
        return [c for c in cases
                if done.get((stage, c), {}).get("outcome")
                not in TERMINAL_OUTCOMES]

    def session_count(self) -> int:
        """Sessions started so far (stage="session" outcome="started"
        marker rows) — the watcher's quick-vs-full window counter."""
        return sum(1 for r in self.rows()
                   if r["stage"] == "session"
                   and r["outcome"] == "started")

    # ----------------------------------------------------------- admin
    def compact(self) -> int:
        """Atomically rewrite to the latest row per (stage, case),
        preserving first-seen order; returns the number of rows
        dropped.  Run between sessions (the watcher), never during one
        — in-session the file is append-only."""
        rows = self.rows()
        latest = self.last_outcomes()
        seen = set()
        keep: List[Dict] = []
        for row in rows:
            key = (row["stage"], row["case"])
            if key in seen:
                continue
            seen.add(key)
            keep.append(latest[key])
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for row in keep:
                f.write(json.dumps(row, sort_keys=True) + "\n")
        os.replace(tmp, self.path)
        return len(rows) - len(keep)

    def compact_if_large(self, max_bytes: Optional[int] = None) -> int:
        """Compact only when the file exceeds the growth bound
        (``YT_JOURNAL_MAX_BYTES``, default 8 MiB) — the session-open
        guard that keeps month-long ``tpu_watch`` loops from growing
        the journal unboundedly.  Returns rows dropped (0 when under
        the bound or the file is missing)."""
        limit = max_journal_bytes() if max_bytes is None else max_bytes
        try:
            if os.path.getsize(self.path) <= limit:
                return 0
        except OSError:
            return 0
        return self.compact()
