"""Deadlines + retry: ``guarded_call`` and the killable subprocess.

Two enforcement shapes, matching how device work actually hangs here:

* :func:`deadline` / :func:`guarded_call` — in-process work under a
  SIGALRM deadline.  Interrupts Python-level stalls (injected hangs,
  polling loops, interruptible waits); a hang inside a C extension
  that never re-enters the interpreter cannot be preempted this way —
  that is what the subprocess shape is for.
* :func:`run_deadlined` — the generalized killable-subprocess trick
  from ``bench._probe_platform``: ``Popen`` in its own process group,
  SIGKILL the *group* on deadline (the backend plugin spawns
  grandchildren that keep pipes open after the child dies), then drain
  whatever partial output survived.

``guarded_call`` composes the whole policy: fault injection at the
named site, the deadline, classification (:func:`~yask_tpu.resilience.
faults.classify`), bounded retry with exponential backoff + jitter for
the retryable kinds, and an optional shared :class:`~yask_tpu.
resilience.faults.Breaker` so repeated failures across calls stay
loud.
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import threading
import time
from contextlib import contextmanager
from typing import Optional, Sequence, Tuple

from yask_tpu.resilience.faults import (Breaker, DeviceHang, classify,
                                        fault_point)

__all__ = ["deadline", "guarded_call", "run_deadlined"]

#: fault kinds retried by default: the transient ones.  Compiler
#: OOM/failures are per-candidate verdicts (retrying re-runs the same
#: doomed compile), anomalies are data bugs.
RETRYABLE = ("relay_down", "device_hang")


def _can_alarm() -> bool:
    return (hasattr(signal, "SIGALRM")
            and threading.current_thread() is threading.main_thread())


@contextmanager
def deadline(secs: Optional[float], site: str = "call"):
    """Hard in-process deadline: raises :class:`DeviceHang` when the
    block runs longer than ``secs``.  No-op when ``secs`` is falsy, off
    the main thread, or without SIGALRM (non-Unix) — callers that must
    not hang even then should use :func:`run_deadlined`."""
    if not secs or not _can_alarm():
        yield
        return

    def _on_alarm(signum, frame):
        raise DeviceHang(f"deadline of {secs:g}s exceeded at {site}",
                         site=site)

    old_handler = signal.signal(signal.SIGALRM, _on_alarm)
    old_timer = signal.setitimer(signal.ITIMER_REAL, secs)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, *(old_timer or (0.0, 0.0)))
        signal.signal(signal.SIGALRM, old_handler)


def guarded_call(fn, *args, site: str = "call",
                 deadline_secs: Optional[float] = None,
                 retries: int = 0, backoff: float = 0.5,
                 max_backoff: float = 8.0, jitter: float = 0.25,
                 retry_on: Sequence[str] = RETRYABLE,
                 breaker: Optional[Breaker] = None, **kwargs):
    """Run ``fn(*args, **kwargs)`` under the shared fault policy.

    Exceptions are classified into the fault taxonomy; unclassified
    exceptions propagate untouched (a bug in our own code must never
    look like a relay blink).  Classified faults whose kind is in
    ``retry_on`` are retried up to ``retries`` times with exponential
    backoff (+ up to ``jitter`` relative randomization, so a fleet of
    watchers does not re-dial the relay in lockstep); the final fault
    is raised as its taxonomy type with ``.cause`` holding the
    original.  ``breaker`` (when shared across calls) records every
    fault and suppresses further retries once tripped."""
    from yask_tpu.obs.tracer import phase_for_site, span
    attempt = 0
    while True:
        fault = None
        # one span per attempt (named by the fault site, phase derived
        # from it) — retries show as sibling spans, and a classified
        # fault lands in the span's attrs; unclassified exceptions
        # propagate through the span close untouched
        with span(f"guard:{site}", phase=phase_for_site(site),
                  attempt=attempt) as sp:
            try:
                with deadline(deadline_secs, site=site):
                    # inside the deadline: an injected "hang" must be
                    # converted to DeviceHang exactly like a real
                    # stall
                    fault_point(site)
                    out = fn(*args, **kwargs)
            except Exception as e:  # noqa: BLE001 - classified below
                fault = classify(e, site=site)
                if fault is None:
                    raise
                sp.set(fault=fault.kind)
        if fault is not None:
            tripped = breaker.record(fault) if breaker is not None \
                else False
            if fault.kind in retry_on and attempt < retries \
                    and not tripped:
                delay = min(backoff * (2 ** attempt), max_backoff)
                time.sleep(delay * (1.0 + jitter * random.random()))
                attempt += 1
                continue
            raise fault from (fault.cause or None)
        if breaker is not None:
            breaker.reset()
        return out


def run_deadlined(cmd: Sequence[str], deadline_secs: float,
                  site: str = "subprocess",
                  env: Optional[dict] = None,
                  stderr=subprocess.DEVNULL) -> Tuple[int, str]:
    """Run ``cmd`` in its own process group with a hard deadline.

    Returns ``(returncode, stdout)``.  On deadline the whole group is
    SIGKILLed (grandchildren included), already-produced stdout is
    drained, and a :class:`DeviceHang` carrying it as
    ``.partial_stdout`` is raised — a partial suite beats losing
    everything to the kill."""
    fault_point(site)
    proc = subprocess.Popen(
        list(cmd), stdout=subprocess.PIPE, stderr=stderr, text=True,
        start_new_session=True, env=env)
    try:
        out, _ = proc.communicate(timeout=deadline_secs)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        proc.wait()  # reap; cannot block after SIGKILL of the group
        try:
            out, _ = proc.communicate(timeout=5)
        except Exception:  # noqa: BLE001
            out = ""
        hang = DeviceHang(
            f"subprocess exceeded {deadline_secs:g}s deadline at "
            f"{site}: {' '.join(cmd[:3])}...", site=site)
        hang.partial_stdout = out or ""
        raise hang
    return proc.returncode, out or ""


def python_cmd(code: str) -> list:
    """``[sys.executable, "-c", code]`` — the probe-subprocess shape."""
    return [sys.executable, "-c", code]
