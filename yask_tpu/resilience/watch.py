"""Relay watcher: window detection, session planning, journal upkeep.

The testable port of ``tools/tpu_watch.sh`` (now a thin wrapper): probe
the axon relay on an interval, and on every window it answers run the
session protocol with arguments chosen from the journal —

* the **first** productive window runs ``--quick`` (bank a perf number
  before validation compiles can eat the window — the round-3 lesson);
* later windows run the full protocol;
* whenever the journal holds incomplete work from a dropped window the
  session gets ``--resume`` so it completes only the missing cases;
* between sessions the journal is compacted (append-only during a
  session, one row per case after it).

Artifacts are committed the moment a session ends, exactly as the
shell version did.  Run: ``python -m yask_tpu.resilience.watch
[--loop | --probe | --plan]``.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import List, Optional

from yask_tpu.resilience.faults import DeviceHang
from yask_tpu.resilience.guard import python_cmd, run_deadlined
from yask_tpu.resilience.journal import (TERMINAL_OUTCOMES,
                                         SessionJournal, repo_root)

__all__ = ["relay_up", "session_args", "run_session", "watch_loop"]

#: the probe requires the axon/TPU backend, not a CPU fallback —
#: otherwise a session would be burned on CPU (same check as
#: bench._probe_platform).
PROBE_CODE = ("import jax, sys; "
              "sys.exit(0 if jax.default_backend() in ('axon', 'tpu') "
              "else 3)")


def relay_up(timeout: float = 90.0,
             probe_cmd: Optional[List[str]] = None) -> bool:
    """One relay probe in a killable subprocess: True only when the
    default backend is the real TPU/axon one.  A hang (relay half-up)
    counts as down."""
    cmd = probe_cmd if probe_cmd is not None else python_cmd(PROBE_CODE)
    try:
        rc, _ = run_deadlined(cmd, timeout, site="watch.probe")
    except DeviceHang:
        return False
    return rc == 0


def session_args(journal: SessionJournal, g: int = 512) -> List[str]:
    """Arguments for the next session, planned from the journal:
    ``--quick`` until one session has completed (bank numbers fast on
    the first window), ``--resume`` whenever journaled work is
    incomplete (a dropped relay no longer forfeits banked cases)."""
    args = ["-g", str(g)]
    rows = journal.rows()
    if not any(r["stage"] == "session" and r["outcome"] == "ok"
               for r in rows):
        # no session has ever completed: bank-numbers-first posture
        args.append("--quick")
    if rows and any(r["outcome"] not in TERMINAL_OUTCOMES
                    for r in journal.last_outcomes().values()):
        args.append("--resume")
    return args


def run_session(args: List[str], timeout: float = 3000.0,
                log_dir: Optional[str] = None) -> int:
    """One ``tools/tpu_session.py`` run under a hard deadline, stdout
    tee'd to a timestamped log under ``tools/logs``."""
    root = repo_root()
    log_dir = log_dir or os.path.join(root, "tools", "logs")
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(
        log_dir, time.strftime("tpu_session_%m%d_%H%M%S.log",
                               time.gmtime()))
    cmd = [sys.executable, os.path.join(root, "tools", "tpu_session.py")]
    cmd += args
    try:
        rc, out = run_deadlined(cmd, timeout, site="watch.session",
                                stderr=subprocess.STDOUT)
    except DeviceHang as e:
        rc, out = -9, e.partial_stdout
    try:
        with open(log_path, "w") as f:
            f.write(out)
    except OSError:
        pass
    return rc


def commit_artifacts(root: Optional[str] = None) -> None:
    """Commit hardware artifacts the moment they exist (round 3 lost
    its numbers by waiting for round end).  Only session-owned paths
    are staged; every failure here is non-fatal — a transient
    index.lock just defers to the next window."""
    root = root or repo_root()
    paths = ["tools/logs"]
    for p in ("TPU_RESULTS.jsonl", "BENCH_suite_latest.json",
              "SESSION_JOURNAL.jsonl"):
        if os.path.exists(os.path.join(root, p)):
            paths.append(p)
    try:
        subprocess.run(["git", "add", "-f", *paths], cwd=root,
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL, timeout=60)
        subprocess.run(
            ["git", "commit", "-m",
             "TPU session artifacts (auto-committed by watch)",
             "--only", *paths],
            cwd=root, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, timeout=60)
    except Exception:  # noqa: BLE001
        pass


def watch_loop(g: int = 512, probe_secs: float = 170.0,
               settle_secs: float = 60.0, max_windows: int = 0,
               journal: Optional[SessionJournal] = None,
               out=None) -> int:
    """Probe forever (or for ``max_windows`` productive windows, for
    tests); on each window plan args from the journal, run the session,
    commit artifacts, compact the journal."""
    out = out or sys.stderr
    journal = journal or SessionJournal()
    windows = 0
    while True:
        if relay_up():
            windows += 1
            # a fresh successful probe is the legitimate evidence that
            # the relay is back: reset the PERSISTENT breaker so the
            # session isn't strangled by a previous window's open state
            # (a mere watcher restart, by contrast, keeps it open)
            from yask_tpu.resilience.faults import (Breaker,
                                                    default_breaker_path)
            Breaker(path=default_breaker_path()).reset()
            args = session_args(journal, g=g)
            out.write(f"watch: relay UP — session {windows} "
                      f"({' '.join(args)})\n")
            rc = run_session(args)
            out.write(f"watch: session {windows} exit {rc}\n")
            commit_artifacts()
            journal.compact()
            if max_windows and windows >= max_windows:
                return 0
            time.sleep(settle_secs)
        else:
            out.write("watch: relay down\n")
            if max_windows and windows >= max_windows:
                return 0
            time.sleep(probe_secs)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    g = 512
    if "-g" in argv:
        i = argv.index("-g")
        g = int(argv[i + 1])
        del argv[i:i + 2]
    if "--probe" in argv:
        up = relay_up()
        print("up" if up else "down")
        return 0 if up else 3
    if "--plan" in argv:
        print(" ".join(session_args(SessionJournal(), g=g)))
        return 0
    if "--compact" in argv:
        dropped = SessionJournal().compact()
        print(f"journal compacted ({dropped} row(s) dropped)")
        return 0
    return watch_loop(g=g)


if __name__ == "__main__":
    sys.exit(main())
