"""yask_tpu.resilience — fault-tolerant TPU sessions.

One shared policy for every device-facing producer: fault taxonomy +
classification (:mod:`.faults`), deadlines/retry/killable subprocess
(:mod:`.guard`), journaled resume (:mod:`.journal`), portable run
checkpoints + the mode-degradation ladder (:mod:`.checkpoint`),
result-sanity guards (:mod:`.sanity`), and the testable relay watcher
(:mod:`.watch`).  Fault injection via ``YT_FAULT_PLAN`` drives all of
it from fast CPU tests — see ``docs/resilience.md``.
"""

from yask_tpu.resilience.checkpoint import (  # noqa: F401
    CKPT_SCHEMA, apply_snapshot, default_ckpt_dir, degradation_ladder,
    extract_snapshot, peek_checkpoint, restore_checkpoint,
    save_checkpoint, snapshot_mismatches)
from yask_tpu.resilience.faults import (  # noqa: F401
    FAULT_KINDS, Breaker, CompileFailed, CompilerOOM, DeviceHang, Fault,
    RelayDown, ResultAnomaly, active_plan, classify, classify_message,
    default_breaker_path, fault_point, maybe_corrupt, reset_faults)
from yask_tpu.resilience.guard import (  # noqa: F401
    RETRYABLE, deadline, guarded_call, python_cmd, run_deadlined)
from yask_tpu.resilience.journal import (  # noqa: F401
    JOURNAL_BASENAME, SCHEMA as JOURNAL_SCHEMA, TERMINAL_OUTCOMES,
    SessionJournal, default_journal_path, max_journal_bytes)
from yask_tpu.resilience.sanity import (  # noqa: F401
    ORACLE_REL_TOL, ZERO_FRAC_MAX, anomaly_fields, array_stats,
    check_output, check_state)

__all__ = [
    "Fault", "RelayDown", "DeviceHang", "CompilerOOM", "CompileFailed",
    "ResultAnomaly", "FAULT_KINDS", "classify", "classify_message",
    "Breaker", "default_breaker_path", "fault_point", "maybe_corrupt",
    "reset_faults", "active_plan",
    "deadline", "guarded_call", "run_deadlined", "python_cmd",
    "RETRYABLE",
    "SessionJournal", "JOURNAL_SCHEMA", "JOURNAL_BASENAME",
    "TERMINAL_OUTCOMES", "default_journal_path", "max_journal_bytes",
    "CKPT_SCHEMA", "extract_snapshot", "apply_snapshot",
    "save_checkpoint", "restore_checkpoint", "peek_checkpoint",
    "snapshot_mismatches", "default_ckpt_dir", "degradation_ladder",
    "check_output", "check_state", "array_stats", "anomaly_fields",
    "ZERO_FRAC_MAX", "ORACLE_REL_TOL",
]
