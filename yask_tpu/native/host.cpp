// Native host-side runtime kernels for yask_tpu.
//
// TPU-native counterpart of the reference's C++ common/runtime substrate:
// the hot host-side paths that sit outside the XLA device program —
// N-D layout math (reference Tuple<T>, src/common/tuple.hpp:130),
// rank-grid factorization (get_compact_factors, setup.cpp:230),
// finite-difference weight generation (fd_coeff2.cpp), and the
// trace-divergence scanner backing the analyze_trace tooling
// (utils/bin/analyze_trace.pl). Exposed with a plain C ABI for ctypes;
// Python falls back to pure-Python implementations when the library
// isn't built.
//
// Build: make -C yask_tpu/native   (or python -m yask_tpu.native.build)

#include <cstdint>
#include <cstddef>
#include <cmath>
#include <vector>
#include <algorithm>

extern "C" {

// ---------------------------------------------------------------------
// N-D layout math (Tuple::layout / unlayout, last dim unit-stride)
// ---------------------------------------------------------------------

// Map npts N-D points (pts[i*ndims + d]) to 1-D offsets under `sizes`.
// Returns 0 on success, -1 on out-of-bounds.
int yt_layout(const int64_t* sizes, int ndims,
              const int64_t* pts, int64_t npts, int64_t* out) {
    for (int64_t i = 0; i < npts; ++i) {
        int64_t idx = 0;
        const int64_t* p = pts + i * ndims;
        for (int d = 0; d < ndims; ++d) {
            if (p[d] < 0 || p[d] >= sizes[d]) return -1;
            idx = idx * sizes[d] + p[d];
        }
        out[i] = idx;
    }
    return 0;
}

// Inverse: 1-D offsets to N-D points.
int yt_unlayout(const int64_t* sizes, int ndims,
                const int64_t* offsets, int64_t n, int64_t* out) {
    int64_t total = 1;
    for (int d = 0; d < ndims; ++d) total *= sizes[d];
    for (int64_t i = 0; i < n; ++i) {
        int64_t off = offsets[i];
        if (off < 0 || off >= total) return -1;
        for (int d = ndims - 1; d >= 0; --d) {
            out[i * ndims + d] = off % sizes[d];
            off /= sizes[d];
        }
    }
    return 0;
}

// ---------------------------------------------------------------------
// Compact factorization of n over ndims grid dims (rank/mesh grids):
// minimize spread (max/min), prefer larger factors later.
// ---------------------------------------------------------------------

static void factor_rec(int64_t rem, int dims_left,
                       std::vector<int64_t>& acc,
                       std::vector<int64_t>& best, double& best_spread,
                       int& best_sorted) {
    if (dims_left == 1) {
        acc.push_back(rem);
        int64_t mx = *std::max_element(acc.begin(), acc.end());
        int64_t mn = *std::min_element(acc.begin(), acc.end());
        double spread = (double)mx / (double)(mn > 0 ? mn : 1);
        int sorted = 0;
        for (size_t i = 0; i + 1 < acc.size(); ++i)
            if (acc[i] > acc[i + 1]) ++sorted;
        if (best.empty() || spread < best_spread ||
            (spread == best_spread && sorted < best_sorted)) {
            best = acc;
            best_spread = spread;
            best_sorted = sorted;
        }
        acc.pop_back();
        return;
    }
    for (int64_t f = 1; f <= rem; ++f) {
        if (rem % f == 0) {
            acc.push_back(f);
            factor_rec(rem / f, dims_left - 1, acc, best, best_spread,
                       best_sorted);
            acc.pop_back();
        }
    }
}

int yt_compact_factors(int64_t n, int ndims, int64_t* out) {
    if (ndims <= 0 || n <= 0) return -1;
    std::vector<int64_t> acc, best;
    double spread = 0.0;
    int sorted = 0;
    factor_rec(n, ndims, acc, best, spread, sorted);
    if ((int)best.size() != ndims) return -1;
    for (int d = 0; d < ndims; ++d) out[d] = best[d];
    return 0;
}

// ---------------------------------------------------------------------
// Fornberg finite-difference weights: order-d derivative at x0 over
// sample points xs[0..n) (fd_coeff API backing).
// ---------------------------------------------------------------------

int yt_fd_weights(int d, double x0, const double* xs, int n, double* out) {
    if (n < 2 || d < 1 || d >= n) return -1;
    std::vector<std::vector<double>> c(d + 1, std::vector<double>(n, 0.0));
    c[0][0] = 1.0;
    double c1 = 1.0;
    double c4 = xs[0] - x0;
    for (int i = 1; i < n; ++i) {
        int mn = std::min(i, d);
        double c2 = 1.0;
        double c5 = c4;
        c4 = xs[i] - x0;
        for (int j = 0; j < i; ++j) {
            double c3 = xs[i] - xs[j];
            c2 *= c3;
            if (j == i - 1) {
                for (int k = mn; k >= 1; --k)
                    c[k][i] = c1 * (k * c[k - 1][i - 1]
                                    - c5 * c[k][i - 1]) / c2;
                c[0][i] = -c1 * c5 * c[0][i - 1] / c2;
            }
            for (int k = mn; k >= 1; --k)
                c[k][j] = (c4 * c[k][j] - k * c[k - 1][j]) / c3;
            c[0][j] = c4 * c[0][j] / c3;
        }
        c1 = c2;
    }
    for (int i = 0; i < n; ++i) out[i] = c[d][i];
    return 0;
}

// ---------------------------------------------------------------------
// Trace divergence scan: first index where |a-b| > atol + rtol*max(|a|,|b|)
// over float32 buffers (the analyze_trace first-divergent-write search).
// Returns index, or -1 if none, -2 on bad args.
// ---------------------------------------------------------------------

int64_t yt_first_divergence_f32(const float* a, const float* b, int64_t n,
                                double rtol, double atol) {
    if (!a || !b || n < 0) return -2;
    for (int64_t i = 0; i < n; ++i) {
        double x = a[i], y = b[i];
        double tol = atol + rtol * std::max(std::fabs(x), std::fabs(y));
        double diff = std::fabs(x - y);
        bool xn = std::isnan(x), yn = std::isnan(y);
        if (xn != yn || (!xn && diff > tol)) return i;
    }
    return -1;
}

// Count of diverging elements (bulk compare used by compare_data).
int64_t yt_count_divergence_f32(const float* a, const float* b, int64_t n,
                                double rtol, double atol) {
    if (!a || !b || n < 0) return -2;
    int64_t bad = 0;
    for (int64_t i = 0; i < n; ++i) {
        double x = a[i], y = b[i];
        double tol = atol + rtol * std::max(std::fabs(x), std::fabs(y));
        double diff = std::fabs(x - y);
        bool xn = std::isnan(x), yn = std::isnan(y);
        if (xn != yn || (!xn && diff > tol)) ++bad;
    }
    return bad;
}

int yt_version() { return 1; }

}  // extern "C"
