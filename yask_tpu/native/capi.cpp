/* C API implementation: embeds CPython and drives the yask_tpu runtime.
 *
 * The reference links apps against libyask_kernel and generated stencil
 * code (src/kernel/Makefile); the TPU framework's runtime is Python/JAX,
 * so the C ABI hosts the interpreter instead — the same re-design choice
 * as the SWIG direction reversed. One interpreter per process; handles
 * are owned references to StencilContext objects.
 */
#include "yask_tpu_api.h"

#include <Python.h>

#include <cmath>
#include <cstdio>
#include <cstring>
#include <string>

namespace {

std::string g_err;
PyObject *g_factory = nullptr;   // yk_factory instance
PyObject *g_env = nullptr;       // yk_env instance

/* Every yt_* body holds the GIL (callable from any host thread). */
struct Gil {
    PyGILState_STATE st;
    Gil() : st(PyGILState_Ensure()) {}
    ~Gil() { PyGILState_Release(st); }
};

void capture_py_error(const char *what) {
    g_err = what;
    if (PyErr_Occurred()) {
        PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
        PyErr_Fetch(&type, &value, &tb);
        PyErr_NormalizeException(&type, &value, &tb);
        if (value) {
            PyObject *s = PyObject_Str(value);
            if (s) {
                g_err += ": ";
                g_err += PyUnicode_AsUTF8(s);
                Py_DECREF(s);
            }
        }
        Py_XDECREF(type);
        Py_XDECREF(value);
        Py_XDECREF(tb);
    }
}

PyObject *call_method(PyObject *obj, const char *name, PyObject *args) {
    PyObject *m = PyObject_GetAttrString(obj, name);
    if (!m) return nullptr;
    PyObject *r = PyObject_CallObject(m, args);
    Py_DECREF(m);
    return r;
}

PyObject *idx_list(const long *idxs, int n) {
    PyObject *lst = PyList_New(n);
    for (int i = 0; i < n; i++)
        PyList_SET_ITEM(lst, i, PyLong_FromLong(idxs[i]));
    return lst;
}

PyObject *get_var(PyObject *ctx, const char *var) {
    PyObject *args = Py_BuildValue("(s)", var);
    PyObject *v = call_method(ctx, "get_var", args);
    Py_DECREF(args);
    return v;
}

} // namespace

extern "C" {

static int setup_locked(void) {
    PyObject *mod = PyImport_ImportModule("yask_tpu");
    if (!mod) {
        capture_py_error("import yask_tpu failed");
        return 1;
    }
    PyObject *fac_cls = PyObject_GetAttrString(mod, "yk_factory");
    Py_DECREF(mod);
    if (!fac_cls) {
        capture_py_error("yk_factory missing");
        return 1;
    }
    g_factory = PyObject_CallObject(fac_cls, nullptr);
    Py_DECREF(fac_cls);
    if (!g_factory) {
        capture_py_error("yk_factory() failed");
        return 1;
    }
    g_env = call_method(g_factory, "new_env", nullptr);
    if (!g_env) {
        capture_py_error("new_env() failed");
        Py_CLEAR(g_factory);
        return 1;
    }
    return 0;
}

int yt_initialize(void) {
    if (g_factory) return 0;
    bool we_initialized = false;
    if (!Py_IsInitialized()) {
        Py_Initialize();   // this thread now holds the GIL
        we_initialized = true;
    }
    int rc;
    {
        Gil gil;
        rc = setup_locked();
    }
    if (we_initialized)
        /* release the GIL we acquired via Py_Initialize so any host
         * thread can enter through PyGILState_Ensure afterwards */
        (void)PyEval_SaveThread();
    return rc;
}

void yt_finalize(void) {
    if (!g_factory) return;
    Gil gil;
    Py_CLEAR(g_env);
    Py_CLEAR(g_factory);
    /* interpreter stays up: cheap, and JAX dislikes re-init */
}

void *yt_new_solution(const char *stencil, int radius) {
    if (yt_initialize() != 0) return nullptr;
    Gil gil;
    PyObject *kwargs = PyDict_New();
    PyObject *sv = PyUnicode_FromString(stencil);
    PyDict_SetItemString(kwargs, "stencil", sv);   // does NOT steal
    Py_DECREF(sv);
    if (radius > 0) {
        PyObject *rv = PyLong_FromLong(radius);
        PyDict_SetItemString(kwargs, "radius", rv);
        Py_DECREF(rv);
    }
    PyObject *args = Py_BuildValue("(O)", g_env);
    PyObject *m = PyObject_GetAttrString(g_factory, "new_solution");
    PyObject *ctx = m ? PyObject_Call(m, args, kwargs) : nullptr;
    Py_XDECREF(m);
    Py_DECREF(args);
    Py_DECREF(kwargs);
    if (!ctx) {
        capture_py_error("new_solution failed");
        return nullptr;
    }
    return ctx;
}

void yt_free_solution(void *soln) {
    Gil gil;
    Py_XDECREF((PyObject *)soln);
}

int yt_apply_options(void *soln, const char *cli) {
    Gil gil;
    PyObject *args = Py_BuildValue("(s)", cli);
    PyObject *r = call_method((PyObject *)soln,
                              "apply_command_line_options", args);
    Py_DECREF(args);
    if (!r) {
        capture_py_error("apply_command_line_options failed");
        return 1;
    }
    Py_DECREF(r);
    return 0;
}

int yt_prepare(void *soln) {
    Gil gil;
    PyObject *r = call_method((PyObject *)soln, "prepare_solution",
                              nullptr);
    if (!r) {
        capture_py_error("prepare_solution failed");
        return 1;
    }
    Py_DECREF(r);
    return 0;
}

static int run_steps(void *soln, const char *method, long a, long b) {
    Gil gil;
    PyObject *args = Py_BuildValue("(ll)", a, b);
    PyObject *r = call_method((PyObject *)soln, method, args);
    Py_DECREF(args);
    if (!r) {
        capture_py_error(method);
        return 1;
    }
    Py_DECREF(r);
    return 0;
}

int yt_run(void *soln, long first_step, long last_step) {
    return run_steps(soln, "run_solution", first_step, last_step);
}

int yt_run_ref(void *soln, long first_step, long last_step) {
    return run_steps(soln, "run_ref", first_step, last_step);
}

int yt_set_element(void *soln, const char *var, double val,
                   const long *idxs, int nidx) {
    Gil gil;
    PyObject *v = get_var((PyObject *)soln, var);
    if (!v) {
        capture_py_error("get_var failed");
        return 1;
    }
    PyObject *args = Py_BuildValue("(dN)", val, idx_list(idxs, nidx));
    PyObject *r = call_method(v, "set_element", args);
    Py_DECREF(args);
    Py_DECREF(v);
    if (!r) {
        capture_py_error("set_element failed");
        return 1;
    }
    Py_DECREF(r);
    return 0;
}

double yt_get_element(void *soln, const char *var,
                      const long *idxs, int nidx) {
    Gil gil;
    g_err.clear();   // NaN doubles as the error sentinel: a cleared
    //                  error message marks a legitimately-NaN element
    PyObject *v = get_var((PyObject *)soln, var);
    if (!v) {
        capture_py_error("get_var failed");
        return std::nan("");
    }
    PyObject *args = Py_BuildValue("(N)", idx_list(idxs, nidx));
    PyObject *r = call_method(v, "get_element", args);
    Py_DECREF(args);
    Py_DECREF(v);
    if (!r) {
        capture_py_error("get_element failed");
        return std::nan("");
    }
    double out = PyFloat_AsDouble(r);
    Py_DECREF(r);
    if (PyErr_Occurred()) {
        capture_py_error("get_element: not a number");
        return std::nan("");
    }
    return out;
}

long yt_compare(void *soln, void *other, double epsilon,
                double abs_epsilon) {
    Gil gil;
    PyObject *kwargs = PyDict_New();
    PyObject *ev = PyFloat_FromDouble(epsilon);
    PyObject *av = PyFloat_FromDouble(abs_epsilon);
    PyDict_SetItemString(kwargs, "epsilon", ev);       // does NOT steal
    PyDict_SetItemString(kwargs, "abs_epsilon", av);
    Py_DECREF(ev);
    Py_DECREF(av);
    PyObject *args = Py_BuildValue("(O)", (PyObject *)other);
    PyObject *m = PyObject_GetAttrString((PyObject *)soln, "compare_data");
    PyObject *r = m ? PyObject_Call(m, args, kwargs) : nullptr;
    Py_XDECREF(m);
    Py_DECREF(args);
    Py_DECREF(kwargs);
    if (!r) {
        capture_py_error("compare_data failed");
        return -1;
    }
    long out = PyLong_AsLong(r);
    Py_DECREF(r);
    return out;
}

const char *yt_last_error(void) { return g_err.c_str(); }

} /* extern "C" */
