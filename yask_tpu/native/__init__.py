"""Native host library loader (ctypes) with pure-Python fallback.

The reference's runtime substrate is C++ throughout; here the device
program is XLA-compiled, and this library covers the host-side hot paths
(layout math, mesh factorization, FD weights, trace scanning). The loader
builds the .so on first use if a toolchain is available and otherwise
reports unavailability — callers keep their Python fallbacks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libyask_tpu_host.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        r = subprocess.run(["make", "-C", _DIR], capture_output=True,
                           text=True, timeout=120)
        return r.returncode == 0 and os.path.exists(_SO)
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    f64p = ctypes.POINTER(ctypes.c_double)
    lib.yt_layout.restype = ctypes.c_int
    lib.yt_layout.argtypes = [i64p, ctypes.c_int, i64p, ctypes.c_int64, i64p]
    lib.yt_unlayout.restype = ctypes.c_int
    lib.yt_unlayout.argtypes = [i64p, ctypes.c_int, i64p, ctypes.c_int64,
                                i64p]
    lib.yt_compact_factors.restype = ctypes.c_int
    lib.yt_compact_factors.argtypes = [ctypes.c_int64, ctypes.c_int, i64p]
    lib.yt_fd_weights.restype = ctypes.c_int
    lib.yt_fd_weights.argtypes = [ctypes.c_int, ctypes.c_double, f64p,
                                  ctypes.c_int, f64p]
    lib.yt_first_divergence_f32.restype = ctypes.c_int64
    lib.yt_first_divergence_f32.argtypes = [f32p, f32p, ctypes.c_int64,
                                            ctypes.c_double, ctypes.c_double]
    lib.yt_count_divergence_f32.restype = ctypes.c_int64
    lib.yt_count_divergence_f32.argtypes = [f32p, f32p, ctypes.c_int64,
                                            ctypes.c_double, ctypes.c_double]
    lib.yt_version.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    return get_lib() is not None


def _as_i64(a) -> np.ndarray:
    return np.ascontiguousarray(a, dtype=np.int64)


def layout(sizes: Sequence[int], pts: np.ndarray) -> np.ndarray:
    """Batch N-D→1-D layout (native; raises if lib unavailable)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    s = _as_i64(sizes)
    p = _as_i64(pts)
    npts = p.shape[0]
    out = np.empty(npts, dtype=np.int64)
    rc = lib.yt_layout(
        s.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(s),
        p.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), npts,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if rc != 0:
        raise ValueError("point out of bounds")
    return out


def unlayout(sizes: Sequence[int], offsets: np.ndarray) -> np.ndarray:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    s = _as_i64(sizes)
    o = _as_i64(offsets)
    out = np.empty((len(o), len(s)), dtype=np.int64)
    rc = lib.yt_unlayout(
        s.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(s),
        o.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(o),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if rc != 0:
        raise ValueError("offset out of bounds")
    return out


def compact_factors(n: int, ndims: int) -> List[int]:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    out = np.empty(ndims, dtype=np.int64)
    rc = lib.yt_compact_factors(
        n, ndims, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if rc != 0:
        raise ValueError(f"cannot factorize {n} into {ndims} dims")
    return out.tolist()


def fd_weights(deriv: int, x0: float, xs: Sequence[float]) -> List[float]:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    x = np.ascontiguousarray(xs, dtype=np.float64)
    out = np.empty(len(x), dtype=np.float64)
    rc = lib.yt_fd_weights(
        deriv, x0, x.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(x), out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    if rc != 0:
        raise ValueError("bad FD parameters")
    return out.tolist()


def first_divergence(a: np.ndarray, b: np.ndarray,
                     rtol: float = 1e-4, atol: float = 1e-7) -> int:
    """Index of the first diverging element of two f32 buffers; -1 if
    none (trace-diff backend)."""
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    x = np.ascontiguousarray(a, dtype=np.float32).ravel()
    y = np.ascontiguousarray(b, dtype=np.float32).ravel()
    if x.size != y.size:
        raise ValueError("size mismatch")
    return int(lib.yt_first_divergence_f32(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        x.size, rtol, atol))


def count_divergence(a: np.ndarray, b: np.ndarray,
                     rtol: float = 1e-4, atol: float = 1e-7) -> int:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    x = np.ascontiguousarray(a, dtype=np.float32).ravel()
    y = np.ascontiguousarray(b, dtype=np.float32).ravel()
    if x.size != y.size:
        raise ValueError("size mismatch")
    return int(lib.yt_count_divergence_f32(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        x.size, rtol, atol))
