/* C++ API smoke app: drive a stencil end to end through the embedded
 * runtime and validate against the oracle — the counterpart of the
 * reference's C++ kernel API test (src/kernel/tests/yask_kernel_api_test
 * .cpp), exercising the same flow: build, configure, seed, run,
 * compare.  Exits 0 on success.
 */
#include "yask_tpu_api.h"

#include <cmath>
#include <cstdio>

int main() {
    using yask_tpu::Solution;
    if (yt_initialize() != 0) {
        std::fprintf(stderr, "init failed: %s\n", yt_last_error());
        return 1;
    }
    try {
        Solution s("3axis", 1);
        s.apply_options("-g 16");
        s.prepare();
        s.set_element("A", 8.0, {0, 8, 8, 8});
        s.run(0, 3);

        Solution ref("3axis", 1);
        ref.apply_options("-g 16");
        ref.prepare();
        ref.set_element("A", 8.0, {0, 8, 8, 8});
        ref.run_ref(0, 3);

        long bad = s.compare(ref, 1e-3, 1e-4);
        double center = s.get_element("A", {4, 8, 8, 8});
        std::printf("capi: mismatches=%ld center=%g\n", bad, center);
        if (bad != 0 || !std::isfinite(center) || center == 0.0)
            return 2;
    } catch (const std::exception &e) {
        std::fprintf(stderr, "capi demo failed: %s\n", e.what());
        return 1;
    }
    std::printf("capi demo passed\n");
    return 0;
}
