/* C/C++ kernel API for the yask_tpu framework.
 *
 * Counterpart of the reference's C++ kernel API surface
 * (include/yask_kernel_api.hpp yk_* classes, exported to apps via SWIG):
 * here the runtime is Python/JAX, so the C ABI embeds the CPython
 * interpreter and drives the same yk_factory/StencilContext objects a
 * Python caller would — one runtime, two front ends.
 *
 * Usage (C):
 *   yt_initialize();
 *   void *s = yt_new_solution("iso3dfd", 8);
 *   yt_apply_options(s, "-g 128");
 *   yt_prepare(s);
 *   long idx[] = {0, 64, 64, 64};
 *   yt_set_element(s, "pressure", 1.0, idx, 4);
 *   yt_run(s, 0, 9);
 *   ...
 *   yt_free_solution(s);
 *   yt_finalize();
 *
 * A RAII C++ wrapper (yask_tpu::Solution) follows the C declarations.
 * All functions return 0 / a valid value on success; on failure they
 * return nonzero / NaN and yt_last_error() describes the problem.
 */
#ifndef YASK_TPU_API_H
#define YASK_TPU_API_H

#ifdef __cplusplus
extern "C" {
#endif

int yt_initialize(void);
void yt_finalize(void);

void *yt_new_solution(const char *stencil, int radius /* <=0: default */);
void yt_free_solution(void *soln);

int yt_apply_options(void *soln, const char *cli);
int yt_prepare(void *soln);
int yt_run(void *soln, long first_step, long last_step);
int yt_run_ref(void *soln, long first_step, long last_step);

int yt_set_element(void *soln, const char *var, double val,
                   const long *idxs, int nidx);
double yt_get_element(void *soln, const char *var,
                      const long *idxs, int nidx);

/* #mismatching points between two prepared solutions (-1 on error). */
long yt_compare(void *soln, void *other, double epsilon,
                double abs_epsilon);

const char *yt_last_error(void);

#ifdef __cplusplus
} /* extern "C" */

#include <stdexcept>
#include <string>
#include <vector>

namespace yask_tpu {

class Solution {
  public:
    Solution(const std::string &stencil, int radius = 0)
        : h_(yt_new_solution(stencil.c_str(), radius)) {
        if (!h_) throw std::runtime_error(yt_last_error());
    }
    ~Solution() { if (h_) yt_free_solution(h_); }
    Solution(const Solution &) = delete;
    Solution &operator=(const Solution &) = delete;

    void apply_options(const std::string &cli) {
        check(yt_apply_options(h_, cli.c_str()));
    }
    void prepare() { check(yt_prepare(h_)); }
    void run(long first, long last) { check(yt_run(h_, first, last)); }
    void run_ref(long first, long last) {
        check(yt_run_ref(h_, first, last));
    }
    void set_element(const std::string &var, double val,
                     const std::vector<long> &idxs) {
        check(yt_set_element(h_, var.c_str(), val, idxs.data(),
                             (int)idxs.size()));
    }
    double get_element(const std::string &var,
                       const std::vector<long> &idxs) {
        double v = yt_get_element(h_, var.c_str(), idxs.data(),
                                  (int)idxs.size());
        // NaN is the error sentinel, but a stored NaN is legal data:
        // the C layer clears its error first, so only a non-empty
        // message marks a real failure.
        if (v != v && yt_last_error()[0] != '\0')
            throw std::runtime_error(yt_last_error());
        return v;
    }
    long compare(Solution &other, double eps = 1e-4,
                 double abs_eps = 1e-7) {
        long n = yt_compare(h_, other.h_, eps, abs_eps);
        if (n < 0) throw std::runtime_error(yt_last_error());
        return n;
    }

  private:
    static void check(int rc) {
        if (rc != 0) throw std::runtime_error(yt_last_error());
    }
    void *h_;
};

} // namespace yask_tpu
#endif /* __cplusplus */

#endif /* YASK_TPU_API_H */
