"""Framework exception type.

Counterpart of ``yask_exception`` (reference
``include/yask_common_api.hpp:125-155``): a single exception class carrying an
accreting message, raised by both compiler and runtime for user-facing errors.
"""

from __future__ import annotations


class YaskException(Exception):
    """Exception raised by the framework for all user-facing error paths.

    Like the reference's ``yask_exception``, messages can be accreted after
    construction via :meth:`add_message`.
    """

    def __init__(self, message: str = ""):
        super().__init__(message)
        self._message = message

    def add_message(self, message: str) -> None:
        """Append to the error message (``yask_exception::add_message``)."""
        self._message += message
        self.args = (self._message,)

    def get_message(self) -> str:
        """Return the current message (``yask_exception::get_message``)."""
        return self._message

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self._message


def yask_assert(cond: bool, msg: str = "internal assertion failed") -> None:
    """Internal invariant check (counterpart of ``yask_assert.hpp``)."""
    if not cond:
        raise YaskException("YASK-TPU internal error: " + msg)
