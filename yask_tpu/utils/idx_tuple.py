"""Ordered named-dimension tuples — the backbone of all size/index math.

TPU-native counterpart of the reference's ``Tuple<T>`` / ``IdxTuple``
(``src/common/tuple.hpp:130``, ``tuple.cpp``): an ordered map from dimension
name to integer value with elementwise arithmetic, N-D↔1-D layout math,
products, compact factorization (used for device-mesh grids the way the
reference uses it for MPI rank grids, ``setup.cpp:230``), and string
formatting.

Implemented natively in Python (dicts are ordered); a C++ fast path for the
layout/factorization math lives in ``yask_tpu/native`` and is used when built.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from yask_tpu.utils.exceptions import YaskException


class IdxTuple:
    """Ordered map of dimension name → int value.

    Construction::

        IdxTuple(x=4, y=5, z=6)
        IdxTuple({"x": 4, "y": 5})
        IdxTuple([("x", 4), ("y", 5)])
    """

    __slots__ = ("_map", "_first_inner")

    def __init__(self, arg=None, first_inner: bool = False, **kwargs):
        self._map: Dict[str, int] = {}
        # Layout convention: last dim is unit-stride ("inner") by default, as
        # on TPU where the minor-most axis maps to the 128-lane register dim.
        self._first_inner = first_inner
        if arg is not None:
            if isinstance(arg, IdxTuple):
                self._map.update(arg._map)
            elif isinstance(arg, dict):
                self._map.update(arg)
            else:
                for name, val in arg:
                    self._map[name] = val
        self._map.update(kwargs)
        for k, v in self._map.items():
            if not isinstance(k, str):
                raise YaskException(f"IdxTuple dim name {k!r} is not a string")
            self._map[k] = int(v)

    # ---- basic accessors -------------------------------------------------

    def get_num_dims(self) -> int:
        return len(self._map)

    def get_dim_names(self) -> List[str]:
        return list(self._map.keys())

    def get_vals(self) -> List[int]:
        return list(self._map.values())

    def has_dim(self, name: str) -> bool:
        return name in self._map

    def get_dim_posn(self, name: str) -> int:
        try:
            return self.get_dim_names().index(name)
        except ValueError:
            raise YaskException(f"dimension '{name}' not in {self}") from None

    def get_dim_name(self, posn: int) -> str:
        return self.get_dim_names()[posn]

    def __getitem__(self, key) -> int:
        if isinstance(key, int):
            return self.get_vals()[key]
        if key not in self._map:
            raise YaskException(f"dimension '{key}' not in {self}")
        return self._map[key]

    def get(self, key: str, default: Optional[int] = None) -> Optional[int]:
        return self._map.get(key, default)

    def __setitem__(self, key, val) -> None:
        if isinstance(key, int):
            key = self.get_dim_name(key)
        if key not in self._map:
            raise YaskException(f"dimension '{key}' not in {self}")
        self._map[key] = int(val)

    def add_dim_back(self, name: str, val: int) -> "IdxTuple":
        if name in self._map:
            raise YaskException(f"duplicate dimension '{name}'")
        self._map[name] = int(val)
        return self

    def add_dim_front(self, name: str, val: int) -> "IdxTuple":
        if name in self._map:
            raise YaskException(f"duplicate dimension '{name}'")
        new = {name: int(val)}
        new.update(self._map)
        self._map = new
        return self

    def remove_dim(self, name: str) -> "IdxTuple":
        self._map.pop(name, None)
        return self

    def set_vals_same(self, val: int) -> "IdxTuple":
        for k in self._map:
            self._map[k] = int(val)
        return self

    def set_vals(self, other: "IdxTuple", add_missing: bool = False) -> "IdxTuple":
        """Copy values from ``other`` for dims present here (optionally add)."""
        for k, v in other.items():
            if k in self._map:
                self._map[k] = int(v)
            elif add_missing:
                self._map[k] = int(v)
        return self

    def items(self) -> Iterable[Tuple[str, int]]:
        return self._map.items()

    def __iter__(self) -> Iterator[str]:
        return iter(self._map)

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, name: str) -> bool:
        return name in self._map

    def copy(self) -> "IdxTuple":
        return IdxTuple(self._map, first_inner=self._first_inner)

    # ---- reductions ------------------------------------------------------

    def product(self) -> int:
        p = 1
        for v in self._map.values():
            p *= v
        return p

    def sum(self) -> int:
        return sum(self._map.values())

    def max_val(self) -> int:
        return max(self._map.values())

    def min_val(self) -> int:
        return min(self._map.values())

    # ---- elementwise math ------------------------------------------------

    def _map_elements(self, op: Callable[[int, int], int], other) -> "IdxTuple":
        out = self.copy()
        if isinstance(other, IdxTuple):
            for k in out._map:
                if other.has_dim(k):
                    out._map[k] = op(out._map[k], other[k])
        else:
            for k in out._map:
                out._map[k] = op(out._map[k], int(other))
        return out

    def add_elements(self, other) -> "IdxTuple":
        return self._map_elements(lambda a, b: a + b, other)

    def sub_elements(self, other) -> "IdxTuple":
        return self._map_elements(lambda a, b: a - b, other)

    def mult_elements(self, other) -> "IdxTuple":
        return self._map_elements(lambda a, b: a * b, other)

    def min_elements(self, other) -> "IdxTuple":
        return self._map_elements(min, other)

    def max_elements(self, other) -> "IdxTuple":
        return self._map_elements(max, other)

    __add__ = add_elements
    __sub__ = sub_elements
    __mul__ = mult_elements

    def map_elements(self, fn: Callable[[int], int]) -> "IdxTuple":
        out = self.copy()
        for k in out._map:
            out._map[k] = int(fn(out._map[k]))
        return out

    # ---- comparisons -----------------------------------------------------

    def __eq__(self, other) -> bool:
        if not isinstance(other, IdxTuple):
            return NotImplemented
        return self._map == other._map

    def __hash__(self) -> int:
        return hash(tuple(self._map.items()))

    def are_dims_same(self, other: "IdxTuple", same_order: bool = True) -> bool:
        if same_order:
            return self.get_dim_names() == other.get_dim_names()
        return set(self.get_dim_names()) == set(other.get_dim_names())

    # ---- layout math (N-D ↔ 1-D) ----------------------------------------

    def layout(self, offsets: "IdxTuple") -> int:
        """Map an N-D point to a 1-D offset within this tuple's sizes.

        Counterpart of ``Tuple::layout`` (``tuple.hpp``). With the default
        last-inner convention the last dim is unit stride.
        """
        names = self.get_dim_names()
        if self._first_inner:
            names = list(reversed(names))
        idx = 0
        for name in names:  # outer → inner
            size = self._map[name]
            ofs = offsets[name]
            if not (0 <= ofs < size):
                raise YaskException(
                    f"offset {name}={ofs} out of bounds for size {size}")
            idx = idx * size + ofs
        return idx

    def unlayout(self, offset: int) -> "IdxTuple":
        """Inverse of :meth:`layout`: 1-D offset → N-D point."""
        if not (0 <= offset < max(self.product(), 1)):
            raise YaskException(f"1-D offset {offset} out of bounds for {self}")
        names = self.get_dim_names()
        if not self._first_inner:
            names = list(reversed(names))
        out = self.copy()
        for name in names:  # inner → outer
            size = self._map[name]
            out._map[name] = offset % size
            offset //= size
        return out

    def strides(self) -> "IdxTuple":
        """Per-dim 1-D stride under this layout."""
        names = self.get_dim_names()
        if self._first_inner:
            names_in_order = names
        else:
            names_in_order = list(reversed(names))
        out = self.copy()
        stride = 1
        for name in names_in_order:  # inner → outer
            out._map[name] = stride
            stride *= self._map[name]
        return out

    def visit_all_points(self) -> Iterator["IdxTuple"]:
        """Yield every point in the box ``[0, size)`` per dim, inner fastest."""
        n = self.product()
        for i in range(n):
            yield self.unlayout(i)

    # ---- factorization ---------------------------------------------------

    def get_compact_factors(self, n: int) -> "IdxTuple":
        """Factorize ``n`` into this tuple's dims as compactly as possible.

        Counterpart of ``get_compact_factors`` (reference ``setup.cpp:230``),
        used there to choose an MPI rank grid and here to choose a device-mesh
        grid: among all factorizations of ``n`` over the dims, pick the one
        minimizing the spread (max/min ratio), preferring larger factors in
        later (inner) dims to keep the minor axis long for TPU lanes.
        """
        ndims = self.get_num_dims()
        if ndims == 0:
            if n != 1:
                raise YaskException("cannot factorize into 0 dims")
            return self.copy()

        # Native fast path (the recursion is exponential in ndims).
        try:
            from yask_tpu import native
            if native.available():
                vals = native.compact_factors(n, ndims)
                out = self.copy()
                for name, val in zip(out.get_dim_names(), vals):
                    out._map[name] = val
                return out
        except (ImportError, ValueError):
            pass

        best: Optional[List[int]] = None
        best_score: Optional[Tuple[float, int]] = None

        def rec(rem: int, dims_left: int, acc: List[int]):
            nonlocal best, best_score
            if dims_left == 1:
                cand = acc + [rem]
                # Spread (lower better), then prefer increasing factors so the
                # inner-most (last) dim gets the biggest factor.
                spread = max(cand) / max(min(cand), 1)
                sortedness = sum(
                    1 for a, b in zip(cand, cand[1:]) if a > b)
                score = (spread, sortedness)
                if best_score is None or score < best_score:
                    best_score = score
                    best = cand
                return
            for f in range(1, rem + 1):
                if rem % f == 0:
                    rec(rem // f, dims_left - 1, acc + [f])

        rec(n, ndims, [])
        if best is None:
            raise YaskException(f"cannot factorize {n} into {ndims} dims")
        out = self.copy()
        for name, val in zip(out.get_dim_names(), best):
            out._map[name] = val
        return out

    # ---- formatting ------------------------------------------------------

    def make_dim_val_str(self, sep: str = ", ", infix: str = "=") -> str:
        return sep.join(f"{k}{infix}{v}" for k, v in self._map.items())

    def make_dim_str(self, sep: str = ", ") -> str:
        return sep.join(self._map.keys())

    def make_val_str(self, sep: str = ", ") -> str:
        return sep.join(str(v) for v in self._map.values())

    def __repr__(self) -> str:
        return f"IdxTuple({self.make_dim_val_str()})"

    def __str__(self) -> str:
        return "{" + self.make_dim_val_str() + "}"


def parse_dim_val_str(s: str) -> IdxTuple:
    """Parse ``"x=4,y=5"`` into an IdxTuple (inverse of make_dim_val_str)."""
    out = IdxTuple()
    s = s.strip()
    if not s:
        return out
    for part in s.split(","):
        part = part.strip()
        if "=" in part:
            k, v = part.split("=", 1)
            out.add_dim_back(k.strip(), int(v))
        else:
            raise YaskException(f"cannot parse dim=val from '{part}'")
    return out


def n_choose_k(n: int, k: int) -> int:
    """Binomial coefficient (counterpart of ``src/common/combo.cpp``)."""
    if k < 0 or k > n:
        return 0
    return math.comb(n, k)


def combination_at(n: int, k: int, index: int) -> List[int]:
    """Return the ``index``-th k-combination of ``range(n)`` in lexicographic
    order (counterpart of the enumeration helpers in ``combo.cpp``)."""
    if not (0 <= index < n_choose_k(n, k)):
        raise YaskException("combination index out of range")
    out: List[int] = []
    start = 0
    for slot in range(k):
        for v in range(start, n):
            c = n_choose_k(n - v - 1, k - slot - 1)
            if index < c:
                out.append(v)
                start = v + 1
                break
            index -= c
    return out
