"""Typed command-line option parser shared by compiler and kernel CLIs.

Counterpart of the reference's hand-rolled ``command_line_parser``
(``include/yask_common_api.hpp:334-``, impl ``src/common/common_utils.cpp``):
typed options (bool with ``-no-`` prefix, int, idx-tuple, double, string,
string-list), help formatting, and partial parsing that returns unconsumed
arguments so several option sets can share one command line — the property the
reference relies on to let ``yk_solution::apply_command_line_options`` and the
harness each take their own flags.
"""

from __future__ import annotations

import textwrap
from typing import Any, Callable, Dict, List, Optional, Sequence


from yask_tpu.utils.exceptions import YaskException


class _Option:
    def __init__(self, name: str, help_msg: str, kind: str,
                 target: Callable[[Any], None],
                 current: Callable[[], Any],
                 nargs: int = 1):
        self.name = name
        self.help_msg = help_msg
        self.kind = kind
        self.set = target
        self.current = current
        self.nargs = nargs


class CommandLineParser:
    """Typed option registry + parser.

    Options are registered against setter/getter callables (typically bound to
    attributes of a settings object), mirroring how the reference binds
    options directly to ``KernelSettings``/``CompilerSettings`` fields.
    """

    def __init__(self, width: int = 78):
        self._opts: Dict[str, _Option] = {}
        self._width = width

    # ---- registration ----------------------------------------------------

    def _bind(self, obj, attr):
        def setter(v):
            setattr(obj, attr, v)

        def getter():
            return getattr(obj, attr)
        return setter, getter

    def add_bool_option(self, name: str, help_msg: str, obj, attr: str) -> None:
        """Registers ``-name`` and ``-no-name`` (reference bool-option style)."""
        setter, getter = self._bind(obj, attr)
        self._opts[name] = _Option(name, help_msg, "bool", setter, getter, 0)

    def add_int_option(self, name: str, help_msg: str, obj, attr: str) -> None:
        setter, getter = self._bind(obj, attr)
        self._opts[name] = _Option(name, help_msg, "int", setter, getter)

    def add_float_option(self, name: str, help_msg: str, obj, attr: str) -> None:
        setter, getter = self._bind(obj, attr)
        self._opts[name] = _Option(name, help_msg, "float", setter, getter)

    def add_string_option(self, name: str, help_msg: str, obj, attr: str) -> None:
        setter, getter = self._bind(obj, attr)
        self._opts[name] = _Option(name, help_msg, "string", setter, getter)

    def add_string_list_option(self, name: str, help_msg: str, obj, attr: str) -> None:
        setter, getter = self._bind(obj, attr)
        self._opts[name] = _Option(name, help_msg, "strlist", setter, getter)

    def add_idx_option(self, name: str, help_msg: str, obj, attr: str,
                       dims: Optional[Sequence[str]] = None) -> None:
        """An option whose value applies to an IdxTuple attribute.

        Accepts either one value for all dims (``-b 64``) or per-dim options
        generated as ``-name_<dim>`` (``-bx 64`` style in the reference is
        spelled ``-b_x`` here).
        """
        setter, getter = self._bind(obj, attr)
        self._opts[name] = _Option(name, help_msg, "idx_all", setter, getter)
        tup = getter()
        for dim in (dims if dims is not None else tup.get_dim_names()):
            def make(dim_name):
                def dim_setter(v):
                    getter()[dim_name] = v
                return dim_setter
            self._opts[f"{name}_{dim}"] = _Option(
                f"{name}_{dim}", f"{help_msg} (dim '{dim}' only)",
                "int_dim", make(dim), getter)

    # ---- parsing ---------------------------------------------------------

    def parse_args(self, args: Sequence[str]) -> List[str]:
        """Consume recognized options; return leftover args (reference
        ``command_line_parser::parse_args`` contract)."""
        leftover: List[str] = []
        i = 0
        args = list(args)
        while i < len(args):
            arg = args[i]
            name = arg.lstrip("-") if arg.startswith("-") else None
            if name is None:
                leftover.append(arg)
                i += 1
                continue
            # bool negation
            if name.startswith("no-") and name[3:] in self._opts \
                    and self._opts[name[3:]].kind == "bool":
                self._opts[name[3:]].set(False)
                i += 1
                continue
            opt = self._opts.get(name)
            if opt is None:
                leftover.append(arg)
                i += 1
                continue
            if opt.kind == "bool":
                opt.set(True)
                i += 1
                continue
            if i + 1 >= len(args):
                raise YaskException(f"missing value for option -{name}")
            val = args[i + 1]
            try:
                if opt.kind == "int" or opt.kind == "int_dim":
                    opt.set(int(val))
                elif opt.kind == "float":
                    opt.set(float(val))
                elif opt.kind == "string":
                    opt.set(val)
                elif opt.kind == "strlist":
                    opt.set(val.split(","))
                elif opt.kind == "idx_all":
                    tup = opt.current()
                    tup.set_vals_same(int(val))
                else:  # pragma: no cover
                    raise YaskException(f"unknown option kind {opt.kind}")
            except ValueError:
                raise YaskException(
                    f"invalid value '{val}' for option -{name}") from None
            i += 2
        return leftover

    # ---- help ------------------------------------------------------------

    def print_help(self, out=None) -> str:
        lines: List[str] = []
        for name in sorted(self._opts):
            opt = self._opts[name]
            if opt.kind == "int_dim":
                continue  # summarized under the parent idx option
            cur = opt.current()
            flag = f"-[no-]{name}" if opt.kind == "bool" else f"-{name} <val>"
            lines.append(f"  {flag}")
            body = opt.help_msg
            if opt.kind == "idx_all":
                body += (" Also settable per dim via "
                         f"-{name}_<dim> <val>.")
            body += f" Current value = {cur}."
            lines.extend(textwrap.wrap(body, self._width,
                                       initial_indent="      ",
                                       subsequent_indent="      "))
        text = "\n".join(lines) + "\n"
        if out is not None:
            out.write(text)
        return text
