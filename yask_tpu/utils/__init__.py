"""Common substrate shared by the compiler and the kernel runtime.

TPU-native counterpart of the reference's ``src/common`` layer
(``tuple.hpp``, ``common_utils.cpp``, ``output.cpp``, ``fd_coeff2.cpp``) and
the shared pieces of ``include/yask_common_api.hpp``.
"""

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.utils.idx_tuple import IdxTuple
from yask_tpu.utils.cli import CommandLineParser
from yask_tpu.utils.output import yask_output_factory

__all__ = [
    "YaskException",
    "IdxTuple",
    "CommandLineParser",
    "yask_output_factory",
]
