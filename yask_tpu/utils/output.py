"""Pluggable output objects for codegen/debug text.

Counterpart of ``yask_output_factory`` and the four ``yask_output`` kinds in
the reference (``include/yask_common_api.hpp:184-272``, ``src/common/output.cpp``):
file, string, stdout, and null sinks, used for printer/debug output routing.
"""

from __future__ import annotations

import io
import sys
from typing import Optional


class yask_output:
    """Base output sink with a file-like ``write``."""

    def get_ostream(self):
        raise NotImplementedError

    def write(self, text: str) -> None:
        self.get_ostream().write(text)


class yask_file_output(yask_output):
    def __init__(self, path: str):
        self._path = path
        self._f = open(path, "w")

    def get_filename(self) -> str:
        return self._path

    def get_ostream(self):
        return self._f

    def close(self) -> None:
        self._f.close()


class yask_string_output(yask_output):
    def __init__(self):
        self._buf = io.StringIO()

    def get_ostream(self):
        return self._buf

    def get_string(self) -> str:
        return self._buf.getvalue()

    def discard(self) -> None:
        self._buf = io.StringIO()


class yask_stdout_output(yask_output):
    def get_ostream(self):
        return sys.stdout


class yask_null_output(yask_output):
    class _Null(io.TextIOBase):
        def write(self, s):  # noqa: D102
            return len(s)

    def __init__(self):
        self._null = self._Null()

    def get_ostream(self):
        return self._null


class yask_output_factory:
    """Factory mirroring ``yask_output_factory`` in the reference API."""

    def new_file_output(self, path: str) -> yask_file_output:
        return yask_file_output(path)

    def new_string_output(self) -> yask_string_output:
        return yask_string_output()

    def new_stdout_output(self) -> yask_stdout_output:
        return yask_stdout_output()

    def new_null_output(self) -> yask_null_output:
        return yask_null_output()
