"""Finite-difference coefficient generation.

Counterpart of the reference's public FD-coefficient API
(``include/yask_common_api.hpp:282-320``, impl ``src/common/fd_coeff2.cpp`` /
``src/contrib/coefficients/fd_coeff.cpp``, which solves a Vandermonde-style
system). Here we use Fornberg's recursive algorithm (Fornberg 1988, public
domain mathematics) which is numerically stabler than an explicit Vandermonde
solve and yields identical coefficients on uniform grids.

Signatures mirror the reference exactly:

* ``get_center_fd_coefficients(d, radius)`` → 2*radius+1 coefficients
* ``get_forward_fd_coefficients(d, accuracy_order)`` → accuracy_order+1
* ``get_backward_fd_coefficients(d, accuracy_order)`` → accuracy_order+1
* ``get_arbitrary_fd_coefficients(d, eval_point, sample_points)``
"""

from __future__ import annotations

from typing import List, Sequence

from yask_tpu.utils.exceptions import YaskException


def _fornberg_weights(d: int, x0: float, xs: Sequence[float]) -> List[float]:
    """Fornberg finite-difference weights for the d-th derivative at x0
    given sample points xs. Returns one weight per sample point.

    Uses the native C++ implementation (``yask_tpu/native/host.cpp``,
    ``yt_fd_weights``) when built; this Python path is the fallback and
    the executable specification."""
    n = len(xs)
    if n < 2:
        raise YaskException("need at least 2 sample points for FD coefficients")
    if d < 1:
        raise YaskException("derivative_order must be >= 1")
    if d >= n:
        raise YaskException(
            f"derivative order {d} needs more than {n} sample points")
    try:
        from yask_tpu import native
        if native.available():
            return native.fd_weights(d, x0, list(xs))
    except Exception:
        pass
    # c[k][j]: weight of xs[j] for the k-th derivative using points xs[0..i].
    c = [[0.0] * n for _ in range(d + 1)]
    c[0][0] = 1.0
    c1 = 1.0
    c4 = xs[0] - x0
    for i in range(1, n):
        mn = min(i, d)
        c2 = 1.0
        c5 = c4
        c4 = xs[i] - x0
        for j in range(i):
            c3 = xs[i] - xs[j]
            c2 *= c3
            if j == i - 1:
                for k in range(mn, 0, -1):
                    c[k][i] = c1 * (k * c[k - 1][i - 1]
                                    - c5 * c[k][i - 1]) / c2
                c[0][i] = -c1 * c5 * c[0][i - 1] / c2
            for k in range(mn, 0, -1):
                c[k][j] = (c4 * c[k][j] - k * c[k - 1][j]) / c3
            c[0][j] = c4 * c[0][j] / c3
        c1 = c2
    return c[d]


def get_arbitrary_fd_coefficients(derivative_order: int, eval_point: float,
                                  sample_points: Sequence[float]) -> List[float]:
    """FD coefficients at arbitrary evaluation and sample points
    (``yask_common_api.hpp:316``)."""
    return _fornberg_weights(derivative_order, eval_point,
                             list(map(float, sample_points)))


def get_center_fd_coefficients(derivative_order: int, radius: int) -> List[float]:
    """Center-form FD coefficients: ``radius`` points on each side, returning
    ``2*radius+1`` coefficients with ``2*radius``-order accuracy
    (``yask_common_api.hpp:282``)."""
    if radius < 1:
        raise YaskException("radius must be >= 1")
    pts = [float(i) for i in range(-radius, radius + 1)]
    return _fornberg_weights(derivative_order, 0.0, pts)


def get_forward_fd_coefficients(derivative_order: int,
                                accuracy_order: int) -> List[float]:
    """Forward-form FD coefficients: ``accuracy_order`` points to the right,
    returning ``accuracy_order+1`` coefficients (``yask_common_api.hpp:294``)."""
    if accuracy_order < 1:
        raise YaskException("accuracy_order must be >= 1")
    pts = [float(i) for i in range(0, accuracy_order + 1)]
    return _fornberg_weights(derivative_order, 0.0, pts)


def get_backward_fd_coefficients(derivative_order: int,
                                 accuracy_order: int) -> List[float]:
    """Backward-form FD coefficients: ``accuracy_order`` points to the left
    (``yask_common_api.hpp:306``)."""
    if accuracy_order < 1:
        raise YaskException("accuracy_order must be >= 1")
    pts = [float(i) for i in range(-accuracy_order, 1)]
    return _fornberg_weights(derivative_order, 0.0, pts)
