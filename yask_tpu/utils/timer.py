"""Wall-clock timers for runtime stats.

Counterpart of ``YaskTimer`` (reference ``src/common/common_utils.hpp``):
start/stop accumulation with nesting guard, used by the runtime for per-phase
accounting (run/halo/compile time — ``context.hpp:318-328``).
"""

from __future__ import annotations

import time


class YaskTimer:
    __slots__ = ("_elapsed", "_start", "_running")

    def __init__(self):
        self._elapsed = 0.0
        self._start = 0.0
        self._running = False

    def clear(self) -> None:
        self._elapsed = 0.0
        self._running = False

    def start(self) -> None:
        if self._running:
            return
        self._start = time.perf_counter()
        self._running = True

    def stop(self) -> float:
        if self._running:
            self._elapsed += time.perf_counter() - self._start
            self._running = False
        return self._elapsed

    def get_elapsed_secs(self) -> float:
        if self._running:
            return self._elapsed + (time.perf_counter() - self._start)
        return self._elapsed

    def __enter__(self) -> "YaskTimer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
