"""Deterministic var initialization for validation/benchmark runs.

The single implementation of the harness' ``-init_seed`` pattern
(reference ``yask_main.cpp:239-249``), shared by the harness CLI, the test
suite's oracle sweeps, and the bitwise cross-backend checker so their
conditioning never diverges: written (state) vars get a position-dependent
sequence; read-only coefficient vars get values near 1 with small
variation — safe as divisors (1/ρ forms) and mild as multipliers so deep
fp32 expression trees stay out of the cancellation regime.

``sub_sizes`` (serve-side shape bucketing) restricts the fill to the
low-corner sub-domain and — critically — generates the SAME values a
solo context at those sizes would: the value sequence is laid out over
the sub-domain shape, not the host geometry's, so a bucketed tenant
and its solo oracle start bit-identical.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def _fill_interior(ctx, name: str, value_fn,
                   sub_sizes: Optional[Dict[str, int]] = None) -> None:
    """Zero the array, then fill the (sub-)interior with
    ``value_fn(n)`` values laid out over the interior shape — interior
    coordinates only, so differently-padded (or bucket-hosted)
    contexts start identical."""
    g = ctx._program.geoms[name]
    for slot in range(len(ctx._state[name])):
        def fill(a, s=slot):
            idxs, ishape = [], []
            for ax, (dn, kind) in enumerate(g.axes):
                if kind == "domain":
                    size = ctx._opts.global_domain_sizes[dn]
                    if sub_sizes is not None:
                        size = int(sub_sizes.get(dn, size))
                    idxs.append(slice(g.origin[dn],
                                      g.origin[dn] + size))
                    ishape.append(size)
                else:
                    idxs.append(slice(None))
                    ishape.append(a.shape[ax])
            n = int(np.prod(ishape)) if ishape else 1
            vals = value_fn(n, s)
            out = np.zeros_like(a)
            out[tuple(idxs)] = vals.reshape(ishape).astype(a.dtype) \
                if ishape else vals.astype(a.dtype)[0]
            return out
        ctx._update_state_array(name, slot, fill)


def init_solution_vars(ctx, seed: float = 0.05,
                       sub_sizes: Optional[Dict[str, int]] = None
                       ) -> None:
    ctx._materialize_state()   # sync any device-resident shard interiors
    written = {eq.lhs.var_name() for eq in ctx._soln.get_equations()}
    for i, name in enumerate(sorted(ctx.get_var_names())):
        if name in written:
            if sub_sizes is None:
                ctx.get_var(name).set_elements_in_seq(seed * (1 + i % 3))
            else:
                # the set_elements_in_seq value law over the SUB shape
                s0 = seed * (1 + i % 3)
                _fill_interior(
                    ctx, name,
                    lambda n, s, s0=s0:
                        (np.arange(n, dtype=np.float64) % 17 + 1.0)
                        * s0 * (s + 1),
                    sub_sizes)
        else:
            _fill_interior(
                ctx, name,
                lambda n, s: 1.0 + 0.01 * (np.arange(n) % 13),
                sub_sizes)
