"""Deterministic var initialization for validation/benchmark runs.

The single implementation of the harness' ``-init_seed`` pattern
(reference ``yask_main.cpp:239-249``), shared by the harness CLI, the test
suite's oracle sweeps, and the bitwise cross-backend checker so their
conditioning never diverges: written (state) vars get a position-dependent
sequence; read-only coefficient vars get values near 1 with small
variation — safe as divisors (1/ρ forms) and mild as multipliers so deep
fp32 expression trees stay out of the cancellation regime.
"""

from __future__ import annotations

import numpy as np


def init_solution_vars(ctx, seed: float = 0.05) -> None:
    ctx._materialize_state()   # sync any device-resident shard interiors
    written = {eq.lhs.var_name() for eq in ctx._soln.get_equations()}
    for i, name in enumerate(sorted(ctx.get_var_names())):
        if name in written:
            ctx.get_var(name).set_elements_in_seq(seed * (1 + i % 3))
        else:
            g = ctx._program.geoms[name]
            for slot in range(len(ctx._state[name])):
                def fill(a):
                    # interior-coordinate based, like set_elements_in_seq:
                    # identical values whatever the pad geometry
                    idxs, ishape = [], []
                    for ax, (dn, kind) in enumerate(g.axes):
                        if kind == "domain":
                            size = ctx._opts.global_domain_sizes[dn]
                            idxs.append(slice(g.origin[dn],
                                              g.origin[dn] + size))
                            ishape.append(size)
                        else:
                            idxs.append(slice(None))
                            ishape.append(a.shape[ax])
                    n = int(np.prod(ishape)) if ishape else 1
                    vals = 1.0 + 0.01 * (np.arange(n) % 13)
                    out = np.zeros_like(a)
                    out[tuple(idxs)] = vals.reshape(ishape).astype(a.dtype) \
                        if ishape else vals.astype(a.dtype)[0]
                    return out
                ctx._update_state_array(name, slot, fill)
