"""The stencil context: ``yk_solution`` driving compiled step programs.

Counterpart of the reference's ``StencilContext``
(``src/kernel/lib/context.hpp:231-786``, ``context.cpp``, ``soln_apis.cpp``):
owns settings, vars, and state storage; ``prepare_solution`` performs the
setup pipeline (decomposition → geometry → allocation, mirroring
``soln_apis.cpp:137-250``); ``run_solution`` advances steps on the selected
execution path; ``run_ref``/``compare_data`` implement the validation oracle
(``context.cpp:46``, ``yask_main.cpp:564-616``).

Execution modes (see ``KernelSettings.mode``):

* ``jit`` — one device: the whole step traced and XLA-fused, steps advanced
  under ``lax.scan`` with donated (ring-rotated) state.
* ``sharded`` — global arrays with ``NamedSharding`` over the device mesh;
  the same traced step; XLA inserts halo collectives for the shifted reads
  (the idiomatic-TPU replacement for MPI halo exchange).
* ``shard_map`` — explicit per-shard program with ``lax.ppermute`` ghost
  exchange (the structural twin of the reference's ``exchange_halos``,
  ``halo.cpp``), used for overlap control and as the scaling path.
* ``ref`` — eager numpy oracle (the reference's scalar ``run_ref``).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.utils.cli import CommandLineParser
from yask_tpu.runtime.env import yk_env
from yask_tpu.runtime.run_state import RunState
from yask_tpu.runtime.settings import KernelSettings
from yask_tpu.runtime.stats import yk_stats
from yask_tpu.runtime.var import yk_var


class StencilContext:
    """One runnable instance of a compiled stencil solution."""

    def __init__(self, env: yk_env, source, dtype=None):
        self._env = env
        # Accept a yc_solution_base (defines on demand), a yc_solution, or a
        # pre-lowered CompiledSolution — the flexibility the reference gets
        # from linking any generated solution into yk_factory.
        from yask_tpu.compiler.solution import yc_solution
        from yask_tpu.compiler.solution_base import yc_solution_base
        from yask_tpu.compiler.lowering import CompiledSolution
        if isinstance(source, yc_solution_base):
            source.run_define()
            soln = source.get_soln()
            self._csol = soln.compile(dtype=dtype)
        elif isinstance(source, yc_solution):
            self._csol = source.compile(dtype=dtype)
        elif isinstance(source, CompiledSolution):
            self._csol = source
        else:
            raise YaskException(
                f"cannot build a kernel solution from {type(source).__name__}")
        self._soln = self._csol.soln
        self._ana = self._csol.ana

        self._opts = KernelSettings(self._ana.domain_dims)
        self._program = None          # StepProgram (compute geometry)
        # ALL per-run mutable state (var rings, resident shard
        # interiors, step position, run/halo timers) lives in the
        # active RunState; the historical attribute names below
        # (_state, _resident, _cur_step, …) are delegating properties,
        # so one prepared solution can serve many swapped runs
        # (ensemble members, repeated sweeps) without re-preparing.
        self._run = RunState()
        self._vars: Dict[str, yk_var] = {}
        self._mode = None
        self._mesh = None
        self._shardings = None
        self._rank_offset: Dict[str, int] = {
            d: 0 for d in self._ana.domain_dims}
        self._jit_cache: Dict = {}
        self._pallas_tiling: Dict = {}  # build key → tiling actually chosen
        self._comm_plans: Dict = {}     # (mode, K, knobs) → CommPlan

        self._compile_secs = 0.0
        self._last_cache_hit = None     # cache verdict of latest build
        # cross-solution pipeline fusion (yask_tpu.ops.pipeline): the
        # merged-chain signature is one more variant-key dimension —
        # a fused chain must never alias an unfused solution's cached
        # executable — and the owning SolutionPipeline registers
        # itself for the tuner's fused-vs-chained arm.
        self._pipeline_sig = None
        self._pipeline = None
        self._pipeline_plan = None

        self._hooks: Dict[str, List[Callable]] = {
            "before_prepare": [], "after_prepare": [],
            "before_run": [], "after_run": []}
        self._trace_dir: Optional[str] = None

        # yc_solution::call_after_new_solution hooks run now — right
        # after kernel-solution construction, as the reference injects
        # its code block at the end of yk_factory::new_solution
        for code in getattr(self._soln, "_after_new_solution", ()):
            if callable(code):
                code(self)
            else:
                exec(compile(str(code), "<call_after_new_solution>",
                             "exec"), {"kernel_soln": self})

    # ------------------------------------------------------------------
    # per-run state delegation (RunState hoist)
    # ------------------------------------------------------------------
    # The historical attribute names stay valid for every consumer
    # (var.py, shard_step.py, the tools) but resolve through the
    # active RunState so whole runs can be swapped under one prepared
    # solution (ensemble batching, repeated sweeps).

    @property
    def _state(self):
        return self._run.state

    @_state.setter
    def _state(self, v):
        self._run.state = v

    @property
    def _resident(self):
        return self._run.resident

    @_resident.setter
    def _resident(self, v):
        self._run.resident = v

    @property
    def _state_on_device(self):
        return self._run.state_on_device

    @_state_on_device.setter
    def _state_on_device(self, v):
        self._run.state_on_device = v

    @property
    def _cur_step(self):
        return self._run.cur_step

    @_cur_step.setter
    def _cur_step(self, v):
        self._run.cur_step = v

    @property
    def _steps_done(self):
        return self._run.steps_done

    @_steps_done.setter
    def _steps_done(self, v):
        self._run.steps_done = v

    @property
    def _run_timer(self):
        return self._run.run_timer

    @property
    def _halo_timer(self):
        return self._run.halo_timer

    def get_run_state(self) -> RunState:
        """The active per-run state bundle."""
        return self._run

    def set_run_state(self, rs: RunState) -> RunState:
        """Swap in another run's state bundle; returns the previous
        one.  The solution side (program, jit cache, tiling) is
        untouched — that is the point: one compile, many runs."""
        prev, self._run = self._run, rs
        return prev

    def new_run_state(self) -> RunState:
        """A fresh zero-state run over the prepared geometry (the
        ensemble-member allocator).  Mirrors ``prepare_solution``'s
        allocation: zero-filled rings, pads identically zero,
        shardings applied when the mode shards resting state."""
        self._check_prepared()
        rs = RunState()
        rs.state = self._program.alloc_state()
        rs.state_on_device = True
        if self._shardings is not None:
            import jax
            rs.state = {name: [jax.device_put(a, self._shardings[name])
                               for a in ring]
                        for name, ring in rs.state.items()}
        return rs

    def new_ensemble(self, n: Optional[int] = None) -> "EnsembleRun":
        """N members of this prepared solution batched as one vmapped
        program (``yask_tpu.runtime.ensemble``).  ``n`` defaults to
        the ``-ensemble`` setting; member 0 adopts the context's
        current run state (initial conditions already set stay
        member 0's)."""
        from yask_tpu.runtime.ensemble import EnsembleRun
        return EnsembleRun(self, n if n is not None
                           else max(self._opts.ensemble, 1))

    # ------------------------------------------------------------------
    # identity / settings / vars
    # ------------------------------------------------------------------

    def get_name(self) -> str:
        return self._soln.get_name()

    def get_description(self) -> str:
        return self._soln.get_description()

    def get_env(self) -> yk_env:
        return self._env

    def get_settings(self) -> KernelSettings:
        return self._opts

    def get_step_dim_name(self) -> str:
        return self._ana.step_dim or ""

    def get_domain_dim_names(self) -> List[str]:
        return list(self._ana.domain_dims)

    def set_overall_domain_size(self, dim: str, size: int) -> None:
        self._opts.global_domain_sizes[dim] = size

    def set_overall_domain_size_vec(self, sizes) -> None:
        for d, v in (sizes.items() if hasattr(sizes, "items") else sizes):
            self._opts.global_domain_sizes[d] = v

    def get_overall_domain_size(self, dim: str) -> int:
        return self._opts.global_domain_sizes[dim]

    def set_rank_domain_size(self, dim: str, size: int) -> None:
        self._opts.rank_domain_sizes[dim] = size

    def get_rank_domain_size(self, dim: str) -> int:
        return self._opts.rank_domain_sizes[dim]

    def set_block_size(self, dim: str, size: int) -> None:
        self._opts.block_sizes[dim] = size

    def get_block_size(self, dim: str) -> int:
        return self._opts.block_sizes[dim]

    def get_element_bytes(self) -> int:
        """Bytes per FP element (reference ``yk_solution::get_element_bytes``,
        driven by ``swe_main.cpp:398``)."""
        return int(np.dtype(self._csol.dtype).itemsize)

    def set_num_ranks(self, dim: str, n: int) -> None:
        self._opts.num_ranks[dim] = n

    def get_num_ranks(self, dim: str) -> int:
        return self._opts.num_ranks[dim]

    def get_num_vars(self) -> int:
        return len([v for v in self._soln.get_vars() if not v.is_scratch()])

    def get_var_names(self) -> List[str]:
        return [v.get_name() for v in self._soln.get_vars()
                if not v.is_scratch()]

    def get_var(self, name: str) -> yk_var:
        if name not in self._vars:
            raise YaskException(
                f"no var '{name}' (or prepare_solution not called)")
        return self._vars[name]

    def get_vars(self) -> List[yk_var]:
        return list(self._vars.values())

    def new_fixed_size_var(self, name: str, dim_names, dim_sizes):
        """Create standalone N-D storage with the var data API
        (``yk_solution::new_fixed_size_var``); not part of stepping."""
        from yask_tpu.runtime.var import FixedSizeVar
        v = FixedSizeVar(name, list(dim_names), list(dim_sizes))
        self._fixed_vars = getattr(self, "_fixed_vars", {})
        self._fixed_vars[name] = v
        return v

    def copy_vars_to_device(self) -> None:
        """Force state onto device (``yk_solution::copy_vars_to_device``;
        mostly a no-op here since runs keep state resident)."""
        self._check_prepared()
        self._state_to_device()

    def copy_vars_from_device(self) -> None:
        self._check_prepared()
        self._state_to_host()

    def fuse_vars(self, other: "StencilContext") -> None:
        """Share storage with another prepared context where var geometry
        matches (``yk_solution::fuse_vars``, used by the reference's
        validation flow to alias vars between solutions). Arrays are
        immutable under JAX, so sharing is simply adopting references.

        Caveat: the jit path's compiled chunks donate their input
        buffers, so after either context RUNS, buffers previously shared
        through fuse_vars may be consumed — re-fuse after runs rather
        than relying on stale aliases."""
        self._check_prepared()
        other._check_prepared()
        self._materialize_state()
        other._materialize_state()
        for name, ring in other._state.items():
            if name not in self._state:
                continue
            mine = self._state[name]
            if len(mine) != len(ring):
                continue
            ok = all(tuple(np.asarray(a).shape) == tuple(np.asarray(b).shape)
                     for a, b in zip(mine, ring))
            if ok:
                self._state[name] = list(ring)

    def first_domain_index(self, dim: str) -> int:
        return 0

    def last_domain_index(self, dim: str) -> int:
        return self._opts.global_domain_sizes[dim] - 1

    # ------------------------------------------------------------------
    # hooks (yk_solution hook registration, soln_apis.cpp)
    # ------------------------------------------------------------------

    def call_before_prepare_solution(self, fn: Callable) -> None:
        self._hooks["before_prepare"].append(fn)

    def call_after_prepare_solution(self, fn: Callable) -> None:
        self._hooks["after_prepare"].append(fn)

    def call_before_run_solution(self, fn: Callable) -> None:
        self._hooks["before_run"].append(fn)

    def call_after_run_solution(self, fn: Callable) -> None:
        self._hooks["after_run"].append(fn)

    # ------------------------------------------------------------------
    # prepare
    # ------------------------------------------------------------------

    def _plan_geometry(self):
        """Settings adjustment → mode resolution → var-geometry planning,
        WITHOUT allocating any state or marking the context prepared.

        Returns the planned :class:`StepProgram`.  ``prepare_solution``
        assigns it to ``self._program`` and allocates; the static
        checker (``yask_tpu.checker``) calls this directly so a 512³
        feasibility question never materializes gigabytes of state —
        ``plan()`` is pure geometry (``alloc_state`` is a separate
        step).  Sets ``self._mode`` / ``self._plan_kwargs`` but NOT
        ``self._program`` (``is_prepared()`` keys off the latter)."""
        ndev = self._env.get_num_ranks()
        self._opts.adjust_settings(ndev)

        mode = self._opts.mode
        nranks = self._opts.num_ranks.product()
        if mode == "auto":
            mode = "jit" if nranks == 1 else "sharded"
        if self._opts.force_scalar:
            mode = "ref"
        self._mode = mode

        extra = {d: (self._opts.min_pad_sizes[d], self._opts.min_pad_sizes[d])
                 for d in self._ana.domain_dims}
        gsizes = self._opts.global_domain_sizes

        if mode in ("shard_map", "shard_pallas"):
            from yask_tpu.parallel.decomp import validate_shard_geometry
            validate_shard_geometry(self._csol, self._opts)
        if mode == "shard_pallas":
            from yask_tpu.ops.pallas_stencil import pallas_applicable
            ok, why = pallas_applicable(self._csol)
            if not ok:
                raise YaskException(
                    f"solution '{self.get_name()}' cannot use the "
                    f"shard_pallas path: {why}; use -mode shard_map")

        # Compute geometry is always the *global* problem; the shard_map
        # path re-plans per-shard geometry inside the mapped region.
        # Sharded mode needs padded extents divisible by the mesh extent
        # (jax requires whole-dim divisibility for NamedSharding).
        pad_mult = None
        if mode == "sharded":
            pad_mult = {d: self._opts.num_ranks[d]
                        for d in self._ana.domain_dims
                        if self._opts.num_ranks[d] > 1}
        if mode == "pallas":
            # The fused Pallas path needs pad ≥ radius × fuse_steps in the
            # leading (tiled) dims so halo tiles can be DMA'd whole.
            from yask_tpu.ops.pallas_stencil import pallas_applicable
            ok, why = pallas_applicable(self._csol)
            if not ok:
                raise YaskException(
                    f"solution '{self.get_name()}' cannot use the pallas "
                    f"path: {why}; use -mode jit")
            K = max(self._opts.wf_steps, 1)
            if self._opts.do_auto_tune:
                # Plan pads for the largest K the joint walk may try so
                # the tuner can grow K, not only shrink it (the pads are
                # zero-filled and cheap; without this every K-doubling
                # candidate fails pad validation and caches as inf).
                K = max(K, self._opts.tune_max_wf_steps)
            for d, (need, need_r) in self._pallas_pad_needs(K).items():
                l, r = extra[d]
                extra[d] = (max(l, need), max(r, need_r))
        # Mosaic lane/sublane alignment only serves the manual-DMA Pallas
        # paths; the XLA/ref paths keep minimal pads (the r3 headline
        # regression was the lane round-up taxing the jit path).
        self._plan_kwargs = dict(extra_pad=extra, pad_multiple=pad_mult,
                                 mosaic_align=mode in ("pallas",
                                                       "shard_pallas"))
        return self._csol.plan(gsizes, **self._plan_kwargs)

    def prepare_solution(self) -> None:
        """Setup pipeline (reference ``prepare_solution``,
        ``soln_apis.cpp:137-250``): settings adjustment → decomposition →
        var geometry → state allocation."""
        for h in self._hooks["before_prepare"]:
            h(self)
        self._ended = False
        self._program = self._plan_geometry()
        mode = self._mode
        self._resident = None
        self._state = self._program.alloc_state()
        self._state_on_device = True

        if mode in ("sharded", "shard_map", "shard_pallas"):
            from yask_tpu.parallel.mesh import build_mesh, state_shardings
            self._mesh = build_mesh(self._env, self._opts)
            if mode == "sharded":
                # Resting state lives sharded over the mesh. (shard_map mode
                # keeps resting state unsharded: its run path shards the
                # interiors itself with per-shard ghost pads.)
                self._shardings = state_shardings(
                    self._mesh, self._program, self._opts)
                self._apply_shardings()

        self._vars = {v.get_name(): yk_var(self, v.get_name())
                      for v in self._soln.get_vars() if not v.is_scratch()}
        self._cur_step = 0
        self._jit_cache.clear()
        self._pallas_tiling.clear()
        self._comm_plans.clear()
        self._halo_frac = {}
        self._halo_xround = {}       # key -> secs per bare exchange round
        self._halo_xpack = {}        # key -> secs pack-only (no collective)
        self._halo_cal_spread = {}   # key -> rel spread of the twin trials
        self._halo_cal_unstable = {}  # key -> outliers survived re-time
        self._halo_cal_reps = {}     # key -> total calibration reps run
        self._halo_tcall = {}        # key -> secs per full timed call
        self._halo_overlap_eff = {}  # key -> hidden collective fraction
        self._halo_nperm = {}        # key -> traced collectives per round
        self._halo_nperm_last = 0
        self._halo_xround_last = 0.0
        self._halo_xpack_last = 0.0
        self._halo_cal_spread_last = 0.0
        self._halo_cal_unstable_last = False
        self._halo_cal_reps_last = 0
        self._halo_overlap_eff_last = 0.0
        for h in self._hooks["after_prepare"]:
            h(self)

    def is_prepared(self) -> bool:
        return self._program is not None

    def _apply_shardings(self) -> None:
        import jax
        for name, ring in self._state.items():
            sh = self._shardings[name]
            self._state[name] = [jax.device_put(a, sh) for a in ring]

    # ------------------------------------------------------------------
    # state plumbing
    # ------------------------------------------------------------------

    def _check_prepared(self):
        if self._program is None:
            if getattr(self, "_ended", False):
                raise YaskException(
                    "end_solution was called; call prepare_solution "
                    "again to run")
            raise YaskException("prepare_solution has not been called")

    def _materialize_state(self) -> None:
        """Re-attach the (zero) global pads if state currently lives as
        device-resident sharded interiors — the lazy sync point for any
        host-visible var access between shard-mode runs."""
        if self._resident is None and self._state is None:
            if getattr(self, "_ended", False):
                raise YaskException(
                    "end_solution was called; call prepare_solution "
                    "again to access var data")
            raise YaskException(
                "solution state was lost (a shard-mode run failed after "
                "its buffers were donated); call prepare_solution again")
        if self._resident is not None:
            from yask_tpu.parallel.shard_step import _repad_global
            res, self._resident = self._resident, None
            self._state = _repad_global(self._program, list(res), res)
            self._state_on_device = True

    def _update_state_array(self, name: str, slot: int, fn) -> None:
        self._check_prepared()
        self._materialize_state()
        arr = self._state[name][slot]
        new = fn(np.asarray(arr))
        # Physical-boundary ghost cells are identically zero in every
        # execution mode (the value unexchanged halos hold in the reference
        # unless explicitly managed); masking here keeps jit / sharded /
        # shard_map / ref bit-consistent at domain edges.
        new = self._zero_pads(name, np.array(new))
        if self._state_on_device:
            import jax
            if self._shardings is not None:
                new = jax.device_put(new.astype(np.asarray(arr).dtype),
                                     self._shardings[name])
            else:
                new = jax.device_put(new.astype(np.asarray(arr).dtype))
        self._state[name][slot] = new

    def _zero_pads(self, name: str, arr: np.ndarray) -> np.ndarray:
        g = self._program.geoms[name]
        idxs = []
        for dn, kind in g.axes:
            if kind == "domain":
                idxs.append(slice(g.origin[dn],
                                  g.origin[dn]
                                  + self._opts.global_domain_sizes[dn]))
            else:
                idxs.append(slice(None))
        out = np.zeros_like(arr)
        out[tuple(idxs)] = arr[tuple(idxs)]
        return out

    def _state_to_host(self) -> None:
        self._materialize_state()
        if self._state_on_device:
            self._state = {k: [np.asarray(a) for a in ring]
                           for k, ring in self._state.items()}
            self._state_on_device = False

    def _state_to_device(self) -> None:
        if self._resident is not None:
            if self._mode in ("shard_map", "shard_pallas"):
                return  # interiors already device-resident (sharded)
            self._materialize_state()  # non-shard path needs padded state
        if not self._state_on_device:
            import jax
            from yask_tpu.obs.tracer import span
            # the host→device staging window is the DMA phase a trace
            # can actually observe (in-kernel DMA never re-enters
            # Python)
            with span("state.to_device", phase="dma",
                      nvars=len(self._state)):
                out = {}
                for k, ring in self._state.items():
                    if self._shardings is not None:
                        out[k] = [jax.device_put(a, self._shardings[k])
                                  for a in ring]
                    else:
                        out[k] = [jax.device_put(a) for a in ring]
                self._state = out
            self._state_on_device = True

    # ------------------------------------------------------------------
    # run
    # ------------------------------------------------------------------

    def _step_seq(self, first_t: int, last_t: int):
        """Evaluation order for the step range (ascending for forward
        stencils, descending for reverse-time, reference ``run_solution``
        stride handling)."""
        if first_t > last_t:
            first_t, last_t = last_t, first_t
        n = last_t - first_t + 1
        start = first_t if self._ana.step_dir > 0 else last_t
        return start, n

    def run_solution(self, first_step_index: int,
                     last_step_index: Optional[int] = None) -> None:
        """Apply the stencil for the given step indices (inclusive), the
        reference's ``run_solution(first_t, last_t)`` hot path."""
        self._check_prepared()
        if last_step_index is None:
            last_step_index = first_step_index
        for h in self._hooks["before_run"]:
            h(self)
        start, n = self._step_seq(first_step_index, last_step_index)

        # Supervised mode: checkpoint cadence / watchdog / deadline knobs
        # re-enter run_solution per chunk with hooks swapped out, exactly
        # like trace mode below.  All-zero knobs (the default) make this
        # three int compares — a true no-op on the hot path.
        o = self._opts
        if (o.ckpt_every > 0 or o.watchdog_every > 0
                or o.run_deadline_secs > 0) \
                and not getattr(self, "_in_supervised", False):
            hooks, self._hooks = self._hooks, {k: [] for k in self._hooks}
            try:
                self._run_supervised(start, n)
            finally:
                self._hooks = hooks
            for h in self._hooks["after_run"]:
                h(self)
            return

        # Trace mode: advance one step at a time, dumping written state
        # after each (trace_mem analog). Hooks fire once for the whole
        # span, exactly as untraced.
        if self._trace_dir and n > 1:
            t = start
            hooks, self._hooks = self._hooks, {k: [] for k in self._hooks}
            try:
                for _ in range(n):
                    self.run_solution(t, t)
                    t += self._ana.step_dir
            finally:
                self._hooks = hooks
            for h in self._hooks["after_run"]:
                h(self)
            return

        if self._opts.do_auto_tune and self._mode in (
                "jit", "sharded", "pallas", "shard_pallas"):
            from yask_tpu.runtime.auto_tuner import AutoTuner
            AutoTuner(self).tune_if_needed()

        if self._mode == "ref":
            self._run_ref_steps(start, n)
        elif self._mode == "pallas":
            self._run_pallas_steps(start, n)
        elif self._mode in ("shard_map", "shard_pallas"):
            from yask_tpu.parallel.shard_step import (run_shard_map,
                                                      run_shard_pallas)
            runner = run_shard_map if self._mode == "shard_map" \
                else run_shard_pallas
            self._state_to_device()
            # wf_steps chunks the span so ONE compiled program length
            # serves any run length (programs are cached per length);
            # interiors stay device-resident across chunks. The runner
            # does its own timer accounting: halo calibration and twin
            # compiles must stay out of elapsed.
            wf = self._opts.wf_steps if self._opts.wf_steps > 0 else n
            if self._mode == "shard_pallas":
                wf = n   # its fusion/grouping happens inside the program
            from yask_tpu.obs.tracer import span
            t, rem = start, n
            while rem > 0:
                k = min(wf, rem)
                with span(f"run.{self._mode}", phase="compute",
                          first=t, k=k) as sp:
                    runner(self, t, k)
                    # the calibrated halo split rides the chunk span:
                    # obs_report separates exchange from compute with
                    # it (0.0 = unsplit; unstable cal = no split)
                    sp.set(halo_frac=float(
                        getattr(self, "_halo_frac_last", 0.0) or 0.0),
                        halo_unstable=bool(
                            getattr(self, "_halo_cal_unstable_last",
                                    False)))
                t += k * self._ana.step_dir
                rem -= k
        else:
            self._run_jit_steps(start, n)

        self._cur_step = start + n * self._ana.step_dir
        self._steps_done += n
        if self._trace_dir:
            self._trace_dump(self._cur_step)
        for h in self._hooks["after_run"]:
            h(self)

    def _run_ref_steps(self, start: int, n: int) -> None:
        from yask_tpu.compiler.lowering import NumpyOps
        self._state_to_host()
        prog = self._csol.plan(self._opts.global_domain_sizes,
                               ops=NumpyOps(), **self._plan_kwargs)
        with self._run_timer:
            t = start
            for _ in range(n):
                self._state = prog.step(self._state, t)
                t += self._ana.step_dir

    # ------------------------------------------------------------------
    # supervised runs: checkpoint cadence, watchdog, degradation ladder
    # ------------------------------------------------------------------

    def _run_supervised(self, start: int, n: int) -> None:
        """Chunked run with checkpoint cadence, per-chunk deadline, a
        cheap device-state watchdog, and on a classified fault a rollback
        to the last good snapshot + retry down the mode-degradation
        ladder (``shard_pallas → shard_map → jit``, ``pallas → jit``).

        Snapshots are interior-coordinate (:mod:`..resilience.checkpoint`)
        so a rollback taken in one mode restores bit-identically into the
        next rung.  A LOCAL breaker (recorded manually — chunk successes
        must not reset it) bounds total degrade attempts; anomalies from
        the watchdog classify as :class:`ResultAnomaly` and take the same
        path.  Progress is tracked as ``(last_good, last_done)`` pairs —
        never inferred from ``_cur_step``."""
        import os
        from yask_tpu.resilience import checkpoint as ckpt
        from yask_tpu.resilience.faults import Breaker, Fault
        from yask_tpu.resilience.guard import guarded_call
        from yask_tpu.resilience.journal import SessionJournal

        o = self._opts
        cad = max(0, int(o.ckpt_every))
        wd = max(0, int(o.watchdog_every))
        ddl = float(o.run_deadline_secs) if o.run_deadline_secs > 0 \
            else None
        dirn = self._ana.step_dir
        ckpt_file = None
        if cad:
            ckpt_dir = o.ckpt_dir or ckpt.default_ckpt_dir()
            if ckpt_dir:
                ckpt_file = os.path.join(
                    ckpt_dir, f"{self.get_name()}.ckpt.npz")

        def _journal(outcome, attempt, **detail):
            # best-effort: supervision journaling is evidence, never a
            # dependency (journal.record raises on I/O failure by
            # contract — a run must survive a read-only journal dir)
            try:
                SessionJournal().record(
                    "run", case=self.get_name(), outcome=outcome,
                    attempt=attempt, **detail)
            except Exception:  # noqa: BLE001
                pass

        from yask_tpu.obs.tracer import span as _span
        # manual enter/exit: the supervised root span brackets the
        # whole chunk loop without re-indenting it (span ignores
        # exception info by design — faults are journaled, not traced)
        _sp = _span("run.supervised", phase="compute",
                    solution=self.get_name(), steps=n,
                    ckpt_every=cad, watchdog_every=wd)
        _sp.__enter__()
        self._in_supervised = True
        try:
            last_good = ckpt.extract_snapshot(self)
            last_done = 0
            if ckpt_file:
                guarded_call(ckpt.save_checkpoint, self, ckpt_file,
                             site="ckpt.save")
            ladder = ckpt.degradation_ladder(self._mode)
            from_mode = self._mode
            breaker = Breaker()
            ladder_path = []
            attempt = 1
            stride = n
            if cad:
                stride = min(stride, cad)
            if wd:
                stride = min(stride, wd)
            done = last_done
            while done < n:
                k = min(stride, n - done)
                t0 = start + done * dirn
                try:
                    guarded_call(self.run_solution, t0,
                                 t0 + (k - 1) * dirn,
                                 site="run.chunk", deadline_secs=ddl)
                    done += k
                    # scan BEFORE the cadence snapshot: corrupt state
                    # must never become the rollback target
                    if wd and (done >= n or done % wd == 0):
                        self._watchdog_scan()
                except Fault as f:
                    breaker.record(f)
                    _journal("fault", attempt, kind=f.kind,
                             site=getattr(f, "site", "run.chunk"),
                             rollback_step=start + last_done * dirn,
                             from_mode=self._mode,
                             ladder=list(ladder))
                    if breaker.tripped or not ladder:
                        raise
                    to_mode = ladder.pop(0)
                    self._opts.mode = to_mode
                    self.prepare_solution()
                    if not ckpt.apply_snapshot(self, last_good):
                        raise
                    ladder_path.append(to_mode)
                    attempt += 1
                    done = last_done
                    continue
                if cad and done < n and done % cad == 0:
                    last_good = ckpt.extract_snapshot(self)
                    last_done = done
                    if ckpt_file:
                        guarded_call(ckpt.save_checkpoint, self,
                                     ckpt_file, site="ckpt.save")
            if ckpt_file:
                guarded_call(ckpt.save_checkpoint, self, ckpt_file,
                             site="ckpt.save")
            if ladder_path:
                _journal("ok", attempt, from_mode=from_mode,
                         final_mode=self._mode,
                         ladder_path=ladder_path, attempts=attempt)
        finally:
            self._in_supervised = False
            _sp.__exit__(None, None, None)

    def _watchdog_scan(self) -> None:
        """Cheap per-cadence state scan: nonfinite / all-zero written
        interiors raise :class:`ResultAnomaly` (same thresholds as
        :mod:`..resilience.sanity`), feeding the supervision ladder."""
        from yask_tpu.resilience.faults import ResultAnomaly, maybe_corrupt
        from yask_tpu.resilience.sanity import check_output
        self._materialize_state()
        gsz = self._opts.global_domain_sizes
        arrs = {}
        for name, g in self._program.geoms.items():
            if not g.is_written or g.is_scratch:
                continue
            idx = tuple(
                slice(g.origin[dn], g.origin[dn] + gsz[dn])
                if kind == "domain" else slice(None)
                for dn, kind in g.axes)
            arrs[name] = [np.asarray(self._state[name][-1][idx])]
        arrs = maybe_corrupt("run.scan", arrs)
        verdict = check_output(arrs)
        if not verdict["ok"]:
            raise ResultAnomaly(
                "watchdog scan flagged written state: "
                + ", ".join(verdict["anomalies"]),
                site="run.scan")

    def _persistent_key(self, kind: str, **build) -> Tuple:
        """Cross-process cache key for :func:`yask_tpu.cache.aot_compile`.

        The key must fully determine the traced program: the equation
        structure (``skey`` covers radii, coefficients, conditions —
        the solution *name* alone under-keys, e.g. radius is a
        constructor arg), the padded state geometry the trace bakes in
        (shapes, origins, ring depths), dtype, step direction, and the
        caller's build parameters (step count / fuse depth / variant
        tuple via ``**build``).  The jax/platform/git fingerprint is
        NOT here — ``aot_compile`` hashes it into the content address
        itself."""
        import hashlib
        eqs = hashlib.sha256(
            repr([e.skey() for e in self._soln.get_equations()])
            .encode()).hexdigest()[:16]
        geoms = tuple(
            (name, tuple(g.shape), g.alloc, g.is_scratch,
             tuple(sorted(g.origin.items())), tuple(g.axes))
            for name, g in sorted(self._program.geoms.items()))
        return (kind, self.get_name(), eqs, str(self._program.dtype),
                self._ana.step_dir, geoms, tuple(sorted(build.items())))

    def _get_compiled_chunk(self, n: int):
        """Compiled function advancing exactly ``n`` steps (cached per n;
        the reference caches per-size auto-tuner results the same way)."""
        key = ("compiled", n)
        if key in self._jit_cache:
            return self._jit_cache[key]
        from jax import lax
        from yask_tpu.cache import aot_compile
        prog = self._program
        dirn = self._ana.step_dir

        def chunk(state, t0):
            def body(carry, _):
                st, t = carry
                st2 = prog.step(st, t)
                return (st2, t + dirn), None
            (st, _), _ = lax.scan(body, (state, t0), None, length=n)
            return st

        self._state_to_device()
        res = aot_compile(chunk, (self._state, 0),
                          key=self._persistent_key("jit_chunk", n=n),
                          platform=self._env.get_platform(),
                          donate_argnums=0)
        self._compile_secs += res.compile_secs
        self._last_cache_hit = res.cache_hit
        self._jit_cache[key] = res.fn
        return res.fn

    def _run_jit_steps(self, start: int, n: int) -> None:
        """Advance ``n`` steps in chunks of ``wf_steps`` (the temporal-
        tiling analog: one compiled chunk per wf_steps steps, reference
        wave-front stride over the step loop, ``context.cpp:352``)."""
        import jax
        self._state_to_device()
        wf = self._opts.wf_steps if self._opts.wf_steps > 0 else n
        dirn = self._ana.step_dir
        # Pre-compile outside the timed section (the reference excludes
        # warmup from trials similarly, yask_main.cpp:131).
        sizes = []
        rem = n
        while rem > 0:
            k = min(wf, rem)
            sizes.append(k)
            rem -= k
        fns = {k: self._get_compiled_chunk(k) for k in set(sizes)}
        t = start
        with self._run_timer:
            st = self._state
            for k in sizes:
                st = fns[k](st, t)
                t += k * dirn
            jax.block_until_ready(st)
        self._state = st

    def vmem_budget(self) -> int:
        """Pallas VMEM budget in bytes: the ``-vmem_mb`` knob, or a
        device-derived default (~16 MiB/core on real TPU, a loose
        100 MiB under CPU interpret where VMEM is emulated and the
        budget only shapes planning)."""
        mb = self._opts.vmem_budget_mb
        if mb > 0:
            return mb * 2 ** 20
        from yask_tpu.ops.pallas_stencil import default_vmem_budget
        return default_vmem_budget(self._env.get_platform())

    def _pallas_pad_needs(self, k: int) -> Dict[str, Tuple[int, int]]:
        """Per-lead-dim ``(left, right)`` pallas pad requirement for fuse
        depth ``k`` — the ONE definition prepare-time planning and
        :meth:`_replan_pallas_pads` both use (a replan that plans leaner
        pads than prepare would silently knock engaged skew dims back to
        uniform shrink after tuning).

        Beyond the radius×k halo, every dim the skewed wavefront MAY
        engage (the ``-skew_dims`` window) gets extra RIGHT pad: ceil
        coverage runs (k−1)·r further right than the uniform grid
        (final-level writes sit shifted left).  The stream dim absorbs
        this through VarGeom's 2·sub_t sublane slab slack; the outer dim
        is an untiled axis with no slack of its own, so without the same
        budget here every 2-D-skew block fails the overshoot check and
        falls back to 1-D."""
        step_rad = self._ana.fused_step_radius()
        lead = self._ana.domain_dims[:-1]
        sk_dims = ()
        if self._opts.skew_wavefront and self._opts.skew_dims_max > 0:
            sk_dims = lead[-self._opts.skew_dims_max:]
        tz_dims = lead[-2:] if self._opts.trapezoid_tiling else ()
        needs = {}
        for d in lead:
            rd = step_rad.get(d, 0)
            need = rd * max(k, 1)
            need_r = need
            if d in sk_dims:
                from yask_tpu.compiler.lowering import tpu_tile_dims
                need_r = need + 2 * tpu_tile_dims(self._csol.dtype)[0]
                if d == lead[-1]:
                    # Misaligned (non-sublane-multiple) stream radii:
                    # the skewed tiling computes E_sk extra right width
                    # and its widened slabs need the same again in
                    # rounding room (single E_sk definition:
                    # pallas_stencil.skew_extra_width).
                    from yask_tpu.ops.pallas_stencil import \
                        skew_extra_width
                    need_r += 2 * skew_extra_width(self._csol.dtype, rd)
            if d in tz_dims and rd > 0:
                # trapezoid window dims: the diamond fill pass centers
                # band tiles on the OUTERMOST tile boundaries, so both
                # sides need the K·r margin + half-band + slab rounding
                # room (single definition: trapezoid_pad_need)
                from yask_tpu.ops.pallas_stencil import trapezoid_pad_need
                tz = trapezoid_pad_need(self._csol.dtype, rd, max(k, 1))
                need = max(need, tz)
                need_r = max(need_r, tz)
            needs[d] = (need, need_r)
        return needs

    def _replan_pallas_pads(self, k: int) -> None:
        """Shrink pallas pads back to radius×k after the tuner settles.

        Pads were pre-planned for ``tune_max_wf_steps`` so the joint
        walk could *grow* K; keeping them would tax every ring slot's
        HBM footprint forever (e.g. radius 8 × Kmax 16 = 128 cells per
        side). Interiors are migrated into right-sized arrays (pads stay
        identically zero — the framework invariant) and the jit cache is
        cleared: compiled chunks are shape-keyed, so the tuned point
        recompiles once at production shape. Note a later
        ``reset_auto_tuner`` re-tune can then only shrink K again."""
        if self._mode != "pallas":
            return
        extra = {d: (self._opts.min_pad_sizes[d],
                     self._opts.min_pad_sizes[d])
                 for d in self._ana.domain_dims}
        for d, (need, need_r) in self._pallas_pad_needs(k).items():
            l, r = extra[d]
            extra[d] = (max(l, need), max(r, need_r))
        if extra == self._plan_kwargs.get("extra_pad"):
            return
        import jax.numpy as jnp
        gsz = self._opts.global_domain_sizes
        new_kwargs = dict(self._plan_kwargs, extra_pad=extra)
        new_prog = self._csol.plan(gsz, **new_kwargs)
        old_prog = self._program

        def interior(g):
            return tuple(
                slice(g.origin[dn], g.origin[dn] + gsz[dn])
                if kind == "domain" else slice(None)
                for dn, kind in g.axes)

        new_state = {}
        for name, ring in self._state.items():
            og, ng = old_prog.geoms[name], new_prog.geoms[name]
            oidx, nidx = interior(og), interior(ng)
            new_state[name] = [
                jnp.zeros(tuple(ng.shape), dtype=new_prog.dtype)
                .at[nidx].set(jnp.asarray(a)[oidx]) for a in ring]
        self._program = new_prog
        self._plan_kwargs = new_kwargs
        self._state = new_state
        self._state_on_device = True
        self._jit_cache.clear()
        self._pallas_tiling.clear()
        self._comm_plans.clear()

    def _pallas_variant_key(self) -> Tuple:
        """(skew, skew_dims_max, vmem_mb) cache-key suffix shared by
        EVERY pallas build variant (single-device and shard): these are
        the settings beyond (K, block) that change the compiled kernel,
        so both the jit cache and the tiling record must key on them —
        the vmem ladder in particular walks the same (K, block) at
        several budgets and the rungs must never alias each other's
        executables."""
        o = self._opts
        skw = None if o.skew_wavefront else False
        sdm = o.skew_dims_max if o.skew_wavefront else 0
        ovx = getattr(o, "overlap_exchange", "auto")
        trz = None if getattr(o, "trapezoid_tiling", False) else False
        # comm-schedule knobs: the shard exchange bodies bake the
        # CommPlan's order/coalescing into the traced program, so
        # toggling them must never alias another schedule's executable
        cmo = getattr(o, "comm_order", "")
        col = getattr(o, "coalesce", "auto")
        # push-memory fusion changes which vars ride the DMA paths, so
        # push variants must never alias each other's executables
        psh = self._push_arg()
        # pipeline-fusion signature: a merged producer→consumer chain
        # compiles a different kernel than any standalone solution
        psig = self._pipeline_sig or ""
        return (skw, sdm, o.vmem_budget_mb, ovx, trz, cmo, col, psh,
                psig)

    def _push_arg(self):
        """The ``build_pallas_chunk(push=)`` argument the configured
        ``push_memory`` setting resolves to — single definition shared
        with the checker's ``plan_pallas`` so the static plan and the
        executed build can never disagree.  ``auto`` engages only for
        pipeline-fused contexts: a plain solution's user expects every
        written var observable after ``run()``, a pipeline hides its
        pushed intermediates behind :meth:`SolutionPipeline.get_var`."""
        pm = getattr(self._opts, "push_memory", "auto")
        if pm == "off":
            return False
        if pm == "on":
            return None
        if pm == "force":
            return True
        if pm != "auto":
            from yask_tpu.utils.exceptions import YaskException
            raise YaskException(
                f"bad -push value '{pm}': expected auto|on|force|off")
        return None if getattr(self, "_pipeline", None) is not None \
            else False

    def comm_plan(self, K: Optional[int] = None):
        """The communication schedule (CommPlan) for the configured
        shard mode — derived once per (mode, K, knobs) and cached; the
        shard_map/shard_pallas exchange paths, the checker's COMM rules
        and the ledger fields all consume this single instance (the
        TilePlan discipline applied to collectives)."""
        from yask_tpu.parallel.comm_plan import build_comm_plan
        mode = self._mode or self._opts.mode
        if K is None:
            K = max(self._opts.wf_steps, 1) if mode == "shard_pallas" \
                else 1
        key = (mode, int(K), getattr(self._opts, "comm_order", ""),
               getattr(self._opts, "coalesce", "auto"))
        if key not in self._comm_plans:
            self._comm_plans[key] = build_comm_plan(self, K=K)
        return self._comm_plans[key]

    def _pallas_build_key(self, K: int):
        """(cache key, block tuple, skew arg) for the configured pallas
        build — single definition so stats can look up the tiling the
        built kernel actually chose (ADVICE r3)."""
        bs = self._opts.block_sizes
        blk = None
        if any(bs[d] > 0 for d in self._ana.domain_dims[:-1]):
            blk = tuple(bs[d] if bs[d] > 0 else 8
                        for d in self._ana.domain_dims[:-1])
        var = self._pallas_variant_key()
        return ("pallas", K, blk) + var, blk, var[0]

    def _get_pallas_chunk(self, K: int):
        """Compiled fused-Pallas chunk for K steps with the current block
        settings (cached per (K, block) — the auto-tuner varies both)."""
        key, blk, skw = self._pallas_build_key(K)
        if key not in self._jit_cache:
            from yask_tpu.ops.pallas_stencil import build_pallas_chunk
            interp = self._env.get_platform() != "tpu"
            chunk, tile_bytes = build_pallas_chunk(
                self._program, fuse_steps=K, block=blk, interpret=interp,
                vmem_budget=self.vmem_budget(), skew=skw,
                vinstr_cap=self._opts.max_tile_vinstr,
                max_skew_dims=self._opts.skew_dims_max,
                trapezoid=(None if self._opts.trapezoid_tiling
                           else False),
                push=self._push_arg())
            self._state_to_device()
            t0c = time.perf_counter()
            if interp:
                fn = chunk
            else:
                # AOT-compile so the first timed call doesn't include
                # XLA/Mosaic compilation (mirrors _get_compiled_chunk).
                # No donation: fuse_vars may share these ring buffers
                # with a peer context.
                from yask_tpu.cache import aot_compile
                res = aot_compile(
                    chunk, (self._state, 0),
                    key=self._persistent_key("pallas_chunk", K=K,
                                             blk=blk,
                                             variant=self._pallas_variant_key()),
                    platform=self._env.get_platform())
                fn = res.fn
                self._last_cache_hit = res.cache_hit
            self._jit_cache[key] = fn
            # only after a successful compile: a Mosaic failure must not
            # leave stats modeling a tiling that never ran
            self._pallas_tiling[key] = getattr(chunk, "tiling", None)
            self._compile_secs += time.perf_counter() - t0c
            self._env.trace_msg(
                f"pallas chunk: K={K}, blocks={blk or 'planner'}, "
                f"tile {tile_bytes / 2**20:.2f} MiB")
        return self._jit_cache[key]

    def _run_pallas_steps(self, start: int, n: int) -> None:
        """Advance using the fused Pallas sweep: ⌊n/K⌋ fused chunks (K =
        wf_steps temporal fusion) plus an XLA-path remainder."""
        import jax
        self._state_to_device()
        K = min(max(self._opts.wf_steps, 1), n)
        fn = self._get_pallas_chunk(K)
        groups, rem = divmod(n, K)
        t = start
        dirn = self._ana.step_dir
        with self._run_timer:
            st = self._state
            for _ in range(groups):
                st = fn(st, t)
                t += K * dirn
            jax.block_until_ready(st)
        self._state = st
        if rem:
            self._run_jit_steps(t, rem)

    def run_ref(self, first_step_index: int,
                last_step_index: Optional[int] = None) -> None:
        """Run the independent eager-numpy oracle over the same state
        (reference ``run_ref``, ``context.cpp:46``)."""
        self._check_prepared()
        if last_step_index is None:
            last_step_index = first_step_index
        start, n = self._step_seq(first_step_index, last_step_index)
        self._run_ref_steps(start, n)
        self._cur_step = start + n * self._ana.step_dir
        self._steps_done += n

    # ------------------------------------------------------------------
    # auto-tuning (yk_solution_api.hpp:839-881)
    # ------------------------------------------------------------------

    def run_auto_tuner_now(self, candidates=None, min_trial_secs=None) -> int:
        """Offline auto-tune (advances real steps, like the reference)."""
        self._check_prepared()
        from yask_tpu.runtime.auto_tuner import AutoTuner
        return AutoTuner(self).run_auto_tuner_now(
            candidates=candidates, min_trial_secs=min_trial_secs)

    def reset_auto_tuner(self, enable: bool = True) -> None:
        self._tuned = False
        self._opts.do_auto_tune = enable

    def is_auto_tuner_enabled(self) -> bool:
        return self._opts.do_auto_tune and not getattr(self, "_tuned", False)

    # ------------------------------------------------------------------
    # validation (yask_main.cpp:564-616 -validate flow)
    # ------------------------------------------------------------------

    def compare_data(self, other: "StencilContext", epsilon: float = 1e-4,
                     abs_epsilon: float = 1e-7,
                     field_epsilon: float = 0.0) -> int:
        """Element-wise compare of all common vars against another context;
        returns #mismatches. Mixed absolute+relative tolerance like the
        reference's within-tolerance check (``compare_data``): a point
        mismatches only if |x−y| > abs_eps + eps·max(|x|,|y|), so fp32
        reassociation noise at near-cancellation points doesn't count.

        ``field_epsilon`` adds a FIELD-scale term to the tolerance:
        ``field_eps · max(‖x‖∞, ‖y‖∞)`` per compared array.  Stencil
        updates sum neighbor values, so rounding error at a point is
        ulps of the largest summed INPUT, not of the local result — a
        point whose true value nearly cancels to zero can carry an
        absolute error of ~ulp(field max) that no pointwise relative
        tolerance models.  Use it when comparing execution paths with
        different FP association (fused in-tile vs XLA-fused order);
        the default 0.0 keeps the strict pointwise behavior.  A real
        geometry bug (dropped halo band, stale margin) produces
        O(field) errors and still fails any small field_epsilon."""
        self._check_prepared()
        other._check_prepared()
        self._materialize_state()
        other._materialize_state()

        def interior(ctx, name, arr):
            g = ctx._program.geoms[name]
            idxs = []
            for dn, kind in g.axes:
                if kind == "domain":
                    idxs.append(slice(
                        g.origin[dn],
                        g.origin[dn] + ctx._opts.global_domain_sizes[dn]))
                else:
                    idxs.append(slice(None))
            return np.asarray(arr, dtype=np.float64)[tuple(idxs)]

        bad = 0
        for name, ring in self._state.items():
            if name not in other._state:
                continue
            oring = other._state[name]
            for a, b in zip(ring[::-1], oring[::-1]):
                x = interior(self, name, a)
                y = interior(other, name, b)
                if x.shape != y.shape:
                    bad += x.size
                    continue
                tol = abs_epsilon + epsilon * np.maximum(np.abs(x), np.abs(y))
                if field_epsilon > 0.0 and x.size:
                    scale = max(np.abs(x).max(), np.abs(y).max())
                    tol = tol + field_epsilon * scale
                bad += int((np.abs(x - y) > tol).sum())
        return bad

    # ------------------------------------------------------------------
    # tracing (SURVEY §5: trace_mem analog — per-step write dumps,
    # diffable by tools/analyze_trace to find the first divergent write)
    # ------------------------------------------------------------------

    def set_trace_dir(self, path: Optional[str]) -> None:
        """Enable per-step state dumps into ``path`` (one .npz per step,
        interiors of all written vars). The runtime then advances steps
        one at a time so each step's writes are observable — the analog of
        the reference's ``trace_mem=1`` builds (``common_utils.hpp:201``)."""
        self._trace_dir = path
        if path:
            import os
            os.makedirs(path, exist_ok=True)

    def _trace_dump(self, t_written: int) -> None:
        import os
        self._materialize_state()
        arrs = {}
        for name, ring in self._state.items():
            g = self._program.geoms[name]
            if not g.is_written:
                continue
            idxs = []
            for dn, kind in g.axes:
                if kind == "domain":
                    idxs.append(slice(
                        g.origin[dn],
                        g.origin[dn] + self._opts.global_domain_sizes[dn]))
                else:
                    idxs.append(slice(None))
            arrs[name] = np.asarray(ring[-1])[tuple(idxs)]
        np.savez(os.path.join(self._trace_dir, f"step_{t_written}.npz"),
                 **arrs)

    # ------------------------------------------------------------------
    # checkpoint / resume (SURVEY §5: the reference has none; the slice
    # get/set API defines the serialization surface — we provide whole-
    # solution snapshot/restore on top of the same state)
    # ------------------------------------------------------------------

    @staticmethod
    def _ckpt_path(path: str) -> str:
        # np.savez appends '.npz' to extensionless paths; normalize so a
        # save/load round trip works with any path string.
        return path if path.endswith(".npz") else path + ".npz"

    def save_checkpoint(self, path: str, backend: str = "npz") -> None:
        """Snapshot all var state + step position.

        ``backend="npz"`` (default) writes one ``.npz`` file;
        ``backend="orbax"`` writes an Orbax PyTree checkpoint directory
        (async-capable, multi-host-ready storage format — the scale
        path for big distributed states; exceeds the reference, which
        has no checkpointing at all)."""
        self._check_prepared()
        self._materialize_state()
        if backend == "orbax":
            import os
            import orbax.checkpoint as ocp
            tree = {
                "cur_step": np.asarray(self._cur_step),
                "steps_done": np.asarray(self._steps_done),
                "state": {name: {f"slot{i}": np.asarray(a)
                                 for i, a in enumerate(ring)}
                          for name, ring in self._state.items()},
            }
            ocp.PyTreeCheckpointer().save(
                os.path.abspath(path), tree, force=True)
            return
        if backend != "npz":
            raise YaskException(
                f"unknown checkpoint backend '{backend}' "
                "(use 'npz' or 'orbax')")
        payload = {"__cur_step__": np.asarray(self._cur_step),
                   "__steps_done__": np.asarray(self._steps_done)}
        for name, ring in self._state.items():
            for i, a in enumerate(ring):
                payload[f"{name}__slot{i}"] = np.asarray(a)
        np.savez(self._ckpt_path(path), **payload)

    def load_checkpoint(self, path: str, backend: str = "npz") -> None:
        """Restore a snapshot (shapes must match the prepared geometry)."""
        self._check_prepared()
        # materialize (not discard) resident interiors: the restore
        # validates shapes against the current rings
        self._materialize_state()
        if backend == "orbax":
            import os
            import orbax.checkpoint as ocp
            tree = ocp.PyTreeCheckpointer().restore(os.path.abspath(path))
            data = {"__cur_step__": tree["cur_step"],
                    "__steps_done__": tree["steps_done"]}
            for name, slots_ in tree["state"].items():
                for k, a in slots_.items():
                    data[f"{name}__slot{k[4:]}"] = a
        elif backend == "npz":
            data = np.load(self._ckpt_path(path))
        else:
            raise YaskException(
                f"unknown checkpoint backend '{backend}' "
                "(use 'npz' or 'orbax')")
        new_state: Dict[str, List] = {}
        for name, ring in self._state.items():
            arrs = []
            for i, old in enumerate(ring):
                key = f"{name}__slot{i}"
                if key not in data:
                    raise YaskException(f"checkpoint missing '{key}'")
                a = data[key]
                if tuple(a.shape) != tuple(np.asarray(old).shape):
                    raise YaskException(
                        f"checkpoint shape mismatch for '{name}': "
                        f"{a.shape} vs {np.asarray(old).shape}")
                arrs.append(a)
            new_state[name] = arrs
        self._state = new_state
        self._state_on_device = False
        self._state_to_device()
        self._cur_step = int(data["__cur_step__"])
        self._steps_done = int(data["__steps_done__"])

    # ------------------------------------------------------------------
    # stats (yk_stats)
    # ------------------------------------------------------------------

    def hbm_model_bytes_pp(self) -> Tuple[float, float]:
        """(read, write) HBM bytes per point per step of the CONFIGURED
        execution path (mode/wf_steps/blocks resolved from settings) —
        THE single resolution used by get_stats and bench.py."""
        if self._program is None:
            return (0.0, 0.0)
        if self._opts.mode in ("pallas", "shard_pallas"):
            blk = {d: self._opts.block_sizes[d]
                   for d in self._ana.domain_dims[:-1]
                   if self._opts.block_sizes[d] > 0} or None
            K = max(1, self._opts.wf_steps)
            built = self._built_pallas_tiling()
            if built is not None:
                return self._program.hbm_bytes_per_point(
                    fuse_steps=built["fuse_steps"],
                    block=built["block"],
                    skew=built.get("skew_dims", built["skew"]))
            from yask_tpu.ops.pallas_stencil import skew_engaged_dims
            skw = []
            if self._opts.skew_wavefront:
                # distributed skew engages per dim only where that dim
                # is unsharded (the carry cannot cross shards)
                lead = self._ana.domain_dims[:-1]
                unsh = None
                if self._opts.mode == "shard_pallas":
                    unsh = [d for d in lead
                            if self._opts.num_ranks[d] <= 1]
                skw = skew_engaged_dims(
                    self._program, K, unsharded=unsh,
                    max_dims=self._opts.skew_dims_max)
            return self._program.hbm_bytes_per_point(
                fuse_steps=K, block=blk, skew=skw)
        return self._program.hbm_bytes_per_point()

    def _built_pallas_tiling(self):
        """The tiling the built kernel ACTUALLY chose for the current
        configuration (skew/pipelining can auto-fall-back during
        planning — ADVICE r3), or None before the first build / on
        non-pallas modes.  Keys on the exact build key the run path
        derives, or an auto-tune walk's other variants could shadow
        it."""
        if self._program is None or self._opts.mode not in (
                "pallas", "shard_pallas"):
            return None
        K = max(1, self._opts.wf_steps)
        # single blk/variant derivation: _pallas_build_key (the shard
        # run path uses the identical formula)
        _key, blk_, _skw = self._pallas_build_key(K)
        probe = (self._opts.mode,) + _key[1:]
        t = self._pallas_tiling.get(probe)
        if t is None:
            # run paths clamp K to the run span (K = min(wf_steps, n)):
            # a short run records under a smaller K — report the
            # nearest built variant rather than predicting
            cands = [k for k in self._pallas_tiling
                     if k[0] == probe[0] and k[2:] == probe[2:]
                     and k[1] <= K]
            if cands:
                t = self._pallas_tiling[max(cands, key=lambda k: k[1])]
        return t

    def get_stats(self) -> yk_stats:
        c = self._ana.counters
        npts = self._opts.global_domain_sizes.product()
        rb_pp, wb_pp = self.hbm_model_bytes_pp()
        st = yk_stats(
            npts=npts, nsteps=self._steps_done,
            nreads_pp=c.num_reads, nwrites_pp=c.num_writes,
            nfpops_pp=c.num_ops,
            elapsed=self._run_timer.get_elapsed_secs(),
            halo_secs=self._halo_timer.get_elapsed_secs(),
            compile_secs=self._compile_secs,
            halo_exchange_secs=self._halo_xround_last,
            halo_pack_secs=self._halo_xpack_last,
            halo_cal_spread=self._halo_cal_spread_last,
            halo_cal_unstable=self._halo_cal_unstable_last,
            halo_cal_reps=getattr(self, "_halo_cal_reps_last", 0),
            halo_overlap_eff=self._halo_overlap_eff_last,
            halo_collectives=getattr(self, "_halo_nperm_last", 0),
            read_bytes_pp=rb_pp, write_bytes_pp=wb_pp,
            # aggregate peak: throughput is global (all chips), so the
            # roofline denominator must scale with the mesh size
            hbm_peak=(self._env.get_hbm_peak_bytes_per_sec()
                      * max(self._env.get_num_ranks(), 1)),
            tiling=self._built_pallas_tiling())
        return st

    def clear_stats(self) -> None:
        self._run_timer.clear()
        self._halo_timer.clear()
        self._steps_done = 0

    # ------------------------------------------------------------------
    # CLI parity
    # ------------------------------------------------------------------

    # ------------------------------------------------------------------
    # full accessor parity (yk_solution_api.hpp) — "grid" names are the
    # reference's v2-era aliases for vars; vector forms return values in
    # domain-dim order; thread/NUMA/offload knobs answer truthfully for
    # a TPU (XLA manages cores; the chip IS the offload device).
    # ------------------------------------------------------------------

    get_grid = get_var
    get_grids = get_vars
    fuse_grids = fuse_vars
    new_fixed_size_grid = new_fixed_size_var

    def get_num_grids(self) -> int:
        return self.get_num_vars()

    def get_num_domain_dims(self) -> int:
        return len(self.get_domain_dim_names())

    def get_first_rank_domain_index(self, dim: str) -> int:
        return 0    # host APIs present the GLOBAL problem (SPMD inside)

    def get_last_rank_domain_index(self, dim: str) -> int:
        return self.get_overall_domain_size(dim) - 1

    def _dvec(self, fn):
        return [fn(d) for d in self.get_domain_dim_names()]

    def get_first_rank_domain_index_vec(self):
        return self._dvec(self.get_first_rank_domain_index)

    def get_last_rank_domain_index_vec(self):
        return self._dvec(self.get_last_rank_domain_index)

    def get_overall_domain_size_vec(self):
        return self._dvec(self.get_overall_domain_size)

    def get_rank_domain_size_vec(self):
        return self._dvec(self.get_rank_domain_size)

    def set_rank_domain_size_vec(self, sizes) -> None:
        for d, s in zip(self.get_domain_dim_names(), sizes):
            self.set_rank_domain_size(d, s)

    def get_block_size_vec(self):
        return self._dvec(self.get_block_size)

    def set_block_size_vec(self, sizes) -> None:
        for d, s in zip(self.get_domain_dim_names(), sizes):
            self.set_block_size(d, s)

    def get_num_ranks_vec(self):
        return self._dvec(self.get_num_ranks)

    def set_num_ranks_vec(self, ns) -> None:
        for d, n in zip(self.get_domain_dim_names(), ns):
            self.set_num_ranks(d, n)

    def get_rank_index(self, dim: str) -> int:
        return 0    # single-process SPMD: shards are traced, not ranked

    def get_rank_index_vec(self):
        return self._dvec(self.get_rank_index)

    def set_rank_index(self, dim: str, idx: int) -> None:
        if idx != 0:
            raise YaskException(
                "explicit rank placement is not applicable: shards are "
                "laid out by the mesh, not per-process (reference "
                "set_rank_index is for manual MPI layouts)")

    def set_rank_index_vec(self, idxs) -> None:
        for d, i in zip(self.get_domain_dim_names(), idxs):
            self.set_rank_index(d, i)

    def get_min_pad_size(self, dim: str) -> int:
        return self._opts.min_pad_sizes[dim]

    def set_min_pad_size(self, dim: str, size: int) -> None:
        self._opts.min_pad_sizes[dim] = max(
            self._opts.min_pad_sizes[dim], int(size))

    def get_step_wrap(self) -> bool:
        return getattr(self, "_step_wrap", False)

    def set_step_wrap(self, wrap: bool) -> None:
        """``yk_solution::set_step_wrap``: with wrapping on, var element
        APIs accept ANY step index and map it onto the ring modulo the
        allocation (consumed by ``yk_var._slot_for_step``)."""
        self._step_wrap = bool(wrap)

    def get_num_outer_threads(self) -> int:
        return 1    # XLA owns core-level parallelism

    def get_num_inner_threads(self) -> int:
        return 1

    def is_offloaded(self) -> bool:
        return self._env.get_platform() == "tpu"

    def get_default_numa_preferred(self) -> int:
        return self._opts.numa_pref

    def set_default_numa_preferred(self, node: int) -> bool:
        self._opts.numa_pref = int(node)
        return True

    def get_elapsed_run_secs(self) -> float:
        return self._run_timer.get_elapsed_secs()

    def get_command_line_values(self) -> str:
        """Echo the effective option values (reference
        ``get_command_line_values``)."""
        o = self._opts
        dd = self.get_domain_dim_names()
        parts = [f"-g_{d} {o.global_domain_sizes[d]}" for d in dd]
        parts += [f"-b_{d} {o.block_sizes[d]}" for d in dd]
        parts += [f"-nr_{d} {o.num_ranks[d]}" for d in dd]
        parts += [f"-wf_steps {o.wf_steps}", f"-mode {o.mode}",
                  f"-vmem_mb {o.vmem_budget_mb}"]
        return " ".join(parts)

    def exchange_halos(self) -> None:
        """Force-refresh ghost copies (reference ``exchange_halos``,
        ``soln_apis.cpp``).  Global-array modes have no persistent
        ghosts (every run re-derives them); shard-resident state is
        materialized so the next run re-places and re-exchanges from
        the authoritative interiors."""
        self._check_prepared()
        self._materialize_state()
        for v in self.get_vars():
            v._dirty = False

    def alloc_storage(self) -> None:
        """Allocate any released var rings (bulk alloc happens in
        prepare_solution; reference splits prepare/alloc)."""
        self._check_prepared()
        for v in self.get_vars():
            v.alloc_storage()

    def end_solution(self) -> None:
        """Release run resources (reference ``end_solution``): drops
        var storage and compiled-program caches; re-prepare to run
        again."""
        self._jit_cache.clear()
        self._pallas_tiling.clear()
        self._comm_plans.clear()
        self._state = None
        self._resident = None
        self._program = None
        self._ended = True

    def apply_command_line_options(self, args) -> List[str]:
        if isinstance(args, str):
            args = args.split()
        p = CommandLineParser()
        self._opts.add_options(p)
        return p.parse_args(list(args))

    def get_command_line_help(self) -> str:
        p = CommandLineParser()
        self._opts.add_options(p)
        return p.print_help()

    def __repr__(self):
        return (f"<StencilContext '{self.get_name()}' mode={self._mode} "
                f"prepared={self.is_prepared()}>")
