"""Kernel environment: device discovery and the mesh bootstrap.

Counterpart of ``yk_env`` / ``KernelEnv`` (reference
``include/yask_kernel_api.hpp:167-293``, ``src/kernel/lib/settings.hpp:47-80``,
init in ``setup.cpp:51-90``): where the reference calls
``MPI_Init_thread`` and splits a shared-memory communicator, the TPU runtime
discovers JAX devices and exposes them as the "ranks" a solution's domain is
decomposed over. Collectives over ranks (barriers, reductions, equality
assertions) are trivial here because the controller is a single process
driving all devices (JAX SPMD); the API surface is kept for parity.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

from yask_tpu.utils.exceptions import YaskException


class yk_env:
    """Execution environment: devices, debug output, trace gating."""

    def __init__(self, devices: Optional[List] = None):
        import jax
        # the ONE library-level device query; drivers probe first
        self._devices = (list(devices) if devices is not None
                         else jax.devices())  # lint: devices-ok
        self._trace = False
        self._debug = sys.stdout
        self._msg_rank = 0

    # ---- device/"rank" info ---------------------------------------------

    def get_num_ranks(self) -> int:
        """Number of devices available for domain decomposition (the
        reference's MPI world size)."""
        return len(self._devices)

    def get_rank_index(self) -> int:
        """Always 0: one controller process drives all devices (JAX SPMD);
        per-device work is expressed via sharding, not per-process code."""
        return 0

    def get_devices(self) -> List:
        return list(self._devices)

    def get_platform(self) -> str:
        """Normalized platform name: "axon" (the TPU-behind-a-relay PJRT
        plugin used in this environment) reports as "tpu" so every
        platform branch (Pallas interpret-vs-Mosaic, bench sizing)
        treats it as the real device it is."""
        if not self._devices:
            return "none"
        plat = self._devices[0].platform
        return "tpu" if plat == "axon" else plat

    def get_hbm_peak_bytes_per_sec(self) -> float:
        """Per-chip HBM peak bandwidth for the roofline readout in
        ``yk_stats`` (public per-generation figures; 0.0 when unknown —
        e.g. the CPU mesh, where a roofline fraction is meaningless)."""
        if not self._devices or self.get_platform() != "tpu":
            return 0.0
        kind = getattr(self._devices[0], "device_kind", "").lower()
        table = (
            ("v5 lite", 819e9), ("v5e", 819e9),
            ("v5p", 2765e9), ("v5", 2765e9),
            ("v6", 1640e9), ("trillium", 1640e9),
            ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
        )
        for tag, peak in table:
            if tag in kind:
                return peak
        return 0.0

    # ---- collectives-over-ranks (single-controller no-ops, kept for API
    # parity with yk_env barriers/reductions) ------------------------------

    def global_barrier(self) -> None:
        import jax
        # Materialize any pending async work — the observable effect a
        # barrier has in the reference harness timing.
        jax.effects_barrier()

    def sum_over_ranks(self, val: int) -> int:
        return val

    def min_over_ranks(self, val: int) -> int:
        return val

    def max_over_ranks(self, val: int) -> int:
        return val

    def assert_equality_over_ranks(self, val: int, descr: str = "") -> None:
        return None  # single controller: trivially equal

    # ---- debug & trace ---------------------------------------------------

    def set_trace_enabled(self, enable: bool) -> None:
        self._trace = bool(enable)

    def is_trace_enabled(self) -> bool:
        return self._trace

    def set_debug_output(self, out) -> None:
        self._debug = out.get_ostream() if hasattr(out, "get_ostream") else out

    def get_debug_output(self):
        return self._debug

    def trace_msg(self, msg: str) -> None:
        if self._trace:
            self._debug.write(f"YASK-TPU: {msg}\n")

    # ---- multi-host bootstrap (the MPI_Init analog across hosts) ---------

    @staticmethod
    def init_distributed(coordinator_address: str, num_processes: int,
                         process_id: int) -> None:
        """Join a multi-host JAX cluster (``jax.distributed``): after this,
        ``jax.devices()`` spans every host and meshes ride ICI within a
        slice / DCN across — the reference's multi-node MPI launch
        (``setup.cpp:51-90``) without per-rank SPMD processes."""
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)

    # ---- profiling (SURVEY §5: VTune/XProf analog) -----------------------

    def start_profiler_trace(self, log_dir: str) -> None:
        """Begin an XProf/TensorBoard trace (the reference's VTune
        resume/pause hooks around trials, ``yask_main.cpp:33-44``)."""
        import jax
        jax.profiler.start_trace(log_dir)

    def stop_profiler_trace(self) -> None:
        import jax
        jax.profiler.stop_trace()

    def finalize(self) -> None:
        """Counterpart of MPI_Finalize; nothing to tear down."""
