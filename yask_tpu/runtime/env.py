"""Kernel environment: device discovery and the mesh bootstrap.

Counterpart of ``yk_env`` / ``KernelEnv`` (reference
``include/yask_kernel_api.hpp:167-293``, ``src/kernel/lib/settings.hpp:47-80``,
init in ``setup.cpp:51-90``): where the reference calls
``MPI_Init_thread`` and splits a shared-memory communicator, the TPU runtime
discovers JAX devices and exposes them as the "ranks" a solution's domain is
decomposed over. Collectives over ranks (barriers, reductions, equality
assertions) are trivial here because the controller is a single process
driving all devices (JAX SPMD); the API surface is kept for parity.
"""

from __future__ import annotations

import os
import sys
from typing import List, Optional

from yask_tpu.utils.exceptions import YaskException


class yk_env:
    """Execution environment: devices, debug output, trace gating."""

    def __init__(self, devices: Optional[List] = None):
        import jax
        self._devices = list(devices) if devices is not None else jax.devices()
        self._trace = False
        self._debug = sys.stdout
        self._msg_rank = 0

    # ---- device/"rank" info ---------------------------------------------

    def get_num_ranks(self) -> int:
        """Number of devices available for domain decomposition (the
        reference's MPI world size)."""
        return len(self._devices)

    def get_rank_index(self) -> int:
        """Always 0: one controller process drives all devices (JAX SPMD);
        per-device work is expressed via sharding, not per-process code."""
        return 0

    def get_devices(self) -> List:
        return list(self._devices)

    def get_platform(self) -> str:
        return self._devices[0].platform if self._devices else "none"

    # ---- collectives-over-ranks (single-controller no-ops, kept for API
    # parity with yk_env barriers/reductions) ------------------------------

    def global_barrier(self) -> None:
        import jax
        # Materialize any pending async work — the observable effect a
        # barrier has in the reference harness timing.
        jax.effects_barrier()

    def sum_over_ranks(self, val: int) -> int:
        return val

    def min_over_ranks(self, val: int) -> int:
        return val

    def max_over_ranks(self, val: int) -> int:
        return val

    def assert_equality_over_ranks(self, val: int, descr: str = "") -> None:
        return None  # single controller: trivially equal

    # ---- debug & trace ---------------------------------------------------

    def set_trace_enabled(self, enable: bool) -> None:
        self._trace = bool(enable)

    def is_trace_enabled(self) -> bool:
        return self._trace

    def set_debug_output(self, out) -> None:
        self._debug = out.get_ostream() if hasattr(out, "get_ostream") else out

    def get_debug_output(self):
        return self._debug

    def trace_msg(self, msg: str) -> None:
        if self._trace:
            self._debug.write(f"YASK-TPU: {msg}\n")

    def finalize(self) -> None:
        """Counterpart of MPI_Finalize; nothing to tear down."""
