"""Runtime settings: every knob the kernel accepts.

Counterpart of ``KernelSettings`` (reference
``src/kernel/lib/settings.hpp:200-327``, option wiring in ``settings.cpp``):
domain geometry, tiling sizes, decomposition grid, overlap/exchange toggles,
and auto-tune controls — re-expressed for TPU execution:

* block sizes become Pallas/XLA tile hints (the auto-tuner's search space);
* the rank grid becomes the device-mesh shape;
* ``overlap_comms``/``use_shm``/``use_device_mpi`` collapse into the
  execution-mode choice (XLA async collectives already overlap; there is no
  host/device copy distinction on TPU) — they are accepted and recorded so
  reference command lines keep working.
"""

from __future__ import annotations

from typing import List, Optional

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.utils.idx_tuple import IdxTuple
from yask_tpu.utils.cli import CommandLineParser


#: Execution modes for run_solution.
MODES = ("auto",       # single device → "jit"; >1 rank requested → "sharded"
         "jit",        # single-device jitted jnp program
         "pallas",     # hand-tiled Pallas kernels w/ K-step temporal fusion
         "sharded",    # global arrays + NamedSharding (XLA inserts comms)
         "shard_map",  # explicit per-shard program + ppermute halo exchange
         "shard_pallas",  # shard_map outer + fused Pallas inner (the
         #                  multi-chip scaling path: exchange every K steps)
         "ref",        # eager numpy oracle (the reference's run_ref)
         )


class KernelSettings:
    """All runtime knobs for one solution instance."""

    def __init__(self, domain_dims: List[str]):
        self.domain_dims = list(domain_dims)
        z = {d: 0 for d in domain_dims}
        # Geometry (reference -g / -d / -b … options).
        self.global_domain_sizes = IdxTuple(z)   # -g* (0 = derive from rank)
        self.rank_domain_sizes = IdxTuple(z)     # -d* (0 = derive from global)
        self.block_sizes = IdxTuple(z)           # -b* tile hints (0 = auto)
        self.min_pad_sizes = IdxTuple(z)         # -mp* extra pad per dim
        self.num_ranks = IdxTuple(z)             # -nr* mesh grid (0 = auto)
        # Temporal tiling (reference wave-front options, context.hpp:331).
        self.wf_steps = 0          # steps fused per compiled chunk (0 = auto)
        # Behavior toggles.
        self.mode = "auto"
        self.overlap_comms = True
        # Calibrate a no-exchange twin of the shard_map program and report
        # measured halo time in stats (reference halo timer breakdown).
        self.measure_halo_time = False
        self.use_shm = True            # accepted for parity; no-op on TPU
        self.use_device_mpi = True     # accepted for parity; no-op on TPU
        self.bundle_allocs = True
        self.force_scalar = False      # run the numpy oracle path
        # Auto-tuner (reference auto_tuner.hpp options).
        self.do_auto_tune = False
        self.auto_tune_each_stage = False
        self.auto_tune_trial_secs = 0.5
        # Largest wf_steps the joint walk may try. When auto-tune is on,
        # pallas-mode pads are planned up to radius × this at prepare
        # time so the walk can *grow* K, not only shrink it.
        self.tune_max_wf_steps = 16
        # Streaming skewed-wavefront tiling on the pallas path (zero
        # redundant compute in the stream dim; the TPU-native answer to
        # the reference's two-phase trapezoid blocking, setup.cpp:863).
        # True = auto (on when the geometry is eligible), False = force
        # the uniform trapezoid shrink.
        self.skew_wavefront = True
        # How many grid dims the skewed wavefront may engage (per-dim
        # profit gates still apply): 1 = the innermost stream dim only
        # (the pre-multi-dim behavior, the 1-D A/B arm), 2 = also the
        # second-innermost dim (its carry buffers a whole inner grid
        # row; the multi-dim trapezoid analog of the reference's
        # wave-front tiling in multiple dims).
        self.skew_dims_max = 2
        # Two-phase trapezoid/diamond temporal tiling on the pallas
        # path (the reference's trapezoidal blocking, setup.cpp:863,
        # recast for a PARALLEL Pallas grid): phase 1 = carry-free
        # upright trapezoids whose per-level write windows shrink by r
        # per side (mutually independent tiles — the grid dims drop the
        # "arbitrary" sequential constraint), phase 2 = inverted
        # trapezoids (diamonds) recomputing the inter-tile gap bands
        # from the level-0 state.  False = off (default; skew remains
        # the auto tiling), True = auto-engage when the TilePlan profit
        # gate says the parallel grid pays; mutually exclusive with the
        # skewed wavefront (carries need a sequential grid).  Pads are
        # planned with the diamond band room when enabled.
        self.trapezoid_tiling = False
        # Push-memory tile-graph fusion on the pallas path (the
        # Halide-to-push-memory dataflow idea, arxiv 2105.12858): an
        # eligible intermediate var's VMEM output tile is consumed by
        # its reader stages inside the SAME grid step and the var
        # leaves both HBM paths (no input DMA, no write-back DMA) —
        # its HBM ring goes stale by design.  "auto" = engage for
        # pipeline-fused contexts only (plain solutions keep every var
        # observable), "on" = auto-engage eligible vars on any pallas
        # context, "force" = raise when nothing is eligible,
        # "off" = never.
        self.push_memory = "auto"
        # Overlapped halo exchange on the shard_pallas path: split each
        # fused K-group into a core chunk (interior shrunk by radius×K
        # per sharded dim, evaluated against PRE-exchange state so XLA
        # runs the previous group's collectives concurrently) + shell
        # slabs on the post-exchange state — the fused-chunk analog of
        # the reference's interior/exterior MPI overlap
        # (context.cpp:377-478).  "auto" = on when every sharded dim's
        # rank domain admits an aligned core (≥ 2·radius·K),
        # "on" = force (raises when infeasible), "off" = serial.
        self.overlap_exchange = "auto"
        # Communication-pattern scheduling for the explicit shard modes
        # (shard_map / shard_pallas), decided by the CommPlan
        # (yask_tpu/parallel/comm_plan.py) off the ICI/DCN link model in
        # perflab.roofline.  comm_order: "" = auto (DCN axes exchange
        # first so their longer flight hides under more compute, then
        # ICI by descending modeled flight time); a comma list like
        # "y,x" forces the order (unknown axes are a CommPlan error —
        # run paths raise, the checker reports COMM-ORDER).
        self.comm_order = ""
        # Message coalescing: pack every buffer's ghost slab for one
        # (mesh axis, direction) into a single concatenated ppermute
        # payload instead of one collective per buffer per face.  Pure
        # data movement — bit-identical to the serial schedule — but
        # fewer collective rounds per exchange.  "auto" = on whenever
        # some axis carries more than one slab, "on" = force,
        # "off" = serial per-buffer collectives.  The joint auto-tuner
        # A/Bs on|off at its winning point when left on "auto".
        self.coalesce = "auto"
        # Let the joint auto-tuner sweep the Pallas VMEM budget
        # (64/96/120 MiB ladder) as an outer tuning axis when
        # vmem_budget_mb is 0 (auto).  Larger budgets admit wider
        # blocks; Mosaic VMEM OOMs are caught as infeasible candidates
        # (never fatal), so the ladder is safe to walk on hardware.
        self.tune_vmem_ladder = True
        # Pallas VMEM budget in MiB (0 = auto: ~16 MiB/core on real TPU
        # per the hardware guide, a loose 100 MiB under CPU interpret
        # where VMEM is emulated). The reference exposes every size knob
        # via CLI (settings.hpp:200-327); this is the TPU-side analog.
        self.vmem_budget_mb = 0
        # Cap on the estimated Mosaic vector-instruction count per fused
        # Pallas kernel (num_ops × wf_steps × VREGs/tile): the tile
        # planner refuses to grow blocks past it.  Guards against
        # pathological Mosaic compile times on op-heavy kernels
        # (ssg-K2/swe2d took >15 min mid-r3); default keeps every
        # current plan (max observed 281k for iso3dfd-256-K2).
        # 0 disables the cap.
        self.max_tile_vinstr = 300_000
        # Run the static checker (yask_tpu.checker) as a preflight in
        # the driver tools (bench.py, tools/tpu_session.py) before
        # spending wall-clock — or a scarce relay window — on a
        # configuration the checker can prove infeasible (the round-3
        # VMEM-OOM class).  Findings print; the launch proceeds (a
        # checker false-positive must not cost a hardware window).
        self.preflight = True
        # Ensemble batching (yask_tpu/runtime/ensemble.py): run N
        # independent instances of the solution as ONE vmapped program
        # — state rings gain a leading batch dim, so N parameter-sweep
        # members share a single compile and saturate the chip on
        # small domains.  Only the single-device modes (jit/pallas)
        # batch; sharded modes decline with a structured reason
        # (ensemble_feasible — the checker's ENSEMBLE-INFEASIBLE rule
        # reads the same definition).  1 = off.
        self.ensemble = 1
        # Server-hosted solution (yask_tpu/serve/): set by
        # StencilServer on the contexts it prepares (also -serve for
        # explicit checker runs).  Gates the checker's serve pass
        # (SERVE-BATCH-INCOMPAT / SERVE-CACHE-COLD) the same way the
        # supervision knobs gate the ckpt pass — a non-serving
        # `make check -all_stencils` stays silent.
        self.serve = False
        # Supervised runs (yask_tpu/resilience/checkpoint.py): checkpoint
        # cadence in steps (0 = off — the hot path sees three int
        # compares and nothing else), snapshot directory (empty = the
        # YT_CKPT_DIR env; cadence without any dir keeps in-memory
        # rollback snapshots only), watchdog scan cadence (nonfinite /
        # all-zero written-interior check every M steps), and a per-chunk
        # deadline in seconds.  Any nonzero knob routes run_solution
        # through the supervision loop with its mode-degradation ladder
        # (shard_pallas → shard_map → jit, pallas → jit).
        self.ckpt_every = 0
        self.ckpt_dir = ""
        self.watchdog_every = 0
        self.run_deadline_secs = 0
        # Misc.
        self.max_threads = 0           # accepted for parity; XLA manages
        self.numa_pref = -1            # accepted for parity
        self.allow_addl_pad = True

    # ------------------------------------------------------------------

    def add_options(self, parser: CommandLineParser) -> None:
        """Register every option (reference ``KernelSettings::add_options``).
        Option names follow the reference CLI (``-g``, ``-d``, ``-b``,
        ``-nr``, ``-wf_steps``…), with per-dim forms like ``-d_x``."""
        dd = self.domain_dims
        parser.add_idx_option(
            "g", "Global (overall) domain size in each dim.", self,
            "global_domain_sizes", dd)
        parser.add_idx_option(
            "d", "Per-rank domain size in each dim.", self,
            "rank_domain_sizes", dd)
        parser.add_idx_option(
            "b", "Block (tile) size hint in each dim.", self,
            "block_sizes", dd)
        parser.add_idx_option(
            "mp", "Minimum extra pad in each dim.", self,
            "min_pad_sizes", dd)
        parser.add_idx_option(
            "nr", "Number of ranks (mesh extent) in each dim.", self,
            "num_ranks", dd)
        parser.add_int_option(
            "wf_steps", "Steps fused per compiled chunk (temporal "
            "wave-front analog).", self, "wf_steps")
        parser.add_string_option(
            "mode", f"Execution mode, one of {MODES}.", self, "mode")
        parser.add_bool_option(
            "overlap_comms", "Overlap ghost exchange with interior compute.",
            self, "overlap_comms")
        parser.add_bool_option(
            "measure_halo", "Measure halo-exchange time (calibrates a "
            "no-exchange twin program once per variant).", self,
            "measure_halo_time")
        parser.add_bool_option(
            "use_shm", "Accepted for reference parity (no-op on TPU).",
            self, "use_shm")
        parser.add_bool_option(
            "use_device_mpi", "Accepted for reference parity (no-op on TPU).",
            self, "use_device_mpi")
        parser.add_bool_option(
            "force_scalar", "Use the eager numpy oracle instead of the "
            "compiled path.", self, "force_scalar")
        parser.add_bool_option(
            "auto_tune", "Auto-tune tile sizes during the run.", self,
            "do_auto_tune")
        parser.add_int_option(
            "tune_max_wf_steps", "Largest wf_steps the auto-tuner may "
            "try (pallas pads are pre-planned to cover it).", self,
            "tune_max_wf_steps")
        parser.add_bool_option(
            "skew", "Streaming skewed-wavefront tiling on the pallas "
            "path (auto-on when eligible; the trapezoid-blocking "
            "analog).", self, "skew_wavefront")
        parser.add_int_option(
            "skew_dims", "Max grid dims the skewed wavefront may "
            "engage (1 = stream dim only, 2 = also the second-inner "
            "dim).", self, "skew_dims_max")
        parser.add_bool_option(
            "trapezoid", "Two-phase trapezoid/diamond temporal tiling "
            "on the pallas path (parallel grid; auto-engaged via the "
            "TilePlan profit gate when enabled).", self,
            "trapezoid_tiling")
        parser.add_string_option(
            "push", "Push-memory tile-graph fusion on the pallas path: "
            "auto|on|force|off (eligible intermediate tiles are "
            "consumed in-VMEM and skip HBM entirely; their rings go "
            "stale — auto engages only for pipeline-fused contexts).",
            self, "push_memory")
        parser.add_string_option(
            "overlap_x", "shard_pallas overlapped halo exchange: "
            "auto|on|off (core/shell split of the fused K-group; the "
            "interior/exterior MPI-overlap analog).", self,
            "overlap_exchange")
        parser.add_string_option(
            "comm_order", "Mesh-axis ghost-exchange order for the shard "
            "modes, e.g. 'y,x' (empty = auto: DCN axes first, then ICI "
            "by modeled flight time — see the CommPlan).", self,
            "comm_order")
        parser.add_string_option(
            "coalesce", "Ghost-exchange message coalescing: auto|on|off "
            "(one concatenated ppermute per mesh axis and direction "
            "instead of one collective per buffer per face).", self,
            "coalesce")
        parser.add_int_option(
            "vmem_mb", "Pallas VMEM budget in MiB (0 = derive from the "
            "device).", self, "vmem_budget_mb")
        parser.add_bool_option(
            "tune_vmem_ladder", "Let the auto-tuner sweep the VMEM "
            "budget (64/96/120 MiB) as an outer axis when -vmem_mb is "
            "0.", self, "tune_vmem_ladder")
        parser.add_int_option(
            "max_vinstr", "Cap on estimated Mosaic vector instructions "
            "per fused kernel (tile-planner growth guard; 0 = off).",
            self, "max_tile_vinstr")
        parser.add_bool_option(
            "preflight", "Run the static checker (yask_tpu.checker) "
            "before launching in the driver tools; findings print, "
            "the launch proceeds (-no-preflight to skip).",
            self, "preflight")
        parser.add_int_option(
            "ensemble", "Batch N independent solution instances as one "
            "vmapped program (jit/pallas single-device modes; sharded "
            "modes decline).  1 = off.", self, "ensemble")
        parser.add_bool_option(
            "serve", "Mark this solution as server-hosted "
            "(yask_tpu/serve/): enables the checker's serve pass "
            "(batch-compatibility + compile-cache warmth).  "
            "StencilServer sets it on the contexts it prepares.",
            self, "serve")
        parser.add_int_option(
            "ckpt_every", "Checkpoint the run every N steps (portable "
            "interior-coordinate snapshots; 0 = off).", self,
            "ckpt_every")
        parser.add_string_option(
            "ckpt_dir", "Directory for on-disk checkpoints (empty = "
            "YT_CKPT_DIR env; cadence without a dir keeps in-memory "
            "rollback snapshots only).", self, "ckpt_dir")
        parser.add_int_option(
            "watchdog_every", "Scan written state for nonfinite / "
            "all-zero interiors every M steps (0 = off).", self,
            "watchdog_every")
        parser.add_int_option(
            "run_deadline", "Per-chunk deadline in seconds for "
            "supervised runs (0 = off).", self, "run_deadline_secs")
        parser.add_int_option(
            "max_threads", "Accepted for reference parity.", self,
            "max_threads")

    # ------------------------------------------------------------------

    def adjust_settings(self, num_devices: int = 1) -> None:
        """Derive unset values (reference ``adjust_settings``,
        ``settings.cpp``): rank grid from device count, global↔rank domain
        sizes, default block sizes."""
        if self.mode not in MODES:
            raise YaskException(f"unknown mode '{self.mode}'; one of {MODES}")

        # Rank grid: like the reference, one rank unless the user asks for
        # decomposition (mpirun -np there; -nr/-mode here). A total of -1 in
        # the first dim means "auto": factorize all devices over the grid
        # keeping the minor-most dim whole for TPU lanes.
        nr = self.num_ranks
        if any(v < 0 for v in nr.get_vals()):
            from yask_tpu.parallel.decomp import factorize_rank_grid
            auto = factorize_rank_grid(max(num_devices, 1), self.domain_dims)
            for d in self.domain_dims:
                nr[d] = auto[d]
        elif all(v == 0 for v in nr.get_vals()) and num_devices > 1 \
                and self.mode in ("sharded", "shard_map", "shard_pallas"):
            # Distribution requested by mode but no grid given: split the
            # outer-most dim so halo slabs stay lane-contiguous.
            for d in self.domain_dims:
                nr[d] = 1
            nr[self.domain_dims[0]] = num_devices
        else:
            for d in self.domain_dims:
                if nr[d] == 0:
                    nr[d] = 1
        if nr.product() > max(num_devices, 1):
            raise YaskException(
                f"rank grid {nr} needs {nr.product()} devices, "
                f"only {num_devices} available")

        # Domain sizes: global ⇄ rank.
        for d in self.domain_dims:
            g, r, n = self.global_domain_sizes[d], self.rank_domain_sizes[d], nr[d]
            if g == 0 and r == 0:
                raise YaskException(f"domain size for dim '{d}' not set")
            if g == 0:
                self.global_domain_sizes[d] = r * n
            elif r == 0:
                if g % n != 0:
                    raise YaskException(
                        f"global size {g} in dim '{d}' not divisible by "
                        f"{n} ranks")
                self.rank_domain_sizes[d] = g // n
            elif r * n != g:
                raise YaskException(
                    f"inconsistent sizes in dim '{d}': global {g} != "
                    f"rank {r} × {n} ranks")
