"""Online auto-tuner.

Counterpart of the reference's ``AutoTuner``
(``src/kernel/lib/auto_tuner.hpp:31-132``, ``auto_tuner.cpp:206``): a
greedy neighborhood walk over the tunable execution parameters, with a
perf cache keyed by the candidate tuple and early abandonment of slower
candidates mid-trial.

On TPU the search space is the **steps fused per compiled chunk**
(``wf_steps`` — the temporal-tiling analog: longer chunks amortize
dispatch and let XLA overlap across steps, at the cost of compile time)
and, when the Pallas backend is active, its **leading-dim block shapes**
(the vector-fold/block analog) — searched jointly: from the planner's
starting point, each move doubles or halves one knob (the reference's
power-of-two radius walk), moving while any neighbor improves. Each
candidate implies one XLA/Mosaic compilation, cached by tuple exactly as
the reference caches per-size results (``auto_tuner.hpp:65``).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from yask_tpu.backend import get_capability
from yask_tpu.resilience import (Breaker, CompilerOOM, classify,
                                 fault_point)


class AutoTuner:
    #: chunk-length candidates for the K-only sweep (jit/sharded modes).
    CHUNK_CANDIDATES = (1, 2, 4, 8, 16, 32)

    #: VMEM-budget rungs (MiB) the joint walks sweep as an OUTER tuning
    #: axis when ``-vmem_mb`` is 0 (auto) and ``-tune_vmem_ladder`` is
    #: on. 64 is the conservative planning default (Mosaic live SSA
    #: values roughly double tile usage); v5e's scoped limit probed
    #: ≥120 MiB, so the upper rungs admit wider blocks (at 512³ r=8 K=2
    #: the 64→96 step is the difference between 8×32 and 16×32 x-blocks)
    #: while Mosaic VMEM OOMs on over-eager rungs are caught as
    #: infeasible candidates, never fatal.  The rungs live in the
    #: backend capability table (single source with the checker's
    #: budget sweep).
    VMEM_LADDER_MIB = get_capability().vmem_ladder_mib

    def __init__(self, ctx):
        self.ctx = ctx
        self.results: Dict[Tuple, float] = {}   # candidate → secs/step
        # Outage breaker shared across every candidate of a walk: a dead
        # relay makes EVERY compile fail, and three consecutive failures
        # must stay loud (round-3 postmortem; hoisted to the shared
        # yask_tpu.resilience.Breaker).
        self._breaker = Breaker(threshold=3)
        # vmem-ladder plan-signature dedupe: rungs whose planner output
        # AND scoped Mosaic limit agree compile identical kernels, so
        # the later rung aliases the earlier rung's measurement instead
        # of re-compiling + re-timing it (all three default rungs share
        # vmem_limit 128 MiB, so a plan the budget doesn't pinch repeats
        # three times without this).
        self._sig_keys: Dict[str, Tuple] = {}
        self.ladder_dedup_hits = 0

    @property
    def _consec_fails(self) -> int:
        return self._breaker.consecutive

    def is_done(self) -> bool:
        return getattr(self.ctx, "_tuned", False)

    def tune_if_needed(self) -> None:
        if not self.is_done():
            self.run_auto_tuner_now()

    def run_auto_tuner_now(self, candidates: Optional[List[int]] = None,
                           min_trial_secs: Optional[float] = None) -> int:
        """Search the candidate space, pick the best, and record it in
        the settings (the API twin of ``yk_solution::run_auto_tuner_now``,
        ``yk_solution_api.hpp:881``). jit/sharded modes sweep chunk
        lengths; the pallas mode walks (K, block-shape) jointly.

        Trials run on a *copy* of the solution state and are discarded:
        unlike the reference (which folds trial steps into the production
        run), replayed trial step indices would corrupt t-dependent
        stencils, so the production run re-executes its full range with
        the tuned settings and the stats/timers only ever see real steps.
        The compiled chunks are cached, so trial compilation is reused."""
        import jax.numpy as jnp
        ctx = self.ctx
        self.trial_secs = (min_trial_secs if min_trial_secs is not None
                           else ctx._opts.auto_tune_trial_secs)
        self.best_rate: Optional[float] = None

        if ctx._mode == "shard_pallas":
            # Trials run on fresh copies of the sharded interiors; the
            # production state (ctx._state / ctx._resident) is untouched.
            # An explicit candidate list becomes a K-only sweep through
            # the SAME distributed executor (never the single-device jit
            # chunk — tuning the multi-chip config on the wrong executor
            # would write a meaningless K into settings).
            saved_cur, saved_done = ctx._cur_step, ctx._steps_done
            try:
                return self._walk_joint_shard(candidates=candidates)
            finally:
                ctx._cur_step, ctx._steps_done = saved_cur, saved_done

        ctx._materialize_state()   # shard-mode runs leave state resident
        ctx._state_to_device()
        saved_state = ctx._state
        saved_cur, saved_done = ctx._cur_step, ctx._steps_done
        # Deep-copy: compiled chunks donate their input buffers, so trials
        # must not be handed the saved arrays.
        ctx._state = {k: [jnp.copy(a) for a in ring]
                      for k, ring in saved_state.items()}
        try:
            if ctx._mode == "pallas" and candidates is None:
                best = self._walk_joint()
            else:
                best = self._sweep_k(candidates)
        finally:
            ctx._state = saved_state
            ctx._cur_step, ctx._steps_done = saved_cur, saved_done
        # After restoring the production state, shrink pads from the
        # tune_max pre-plan to the tuned K (memory; see _replan docstring).
        ctx._replan_pallas_pads(ctx._opts.wf_steps)
        return best

    # ------------------------------------------------------------------

    def _measure(self, key: Tuple, make_compiled, call=None,
                 k: Optional[int] = None) -> float:
        """Timed trial of one candidate (cached): secs/step, or inf when
        the candidate cannot compile (e.g. tile over the VMEM budget).
        A candidate clearly slower than the best is abandoned mid-trial
        (the reference's eval cutoff, ``auto_tuner.cpp:206`` region).

        ``call(compiled)`` performs one k-step trial call (state
        threading included); the default drives ``ctx._state`` — the
        shard walk supplies its own, keeping the warmup/abandonment
        policy in exactly one place."""
        import jax
        if key in self.results:
            return self.results[key]
        ctx = self.ctx
        if k is None:
            k = key[0]
        if call is None:
            dirn = ctx._ana.step_dir

            def call(compiled):
                st = compiled(ctx._state, ctx._cur_step)
                jax.block_until_ready(st)
                ctx._state = st
                ctx._cur_step += k * dirn
        from yask_tpu.utils.exceptions import YaskException
        try:
            fault_point("tuner.measure")
            compiled = make_compiled()
        except YaskException:
            # infeasible candidate (tile over the VMEM budget, fusion
            # beyond planned pads) — skip it
            self.results[key] = float("inf")
            return float("inf")
        except Exception as e:  # noqa: BLE001
            # Backend compile failures are also infeasibility signals:
            # the in-build tile model cannot see Mosaic's register-
            # allocator spill slots, so a candidate can pass the budget
            # check yet exhaust VMEM at compile time (observed on v5e:
            # "Ran out of memory in memory space vmem ... register
            # allocator spill slots", surfaced as an INTERNAL remote-
            # compile error).  Walking on is the reference tuner's
            # stance too: a failed apply just scores worst
            # (auto_tuner.cpp eval loop).  Classification lives in
            # yask_tpu.resilience: a CompilerOOM is a *genuinely
            # infeasible candidate* and never counts toward the outage
            # breaker (so the vmem ladder's ambitious rungs can strike
            # out on dense kernels without ending the walk); every
            # other classified fault (relay drop / hang / compile
            # failure — a dead relay makes EVERY compile fail) feeds
            # the breaker, and three consecutive failures re-raise so
            # an outage stays loud instead of ending the walk
            # "successfully" with all-inf results.
            fault = classify(e, site="tuner.measure")
            if fault is None:
                raise
            msg = f"{type(e).__name__}: {e}"
            if isinstance(fault, CompilerOOM):
                self.ctx._env.trace_msg(
                    f"auto-tuner: candidate {key} exceeded VMEM "
                    f"({msg[:160]}); marking infeasible")
                self.results[key] = float("inf")
                return float("inf")
            if self._breaker.record(fault):
                raise
            self.ctx._env.trace_msg(
                f"auto-tuner: candidate {key} failed "
                f"[{fault.kind}] ({msg[:160]}); marking infeasible")
            self.results[key] = float("inf")
            return float("inf")
        self._breaker.reset()
        from yask_tpu.obs.tracer import span
        with span("tuner.trial", phase="tune",
                  candidate=repr(key), k=k) as sp:
            # warmup call (not timed — excludes dispatch jitter)
            call(compiled)
            calls = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < self.trial_secs:
                call(compiled)
                calls += 1
                if self.best_rate is not None and \
                        (time.perf_counter() - t0) / (calls * k) \
                        > 2.0 * self.best_rate:
                    break
            per_step = (time.perf_counter() - t0) / max(calls * k, 1)
            sp.set(per_step=per_step, calls=calls)
        self.results[key] = per_step
        if self.best_rate is None or per_step < self.best_rate:
            self.best_rate = per_step
        return per_step

    def _sweep_k(self, candidates: Optional[List[int]]) -> int:
        """Chunk-length sweep (jit/sharded, or an explicit K list)."""
        ctx = self.ctx
        use_pallas = ctx._mode == "pallas"
        best_key, best = None, None
        for k in list(candidates or self.CHUNK_CANDIDATES):
            if use_pallas:
                mk = (lambda k=k: ctx._get_pallas_chunk(k))
            else:
                mk = (lambda k=k: ctx._get_compiled_chunk(k))
            r = self._measure((k,), mk)
            if r != float("inf") and (best is None or r < best):
                best_key, best = (k,), r
        ctx._tuned = True
        if best_key is None:
            ctx._env.trace_msg("auto-tuner: no feasible candidates; "
                               "keeping current settings")
            return ctx._opts.wf_steps
        ctx._opts.wf_steps = best_key[0]
        ctx._env.trace_msg(
            f"auto-tuner: wf_steps={best_key[0]} ({best * 1e3:.3f} ms/step)")
        return best_key[0]

    def _walk(self, measure, k0, blk0, sizes, lead, kmax) -> Tuple:
        """The greedy (K, block-shape) neighborhood walk itself: a
        coarse ×2/÷2 phase from the starting point, then a refinement
        phase stepping to *adjacent divisors* of each dim (the
        reference's shrinking-radius refinement, ``auto_tuner.cpp:206``
        region — without it, e.g. block 24 on a 48-sized dim is
        unreachable from 8 by doublings alone). Returns the best
        ``(k, blk)`` and its rate via ``self.results``."""

        def fit(d, b):
            b = max(1, min(b, sizes[d]))
            while sizes[d] % b != 0:
                b -= 1
            return b

        def divisor_steps(d, b):
            """Nearest divisors of the dim size strictly above/below b."""
            up = b + 1
            while up <= sizes[d] and sizes[d] % up != 0:
                up += 1
            down = b - 1
            while down >= 1 and sizes[d] % down != 0:
                down -= 1
            out = []
            if up <= sizes[d]:
                out.append(up)
            if down >= 1:
                out.append(down)
            return out

        def walk_from(cur, cur_rate, neigh_fn):
            moved = True
            while moved:
                moved = False
                for cand in neigh_fn(*cur):
                    r = measure(cand)
                    if r < cur_rate:
                        cur, cur_rate = cand, r
                        moved = True
            return cur, cur_rate

        def coarse(k, blk):
            out = []
            for nk in (k * 2, k // 2):
                if 1 <= nk <= kmax:
                    out.append((nk, blk))
            for i, d in enumerate(lead):
                for nb in (fit(d, blk[i] * 2), fit(d, blk[i] // 2)):
                    if nb != blk[i]:
                        out.append((k, blk[:i] + (nb,) + blk[i + 1:]))
            return out

        def refine(k, blk):
            out = []
            for nk in (k + 1, k - 1):
                if 1 <= nk <= kmax:
                    out.append((nk, blk))
            for i, d in enumerate(lead):
                for nb in divisor_steps(d, blk[i]):
                    out.append((k, blk[:i] + (nb,) + blk[i + 1:]))
            return out

        cur = (k0, tuple(fit(d, b) for d, b in zip(lead, blk0)))
        cur_rate = measure(cur)
        cur, cur_rate = walk_from(cur, cur_rate, coarse)
        cur, cur_rate = walk_from(cur, cur_rate, refine)
        return cur, cur_rate

    def _ladder_rungs(self) -> List[int]:
        """VMEM-budget rungs for the joint walks: the full ladder when
        the budget is auto (``-vmem_mb 0``) and ``-tune_vmem_ladder`` is
        on, else just the configured budget (a single rung — the walk
        runs exactly as before)."""
        opts = self.ctx._opts
        if opts.vmem_budget_mb == 0 and getattr(
                opts, "tune_vmem_ladder", False):
            return list(self.VMEM_LADDER_MIB)
        return [opts.vmem_budget_mb]

    def _walk_ladder(self, walk_one, lead) -> int:
        """Outer vmem-budget loop shared by both joint walks.

        ``walk_one(mb, ladder)`` runs one full (K, block) walk with
        ``ctx._opts.vmem_budget_mb`` temporarily set to ``mb`` and
        returns ``(cur, cur_rate)``; measure keys gain the budget
        element only when laddering so single-rung behavior (and every
        existing test's key shapes) is unchanged. The winning rung is
        applied into ``vmem_budget_mb`` alongside ``_finish_joint`` so
        production compiles — and ``apply_best`` replays — use it."""
        ctx = self.ctx
        rungs = self._ladder_rungs()
        ladder = len(rungs) > 1
        saved_mb = ctx._opts.vmem_budget_mb
        outcomes = []
        try:
            for mb in rungs:
                ctx._opts.vmem_budget_mb = mb
                cur, cur_rate = walk_one(mb, ladder)
                outcomes.append((cur_rate, mb, cur))
                if ladder:
                    ctx._env.trace_msg(
                        f"auto-tuner: vmem rung {mb} MiB -> "
                        f"{cur} ({cur_rate * 1e3:.3f} ms/step)")
        finally:
            ctx._opts.vmem_budget_mb = saved_mb
        cur_rate, mb, cur = min(outcomes, key=lambda t: t[0])
        if ladder and cur_rate != float("inf"):
            ctx._opts.vmem_budget_mb = mb
            ctx._env.trace_msg(f"auto-tuner: vmem budget {mb} MiB wins")
        return self._finish_joint(cur, cur_rate, lead)

    def _plan_signature(self, k: int, blk: Tuple, mb: int):
        """Canonical JSON of the planner's full decision record for
        ``(K, block, budget)`` plus the scoped Mosaic limit that budget
        implies.  Two ladder rungs with equal signatures would compile
        byte-identical kernels — ``plan_only`` is the planner itself, so
        every block shrink, skew/trapezoid engagement, and pipeline
        decision is in the dict and the signature cannot drift from the
        build.  ``reasons`` strings (and the raw budget) are stripped
        recursively: they mention the rung by name without changing the
        artifact.  Returns None when planning fails (``_measure``
        classifies the failure on the real build instead)."""
        import json
        ctx = self.ctx
        from yask_tpu.checker.vmem import plan_pallas
        from yask_tpu.ops.pallas_stencil import vmem_limit_bytes
        bs = ctx._opts.block_sizes
        lead = ctx._ana.domain_dims[:-1]
        old_b = {d: bs[d] for d in lead}
        old_k = ctx._opts.wf_steps
        for d, b in zip(lead, blk):
            bs[d] = b
        ctx._opts.wf_steps = k
        try:
            plan = plan_pallas(ctx, ctx._program, mb * 2 ** 20)
        except Exception:  # noqa: BLE001 — infeasible rung, no dedupe
            return None
        finally:
            for d in lead:
                bs[d] = old_b[d]
            ctx._opts.wf_steps = old_k

        def strip(o):
            if isinstance(o, dict):
                return {kk: strip(v) for kk, v in o.items()
                        if kk not in ("reasons", "vmem_budget")}
            if isinstance(o, (list, tuple)):
                return [strip(x) for x in o]
            return o

        sig = strip(plan)
        sig["vmem_limit"] = vmem_limit_bytes(mb * 2 ** 20)
        return json.dumps(sig, sort_keys=True, default=str)

    def _dedup_ladder_key(self, k: int, blk: Tuple, mb: int,
                          key: Tuple) -> bool:
        """Alias ``key``'s result to an earlier rung's measurement when
        the plan signatures agree.  Returns True on a dedupe hit."""
        if key in self.results:
            return False
        sig = self._plan_signature(k, blk, mb)
        if sig is None:
            return False
        first = self._sig_keys.setdefault(sig, key)
        if first != key and first in self.results:
            self.results[key] = self.results[first]
            self.ladder_dedup_hits += 1
            self.ctx._env.trace_msg(
                f"auto-tuner: rung candidate {key} plans identically to "
                f"{first}; reusing its measurement")
            return True
        return False

    def _start_point(self, k0):
        """Planner-informed starting (K, blocks) for the joint walk."""
        from yask_tpu.ops.tile_planner import plan_blocks
        ctx = self.ctx
        lead = ctx._ana.domain_dims[:-1]
        bs = ctx._opts.block_sizes
        if any(bs[d] > 0 for d in lead):
            blk0 = tuple(bs[d] if bs[d] > 0 else 8 for d in lead)
        else:
            # seed with the same carry-floor + skewed-margin hints the
            # build's default plan uses, or the walk wastes trials
            # re-discovering the build's own block shape.  shard_pallas
            # engages skew per dim only where that dim is unsharded
            # (the carry cannot cross shards), so the seed must model
            # uniform margins in the sharded dims — same per-dim guard
            # as the HBM model.
            from yask_tpu.ops.pallas_stencil import (
                skew_engaged_dims, skew_plan_hints)
            smin, smarg = None, None
            if ctx._opts.skew_wavefront:
                unsh = None
                if ctx._opts.mode == "shard_pallas":
                    unsh = [d for d in lead
                            if ctx._opts.num_ranks[d] <= 1]
                engaged = skew_engaged_dims(
                    ctx._program, k0, unsharded=unsh,
                    max_dims=ctx._opts.skew_dims_max)
                if engaged:
                    smin, smarg = skew_plan_hints(ctx._program, k0,
                                                  engaged=engaged)
            planned = plan_blocks(ctx._program, fuse_steps=k0,
                                  vmem_budget=ctx.vmem_budget(),
                                  vinstr_cap=ctx._opts.max_tile_vinstr,
                                  min_block=smin, margin_override=smarg)
            blk0 = tuple(planned[d] for d in lead)
        return blk0

    def _finish_joint(self, cur, cur_rate, lead) -> int:
        ctx = self.ctx
        ctx._tuned = True
        if cur_rate == float("inf"):
            ctx._env.trace_msg("auto-tuner: no feasible candidates; "
                               "keeping current settings")
            return ctx._opts.wf_steps
        k, blk = cur
        ctx._opts.wf_steps = k
        for d, b in zip(lead, blk):
            ctx._opts.block_sizes[d] = b
        ctx._env.trace_msg(
            f"auto-tuner: wf_steps={k}, blocks={dict(zip(lead, blk))} "
            f"({cur_rate * 1e3:.3f} ms/step, {len(self.results)} "
            "candidates tried)")
        return k

    def _walk_joint(self) -> int:
        """Joint (K, block-shape) walk for the single-device pallas path.
        K can grow up to ``tune_max_wf_steps`` (pads are pre-planned for
        it when auto-tune was enabled at prepare time; otherwise larger
        Ks fail pad validation and are skipped as infeasible)."""
        ctx = self.ctx
        lead = ctx._ana.domain_dims[:-1]
        sizes = {d: ctx._program.sizes[d] for d in lead}
        bs = ctx._opts.block_sizes
        k0 = max(ctx._opts.wf_steps, 1)
        kmax = max(ctx._opts.tune_max_wf_steps, k0)

        def walk_one(mb, ladder):
            def measure(cand):
                k, blk = cand

                def mk():
                    old = {d: bs[d] for d in lead}
                    for d, b in zip(lead, blk):
                        bs[d] = b
                    try:
                        return ctx._get_pallas_chunk(k)
                    finally:
                        for d in lead:
                            bs[d] = old[d]
                key = (k, blk, mb) if ladder else (k, blk)
                if ladder:
                    self._dedup_ladder_key(k, blk, mb, key)
                return self._measure(key, mk, k=k)

            return self._walk(measure, k0, self._start_point(k0),
                              sizes, lead, kmax)

        best_k = self._walk_ladder(walk_one, lead)
        self._trapezoid_ab(best_k)
        self._push_ab(best_k)
        self._pipeline_ab(best_k)
        return best_k

    def _push_ab(self, kw: int) -> None:
        """Push-memory fusion on/off at the winning (K, blocks, vmem)
        point — the same final-axis shape as the trapezoid arm.  Only
        when the configured ``push_memory`` knob resolves to a live
        push argument AND the planner actually engages a push at the
        winning point (otherwise both arms compile the same kernel);
        the losing arm pins ``push_memory`` so production compiles
        follow the measurement."""
        ctx = self.ctx
        if ctx._push_arg() is False:
            return
        kw = max(kw, 1)
        lead = ctx._ana.domain_dims[:-1]
        blkw = tuple(ctx._opts.block_sizes[d] for d in lead)
        # 0 = unset: plan at the effective default budget, not 0 MiB
        mbw = ctx._opts.vmem_budget_mb or (ctx.vmem_budget() >> 20)
        try:
            plan = self._plan_signature(kw, blkw, mbw)
            import json
            engaged = (plan is not None
                       and json.loads(plan).get("push", False))
        except Exception:  # noqa: BLE001
            engaged = False
        if not engaged:
            return
        rates = {}
        saved = ctx._opts.push_memory
        arms = {False: "off", True: saved}
        try:
            for on in (False, True):
                ctx._opts.push_memory = arms[on]

                def mk():
                    return ctx._get_pallas_chunk(kw)

                rates[on] = self._measure(("push", kw, blkw, mbw, on),
                                          mk, k=kw)
        finally:
            ctx._opts.push_memory = saved
        r_on = rates.get(True, float("inf"))
        r_off = rates.get(False, float("inf"))
        if r_on == float("inf") and r_off == float("inf"):
            return
        win = r_on < r_off
        ctx._opts.push_memory = saved if win else "off"
        ctx._env.trace_msg(
            f"auto-tuner: push={'on' if win else 'off'} "
            f"(on {r_on * 1e3:.3f} vs off {r_off * 1e3:.3f} ms/step)")

    def _trapezoid_ab(self, kw: int) -> None:
        """Trapezoid on/off as the final axis of the single-device joint
        walk, A/B'd at the winning (K, blocks, vmem) point — the analog
        of the shard walk's overlap arm.  Only when the ``-trapezoid``
        knob is enabled AND the auto gate actually engages it at the
        winning point (arms that plan identically would time the same
        kernel twice); the losing arm pins ``trapezoid_tiling`` off so
        production compiles skip the gate the measurement overruled."""
        ctx = self.ctx
        if not getattr(ctx._opts, "trapezoid_tiling", False):
            return
        kw = max(kw, 1)
        lead = ctx._ana.domain_dims[:-1]
        blkw = tuple(ctx._opts.block_sizes[d] for d in lead)
        mbw = ctx._opts.vmem_budget_mb
        try:
            plan = self._plan_signature(kw, blkw, mbw)
            import json
            engaged = (plan is not None
                       and json.loads(plan).get("trapezoid", False))
        except Exception:  # noqa: BLE001
            engaged = False
        if not engaged:
            return
        rates = {}
        saved = ctx._opts.trapezoid_tiling
        try:
            for on in (False, True):
                ctx._opts.trapezoid_tiling = on

                def mk():
                    return ctx._get_pallas_chunk(kw)

                rates[on] = self._measure(("trap", kw, blkw, mbw, on),
                                          mk, k=kw)
        finally:
            ctx._opts.trapezoid_tiling = saved
        r_on = rates.get(True, float("inf"))
        r_off = rates.get(False, float("inf"))
        if r_on == float("inf") and r_off == float("inf"):
            return
        win = r_on < r_off
        ctx._opts.trapezoid_tiling = win
        ctx._env.trace_msg(
            f"auto-tuner: trapezoid={'on' if win else 'off'} "
            f"(on {r_on * 1e3:.3f} vs off {r_off * 1e3:.3f} ms/step)")

    def _pipeline_ab(self, kw: int) -> None:
        """Fused vs host-chained pipeline arm, A/B'd at the winning
        (K, blocks, vmem) point of the joint walk — only when this
        context is the fused program of a
        :class:`~yask_tpu.ops.pipeline.SolutionPipeline` that engaged.
        The chained arm replays the per-step per-stage schedule
        (binding pushes included — its real cost) on trial copies of
        the stage states; the losing arm is pinned into the pipeline
        and the verdict recorded as a structured reason, so a fusion
        the HBM model likes but the measurement overrules never runs
        in production."""
        import jax.numpy as jnp
        ctx = self.ctx
        pipe = getattr(ctx, "_pipeline", None)
        if pipe is None or not getattr(pipe, "_fused", False):
            return
        kw = max(kw, 1)

        def mk():
            return ctx._get_pallas_chunk(kw)

        r_fused = self._measure(("pipe", "fused", kw), mk, k=kw)

        from yask_tpu.utils.exceptions import YaskException
        try:
            ctxs = pipe._ensure_stage_ctxs()
        except YaskException as e:
            ctx._env.trace_msg(
                f"auto-tuner: pipeline chained arm unpreparable ({e}); "
                "keeping fused")
            return
        saved = {}
        for s, c in ctxs.items():
            c._materialize_state()
            c._state_to_device()
            saved[s] = (c._state, c._cur_step, c._steps_done)
            c._state = {k: [jnp.copy(a) for a in ring]
                        for k, ring in c._state.items()}
        c0 = ctxs[pipe.stage_names[0]]
        dirn = c0._ana.step_dir
        t0 = c0._cur_step

        def call(_):
            pipe._run_chained(t0, t0 + (kw - 1) * dirn)

        try:
            r_chain = self._measure(("pipe", "chained", kw),
                                    lambda: None, call=call, k=kw)
        finally:
            for s, c in ctxs.items():
                c._state, c._cur_step, c._steps_done = saved[s]
        if r_fused == float("inf") and r_chain == float("inf"):
            return
        win_fused = r_fused <= r_chain
        verdict = {
            "code": "pipeline-ab", "ok": True,
            "msg": (f"tuner A/B at K={kw}: fused "
                    f"{r_fused * 1e3:.3f} vs chained "
                    f"{r_chain * 1e3:.3f} ms/step -> "
                    f"{'fused' if win_fused else 'host-chained'}"),
            "fused_secs_per_step": r_fused,
            "chained_secs_per_step": r_chain,
        }
        plan = getattr(pipe, "_plan", None)
        if plan is not None:
            plan["reasons"].append(verdict)
        if not win_fused:
            pipe._fused = False
            if plan is not None:
                plan["fused"] = False
        ctx._env.trace_msg("auto-tuner: " + verdict["msg"])

    def _walk_joint_shard(self, candidates=None) -> int:
        """Joint (K, block-shape) walk for the distributed shard_pallas
        path (VERDICT r2: the multi-chip config was tuned on one knob).
        Trials time the real compiled shard_map program — one K-step
        group per call — on copies of the sharded interiors; block
        feasibility is against the *rank* domain (blocks tile shards,
        not the global domain)."""
        import jax
        import jax.numpy as jnp
        from yask_tpu.parallel.shard_step import (
            get_shard_pallas_fn, _prep_names_specs,
            _strip_global_interiors)
        ctx = self.ctx
        lead = ctx._ana.domain_dims[:-1]
        lsizes = ctx._opts.rank_domain_sizes
        sizes = {d: lsizes[d] for d in lead}
        nr = {d: ctx._opts.num_ranks[d] for d in ctx._ana.domain_dims}
        k0 = max(ctx._opts.wf_steps, 1)
        kmax = max(ctx._opts.tune_max_wf_steps, k0)
        dirn = ctx._ana.step_dir

        names, specs_for = _prep_names_specs(ctx, nr)
        src = _strip_global_interiors(ctx, ctx._program, names, ctx._mesh,
                                      specs_for, ctx._opts.global_domain_sizes)
        # Trials donate their inputs: hand them copies, keep src intact.
        trial = {k: [jnp.copy(a) for a in ring] for k, ring in src.items()}
        t_trial = ctx._cur_step
        # Trial executables are keyed (shard_pallas, k, k, blk); evict
        # them when the walk ends — production keys on the full run span,
        # so keeping tens of dead Mosaic executables (and their device
        # buffers) alive for the context's lifetime buys nothing.
        keys_before = set(ctx._jit_cache)

        def make_measure(mb=None, ladder=False):
            def measure(cand):
                k, blk = cand

                def mk():
                    return get_shard_pallas_fn(ctx, trial, t_trial,
                                               n=k, K=k, blk=blk)

                def call(fn):
                    # The donated input is exactly the previous call's
                    # output, so no per-call copy is needed.
                    nonlocal trial, t_trial
                    st = fn(trial, jnp.asarray(t_trial, dtype=jnp.int32))
                    jax.block_until_ready(st)
                    trial = st
                    t_trial += k * dirn
                key = (("sp", k, blk, mb) if ladder else ("sp", k, blk))
                return self._measure(key, mk, call=call, k=k)
            return measure

        measure = make_measure()

        try:
            if candidates is not None:
                # explicit K list: sweep at the current block settings
                def fitd(d, b):
                    b = max(1, min(b, sizes[d]))
                    while sizes[d] % b != 0:
                        b -= 1
                    return b
                blk0 = tuple(fitd(d, b) for d, b in
                             zip(lead, self._start_point(k0)))
                best_key, best = None, None
                for k in candidates:
                    r = measure((k, blk0))
                    if r != float("inf") and (best is None or r < best):
                        best_key, best = (k, blk0), r
                ctx._tuned = True
                if best_key is None:
                    ctx._env.trace_msg("auto-tuner: no feasible "
                                       "candidates; keeping current "
                                       "settings")
                    return ctx._opts.wf_steps
                best_k = self._finish_joint(best_key, best, lead)
            else:
                def walk_one(mb, ladder):
                    return self._walk(make_measure(mb, ladder), k0,
                                      self._start_point(k0), sizes,
                                      lead, kmax)

                best_k = self._walk_ladder(walk_one, lead)

            # Overlapped halo exchange on/off as the final axis of the
            # joint walk, A/B'd at the winning (K, blocks, vmem) point.
            # The walk's own trials run one K-group per call (n=K),
            # where there is no second group to overlap — both
            # schedules compile to the same program — so the arms are
            # timed on TWO-group calls (n=2K, one mid-call exchange
            # round) where the core/shell split can actually hide the
            # collectives.  Only when the setting is "auto" (an
            # explicit on/off is the user's call, not the tuner's) and
            # the geometry admits an aligned core.
            if getattr(ctx._opts, "overlap_exchange", None) == "auto":
                from yask_tpu.parallel.shard_step import overlap_decision
                kw = max(ctx._opts.wf_steps, 1)
                ov_ok, _, _, _ = overlap_decision(ctx, kw)
                if ov_ok:
                    blkw = tuple(ctx._opts.block_sizes[d] for d in lead)
                    mbw = ctx._opts.vmem_budget_mb
                    rates = {}
                    try:
                        for ov in (False, True):
                            ctx._opts.overlap_exchange = ("on" if ov
                                                          else "off")

                            def mk():
                                return get_shard_pallas_fn(
                                    ctx, trial, t_trial, n=2 * kw,
                                    K=kw, blk=blkw)

                            def call(fn):
                                nonlocal trial, t_trial
                                st = fn(trial, jnp.asarray(
                                    t_trial, dtype=jnp.int32))
                                jax.block_until_ready(st)
                                trial = st
                                t_trial += 2 * kw * dirn
                            rates[ov] = self._measure(
                                ("sp", kw, blkw, mbw, ov), mk,
                                call=call, k=2 * kw)
                    finally:
                        ctx._opts.overlap_exchange = "auto"
                    r_on = rates.get(True, float("inf"))
                    r_off = rates.get(False, float("inf"))
                    if r_on != float("inf") or r_off != float("inf"):
                        win = r_on < r_off
                        ctx._opts.overlap_exchange = ("on" if win
                                                      else "off")
                        ctx._env.trace_msg(
                            f"auto-tuner: overlap_x="
                            f"{'on' if win else 'off'} "
                            f"(on {r_on * 1e3:.3f} vs off "
                            f"{r_off * 1e3:.3f} ms/step, "
                            f"2-group trials)")

            # Message coalescing on/off as a final A/B at the winning
            # point (auto only — explicit on/off is the user's call).
            # Only when the CommPlan models a saving (some axis carries
            # more than one slab; a one-buffer exchange already sits at
            # the 2-collectives-per-axis floor).  Timed on two-group
            # calls like the overlap arm: the walk's one-group trials
            # never reach a mid-call exchange, so both schedules would
            # compile to the same program.
            if getattr(ctx._opts, "coalesce", None) == "auto":
                kw = max(ctx._opts.wf_steps, 1)
                plan0 = ctx.comm_plan(kw)
                if plan0.order and not plan0.errors and \
                        plan0.rounds_serial > 2 * len(plan0.order):
                    blkw = tuple(ctx._opts.block_sizes[d] for d in lead)
                    mbw = ctx._opts.vmem_budget_mb
                    rates = {}
                    try:
                        for co in (False, True):
                            ctx._opts.coalesce = "on" if co else "off"

                            def mk():
                                return get_shard_pallas_fn(
                                    ctx, trial, t_trial, n=2 * kw,
                                    K=kw, blk=blkw)

                            def call(fn):
                                nonlocal trial, t_trial
                                st = fn(trial, jnp.asarray(
                                    t_trial, dtype=jnp.int32))
                                jax.block_until_ready(st)
                                trial = st
                                t_trial += 2 * kw * dirn
                            rates[co] = self._measure(
                                ("spc", kw, blkw, mbw, co), mk,
                                call=call, k=2 * kw)
                    finally:
                        ctx._opts.coalesce = "auto"
                    r_on = rates.get(True, float("inf"))
                    r_off = rates.get(False, float("inf"))
                    if r_on != float("inf") or r_off != float("inf"):
                        win = r_on < r_off
                        ctx._opts.coalesce = "on" if win else "off"
                        ctx._env.trace_msg(
                            f"auto-tuner: coalesce="
                            f"{'on' if win else 'off'} "
                            f"(on {r_on * 1e3:.3f} vs off "
                            f"{r_off * 1e3:.3f} ms/step, "
                            f"2-group trials)")
            return best_k
        finally:
            for key in set(ctx._jit_cache) - keys_before:
                if key[0] == "shard_pallas":
                    del ctx._jit_cache[key]

    def apply_best(self) -> None:
        feasible = {k: v for k, v in self.results.items()
                    if v != float("inf")}
        if not feasible:    # nothing measurable — keep current settings
            return
        best = min(feasible, key=feasible.get)
        trap_flag = None
        coal_flag = None
        if best[0] == "sp":     # shard_pallas joint result
            best = best[1:]
        elif best[0] == "trap":  # trapezoid A/B arm won outright
            trap_flag = bool(best[4])
            best = best[1:4]
        elif best[0] == "spc":  # coalesce A/B arm won outright
            coal_flag = bool(best[4])
            best = best[1:4]
        self.ctx._opts.wf_steps = best[0]
        if len(best) > 1:   # joint (k, block-shape) result
            lead = self.ctx._ana.domain_dims[:-1]
            for d, b in zip(lead, best[1]):
                self.ctx._opts.block_sizes[d] = b
        if len(best) > 2 and best[2] is not None:
            # vmem-ladder result: pin the winning budget so replays
            # compile with the rung the measurement actually used
            self.ctx._opts.vmem_budget_mb = best[2]
        if hasattr(self.ctx._opts, "trapezoid_tiling"):
            if trap_flag is not None:
                self.ctx._opts.trapezoid_tiling = trap_flag
            else:
                # trapezoid A/B arms measured at this K but a plain walk
                # key won on raw rate — still pin the faster arm so
                # replays get the tiling the A/B decided on (mirror of
                # the overlap-arm pinning below)
                tarms = {kk[4]: v for kk, v in feasible.items()
                         if len(kk) == 5 and kk[0] == "trap"
                         and kk[1] == best[0]}
                if tarms:
                    self.ctx._opts.trapezoid_tiling = bool(
                        min(tarms, key=tarms.get))
        if hasattr(self.ctx._opts, "coalesce"):
            if coal_flag is not None:
                self.ctx._opts.coalesce = "on" if coal_flag else "off"
            else:
                # mirror of the trapezoid/overlap pinning: the A/B
                # answered the question even when a walk key won on raw
                # rate — pin the faster coalesce arm at the chosen K
                carms = {kk[4]: v for kk, v in feasible.items()
                         if len(kk) == 5 and kk[0] == "spc"
                         and kk[1] == best[0]}
                if carms:
                    self.ctx._opts.coalesce = (
                        "on" if min(carms, key=carms.get) else "off")
        if not hasattr(self.ctx._opts, "overlap_exchange"):
            return
        if len(best) > 3 and best[3] is not None:
            # overlap A/B result (shard_pallas): pin the winning arm —
            # best[3] is the boolean overlap flag of the timed trial
            self.ctx._opts.overlap_exchange = "on" if best[3] else "off"
        else:
            # The walk's one-group trials (no exchange to overlap) can
            # out-rate the two-group A/B arms on raw ms/step, leaving
            # the global best without an overlap element; the A/B still
            # answered the question — pin the faster arm at the chosen
            # K so replays get the schedule the walk decided on.
            arms = {k[4]: v for k, v in feasible.items()
                    if len(k) == 5 and k[0] == "sp" and k[1] == best[0]}
            if arms:
                self.ctx._opts.overlap_exchange = (
                    "on" if min(arms, key=arms.get) else "off")
