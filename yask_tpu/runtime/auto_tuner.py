"""Online auto-tuner.

Counterpart of the reference's ``AutoTuner``
(``src/kernel/lib/auto_tuner.hpp:31-132``, ``auto_tuner.cpp:206``): a greedy
search over the tunable execution parameters, evaluated by timing *real*
solution steps that count toward the run (the reference folds trials into the
production run the same way), with a perf cache keyed by the candidate tuple
and early abandonment of slower candidates.

On TPU the search space is not OpenMP block sizes but the **steps fused per
compiled chunk** (``wf_steps`` — the temporal-tiling analog: longer chunks
amortize dispatch and let XLA overlap across steps, at the cost of compile
time) and, when the Pallas backend is active, its block shapes. Each
candidate implies one XLA compilation, cached by tuple exactly as the
reference caches per-size results (``auto_tuner.hpp:65``).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple


class AutoTuner:
    #: chunk-length candidates (powers of two, like the reference's
    #: power-of-two radius shrinking walk).
    CHUNK_CANDIDATES = (1, 2, 4, 8, 16, 32)

    def __init__(self, ctx):
        self.ctx = ctx
        self.results: Dict[Tuple, float] = {}   # candidate → secs/step

    def is_done(self) -> bool:
        return getattr(self.ctx, "_tuned", False)

    def tune_if_needed(self) -> None:
        if not self.is_done():
            self.run_auto_tuner_now()

    def run_auto_tuner_now(self, candidates: Optional[List[int]] = None,
                           min_trial_secs: Optional[float] = None) -> int:
        """Time each chunk-length candidate, pick the best, and record it
        in ``settings.wf_steps`` (the API twin of
        ``yk_solution::run_auto_tuner_now``, ``yk_solution_api.hpp:881``).

        Trials run on a *copy* of the solution state and are discarded:
        unlike the reference (which folds trial steps into the production
        run), replayed trial step indices would corrupt t-dependent
        stencils, so the production run re-executes its full range with
        the tuned settings and the stats/timers only ever see real steps.
        The compiled chunks are cached, so trial compilation is reused."""
        import jax
        import jax.numpy as jnp
        ctx = self.ctx
        cands = list(candidates or self.CHUNK_CANDIDATES)
        trial_secs = (min_trial_secs if min_trial_secs is not None
                      else ctx._opts.auto_tune_trial_secs)
        dirn = ctx._ana.step_dir
        use_pallas = ctx._mode == "pallas"

        ctx._state_to_device()
        saved_state = ctx._state
        saved_cur, saved_done = ctx._cur_step, ctx._steps_done
        # Deep-copy: compiled chunks donate their input buffers, so trials
        # must not be handed the saved arrays.
        ctx._state = {k: [jnp.copy(a) for a in ring]
                      for k, ring in saved_state.items()}
        try:
            return self._trial_loop(jax, ctx, cands, trial_secs,
                                    dirn, use_pallas)
        finally:
            ctx._state = saved_state
            ctx._cur_step, ctx._steps_done = saved_cur, saved_done

    def _trial_loop(self, jax, ctx, cands, trial_secs,
                    dirn, use_pallas) -> int:
        best_key, best_rate = None, None
        for k in cands:
            key = (k,)
            if use_pallas:
                try:
                    pfn = ctx._get_pallas_chunk(k)
                except Exception:
                    continue  # tile wouldn't fit VMEM etc.
                compiled = pfn
            else:
                compiled = ctx._get_compiled_chunk(k)
            # warmup call (not timed — excludes dispatch jitter)
            st = compiled(ctx._state, ctx._cur_step)
            jax.block_until_ready(st)
            ctx._state = st
            ctx._cur_step += k * dirn
            ctx._steps_done += k
            # timed calls until the trial budget is spent, abandoning the
            # candidate mid-trial once it is clearly slower than the best
            # (the reference's eval cutoff, auto_tuner.cpp:206 region)
            calls = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < trial_secs:
                st = compiled(ctx._state, ctx._cur_step)
                jax.block_until_ready(st)
                ctx._state = st
                ctx._cur_step += k * dirn
                ctx._steps_done += k
                calls += 1
                if best_rate is not None and \
                        (time.perf_counter() - t0) / (calls * k) \
                        > 2.0 * best_rate:
                    break
            elapsed = time.perf_counter() - t0
            per_step = elapsed / max(calls * k, 1)
            self.results[key] = per_step
            if best_rate is None or per_step < best_rate:
                best_rate, best_key = per_step, key
        ctx._tuned = True
        if best_key is None:
            # every candidate infeasible (e.g. pallas tiles over the VMEM
            # budget): keep current settings rather than crash the run
            ctx._env.trace_msg("auto-tuner: no feasible candidates; "
                               "keeping current settings")
            return ctx._opts.wf_steps
        ctx._opts.wf_steps = best_key[0]
        ctx._env.trace_msg(
            f"auto-tuner: wf_steps={best_key[0]} "
            f"({best_rate * 1e3:.3f} ms/step)")
        return best_key[0]

    def apply_best(self) -> None:
        if self.results:
            best = min(self.results, key=self.results.get)
            self.ctx._opts.wf_steps = best[0]
