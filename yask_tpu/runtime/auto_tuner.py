"""Online auto-tuner.

Counterpart of the reference's ``AutoTuner``
(``src/kernel/lib/auto_tuner.hpp:31-132``, ``auto_tuner.cpp:206``): a
greedy neighborhood walk over the tunable execution parameters, with a
perf cache keyed by the candidate tuple and early abandonment of slower
candidates mid-trial.

On TPU the search space is the **steps fused per compiled chunk**
(``wf_steps`` — the temporal-tiling analog: longer chunks amortize
dispatch and let XLA overlap across steps, at the cost of compile time)
and, when the Pallas backend is active, its **leading-dim block shapes**
(the vector-fold/block analog) — searched jointly: from the planner's
starting point, each move doubles or halves one knob (the reference's
power-of-two radius walk), moving while any neighbor improves. Each
candidate implies one XLA/Mosaic compilation, cached by tuple exactly as
the reference caches per-size results (``auto_tuner.hpp:65``).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple


class AutoTuner:
    #: chunk-length candidates for the K-only sweep (jit/sharded modes).
    CHUNK_CANDIDATES = (1, 2, 4, 8, 16, 32)

    def __init__(self, ctx):
        self.ctx = ctx
        self.results: Dict[Tuple, float] = {}   # candidate → secs/step

    def is_done(self) -> bool:
        return getattr(self.ctx, "_tuned", False)

    def tune_if_needed(self) -> None:
        if not self.is_done():
            self.run_auto_tuner_now()

    def run_auto_tuner_now(self, candidates: Optional[List[int]] = None,
                           min_trial_secs: Optional[float] = None) -> int:
        """Search the candidate space, pick the best, and record it in
        the settings (the API twin of ``yk_solution::run_auto_tuner_now``,
        ``yk_solution_api.hpp:881``). jit/sharded modes sweep chunk
        lengths; the pallas mode walks (K, block-shape) jointly.

        Trials run on a *copy* of the solution state and are discarded:
        unlike the reference (which folds trial steps into the production
        run), replayed trial step indices would corrupt t-dependent
        stencils, so the production run re-executes its full range with
        the tuned settings and the stats/timers only ever see real steps.
        The compiled chunks are cached, so trial compilation is reused."""
        import jax.numpy as jnp
        ctx = self.ctx
        self.trial_secs = (min_trial_secs if min_trial_secs is not None
                           else ctx._opts.auto_tune_trial_secs)
        self.best_rate: Optional[float] = None

        ctx._state_to_device()
        saved_state = ctx._state
        saved_cur, saved_done = ctx._cur_step, ctx._steps_done
        # Deep-copy: compiled chunks donate their input buffers, so trials
        # must not be handed the saved arrays.
        ctx._state = {k: [jnp.copy(a) for a in ring]
                      for k, ring in saved_state.items()}
        try:
            if ctx._mode == "pallas" and candidates is None:
                return self._walk_joint()
            return self._sweep_k(candidates)
        finally:
            ctx._state = saved_state
            ctx._cur_step, ctx._steps_done = saved_cur, saved_done

    # ------------------------------------------------------------------

    def _measure(self, key: Tuple, make_compiled) -> float:
        """Timed trial of one candidate (cached): secs/step, or inf when
        the candidate cannot compile (e.g. tile over the VMEM budget).
        A candidate clearly slower than the best is abandoned mid-trial
        (the reference's eval cutoff, ``auto_tuner.cpp:206`` region)."""
        import jax
        if key in self.results:
            return self.results[key]
        ctx = self.ctx
        k = key[0]
        dirn = ctx._ana.step_dir
        from yask_tpu.utils.exceptions import YaskException
        try:
            compiled = make_compiled()
        except YaskException:
            # infeasible candidate (tile over the VMEM budget, fusion
            # beyond planned pads) — skip it; real compile errors raise
            self.results[key] = float("inf")
            return float("inf")
        # warmup call (not timed — excludes dispatch jitter)
        st = compiled(ctx._state, ctx._cur_step)
        jax.block_until_ready(st)
        ctx._state = st
        ctx._cur_step += k * dirn
        calls = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < self.trial_secs:
            st = compiled(ctx._state, ctx._cur_step)
            jax.block_until_ready(st)
            ctx._state = st
            ctx._cur_step += k * dirn
            calls += 1
            if self.best_rate is not None and \
                    (time.perf_counter() - t0) / (calls * k) \
                    > 2.0 * self.best_rate:
                break
        per_step = (time.perf_counter() - t0) / max(calls * k, 1)
        self.results[key] = per_step
        if self.best_rate is None or per_step < self.best_rate:
            self.best_rate = per_step
        return per_step

    def _sweep_k(self, candidates: Optional[List[int]]) -> int:
        """Chunk-length sweep (jit/sharded, or an explicit K list)."""
        ctx = self.ctx
        use_pallas = ctx._mode == "pallas"
        best_key, best = None, None
        for k in list(candidates or self.CHUNK_CANDIDATES):
            if use_pallas:
                mk = (lambda k=k: ctx._get_pallas_chunk(k))
            else:
                mk = (lambda k=k: ctx._get_compiled_chunk(k))
            r = self._measure((k,), mk)
            if r != float("inf") and (best is None or r < best):
                best_key, best = (k,), r
        ctx._tuned = True
        if best_key is None:
            ctx._env.trace_msg("auto-tuner: no feasible candidates; "
                               "keeping current settings")
            return ctx._opts.wf_steps
        ctx._opts.wf_steps = best_key[0]
        ctx._env.trace_msg(
            f"auto-tuner: wf_steps={best_key[0]} ({best * 1e3:.3f} ms/step)")
        return best_key[0]

    def _walk_joint(self) -> int:
        """Greedy (K, block-shape) neighborhood walk for the pallas path:
        start from the planner's choice, try doubling/halving each knob,
        move while something improves (the reference's shrinking-
        neighborhood walk over all block-level sizes)."""
        from yask_tpu.ops.tile_planner import plan_blocks
        ctx = self.ctx
        lead = ctx._ana.domain_dims[:-1]
        sizes = {d: ctx._program.sizes[d] for d in lead}

        def fit(d, b):
            b = max(1, min(b, sizes[d]))
            while sizes[d] % b != 0:
                b -= 1
            return b

        k0 = max(ctx._opts.wf_steps, 1)
        bs = ctx._opts.block_sizes
        if any(bs[d] > 0 for d in lead):
            blk0 = tuple(fit(d, bs[d] if bs[d] > 0 else 8) for d in lead)
        else:
            planned = plan_blocks(ctx._program, fuse_steps=k0)
            blk0 = tuple(planned[d] for d in lead)

        def measure(cand):
            k, blk = cand

            def mk():
                old = {d: bs[d] for d in lead}
                for d, b in zip(lead, blk):
                    bs[d] = b
                try:
                    return ctx._get_pallas_chunk(k)
                finally:
                    for d in lead:
                        bs[d] = old[d]
            return self._measure((k, blk), mk)

        cur = (k0, blk0)
        cur_rate = measure(cur)
        moved = True
        while moved:
            moved = False
            k, blk = cur
            neighbors = []
            for nk in (k * 2, k // 2):
                if nk >= 1:
                    neighbors.append((nk, blk))
            for i, d in enumerate(lead):
                for nb in (fit(d, blk[i] * 2), fit(d, blk[i] // 2)):
                    if nb != blk[i]:
                        neighbors.append(
                            (k, blk[:i] + (nb,) + blk[i + 1:]))
            for cand in neighbors:
                r = measure(cand)
                if r < cur_rate:
                    cur, cur_rate = cand, r
                    moved = True
            # moved → walk again from the new best point

        ctx._tuned = True
        if cur_rate == float("inf"):
            ctx._env.trace_msg("auto-tuner: no feasible candidates; "
                               "keeping current settings")
            return ctx._opts.wf_steps
        k, blk = cur
        ctx._opts.wf_steps = k
        for d, b in zip(lead, blk):
            ctx._opts.block_sizes[d] = b
        ctx._env.trace_msg(
            f"auto-tuner: wf_steps={k}, blocks={dict(zip(lead, blk))} "
            f"({cur_rate * 1e3:.3f} ms/step, {len(self.results)} "
            "candidates tried)")
        return k

    def apply_best(self) -> None:
        if self.results:
            best = min(self.results, key=self.results.get)
            self.ctx._opts.wf_steps = best[0]
            if len(best) > 1:   # joint (k, block-shape) result
                lead = self.ctx._ana.domain_dims[:-1]
                for d, b in zip(lead, best[1]):
                    self.ctx._opts.block_sizes[d] = b
