"""Per-run mutable state, hoisted out of :class:`StencilContext`.

A prepared context owns two kinds of state with different lifetimes:

* the *solution* side — compiled program, geometry plan, jit cache,
  tiling records — built once by ``prepare_solution`` and valid for
  any number of runs;
* the *run* side — the var rings, the device-resident shard
  interiors, the step position, and the run/halo timers — one
  instance per live simulation.

This module is the run side.  ``StencilContext`` keeps its historical
attribute names (``_state``, ``_resident``, ``_cur_step``, …) as
delegating properties onto the active :class:`RunState`, so the var
APIs and every execution path read/write through it unchanged — but
the whole bundle can now be swapped: one prepared+compiled solution
serves many ensemble members (``yask_tpu.runtime.ensemble``) and
repeated runs without re-preparing.  The reference's analog is one
``yk_solution`` per simulation instance sharing a linked kernel
library; here the "library" is the AOT compile cache
(``yask_tpu.cache``) plus the context's plan.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from yask_tpu.utils.timer import YaskTimer


class RunState:
    """One live simulation's mutable state.

    Fields mirror the context attributes they replaced:

    * ``state`` — dict var → ring (list) of padded device arrays,
      oldest→newest (None when unallocated or while ``resident``
      holds the authoritative copy);
    * ``resident`` — device-resident sharded interiors between
      shard-mode runs (pads stripped); host access materializes
      lazily via ``ctx._materialize_state()``;
    * ``state_on_device`` — whether ``state`` arrays are device
      arrays (vs host numpy);
    * ``cur_step`` — the next step index a ``run_solution`` continues
      from (var element APIs resolve ring slots against it);
    * ``steps_done`` — steps accumulated since the last
      ``clear_stats`` (the stats denominator);

    A checkpoint restore (``resilience.checkpoint.apply_snapshot``)
    rewinds ``cur_step``/``steps_done`` to the snapshot's values, but
    steps a supervised run REDOES after a rollback keep accumulating
    in ``steps_done`` and ``run_timer`` once re-run — throughput stats
    honestly charge the redone work instead of hiding it.
    * ``run_timer`` / ``halo_timer`` — elapsed wall-clock accounting
      (compile and halo calibration stay excluded, as before).
    """

    def __init__(self):
        self.state: Optional[Dict[str, List]] = None
        self.resident: Optional[Dict[str, List]] = None
        self.state_on_device = False
        self.cur_step = 0
        self.steps_done = 0
        self.run_timer = YaskTimer()
        self.halo_timer = YaskTimer()

    def reset(self) -> None:
        """Back to the just-prepared shape (timers/step counters keep
        accumulating — ``clear_stats`` is the explicit reset, exactly
        as on the pre-hoist context)."""
        self.state = None
        self.resident = None
        self.state_on_device = False
        self.cur_step = 0

    def __repr__(self):
        return (f"<RunState step={self.cur_step} "
                f"alloc={self.state is not None} "
                f"resident={self.resident is not None}>")
