"""Run statistics: the ``yk_stats`` API.

Counterpart of the reference's ``Stats``/``yk_stats``
(``src/kernel/lib/context.hpp:145-198``, printed by ``get_stats``,
``soln_apis.cpp:349,536-551``): points/reads/writes/FLOP throughput over the
steps done since the last reset, plus the per-phase timer breakdown the
reference keeps for halo exchange (``context.hpp:318-328``).
"""

from __future__ import annotations


class yk_stats:
    def __init__(self, npts: int, nsteps: int, nreads_pp: int,
                 nwrites_pp: int, nfpops_pp: int, elapsed: float,
                 halo_secs: float = 0.0, compile_secs: float = 0.0,
                 halo_exchange_secs: float = 0.0,
                 halo_pack_secs: float = 0.0,
                 halo_cal_spread: float = 0.0,
                 halo_cal_unstable: bool = False,
                 halo_cal_reps: int = 0,
                 halo_overlap_eff: float = 0.0,
                 halo_collectives: int = 0,
                 read_bytes_pp: float = 0.0, write_bytes_pp: float = 0.0,
                 hbm_peak: float = 0.0, tiling: dict | None = None):
        self._npts = npts
        self._nsteps = nsteps
        self._nreads_pp = nreads_pp
        self._nwrites_pp = nwrites_pp
        self._nfpops_pp = nfpops_pp
        self._elapsed = elapsed
        self._halo = halo_secs
        self._compile = compile_secs
        self._halo_xround = halo_exchange_secs
        self._halo_xpack = halo_pack_secs
        self._halo_cal_spread = halo_cal_spread
        self._halo_cal_unstable = halo_cal_unstable
        self._halo_cal_reps = halo_cal_reps
        self._halo_overlap_eff = halo_overlap_eff
        self._halo_collectives = halo_collectives
        self._rb_pp = read_bytes_pp
        self._wb_pp = write_bytes_pp
        self._hbm_peak = hbm_peak
        self._tiling = tiling

    def get_tiling(self) -> dict | None:
        """The Pallas tiling the built kernel actually chose (blocks,
        skew, pipelining flags, modeled margin overhead), or None on
        non-pallas paths / before the first build.  Returns a copy —
        the underlying dict also drives the context's HBM traffic
        model."""
        if self._tiling is None:
            return None
        out = dict(self._tiling)
        if isinstance(out.get("block"), dict):
            out["block"] = dict(out["block"])
        return out

    def get_num_elements(self) -> int:
        """Points in the global domain (per step)."""
        return self._npts

    def get_num_steps_done(self) -> int:
        return self._nsteps

    def get_num_writes_done(self) -> int:
        return self._npts * self._nwrites_pp * self._nsteps

    def get_num_reads_done(self) -> int:
        return self._npts * self._nreads_pp * self._nsteps

    def get_est_fp_ops_done(self) -> int:
        return self._npts * self._nfpops_pp * self._nsteps

    def get_elapsed_secs(self) -> float:
        return self._elapsed

    def get_halo_secs(self) -> float:
        return self._halo

    def get_compile_secs(self) -> float:
        """TPU-specific: XLA compilation time excluded from throughput
        (the analog of the reference excluding auto-tuner warmup)."""
        return self._compile

    # -- derived throughput (the log lines YaskUtils.pm:40-58 scrapes) -----

    def get_pts_per_sec(self) -> float:
        tot = self._npts * self._nsteps
        return tot / self._elapsed if self._elapsed > 0 else 0.0

    def get_flops(self) -> float:
        return (self.get_est_fp_ops_done() / self._elapsed
                if self._elapsed > 0 else 0.0)

    def get_halo_exchange_secs(self) -> float:
        """Calibrated cost of ONE bare ghost-exchange round (pack +
        collectives + unpack) — next to get_halo_secs(), which includes
        overlap effects."""
        return self._halo_xround

    def get_halo_pack_secs(self) -> float:
        """Slab pack/unpack share of one exchange round (the round with
        collectives elided) — reference pack/unpack timers,
        ``context.hpp:318-328``."""
        return self._halo_xpack

    def get_halo_collective_secs(self) -> float:
        """Collective-wait share of one exchange round (round − pack) —
        reference MPI wait-timer analog."""
        return max(0.0, self._halo_xround - self._halo_xpack)

    def get_halo_cal_spread(self) -> float:
        """Relative spread ((max−min)/median) across the ≥3 calibration
        trials behind the halo fraction (real program vs no-exchange
        twin).  A fraction whose spread is of the same magnitude is
        noise, not signal — consumers (ledger rows, the sentinel)
        record this next to the fraction so short-run twin jitter
        can't masquerade as a halo-cost change."""
        return self._halo_cal_spread

    def get_halo_cal_unstable(self) -> bool:
        """True when the halo calibration stayed outlier-contaminated
        even after its one full re-time (an extreme trial beyond 3× the
        agreeing pair's spread, twice in a row).  The fraction is still
        reported — the median is the best available estimate — but
        consumers must treat the row as noise, not evidence: the ledger
        marks it ``halo_cal_unstable`` and the sentinel's baseline
        logic ignores such rows.  Unstable is only declared after one
        LAST scaled round (2·trials+1 samples) also failed —
        :func:`get_halo_cal_reps` says how many were burned."""
        return self._halo_cal_unstable

    def get_halo_cal_reps(self) -> int:
        """Total calibration trials run across the (real, twin) pair —
        6 when every round was clean, more when outliers forced
        re-times / the final scaled round.  0 when no calibration ran
        (non-shard modes, measure_halo off)."""
        return self._halo_cal_reps

    def get_halo_collectives(self) -> int:
        """Collectives (ppermutes) one full ghost-exchange round issues
        under the scheduled comm plan — counted while tracing the
        exchange-only calibration twin, so it is the executed schedule,
        not a model.  Message coalescing (CommPlan) drops this to
        2 × (exchanged mesh axes); the serial per-buffer schedule pays
        2 × slabs per axis.  0 before halo calibration runs."""
        return self._halo_collectives

    def get_halo_overlap_eff(self) -> float:
        """Fraction of the bare collective cost the shard_pallas
        schedule hid: 1 − measured-halo-cost / (rounds × bare exchange
        round), clamped to [0, 1].  Nonzero for the serial arm too
        (XLA hides some latency regardless); the overlapped core/shell
        split should push it toward 1.  0 when the calibration is
        missing or nothing was hidden — the MPI-overlap efficiency the
        reference derives from its exterior/interior timers."""
        return self._halo_overlap_eff

    def get_hbm_bytes_per_point(self) -> float:
        """Modeled HBM traffic (read+write) per point per step."""
        return self._rb_pp + self._wb_pp

    def get_hbm_bytes_per_sec(self) -> float:
        return self.get_pts_per_sec() * self.get_hbm_bytes_per_point()

    def get_hbm_roofline_fraction(self) -> float:
        """Achieved / peak HBM bandwidth (0 when the peak is unknown)."""
        if self._hbm_peak <= 0:
            return 0.0
        return self.get_hbm_bytes_per_sec() / self._hbm_peak

    def format(self) -> str:
        gpts = self.get_pts_per_sec() / 1e9
        return (f"num-points-per-step: {self._npts}\n"
                f"num-steps-done: {self._nsteps}\n"
                f"elapsed-time (sec): {self._elapsed:.6g}\n"
                f"throughput (num-points/sec): {self.get_pts_per_sec():.6g}\n"
                f"throughput (GPts/s): {gpts:.6g}\n"
                f"throughput (est-FLOPS): {self.get_flops():.6g}\n"
                f"halo-time (sec): {self._halo:.6g}\n"
                f"halo-fraction (%): "
                f"{100.0 * self._halo / self._elapsed if self._elapsed else 0.0:.4g}\n"
                f"halo-exchange-round (sec): {self._halo_xround:.6g}\n"
                f"halo-pack (sec): {self._halo_xpack:.6g}\n"
                f"halo-cal-spread (rel): {self._halo_cal_spread:.4g}\n"
                + ("halo-cal-unstable: true\n"
                   if self._halo_cal_unstable else "")
                + (f"halo-cal-reps: {self._halo_cal_reps}\n"
                   if self._halo_cal_reps else "")
                + f"halo-collective (sec): "
                f"{self.get_halo_collective_secs():.6g}\n"
                + (f"halo-collectives-per-round: "
                   f"{self._halo_collectives}\n"
                   if self._halo_collectives else "")
                + (f"halo-overlap-eff (%): "
                   f"{100.0 * self._halo_overlap_eff:.4g}\n"
                   if self._halo_overlap_eff > 0 else "")
                + f"hbm-bytes-per-point (read+write): "
                f"{self.get_hbm_bytes_per_point():.6g}\n"
                f"achieved-HBM (GB/s): "
                f"{self.get_hbm_bytes_per_sec() / 1e9:.6g}\n"
                f"hbm-roofline-fraction (%): "
                f"{100.0 * self.get_hbm_roofline_fraction():.4g}\n"
                + (f"pallas-tiling: {self._tiling}\n"
                   if self._tiling else "")
                + f"compile-time (sec): {self._compile:.6g}\n")
