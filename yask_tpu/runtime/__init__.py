"""Kernel runtime: the ``yk_*`` API executing as compiled JAX programs.

TPU-native counterpart of the reference's ``src/kernel`` layer: solution
lifecycle (``prepare_solution``/``run_solution``), var storage with halo/pad
geometry and numpy interop, stats/timers, auto-tuning, and distributed
execution over a device mesh instead of MPI ranks.
"""

from yask_tpu.runtime.env import yk_env
from yask_tpu.runtime.settings import KernelSettings
from yask_tpu.runtime.factory import yk_factory
from yask_tpu.runtime.context import StencilContext

__all__ = ["yk_env", "KernelSettings", "yk_factory", "StencilContext"]
