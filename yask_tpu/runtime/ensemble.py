"""Ensemble batching: N independent instances of one prepared
solution run as a single vmapped program.

Small domains (≤128³ — the parameter-sweep / ensemble-seismic-shot
regime) leave most of a chip idle, and N separate runs pay N
trace+lower+compiles.  Here the state rings gain a leading batch dim
(``jnp.stack`` over the members' rings), the step chunk is
``jax.vmap``ed over it, and the batched executable is built once
through :func:`yask_tpu.cache.aot_compile` — so N members cost one
compile and one fused device program per chunk.  The reference's
analog is one ``yk_solution`` per simulation instance sharing a
linked kernel library; the :class:`RunState` hoist
(``yask_tpu/runtime/run_state.py``) is what lets one prepared context
serve all members.

Feasibility is a *mode* property with a single definition
(:func:`ensemble_feasible`): the single-device modes (jit / pallas)
batch; the sharded modes decline with a structured reason (their
state is mesh-decomposed — batching over an unsharded mesh axis is
future work), and ``ref`` is the sequential oracle by contract.  The
checker's ENSEMBLE-INFEASIBLE rule and the bench A/B read the same
function, so a decline is a diagnosable verdict, not a crash.

Per-member initial conditions and result extraction ride the existing
interior-coordinate var APIs unchanged: :meth:`EnsembleRun.member`
swaps the context's active :class:`RunState`, so inside the ``with``
block every ``yk_var`` call targets that member.

Bit-identity contract: a batched run must produce, per member, the
same bits as that member run alone (tests/test_ensemble.py) — vmap
adds a leading axis but the per-lane arithmetic is unchanged.

Masked sub-domain members (``sub_domains=``, serve-side shape
bucketing): a member may occupy only the low-corner ``{dim: size}``
sub-box of the shared geometry.  The masked jit chunk zeroes
everything outside each member's sub-domain after every step (and on
entry), which reproduces the solo run's ghost-zero boundary exactly —
bit-identity extends to members at DIFFERENT logical domain sizes
riding one executable.  jit-only: pallas fuses wf_steps in-kernel and
has no inter-step hook (`yask_tpu.serve.buckets` is the feasibility
gate).  When
the vmapped build fails (e.g. a Pallas primitive without a batching
rule under interpret), the run degrades to sequential members that
still share the context's compiled chunk, and
:attr:`EnsembleRun.batched_reason` records why.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from yask_tpu.utils.exceptions import YaskException

#: modes whose whole state lives on one device — the ones a leading
#: batch dim can simply vmap over.
BATCHED_MODES = ("jit", "pallas")


def sub_domain_masks(ctx, sub_sizes: Dict[str, int]) -> Dict:
    """Per-var boolean masks selecting a tenant's sub-domain inside a
    larger (bucket) geometry: True on ``[origin, origin+sub)`` along
    every domain dim (LOW-corner anchoring), True across misc axes.

    The masked ensemble chunk zeroes everything outside the mask after
    EVERY step — the physical-boundary ghost-zero contract extended
    inward, so an interior point's neighborhood reads exactly what a
    solo run at ``sub_sizes`` would read from its ghost pads.  The
    same masks also zero the INITIAL stacked state: read-only
    coefficient vars are never stepped, so a fill that strayed past
    the sub-domain (e.g. ``set_all_elements_same`` over the whole
    bucket) must be zeroed before the first step reads it."""
    import numpy as np
    ctx._check_prepared()
    masks = {}
    for name, g in ctx._program.geoms.items():
        if g.is_scratch:
            continue
        m = np.zeros(tuple(g.shape), dtype=bool)
        idx = []
        for dn, kind in g.axes:
            if kind == "domain":
                size = int(sub_sizes.get(
                    dn, ctx._opts.global_domain_sizes[dn]))
                idx.append(slice(g.origin[dn], g.origin[dn] + size))
            else:
                idx.append(slice(None))
        m[tuple(idx)] = True
        masks[name] = m
    return masks


def ensemble_feasible(ctx) -> Tuple[bool, str]:
    """Can this configured context batch an ensemble?  Returns
    ``(ok, reason)`` — the ONE definition the run path, the checker's
    ENSEMBLE-INFEASIBLE rule, and the bench A/B all consult (a mode's
    verdict must never differ between preflight and runtime)."""
    mode = ctx._mode or ctx._opts.mode
    if mode == "auto":
        mode = "jit" if ctx._opts.num_ranks.product() <= 1 else "sharded"
    if mode in BATCHED_MODES:
        return True, ""
    if mode == "ref":
        return False, ("mode 'ref' is the sequential numpy oracle; "
                       "ensemble batching only applies to the "
                       "compiled paths (jit/pallas)")
    return False, (
        f"mode '{mode}' decomposes state over the device mesh; "
        "batching would need an unsharded mesh axis (future work) — "
        "run members sequentially or use -mode jit/pallas")


class EnsembleRun:
    """N members of one prepared solution, run as a batch.

    Member 0 *is* the context's current :class:`RunState` (whatever
    initial conditions were already set stay member 0's); members
    1..N-1 get fresh zero-filled states from ``ctx.new_run_state()``.
    Use :meth:`member` to set per-member initial conditions / read
    per-member results through the normal var APIs, and :meth:`run`
    to advance all members together.
    """

    def __init__(self, ctx, n: Optional[int] = None,
                 members: Optional[List] = None,
                 sub_domains: Optional[List[Optional[Dict[str, int]]]]
                 = None):
        ctx._check_prepared()
        if members is not None:
            # Batch EXISTING RunStates (the serving scheduler's shape:
            # each tenant session owns its state; a micro-batch groups
            # them under the one prepared context without adopting the
            # context's own current state as a member).
            if n is not None and n != len(members):
                raise YaskException(
                    f"ensemble n={n} disagrees with {len(members)} "
                    "explicit members")
            n = len(members)
        if n is None or n < 1:
            raise YaskException(f"ensemble size must be >= 1, got {n}")
        ok, why = ensemble_feasible(ctx)
        if not ok:
            raise YaskException(f"ensemble={n} infeasible: {why}")
        self._ctx = ctx
        if members is not None:
            self._members = list(members)
        else:
            self._members = [ctx.get_run_state()]
            self._members += [ctx.new_run_state() for _ in range(n - 1)]
        # Sub-domain masking (serve-side shape bucketing): member i
        # runs as a masked sub-domain of the shared geometry when
        # sub_domains[i] is a {dim: size} dict (None = full domain).
        # Masking interposes after every step INSIDE the scanned jit
        # chunk — pallas fuses wf_steps in-kernel, so masked members
        # are a jit-only contract (buckets.bucket_cobatch_feasible is
        # the single feasibility definition the serve layer consults
        # before ever building one of these).
        self._sub_domains = list(sub_domains) if sub_domains else None
        if self._sub_domains is not None:
            if len(self._sub_domains) != len(self._members):
                raise YaskException(
                    f"sub_domains has {len(self._sub_domains)} entries "
                    f"for {len(self._members)} members")
            if not any(self._sub_domains):
                self._sub_domains = None
        if self._sub_domains is not None \
                and (ctx._mode or ctx._opts.mode) != "jit":
            raise YaskException(
                "masked sub-domain members need the per-step mask "
                "hook of the scanned jit chunk; mode "
                f"'{ctx._mode or ctx._opts.mode}' fuses steps")
        #: "" after a vmapped run; otherwise why the last run degraded
        #: to sequential members (still sharing compiled chunks).
        self.batched_reason = ""

    @property
    def masked(self) -> bool:
        return self._sub_domains is not None

    @property
    def n(self) -> int:
        return len(self._members)

    @contextmanager
    def member(self, i: int):
        """Make member ``i`` the context's active run state for the
        block: every var API call inside targets that member."""
        prev = self._ctx.set_run_state(self._members[i])
        try:
            yield self._ctx
        finally:
            self._ctx.set_run_state(prev)

    # ------------------------------------------------------------------

    def _stack_states(self):
        """Leading-batch-dim state: var → ring of (N, *shape) arrays.
        Stacking copies, so the members' own rings stay valid — the
        sequential fallback restarts from them untouched."""
        import jax.numpy as jnp
        ctx = self._ctx
        for i in range(self.n):
            with self.member(i):
                ctx._check_prepared()
                ctx._state_to_device()
        names = list(self._members[0].state)
        return {
            name: [jnp.stack([m.state[name][s] for m in self._members])
                   for s in range(len(self._members[0].state[name]))]
            for name in names}

    def _unstack_states(self, batched) -> None:
        for i, m in enumerate(self._members):
            m.state = {name: [b[i] for b in ring]
                       for name, ring in batched.items()}
            m.state_on_device = True
            m.resident = None

    def _stacked_masks(self):
        """(N, *shape) boolean mask per state var — True where the
        member's sub-domain lives (full-domain members are all-True,
        so ``where(mask, x, 0)`` is bitwise identity for them and one
        compiled masked chunk serves any sub-domain mix)."""
        import numpy as np
        ctx = self._ctx
        per_member = []
        for sd in self._sub_domains:
            per_member.append(sub_domain_masks(ctx, sd or {}))
        names = list(per_member[0])
        return {name: np.stack([pm[name] for pm in per_member])
                for name in names}

    def _batched_chunk_fn(self, k: int):
        """vmapped+AOT-compiled chunk advancing every member ``k``
        steps.  Cached in the context's jit cache under an
        ensemble-tagged key; persisted via yask_tpu.cache like any
        other executable (key carries the ensemble width — a batched
        program must never alias the unbatched one).  The masked
        variant takes the per-member masks as a RUNTIME argument
        (vmapped alongside the state, never donated), so the same
        executable serves every sub-domain mix at this width."""
        ctx = self._ctx
        key = ("ens_compiled", self.n, k, ctx._mode, self.masked)
        if key in ctx._jit_cache:
            return ctx._jit_cache[key]
        import jax
        from jax import lax
        from yask_tpu.cache import aot_compile
        prog = ctx._program
        dirn = ctx._ana.step_dir

        if ctx._mode == "pallas":
            from yask_tpu.ops.pallas_stencil import build_pallas_chunk
            _, blk, skw = ctx._pallas_build_key(k)
            chunk, _tb = build_pallas_chunk(
                prog, fuse_steps=k, block=blk,
                interpret=ctx._env.get_platform() != "tpu",
                vmem_budget=ctx.vmem_budget(), skew=skw,
                vinstr_cap=ctx._opts.max_tile_vinstr,
                max_skew_dims=ctx._opts.skew_dims_max,
                trapezoid=(None if ctx._opts.trapezoid_tiling
                           else False))
        elif self.masked:
            import jax.numpy as jnp

            # zero-mask after EVERY step: the ghost-zero contract
            # extended inward, so a sub-domain point's neighborhood
            # reads exactly what the solo run's ghost pads would
            # hold.  The selects must live in their OWN programs:
            # even fenced behind lax.optimization_barrier on both
            # sides, a select inside the scan body shifts how XLA
            # compiles the stencil arithmetic itself (fusion /
            # vectorization choices) and the masked run drifts from
            # its solo twin by ulps.  So the masked "chunk" is a
            # chained pair of executables — a vmapped ONE-step
            # program whose graph is exactly the solo chunk's, and a
            # vmapped select program between steps — called k times.
            # Chained == fused is bit-exact for the jit step program
            # (the sequential fallback rests on the same fact);
            # keeping the step graph select-free is what buys
            # bit-identity, the bucketing contract.
            def step1(state, t0):
                def body(carry, _):
                    st, t = carry
                    return (prog.step(st, t), t + dirn), None
                (st, _), _ = lax.scan(body, (state, t0), None,
                                      length=1)
                return st

            def mask_sel(state, masks):
                return {name: [jnp.where(masks[name], s, 0)
                               if name in masks else s for s in ring]
                        for name, ring in state.items()}

            # the step program is graph-identical to an unmasked
            # width-n k=1 ensemble chunk — share its persistent key
            # so warm caches hit across masked/unmasked servers
            res_s = aot_compile(
                jax.vmap(step1, in_axes=(0, None)),
                (self._stacked_example, 0),
                key=ctx._persistent_key("ens_chunk", n=1,
                                        ensemble=self.n,
                                        mode=ctx._mode,
                                        variant=ctx._pallas_variant_key()),
                platform=ctx._env.get_platform(), donate_argnums=0)
            res_m = aot_compile(
                jax.vmap(mask_sel, in_axes=(0, 0)),
                (self._stacked_example, self._mask_example),
                key=ctx._persistent_key("ens_mask", ensemble=self.n,
                                        mode=ctx._mode),
                platform=ctx._env.get_platform(), donate_argnums=0)
            ctx._compile_secs += res_s.compile_secs + res_m.compile_secs
            ctx._last_cache_hit = res_s.cache_hit and res_m.cache_hit
            sfn, mfn = res_s.fn, res_m.fn

            def masked_chunk(state, t0, masks):
                st, t = state, t0
                for _ in range(k):
                    st = mfn(sfn(st, t), masks)
                    t += dirn
                return st

            ctx._jit_cache[key] = masked_chunk
            return masked_chunk
        else:
            def chunk(state, t0):
                def body(carry, _):
                    st, t = carry
                    return (prog.step(st, t), t + dirn), None
                (st, _), _ = lax.scan(body, (state, t0), None, length=k)
                return st

        bchunk = jax.vmap(chunk, in_axes=(0, None))
        example = (self._stacked_example, 0)
        res = aot_compile(
            bchunk, example,
            key=ctx._persistent_key("ens_chunk", n=k, ensemble=self.n,
                                    mode=ctx._mode,
                                    variant=ctx._pallas_variant_key()),
            platform=ctx._env.get_platform(), donate_argnums=0)
        ctx._compile_secs += res.compile_secs
        ctx._last_cache_hit = res.cache_hit
        ctx._jit_cache[key] = res.fn
        return res.fn

    def run(self, first_step_index: int,
            last_step_index: Optional[int] = None) -> None:
        """Advance every member over the step range (inclusive) — the
        ensemble analog of ``run_solution``.  Wall-clock lands in
        member 0's run timer (it is the *aggregate* batched time, not
        a per-member cost); every member's ``cur_step``/``steps_done``
        advance as if run alone."""
        from yask_tpu.obs.tracer import span
        ctx = self._ctx
        ctx._check_prepared()
        if last_step_index is None:
            last_step_index = first_step_index
        start, n = ctx._step_seq(first_step_index, last_step_index)

        try:
            with span("ensemble.run", phase="compute",
                      members=self.n, steps=n, masked=self.masked):
                self._run_batched(start, n)
            self.batched_reason = ""
        except YaskException:
            raise
        except Exception as e:  # noqa: BLE001 - degrade, don't die:
            # a missing vmap batching rule (Pallas primitives under
            # interpret) must cost the batching win, not the run.
            # Member states are untouched (stacking copies), so the
            # sequential path restarts cleanly and still shares the
            # context's compiled per-member chunk.
            self.batched_reason = f"{type(e).__name__}: {e}"
            with span("ensemble.sequential", phase="compute",
                      members=self.n, steps=n,
                      reason=self.batched_reason[:120]):
                self._run_sequential(first_step_index,
                                     last_step_index)
            return

        dirn = ctx._ana.step_dir
        for m in self._members:
            m.cur_step = start + n * dirn
            m.steps_done += n

    def _run_batched(self, start: int, n: int) -> None:
        import jax
        ctx = self._ctx
        batched = self._stack_states()
        masks = None
        if self.masked:
            import jax.numpy as jnp
            masks = {name: jnp.asarray(m)
                     for name, m in self._stacked_masks().items()}
            # mask the INITIAL state too: read-only vars are never
            # stepped, so out-of-sub-domain fill values would leak
            # into the first step's neighborhood reads otherwise
            batched = {name: [jnp.where(masks[name], s, 0)
                              if name in masks else s for s in ring]
                       for name, ring in batched.items()}
        # Example avals for lowering (shapes only — jit caches by
        # shape; keeping the live dict separate lets donation consume
        # it while the key stays valid for every group).
        self._stacked_example = batched
        self._mask_example = masks
        if ctx._mode == "pallas":
            # mirror _run_pallas_steps: fuse depth is bounded by the
            # K the pads were planned for (wf_steps; 0 → 1), never n
            wf = min(max(ctx._opts.wf_steps, 1), n)
        else:
            wf = ctx._opts.wf_steps if ctx._opts.wf_steps > 0 else n
        sizes = []
        rem = n
        while rem > 0:
            k = min(wf, rem)
            sizes.append(k)
            rem -= k
        fns = {k: self._batched_chunk_fn(k) for k in set(sizes)}
        del self._stacked_example
        self._mask_example = None
        dirn = ctx._ana.step_dir
        t = start
        with self._members[0].run_timer:
            st = batched
            for k in sizes:
                st = fns[k](st, t) if masks is None \
                    else fns[k](st, t, masks)
                t += k * dirn
            jax.block_until_ready(st)
        self._unstack_states(st)

    def _mask_member_state(self, i: int) -> None:
        """Zero member ``i``'s state outside its sub-domain — the
        sequential fallback's analog of the in-chunk mask (applied
        before the run and after every step, so fallback bits equal
        the vmapped masked chunk's: jit fused==chained is exact)."""
        import jax.numpy as jnp
        sd = self._sub_domains[i]
        if not sd:
            return
        masks = sub_domain_masks(self._ctx, sd)
        m = self._members[i]
        m.state = {name: [jnp.where(masks[name], s, 0)
                          if name in masks else s for s in ring]
                   for name, ring in m.state.items()}
        m.state_on_device = True
        m.resident = None

    def _run_sequential(self, first_step_index: int,
                        last_step_index: int) -> None:
        if not self.masked:
            for i in range(self.n):
                with self.member(i):
                    self._ctx.run_solution(first_step_index,
                                           last_step_index)
            return
        ctx = self._ctx
        start, n = ctx._step_seq(first_step_index, last_step_index)
        dirn = ctx._ana.step_dir
        for i in range(self.n):
            with self.member(i):
                ctx._state_to_device()
                self._mask_member_state(i)
                t = start
                for _ in range(n):
                    ctx.run_solution(t, t)
                    self._mask_member_state(i)
                    t += dirn
