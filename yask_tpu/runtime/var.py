"""Runtime vars: the ``yk_var`` API over ring-buffered padded arrays.

Counterpart of the reference's var storage layer
(``src/kernel/lib/yk_var.hpp``, ``yk_var_apis.cpp``, ~4.8 kLoC): element and
slice access with numpy interop (the reference uses SWIG pybuffer maps,
``src/kernel/swig/yask_kernel_api.i:30-87``), halo/pad/alloc geometry per
dim, step-index wrapping, dirty tracking, reductions, and fixed-size vars.

Storage itself is a list of padded device arrays (the step ring) owned by the
:class:`~yask_tpu.runtime.context.StencilContext`; a ``yk_var`` is a view
binding the var name to that state — the functional-JAX analog of the
reference's ``YkVarImpl`` holding a pointer into bundled allocations.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from yask_tpu.utils.exceptions import YaskException


class yk_var:
    """View of one var's storage + geometry."""

    def __init__(self, ctx, name: str):
        self._ctx = ctx
        self._name = name
        # Per-step-slot dirty flags for ghost regions (reference dirty
        # bitsets, yk_var.hpp:564,664): True → neighbors' copies stale.
        self._dirty = True

    # -- identity & geometry ----------------------------------------------

    def _geom(self):
        g = self._ctx._program.geoms.get(self._name) if self._ctx._program \
            else None
        if g is None:
            if getattr(self._ctx, "_ended", False):
                raise YaskException(
                    f"var '{self._name}': end_solution was called; call "
                    "prepare_solution again to access var data")
            raise YaskException(
                f"var '{self._name}' not available before prepare_solution")
        return g

    def get_name(self) -> str:
        return self._name

    def get_num_dims(self) -> int:
        return len(self._var().get_dims())

    def get_dim_names(self) -> List[str]:
        return self._var().get_dim_names()

    def is_dim_used(self, dim: str) -> bool:
        return dim in self._var().get_dim_names()

    def _var(self):
        return self._ctx._soln.get_var(self._name)

    def is_fixed_size(self) -> bool:
        return False

    # halo / pad / alloc geometry per domain dim (yk_var_api.hpp geometry
    # accessors; values fixed at prepare time like the reference post-alloc)
    def get_left_halo_size(self, dim: str) -> int:
        return self._var().halo.get(dim, (0, 0))[0]

    def get_right_halo_size(self, dim: str) -> int:
        return self._var().halo.get(dim, (0, 0))[1]

    def get_halo_size(self, dim: str) -> int:
        l, r = self._var().halo.get(dim, (0, 0))
        return max(l, r)

    def set_halo_size(self, dim: str, size: int) -> None:
        """Grow the halo before prepare (``yk_var::set_halo_size``)."""
        if self._ctx._program is not None:
            raise YaskException("cannot change halo after prepare_solution")
        self._var().update_halo(dim, size)
        self._var().update_halo(dim, -size)

    def get_left_pad_size(self, dim: str) -> int:
        return self._geom().pads.get(dim, (0, 0))[0]

    def get_right_pad_size(self, dim: str) -> int:
        return self._geom().pads.get(dim, (0, 0))[1]

    def get_alloc_size(self, dim: str) -> int:
        g = self._geom()
        if dim in g.domain_dims:
            return g.shape[g.axis_of(dim)]
        for n, k in g.axes:
            if n == dim:
                return g.shape[g.axis_of(dim)]
        v = self._var()
        if v.step_dim() is not None and v.step_dim().name == dim:
            return g.alloc
        raise YaskException(f"var '{self._name}' has no dim '{dim}'")

    def get_first_misc_index(self, dim: str) -> int:
        return self._geom().misc_lo[dim]

    def get_last_misc_index(self, dim: str) -> int:
        g = self._geom()
        return g.misc_lo[dim] + g.misc_ext[dim] - 1

    def set_first_misc_index(self, dim: str, idx: int) -> None:
        """Re-base a misc dim's first index (``yk_var_api.hpp``; before
        prepare, like the reference's pre-alloc requirement)."""
        if self._ctx._program is not None:
            raise YaskException(
                "cannot re-base misc indices after prepare_solution")
        v = self._var()
        ext = v.misc_range[dim][1] - v.misc_range[dim][0]
        v.misc_range[dim] = (idx, idx + ext)

    # -- full accessor parity (yk_var_api.hpp) -------------------------
    # The reference distinguishes rank-domain / halo / alloc / "local"
    # index spaces per dim.  This runtime presents the GLOBAL problem on
    # every host API (SPMD shards live inside jit), so rank == overall
    # and "local" == allocation (one address space):
    #   first_rank_domain_index = 0, last = size−1;
    #   halo indices extend by the halos, alloc/local by the pads.

    def get_num_domain_dims(self) -> int:
        return len(self._var().domain_dim_names())

    def get_domain_dim_names(self) -> List[str]:
        return list(self._var().domain_dim_names())

    def get_misc_dim_names(self) -> List[str]:
        return [n for n, k in self._geom().axes if k == "misc"]

    def get_step_dim_name(self) -> str:
        sd = self._var().step_dim()
        return sd.name if sd is not None else ""

    def get_left_extra_pad_size(self, dim: str) -> int:
        return self.get_left_pad_size(dim) - self.get_left_halo_size(dim)

    def get_right_extra_pad_size(self, dim: str) -> int:
        return self.get_right_pad_size(dim) - self.get_right_halo_size(dim)

    def set_left_halo_size(self, dim: str, size: int) -> None:
        """Grow-only, like ``set_halo_size``: the analysis-computed read
        radius is the floor (shrinking below it would undersize pads)."""
        v = self._var()
        if self._ctx._program is not None:
            raise YaskException("cannot change halo after prepare_solution")
        l, r = v.halo.get(dim, (0, 0))
        v.halo[dim] = (max(l, size), r)

    def set_right_halo_size(self, dim: str, size: int) -> None:
        v = self._var()
        if self._ctx._program is not None:
            raise YaskException("cannot change halo after prepare_solution")
        l, r = v.halo.get(dim, (0, 0))
        v.halo[dim] = (l, max(r, size))

    def get_min_pad_size(self, dim: str) -> int:
        return self._ctx._opts.min_pad_sizes[dim]

    def set_min_pad_size(self, dim: str, size: int) -> None:
        """Request at least this much pad (``yk_var::set_min_pad_size``).
        Applied at the next prepare; recorded per dim (a per-var request
        widens every var — a superset of the reference's guarantee)."""
        o = self._ctx._opts
        o.min_pad_sizes[dim] = max(o.min_pad_sizes[dim], int(size))

    set_left_min_pad_size = set_min_pad_size
    set_right_min_pad_size = set_min_pad_size

    def get_rank_domain_size(self, dim: str) -> int:
        return self._ctx.get_overall_domain_size(dim)

    def get_first_rank_domain_index(self, dim: str) -> int:
        return 0

    def get_last_rank_domain_index(self, dim: str) -> int:
        return self._ctx.get_overall_domain_size(dim) - 1

    def get_first_rank_halo_index(self, dim: str) -> int:
        return -self.get_left_halo_size(dim)

    def get_last_rank_halo_index(self, dim: str) -> int:
        return self.get_last_rank_domain_index(dim) \
            + self.get_right_halo_size(dim)

    def get_first_rank_alloc_index(self, dim: str) -> int:
        return -self.get_left_pad_size(dim)

    def get_last_rank_alloc_index(self, dim: str) -> int:
        return self.get_last_rank_domain_index(dim) \
            + self.get_right_pad_size(dim)

    def get_first_local_index(self, dim: str) -> int:
        """First allocated index in ``dim`` (one address space: local ==
        alloc; step dim → oldest valid step, misc → first misc)."""
        g = self._geom()
        v = self._var()
        if v.step_dim() is not None and v.step_dim().name == dim:
            return self.get_first_valid_step_index()
        for n, k in g.axes:
            if n == dim and k == "misc":
                return self.get_first_misc_index(dim)
        return self.get_first_rank_alloc_index(dim)

    def get_last_local_index(self, dim: str) -> int:
        g = self._geom()
        v = self._var()
        if v.step_dim() is not None and v.step_dim().name == dim:
            return self.get_last_valid_step_index()
        for n, k in g.axes:
            if n == dim and k == "misc":
                return self.get_last_misc_index(dim)
        return self.get_last_rank_alloc_index(dim)

    def get_first_valid_step_index(self) -> int:
        """Smallest valid step index currently in the ring
        (``yk_var_api.hpp:317``).  Metadata-only: answered from the
        geometry, never materializing device-resident shard state.
        For reverse-time solutions (step_dir=-1) the oldest slot has the
        LARGER index, so first/last are ordered numerically (ADVICE r3)
        to keep ``are_indices_local`` range checks valid."""
        nslots = self._geom().num_slots
        d = self._ctx._csol.ana.step_dir or 1
        oldest = self._ctx._cur_step - (nslots - 1) * d
        return min(oldest, self._ctx._cur_step)

    def get_last_valid_step_index(self) -> int:
        nslots = self._geom().num_slots
        d = self._ctx._csol.ana.step_dir or 1
        oldest = self._ctx._cur_step - (nslots - 1) * d
        return max(oldest, self._ctx._cur_step)

    def are_indices_local(self, indices) -> bool:
        """True when every index is within the allocated (local) bounds
        (``yk_var_api.hpp:565``)."""
        names = self.get_dim_names()
        try:
            for n, i in zip(names, indices):
                if not (self.get_first_local_index(n) <= i
                        <= self.get_last_local_index(n)):
                    return False
        except YaskException:
            return False
        return True

    # vector forms (the reference's idx_t_vec overloads): values in
    # declared-dim order
    def _vec(self, fn, dims=None):
        return [fn(d) for d in (dims or self.get_dim_names())]

    def get_alloc_size_vec(self):
        return self._vec(self.get_alloc_size)

    def get_first_local_index_vec(self):
        return self._vec(self.get_first_local_index)

    def get_last_local_index_vec(self):
        return self._vec(self.get_last_local_index)

    def get_first_rank_domain_index_vec(self):
        return self._vec(self.get_first_rank_domain_index,
                         self.get_domain_dim_names())

    def get_last_rank_domain_index_vec(self):
        return self._vec(self.get_last_rank_domain_index,
                         self.get_domain_dim_names())

    def get_first_rank_halo_index_vec(self):
        return self._vec(self.get_first_rank_halo_index,
                         self.get_domain_dim_names())

    def get_last_rank_halo_index_vec(self):
        return self._vec(self.get_last_rank_halo_index,
                         self.get_domain_dim_names())

    def get_first_rank_alloc_index_vec(self):
        return self._vec(self.get_first_rank_alloc_index,
                         self.get_domain_dim_names())

    def get_last_rank_alloc_index_vec(self):
        return self._vec(self.get_last_rank_alloc_index,
                         self.get_domain_dim_names())

    def get_rank_domain_size_vec(self):
        return self._vec(self.get_rank_domain_size,
                         self.get_domain_dim_names())

    # parity toggles with documented TPU behavior
    def is_dynamic_step_alloc(self) -> bool:
        return False   # ring allocations are static (XLA static shapes)

    def get_numa_preferred(self) -> int:
        return self._ctx._opts.numa_pref

    def set_numa_preferred(self, node: int) -> bool:
        self._ctx._opts.numa_pref = int(node)   # accepted; HBM is flat
        return True

    def get_halo_exchange_l1_norm(self) -> int:
        return getattr(self, "_l1_norm", 0)

    def set_halo_exchange_l1_norm(self, norm: int) -> None:
        # accepted for parity: exchanges ship rectangular slabs (the
        # ppermute payload), so the diamond-norm optimization is moot
        self._l1_norm = int(norm)

    # -- storage ----------------------------------------------------------

    def is_storage_allocated(self) -> bool:
        ctx = self._ctx
        if ctx._resident is not None:
            return self._name in ctx._resident
        return ctx._state is not None and self._name in ctx._state

    def _ring(self) -> List:
        if not self.is_storage_allocated():
            raise YaskException(
                f"storage for var '{self._name}' not allocated "
                "(call prepare_solution)")
        self._ctx._materialize_state()  # sync from resident shard state
        return self._ctx._state[self._name]

    def _slot_idx(self, t: Optional[int], nslots: int) -> int:
        """Map an absolute step index to a ring slot (the reference's
        step-index wrapping, ``yk_var.hpp:820-825``) given the ring
        length — shared by the padded-state and device-resident paths."""
        g = self._geom()
        if not (g.has_step and g.is_written):
            return 0
        cur = self._ctx._cur_step
        if t is None:
            return nslots - 1
        d = (cur - t) * self._ctx._csol.ana.step_dir
        slot = nslots - 1 - d
        if not (0 <= slot < nslots):
            if self._ctx.get_step_wrap():
                # yk_solution::set_step_wrap(true): any step index is
                # valid and wraps onto the ring (yk_var_api.hpp:95)
                return slot % nslots
            raise YaskException(
                f"step {t} of var '{self._name}' not in allocation "
                f"(current step {cur}, {nslots} slot(s))")
        return slot

    def _slot_for_step(self, t: Optional[int]) -> int:
        return self._slot_idx(t, len(self._ring()))

    def _resident_idx(self, indices: Sequence[int]):
        """(slot, physical index) onto the device-resident stripped
        interiors, or None when state is not resident, any domain index
        addresses a pad, or anything else needs the strict padded path.

        The reference keeps mid-run element writes cheap with per-var
        dirty flags (``yk_var.hpp:564``); here shard-mode state lives
        device-resident between runs and every run re-pads + exchanges
        from the interiors, so an in-place device update is always
        consistent — the escape hatch that avoids a full
        materialize/re-pad round trip per element access."""
        ctx = self._ctx
        if ctx._resident is None or self._name not in ctx._resident:
            return None
        v = self._var()
        g = self._geom()
        if len(indices) != len(v.get_dims()):
            return None   # strict path raises the right error
        t = None
        by_dim = {}
        for d, i in zip(v.get_dims(), indices):
            if d.type.value == "step":
                t = int(i)
                continue
            if d.type.value == "domain":
                idx = int(i) - ctx._rank_offset.get(d.name, 0)
                size = ctx._opts.global_domain_sizes[d.name]
                if not (0 <= idx < size):
                    return None   # pad access: strict path handles it
            else:
                idx = int(i) - g.misc_lo[d.name]
                if not (0 <= idx < g.misc_ext[d.name]):
                    return None
            by_dim[d.name] = idx
        ring = ctx._resident[self._name]
        slot = self._slot_idx(t, len(ring))
        rest = tuple(by_dim[n] for n, _k in g.axes)
        return slot, rest

    def _split_indices(self, indices: Sequence[int]) -> Tuple[Optional[int], List]:
        """Split full-index list (declared dim order) into (step, rest),
        with strict bounds checking (the reference's ``check=1``
        bounds-checked access builds, ``generic_var.hpp:70-97``: indices
        must land inside the allocation — negative indices address the
        left pad explicitly, they never wrap)."""
        v = self._var()
        dims = v.get_dims()
        if len(indices) != len(dims):
            raise YaskException(
                f"var '{self._name}' needs {len(dims)} indices, "
                f"got {len(indices)}")
        t = None
        g = self._geom()
        by_dim = {}
        for d, i in zip(dims, indices):
            if d.type.value == "step":
                t = int(i)
                continue
            if d.type.value == "domain":
                idx = (int(i) + g.origin[d.name]
                       - self._ctx._rank_offset.get(d.name, 0))
                size = g.shape[g.axis_of(d.name)]
            else:
                idx = int(i) - g.misc_lo[d.name]
                # DECLARED misc range, not the tile-padded allocation:
                # strict (check=1) indexing must reject pad rows
                size = g.misc_ext[d.name]
            if not (0 <= idx < size):
                raise YaskException(
                    f"index {d.name}={i} of var '{self._name}' outside "
                    f"the allocation (padded extent {size}, left pad "
                    f"{g.pads.get(d.name, (0, 0))[0] if d.type.value == 'domain' else 0})")
            by_dim[d.name] = idx
        # arrays are stored in PHYSICAL axis order (g.axes: misc first),
        # which may differ from the declared order of the index list
        rest = [by_dim[n] for n, _k in g.axes]
        return t, rest

    # -- element access (yk_var_api.hpp:700-951) ---------------------------

    def get_element(self, indices: Sequence[int]) -> float:
        ri = self._resident_idx(indices)
        if ri is not None:
            slot, rest = ri
            return float(self._ctx._resident[self._name][slot][rest])
        t, rest = self._split_indices(indices)
        arr = np.asarray(self._ring()[self._slot_for_step(t)])
        return float(arr[tuple(rest)])

    def set_element(self, val: float, indices: Sequence[int],
                    strict_indices: bool = True) -> int:
        ri = self._resident_idx(indices)
        if ri is not None:
            slot, rest = ri
            ring = list(self._ctx._resident[self._name])
            ring[slot] = ring[slot].at[rest].set(val)
            self._ctx._resident[self._name] = ring
            self._dirty = True
            return 1
        t, rest = self._split_indices(indices)
        slot = self._slot_for_step(t)
        self._ctx._update_state_array(
            self._name, slot, lambda a: _np_set(a, tuple(rest), val))
        self._dirty = True
        return 1

    def add_to_element(self, val: float, indices: Sequence[int]) -> int:
        ri = self._resident_idx(indices)
        if ri is not None:
            slot, rest = ri
            ring = list(self._ctx._resident[self._name])
            ring[slot] = ring[slot].at[rest].add(val)
            self._ctx._resident[self._name] = ring
            self._dirty = True
            return 1
        t, rest = self._split_indices(indices)
        slot = self._slot_for_step(t)
        self._ctx._update_state_array(
            self._name, slot,
            lambda a: _np_set(a, tuple(rest), a[tuple(rest)] + val))
        self._dirty = True
        return 1

    # -- slice access ------------------------------------------------------

    def _slice_idx(self, first: Sequence[int], last: Sequence[int]):
        tf, rf = self._split_indices(first)
        tl, rl = self._split_indices(last)
        if tf is not None and tl is not None and tf != tl:
            raise YaskException("slice access must use a single step index")
        idx = tuple(slice(a, b + 1) for a, b in zip(rf, rl))
        return tf, idx

    def _declared_perm(self):
        """Permutation mapping physical (g.axes, misc-first) axis order
        to the var's declared dim order — the buffer layout the
        reference's slice APIs promise."""
        g = self._geom()
        phys = [n for n, _k in g.axes]
        decl = [d.name for d in self._var().get_dims()
                if d.type.value != "step"]
        return [phys.index(n) for n in decl]

    def _resident_slice(self, first, last):
        """(slot, physical slice tuple) onto the device-resident
        stripped interiors for an all-interior box, or None (falls back
        to the strict materializing path) — the slice twin of
        :meth:`_resident_idx`, so full-field extraction between shard
        runs (the examples' per-interval probes, the harness'
        validation reads) costs one device slice + transfer instead of
        a whole-state re-pad."""
        v = self._var()
        if len(first) == len(v.get_dims()) == len(last):
            for d, a, b in zip(v.get_dims(), first, last):
                if d.type.value == "step" and int(a) != int(b):
                    return None   # strict path raises single-step error
        rf = self._resident_idx(first)
        rl = self._resident_idx(last)
        if rf is None or rl is None or rf[0] != rl[0]:
            return None
        if any(b < a for a, b in zip(rf[1], rl[1])):
            return None   # reversed/empty box: strict path's no-op
        return rf[0], tuple(slice(a, b + 1)
                            for a, b in zip(rf[1], rl[1]))

    def get_elements_in_slice(self, first_indices: Sequence[int],
                              last_indices: Sequence[int]) -> np.ndarray:
        """Return a numpy copy of the box [first, last] (inclusive) in
        DECLARED dim order, the buffer-protocol surface the reference
        exposes via SWIG pybuffer (arrays are stored misc-first
        physically)."""
        rs = self._resident_slice(first_indices, last_indices)
        if rs is not None:
            slot, idx = rs
            # np.array, not asarray: the API promises a writable COPY
            # (asarray of a jax array is a read-only zero-copy view)
            out = np.array(self._ctx._resident[self._name][slot][idx])
        else:
            t, idx = self._slice_idx(first_indices, last_indices)
            arr = np.asarray(self._ring()[self._slot_for_step(t)])
            out = np.array(arr[idx])
        perm = self._declared_perm()
        if perm != list(range(out.ndim)):
            out = out.transpose(perm)
        return out

    def set_elements_in_slice(self, buf, first_indices: Sequence[int],
                              last_indices: Sequence[int]) -> int:
        data = np.asarray(buf)
        perm = self._declared_perm()
        rs = self._resident_slice(first_indices, last_indices)
        if rs is not None:
            slot, idx = rs
            tgt_shape = tuple(s.stop - s.start for s in idx)
            decl_shape = tuple(tgt_shape[p] for p in perm)
            d = data.reshape(decl_shape)
            if perm != list(range(len(idx))):
                d = d.transpose(np.argsort(perm))
            ring = list(self._ctx._resident[self._name])
            d = d.astype(ring[slot].dtype)
            ring[slot] = ring[slot].at[idx].set(d)
            self._ctx._resident[self._name] = ring
            self._dirty = True
            return int(np.prod(data.shape)) if data.shape else 1
        t, idx = self._slice_idx(first_indices, last_indices)
        slot = self._slot_for_step(t)

        def upd(a):
            out = np.array(a)
            tgt = out[idx]
            # buffer arrives in DECLARED order; store physically
            decl_shape = tuple(tgt.shape[p] for p in perm)
            d = data.reshape(decl_shape)
            if perm != list(range(tgt.ndim)):
                d = d.transpose(np.argsort(perm))
            out[idx] = d
            return out
        self._ctx._update_state_array(self._name, slot, upd)
        self._dirty = True
        return int(np.prod(data.shape)) if data.shape else 1

    def _resident_ring(self):
        """The device-resident stripped-interior ring for whole-var
        fills, or None (strict materializing path).  Fill APIs write by
        INTERIOR coordinates only, and the resident arrays ARE the
        interiors (every shard run re-pads + exchanges from them), so
        an in-place device fill is always consistent — the whole-var
        twin of :meth:`_resident_idx`, saving the materialize/re-pad
        round trip the examples' init-between-intervals pattern pays
        per var."""
        ctx = self._ctx
        if ctx._resident is None or self._name not in ctx._resident:
            return None
        return ctx._resident[self._name]

    def set_all_elements_same(self, val: float) -> None:
        ring = self._resident_ring()
        if ring is not None:
            import jax
            new = []
            for a in ring:
                fill = np.full(a.shape, val, dtype=a.dtype)
                new.append(jax.device_put(fill, a.sharding))
            self._ctx._resident[self._name] = new
            self._dirty = True
            return
        for slot in range(len(self._ring())):
            self._ctx._update_state_array(
                self._name, slot, lambda a: np.full_like(np.asarray(a), val))
        self._dirty = True

    def set_elements_in_seq(self, seed: float = 0.1) -> None:
        """Fill the interior with a deterministic position-dependent
        sequence (the harness' ``-init_seed`` pattern, ``yask_main.cpp:
        239-249``). Values depend only on interior coordinates — never on
        pad geometry — so differently-padded contexts (jit vs pallas vs
        sharded) start from identical state."""
        g = self._geom()
        ring = self._resident_ring()
        if ring is not None:
            # resident arrays are exactly the interiors (domain dims at
            # global size, misc axes whole), so the padded path's
            # interior fill IS a whole-array fill here — same values,
            # element for element
            import jax
            new = []
            for s, a in enumerate(ring):
                n = int(np.prod(a.shape)) if a.shape else 1
                vals = (np.arange(n, dtype=np.float64) % 17 + 1.0) \
                    * seed * (s + 1)
                fill = (vals.reshape(a.shape).astype(a.dtype)
                        if a.shape else vals.astype(a.dtype)[0])
                new.append(jax.device_put(fill, a.sharding))
            self._ctx._resident[self._name] = new
            self._dirty = True
            return
        for slot in range(len(self._ring())):
            def fill(a, s=slot):
                a = np.asarray(a)
                idxs = []
                ishape = []
                for dn, kind in g.axes:
                    if kind == "domain":
                        size = self._ctx._opts.global_domain_sizes[dn]
                        idxs.append(slice(g.origin[dn], g.origin[dn] + size))
                        ishape.append(size)
                    else:
                        idxs.append(slice(None))
                        ishape.append(a.shape[len(idxs) - 1])
                n = int(np.prod(ishape)) if ishape else 1
                vals = (np.arange(n, dtype=np.float64) % 17 + 1.0) \
                    * seed * (s + 1)
                out = np.zeros_like(a)
                out[tuple(idxs)] = vals.reshape(ishape).astype(a.dtype) \
                    if ishape else vals.astype(a.dtype)[0]
                return out
            self._ctx._update_state_array(self._name, slot, fill)
        self._dirty = True

    # -- reductions (yk_var_api.hpp:992-1044) ------------------------------

    # reduction bitmasks (yk_var_api.hpp:965-977)
    yk_sum_reduction = 0x01
    yk_sum_squares_reduction = 0x02
    yk_product_reduction = 0x04
    yk_max_reduction = 0x08
    yk_min_reduction = 0x10

    def reduce_elements_in_slice(self, op, first_indices, last_indices):
        """Reduce a slice.  ``op`` may be a name ('sum', 'product',
        'min', 'max') returning a float, or a bitmask of the
        ``yk_*_reduction`` constants returning a
        :class:`yk_reduction_result` (the reference form,
        ``yk_var_api.hpp:1060``)."""
        data = self.get_elements_in_slice(first_indices, last_indices)
        data64 = data.astype(np.float64)
        if isinstance(op, str):
            if op in ("sum", "add"):
                return float(data64.sum())
            if op in ("product", "mul"):
                return float(data64.prod())
            if op == "min":
                return float(data64.min())
            if op == "max":
                return float(data64.max())
            raise YaskException(f"unknown reduction '{op}'")
        return yk_reduction_result(int(op), data64)

    def sum_elements_in_slice(self, first_indices, last_indices) -> float:
        return self.reduce_elements_in_slice("sum", first_indices, last_indices)

    def _whole_slice(self):
        names = self.get_dim_names()
        first = [self.get_first_local_index(d) for d in names]
        last = [self.get_last_local_index(d) for d in names]
        # reductions cover the owned domain (not pads: ghost zeros would
        # poison products/mins)
        for i, d in enumerate(names):
            if d in self.get_domain_dim_names():
                first[i] = self.get_first_rank_domain_index(d)
                last[i] = self.get_last_rank_domain_index(d)
        v = self._var()
        if v.step_dim() is not None:
            si = names.index(v.step_dim().name)
            # the NEWEST step is cur_step regardless of step direction
            # (for reverse time the numeric max is the OLDEST slot)
            first[si] = last[si] = self._ctx._cur_step
        return first, last

    def get_sum(self) -> float:
        f, l = self._whole_slice()
        return self.reduce_elements_in_slice("sum", f, l)

    def get_sum_squares(self) -> float:
        f, l = self._whole_slice()
        data = self.get_elements_in_slice(f, l).astype(np.float64)
        return float((data * data).sum())

    def get_product(self) -> float:
        f, l = self._whole_slice()
        return self.reduce_elements_in_slice("product", f, l)

    def get_max(self) -> float:
        f, l = self._whole_slice()
        return self.reduce_elements_in_slice("max", f, l)

    def get_min(self) -> float:
        f, l = self._whole_slice()
        return self.reduce_elements_in_slice("min", f, l)

    # -- storage parity (yk_var_api.hpp storage section) ----------------

    def get_num_storage_elements(self) -> int:
        g = self._geom()
        per = 1
        for e in g.shape:
            per *= int(e)
        return per * g.num_slots   # metadata only: no state materialize

    def get_num_storage_bytes(self) -> int:
        return self.get_num_storage_elements() \
            * np.dtype(self._ctx._program.dtype).itemsize

    def get_raw_storage_buffer(self) -> np.ndarray:
        """Host copy of the newest ring slot's padded array (the
        reference returns the raw pointer; device-resident HBM has no
        host-addressable alias, so this is an explicit materialized
        copy)."""
        return np.asarray(self._ring()[-1])

    def alloc_storage(self) -> None:
        """(Re-)allocate this var's ring, zero-filled (the standalone
        half of the reference's alloc path; prepare_solution allocates
        everything in bulk)."""
        ctx = self._ctx
        ctx._check_prepared()
        if self.is_storage_allocated():
            return
        g = self._geom()
        import jax.numpy as jnp
        ctx._materialize_state()
        # jnp.zeros is already a placed device array; other vars' rings
        # keep whatever placement they had (no forced re-transfer)
        ctx._state[self._name] = [
            jnp.zeros(tuple(g.shape), ctx._program.dtype)
            for _ in range(g.num_slots)]

    alloc_data = alloc_storage   # v2 name

    def release_storage(self) -> None:
        """Drop this var's ring (reference ``release_storage``); call
        ``alloc_storage`` (or re-prepare) before running again."""
        ctx = self._ctx
        if self.is_storage_allocated():
            ctx._materialize_state()
            del ctx._state[self._name]

    def is_storage_layout_identical(self, other: "yk_var") -> bool:
        a, b = self._geom(), other._geom()
        return a.axes == b.axes and tuple(a.shape) == tuple(b.shape) \
            and a.num_slots == b.num_slots

    # -- misc --------------------------------------------------------------

    def format_indices(self, indices: Sequence[int]) -> str:
        dims = self.get_dim_names()
        return ", ".join(f"{d}={i}" for d, i in zip(dims, indices))

    def __repr__(self):
        return f"<yk_var '{self._name}'>"


class yk_reduction_result:
    """Result of a mask-form ``reduce_elements_in_slice``
    (``yk_var_api.hpp:983``): reductions are computed in f64 regardless
    of the solution precision; asking for one that was not in the mask
    raises."""

    def __init__(self, mask: int, data64: "np.ndarray"):
        self._mask = mask
        self._n = int(data64.size)
        self._vals = {}
        if mask & yk_var.yk_sum_reduction:
            self._vals["sum"] = float(data64.sum())
        if mask & yk_var.yk_sum_squares_reduction:
            self._vals["sum_squares"] = float((data64 * data64).sum())
        if mask & yk_var.yk_product_reduction:
            self._vals["product"] = float(data64.prod()) if self._n else 1.0
        if mask & yk_var.yk_max_reduction:
            self._vals["max"] = float(data64.max()) if self._n \
                else -float("inf")
        if mask & yk_var.yk_min_reduction:
            self._vals["min"] = float(data64.min()) if self._n \
                else float("inf")

    def get_reduction_mask(self) -> int:
        return self._mask

    def get_num_elements_reduced(self) -> int:
        return self._n

    def _get(self, key):
        if key not in self._vals:
            raise YaskException(f"reduction '{key}' was not requested")
        return self._vals[key]

    def get_sum(self) -> float:
        return self._get("sum")

    def get_sum_squares(self) -> float:
        return self._get("sum_squares")

    def get_product(self) -> float:
        return self._get("product")

    def get_max(self) -> float:
        return self._get("max")

    def get_min(self) -> float:
        return self._get("min")


def _np_set(a, idx, val):
    out = np.array(a)
    out[idx] = val
    return out


class FixedSizeVar:
    """A runtime-created var outside any solution (``yk_solution::
    new_fixed_size_var``, reference fixed-size vars ``yk_var.hpp``): plain
    N-D storage with the element/slice API, used for staging user data.
    Not part of the step program."""

    def __init__(self, name: str, dim_names: List[str],
                 dim_sizes: List[int], dtype=np.float32):
        if len(dim_names) != len(dim_sizes):
            raise YaskException("dim names/sizes length mismatch")
        self._name = name
        self._dims = list(dim_names)
        self._arr = np.zeros(tuple(int(s) for s in dim_sizes), dtype=dtype)

    def get_name(self) -> str:
        return self._name

    def get_num_dims(self) -> int:
        return len(self._dims)

    def get_dim_names(self) -> List[str]:
        return list(self._dims)

    def is_fixed_size(self) -> bool:
        return True

    def get_alloc_size(self, dim: str) -> int:
        return self._arr.shape[self._dims.index(dim)]

    def get_element(self, indices) -> float:
        return float(self._arr[tuple(int(i) for i in indices)])

    def set_element(self, val: float, indices) -> int:
        self._arr[tuple(int(i) for i in indices)] = val
        return 1

    def get_elements_in_slice(self, first_indices, last_indices) -> np.ndarray:
        idx = tuple(slice(int(a), int(b) + 1)
                    for a, b in zip(first_indices, last_indices))
        return np.array(self._arr[idx])

    def set_elements_in_slice(self, buf, first_indices, last_indices) -> int:
        idx = tuple(slice(int(a), int(b) + 1)
                    for a, b in zip(first_indices, last_indices))
        data = np.asarray(buf)
        self._arr[idx] = data.reshape(self._arr[idx].shape)
        return int(data.size)

    def set_all_elements_same(self, val: float) -> None:
        self._arr.fill(val)

    def reduce_elements_in_slice(self, op, first_indices, last_indices):
        d = self.get_elements_in_slice(first_indices,
                                       last_indices).astype(np.float64)
        return {"sum": d.sum, "add": d.sum, "product": d.prod,
                "mul": d.prod, "min": d.min, "max": d.max}[op]()

    def as_numpy(self) -> np.ndarray:
        return self._arr
