"""Runtime vars: the ``yk_var`` API over ring-buffered padded arrays.

Counterpart of the reference's var storage layer
(``src/kernel/lib/yk_var.hpp``, ``yk_var_apis.cpp``, ~4.8 kLoC): element and
slice access with numpy interop (the reference uses SWIG pybuffer maps,
``src/kernel/swig/yask_kernel_api.i:30-87``), halo/pad/alloc geometry per
dim, step-index wrapping, dirty tracking, reductions, and fixed-size vars.

Storage itself is a list of padded device arrays (the step ring) owned by the
:class:`~yask_tpu.runtime.context.StencilContext`; a ``yk_var`` is a view
binding the var name to that state — the functional-JAX analog of the
reference's ``YkVarImpl`` holding a pointer into bundled allocations.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from yask_tpu.utils.exceptions import YaskException


class yk_var:
    """View of one var's storage + geometry."""

    def __init__(self, ctx, name: str):
        self._ctx = ctx
        self._name = name
        # Per-step-slot dirty flags for ghost regions (reference dirty
        # bitsets, yk_var.hpp:564,664): True → neighbors' copies stale.
        self._dirty = True

    # -- identity & geometry ----------------------------------------------

    def _geom(self):
        g = self._ctx._program.geoms.get(self._name) if self._ctx._program \
            else None
        if g is None:
            raise YaskException(
                f"var '{self._name}' not available before prepare_solution")
        return g

    def get_name(self) -> str:
        return self._name

    def get_num_dims(self) -> int:
        return len(self._var().get_dims())

    def get_dim_names(self) -> List[str]:
        return self._var().get_dim_names()

    def is_dim_used(self, dim: str) -> bool:
        return dim in self._var().get_dim_names()

    def _var(self):
        return self._ctx._soln.get_var(self._name)

    def is_fixed_size(self) -> bool:
        return False

    # halo / pad / alloc geometry per domain dim (yk_var_api.hpp geometry
    # accessors; values fixed at prepare time like the reference post-alloc)
    def get_left_halo_size(self, dim: str) -> int:
        return self._var().halo.get(dim, (0, 0))[0]

    def get_right_halo_size(self, dim: str) -> int:
        return self._var().halo.get(dim, (0, 0))[1]

    def get_halo_size(self, dim: str) -> int:
        l, r = self._var().halo.get(dim, (0, 0))
        return max(l, r)

    def set_halo_size(self, dim: str, size: int) -> None:
        """Grow the halo before prepare (``yk_var::set_halo_size``)."""
        if self._ctx._program is not None:
            raise YaskException("cannot change halo after prepare_solution")
        self._var().update_halo(dim, size)
        self._var().update_halo(dim, -size)

    def get_left_pad_size(self, dim: str) -> int:
        return self._geom().pads.get(dim, (0, 0))[0]

    def get_right_pad_size(self, dim: str) -> int:
        return self._geom().pads.get(dim, (0, 0))[1]

    def get_alloc_size(self, dim: str) -> int:
        g = self._geom()
        if dim in g.domain_dims:
            return g.shape[g.axis_of(dim)]
        for n, k in g.axes:
            if n == dim:
                return g.shape[g.axis_of(dim)]
        v = self._var()
        if v.step_dim() is not None and v.step_dim().name == dim:
            return g.alloc
        raise YaskException(f"var '{self._name}' has no dim '{dim}'")

    def get_first_misc_index(self, dim: str) -> int:
        return self._geom().misc_lo[dim]

    def get_last_misc_index(self, dim: str) -> int:
        g = self._geom()
        return g.misc_lo[dim] + g.misc_ext[dim] - 1

    # -- storage ----------------------------------------------------------

    def is_storage_allocated(self) -> bool:
        ctx = self._ctx
        if ctx._resident is not None:
            return self._name in ctx._resident
        return ctx._state is not None and self._name in ctx._state

    def _ring(self) -> List:
        if not self.is_storage_allocated():
            raise YaskException(
                f"storage for var '{self._name}' not allocated "
                "(call prepare_solution)")
        self._ctx._materialize_state()  # sync from resident shard state
        return self._ctx._state[self._name]

    def _slot_for_step(self, t: Optional[int]) -> int:
        """Map an absolute step index to a ring slot (the reference's
        step-index wrapping, ``yk_var.hpp:820-825``)."""
        ring = self._ring()
        g = self._geom()
        if not (g.has_step and g.is_written):
            return 0
        cur = self._ctx._cur_step
        if t is None:
            return len(ring) - 1
        d = (cur - t) * self._ctx._csol.ana.step_dir
        slot = len(ring) - 1 - d
        if not (0 <= slot < len(ring)):
            raise YaskException(
                f"step {t} of var '{self._name}' not in allocation "
                f"(current step {cur}, {len(ring)} slot(s))")
        return slot

    def _split_indices(self, indices: Sequence[int]) -> Tuple[Optional[int], List]:
        """Split full-index list (declared dim order) into (step, rest),
        with strict bounds checking (the reference's ``check=1``
        bounds-checked access builds, ``generic_var.hpp:70-97``: indices
        must land inside the allocation — negative indices address the
        left pad explicitly, they never wrap)."""
        v = self._var()
        dims = v.get_dims()
        if len(indices) != len(dims):
            raise YaskException(
                f"var '{self._name}' needs {len(dims)} indices, "
                f"got {len(indices)}")
        t = None
        g = self._geom()
        by_dim = {}
        for d, i in zip(dims, indices):
            if d.type.value == "step":
                t = int(i)
                continue
            if d.type.value == "domain":
                idx = (int(i) + g.origin[d.name]
                       - self._ctx._rank_offset.get(d.name, 0))
                size = g.shape[g.axis_of(d.name)]
            else:
                idx = int(i) - g.misc_lo[d.name]
                # DECLARED misc range, not the tile-padded allocation:
                # strict (check=1) indexing must reject pad rows
                size = g.misc_ext[d.name]
            if not (0 <= idx < size):
                raise YaskException(
                    f"index {d.name}={i} of var '{self._name}' outside "
                    f"the allocation (padded extent {size}, left pad "
                    f"{g.pads.get(d.name, (0, 0))[0] if d.type.value == 'domain' else 0})")
            by_dim[d.name] = idx
        # arrays are stored in PHYSICAL axis order (g.axes: misc first),
        # which may differ from the declared order of the index list
        rest = [by_dim[n] for n, _k in g.axes]
        return t, rest

    # -- element access (yk_var_api.hpp:700-951) ---------------------------

    def get_element(self, indices: Sequence[int]) -> float:
        t, rest = self._split_indices(indices)
        arr = np.asarray(self._ring()[self._slot_for_step(t)])
        return float(arr[tuple(rest)])

    def set_element(self, val: float, indices: Sequence[int],
                    strict_indices: bool = True) -> int:
        t, rest = self._split_indices(indices)
        slot = self._slot_for_step(t)
        self._ctx._update_state_array(
            self._name, slot, lambda a: _np_set(a, tuple(rest), val))
        self._dirty = True
        return 1

    def add_to_element(self, val: float, indices: Sequence[int]) -> int:
        t, rest = self._split_indices(indices)
        slot = self._slot_for_step(t)
        self._ctx._update_state_array(
            self._name, slot,
            lambda a: _np_set(a, tuple(rest), a[tuple(rest)] + val))
        self._dirty = True
        return 1

    # -- slice access ------------------------------------------------------

    def _slice_idx(self, first: Sequence[int], last: Sequence[int]):
        tf, rf = self._split_indices(first)
        tl, rl = self._split_indices(last)
        if tf is not None and tl is not None and tf != tl:
            raise YaskException("slice access must use a single step index")
        idx = tuple(slice(a, b + 1) for a, b in zip(rf, rl))
        return tf, idx

    def _declared_perm(self):
        """Permutation mapping physical (g.axes, misc-first) axis order
        to the var's declared dim order — the buffer layout the
        reference's slice APIs promise."""
        g = self._geom()
        phys = [n for n, _k in g.axes]
        decl = [d.name for d in self._var().get_dims()
                if d.type.value != "step"]
        return [phys.index(n) for n in decl]

    def get_elements_in_slice(self, first_indices: Sequence[int],
                              last_indices: Sequence[int]) -> np.ndarray:
        """Return a numpy copy of the box [first, last] (inclusive) in
        DECLARED dim order, the buffer-protocol surface the reference
        exposes via SWIG pybuffer (arrays are stored misc-first
        physically)."""
        t, idx = self._slice_idx(first_indices, last_indices)
        arr = np.asarray(self._ring()[self._slot_for_step(t)])
        out = np.array(arr[idx])
        perm = self._declared_perm()
        if perm != list(range(out.ndim)):
            out = out.transpose(perm)
        return out

    def set_elements_in_slice(self, buf, first_indices: Sequence[int],
                              last_indices: Sequence[int]) -> int:
        t, idx = self._slice_idx(first_indices, last_indices)
        slot = self._slot_for_step(t)
        data = np.asarray(buf)
        perm = self._declared_perm()

        def upd(a):
            out = np.array(a)
            tgt = out[idx]
            # buffer arrives in DECLARED order; store physically
            decl_shape = tuple(tgt.shape[p] for p in perm)
            d = data.reshape(decl_shape)
            if perm != list(range(tgt.ndim)):
                d = d.transpose(np.argsort(perm))
            out[idx] = d
            return out
        self._ctx._update_state_array(self._name, slot, upd)
        self._dirty = True
        return int(np.prod(data.shape)) if data.shape else 1

    def set_all_elements_same(self, val: float) -> None:
        for slot in range(len(self._ring())):
            self._ctx._update_state_array(
                self._name, slot, lambda a: np.full_like(np.asarray(a), val))
        self._dirty = True

    def set_elements_in_seq(self, seed: float = 0.1) -> None:
        """Fill the interior with a deterministic position-dependent
        sequence (the harness' ``-init_seed`` pattern, ``yask_main.cpp:
        239-249``). Values depend only on interior coordinates — never on
        pad geometry — so differently-padded contexts (jit vs pallas vs
        sharded) start from identical state."""
        g = self._geom()
        for slot in range(len(self._ring())):
            def fill(a, s=slot):
                a = np.asarray(a)
                idxs = []
                ishape = []
                for dn, kind in g.axes:
                    if kind == "domain":
                        size = self._ctx._opts.global_domain_sizes[dn]
                        idxs.append(slice(g.origin[dn], g.origin[dn] + size))
                        ishape.append(size)
                    else:
                        idxs.append(slice(None))
                        ishape.append(a.shape[len(idxs) - 1])
                n = int(np.prod(ishape)) if ishape else 1
                vals = (np.arange(n, dtype=np.float64) % 17 + 1.0) \
                    * seed * (s + 1)
                out = np.zeros_like(a)
                out[tuple(idxs)] = vals.reshape(ishape).astype(a.dtype) \
                    if ishape else vals.astype(a.dtype)[0]
                return out
            self._ctx._update_state_array(self._name, slot, fill)
        self._dirty = True

    # -- reductions (yk_var_api.hpp:992-1044) ------------------------------

    def reduce_elements_in_slice(self, op: str, first_indices, last_indices) -> float:
        data = self.get_elements_in_slice(first_indices, last_indices)
        data64 = data.astype(np.float64)
        if op in ("sum", "add"):
            return float(data64.sum())
        if op in ("product", "mul"):
            return float(data64.prod())
        if op == "min":
            return float(data64.min())
        if op == "max":
            return float(data64.max())
        raise YaskException(f"unknown reduction '{op}'")

    def sum_elements_in_slice(self, first_indices, last_indices) -> float:
        return self.reduce_elements_in_slice("sum", first_indices, last_indices)

    # -- misc --------------------------------------------------------------

    def format_indices(self, indices: Sequence[int]) -> str:
        dims = self.get_dim_names()
        return ", ".join(f"{d}={i}" for d, i in zip(dims, indices))

    def __repr__(self):
        return f"<yk_var '{self._name}'>"


def _np_set(a, idx, val):
    out = np.array(a)
    out[idx] = val
    return out


class FixedSizeVar:
    """A runtime-created var outside any solution (``yk_solution::
    new_fixed_size_var``, reference fixed-size vars ``yk_var.hpp``): plain
    N-D storage with the element/slice API, used for staging user data.
    Not part of the step program."""

    def __init__(self, name: str, dim_names: List[str],
                 dim_sizes: List[int], dtype=np.float32):
        if len(dim_names) != len(dim_sizes):
            raise YaskException("dim names/sizes length mismatch")
        self._name = name
        self._dims = list(dim_names)
        self._arr = np.zeros(tuple(int(s) for s in dim_sizes), dtype=dtype)

    def get_name(self) -> str:
        return self._name

    def get_num_dims(self) -> int:
        return len(self._dims)

    def get_dim_names(self) -> List[str]:
        return list(self._dims)

    def is_fixed_size(self) -> bool:
        return True

    def get_alloc_size(self, dim: str) -> int:
        return self._arr.shape[self._dims.index(dim)]

    def get_element(self, indices) -> float:
        return float(self._arr[tuple(int(i) for i in indices)])

    def set_element(self, val: float, indices) -> int:
        self._arr[tuple(int(i) for i in indices)] = val
        return 1

    def get_elements_in_slice(self, first_indices, last_indices) -> np.ndarray:
        idx = tuple(slice(int(a), int(b) + 1)
                    for a, b in zip(first_indices, last_indices))
        return np.array(self._arr[idx])

    def set_elements_in_slice(self, buf, first_indices, last_indices) -> int:
        idx = tuple(slice(int(a), int(b) + 1)
                    for a, b in zip(first_indices, last_indices))
        data = np.asarray(buf)
        self._arr[idx] = data.reshape(self._arr[idx].shape)
        return int(data.size)

    def set_all_elements_same(self, val: float) -> None:
        self._arr.fill(val)

    def reduce_elements_in_slice(self, op, first_indices, last_indices):
        d = self.get_elements_in_slice(first_indices,
                                       last_indices).astype(np.float64)
        return {"sum": d.sum, "add": d.sum, "product": d.prod,
                "mul": d.prod, "min": d.min, "max": d.max}[op]()

    def as_numpy(self) -> np.ndarray:
        return self._arr
