"""Kernel factory: ``yk_factory``.

Counterpart of the reference's ``yk_factory`` (``src/kernel/lib/factory.cpp:
36-107``): ``new_env`` bootstraps the execution environment (MPI there,
device discovery here); ``new_solution`` instantiates a runnable context
from a compiled solution — where the reference links a generated
``YASK_STENCIL_SOLUTION`` class, we accept any DSL solution object or a
registered stencil name.
"""

from __future__ import annotations

from typing import Optional

from yask_tpu.runtime.env import yk_env
from yask_tpu.runtime.context import StencilContext


class yk_factory:
    def get_version_string(self) -> str:
        from yask_tpu import __version__
        return __version__

    def new_env(self, devices=None) -> yk_env:
        return yk_env(devices=devices)

    def new_solution(self, env: yk_env, source=None, *,
                     stencil: Optional[str] = None,
                     radius: Optional[int] = None,
                     dtype=None) -> StencilContext:
        """Build a runnable solution.

        ``source`` may be a ``yc_solution``, ``yc_solution_base``, or
        ``CompiledSolution``; alternatively pass ``stencil=`` (+ optional
        ``radius=``) to instantiate from the registered stencil library the
        way the reference's harness selects ``-stencil`` at build time.
        """
        if source is None:
            if stencil is None:
                raise YaskExceptionHelper()
            from yask_tpu.compiler.solution_base import create_solution
            source = create_solution(stencil, radius=radius)
        return StencilContext(env, source, dtype=dtype)


def YaskExceptionHelper():
    from yask_tpu.utils.exceptions import YaskException
    return YaskException("new_solution needs a solution object or stencil=")
