"""Domain decomposition: rank-grid factorization and geometry checks.

Counterpart of the reference's ``setup_rank`` topology work
(``src/kernel/lib/setup.cpp:169-260``): factorizing the rank count into an
N-D grid (``get_compact_factors``, ``setup.cpp:230``), and validating that
each rank's sub-domain can satisfy its neighbors' halo reads.
"""

from __future__ import annotations

from typing import List

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.utils.idx_tuple import IdxTuple


def factorize_rank_grid(num_ranks: int, dims: List[str],
                        minor_dim_whole: bool = True) -> IdxTuple:
    """Choose an N-D rank grid for ``num_ranks`` devices.

    Like the reference's compact factorization, but TPU-first: by default the
    minor-most (last) dim is left unsplit so the 128-lane axis stays long
    and halo slabs stay contiguous.
    """
    t = IdxTuple({d: 1 for d in dims})
    if num_ranks == 1:
        return t
    fact_dims = dims[:-1] if (minor_dim_whole and len(dims) > 1) else dims
    sub = IdxTuple({d: 1 for d in fact_dims})
    sub = sub.get_compact_factors(num_ranks)
    for d in fact_dims:
        t[d] = sub[d]
    return t


def validate_shard_geometry(csol, opts) -> None:
    """Each shard must be at least as wide as the ghost region it serves
    (the reference asserts rank domain ≥ halo similarly during setup)."""
    halos = csol.ana.max_halos()
    for d in csol.ana.domain_dims:
        n = opts.num_ranks[d]
        if n <= 1:
            continue
        g = opts.global_domain_sizes[d]
        if g % n != 0:
            raise YaskException(
                f"shard_map mode needs global size divisible by ranks in "
                f"dim '{d}' ({g} % {n} != 0)")
        local = g // n
        l, r = halos.get(d, (0, 0))
        if local < max(l, r):
            raise YaskException(
                f"rank domain {local} in dim '{d}' smaller than halo "
                f"{max(l, r)}")
