"""Communication-pattern scheduling for the explicit shard modes.

The reference pumps its MPI halo exchange in a fixed neighbor/rank
order and overlaps the flight with interior compute
(``context.cpp:377-478``); large TPU meshes add a twist the reference
never had: axes differ in transport — on-slice ICI torus links vs the
host-crossing DCN, three orders of magnitude apart in latency.  The
``CommPlan`` is the TilePlan analog for that problem, derived once per
prepared solution (pure geometry, never raises) and consumed by BOTH
the shard_map/shard_pallas exchange executors and the static checker's
``COMM-*`` rules, so the executed schedule and the reported one cannot
drift.  Per mesh axis it decides:

* **ordering** — which axis exchanges first.  DCN axes go first (their
  longer flight time needs the most downstream work to hide under),
  then ICI axes by descending modeled flight time, off the link model
  in ``perflab.roofline`` (``link_model``/``order_comm_axes``).  An
  explicit ``-comm_order`` list overrides.
* **coalescing** — every buffer's ghost slab for one (axis, direction)
  packed into a single concatenated ``ppermute`` payload instead of
  one collective per buffer per face (the channel-merging move of
  "Improving Communication Patterns in Polyhedral Process Networks",
  arxiv 1801.04821, applied to halo channels).  ``ppermute`` only
  moves bytes, so the packed schedule is bit-identical to the serial
  one.
* **corners** — nothing: diagonal ghosts are already composed axis
  exchanges (a later axis's slab spans the earlier axes' freshly
  filled ghosts, so X-then-Y forwards the received edges), and that
  composition survives coalescing because the packed path still goes
  axis-by-axis in plan order.  The plan just guarantees an order
  exists; no dedicated diagonal collectives on 2-D/3-D meshes.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class CommPlan:
    """One solution's communication schedule (see module docstring).

    ``order``         — mesh axes in exchange order (only axes that
                        actually carry ghost traffic);
    ``coalesce``      — pack all slabs per (axis, direction) into one
                        ppermute payload;
    ``axes``          — per-dim model: nranks, ici/dcn kind, payload
                        slabs ("items"), bytes per steady-state round,
                        link gbps/latency and modeled flight secs;
    ``rounds`` / ``rounds_serial`` — modeled collective count per full
                        exchange round under this plan vs the serial
                        per-buffer schedule;
    ``reasons``       — structured decision records (explain-pass
                        style), ``errors`` — invalid-knob messages (the
                        run paths raise on them; the checker reports
                        them as ``COMM-ORDER`` instead).
    """

    __slots__ = ("order", "coalesce", "axes", "reasons", "errors",
                 "rounds", "rounds_serial", "mesh_shape", "K", "mode")

    def __init__(self, order, coalesce, axes, reasons, errors,
                 rounds, rounds_serial, mesh_shape, K, mode):
        self.order = list(order)
        self.coalesce = bool(coalesce)
        self.axes = axes
        self.reasons = reasons
        self.errors = errors
        self.rounds = rounds
        self.rounds_serial = rounds_serial
        self.mesh_shape = mesh_shape
        self.K = K
        self.mode = mode

    def key(self):
        """Compiled-schedule cache-key suffix: the parts of the plan a
        traced exchange body bakes in."""
        return (",".join(self.order), self.coalesce)

    def record(self) -> Dict:
        """Structured record for tiling dicts, checker details and
        ledger rows — every per-axis decision, JSON-clean."""
        return {
            "order": list(self.order),
            "coalesce": self.coalesce,
            "mesh": dict(self.mesh_shape),
            "K": self.K,
            "mode": self.mode,
            "axes": {d: dict(a) for d, a in self.axes.items()},
            "rounds": self.rounds,
            "rounds_serial": self.rounds_serial,
            "reasons": [dict(r) for r in self.reasons],
            "errors": list(self.errors),
        }


def mesh_axis_kinds(mesh, dims) -> Dict[str, str]:
    """ici/dcn per mesh axis: an axis whose device row crosses jax
    process boundaries is DCN (multi-host), everything else ICI.  A
    ``None`` mesh (unprepared context) classifies everything ICI."""
    kinds = {d: "ici" for d in dims}
    if mesh is None:
        return kinds
    devs = np.asarray(mesh.devices)
    pidx = np.vectorize(lambda dev: getattr(dev, "process_index", 0))(devs)
    names = list(mesh.axis_names)
    for i, name in enumerate(names):
        if name in kinds and devs.shape[i] > 1:
            first = np.take(pidx, [0], axis=i)
            if bool((pidx != first).any()):
                kinds[name] = "dcn"
    return kinds


def build_comm_plan(ctx, K: Optional[int] = None, prog=None) -> CommPlan:
    """Derive the CommPlan for a configured solution context.

    Pure geometry — never raises, never allocates, never touches a
    device; invalid knobs land in ``plan.errors`` (run paths raise on
    them, the checker reports them).  ``K`` is the fused group size the
    exchange serves (shard_pallas moves radius×K slabs of the min(K,
    slots) newest ring slots; shard_map moves every slot at the raw
    halo widths).
    """
    from yask_tpu.perflab.roofline import (link_model, link_secs,
                                           order_comm_axes)
    opts = ctx._opts
    ana = ctx._ana
    dims = list(ana.domain_dims)
    mode = ctx._mode or opts.mode
    if K is None:
        K = max(opts.wf_steps, 1) if mode == "shard_pallas" else 1
    K = max(int(K), 1)
    if prog is None:
        prog = ctx._program if ctx._program is not None \
            else ctx._plan_geometry()
    nr = {d: int(opts.num_ranks[d]) for d in dims}
    lsizes = opts.rank_domain_sizes
    rad = ana.fused_step_radius()
    hK = {d: rad.get(d, 0) * K for d in dims}
    eb = int(np.dtype(prog.dtype).itemsize)
    reasons: List[dict] = []
    errors: List[str] = []

    kinds = mesh_axis_kinds(ctx._mesh, dims)
    dev_kind = ""
    try:
        devs = ctx._env.get_devices()
        if devs:
            dev_kind = getattr(devs[0], "device_kind", "") or ""
    except Exception:
        pass

    # ---- per-axis payload model (mirrors the executed schedule: the
    # steady-state exchange round the halo calibration times) ----------
    geoms = [g for g in prog.geoms.values() if not g.is_scratch]
    axes: Dict[str, dict] = {}
    for d in dims:
        if nr.get(d, 1) <= 1 or hK.get(d, 0) <= 0:
            continue
        items = 0
        nbytes = 0
        for g in geoms:
            if d not in g.domain_dims:
                continue
            if mode == "shard_pallas":
                # per-K-group refresh: written vars only, min(K, slots)
                # newest slots, uniform radius×K widths (the
                # single-definition exchange invariant)
                if not g.is_written:
                    continue
                moved = min(K, g.num_slots)
                wl = wr = hK[d]
            else:
                hl, hr = g.var.halo.get(d, (0, 0))
                if (hl, hr) == (0, 0):
                    continue
                moved = g.num_slots
                wl, wr = hl, hr
            cross = 1
            for i, (dn, kind) in enumerate(g.axes):
                if dn == d and kind == "domain":
                    continue
                cross *= (int(lsizes[dn]) if kind == "domain"
                          else int(g.shape[i]))
            items += moved
            nbytes += moved * (wl + wr) * cross * eb
        if items:
            link = link_model(dev_kind, kinds[d])
            secs = link_secs(nbytes, link)
            axes[d] = {"nranks": nr[d], "kind": kinds[d],
                       "items": items, "bytes": int(nbytes),
                       "gbps": link["gbps"],
                       "latency_us": link["latency_us"],
                       "secs": secs}
            reasons.append({"code": "comm_axis", "dim": d,
                            "kind": kinds[d], "items": items,
                            "bytes": int(nbytes),
                            "secs": round(secs, 9)})

    # ---- ordering -----------------------------------------------------
    auto_order = order_comm_axes(
        {d: {"kind": axes[d]["kind"], "secs": axes[d]["secs"]}
         for d in axes})
    setting_order = (getattr(opts, "comm_order", "") or "").strip()
    if setting_order:
        req = [s.strip() for s in setting_order.replace(";", ",")
               .split(",") if s.strip()]
        order: List[str] = []
        for dn in req:
            if dn not in axes:
                errors.append(
                    f"-comm_order names '{dn}' which is not an "
                    f"exchanged mesh axis (have {sorted(axes)})")
            elif dn in order:
                errors.append(f"-comm_order repeats '{dn}'")
            else:
                order.append(dn)
        missing = [d for d in auto_order if d not in order]
        if missing and not errors:
            reasons.append({
                "code": "comm_order_appended", "dims": list(missing),
                "cause": "-comm_order omitted exchanged axes; appended "
                         "in cost-model order"})
        order += missing
        cause = f"explicit -comm_order '{setting_order}'"
    else:
        order = auto_order
        cause = ("cost model: dcn axes first, then descending modeled "
                 "flight time")
    reasons.append({"code": "comm_order", "order": list(order),
                    "cause": cause})

    # ---- coalescing ---------------------------------------------------
    rounds_serial = sum(2 * axes[d]["items"] for d in order)
    rounds_coal = 2 * len(order)
    cset = str(getattr(opts, "coalesce", "auto")).lower()
    if cset in ("on", "true", "1"):
        coal, ccause = True, "coalesce=on (forced)"
    elif cset in ("off", "false", "0"):
        coal, ccause = False, "coalesce=off"
    elif cset == "auto":
        coal = rounds_serial > rounds_coal
        ccause = (f"auto: {rounds_serial} serial collectives per round "
                  f"vs {rounds_coal} coalesced" if coal else
                  "auto: no axis carries more than one slab — the "
                  "serial schedule already hits the collective floor")
    else:
        errors.append(f"-coalesce '{cset}' is not one of on|off|auto")
        coal, ccause = False, "invalid setting"
    rounds = rounds_coal if coal else rounds_serial
    reasons.append({
        "code": "comm_coalesce_engaged" if coal else "comm_coalesce_off",
        "cause": ccause, "rounds": rounds,
        "rounds_serial": rounds_serial})

    mesh_shape = {d: nr[d] for d in dims if nr.get(d, 1) > 1}
    return CommPlan(order=order, coalesce=coal, axes=axes,
                    reasons=reasons, errors=errors, rounds=rounds,
                    rounds_serial=rounds_serial, mesh_shape=mesh_shape,
                    K=K, mode=mode)


def comm_ledger_fields(ctx, plan: Optional[CommPlan] = None) -> Dict:
    """Flat per-row ledger fields for one context's comm schedule —
    mesh shape, per-axis exchange bytes and collective-round counts,
    so coalescing A/Bs are distinguishable in PERF_LEDGER.jsonl."""
    if plan is None:
        plan = ctx.comm_plan()
    fields = {
        "mesh": dict(plan.mesh_shape),
        "comm_order": list(plan.order),
        "coalesce": plan.coalesce,
        "comm_rounds": plan.rounds,
        "comm_rounds_serial": plan.rounds_serial,
        "comm_axis_kb": {d: round(a["bytes"] / 1e3, 2)
                         for d, a in plan.axes.items()},
        "comm_axis_kind": {d: a["kind"] for d, a in plan.axes.items()},
    }
    nperm = getattr(ctx, "_halo_nperm_last", 0)
    if nperm:
        # measured (traced) collectives per exchange round, when halo
        # calibration ran — the ground truth next to the model
        fields["comm_rounds_measured"] = int(nperm)
    return fields
