"""Explicit sharded stepping: ``shard_map`` + ``lax.ppermute`` ghost exchange.

The structural twin of the reference's MPI halo-exchange machinery
(``src/kernel/lib/halo.cpp``): per-var, per-dim edge slabs are sent to
neighbor shards before each stage that reads them — but expressed as XLA
collective-permutes over ICI inside a ``shard_map``, so the compiler's
latency-hiding scheduler overlaps them with compute (replacing the
reference's interior/exterior split + ``MPI_Test`` progress pump,
``context.cpp:377-478``, ``halo.cpp:494``).

Design notes mapping to the reference:

* *dirty tracking* (``yk_var.hpp:564``): statically resolved — the exchange
  set per stage comes from ``StepProgram.stage_reads`` (which vars are read
  with nonzero offsets), so only stale ghosts are exchanged, and each ring
  slot is exchanged exactly once per step (older slots were refreshed when
  they were newest).
* *shm/device-direct paths* (``halo.cpp:33-66``): collapsed — ICI is the
  only transport, and XLA picks the best implementation.
* *non-periodic boundaries*: ``ppermute`` members that receive nothing get
  zeros, matching this runtime's zero-filled physical-boundary ghosts.
"""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

import numpy as np

from yask_tpu.cache import aot_compile
from yask_tpu.utils.exceptions import YaskException


def _shard_map_fn():
    import jax
    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map
    return shard_map


class _TraceStats:
    """Trace-time collective counter: every ppermute the exchange paths
    issue bumps ``nperm`` while the program is being traced/lowered.
    The run paths read the delta around lowering the exchange-only
    calibration twin, so ``halo-cal`` reports the collective count of
    the schedule that actually compiled (model-free) — the number the
    coalescing A/B exists to move."""

    def __init__(self):
        self.nperm = 0


_trace_stats = _TraceStats()


def exchange_ghosts(arr, geom, dim_widths: Dict[str, Tuple[int, int]],
                    nr, local_sizes):
    """Fill ``arr``'s ghost pads from neighbor shards for the given dims.

    ``arr`` is a locally-padded shard array; for each dim with width (l, r):
    my right-interior edge slab -> right neighbor's left ghost, and vice
    versa (the pack/send/unpack cycle of ``exchange_halos``, ``halo.cpp:146``
    collapsed into two ppermutes per dim).
    """
    from jax import lax
    for d, (l, r) in dim_widths.items():
        n = nr.get(d, 1)
        if n <= 1 or d not in geom.domain_dims:
            continue
        ax = geom.axis_of(d)
        o = geom.origin[d]
        sz = local_sizes[d]
        if l > 0:
            slab = lax.slice_in_dim(arr, o + sz - l, o + sz, axis=ax)
            _trace_stats.nperm += 1
            recv = lax.ppermute(slab, d, [(i, i + 1) for i in range(n - 1)])
            arr = lax.dynamic_update_slice_in_dim(arr, recv, o - l, axis=ax)
        if r > 0:
            slab = lax.slice_in_dim(arr, o, o + r, axis=ax)
            _trace_stats.nperm += 1
            recv = lax.ppermute(slab, d, [(i + 1, i) for i in range(n - 1)])
            arr = lax.dynamic_update_slice_in_dim(arr, recv, o + sz, axis=ax)
    return arr


def _exchange_coalesced(items, nr, local_sizes, order):
    """Coalesced ghost exchange: ONE ppermute per (mesh axis, direction)
    carrying every buffer's slab, flattened and concatenated (grouped by
    dtype), then split/reshaped back into each buffer's ghost band.

    ``ppermute`` only moves bytes, so the result is bit-identical to
    per-buffer collectives; axes still go strictly in plan order, so the
    corner composition (a later axis's slab spans the earlier axes'
    freshly filled ghosts) is preserved — diagonal ghosts keep arriving
    without dedicated collectives.
    """
    import jax.numpy as jnp
    from jax import lax
    arrs = [a for a, _g, _w in items]
    metas = [(g, w) for _a, g, w in items]
    for d in order:
        n = nr.get(d, 1)
        if n <= 1:
            continue
        sz = local_sizes[d]
        for left in (True, False):
            perm = ([(i, i + 1) for i in range(n - 1)] if left
                    else [(i + 1, i) for i in range(n - 1)])
            # dtype -> (flattened slabs, (item idx, axis, write pos,
            # slab shape, element count))
            groups: Dict[str, Tuple[list, list]] = {}
            for i, (g, w) in enumerate(metas):
                if d not in w or d not in g.domain_dims:
                    continue
                wl, wr = w[d]
                width = wl if left else wr
                if width <= 0:
                    continue
                ax = g.axis_of(d)
                o = g.origin[d]
                lo = (o + sz - width) if left else o
                slab = lax.slice_in_dim(arrs[i], lo, lo + width, axis=ax)
                wr_at = (o - width) if left else (o + sz)
                slabs, meta = groups.setdefault(str(slab.dtype),
                                                ([], []))
                slabs.append(slab)
                meta.append((i, ax, wr_at, slab.shape,
                             int(np.prod(slab.shape))))
            for slabs, meta in groups.values():
                if len(slabs) == 1:
                    # single payload: nothing to pack
                    i, ax, wr_at, _shp, _n = meta[0]
                    _trace_stats.nperm += 1
                    recv = lax.ppermute(slabs[0], d, perm)
                    arrs[i] = lax.dynamic_update_slice_in_dim(
                        arrs[i], recv, wr_at, axis=ax)
                    continue
                payload = jnp.concatenate(
                    [jnp.reshape(s, (-1,)) for s in slabs])
                _trace_stats.nperm += 1
                recv = lax.ppermute(payload, d, perm)
                off = 0
                for i, ax, wr_at, shp, nel in meta:
                    part = jnp.reshape(
                        lax.slice_in_dim(recv, off, off + nel, axis=0),
                        shp)
                    off += nel
                    arrs[i] = lax.dynamic_update_slice_in_dim(
                        arrs[i], part, wr_at, axis=ax)
    return arrs


def exchange_many(items, nr, local_sizes, plan=None,
                  exchange=exchange_ghosts):
    """The one multi-buffer exchange entry both shard paths trace.

    ``items`` is a list of ``(padded array, geom, dim_widths)``; returns
    the exchanged arrays in the same order.  The CommPlan decides the
    schedule: without one (or with coalescing off, or when ``exchange``
    is a calibration stand-in like ``_no_exchange``) each buffer runs
    the serial per-buffer ``exchange`` with its width dims reordered to
    the plan; with coalescing on, all slabs for one (axis, direction)
    ride a single concatenated ppermute (``_exchange_coalesced``).
    Either way axes go in plan order, so corner ghosts stay composed
    exchanges and both schedules are bit-identical.
    """
    if not items:
        return []
    order = list(plan.order) if plan is not None else []
    seen = set(order)
    for _a, _g, w in items:
        for d in w:
            if d not in seen:
                order.append(d)
                seen.add(d)
    if plan is None or not plan.coalesce \
            or exchange is not exchange_ghosts:
        out = []
        for a, g, w in items:
            ww = {d: w[d] for d in order if d in w}
            out.append(exchange(a, g, ww, nr, local_sizes))
        return out
    return _exchange_coalesced(items, nr, local_sizes, order)


def _widen(applied: Dict, key, widths: Dict[str, Tuple[int, int]]):
    """Track the union of exchanged ghost widths per buffer: returns
    (union, grew) where ``grew`` means this refresh must actually run —
    a later stage reading the same buffer with WIDER ghosts re-exchanges
    the union, not the narrow refresh. Shared by both shard paths'
    refresh hooks so the tracking cannot drift."""
    out = dict(applied.get(key, {}))
    grew = key not in applied
    for d, (l, r) in widths.items():
        al, ar = out.get(d, (0, 0))
        if l > al or r > ar:
            grew = True
        out[d] = (max(al, l), max(ar, r))
    return out, grew


def _no_exchange(arr, geom, dim_widths, nr, local_sizes):
    """Exchange stand-in for halo-time calibration: the compiled twin with
    this in place of ``exchange_ghosts`` differs from the real program
    only by the collectives, so (t_real − t_twin)/t_real is the measured
    halo fraction (the reference's halo-time breakdown,
    ``context.hpp:318-328``, recast for fused XLA programs)."""
    return arr


def overlap_decision(ctx, K: int, local_prog=None):
    """Shared engage/reject decision for the overlapped shard_pallas
    exchange schedule (the core/shell split of the fused K-group).

    Returns ``(engage, core, shells, reasons)`` where ``core`` is the
    region dict ``{dim: (lo, hi)}`` for the core chunk, ``shells`` is a
    list of ``(dim, lo, hi)`` face slabs, and ``reasons`` carries the
    structured engage/reject codes the explain pass surfaces.  Pure
    geometry, never raises for infeasibility (``_prep_shard_pallas``
    raises only when the setting forces ``"on"``); the static checker's
    OVERLAP rule calls this same function so the two can never drift.

    Eligibility: setting not ``"off"``, at least one mesh-decomposed
    leading dim with a nonzero fused ghost width ``hK = radius×K``, the
    minor (lane) dim unsharded (lane-axis windows cannot restrict), and
    per sharded dim an aligned core span — ``lo = align_up(hK)``,
    ``hi = align_down(lsize − hK)`` with the sublane tile as the unit
    when the dim is some var's sublane axis (output DMA offsets must
    stay 8-aligned on real Mosaic) — of at least one alignment unit.
    The auto gate therefore engages exactly when every sharded dim's
    rank domain admits a core shrunk by ≥ hK per face (≈ 2·hK total).
    """
    opts = ctx._opts
    ana = ctx._ana
    dims = ana.domain_dims
    minor = dims[-1]
    nr = {d: opts.num_ranks[d] for d in dims}
    lsizes = opts.rank_domain_sizes
    # the core/shell shrink margin comes off THE TilePlan (the single
    # margin-math source for the fused pallas path); the minor (lane)
    # dim is never a tiled lead dim, so its ghost width stays the raw
    # fused halo for the extra-pad map below
    from yask_tpu.ops.tile_planner import TilePlan
    tplan = TilePlan(ctx._program, K)
    rad = ana.fused_step_radius()
    hK = {d: tplan.halo(d) for d in tplan.lead}
    hK[tplan.minor] = rad.get(tplan.minor, 0) * K
    setting = getattr(opts, "overlap_exchange", "auto")
    reasons: List[dict] = []

    if setting == "off":
        reasons.append({"code": "overlap_disabled",
                        "cause": "overlap_exchange=off"})
        return False, None, None, reasons
    if K < 2:
        # a K=1 group is one fused step: there is no core compute
        # window left to hide the exchange under, so the split buys
        # nothing (and single-step groups run whole on post-exchange
        # state inside the overlapped schedule — see ov_group)
        reasons.append({
            "code": ("overlap_infeasible" if setting == "on"
                     else "overlap_ineligible"),
            "cause": "wf_steps=1: a single-step group leaves no core "
                     "compute to overlap the exchange with"})
        return False, None, None, reasons
    if nr.get(minor, 1) > 1:
        reasons.append({"code": "overlap_ineligible",
                        "cause": f"minor dim '{minor}' is sharded "
                                 "(lane-axis windows cannot restrict)"})
        return False, None, None, reasons
    sharded = [d for d in dims[:-1] if nr.get(d, 1) > 1 and hK[d] > 0]
    if not sharded:
        reasons.append({"code": "overlap_ineligible",
                        "cause": "no sharded leading dim with a "
                                 "nonzero fused ghost width"})
        return False, None, None, reasons

    if local_prog is None:
        local_prog = ctx._csol.plan(
            lsizes, global_sizes=opts.global_domain_sizes,
            extra_pad={d: (hK[d], hK[d]) for d in dims})
    # Dims that are some var's sublane axis: split boundaries there
    # must ride the sublane tile (same rule build_pallas_chunk enforces
    # statically for its output DMA windows).
    from yask_tpu.compiler.lowering import tpu_tile_dims
    sub_t, _lane_t = tpu_tile_dims(local_prog.dtype)
    sub_dims = set()
    for g in local_prog.geoms.values():
        if g.is_scratch or len(g.axes) < 2:
            continue
        dn, kind = g.axes[-2]
        if kind == "domain" and dn != minor:
            sub_dims.add(dn)

    core: Dict[str, Tuple[int, int]] = {}
    shells: List[Tuple[str, int, int]] = []
    for d in sharded:
        q = sub_t if d in sub_dims else 1
        lo = -(-hK[d] // q) * q
        hi = ((lsizes[d] - hK[d]) // q) * q
        if hi - lo < q:
            reasons.append({
                "code": ("overlap_infeasible" if setting == "on"
                         else "overlap_ineligible"),
                "cause": f"dim '{d}': aligned core span [{lo},{hi}) is "
                         f"empty — rank domain {lsizes[d]} cannot "
                         f"cover 2×hK={2 * hK[d]} plus alignment "
                         f"(unit {q})", "dim": d})
            return False, None, None, reasons
        core[d] = (lo, hi)
        shells.append((d, 0, lo))
        shells.append((d, hi, lsizes[d]))
    reasons.append({"code": "overlap_engaged",
                    "core": {d: list(core[d]) for d in sorted(core)},
                    "hK": {d: hK[d] for d in sorted(core)}})
    return True, core, shells, reasons


def _make_overlap_step(prog, nr, lsizes, plan=None,
                       exchange=exchange_ghosts):
    """Interior/exterior-split step: the reference's compute/communication
    overlap (``run_solution`` exterior-then-interior structure,
    ``context.cpp:377-478``, ``MpiSection`` flags ``context.hpp:789-833``)
    recast for XLA's scheduler.

    Per stage: the *core* region (interior shrunk by the stage's ghost
    widths in sharded dims) is evaluated against the **pre-exchange**
    arrays — its data dependencies exclude the ppermutes, so XLA is free
    to run the collectives concurrently with core compute. The boundary
    *shell* slabs are then evaluated against the exchanged arrays.
    Overlapping shell corners recompute identical values (idempotent).
    """
    ana = prog.ana
    dims = ana.domain_dims
    stage_writes = []
    for stage in ana.stages:
        ws = []
        for part in stage.parts:
            if not part.is_scratch:
                for eq in part.eqs:
                    if eq.lhs.var_name() not in ws:
                        ws.append(eq.lhs.var_name())
        stage_writes.append(ws)

    def one_step(st, t):
        computed: Dict[str, object] = {}
        computed_post: Dict[str, object] = {}
        state_post = dict(st)
        # widths already exchanged per buffer — a later stage reading the
        # same var with *wider* ghosts must re-exchange the union, not
        # reuse the narrow refresh
        ring_w: Dict[str, Dict[str, Tuple[int, int]]] = {}
        post_w: Dict[str, Dict[str, Tuple[int, int]]] = {}

        for si in range(len(ana.stages)):
            reads = prog.stage_reads[si]
            split = prog.stage_reads_split[si]
            # refresh ghosts (post versions) for this stage's inputs —
            # BOTH buffers a read can hit: the computed (this-step)
            # array of an earlier stage, and the newest ring slot for
            # previous-step reads (a var can need both; refreshing only
            # computed would rotate stale ghosts into the next step)
            # ... batched through exchange_many so a coalescing
            # CommPlan packs this stage's refreshes into one ppermute
            # per (axis, direction)
            items, tags = [], []
            for vname, widths in split["computed"].items():
                g = prog.geoms[vname]
                if not any(nr.get(d, 1) > 1 for d in widths):
                    continue
                if vname in computed:
                    union, grew = _widen(post_w, vname, widths)
                    if vname not in computed_post or grew:
                        items.append((computed[vname], g, union))
                        tags.append(("c", vname, union))
            for vname, widths in split["ring"].items():
                g = prog.geoms[vname]
                if not any(nr.get(d, 1) > 1 for d in widths):
                    continue
                if g.is_written and g.has_step:
                    union, grew = _widen(ring_w, vname, widths)
                    if grew:
                        items.append((state_post[vname][-1], g, union))
                        tags.append(("s", vname, union))
            if items:
                new = exchange_many(items, nr, lsizes, plan, exchange)
                for (kind, vname, union), a in zip(tags, new):
                    if kind == "c":
                        computed_post[vname] = a
                        post_w[vname] = union
                    else:
                        ring = list(state_post[vname])
                        ring[-1] = a
                        state_post[vname] = ring
                        ring_w[vname] = union

            # stage ghost widths in sharded dims
            act: Dict[str, Tuple[int, int]] = {}
            for vname, widths in reads.items():
                for d, (l, r) in widths.items():
                    if nr.get(d, 1) > 1:
                        cl, cr = act.get(d, (0, 0))
                        act[d] = (max(cl, l), max(cr, r))
            splittable = act and all(
                lsizes[d] - l - r > 0 for d, (l, r) in act.items())

            post_env = {**computed, **computed_post}
            if not splittable:
                tmp = dict(post_env)
                prog.eval_stage(si, t, state_post, tmp, {})
                for name in stage_writes[si]:
                    computed[name] = tmp[name]
                    # an exchanged snapshot of an older value is now stale
                    computed_post.pop(name, None)
                    post_w.pop(name, None)
                continue

            # core with PRE-exchange arrays
            core = {d: (act.get(d, (0, 0))[0],
                        lsizes[d] - act.get(d, (0, 0))[1]) for d in dims}
            tmp_core = dict(computed)
            prog.eval_stage(si, t, st, tmp_core, {}, over=core)

            # shells with POST-exchange arrays, accumulating on core output
            tmp = dict(post_env)
            for name in stage_writes[si]:
                tmp[name] = tmp_core[name]
            interior = {d: (0, lsizes[d]) for d in dims}
            for d, (l, r) in act.items():
                for a, b in ((0, l), (lsizes[d] - r, lsizes[d])):
                    if b <= a:
                        continue
                    over = dict(interior)
                    over[d] = (a, b)
                    prog.eval_stage(si, t, state_post, tmp, {}, over=over)
            for name in stage_writes[si]:
                computed[name] = tmp[name]
                computed_post.pop(name, None)
                post_w.pop(name, None)

        # ring rotation (mirrors StepProgram.step), carrying exchanged rings
        new_state: Dict[str, List] = {}
        for name, ring in state_post.items():
            g = prog.geoms[name]
            if name in computed:
                if g.has_step:
                    new_state[name] = list(ring[1:]) + [computed[name]]
                else:
                    new_state[name] = [computed[name]]
            else:
                new_state[name] = list(ring)
        return new_state

    return one_step


def _make_specs_for(local_prog, nr):
    """PartitionSpec builder: domain axes with >1 rank follow the mesh."""
    from jax.sharding import PartitionSpec

    def specs_for(name):
        g = local_prog.geoms[name]
        spec = []
        for dn, kind in g.axes:
            spec.append(dn if (kind == "domain" and nr.get(dn, 1) > 1)
                        else None)
        return PartitionSpec(*spec)
    return specs_for


def _strip_global_interiors(ctx, gprog, names, mesh, specs_for, gsizes):
    """Global padded state → sharded interior blocks. Pads are
    identically zero (framework invariant), so stripping and
    re-attaching are pure device ops — no host round trip.

    If a previous shard-mode run left its interiors device-resident,
    they are handed over directly — repeated short runs then skip the
    per-call strip entirely (VERDICT r1 item 9). ``ctx._resident`` is
    NOT cleared here: the caller clears it immediately before the
    (buffer-donating) program call, so a failure in between leaves the
    state recoverable."""
    import jax
    from jax.sharding import NamedSharding
    if ctx._resident is not None:
        return ctx._resident
    interior = {}
    for k in names:
        g = gprog.geoms[k]
        idxs = []
        for dn, kind in g.axes:
            if kind == "domain":
                idxs.append(slice(g.origin[dn], g.origin[dn] + gsizes[dn]))
            else:
                idxs.append(slice(None))
        sh = NamedSharding(mesh, specs_for(k))
        interior[k] = [jax.device_put(a[tuple(idxs)], sh)
                       for a in ctx._state[k]]
    return interior


def _is_outlier(samples):
    """Is the extreme sample an outlier?  The near distance (the
    spread of the agreeing pair, floored at 2% of the median so two
    near-identical samples don't declare everything an outlier)
    sets the scale; an extreme beyond 3× it is rejected."""
    lo, med, hi = samples[0], samples[len(samples) // 2], samples[-1]
    if med <= 0:
        return False
    d_lo, d_hi = med - lo, hi - med
    base = max(min(d_lo, d_hi), 0.02 * med)
    return max(d_lo, d_hi) > 3.0 * base


def timed_median(sample, trials=3):
    """Median of ≥3 independent trials of the zero-arg ``sample``
    timer + their relative spread ((max−min)/median) + an instability
    flag + the total rep count.  The halo fraction is a (real − twin)
    subtraction of two short samples, so a single outlier trial (GC
    pause, co-tenant burst) lands directly in the reported fraction;
    the median rejects it, and an extreme beyond 3× the agreeing
    pair's spread triggers ONE full re-time.  A re-time that is still
    wild gets one LAST scaled round (2·trials+1 samples — short runs
    are exactly where per-trial jitter dominates, and a wider sample
    often settles the median) before the calibration is marked
    unstable (``halo_cal_unstable`` on the ledger row) instead of
    banking a noisy split as evidence.  The rep count is recorded so
    the ledger row says how hard the number was to obtain.

    Every rep is recorded as a ``halo_cal.rep`` span (phase
    ``exchange``) and each round's verdict as a ``halo_cal.round``
    span carrying the spread/outlier attrs — a noisy split is visible
    in the obs_report timeline, not only in ledger rows."""
    from yask_tpu.obs.tracer import span

    def one(rnd, i):
        with span("halo_cal.rep", phase="exchange", round=rnd,
                  rep=i) as sp:
            v = sample()
            sp.set(secs=v)
        return v

    def rnd(idx, n):
        with span("halo_cal.round", phase="exchange", round=idx,
                  trials=n) as sp:
            s = sorted(one(idx, i) for i in range(n))
            med = s[len(s) // 2]
            sp.set(median=med, outlier=_is_outlier(s),
                   spread=((s[-1] - s[0]) / med) if med > 0 else 0.0)
        return s

    samples = rnd(0, trials)
    reps = trials
    unstable = False
    if _is_outlier(samples):
        samples = rnd(1, trials)
        reps += trials
        if _is_outlier(samples):
            n = 2 * trials + 1
            samples = rnd(2, n)
            reps += n
            unstable = _is_outlier(samples)
    med = samples[len(samples) // 2]
    spread = (samples[-1] - samples[0]) / med if med > 0 else 0.0
    return med, spread, unstable, reps


def _calibrate_halo_frac(ctx, key, fn, fn_no, interior, start,
                         fn_xonly=None, fn_pack=None):
    """Measured halo breakdown for one compiled variant (reference
    per-phase halo timers, ``context.hpp:318-328``, recast for fused XLA
    programs). Three calibration points, cached under ``key``:

    * halo fraction — time the real program against its no-exchange
      twin; the shortfall is the per-call halo cost INCLUDING overlap
      effects (what the program actually pays);
    * exchange round — time one full-state ghost exchange alone; the
      bare collective cost. halo_cost − rounds×this is the overlap
      shortfall (scheduling/serialization the collectives induce);
    * pack round — the exchange-only program with collectives elided
      (pad + strip only): the slab-pack share of the round.  round −
      pack ≈ collective wait, the reference's wait-timer analog."""
    import jax
    import jax.numpy as jnp

    # Real hardware needs a longer sample: sub-ms dispatches against a
    # ~0.05 s window made the fraction noise-prone (the (real − twin)
    # subtraction amplifies jitter), and calibration runs once per
    # variant so the extra cost is bounded.
    on_hw = ctx._env.get_platform() == "tpu"
    min_secs = 0.25 if on_hw else 0.05
    max_calls = 64 if on_hw else 8
    min_calls = 4 if on_hw else 2

    def timed(f):
        st = {k: [jnp.copy(a) for a in ring]
              for k, ring in interior.items()}
        t = jnp.asarray(start, dtype=jnp.int32)
        st = f(st, t)           # warmup (compile + first dispatch)
        jax.block_until_ready(st)
        # Repeat until the sample is long enough to be stable.  The
        # call cap auto-scales: a sub-ms dispatch used to exhaust
        # max_calls with the window still far below min_secs, and the
        # (real − twin) subtraction then banked pure jitter — so when
        # the cap is hit short, extend it by the measured per-call
        # rate (bounded, so a hung dispatch can't loop forever).
        calls = 0
        cap = max_calls
        t0 = time.perf_counter()
        while calls < cap:
            st = f(st, t)
            jax.block_until_ready(st)
            calls += 1
            el = time.perf_counter() - t0
            if el >= min_secs and calls >= min_calls:
                break
            if calls == cap and el < min_secs and cap < 1024:
                per = el / calls
                cap = min(1024, calls
                          + int((min_secs - el) / max(per, 1e-9)) + 1)
        return (time.perf_counter() - t0) / calls

    from yask_tpu.obs.tracer import span
    with span("halo_cal", phase="exchange", key=repr(key)) as _cal_sp:
        t_no, sp_no, un_no, rp_no = timed_median(lambda: timed(fn_no))
        t_ex, sp_ex, un_ex, rp_ex = timed_median(lambda: timed(fn))
        unstable = bool(un_no or un_ex)
        _cal_sp.set(unstable=unstable,
                    spread=max(sp_no, sp_ex), reps=rp_no + rp_ex,
                    frac=(max(0.0, 1.0 - t_no / t_ex)
                          if not unstable and t_ex > 0 else None))
    if unstable:
        # Twice-unstable twin: the (real − twin) subtraction is noise,
        # not a halo datum.  Bank NO split (halo_time reports null and
        # the halo timer stays untouched) instead of a noise-derived
        # fraction — total step time is still real evidence.
        ctx._halo_frac[key] = None
    else:
        ctx._halo_frac[key] = max(0.0, 1.0 - t_no / t_ex) \
            if t_ex > 0 else 0.0
    ctx._halo_cal_spread[key] = max(sp_no, sp_ex)
    ctx._halo_cal_unstable[key] = unstable
    ctx._halo_cal_reps[key] = rp_no + rp_ex
    ctx._halo_tcall[key] = t_ex
    if fn_xonly is not None:
        ctx._halo_xround[key] = timed(fn_xonly)
    if fn_pack is not None:
        ctx._halo_xpack[key] = timed(fn_pack)
    return ctx._halo_frac[key]


def _build_exchange_only(ctx, names, specs_for, slots, nr, lsizes,
                         gsizes, width_scale: int = 1,
                         written_only: bool = False, extra_pad=None,
                         uniform_widths=None, exchange=exchange_ghosts,
                         plan=None):
    """One ghost-exchange round compiled alone: pad, exchange at halo
    widths × ``width_scale``, strip — no compute. The second halo
    calibration point (bare collective cost). ``width_scale``/
    ``written_only`` mirror the shard_pallas per-K-group exchange
    (radius×K ghosts, only the freshly produced slots move); shard_map
    uses the defaults (per-step halo-width refresh of every buffer).
    ``exchange=_no_exchange`` builds the PACK-ONLY twin (pad + strip,
    no collectives): timing it against the full round splits the bare
    exchange cost into slab-pack vs collective-wait — the distinction
    the reference's per-phase MPI timers exist to make
    (``context.hpp:318-328``)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec
    shard_map = _shard_map_fn()
    mesh = ctx._mesh
    ana = ctx._csol.ana
    in_specs = ({k: [specs_for(k)] * slots[k] for k in names},
                PartitionSpec())
    out_specs = {k: [specs_for(k)] * slots[k] for k in names}

    def body(interior_state, t0):
        offs = {d: lax.axis_index(d) * lsizes[d] if nr[d] > 1 else 0
                for d in ana.domain_dims}
        prog = ctx._csol.plan(lsizes, global_sizes=gsizes,
                              rank_offset=offs,
                              extra_pad=extra_pad or {},
                              mosaic_align=False)
        padded, post, items, locs = {}, {}, [], []
        for k in names:
            g = prog.geoms[k]
            if written_only and not g.is_written:
                continue
            pads, strip = [], []
            for dn, kind in g.axes:
                if kind == "domain":
                    pads.append(g.pads[dn])
                    strip.append(slice(g.origin[dn],
                                       g.origin[dn] + lsizes[dn]))
                else:
                    pads.append((0, 0))
                    strip.append(slice(None))
            widths = {}
            for d in g.domain_dims:
                if uniform_widths is not None:
                    # shard_pallas exchanges fused_step_radius×K slabs
                    # uniformly (the single-definition invariant) — the
                    # twin must move the same payload
                    hl, hr = uniform_widths.get(d, (0, 0))
                else:
                    hl, hr = g.var.halo.get(d, (0, 0))
                    hl, hr = hl * width_scale, hr * width_scale
                # pads bound what a round can move
                pl_, pr_ = g.pads[d]
                hl, hr = min(hl, pl_), min(hr, pr_)
                if (hl, hr) != (0, 0):
                    widths[d] = (hl, hr)
            moved = len(interior_state[k]) if not written_only \
                else min(max(width_scale, 1), len(interior_state[k]))
            ring = [jnp.pad(a, pads) if pads else a
                    for a in interior_state[k]]
            padded[k] = (ring, pads, strip)
            if widths:
                for si in range(len(ring) - moved, len(ring)):
                    items.append((ring[si], g, widths))
                    locs.append((k, si))
        # one batched exchange across every moved slot: under a
        # coalescing CommPlan the round's collective count is what the
        # real schedule pays (the twin must mirror it exactly)
        for (k, si), a in zip(locs,
                              exchange_many(items, nr, lsizes, plan,
                                            exchange)):
            padded[k][0][si] = a
        out = {}
        for k in names:
            if k not in padded:
                out[k] = list(interior_state[k])
                continue
            ring, pads, strip = padded[k]
            out[k] = [p[tuple(strip)] if pads else p for p in ring]
        return out

    try:
        mapped = shard_map(body, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_vma=False)
    except TypeError:  # older jax spells it check_rep
        mapped = shard_map(body, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False)
    return jax.jit(mapped, donate_argnums=0)


def _repad_global(gprog, names, out):
    """Re-attach the (zero) global pads on device."""
    import jax.numpy as jnp
    new_state = {}
    for k in names:
        g = gprog.geoms[k]
        pads = []
        for dn, kind in g.axes:
            pads.append(g.pads[dn] if kind == "domain" else (0, 0))
        ring = []
        for res in out[k]:
            ring.append(jnp.pad(res, pads) if pads else res)
        new_state[k] = ring
    return new_state


def run_shard_map(ctx, start: int, n: int) -> None:
    """Advance ``n`` steps in explicit shard_map mode, updating
    ``ctx._state`` (global padded arrays) in place."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec

    opts = ctx._opts
    ana = ctx._ana
    mesh = ctx._mesh
    nr = {d: opts.num_ranks[d] for d in ana.domain_dims}
    gsizes = opts.global_domain_sizes
    lsizes = opts.rank_domain_sizes
    dirn = ana.step_dir

    # Static local geometry (pads = halos); the traced twin inside the body
    # only differs in rank offsets.
    # XLA-only per-shard geometry: no Mosaic alignment (see VarGeom)
    local_prog = ctx._csol.plan(lsizes, global_sizes=gsizes,
                                mosaic_align=False)
    gprog = ctx._program

    src_state = ctx._resident if ctx._resident is not None else ctx._state
    names = list(src_state.keys())
    slots = {k: len(src_state[k]) for k in names}
    specs_for = _make_specs_for(local_prog, nr)

    # The CommPlan (axis order + coalescing) is baked into the traced
    # exchange bodies, so it joins overlap_comms in the cache key —
    # toggling either between equal-length runs must never reuse the
    # other schedule's compiled body.
    plan = ctx.comm_plan()
    if plan.errors:
        raise YaskException("communication plan invalid: "
                            + "; ".join(plan.errors))
    key = ("shard_map", n, opts.overlap_comms) + plan.key()

    def build(exchange):
        shard_map = _shard_map_fn()

        in_specs = ({k: [specs_for(k)] * slots[k] for k in names},
                    PartitionSpec())
        out_specs = {k: [specs_for(k)] * slots[k] for k in names}

        def body(interior_state, t0):
            # Per-shard program with traced rank offsets.
            offs = {d: lax.axis_index(d) * lsizes[d] if nr[d] > 1 else 0
                    for d in ana.domain_dims}
            prog = ctx._csol.plan(lsizes, global_sizes=gsizes,
                                  rank_offset=offs, mosaic_align=False)

            # 1) pad local blocks (ghost + physical-boundary zeros).
            state = {}
            for k in names:
                g = prog.geoms[k]
                pads = []
                for dn, kind in g.axes:
                    if kind == "domain":
                        pads.append(g.pads[dn])
                    else:
                        pads.append((0, 0))
                state[k] = [jnp.pad(a, pads) if pads else a
                            for a in interior_state[k]]

            # 2) pre-exchange every slot once so older ring slots carry
            #    valid ghosts (steady-state invariant: only the newest slot
            #    is stale afterwards) — batched, so a coalescing CommPlan
            #    packs all slabs per (axis, direction) into one ppermute.
            items, locs = [], []
            for k in names:
                g = prog.geoms[k]
                widths = {d: g.var.halo.get(d, (0, 0))
                          for d in g.domain_dims}
                widths = {d: w for d, w in widths.items() if w != (0, 0)}
                if widths:
                    for si, a in enumerate(state[k]):
                        items.append((a, g, widths))
                        locs.append((k, si))
            for (k, si), a in zip(locs,
                                  exchange_many(items, nr, lsizes,
                                                plan, exchange)):
                state[k][si] = a

            # 3) scan steps; before each stage refresh stale ghosts only.
            def one_step_plain(st, t):
                # widths already applied per buffer: a later stage with
                # wider ghost reads re-exchanges the union
                applied = {}

                def hook(si, state_, computed):
                    # refresh BOTH buffers a stage's reads can hit (see
                    # stage_read_widths_split: refreshing only the
                    # computed array would leave previous-step ring
                    # reads of the same var with stale shard ghosts) —
                    # batched through exchange_many so the stage's
                    # refreshes share collectives under a coalescing
                    # CommPlan
                    split = prog.stage_reads_split[si]
                    items, tags = [], []
                    for vname, widths in split["computed"].items():
                        if vname not in computed:
                            continue
                        g2 = prog.geoms[vname]
                        u, grew = _widen(applied, (vname, "c"), widths)
                        if grew:
                            items.append((computed[vname], g2, u))
                            tags.append(("c", vname, u))
                    for vname, widths in split["ring"].items():
                        g2 = prog.geoms[vname]
                        if not (g2.is_written and g2.has_step):
                            continue
                        u, grew = _widen(applied, (vname, "s"), widths)
                        if grew:
                            items.append((state_[vname][-1], g2, u))
                            tags.append(("s", vname, u))
                    if items:
                        new = exchange_many(items, nr, lsizes, plan,
                                            exchange)
                        for (kind, vname, u), a in zip(tags, new):
                            if kind == "c":
                                computed = {**computed, vname: a}
                                applied[(vname, "c")] = u
                            else:
                                ring = list(state_[vname])
                                ring[-1] = a
                                state_ = {**state_, vname: ring}
                                applied[(vname, "s")] = u
                    return state_, computed

                return prog.step(st, t, halo_hook=hook)

            one_step_ov = _make_overlap_step(prog, nr, lsizes,
                                             plan=plan,
                                             exchange=exchange)
            one_step = one_step_ov if ctx._opts.overlap_comms \
                else one_step_plain

            def scan_body(carry, _):
                st, t = carry
                return (one_step(st, t), t + dirn), None

            (state, _), _ = lax.scan(scan_body, (state, t0), None, length=n)

            # 4) strip pads.
            out = {}
            for k in names:
                g = prog.geoms[k]
                idxs = []
                for dn, kind in g.axes:
                    if kind == "domain":
                        idxs.append(slice(g.origin[dn],
                                          g.origin[dn] + lsizes[dn]))
                    else:
                        idxs.append(slice(None))
                out[k] = [a[tuple(idxs)] for a in state[k]]
            return out

        try:
            mapped = shard_map(body, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_vma=False)
        except TypeError:  # older jax spells it check_rep
            mapped = shard_map(body, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_rep=False)
        return jax.jit(mapped, donate_argnums=0)

    if key not in ctx._jit_cache:
        t0c = time.perf_counter()
        ctx._jit_cache[key] = build(exchange_ghosts)
        ctx._compile_secs += time.perf_counter() - t0c
    fn = ctx._jit_cache[key]

    # Strip global pads → sharded interior blocks. Pads are identically
    # zero (framework invariant), so stripping and re-attaching are pure
    # device ops — no host round trip. (State is already on device:
    # run_solution's shard_map branch owns that placement.)
    # The run timer covers strip + program + re-pad (the per-call work
    # every mode pays); only halo calibration is excluded, like compile.
    t0r = time.perf_counter()
    interior = _strip_global_interiors(ctx, gprog, names, mesh,
                                       specs_for, gsizes)

    # Halo-time calibration (once per compiled variant): time the real
    # program against its no-exchange twin on copies of the interiors;
    # the shortfall is the halo cost this variant pays per call. With
    # -overlap_comms the fraction shrinks — the overlap payoff the
    # reference reports via its MPI wait timers (context.hpp:318-328).
    frac = 0.0
    cal_secs = 0.0
    if opts.measure_halo_time:
        t0cal = time.perf_counter()
        if key not in ctx._halo_frac:
            t0c = time.perf_counter()
            tj = jnp.asarray(start, dtype=jnp.int32)
            # unkeyed aot_compile: per-call shard shapes — ctx's own
            # memo (_halo_frac keyed per variant) is the right cache
            fn_no = aot_compile(build(_no_exchange), (interior, tj)).fn
            np0 = _trace_stats.nperm
            fn_x = aot_compile(_build_exchange_only(
                ctx, names, specs_for, slots, nr, lsizes,
                gsizes, plan=plan), (interior, tj)).fn
            # collectives per exchange round, counted off the trace of
            # the schedule that actually compiled
            ctx._halo_nperm[key] = _trace_stats.nperm - np0
            fn_p = aot_compile(_build_exchange_only(
                ctx, names, specs_for, slots, nr, lsizes,
                gsizes, exchange=_no_exchange), (interior, tj)).fn
            ctx._compile_secs += time.perf_counter() - t0c
            _calibrate_halo_frac(ctx, key, fn, fn_no, interior, start,
                                 fn_xonly=fn_x, fn_pack=fn_p)
            del fn_no, fn_x, fn_p
        frac = ctx._halo_frac[key] or 0.0  # None = unstable, no split
        ctx._halo_xround_last = ctx._halo_xround.get(key, 0.0)
        ctx._halo_xpack_last = ctx._halo_xpack.get(key, 0.0)
        ctx._halo_cal_spread_last = ctx._halo_cal_spread.get(key, 0.0)
        ctx._halo_cal_unstable_last = ctx._halo_cal_unstable.get(key, False)
        ctx._halo_cal_reps_last = ctx._halo_cal_reps.get(key, 0)
        ctx._halo_nperm_last = ctx._halo_nperm.get(key, 0)
        ctx._halo_overlap_eff_last = 0.0   # shard_pallas-only metric
        cal_secs = time.perf_counter() - t0cal

    t0c2 = time.perf_counter()
    t0c2_wall = time.time()
    ctx._resident = None   # interior buffers are donated next; any
    #                          failure before this point kept them valid
    out = fn(interior, jnp.asarray(start, dtype=jnp.int32))
    jax.block_until_ready(out)
    dt_call = time.perf_counter() - t0c2

    # Keep the interiors device-resident: the next shard-mode run takes
    # them directly, and any host access materializes (re-pads) lazily.
    ctx._resident = out
    ctx._state = None

    # Elapsed = strip + program + re-pad, minus the one-off calibration;
    # the halo fraction applies to the program window it was measured on.
    ctx._run_timer._elapsed += time.perf_counter() - t0r - cal_secs
    ctx._halo_timer._elapsed += frac * dt_call
    ctx._halo_frac_last = frac
    if frac > 0:
        from yask_tpu.obs.tracer import record_span
        # retroactive span: the calibrated exchange share of THIS
        # program call (CommPlan execution is inside the jitted scan —
        # this estimate is the only runtime exchange datum available)
        record_span("halo.share", "exchange", t0c2_wall,
                    frac * dt_call, frac=frac,
                    nperm=ctx._halo_nperm.get(key, 0),
                    unstable=bool(ctx._halo_cal_unstable.get(key,
                                                             False)))


def _prep_shard_pallas(ctx, n: int, K: int, blk):
    """Validate + plan one ``(n, K, blk)`` shard_pallas variant.

    Returns ``(names, specs_for, build)`` where ``build(exchange)``
    is the un-jitted shard_map program (``exchange`` selects the real
    ghost exchange or the no-exchange calibration twin). Raises
    ``YaskException`` for infeasible candidates (minor-dim sharding at
    K>1, rank domain smaller than the fused ghost width, tile over the
    VMEM budget) — the auto-tuner relies on this to skip them."""
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec
    from yask_tpu.ops.pallas_stencil import build_pallas_chunk

    opts = ctx._opts
    ana = ctx._ana
    mesh = ctx._mesh
    dims = ana.domain_dims
    minor = dims[-1]
    nr = {d: opts.num_ranks[d] for d in dims}
    gsizes = opts.global_domain_sizes
    lsizes = opts.rank_domain_sizes
    dirn = ana.step_dir

    if K > 1 and nr.get(minor, 1) > 1:
        raise YaskException(
            f"shard_pallas with wf_steps={K} > 1 cannot shard the minor "
            f"dim '{minor}' (its in-tile region never shrinks); use "
            "wf_steps 1 or keep the minor dim whole")
    # ghost widths off THE TilePlan (single margin-math source; the
    # minor dim keeps the raw fused halo — it is never a tiled lead dim)
    from yask_tpu.ops.tile_planner import TilePlan
    _tplan = TilePlan(ctx._program, K)
    rad = ana.fused_step_radius()
    hK = {d: _tplan.halo(d) for d in _tplan.lead}
    hK[_tplan.minor] = rad.get(_tplan.minor, 0) * K
    for d in dims:
        if nr.get(d, 1) > 1 and lsizes[d] < hK[d]:
            raise YaskException(
                f"rank domain {lsizes[d]} in dim '{d}' smaller than the "
                f"fused ghost width {hK[d]} (radius × wf_steps)")

    # Communication schedule for this (mode, K): axis order +
    # coalescing off the ICI/DCN cost model, baked into the traced
    # exchange closures below (the variant cache key carries the knobs)
    plan = ctx.comm_plan(K)
    if plan.errors:
        raise YaskException("communication plan invalid: "
                            + "; ".join(plan.errors))

    # Per-shard plan: pads grown to the fused ghost width so the kernel's
    # halo DMAs stay inside the array and exchanges have room.
    extra = {d: (hK[d], hK[d]) for d in dims}
    local_prog = ctx._csol.plan(lsizes, global_sizes=gsizes,
                                extra_pad=extra)

    names = [k for k, g in ctx._program.geoms.items() if not g.is_scratch]
    slots = {k: ctx._program.geoms[k].num_slots for k in names}
    specs_for = _make_specs_for(local_prog, nr)

    groups, rem = divmod(n, K)
    interp = ctx._env.get_platform() != "tpu"
    budget = ctx.vmem_budget()
    # Temporal blocking across shards: the skewed wavefront may engage
    # inside each shard when the stream dim is NOT mesh-decomposed —
    # the carry then never crosses a shard boundary and the r·K ghost
    # pads cover the skew margins, so the distributed path stops paying
    # the uniform 2·r·K recompute margin in that dim (the rank-level
    # temporal-tiling analog of the reference's update_tb_info,
    # setup.cpp:863).
    lead_local = dims[:-1]
    # per-dim: each skewed dim's carry must stay on-shard, so a dim may
    # engage exactly when it is not mesh-decomposed (the r·K ghost pads
    # then cover its skew margins)
    unsh = tuple(d for d in lead_local if nr.get(d, 1) == 1)
    skw = None if ctx._opts.skew_wavefront else False
    chunk, tile_bytes = build_pallas_chunk(
        local_prog, fuse_steps=K, block=blk, interpret=interp,
        distributed=True, vmem_budget=budget,
        vinstr_cap=ctx._opts.max_tile_vinstr, skew=skw,
        unsharded_dims=unsh,
        max_skew_dims=ctx._opts.skew_dims_max)
    chunk_rem = None
    if rem:
        chunk_rem, _ = build_pallas_chunk(
            local_prog, fuse_steps=rem, block=blk, interpret=interp,
            distributed=True, vmem_budget=budget,
            vinstr_cap=ctx._opts.max_tile_vinstr, skew=skw,
            unsharded_dims=unsh,
            max_skew_dims=ctx._opts.skew_dims_max)
    ctx._env.trace_msg(
        f"shard_pallas chunk: K={K}, blocks={blk or 'planner'}, "
        f"tile {tile_bytes / 2**20:.2f} MiB, "
        f"skew={chunk.tiling['skew']}, "
        f"margin_overhead={chunk.tiling['margin_overhead']}")

    # ---- overlapped exchange schedule (core/shell split) ---------------
    # The core chunk covers the interior shrunk by hK per sharded face
    # and is evaluated against PRE-exchange state: its reads stay inside
    # [core_lo−hK, core_hi+hK) ⊆ the interior, so it carries no data
    # dependence on the ppermutes and XLA overlaps the previous group's
    # collectives with it.  The width-hK shell slabs then run on the
    # post-exchange state — the reference's exterior/interior MPI
    # overlap (context.cpp:377-478) at the fused-chunk level.
    ngroups = groups + (1 if rem else 0)
    ov_engage, ov_core, ov_shells, ov_reasons = \
        overlap_decision(ctx, K, local_prog=local_prog)
    ov_setting = getattr(opts, "overlap_exchange", "auto")
    if ov_setting == "on" and not ov_engage:
        raise YaskException(
            "overlap_exchange=on but the core/shell split is "
            "infeasible: " + "; ".join(
                r.get("cause", r["code"]) for r in ov_reasons))
    if ov_engage and ngroups < 2:
        ov_engage = False
        ov_reasons.append({"code": "overlap_inactive",
                           "cause": f"single K-group (n={n} ≤ K={K}): "
                                    "no exchange to overlap"})
    chunk_core = chunk_core_rem = None
    shell_chunks: List = []
    shell_chunks_rem: List = []
    if ov_engage:
        def _build_split(fs):
            core_c, _ = build_pallas_chunk(
                local_prog, fuse_steps=fs, block=blk, interpret=interp,
                distributed=True, vmem_budget=budget,
                vinstr_cap=ctx._opts.max_tile_vinstr, skew=skw,
                unsharded_dims=unsh,
                max_skew_dims=ctx._opts.skew_dims_max, region=ov_core)
            sh_cs = []
            for d, a, b in ov_shells:
                sc, _ = build_pallas_chunk(
                    local_prog, fuse_steps=fs, block=blk,
                    interpret=interp, distributed=True,
                    vmem_budget=budget,
                    vinstr_cap=ctx._opts.max_tile_vinstr, skew=skw,
                    unsharded_dims=unsh,
                    max_skew_dims=ctx._opts.skew_dims_max,
                    region={d: (a, b)})
                sh_cs.append(sc)
            return core_c, sh_cs
        try:
            chunk_core, shell_chunks = _build_split(K)
            if rem >= 2:
                chunk_core_rem, shell_chunks_rem = _build_split(rem)
            elif rem:
                # a 1-step remainder group has no core compute window:
                # ov_group runs the whole chunk_rem on post-exchange
                # state (core_fn None), keeping bit-equality with the
                # serial schedule
                ov_reasons.append({
                    "code": "overlap_rem_unsplit",
                    "cause": "remainder group fuses a single step: run "
                             "whole on post-exchange state (no compute "
                             "to hide its exchange under)"})
        except YaskException as e:
            # the split planner rejected a region (e.g. an unalignable
            # boundary): fall back to the serial schedule unless forced
            if ov_setting == "on":
                raise
            ov_engage = False
            chunk_core = chunk_core_rem = None
            ov_reasons.append({"code": "overlap_fallback",
                               "cause": str(e)})
        else:
            ctx._env.trace_msg(
                f"shard_pallas overlap: core="
                f"{ {d: list(v) for d, v in ov_core.items()} }, "
                f"{len(ov_shells)} shell slab(s)")
    chunk.tiling["overlap_exchange"] = bool(ov_engage)
    chunk.tiling["overlap_reasons"] = list(ov_reasons)
    # every per-axis comm decision rides the tiling record (stats /
    # explain pass / ledger rows read it from here)
    chunk.tiling["comm"] = plan.record()
    if ov_engage:
        chunk.tiling["overlap_core"] = {d: list(v)
                                        for d, v in ov_core.items()}

    def build(exchange):
        """shard_map program with the given exchange implementation —
        the no-exchange twin drives halo-time calibration exactly as in
        run_shard_map."""
        shard_map = _shard_map_fn()
        in_specs = ({k: [specs_for(k)] * slots[k] for k in names},
                    PartitionSpec())
        out_specs = {k: [specs_for(k)] * slots[k] for k in names}

        def _widths(g):
            return {d: (hK[d], hK[d]) for d in g.domain_dims
                    if nr.get(d, 1) > 1 and hK[d] > 0}

        def _apply_many(state, items, locs):
            if not items:
                return state
            rings = {}
            for (k, si), a in zip(locs,
                                  exchange_many(items, nr, lsizes,
                                                plan, exchange)):
                rings.setdefault(k, list(state[k]))[si] = a
            return {**state, **rings}

        def exchange_all(state):
            """Full refresh: every slot of every var (run once up front —
            read-only vars and surviving ring slots keep valid ghosts
            after this), batched so a coalescing CommPlan shares
            collectives across vars and slots."""
            items, locs = [], []
            for k in names:
                g = local_prog.geoms[k]
                widths = _widths(g)
                if widths:
                    for si, a in enumerate(state[k]):
                        items.append((a, g, widths))
                        locs.append((k, si))
            return _apply_many(state, items, locs)

        def exchange_newest(state):
            """Per-group refresh: only the min(K, alloc) slots the chunk
            just produced (it re-zeroed their pads); everything else
            still holds valid ghosts."""
            items, locs = [], []
            for k in names:
                g = local_prog.geoms[k]
                if not g.is_written:
                    continue
                widths = _widths(g)
                if not widths:
                    continue
                nback = min(K, len(state[k]))
                for si in range(len(state[k]) - nback, len(state[k])):
                    items.append((state[k][si], g, widths))
                    locs.append((k, si))
            return _apply_many(state, items, locs)

        def body(interior_state, t0):
            offs = {d: lax.axis_index(d) * lsizes[d] if nr[d] > 1 else 0
                    for d in dims}
            off_vec = jnp.stack(
                [jnp.asarray(offs[d], dtype=jnp.int32) for d in dims])

            # 1) pad local interiors (ghost + physical zeros).
            state = {}
            for k in names:
                g = local_prog.geoms[k]
                pads = [(g.pads[dn] if kind == "domain" else (0, 0))
                        for dn, kind in g.axes]
                state[k] = [jnp.pad(a, pads) if pads else a
                            for a in interior_state[k]]

            def _strip(st):
                out = {}
                for k in names:
                    g = local_prog.geoms[k]
                    idxs = []
                    for dn, kind in g.axes:
                        if kind == "domain":
                            idxs.append(slice(g.origin[dn],
                                              g.origin[dn] + lsizes[dn]))
                        else:
                            idxs.append(slice(None))
                    out[k] = [a[tuple(idxs)] for a in st[k]]
                return out

            # 2) one full exchange up front, then per K-group the fused
            #    chunk runs and only its freshly produced slots (whose
            #    pads it re-zeroed) are re-exchanged — read-only vars and
            #    surviving slots never move again. The final chunk is
            #    unrolled so no exchange is wasted after the last group.
            state = exchange_all(state)

            if not ov_engage:
                def group(carry, _):
                    st, t = carry
                    st = chunk(st, t, off_vec)
                    st = exchange_newest(st)
                    return (st, t + K * dirn), None

                nscan = groups if rem else groups - 1
                (state, t), _ = lax.scan(group, (state, t0), None,
                                         length=nscan)
                if rem:
                    state = chunk_rem(state, t, off_vec)
                else:
                    state = chunk(state, t, off_vec)
                return _strip(state)

            # Overlapped schedule: group 0 runs the plain chunk on the
            # fully exchanged state; each later group exchanges FIRST,
            # then evaluates the core against the pre-exchange state
            # (its reads stay ≥ hK from every sharded face, so the
            # ppermutes are not on its dataflow and XLA overlaps them)
            # and the shell slabs against the post-exchange state.
            # Same T−1 exchanges as the serial schedule, moved from the
            # group tails to the heads.
            def ov_group(st, t, core_fn, shell_fns, gk):
                st_post = exchange_newest(st)
                if core_fn is None:
                    # single-step group (K=1 remainder): one fused step
                    # leaves no core compute window to hide its exchange
                    # under, and the split would trade bit-equality with
                    # the serial schedule for nothing — run the whole
                    # chunk on the post-exchange state instead (same
                    # exchange placement, same values to the last bit).
                    fo = (chunk if gk == K else chunk_rem)(
                        st_post, t, off_vec)
                    out = {}
                    for k in names:
                        g = local_prog.geoms[k]
                        if not g.is_written:
                            out[k] = list(st_post[k])
                            continue
                        L = len(st_post[k])
                        nb = min(gk, L)
                        out[k] = (list(st_post[k][nb:])
                                  + list(fo[k][L - nb:]))
                    return out
                core_out = core_fn(st, t, off_vec)
                shell_outs = [fn(st_post, t, off_vec)
                              for fn in shell_fns]
                new_state = {}
                for k in names:
                    g = local_prog.geoms[k]
                    if not g.is_written:
                        new_state[k] = list(st_post[k])
                        continue
                    L = len(st_post[k])
                    nback = min(gk, L)
                    merged = []
                    for s in range(L - nback, L):
                        a = core_out[k][s]
                        for (d, lo, hi), sh in zip(ov_shells,
                                                   shell_outs):
                            if d not in g.domain_dims:
                                # a var without the split dim is
                                # d-invariant (missing-dim race rule):
                                # the core's copy is already complete
                                continue
                            idx = [slice(None)] * a.ndim
                            idx[g.axis_of(d)] = slice(
                                g.origin[d] + lo, g.origin[d] + hi)
                            a = a.at[tuple(idx)].set(
                                sh[k][s][tuple(idx)])
                        merged.append(a)
                    # surviving (rotated-forward) slots must come from
                    # st_post — they keep their exchanged pads; the
                    # core output's cells outside its region windows
                    # are unwritten
                    new_state[k] = list(st_post[k][nback:]) + merged
                return new_state

            state = chunk(state, t0, off_vec)

            def group(carry, _):
                st, t = carry
                st = ov_group(st, t, chunk_core, shell_chunks, K)
                return (st, t + K * dirn), None

            (state, t), _ = lax.scan(
                group, (state, t0 + K * dirn), None,
                length=groups - 1)
            if rem:
                state = ov_group(state, t, chunk_core_rem,
                                 shell_chunks_rem, rem)
            return _strip(state)

        try:
            return shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
        except TypeError:  # older jax spells it check_rep
            return shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)

    # carried to get_shard_pallas_fn, which records it into
    # ctx._pallas_tiling only AFTER a successful Mosaic compile (a
    # failure must not leave stats modeling a tiling that never ran —
    # same invariant as the single-device path, context.py)
    build.tiling = chunk.tiling
    return names, specs_for, build


def get_shard_pallas_fn(ctx, interior, start: int, n: int, K: int, blk,
                        build=None):
    """AOT-compiled shard_pallas program for ``(n, K, blk)``, cached in
    the context's jit cache — the single compile policy (donation, AOT
    lowering, compile-time accounting) for both tuner trials and
    production runs. Trials use ``n == K`` (one group per call) while
    production runs key on the full run span, so a tuned variant is
    re-lowered once for its first real run — the trade for the tuner
    timing exactly one exchange+group instead of a whole run.
    ``interior`` provides the lowering avals; ``build`` lets a caller
    that already planned the variant skip the re-plan. May raise
    ``YaskException`` for infeasible candidates."""
    import jax
    import jax.numpy as jnp
    var = ctx._pallas_variant_key()
    key = ("shard_pallas", n, K, blk) + var
    if key not in ctx._jit_cache:
        if build is None:
            _, _, build = _prep_shard_pallas(ctx, n, K, blk)
        t0c = time.perf_counter()
        ctx._jit_cache[key] = aot_compile(
            build(exchange_ghosts),
            (interior, jnp.asarray(start, dtype=jnp.int32)),
            donate_argnums=0).fn
        ctx._compile_secs += time.perf_counter() - t0c
        # only after a successful compile (see _prep_shard_pallas)
        if getattr(build, "tiling", None) is not None:
            ctx._pallas_tiling[("shard_pallas", K, blk) + var] = \
                build.tiling
    return ctx._jit_cache[key]


def _prep_names_specs(ctx, nr):
    """(names, specs_for) for an already-compiled variant (no re-plan:
    axes structure is K-independent, so the global program's geometry
    serves for the PartitionSpecs)."""
    gprog = ctx._program
    names = [k for k, g in gprog.geoms.items() if not g.is_scratch]
    return names, _make_specs_for(gprog, nr)


def run_shard_pallas(ctx, start: int, n: int) -> None:
    """Distributed fused stepping: shard_map outer + Pallas inner.

    The scaling path for the flagship multi-chip target (reference
    wave-front + MPI-exchange interplay, ``context.cpp:352-576``): each
    shard carries ghost pads sized radius×K, ``lax.ppermute`` refreshes
    them once per K-step group, and the fused Pallas chunk advances K
    steps entirely on-shard (its domain mask works in global coordinates
    via the shard offset, so exchanged ghosts update through sub-steps
    while physical boundaries stay zero).
    """
    import jax
    import jax.numpy as jnp

    opts = ctx._opts
    dims = ctx._ana.domain_dims
    gprog = ctx._program
    gsizes = opts.global_domain_sizes
    mesh = ctx._mesh
    nr = {d: opts.num_ranks[d] for d in dims}

    K = min(max(opts.wf_steps, 1), n)
    bs = opts.block_sizes
    blk = None
    if any(bs[d] > 0 for d in dims[:-1]):
        blk = tuple(bs[d] if bs[d] > 0 else 8 for d in dims[:-1])
    key = ("shard_pallas", n, K, blk) + ctx._pallas_variant_key()

    need_build = key not in ctx._jit_cache
    need_cal = (opts.measure_halo_time and key not in ctx._halo_frac)
    build = None
    if need_build or need_cal:
        names, specs_for, build = _prep_shard_pallas(ctx, n, K, blk)
    else:
        names, specs_for = _prep_names_specs(ctx, nr)

    # Strip global pads → sharded interiors, run, re-pad (device-side,
    # pads are zero by invariant). Same accounting as run_shard_map; the
    # stripped interiors serve both AOT lowering (first call) and the
    # run, and compile/calibration time is excluded from the run window.
    t0r = time.perf_counter()
    interior = _strip_global_interiors(ctx, gprog, names, mesh,
                                       specs_for, gsizes)
    if need_build:
        # AOT-compile (shared policy: get_shard_pallas_fn) so the first
        # timed call doesn't include XLA/Mosaic compilation.
        cs0 = ctx._compile_secs
        get_shard_pallas_fn(ctx, interior, start, n, K, blk, build=build)
        t0r += ctx._compile_secs - cs0
    fn = ctx._jit_cache[key]

    # Halo-time calibration against the no-exchange twin (same scheme
    # and accounting as run_shard_map).
    frac = 0.0
    if opts.measure_halo_time:
        if need_cal:
            t0cal = time.perf_counter()
            t0c = time.perf_counter()
            tj = jnp.asarray(start, dtype=jnp.int32)
            fn_no = aot_compile(build(_no_exchange), (interior, tj),
                                donate_argnums=0).fn
            slots_ = {k: ctx._program.geoms[k].num_slots for k in names}
            rad = ctx._ana.fused_step_radius()
            xpad = {d: (rad.get(d, 0) * K, rad.get(d, 0) * K)
                    for d in dims}
            np0 = _trace_stats.nperm
            fn_x = aot_compile(_build_exchange_only(
                ctx, names, specs_for, slots_, nr,
                opts.rank_domain_sizes, gsizes, width_scale=K,
                written_only=True, extra_pad=xpad, uniform_widths=xpad,
                plan=ctx.comm_plan(K)), (interior, tj)).fn
            # collectives per exchange round off the compiled schedule
            ctx._halo_nperm[key] = _trace_stats.nperm - np0
            fn_p = aot_compile(_build_exchange_only(
                ctx, names, specs_for, slots_, nr,
                opts.rank_domain_sizes, gsizes, width_scale=K,
                written_only=True, extra_pad=xpad, uniform_widths=xpad,
                exchange=_no_exchange), (interior, tj)).fn
            ctx._compile_secs += time.perf_counter() - t0c
            _calibrate_halo_frac(ctx, key, fn, fn_no, interior, start,
                                 fn_xonly=fn_x, fn_pack=fn_p)
            del fn_no, fn_x, fn_p
            t0r += time.perf_counter() - t0cal
        frac = ctx._halo_frac[key] or 0.0  # None = unstable, no split
        ctx._halo_xround_last = ctx._halo_xround.get(key, 0.0)
        ctx._halo_xpack_last = ctx._halo_xpack.get(key, 0.0)
        ctx._halo_cal_spread_last = ctx._halo_cal_spread.get(key, 0.0)
        ctx._halo_cal_unstable_last = ctx._halo_cal_unstable.get(key, False)
        ctx._halo_cal_reps_last = ctx._halo_cal_reps.get(key, 0)
        ctx._halo_nperm_last = ctx._halo_nperm.get(key, 0)
        # Overlap efficiency: the serial model pays rounds × bare
        # exchange cost per call; the measured halo cost is frac ×
        # t_call.  Their shortfall is the share of the bare collective
        # cost the schedule hid (XLA overlap) — the reference derives
        # the same number from its exterior/interior MPI timers.
        if key not in ctx._halo_overlap_eff:
            g_, r_ = divmod(n, K)
            rounds = g_ + (1 if r_ else 0) - 1
            t_x = ctx._halo_xround.get(key, 0.0)
            t_call = ctx._halo_tcall.get(key, 0.0)
            eff = 0.0
            if rounds > 0 and t_x > 0 and t_call > 0 \
                    and ctx._halo_frac.get(key) is not None:
                eff = max(0.0, min(1.0, 1.0 - (frac * t_call)
                                   / (rounds * t_x)))
            ctx._halo_overlap_eff[key] = eff
        ctx._halo_overlap_eff_last = ctx._halo_overlap_eff.get(key, 0.0)

    ctx._resident = None   # interior buffers are donated next; any
    #                          failure before this point kept them valid
    t0c2 = time.perf_counter()
    t0c2_wall = time.time()
    out = fn(interior, jnp.asarray(start, dtype=jnp.int32))
    jax.block_until_ready(out)
    dt_call = time.perf_counter() - t0c2
    # Keep the interiors device-resident: the next shard-mode run takes
    # them directly, and any host access materializes (re-pads) lazily.
    ctx._resident = out
    ctx._state = None
    ctx._run_timer._elapsed += time.perf_counter() - t0r
    ctx._halo_timer._elapsed += frac * dt_call
    ctx._halo_frac_last = frac
    if frac > 0:
        from yask_tpu.obs.tracer import record_span
        # retroactive exchange-share span (see run_shard_map)
        record_span("halo.share", "exchange", t0c2_wall,
                    frac * dt_call, frac=frac,
                    nperm=ctx._halo_nperm.get(key, 0),
                    unstable=bool(ctx._halo_cal_unstable.get(key,
                                                             False)))
