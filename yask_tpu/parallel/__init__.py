"""Distribution: device-mesh decomposition and ghost-cell exchange.

TPU-native counterpart of the reference's MPI layer
(``src/kernel/lib/setup.cpp`` rank topology, ``halo.cpp`` exchange): the
N-D rank grid becomes a ``jax.sharding.Mesh`` whose axes are domain dims;
halo exchange becomes ``lax.ppermute`` neighbor shifts over ICI inside
``shard_map`` (or XLA-inserted collectives in ``sharded`` mode).
"""

from yask_tpu.parallel.mesh import build_mesh, make_mesh, state_shardings
from yask_tpu.parallel.comm_plan import (
    CommPlan,
    build_comm_plan,
    comm_ledger_fields,
)
from yask_tpu.parallel.decomp import (
    factorize_rank_grid,
    validate_shard_geometry,
)

__all__ = ["build_mesh", "make_mesh", "state_shardings",
           "CommPlan", "build_comm_plan", "comm_ledger_fields",
           "factorize_rank_grid", "validate_shard_geometry"]
