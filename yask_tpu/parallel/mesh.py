"""Device-mesh construction and state shardings.

The reference arranges MPI ranks in an N-D grid and records per-rank offsets
(``setup_rank``, ``setup.cpp:169``); here the grid is a ``jax.sharding.Mesh``
whose axis names ARE the solution's domain dims, and per-var shardings are
``NamedSharding`` partition specs over the dims that are actually split.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from yask_tpu.utils.exceptions import YaskException


def build_mesh(env, opts):
    """Mesh over the device grid implied by ``opts.num_ranks``."""
    from jax.sharding import Mesh
    nr = opts.num_ranks
    dims = nr.get_dim_names()
    shape = [nr[d] for d in dims]
    need = int(np.prod(shape))
    devs = env.get_devices()
    if need > len(devs):
        raise YaskException(
            f"mesh {dict(zip(dims, shape))} needs {need} devices, "
            f"have {len(devs)}")
    arr = np.array(devs[:need]).reshape(shape)
    return Mesh(arr, axis_names=tuple(dims))


def state_shardings(mesh, program, opts) -> Dict[str, object]:
    """Per-var NamedSharding: split each var's domain axes that lie on a
    mesh axis with extent > 1; everything else replicated."""
    from jax.sharding import NamedSharding, PartitionSpec
    out = {}
    for name, g in program.geoms.items():
        if g.is_scratch:
            continue
        spec = []
        for n, kind in g.axes:
            if kind == "domain" and opts.num_ranks.get(n, 1) > 1:
                spec.append(n)
            else:
                spec.append(None)
        out[name] = NamedSharding(mesh, PartitionSpec(*spec))
    return out
