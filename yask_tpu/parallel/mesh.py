"""Device-mesh construction and state shardings.

The reference arranges MPI ranks in an N-D grid and records per-rank offsets
(``setup_rank``, ``setup.cpp:169``); here the grid is a ``jax.sharding.Mesh``
whose axis names ARE the solution's domain dims, and per-var shardings are
``NamedSharding`` partition specs over the dims that are actually split.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from yask_tpu.utils.exceptions import YaskException


def make_mesh(devices, axis_sizes):
    """THE mesh factory — the single ``jax.sharding.Mesh`` construction
    site in the repo (``repo_lint``'s MESH-DIRECT rule enforces it).

    ``devices`` is a flat device list; ``axis_sizes`` an ordered
    ``(name, extent)`` sequence.  Centralizing construction makes the
    backend a *config*, not a port: a GPU or any other PJRT backend is
    just a different device list handed in (the device-mesh pattern the
    multi-backend frameworks use), and multi-host meshes are the same
    call over a ``jax.distributed``-initialized global device list
    (``tools/launch_multihost.py``).
    """
    from jax.sharding import Mesh
    axis_sizes = list(axis_sizes)
    dims = [d for d, _n in axis_sizes]
    shape = [int(n) for _d, n in axis_sizes]
    need = int(np.prod(shape))
    devices = list(devices)
    if need > len(devices):
        raise YaskException(
            f"mesh {dict(zip(dims, shape))} needs {need} devices, "
            f"have {len(devices)}")
    arr = np.array(devices[:need]).reshape(shape)
    return Mesh(arr, axis_names=tuple(dims))


def build_mesh(env, opts):
    """Mesh over the device grid implied by ``opts.num_ranks``."""
    nr = opts.num_ranks
    dims = nr.get_dim_names()
    return make_mesh(env.get_devices(), [(d, nr[d]) for d in dims])


def state_shardings(mesh, program, opts) -> Dict[str, object]:
    """Per-var NamedSharding: split each var's domain axes that lie on a
    mesh axis with extent > 1; everything else replicated."""
    from jax.sharding import NamedSharding, PartitionSpec
    out = {}
    for name, g in program.geoms.items():
        if g.is_scratch:
            continue
        spec = []
        for n, kind in g.axes:
            if kind == "domain" and opts.num_ranks.get(n, 1) > 1:
                spec.append(n)
            else:
                spec.append(None)
        out[name] = NamedSharding(mesh, PartitionSpec(*spec))
    return out
