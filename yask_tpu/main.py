"""Performance & validation harness CLI.

Counterpart of the reference's ``yask_main.cpp`` (``src/kernel/yask_main.cpp:
251``) and its trial protocol (:53-66): pick a registered stencil, set sizes,
optionally pre-auto-tune, warm up (compiles — excluded from timing, like the
reference's warmup), run N timed trials, report best/mid/ave statistics in
the same log-key format the reference's CSV scraper reads
(``utils/lib/YaskUtils.pm:40-58``), and optionally validate against the
eager-numpy oracle (the ``-validate`` flow, ``yask_main.cpp:564-616``).

Usage::

    python -m yask_tpu.main -stencil iso3dfd -radius 8 -g 256 \
        -num_trials 3 -trial_steps 20
    python -m yask_tpu.main -stencil ssg -g 32 -validate
"""

from __future__ import annotations

import statistics
import sys
import time
from typing import List, Optional

from yask_tpu.utils.cli import CommandLineParser
from yask_tpu.utils.exceptions import YaskException


class HarnessSettings:
    def __init__(self):
        self.stencil = ""
        self.radius = 0
        self.num_trials = 3
        self.trial_steps = 10
        self.warmup_steps = 0     # 0 → same as trial_steps
        self.validate = False
        self.validate_steps = 2   # short, like the reference's validation
        self.init_seed = 0.1
        self.pre_auto_tune = False
        self.trace = False
        self.profile_dir = ""     # jax.profiler trace output
        self.ledger = False       # append the run to PERF_LEDGER.jsonl
        self.list_stencils = False
        self.help = False

    def add_options(self, p: CommandLineParser) -> None:
        p.add_string_option("stencil", "Registered stencil name.",
                            self, "stencil")
        p.add_int_option("radius", "Stencil radius (0 = default).",
                         self, "radius")
        p.add_int_option("num_trials", "Number of timed trials.",
                         self, "num_trials")
        p.add_int_option("trial_steps", "Steps per trial.",
                         self, "trial_steps")
        p.add_int_option("warmup_steps", "Warmup steps (0 = trial_steps).",
                         self, "warmup_steps")
        p.add_bool_option("validate", "Compare vs the numpy oracle instead "
                          "of timing.", self, "validate")
        p.add_int_option("validate_steps", "Steps for -validate (short, "
                         "like the reference's '-trial_steps 2' validation "
                         "runs: fp32 noise compounds per step).",
                         self, "validate_steps")
        p.add_string_option(
            "profile", "Write a jax.profiler trace of the timed trials "
            "to this directory (open with TensorBoard/xprof — the "
            "view_asm/trace analog at the XLA-op level).",
            self, "profile_dir")
        p.add_float_option("init_seed", "Per-var init sequence seed.",
                           self, "init_seed")
        p.add_bool_option(
            "ledger", "Append the mid-throughput (with provenance, "
            "roofline context, and a sentinel guard verdict) to the "
            "unified perf ledger (PERF_LEDGER.jsonl).", self, "ledger")
        p.add_bool_option("auto_tune", "Pre-run the auto-tuner.",
                          self, "pre_auto_tune")
        p.add_bool_option("trace", "Enable trace messages.", self, "trace")
        p.add_bool_option("list", "List registered stencils.",
                          self, "list_stencils")
        p.add_bool_option("help", "Print help.", self, "help")


from yask_tpu.runtime.init_utils import init_solution_vars as _init_vars


def _comm_fields(ctx, mode) -> dict:
    """Comm-schedule ledger fields for the explicit shard modes; {} on
    single-device paths (no exchanged axes, nothing to record)."""
    if mode not in ("shard_map", "shard_pallas"):
        return {}
    from yask_tpu.parallel.comm_plan import comm_ledger_fields
    try:
        return comm_ledger_fields(ctx)
    except Exception:
        return {}


def _build(opts: HarnessSettings, extra_args: List[str]):
    from yask_tpu import yk_factory
    fac = yk_factory()
    env = fac.new_env()
    env.set_trace_enabled(opts.trace)
    ctx = fac.new_solution(env, stencil=opts.stencil,
                           radius=opts.radius or None)
    rest = ctx.apply_command_line_options(extra_args)
    if rest:
        raise YaskException(f"unrecognized options: {' '.join(rest)}")
    return env, ctx


def run_harness(argv: Optional[List[str]] = None, out=None) -> int:
    out = out or sys.stdout
    opts = HarnessSettings()
    p = CommandLineParser()
    opts.add_options(p)
    rest = p.parse_args(list(argv if argv is not None else sys.argv[1:]))

    if opts.help:
        out.write("yask_tpu harness options:\n")
        p.print_help(out)
        out.write("\nplus all kernel options (-g, -d, -b, -nr, -mode, "
                  "-wf_steps, ...):\n")
        return 0
    from yask_tpu.compiler.solution_base import get_registered_solutions
    if opts.list_stencils:
        out.write("\n".join(get_registered_solutions()) + "\n")
        return 0
    if not opts.stencil:
        out.write("error: -stencil <name> required; -list to enumerate.\n")
        return 2

    env, ctx = _build(opts, rest)
    out.write(f"YASK-TPU harness: stencil '{opts.stencil}' on "
              f"{env.get_platform()} ({env.get_num_ranks()} device(s))\n")
    ctx.prepare_solution()
    _init_vars(ctx, opts.init_seed)
    soln_ana = ctx._ana
    npts = ctx.get_settings().global_domain_sizes.product()
    out.write(f"domain: "
              f"{ctx.get_settings().global_domain_sizes.make_dim_val_str()}"
              f" ({npts} points); {soln_ana.summary()}\n")

    if opts.validate:
        # -validate flow: run both engines on identical state, compare.
        steps = max(opts.validate_steps, 1)
        ctx.run_solution(0, steps - 1)
        env2, ref = _build(opts, rest)
        ref.get_settings().mode = "ref"
        ref.prepare_solution()
        _init_vars(ref, opts.init_seed)
        ref.run_solution(0, steps - 1)
        bad = ctx.compare_data(ref, epsilon=1e-3, abs_epsilon=1e-4)
        if bad:
            out.write(f"VALIDATION FAILED: {bad} mismatching point(s) "
                      f"after {steps} step(s).\n")
            return 1
        out.write(f"validation passed after {steps} step(s) "
                  "(optimized vs numpy oracle).\n")
        return 0

    if opts.pre_auto_tune:
        best = ctx.run_auto_tuner_now()
        out.write(f"auto-tuner: wf_steps={best}\n")

    # Warmup (includes XLA compile; excluded from trials).
    warm = opts.warmup_steps or opts.trial_steps
    t = 0
    ctx.run_solution(t, t + warm - 1)
    t += warm
    out.write(f"warmup done ({warm} step(s); compile "
              f"{ctx.get_stats().get_compile_secs():.3g} s).\n")

    profiling = False
    if opts.profile_dir:
        env.start_profiler_trace(opts.profile_dir)
        profiling = True
        out.write(f"profiling trials into {opts.profile_dir}\n")

    rates = []
    try:
        for trial in range(opts.num_trials):
            ctx.clear_stats()
            t0 = time.perf_counter()
            ctx.run_solution(t, t + opts.trial_steps - 1)
            dt = time.perf_counter() - t0
            t += opts.trial_steps
            pts_ps = npts * opts.trial_steps / dt
            rates.append(pts_ps)
            st = ctx.get_stats()
            out.write(f"trial {trial + 1}/{opts.num_trials}:\n")
            out.write(f"  num-steps-done: {opts.trial_steps}\n")
            out.write(f"  elapsed-time (sec): {dt:.6g}\n")
            out.write(f"  throughput (num-points/sec): {pts_ps:.6g}\n")
            out.write(f"  throughput (est-FLOPS): "
                      f"{pts_ps * soln_ana.counters.num_ops:.6g}\n")
            if st.get_halo_secs() > 0:
                out.write(f"  halo-time (sec): "
                          f"{st.get_halo_secs():.6g}\n")
                out.write(
                    f"  halo-fraction (%): "
                    f"{100.0 * st.get_halo_secs() / max(dt, 1e-12):.4g}\n")
            elif st.get_halo_cal_unstable():
                # twice-unstable twin: no split is banked — total step
                # time is the evidence, the halo share is unknown
                out.write("  halo-time (sec): null "
                          "(calibration unstable)\n")
    finally:
        if profiling:
            env.stop_profiler_trace()

    rates.sort()
    mid = rates[len(rates) // 2]
    out.write("summary:\n")
    out.write(f"  best-throughput (num-points/sec): {rates[-1]:.6g}\n")
    out.write(f"  mid-throughput (num-points/sec): {mid:.6g}\n")
    out.write(f"  min-throughput (num-points/sec): {rates[0]:.6g}\n")
    out.write(f"  ave-throughput (num-points/sec): "
              f"{statistics.fmean(rates):.6g}\n")
    if len(rates) > 1:
        out.write(f"  stddev-throughput (num-points/sec): "
                  f"{statistics.stdev(rates):.6g}\n")
    out.write(f"  mid-throughput (GPts/s): {mid / 1e9:.6g}\n")
    # roofline context for the mid rate (reference prints its full
    # stats block) — the shared perflab model, so the harness, bench,
    # suite, and session all derive the fraction identically
    from yask_tpu.perflab.roofline import ctx_roofline, format_roofline
    st = ctx.get_stats()
    roof = ctx_roofline(ctx, env, mid / 1e9)
    if roof["hbm_bytes_pp"] > 0:
        out.write(format_roofline(roof))
    if st.get_tiling():
        out.write(f"  pallas-tiling: {st.get_tiling()}\n")

    if opts.ledger:
        # one unified row per harness run: -ledger turns any ad-hoc
        # measurement into a tracked series the sentinel can guard
        from yask_tpu.perflab import capture_provenance
        from yask_tpu.perflab.sentinel import guard_and_append
        s = ctx.get_settings()
        sizes = s.global_domain_sizes.make_val_str("x")
        mode = getattr(ctx, "_mode", None) or s.mode
        key = (f"{opts.stencil} g={sizes} {env.get_platform()} "
               f"harness ({mode}"
               + (f"-K{s.wf_steps}" if s.wf_steps > 1 else "") + ")")
        prov = capture_provenance(
            platform=env.get_platform(),
            device_kind=(getattr(env.get_devices()[0], "device_kind",
                                 "") if env.get_devices() else ""))
        row = guard_and_append(
            key, round(mid / 1e9, 4), "GPts/s", env.get_platform(),
            "harness", prov, roofline=roof,
            extra={"trials": opts.num_trials,
                   "trial_steps": opts.trial_steps,
                   **({"tiling": st.get_tiling()} if st.get_tiling()
                      else {}),
                   # noise context for the measured halo fraction: the
                   # relative spread across the ≥3 calibration trials
                   # (a fraction of the same magnitude is twin jitter,
                   # not a halo-cost change)
                   **({"halo_cal_spread":
                       round(st.get_halo_cal_spread(), 4)}
                      if st.get_halo_cal_spread() > 0 else {}),
                   # calibration kept an outlier beyond 3× the agreeing
                   # pair's spread even after the one re-time: the split
                   # is noise — halo_time reports null (no noise-derived
                   # split banked), total step time stands alone
                   **({"halo_cal_unstable": True, "halo_time": None}
                      if st.get_halo_cal_unstable() else {}),
                   # how many trials the calibration burned (6 = clean;
                   # more = outlier re-times / the final scaled round)
                   **({"halo_cal_reps": st.get_halo_cal_reps()}
                      if st.get_halo_cal_reps() > 0 else {}),
                   # share of the bare collective cost the schedule hid
                   # (the overlapped core/shell split should push this
                   # toward 1; the serial arm shows XLA's baseline)
                   **({"halo_overlap_eff":
                       round(st.get_halo_overlap_eff(), 4)}
                      if st.get_halo_overlap_eff() > 0 else {}),
                   # comm schedule: mesh shape, per-axis bytes, and
                   # collective-round counts, so coalescing A/Bs are
                   # distinguishable series in the ledger
                   **(_comm_fields(ctx, mode))})
        out.write(f"ledger: recorded '{key}' "
                  f"(guard {row['guard'].get('status')})\n")
    return 0


def main() -> None:  # pragma: no cover - thin wrapper
    try:
        sys.exit(run_harness())
    except YaskException as e:
        sys.stderr.write(f"error: {e}\n")
        sys.exit(2)


if __name__ == "__main__":
    main()
