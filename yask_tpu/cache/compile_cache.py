"""Persistent AOT compile cache: THE chokepoint every executable
build goes through.

The reference ships its stencils as build-once-run-many kernel
libraries (``libyask_kernel.<stencil>.<arch>.so``): compiling is a
*build step*, running is a *link*.  Here the analog was missing —
every ``(stencil, geometry, variant)`` point paid a full
trace+lower+compile on each process start, and the auto-tuner alone
re-compiles dozens of variants per session.  This module centralizes
executable construction (the Titanax chokepoint shape) and persists
compiled executables on disk so the second process start is a cache
lookup:

* :func:`aot_compile` — the one function allowed to call
  ``jax.jit(...).lower(...).compile()`` (``tools/repo_lint.py``'s
  COMPILE-DIRECT rule fails any chain outside this package).  Returns
  an :class:`AotResult` carrying the executable plus the cache verdict
  (``cache_hit``/``compile_secs``) producers put in ledger rows.
* Persistence: when ``key`` is given and ``YT_COMPILE_CACHE`` names a
  directory, executables are serialized via
  ``jax.experimental.serialize_executable`` into content-addressed
  entries (sha-256 of the schema + caller key + backend fingerprint).
  Writes are atomic (tmp + ``os.replace``); entries are versioned
  (:data:`SCHEMA`) and carry the fingerprint in the body too, so the
  checker's CACHE-STALE pass can tell "stale for this jax" from
  "corrupt".  Any load/deserialize failure falls back to a fresh
  compile — a corrupt cache entry must never break a run.
* The **trace counter**: ``stats()["lowerings"]`` counts actual
  trace+lower+compile executions.  A warm process re-running a cached
  variant must show 0 — the tpu_session ``compile_cache_ab`` stage and
  ``tests/test_cache.py`` assert on the counter, not on wall-clock.
* Fault sites: disk I/O routes through ``guarded_call`` at
  ``cache.load`` / ``cache.store`` so ``YT_FAULT_PLAN`` injection can
  drive both failure paths from fast CPU tests (docs/resilience.md).

The fingerprint (jax/jaxlib versions + backend platform, via
``perflab.provenance``) is part of the content address: a jax upgrade
changes every digest, so stale entries become unreachable rather than
deserialize hazards.  Eviction keeps the directory bounded
(``YT_COMPILE_CACHE_MAX`` entries, oldest-mtime first).

Platform note: keyed compiles on ``cpu`` are built WITHOUT donation
(see the comment in :func:`aot_compile`) — XLA:CPU's
deserialize-as-recompile path mishandles donated aliased buffers, so
persistable executables use an alias-free convention there.  Keyed
callers must therefore pass plain functions plus ``donate_argnums``,
never a pre-jitted callable with donation baked in.
"""

from __future__ import annotations

import os
import pickle
import time
from dataclasses import dataclass
from hashlib import sha256
from typing import Any, Dict, Iterator, Optional, Tuple

#: entry format version; bump on any layout change so old files read
#: as stale (schema mismatch → fresh compile), never as garbage.
SCHEMA = "yask_tpu.compile_cache/1"

#: default bound on on-disk entries (override: YT_COMPILE_CACHE_MAX).
DEFAULT_MAX_ENTRIES = 64

_SUFFIX = ".aotc"

#: in-process memo (digest → executable): one compile serves every
#: context in the process, not just the one that built it.
_memo: Dict[str, Any] = {}

_STATS_KEYS = ("lowerings", "memory_hits", "disk_hits", "misses",
               "stores", "load_failures", "store_failures", "evictions")
_stats: Dict[str, int] = {k: 0 for k in _STATS_KEYS}


class CacheEntryError(Exception):
    """A persisted entry is unusable (bad schema, wrong fingerprint,
    truncated pickle).  Internal: always handled by falling back to a
    fresh compile."""


@dataclass
class AotResult:
    """What :func:`aot_compile` hands back: the runnable executable
    plus the cache verdict producers record in ledger rows."""
    fn: Any                      # the compiled executable (callable)
    cache_hit: Optional[str]     # None | "memory" | "disk"
    compile_secs: float          # 0.0 on any hit
    digest: Optional[str]        # content address (None when unkeyed)


def stats() -> Dict[str, int]:
    """Snapshot of the process-wide counters.  ``lowerings`` is the
    trace counter: actual ``jit→lower→compile`` executions."""
    return dict(_stats)


def reset_stats() -> None:
    for k in _STATS_KEYS:
        _stats[k] = 0


def clear_memo() -> None:
    """Drop the in-process memo (test isolation; disk entries stay)."""
    _memo.clear()


def cache_dir() -> Optional[str]:
    """The persistent cache directory (``YT_COMPILE_CACHE``), or None
    when persistence is off (unset/empty)."""
    d = os.environ.get("YT_COMPILE_CACHE", "").strip()
    return d or None


def max_entries() -> int:
    try:
        return max(int(os.environ.get("YT_COMPILE_CACHE_MAX",
                                      str(DEFAULT_MAX_ENTRIES))), 1)
    except ValueError:
        return DEFAULT_MAX_ENTRIES


_fp_static: Dict[str, str] = {}


def backend_fingerprint(platform: str = "") -> Dict[str, str]:
    """The jax/backend + code identity an executable is only valid
    under.  Versions come from ``perflab.provenance``
    (importlib.metadata — no jax import, so fingerprinting never dials
    the relay); ``platform`` is the caller's ``yk_env`` platform for
    the same reason; ``code`` is the repo's git SHA so a kernel-code
    change invalidates persisted executables (sessions on the same
    commit still share)."""
    if not _fp_static:
        from yask_tpu.perflab.provenance import _pkg_version, git_sha
        _fp_static.update(jax=_pkg_version("jax"),
                          jaxlib=_pkg_version("jaxlib"),
                          code=git_sha() or "")
    return dict(_fp_static, platform=platform or "")


def key_digest(key, fingerprint: Dict[str, str]) -> str:
    """Content address: schema + caller key + fingerprint.  The
    fingerprint being part of the address makes a jax upgrade a clean
    miss (stale entries become unreachable, not deserialize hazards)."""
    blob = repr((SCHEMA, key, tuple(sorted(fingerprint.items()))))
    return sha256(blob.encode()).hexdigest()[:40]


def args_signature(example_args) -> Tuple:
    """Shape/dtype/SHARDING of every example-arg leaf.  An AOT
    executable is specialized to its input shardings and shapes —
    calling it with others raises — so they must be part of the
    content address alongside the caller's key: a jit-oracle chunk
    and a sharded-mode chunk over identically-padded state trace the
    same program text but compile incompatible executables."""
    from jax import tree_util

    def leaf(x):
        shp = getattr(x, "shape", None)
        if shp is not None:
            return ("arr", tuple(shp), str(getattr(x, "dtype", "")),
                    repr(getattr(x, "sharding", None)))
        return ("lit", type(x).__name__,
                repr(x) if isinstance(x, (int, float, bool, str,
                                          type(None))) else "")

    leaves, treedef = tree_util.tree_flatten(example_args)
    return (repr(treedef), tuple(leaf(v) for v in leaves))


def entry_path(digest: str, directory: Optional[str] = None) -> str:
    return os.path.join(directory or cache_dir() or ".", digest + _SUFFIX)


# ---------------------------------------------------------------------------
# disk layer (guarded: cache.load / cache.store fault sites)

def _read_entry(path: str) -> Dict:
    with open(path, "rb") as f:
        entry = pickle.load(f)
    if not isinstance(entry, dict) or entry.get("schema") != SCHEMA:
        raise CacheEntryError(
            f"bad schema in {os.path.basename(path)}: "
            f"{entry.get('schema') if isinstance(entry, dict) else type(entry)}")
    return entry


def _load_entry(path: str, fingerprint: Dict[str, str]) -> Dict:
    entry = _read_entry(path)
    if entry.get("fingerprint") != fingerprint:
        # unreachable through the content address in normal operation
        # (the fingerprint is hashed into the digest) — this guards a
        # hand-copied or tampered entry
        raise CacheEntryError(
            f"fingerprint mismatch in {os.path.basename(path)}")
    return entry


def _write_atomic(path: str, blob: bytes) -> None:
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".tmp.{os.getpid()}.{os.path.basename(path)}")
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)


def _remove_quietly(path: str) -> None:
    try:
        os.remove(path)
    except OSError:
        pass


def _evict(directory: str) -> None:
    """Drop oldest-mtime entries beyond the bound.  Best-effort: a
    racing process deleting the same file is fine."""
    try:
        names = [n for n in os.listdir(directory) if n.endswith(_SUFFIX)]
    except OSError:
        return
    cap = max_entries()
    if len(names) <= cap:
        return
    def mtime(n):
        try:
            return os.path.getmtime(os.path.join(directory, n))
        except OSError:
            return 0.0
    for n in sorted(names, key=mtime)[:len(names) - cap]:
        _remove_quietly(os.path.join(directory, n))
        _stats["evictions"] += 1


def iter_entries(directory: Optional[str] = None
                 ) -> Iterator[Tuple[str, Dict]]:
    """Yield ``(path, meta)`` for every persisted entry — meta carries
    ``schema``/``key``/``fingerprint`` (payload omitted) or
    ``{"unreadable": <why>}`` for corrupt files.  The checker's
    CACHE-STALE pass scans this; it must never raise."""
    d = directory or cache_dir()
    if not d or not os.path.isdir(d):
        return
    for n in sorted(os.listdir(d)):
        if not n.endswith(_SUFFIX):
            continue
        path = os.path.join(d, n)
        try:
            e = _read_entry(path)
            yield path, {"schema": e.get("schema"),
                         "key": e.get("key"),
                         "fingerprint": e.get("fingerprint", {})}
        except Exception as e:  # noqa: BLE001 - scan must survive junk
            yield path, {"unreadable": f"{type(e).__name__}: {e}"}


# ---------------------------------------------------------------------------
# the chokepoint

def _fresh_compile(fn, example_args, jit_kwargs) -> Tuple[Any, float]:
    import jax
    t0 = time.perf_counter()
    # Accept pre-jitted callables (the shard builders return jax.jit
    # objects carrying their own donate_argnums): re-wrapping would
    # nest jits and silently drop inner donation.
    if not jit_kwargs and hasattr(fn, "lower"):
        lowered = fn.lower(*example_args)
    else:
        lowered = jax.jit(fn, **jit_kwargs).lower(*example_args)
    _stats["lowerings"] += 1
    exe = lowered.compile()
    return exe, time.perf_counter() - t0


def aot_compile(fn, example_args, *, key=None, platform: str = "",
                donate_argnums=None, static_argnums=None) -> AotResult:
    """Build (or fetch) the executable for ``fn`` at the shapes of
    ``example_args`` — the one sanctioned ``jit→lower→compile`` site.
    Every call opens a ``cache.aot`` span (phase ``compile``) whose
    attrs record the hit tier — the trace answers "did this request
    pay a lowering" without grepping stats."""
    from yask_tpu.obs.tracer import span
    with span("cache.aot", phase="compile",
              keyed=key is not None) as sp:
        res = _aot_compile(fn, example_args, key=key,
                           platform=platform,
                           donate_argnums=donate_argnums,
                           static_argnums=static_argnums)
        sp.set(hit=res.cache_hit or "miss",
               compile_secs=round(res.compile_secs, 6),
               digest=res.digest or "")
        return res


def _aot_compile(fn, example_args, *, key=None, platform: str = "",
                 donate_argnums=None, static_argnums=None) -> AotResult:
    """The uninstrumented chokepoint (see :func:`aot_compile`).

    ``key=None``: no persistence — a plain AOT compile that still
    feeds the trace counter (per-call shapes like the shard twins,
    where the caller's own memo is the right cache).  With ``key``,
    the executable is memoized in-process and (when
    ``YT_COMPILE_CACHE`` is set) persisted across processes.  ``key``
    must fully determine the lowered program TEXT: the callers' keys
    combine stencil identity, padded state geometry, dtype, step
    count/fusion depth, mode, and the pallas variant tuple — anything
    they bake into the trace.  ``args_signature(example_args)``
    (shape/dtype/sharding per leaf) is hashed in here, so two calls
    under the same key whose inputs are placed differently can never
    share an executable.

    Every failure path (missing entry, corrupt pickle, deserialize
    error, store I/O) degrades to a fresh compile / a skipped store;
    the cache can only ever cost a compile, never a run."""
    jit_kwargs = {}
    if donate_argnums is not None:
        jit_kwargs["donate_argnums"] = donate_argnums
    if static_argnums is not None:
        jit_kwargs["static_argnums"] = static_argnums

    # XLA:CPU deserializes an executable by RECOMPILING its serialized
    # HLO, and the recompiled binary mishandles ownership of donated
    # aliased buffers: a donated passthrough output (e.g. a read-only
    # var forwarded through a scan) can alias a buffer the runtime has
    # already returned to the allocator, which then scribbles its
    # free-list header over the first bytes (probed: 8 garbage floats
    # at offset 0, nondeterministic, needs a fresh-compiled twin in
    # the same process).  Donation is a device-memory optimization
    # with no semantic effect, so every KEYED compile on cpu — the
    # ones a later process may serve from disk — drops it; fresh and
    # disk-loaded twins then share one safe, alias-free convention.
    # Unkeyed compiles are never serialized and keep their donation.
    if key is not None and platform == "cpu":
        jit_kwargs.pop("donate_argnums", None)

    if key is None:
        exe, secs = _fresh_compile(fn, example_args, jit_kwargs)
        _stats["misses"] += 1
        return AotResult(fn=exe, cache_hit=None, compile_secs=secs,
                         digest=None)

    fp = backend_fingerprint(platform)
    digest = key_digest((key, args_signature(example_args)), fp)

    if digest in _memo:
        _stats["memory_hits"] += 1
        return AotResult(fn=_memo[digest], cache_hit="memory",
                         compile_secs=0.0, digest=digest)

    d = cache_dir()
    from yask_tpu.resilience import guarded_call
    if d is not None:
        path = entry_path(digest, d)
        if os.path.exists(path):
            try:
                entry = guarded_call(_load_entry, path, fp,
                                     site="cache.load")
                from jax.experimental.serialize_executable import \
                    deserialize_and_load
                exe = deserialize_and_load(entry["payload"],
                                           entry["in_tree"],
                                           entry["out_tree"])
                _memo[digest] = exe
                _stats["disk_hits"] += 1
                return AotResult(fn=exe, cache_hit="disk",
                                 compile_secs=0.0, digest=digest)
            except Exception:  # noqa: BLE001 - any bad entry → recompile
                # classified faults included: a cache problem must never
                # break (or retry-loop) the run it was meant to speed up
                _stats["load_failures"] += 1
                _remove_quietly(path)

    exe, secs = _fresh_compile(fn, example_args, jit_kwargs)
    _stats["misses"] += 1
    _memo[digest] = exe

    if d is not None:
        try:
            from jax.experimental.serialize_executable import serialize
            payload, in_tree, out_tree = serialize(exe)
            blob = pickle.dumps({
                "schema": SCHEMA, "key": repr(key), "fingerprint": fp,
                "payload": payload, "in_tree": in_tree,
                "out_tree": out_tree})
            guarded_call(_write_atomic, entry_path(digest, d), blob,
                         site="cache.store")
            _stats["stores"] += 1
            _evict(d)
        except Exception:  # noqa: BLE001 - persistence is best-effort
            _stats["store_failures"] += 1

    return AotResult(fn=exe, cache_hit=None, compile_secs=secs,
                     digest=digest)
