"""yask_tpu.cache — the persistent AOT compile cache.

Every executable in the framework is built through
:func:`aot_compile` (jit step scans, pallas chunks, shard twins,
tuner candidates); ``tools/repo_lint.py``'s COMPILE-DIRECT rule fails
any ``.lower(...).compile()`` chain outside this package.  See
``compile_cache`` for the design and ``docs/performance.md``
("compile amortization") for the model.
"""

from yask_tpu.cache.compile_cache import (AotResult, DEFAULT_MAX_ENTRIES,
                                          SCHEMA, aot_compile,
                                          backend_fingerprint, cache_dir,
                                          clear_memo, entry_path,
                                          iter_entries, key_digest,
                                          max_entries, reset_stats,
                                          stats)

__all__ = ["AotResult", "DEFAULT_MAX_ENTRIES", "SCHEMA", "aot_compile",
           "backend_fingerprint", "cache_dir", "clear_memo",
           "entry_path", "iter_entries", "key_digest", "max_entries",
           "reset_stats", "stats"]
