"""Alias package: the framework's "models" are stencil solutions.

In an ML framework this directory would hold model families; in a stencil
framework the equivalent artifact is the solution library — seismic
(iso3dfd, ssg/fsg, awp, tti), 2-D physics (wave2d, swe2d), filters, and
the feature-coverage test fixtures. They live in
:mod:`yask_tpu.stencils`; this alias re-exports the registry for
discoverability.
"""

from yask_tpu.stencils import *  # noqa: F401,F403
from yask_tpu.compiler.solution_base import (  # noqa: F401
    create_solution,
    get_registered_solutions,
)
