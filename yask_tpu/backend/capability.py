"""Backend capability table — THE single legality/layout oracle.

YASK's compiler owes its portability to one discipline: every
target's legality facts (vector fold shapes, alignment, intrinsics)
live in one target description that both code generation and
validation consult.  This module is the TPU-era equivalent: a frozen,
versioned table (schema ``yask_tpu.capability/1``) encoding what was
**probed on real hardware** (v5e, round 3 — see CLAUDE.md "Mosaic TC
rules"), consumed by every layer that used to bake the same numbers in
as module constants:

* ``lowering.tpu_tile_dims`` / ``VarGeom`` pad math — :meth:`tile_dims`;
* ``tile_planner.sublane_count`` / ``plan_blocks`` — :meth:`sublane_count`
  and :meth:`tile_cells`;
* ``pallas_stencil.vmem_limit_bytes`` / ``default_vmem_budget`` —
  :meth:`vmem_limit_bytes` and :meth:`plan_budget_bytes`;
* the auto-tuner's VMEM ladder — :attr:`vmem_ladder_mib`;
* the checker's ``mosaic`` / ``vmem`` passes — the same accessors, so
  the static model *cannot* drift from the runtime.

``tools/repo_lint.py``'s ``CAP-CONST`` rule flags raw lane/sublane/
VMEM-byte literals re-appearing in those modules; this file is the
only sanctioned home for them.  ``tools/checker_conformance.py``
differentially tests that the checker's static verdicts match what the
runtime actually does for randomized solutions.

Entries:

* ``tpu:v5e`` — the probed Mosaic TensorCore rules.
* ``cpu:interpret`` — the Pallas interpret-mode host.  It DELIBERATELY
  carries the TPU's legality facts (round-8 invariant: a CPU-host
  check must answer for Mosaic), differing only in the planning-budget
  default (VMEM is emulated under interpret; a loose budget only
  shapes planning).

Extension recipe (what a ``pallas:triton`` entry would fill in) is in
``docs/checking.md``.
"""

from __future__ import annotations

import os
from dataclasses import asdict, dataclass, field
from typing import Dict, Optional, Tuple

SCHEMA = "yask_tpu.capability/1"

#: env override for the default backend entry (tests / future targets)
_ENV_KNOB = "YT_BACKEND"


@dataclass(frozen=True)
class BackendCapability:
    """Legality + layout facts of one execution backend.

    Frozen: a capability is data, not policy — consumers derive their
    decisions from it but never mutate it.  All ``*_mib`` fields are
    MiB (the probed numbers are round MiB values); byte values come
    from the accessor methods.
    """

    #: registry key, e.g. ``"tpu:v5e"``
    name: str
    #: coarse family: ``"tpu"`` (real Mosaic) or ``"cpu"`` (interpret)
    kind: str

    # ---- register/DMA tiling (probed v5e, round 3) -------------------
    #: lane (last physical axis) tile extent — every dtype
    lane_tile: int = 128
    #: bytes per sublane tile row: sublane extent scales with element
    #: width (32 B ⇒ 8 for f32, 16 for bf16)
    sublane_tile_bytes: int = 32
    #: floor for the planner's sublane fold unit (f64's 4-row DMA tile
    #: still plans blocks in 8-row folds)
    min_sublane_fold: int = 8
    #: DMA windows on HBM/ANY refs need lane-tile-multiple sizes AND
    #: offsets (a full-extent slice of a non-multiple lane total is
    #: itself unaligned: physical tiled layout ≠ logical extent)
    dma_tile_aligned: bool = True
    #: misc axes must be physically FIRST (the trailing two axes belong
    #: to the sublane×lane tiling)
    misc_axes_first: bool = True
    #: only the solution-minor domain dim may ride the lane axis of a
    #: DMA-windowed var (anything else needs pid-dependent non-aligned
    #: offsets → pallas fallback)
    minor_dim_lane_only: bool = True
    #: no-domain-dim vars ride SMEM with static scalar reads
    smem_scalars: bool = True
    #: skew/trapezoid write-back windows on the sublane axis must stay
    #: sublane-tile aligned (shifted output DMAs)
    sublane_aligned_writes: bool = True

    # ---- in-kernel op vocabulary (Mosaic TC rejections, probed) ------
    #: op classes the kernel generator must never emit (static region
    #: inserts go through lax.pad + broadcasted_iota masks instead)
    banned_kernel_ops: Tuple[str, ...] = (
        "dynamic_update_slice", "scatter", "sort", "gather",
        "1d_iota_on_lane_axis",
    )
    #: expression-node vocabulary the in-kernel evaluator can lower
    #: with legal patterns (the checker's MOSAIC-KERNEL-OPS rule)
    kernel_expr_nodes: Tuple[str, ...] = (
        "ConstExpr", "VarPoint", "IndexExpr", "FirstIndexExpr",
        "LastIndexExpr", "NegExpr", "AddExpr", "MultExpr", "SubExpr",
        "DivExpr", "ModExpr", "FuncExpr", "CompExpr", "AndExpr",
        "OrExpr", "NotExpr", "EqualsExpr",
    )

    # ---- VMEM (probed v5e, rounds 3/5) -------------------------------
    #: Mosaic's default scoped VMEM limit before CompilerParams raises it
    vmem_default_scope_mib: int = 16
    #: probed usable scoped VMEM (v5e takes ≥ this)
    vmem_probed_mib: int = 120
    #: cap for the requested scoped limit (safely below the probed
    #: 120..128 range)
    vmem_limit_cap_mib: int = 128
    #: live SSA values ≈ this many copies of the tiles (the round-3
    #: register-spill OOM model)
    vmem_live_multiplier: int = 2
    #: default planning TILE budget: live_multiplier × budget must fit
    #: the scoped limit, so the model budgets half the cap
    plan_budget_mib: int = 64
    #: the auto-tuner's VMEM-budget ladder rungs
    vmem_ladder_mib: Tuple[int, ...] = (64, 96, 120)

    #: free-form provenance notes (probe round, hardware)
    notes: Dict[str, str] = field(default_factory=dict)

    # ---- derived accessors -------------------------------------------

    def tile_dims(self, dtype) -> Tuple[int, int]:
        """(sublane, lane) DMA/register tile extents of the last two
        physical axes for ``dtype`` (8×128 for f32, 16×128 for bf16).
        THE single definition behind ``lowering.tpu_tile_dims``."""
        import numpy as np
        esize = np.dtype(dtype).itemsize
        sub = max(1, self.sublane_tile_bytes // max(1, esize))
        return sub, self.lane_tile

    def sublane_count(self, dtype) -> int:
        """The planner's sublane fold unit for ``dtype``: the DMA
        sublane tile, floored at :attr:`min_sublane_fold` (f64's 4-row
        tile still folds in 8s)."""
        return max(self.min_sublane_fold, self.tile_dims(dtype)[0])

    def tile_cells(self, dtype) -> int:
        """Cells per vector register tile (sublane fold × lane)."""
        return self.sublane_count(dtype) * self.lane_tile

    def vmem_limit_bytes(self, vmem_budget: int) -> int:
        """Scoped Mosaic VMEM limit requested for a tile budget:
        live_multiplier × budget (live SSA values ≈ extra tile copies),
        capped below the probed ceiling.  THE single definition the
        kernel's CompilerParams and the checker's spill model share."""
        return int(min(self.vmem_limit_cap_mib * 2 ** 20,
                       self.vmem_live_multiplier * vmem_budget))

    def plan_budget_bytes(self) -> int:
        """Default Pallas tile-planning budget (the ``-vmem_mb`` knob
        overrides)."""
        return self.plan_budget_mib * 2 ** 20

    def vmem_ladder_bytes(self) -> Tuple[int, ...]:
        return tuple(mb * 2 ** 20 for mb in self.vmem_ladder_mib)

    def to_json(self) -> dict:
        """Schema-stamped dict (``yask_tpu.capability/1``)."""
        out = {"schema": SCHEMA}
        out.update(asdict(self))
        return out


_REGISTRY: Dict[str, BackendCapability] = {}


def register_capability(cap: BackendCapability) -> BackendCapability:
    """Register a backend entry (the extension point: a new target is
    a table entry plus — at most — a new kernel emitter, never edits
    to the planner/checker constants)."""
    if cap.name in _REGISTRY:
        raise ValueError(f"duplicate backend capability '{cap.name}'")
    _REGISTRY[cap.name] = cap
    return cap


def backend_names() -> Tuple[str, ...]:
    return tuple(sorted(_REGISTRY))


#: the probed v5e TensorCore rules — every number here has hardware
#: provenance (CLAUDE.md "Mosaic TC rules", docs/checking.md)
TPU_V5E = register_capability(BackendCapability(
    name="tpu:v5e", kind="tpu",
    notes={"provenance": "probed on v5e, rounds 3-5",
           "vmem": "scoped limit raised via CompilerParams; >=120 MiB "
                   "usable; live SSA values ~double tile usage"},
))

#: Pallas interpret mode on a CPU host.  Legality facts DELIBERATELY
#: model the TPU (round-8 invariant: a CPU-host check must answer for
#: Mosaic); only the planning budget is looser — VMEM is emulated, the
#: budget only shapes planning.
CPU_INTERPRET = register_capability(BackendCapability(
    name="cpu:interpret", kind="cpu",
    plan_budget_mib=100,
    notes={"provenance": "mirror of tpu:v5e legality by design",
           "vmem": "emulated; budget shapes planning only"},
))


def get_capability(name: Optional[str] = None) -> BackendCapability:
    """THE accessor every consumer reads the table through.

    ``name`` picks an entry; ``None`` resolves ``YT_BACKEND`` and
    falls back to ``tpu:v5e`` — legality questions always answer for
    the real target, even on a CPU host (checker invariant)."""
    key = name or os.environ.get(_ENV_KNOB) or "tpu:v5e"
    try:
        return _REGISTRY[key]
    except KeyError:
        raise KeyError(
            f"unknown backend capability '{key}'; registered: "
            f"{', '.join(backend_names())}") from None


def capability_for_platform(platform: str) -> BackendCapability:
    """Map a jax platform string to its capability entry (``tpu`` and
    the axon relay alias → ``tpu:v5e``; anything else plans as the
    interpret host)."""
    return get_capability(
        "tpu:v5e" if platform in ("tpu", "axon") else "cpu:interpret")
