"""yask_tpu.backend — per-backend capability tables.

The single place where a target's legality and layout facts live
(tile shapes, DMA alignment, banned in-kernel ops, VMEM limits).
Everything that generates, plans, or checks device code reads these
facts through :func:`yask_tpu.backend.capability.get_capability` —
never from module-local constants — so the static checker and the
runtime can never drift apart.  See ``docs/checking.md`` ("Backend
capability table") for the schema and the backend-extension recipe.
"""

from yask_tpu.backend.capability import (  # noqa: F401
    SCHEMA,
    BackendCapability,
    backend_names,
    capability_for_platform,
    get_capability,
    register_capability,
)
