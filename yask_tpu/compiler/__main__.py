"""Compiler driver CLI.

Counterpart of the reference's ``yask_compiler.exe``
(``src/compiler/compiler_main.cpp:158``): pick a registered stencil, set
radius/target, run ``define()``, and write the output artifact — here a
pseudo/dot/py-api text or (for TPU targets) the generated Python module
that rebuilds the solution.

Usage::

    python -m yask_tpu.compiler -stencil iso3dfd -radius 8 -target pseudo -p -
    python -m yask_tpu.compiler -stencil ssg -target py-api -p ssg_gen.py
    python -m yask_tpu.compiler -list
"""

from __future__ import annotations

import sys
from typing import List, Optional

from yask_tpu.utils.cli import CommandLineParser
from yask_tpu.utils.exceptions import YaskException
from yask_tpu.utils.output import yask_output_factory


class CompilerCLISettings:
    def __init__(self):
        self.stencil = ""
        self.radius = 0
        self.target = "pseudo"
        self.path = "-"
        self.elem_bytes = 4
        self.fold = ""
        self.list_stencils = False
        self.help = False

    def add_options(self, p: CommandLineParser):
        p.add_string_option("stencil", "Registered stencil name.",
                            self, "stencil")
        p.add_int_option("radius", "Stencil radius (0 = default).",
                         self, "radius")
        from yask_tpu.compiler.solution import ALL_TARGETS
        p.add_string_option(
            "target", "Output target: " + "|".join(ALL_TARGETS) + ".",
            self, "target")
        p.add_string_option("p", "Output path ('-' = stdout).",
                            self, "path")
        p.add_int_option("elem-bytes", "FP element size (2|4|8).",
                         self, "elem_bytes")
        p.add_string_option("fold", "Tile-shape hint 'x=8,y=128'.",
                            self, "fold")
        p.add_bool_option("list", "List registered stencils.",
                          self, "list_stencils")
        p.add_bool_option("help", "Print help.", self, "help")


def run_compiler(argv: Optional[List[str]] = None, out=None) -> int:
    out = out or sys.stdout
    opts = CompilerCLISettings()
    p = CommandLineParser()
    opts.add_options(p)
    rest = p.parse_args(list(argv if argv is not None else sys.argv[1:]))
    if opts.help:
        p.print_help(out)
        return 0
    from yask_tpu.compiler.solution_base import (
        create_solution, get_registered_solutions)
    if opts.list_stencils:
        out.write("\n".join(get_registered_solutions()) + "\n")
        return 0
    if rest:
        raise YaskException(f"unrecognized options: {' '.join(rest)}")
    if not opts.stencil:
        out.write("error: -stencil <name> required; -list to enumerate.\n")
        return 2
    sb = create_solution(opts.stencil, radius=opts.radius or None)
    soln = sb.get_soln()
    soln.set_target(opts.target)
    soln.set_element_bytes(opts.elem_bytes)
    if opts.fold:
        from yask_tpu.utils.idx_tuple import parse_dim_val_str
        for d, v in parse_dim_val_str(opts.fold).items():
            soln.set_fold_len(d, v)
    fac = yask_output_factory()
    sink = fac.new_stdout_output() if opts.path == "-" \
        else fac.new_file_output(opts.path)
    soln.output_solution(sink)
    if opts.path != "-":
        sink.close()
        out.write(f"wrote {opts.target} output for '{opts.stencil}' "
                  f"to {opts.path}\n")
    return 0


def main() -> None:  # pragma: no cover - thin wrapper
    try:
        sys.exit(run_compiler())
    except YaskException as e:
        sys.stderr.write(f"error: {e}\n")
        sys.exit(2)


if __name__ == "__main__":
    main()
