"""Solution base classes and the stencil registry.

Counterpart of ``yc_solution_base`` / ``yc_solution_with_radius_base`` and the
``REGISTER_SOLUTION`` static-registration mechanism
(``include/aux/yc_solution_api.hpp:57,246``): stencil definitions subclass a
base, implement ``define()``, and register by name so the CLI/harness can
instantiate them (``src/compiler/compiler_main.cpp:181``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Type

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.compiler.node_api import yc_node_factory
from yask_tpu.compiler.solution import yc_factory, yc_solution


_REGISTRY: Dict[str, Type["yc_solution_base"]] = {}


def register_solution(cls: Type["yc_solution_base"]):
    """Class decorator: the Python spelling of ``REGISTER_SOLUTION``.

    Registration is keyed by the name the class passes to the base
    constructor; we instantiate once lazily to learn it, matching the
    reference where construction-time static objects self-register."""
    probe = cls()
    name = probe.get_soln().get_name()
    if name in _REGISTRY:
        raise YaskException(f"duplicate registered solution '{name}'")
    _REGISTRY[name] = cls
    return cls


def get_registered_solutions() -> List[str]:
    """Names of all registered stencils (``compiler_main`` list support)."""
    _ensure_library_loaded()
    return sorted(_REGISTRY)


def create_solution(name: str, radius: Optional[int] = None,
                    **kwargs) -> "yc_solution_base":
    """Instantiate a registered stencil, optionally setting the radius, and
    run its ``define()`` (what ``compiler_main.cpp:181-204`` does)."""
    _ensure_library_loaded()
    if name not in _REGISTRY:
        raise YaskException(
            f"unknown stencil '{name}'; known: {', '.join(sorted(_REGISTRY))}")
    obj = _REGISTRY[name](**kwargs)
    if radius is not None:
        if not isinstance(obj, yc_solution_with_radius_base):
            raise YaskException(f"stencil '{name}' takes no radius")
        if not obj.set_radius(radius):
            raise YaskException(f"invalid radius {radius} for '{name}'")
    obj.run_define()
    return obj


def _ensure_library_loaded() -> None:
    # Importing the library package runs all @register_solution decorators.
    import yask_tpu.stencils  # noqa: F401


class yc_solution_base:
    """Base class for stencil definitions (``yc_solution_base``)."""

    def __init__(self, name: str):
        self._soln = yc_factory().new_solution(name)
        self._nfac = yc_node_factory()
        self._defined = False

    @staticmethod
    def get_registry():
        """Names of registered stencil solutions (the reference's
        ``yc_solution_base::get_registry`` over its static factory
        list)."""
        return get_registered_solutions()

    def __init_subclass__(cls, **kwargs):
        """Wrap each subclass's ``define()`` so ANY successful call —
        including a user calling ``s.define()`` directly before handing
        the object to the runtime — marks the solution defined. This is
        what lets ``run_define`` key purely off the flag/equations
        without re-running ``define()`` (which would raise duplicate-var
        for vars-only solutions) and without mistaking constructor-made
        vars for a completed definition (ADVICE r2: the reference's
        canonical vars-in-constructor pattern must still run define)."""
        super().__init_subclass__(**kwargs)
        if "define" in cls.__dict__:
            import functools
            orig = cls.__dict__["define"]

            @functools.wraps(orig)
            def define(self, *a, **kw):
                r = orig(self, *a, **kw)
                self._defined = True
                return r
            cls.define = define

    def run_define(self) -> None:
        """Run ``define()`` exactly once. Only prior *equations* (or the
        explicit flag) count as already-defined: vars alone must not —
        the reference's canonical pattern creates vars in the
        constructor and equations in ``define()`` (Iso3dfdStencil's
        MAKE_VAR members), and treating those vars as "defined" would
        silently skip ``define()`` and run a no-op solution. Legal
        zero-equation solutions (test_empty family) are covered by the
        flag, set after their (empty-ish) ``define()`` runs."""
        if self._defined or self._soln.get_num_equations() > 0:
            self._defined = True
            return
        self.define()
        self._defined = True

    def get_soln(self) -> yc_solution:
        return self._soln

    def get_node_factory(self) -> yc_node_factory:
        return self._nfac

    def define(self) -> None:
        raise YaskException(
            f"solution '{self._soln.get_name()}' does not define equations")

    # Convenience index/var helpers used heavily by the stencil library.
    def new_step_index(self, name: str):
        return self._soln.new_step_index(name)

    def new_domain_index(self, name: str):
        return self._soln.new_domain_index(name)

    def new_misc_index(self, name: str):
        return self._soln.new_misc_index(name)

    def new_var(self, name, dims):
        return self._soln.new_var(name, dims)

    def new_scratch_var(self, name, dims):
        return self._soln.new_scratch_var(name, dims)

    def first_domain_index(self, dim):
        return self._nfac.new_first_domain_index(dim)

    def last_domain_index(self, dim):
        return self._nfac.new_last_domain_index(dim)


class yc_solution_with_radius_base(yc_solution_base):
    """Radius-parameterized base (``yc_solution_with_radius_base``): the
    radius scales the FD order (order = 2 × radius for center forms)."""

    def __init__(self, name: str, radius: int = 1):
        super().__init__(name)
        self._radius = 0
        self.set_radius(radius)

    def set_radius(self, radius: int) -> bool:
        ok = radius >= 1
        self._radius = max(radius, 1)
        # Changing radius invalidates previously-built equations.
        self._soln.clear_equations()
        return ok

    def get_radius(self) -> int:
        return self._radius
