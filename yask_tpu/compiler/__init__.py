"""Stencil DSL compiler: AST → analysis → TPU lowering.

TPU-native counterpart of the reference's ``src/compiler`` layer: the same
pipeline (expression AST, equation validity/dependency analysis, partitioning
into parts and stages, halo calculation) but the code generators emit JAX/XLA
computations and Pallas kernels instead of intrinsic C++ source text
(``src/compiler/lib/Solution.cpp:241-259`` picks printers; here
``yc_solution.output_solution``/``compile`` picks lowering targets).
"""

from yask_tpu.compiler.expr import (
    ConstExpr,
    IndexExpr,
    IndexType,
    NumExpr,
    VarPoint,
    EqualsExpr,
)
from yask_tpu.compiler.var import Var
from yask_tpu.compiler.solution import yc_solution, yc_factory
from yask_tpu.compiler.solution_base import (
    yc_solution_base,
    yc_solution_with_radius_base,
    register_solution,
    get_registered_solutions,
)
from yask_tpu.compiler.node_api import yc_node_factory

__all__ = [
    "ConstExpr", "IndexExpr", "IndexType", "NumExpr", "VarPoint",
    "EqualsExpr", "Var", "yc_solution", "yc_factory", "yc_solution_base",
    "yc_solution_with_radius_base", "register_solution",
    "get_registered_solutions", "yc_node_factory",
]
