"""Lowering: analyzed solutions → executable TPU step programs.

This is the TPU analog of the reference's code generators
(``src/compiler/lib/Cpp.cpp``, ``YaskKernel.cpp``): where the reference emits
intrinsic C++ for nano/pico loops, we build a *traced JAX computation* for a
whole step — XLA then performs the fusion/tiling the reference does by hand.

Key representation choices (each mirrors a reference mechanism):

* **Ring-buffer state.** A var with step dim and step-alloc ``A``
  (``calc_lifespans``, ``Eqs.cpp:1912``) is a list of ``A`` padded arrays
  holding steps ``[t-A+1 … t]``. Writing step ``t+1`` re-uses the evicted
  oldest buffer (the reference's step-index wrapping, ``yk_var.hpp:820``),
  which under ``lax.scan`` + donation is a true in-place rotation.
* **Padded storage + static slices.** Arrays carry left/right pads ≥ halo
  (``update_var_info``, ``setup.cpp:666``); every stencil read is a *static*
  slice of a padded array, which XLA fuses into one loop per part.
* **Masked writes.** Sub-domain/step conditions (``IF_DOMAIN``/``IF_STEP``)
  lower to ``where`` against the evicted buffer's contents, reproducing the
  reference semantics that unwritten points retain stale slot data.
* **Scratch vars** are materialized per step over the domain *expanded by
  their write-halo* (``find_scratch_write_halos``, ``setup.cpp:1044``) and
  die at step end — they never enter the carried state.
* **Array-backend abstraction.** The same lowering executes under numpy
  (eager, independent oracle — the analog of ``run_ref``/``-validate``,
  ``context.cpp:46``) or jnp/XLA (the optimized path).
"""

from __future__ import annotations

import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.utils.idx_tuple import IdxTuple
from yask_tpu.compiler.expr import (
    AddExpr,
    AndExpr,
    CompExpr,
    ConstExpr,
    DivExpr,
    EqualsExpr,
    Expr,
    FirstIndexExpr,
    FuncExpr,
    IndexExpr,
    IndexType,
    LastIndexExpr,
    ModExpr,
    MultExpr,
    NegExpr,
    NotExpr,
    NumExpr,
    OrExpr,
    SubExpr,
    VarPoint,
)
from yask_tpu.compiler.analysis import SolutionAnalysis, Part, Stage


# ---------------------------------------------------------------------------
# array-backend adapters
# ---------------------------------------------------------------------------


class ArrayOps:
    """Minimal array-op surface needed by the evaluator."""

    name = "abstract"

    def update(self, arr, idx, val):
        raise NotImplementedError

    def index_array(self, start: int, stop: int, dtype):
        raise NotImplementedError

    def where(self, c, a, b):
        raise NotImplementedError

    def broadcast_to(self, v, shape):
        raise NotImplementedError

    def full(self, shape, val, dtype):
        raise NotImplementedError

    def func(self, name: str, args):
        raise NotImplementedError

    def logical(self, op: str, a, b=None):
        raise NotImplementedError

    def asdtype(self, v, dtype):
        raise NotImplementedError


class JnpOps(ArrayOps):
    name = "jnp"

    def __init__(self):
        import jax.numpy as jnp
        import jax.scipy.special as jsp
        self.jnp = jnp
        self._funcs = {
            "sqrt": jnp.sqrt, "cbrt": jnp.cbrt, "fabs": jnp.abs,
            "erf": jsp.erf, "exp": jnp.exp, "log": jnp.log,
            "atan": jnp.arctan, "sin": jnp.sin, "cos": jnp.cos,
            "tan": jnp.tan, "asin": jnp.arcsin, "acos": jnp.arccos,
            "pow": jnp.power, "max": jnp.maximum, "min": jnp.minimum,
        }

    def update(self, arr, idx, val):
        return arr.at[idx].set(val)

    def index_array(self, start, stop, dtype):
        return self.jnp.arange(start, stop, dtype=self.jnp.int32)

    def where(self, c, a, b):
        return self.jnp.where(c, a, b)

    def broadcast_to(self, v, shape):
        return self.jnp.broadcast_to(v, shape)

    def full(self, shape, val, dtype):
        return self.jnp.full(shape, val, dtype=dtype)

    def func(self, name, args):
        return self._funcs[name](*args)

    def logical(self, op, a, b=None):
        if op == "and":
            return self.jnp.logical_and(a, b)
        if op == "or":
            return self.jnp.logical_or(a, b)
        return self.jnp.logical_not(a)

    def asdtype(self, v, dtype):
        return self.jnp.asarray(v, dtype=dtype)


class NumpyOps(ArrayOps):
    """Eager numpy execution — the independent validation oracle (the role
    of the reference's scalar ``run_ref`` context, ``context.cpp:46``)."""

    name = "numpy"

    def __init__(self):
        import numpy as np
        self.np = np
        try:
            from scipy.special import erf as _erf  # scipy ships with jax
        except Exception:  # pragma: no cover
            _erf = np.vectorize(math.erf)
        self._funcs = {
            "sqrt": np.sqrt, "cbrt": np.cbrt, "fabs": np.abs,
            "erf": _erf, "exp": np.exp, "log": np.log,
            "atan": np.arctan, "sin": np.sin, "cos": np.cos,
            "tan": np.tan, "asin": np.arcsin, "acos": np.arccos,
            "pow": np.power, "max": np.maximum, "min": np.minimum,
        }

    def update(self, arr, idx, val):
        out = arr.copy()
        out[idx] = val
        return out

    def index_array(self, start, stop, dtype):
        return self.np.arange(start, stop, dtype=self.np.int32)

    def where(self, c, a, b):
        return self.np.where(c, a, b)

    def broadcast_to(self, v, shape):
        return self.np.broadcast_to(v, shape)

    def full(self, shape, val, dtype):
        return self.np.full(shape, val, dtype=dtype)

    def func(self, name, args):
        r = self._funcs[name](*args)
        # numpy promotes float32 scalars/arrays to float64 in some funcs;
        # keep the caller responsible for final dtype.
        return r

    def logical(self, op, a, b=None):
        if op == "and":
            return self.np.logical_and(a, b)
        if op == "or":
            return self.np.logical_or(a, b)
        return self.np.logical_not(a)

    def asdtype(self, v, dtype):
        return self.np.asarray(v, dtype=dtype)


# ---------------------------------------------------------------------------
# var geometry
# ---------------------------------------------------------------------------


def tpu_tile_dims(dtype) -> Tuple[int, int]:
    """(sublane, lane) tile extents of the last two physical axes for
    ``dtype`` (8×128 for f32, 16×128 for bf16) — read from the backend
    capability table so VarGeom's allocation alignment, the pallas DMA
    slab planner, and the checker all consult ONE definition."""
    from yask_tpu.backend import get_capability
    return get_capability().tile_dims(dtype)


class VarGeom:
    """Array geometry for one var: axis order, pads, step allocation —
    the lowered analog of the reference's per-var halo/pad/alloc geometry
    (``yk_var.hpp`` geometry accessors)."""

    def __init__(self, var, ana: SolutionAnalysis, sizes: IdxTuple,
                 extra_pad: Dict[str, Tuple[int, int]],
                 pad_multiple: Optional[Dict[str, int]] = None,
                 dtype="float32", mosaic_align: bool = True):
        self.var = var
        self.name = var.get_name()
        self.has_step = var.step_dim() is not None
        self.alloc = var.get_step_alloc_size() if self.has_step else 1
        self.is_written = var.is_written
        self.is_scratch = var.is_scratch()

        # Physical axis order: misc axes FIRST, then domain axes in
        # declared order, step dim removed (step → list position). TPU
        # tiled HBM layouts constrain the last two physical axes
        # (sublane×lane tiles), so domain dims must own them: small misc
        # extents on the lane dim would force 128× over-padding, and the
        # pallas DMA slab rules (see ops/pallas_stencil.py) only hold for
        # domain windows.
        self.axes: List[Tuple[str, str]] = []  # (dim name, kind)
        doms: List[Tuple[str, str]] = []
        for d in var.get_dims():
            if d.type == IndexType.STEP:
                continue
            if d.type.value == "misc":
                self.axes.append((d.name, d.type.value))
            else:
                doms.append((d.name, d.type.value))
        self.axes += doms

        self.domain_dims = [n for n, k in self.axes if k == "domain"]
        self.misc_lo: Dict[str, int] = {}
        self.misc_ext: Dict[str, int] = {}   # DECLARED extent (pre-pad)
        self.shape: List[int] = []
        self.origin: Dict[str, int] = {}   # pad_left per domain dim
        self.pads: Dict[str, Tuple[int, int]] = {}

        # TPU tiling of the last two physical axes: lane tile is 128 for
        # every dtype, sublane tile scales with element width (8 for f32,
        # 16 for bf16). Mosaic DMA windows on tiled memrefs must have
        # tile-aligned sizes and offsets (probed on v5e), so allocations
        # keep lane totals 128-divisible, sublane origins/totals
        # 8-divisible, and sublane right pads carry slack for slab
        # rounding. ``mosaic_align`` applies the rounding — required for
        # the Pallas manual-DMA paths, pure waste on the XLA/ref paths
        # (XLA handles any extent; at 128^3 r=8 the lane round-up alone
        # is +78% footprint and cost the r3 headline 1.8x — VERDICT r3
        # item 4).
        sub_t, lane_t = tpu_tile_dims(dtype)
        nax = len(self.axes)
        lane_ax = nax - 1 if mosaic_align else -99
        sub_ax = nax - 2 if mosaic_align else -99

        def _lcm(a: int, b: int) -> int:
            import math as _m
            return a * b // _m.gcd(a, b)

        wh = ana.scratch_write_halo.get(self.name, {})
        for ai, (n, k) in enumerate(self.axes):
            if k == "domain":
                hl, hr = var.halo.get(n, (0, 0))
                el, er = extra_pad.get(n, (0, 0))
                wl, wr = wh.get(n, (0, 0))
                pl, pr = hl + wl + el, hr + wr + er
                # Round the allocation up so the padded extent is divisible
                # (sharded mode needs whole-array divisibility; the analog
                # of the reference rounding allocs to vector multiples).
                mult = (pad_multiple or {}).get(n, 1)
                if ai == lane_ax:
                    mult = _lcm(max(mult, 1), lane_t)
                elif ai == sub_ax:
                    pl += (-pl) % sub_t          # aligned origin
                    pr += 2 * sub_t              # slab-rounding slack
                    mult = _lcm(max(mult, 1), sub_t)
                if mult > 1:
                    pr += (-(sizes[n] + pl + pr)) % mult
                self.pads[n] = (pl, pr)
                self.origin[n] = pl
                self.shape.append(sizes[n] + pl + pr)
            else:  # misc
                lo, hi = var.misc_range.get(n, (0, 0))
                self.misc_lo[n] = lo
                ext = hi - lo + 1
                self.misc_ext[n] = ext
                # misc axes in the tiled (last-two) positions only occur
                # on vars WITH domain dims (a single-domain-dim var keeps
                # misc at its sublane) — those are DMA'd whole, so the
                # extent must be tile-aligned. Vars with no domain dims
                # ride SMEM on the pallas path and stay unpadded.
                if self.domain_dims:
                    if ai == lane_ax:
                        ext += (-ext) % lane_t
                    elif ai == sub_ax:
                        ext += (-ext) % sub_t
                self.shape.append(ext)

    @property
    def num_slots(self) -> int:
        """Ring slots allocated in state: write-back-optimized alloc for
        written step vars, one slot otherwise. THE single definition —
        shard_map in_specs, pallas ring handling, and tile planning must
        all agree with ``alloc_state`` or the shard pytree structure
        desynchronizes from the state rings at trace time."""
        return self.alloc if (self.has_step and self.is_written) else 1

    def axis_of(self, dim: str) -> int:
        for i, (n, _) in enumerate(self.axes):
            if n == dim:
                return i
        raise YaskException(f"var '{self.name}' has no dim '{dim}'")


# ---------------------------------------------------------------------------
# step program
# ---------------------------------------------------------------------------


class StepProgram:
    """An executable step function for fixed domain sizes.

    ``state`` is ``{var_name: [array, ...]}`` where the list is the
    step-ring (oldest→newest; length = step-alloc; length 1 for stepless
    vars). ``step(state, t)`` returns the new state after one step.
    """

    def __init__(self, csol: "CompiledSolution", sizes: IdxTuple,
                 extra_pad: Optional[Dict[str, Tuple[int, int]]] = None,
                 ops: Optional[ArrayOps] = None,
                 rank_offset: Optional[Dict[str, int]] = None,
                 global_sizes: Optional[IdxTuple] = None,
                 pad_multiple: Optional[Dict[str, int]] = None,
                 mosaic_align: bool = True):
        self.csol = csol
        ana = self.ana = csol.ana
        self.soln = csol.soln
        self.sizes = sizes.copy()
        self.ops = ops or JnpOps()
        self.dtype = csol.dtype
        extra_pad = extra_pad or {}
        # Local-interior origin in global coordinates (0 on single device;
        # the shard offset under shard_map — reference rank offsets,
        # setup.cpp:169).
        self.rank_offset = dict(
            rank_offset or {d: 0 for d in self.ana.domain_dims})
        gsz = global_sizes if global_sizes is not None else sizes
        self.global_first = {d: 0 for d in ana.domain_dims}
        self.global_last = {d: gsz[d] - 1 for d in ana.domain_dims}

        self.mosaic_align = mosaic_align
        self.geoms: Dict[str, VarGeom] = {}
        for v in self.soln.get_vars():
            self.geoms[v.get_name()] = VarGeom(v, self.ana, sizes, extra_pad,
                                               pad_multiple,
                                               dtype=self.dtype,
                                               mosaic_align=mosaic_align)

        # Stage metadata for halo exchange / fused-tile margin accounting
        # (the dirty-width analog of the reference's per-var dirty flags,
        # yk_var.hpp:564; see SolutionAnalysis.stage_read_widths).
        # one equation scan: the union form derives from the split form
        self.stage_reads_split = self.ana.stage_read_widths_split()
        self.stage_reads = []
        for kinds in self.stage_reads_split:
            reads: Dict[str, Dict[str, Tuple[int, int]]] = {}
            for kind in ("ring", "computed"):
                for vname, widths in kinds[kind].items():
                    entry = reads.setdefault(vname, {})
                    for d, (l, r) in widths.items():
                        cl, cr = entry.get(d, (0, 0))
                        entry[d] = (max(cl, l), max(cr, r))
            self.stage_reads.append(reads)

    # -- state construction ------------------------------------------------

    def alloc_state(self, init: Optional[Dict[str, object]] = None):
        """Allocate the state dict; arrays zero-filled unless ``init``
        provides full padded arrays or callables(shape)->array."""
        import numpy as np
        state: Dict[str, List[object]] = {}
        for name, g in self.geoms.items():
            if g.is_scratch:
                continue
            nslots = g.num_slots
            arrs = []
            for _ in range(nslots):
                if init and name in init:
                    a = init[name]
                    a = a(tuple(g.shape)) if callable(a) else np.asarray(a)
                    if tuple(a.shape) != tuple(g.shape):
                        raise YaskException(
                            f"init for '{name}' has shape {a.shape}, "
                            f"expected {tuple(g.shape)}")
                    arrs.append(self.ops.asdtype(a, self.dtype))
                else:
                    arrs.append(self.ops.full(tuple(g.shape), 0.0, self.dtype))
            state[name] = arrs
        return state

    def hbm_bytes_per_point(self, fuse_steps: int = 1,
                            block: Optional[Dict[str, int]] = None,
                            skew=False
                            ) -> Tuple[float, float]:
        """Modeled HBM traffic per interior point per STEP as
        ``(read_bytes, write_bytes)`` — the roofline yardstick next to
        est-FLOPS (reference reads/writes-per-point report,
        ``soln_apis.cpp:536-551``, recast at array granularity: a fused
        XLA/Pallas step reads each live (var, ring-slot) array once and
        writes each produced slot once; scratch vars never leave VMEM).
        ``fuse_steps``/``block`` model the pallas K-group: reads pay the
        tile-halo overlap factor and amortize over K.  ``skew`` models
        the streaming skewed wavefront: each skewed blocked dim fetches
        (K+1)·r + E of margin instead of 2·K·r (the inter-tile strips
        ride the VMEM carry).  Accepts the legacy bool (True = the
        innermost blocked dim) or the per-dim form — a collection of
        dim names, as reported by ``chunk.tiling['skew_dims']``."""
        import numpy as np
        esize = np.dtype(self.dtype).itemsize
        dompts = 1
        for d in self.ana.domain_dims:
            dompts *= self.sizes[d]
        K = max(1, fuse_steps)
        rad = self.ana.fused_step_radius()
        lead = self.ana.domain_dims[:-1]
        sdim = lead[-1] if lead else None
        if isinstance(skew, (list, tuple, set, frozenset)):
            skew_dims = set(skew)
        else:
            skew_dims = {sdim} if (skew and sdim is not None) else set()
        rd = 0.0
        wr = 0.0
        for name, g in self.geoms.items():
            if g.is_scratch:
                continue
            cells = 1
            for ext in g.shape:
                cells *= ext
            # fused-tile halo overlap on the lead dims actually blocked
            ov = 1.0
            if block:
                num = den = 1.0
                for d in lead:
                    if d in g.domain_dims and block.get(d):
                        if d in skew_dims:
                            # only the sublane (stream) dim pays E_sk:
                            # misaligned radii add 2·sub_t of computed
                            # right margin (see pallas_stencil); outer
                            # skewed dims are untiled (E = 0)
                            r_ = rad.get(d, 0)
                            e_ = 0
                            if d == sdim:
                                sub_t = tpu_tile_dims(self.dtype)[0]
                                e_ = 2 * sub_t if r_ % sub_t else 0
                            num *= block[d] + (K + 1) * r_ + e_
                        else:
                            num *= block[d] + 2 * rad.get(d, 0) * K
                        den *= block[d]
                ov = num / max(den, 1.0)
            rd += g.num_slots * cells * ov
            if g.is_written:
                wr += min(K, g.num_slots) * cells
        return (esize * rd / (dompts * K), esize * wr / (dompts * K))

    # -- expression evaluation --------------------------------------------

    def _region_shape(self, region: Dict[str, Tuple[int, int]]) -> Tuple[int, ...]:
        return tuple(region[d][1] - region[d][0] for d in self.ana.domain_dims)

    def _read_point(self, p: VarPoint, region, state, computed, scratch_vals):
        """Slice a var access over ``region`` (coords relative to the local
        interior origin) into an array broadcast over the region shape."""
        g = self.geoms[p.var_name()]
        ofs = p.domain_offsets()
        misc = p.misc_vals()
        so = p.step_offset()

        # Choose the source array.
        if g.is_scratch:
            if p.var_name() not in scratch_vals:
                raise YaskException(
                    f"scratch var '{p.var_name()}' read before written")
            arr, sc_origin = scratch_vals[p.var_name()]
        else:
            ring = state[p.var_name()]
            if so is not None and g.has_step and g.is_written \
                    and so == self.ana.step_dir:
                # Reading the value being computed this step.
                if p.var_name() in computed:
                    arr = computed[p.var_name()]
                else:
                    raise YaskException(
                        f"'{p.var_name()}' read at the written step before "
                        "any equation computed it (ordering bug)")
            elif g.has_step and g.is_written:
                s = so if so is not None else 0
                # ring holds steps [t-A+1 .. t]; offset s ≤ 0 → index A-1+s
                # (mirrored for negative step_dir).
                idx = len(ring) - 1 + s * self.ana.step_dir
                if not (0 <= idx < len(ring)):
                    raise YaskException(
                        f"step offset {s} of '{p.var_name()}' outside its "
                        f"allocation {g.alloc}")
                arr = ring[idx]
            else:
                arr = ring[0]
            sc_origin = None

        # Build the index tuple in the var's axis order.
        idxs = []
        for n, kind in g.axes:
            if kind == "misc":
                idxs.append(misc[n] - g.misc_lo[n])
            else:
                a, b = region[n]
                o = ofs.get(n, 0)
                if sc_origin is not None:
                    base = sc_origin[n]
                else:
                    base = g.origin[n]
                lo = base + a + o
                hi = base + b + o
                if lo < 0 or hi > g.shape[g.axis_of(n)]:
                    raise YaskException(
                        f"read of '{p.var_name()}' dim {n} offset {o} over "
                        f"[{a},{b}) exceeds padded array (pad too small)")
                idxs.append(slice(lo, hi))
        out = arr[tuple(idxs)]

        # Broadcast into solution domain-dim order over the region.
        # out currently has one axis per var domain dim, in var order.
        tgt_shape = self._region_shape(region)
        var_ddims = [n for n, k in g.axes if k == "domain"]
        if var_ddims != self.ana.domain_dims:
            # transpose var order → solution order (of present dims),
            # then insert singleton axes for missing dims.
            present = [d for d in self.ana.domain_dims if d in var_ddims]
            perm = [var_ddims.index(d) for d in present]
            if perm != list(range(len(perm))):
                out = out.transpose(perm)
            shape = []
            k = 0
            for d in self.ana.domain_dims:
                if d in var_ddims:
                    shape.append(region[d][1] - region[d][0])
                    k += 1
                else:
                    shape.append(1)
            out = out.reshape(tuple(shape))
            out = self.ops.broadcast_to(out, tgt_shape)
        return out

    def _eval(self, e: Expr, region, t, state, computed, scratch_vals, memo):
        # Structural memo key: common subexpressions are traced once per
        # part even across equations (the reference's CSE pass,
        # ExprUtils.hpp:77, done here as hash-consing at eval time).
        key = e.skey()
        if key in memo:
            return memo[key]
        ops = self.ops
        ev = lambda x: self._eval(x, region, t, state, computed,
                                  scratch_vals, memo)
        if isinstance(e, ConstExpr):
            r = e.value
        elif isinstance(e, IndexExpr):
            if e.type == IndexType.STEP:
                r = t
            elif e.type == IndexType.DOMAIN:
                a, b = region[e.name]
                # rank_offset may be a traced scalar (lax.axis_index-derived
                # under shard_map), so keep the arange static and add it.
                off = self.rank_offset[e.name]
                iarr = ops.index_array(a, b, None)
                shape = [1] * len(self.ana.domain_dims)
                ax = self.ana.domain_dims.index(e.name)
                shape[ax] = b - a
                r = iarr.reshape(tuple(shape)) + off
            else:
                # A misc index used as a VALUE is the current equation's
                # pinned LHS misc index — a per-equation constant
                # (reference generated code inlines it). Never memoized:
                # the same node appears in sibling equations with
                # different LHS bindings.
                mv = getattr(self, "_cur_misc", None) or {}
                if e.name not in mv:
                    raise YaskException(
                        f"misc index '{e.name}' used as a value outside "
                        "an equation that pins it on the LHS")
                return mv[e.name]
        elif isinstance(e, FirstIndexExpr):
            r = self.global_first[e.dim.name]
        elif isinstance(e, LastIndexExpr):
            r = self.global_last[e.dim.name]
        elif isinstance(e, VarPoint):
            r = self._read_point(e, region, state, computed, scratch_vals)
        elif isinstance(e, NegExpr):
            r = -ev(e.arg)
        elif isinstance(e, AddExpr):
            r = ev(e.args[0])
            for a in e.args[1:]:
                r = r + ev(a)
        elif isinstance(e, MultExpr):
            r = ev(e.args[0])
            for a in e.args[1:]:
                r = r * ev(a)
        elif isinstance(e, SubExpr):
            r = ev(e.lhs) - ev(e.rhs)
        elif isinstance(e, DivExpr):
            r = ev(e.lhs) / ev(e.rhs)
        elif isinstance(e, ModExpr):
            r = ev(e.lhs) % ev(e.rhs)
        elif isinstance(e, FuncExpr):
            from yask_tpu.compiler.expr import paired_func_eval
            r = paired_func_eval(ops.func, e, [ev(a) for a in e.args],
                                 memo, getattr(self.ana, "sincos_args",
                                               ()))
        elif isinstance(e, CompExpr):
            a, b = ev(e.lhs), ev(e.rhs)
            r = {"==": lambda: a == b, "!=": lambda: a != b,
                 "<": lambda: a < b, "<=": lambda: a <= b,
                 ">": lambda: a > b, ">=": lambda: a >= b}[e.op]()
        elif isinstance(e, AndExpr):
            r = ops.logical("and", ev(e.lhs), ev(e.rhs))
        elif isinstance(e, OrExpr):
            r = ops.logical("or", ev(e.lhs), ev(e.rhs))
        elif isinstance(e, NotExpr):
            r = ops.logical("not", ev(e.arg))
        else:  # pragma: no cover
            raise YaskException(f"cannot evaluate node {type(e).__name__}")
        memo[key] = r
        return r

    # -- equation / part / stage evaluation -------------------------------

    def _interior_region(self) -> Dict[str, Tuple[int, int]]:
        return {d: (0, self.sizes[d]) for d in self.ana.domain_dims}

    def _to_var_layout(self, val, g: VarGeom, region):
        """Convert a value computed in solution domain-dim order over
        ``region`` into the target var's own axis order, dropping dims the
        var lacks (the RHS must be constant along those — index 0 taken)
        and transposing when the var declares its dims in another order."""
        shape = self._region_shape(region)
        val = self.ops.broadcast_to(val, shape)
        sol = self.ana.domain_dims
        var_dd = g.domain_dims
        if var_dd == sol:
            return val
        idx = tuple(slice(None) if d in var_dd else 0 for d in sol)
        val = val[idx]
        present = [d for d in sol if d in var_dd]
        perm = [present.index(d) for d in var_dd]
        if perm != list(range(len(perm))):
            val = val.transpose(perm)
        return val

    def _eval_part(self, part: Part, t, state, computed, scratch_vals,
                   over: Optional[Dict[str, Tuple[int, int]]] = None):
        """Evaluate a part; ``over`` restricts evaluation to a sub-region
        of the interior (interior coords) — the basis of the
        interior/exterior overlap split (reference ``MpiSection``,
        ``context.hpp:789-833``)."""
        ops = self.ops
        base_region = over if over is not None else self._interior_region()
        if part.is_scratch:
            # Evaluate over the (sub-)region expanded by the write-halo.
            for eq in part.eqs:
                self._cur_misc = eq.lhs.misc_vals()
                g = self.geoms[eq.lhs.var_name()]
                wh = self.ana.scratch_write_halo.get(g.name, {})
                region = {}
                for d in self.ana.domain_dims:
                    wl, wr = wh.get(d, (0, 0))
                    if d in g.domain_dims:
                        a, b = base_region[d]
                        region[d] = (a - wl, b + wr)
                    else:
                        region[d] = (0, 1)  # scratch lacks this dim? rare
                memo: Dict = {}
                val = self._eval(eq.rhs, region, t, state, computed,
                                 scratch_vals, memo)
                val = self._to_var_layout(
                    ops.asdtype(val, self.dtype), g, region)
                if eq.cond is not None:
                    mask = self._eval(eq.cond, region, t, state, computed,
                                      scratch_vals, memo)
                    mask = self._to_var_layout(mask, g, region)
                    old = scratch_vals.get(g.name)
                    base = old[0] if old else \
                        ops.full(val.shape, 0.0, self.dtype)
                    val = ops.where(mask, val, base)
                origin = {d: -region[d][0] for d in self.ana.domain_dims
                          if d in g.domain_dims}
                scratch_vals[g.name] = (val, origin)
            return

        region = base_region
        # One memo across the whole part: no eq in a part reads a var the
        # part writes (parts have no internal deps), so cached reads stay
        # valid and duplicated subtrees across equations trace once.
        # Exception: misc-index-as-value expressions evaluate differently
        # per equation (LHS binding), so such parts memoize per equation.
        from yask_tpu.compiler.expr import uses_misc_index
        part_misc = any(uses_misc_index(eq.rhs, eq.cond, eq.step_cond)
                        for eq in part.eqs)
        memo: Dict = {}
        for eq in part.eqs:
            if part_misc:
                memo = {}
            self._cur_misc = eq.lhs.misc_vals()
            name = eq.lhs.var_name()
            g = self.geoms[name]
            ring = state[name]
            base_arr = computed.get(name, ring[0])  # evicted slot is base
            val = self._eval(eq.rhs, region, t, state, computed,
                             scratch_vals, memo)
            val = self._to_var_layout(ops.asdtype(val, self.dtype), g, region)

            # Written-region index tuple in the var's own axis order.
            idxs = []
            misc = eq.lhs.misc_vals()
            for n, kind in g.axes:
                if kind == "misc":
                    idxs.append(misc[n] - g.misc_lo[n])
                else:
                    a, b = region[n]
                    idxs.append(slice(g.origin[n] + a, g.origin[n] + b))

            cond_mask = None
            if eq.cond is not None:
                cond_mask = self._eval(eq.cond, region, t, state, computed,
                                       scratch_vals, memo)
            if eq.step_cond is not None:
                sc = self._eval(eq.step_cond, region, t, state, computed,
                                scratch_vals, memo)
                cond_mask = sc if cond_mask is None else \
                    ops.logical("and", cond_mask, sc)
            if cond_mask is not None:
                old_val = base_arr[tuple(idxs)]
                mask = self._to_var_layout(cond_mask, g, region)
                val = ops.where(mask, val, old_val)

            computed[name] = ops.update(base_arr, tuple(idxs), val)

    def eval_stage(self, stage_idx: int, t, state, computed, scratch_vals,
                   over: Optional[Dict[str, Tuple[int, int]]] = None):
        """Evaluate one stage in place on (computed, scratch_vals);
        ``over`` restricts to a sub-region (overlap split)."""
        for part in self.ana.stages[stage_idx].parts:
            self._eval_part(part, t, state, computed, scratch_vals,
                            over=over)

    def step(self, state, t, halo_hook: Optional[Callable] = None):
        """Advance the solution by one step; returns the new state.

        ``halo_hook(stage_idx, state, computed)`` is called before each
        stage — the distributed runtime injects ghost-cell exchange there
        (the reference's between-stage ``exchange_halos``,
        ``context.cpp:438``).
        """
        computed: Dict[str, object] = {}
        scratch_vals: Dict[str, Tuple[object, Dict[str, int]]] = {}
        for si in range(len(self.ana.stages)):
            if halo_hook is not None:
                state, computed = halo_hook(si, state, computed)
            self.eval_stage(si, t, state, computed, scratch_vals)
        # Rotate rings.
        new_state: Dict[str, List[object]] = {}
        for name, ring in state.items():
            g = self.geoms[name]
            if name in computed:
                if g.has_step:
                    new_state[name] = list(ring[1:]) + [computed[name]]
                else:
                    new_state[name] = [computed[name]]
            else:
                new_state[name] = list(ring)
        return new_state


class CompiledSolution:
    """A solution lowered for TPU execution (what the reference's generated
    ``.so`` is: the thing ``yk_factory::new_solution`` instantiates).

    Holds the analysis and dtype; :meth:`plan` binds domain sizes/pads and
    returns a :class:`StepProgram`.
    """

    def __init__(self, soln, analysis: SolutionAnalysis,
                 dtype: Optional[object] = None):
        self.soln = soln
        self.ana = analysis
        if dtype is None:
            import numpy as np
            eb = soln.get_settings().elem_bytes
            try:
                import jax.numpy as jnp
                dtype = {2: jnp.bfloat16, 4: np.float32, 8: np.float64}[eb]
            except ImportError:  # pragma: no cover
                dtype = {2: np.float16, 4: np.float32, 8: np.float64}[eb]
        self.dtype = dtype

    def plan(self, sizes: IdxTuple, ops: Optional[ArrayOps] = None,
             extra_pad: Optional[Dict[str, Tuple[int, int]]] = None,
             rank_offset: Optional[Dict[str, int]] = None,
             global_sizes: Optional[IdxTuple] = None,
             pad_multiple: Optional[Dict[str, int]] = None,
             mosaic_align: bool = True) -> StepProgram:
        for d in self.ana.domain_dims:
            if not sizes.has_dim(d):
                raise YaskException(f"domain size for dim '{d}' not given")
        return StepProgram(self, sizes, extra_pad=extra_pad, ops=ops,
                           rank_offset=rank_offset, global_sizes=global_sizes,
                           pad_multiple=pad_multiple,
                           mosaic_align=mosaic_align)
