"""Stencil variables (the compiler-side ``yc_var``).

Counterpart of the reference's ``Var``/``Vars``/``yc_var_proxy``
(``src/compiler/lib/Var.hpp:45,354``, ``include/yask_compiler_api.hpp:1046``):
an N-D variable over step/domain/misc dims. Calling the var with index
expressions (``u(t+1, x, y, z)``) yields a :class:`VarPoint` access node.

Halo and lifespan bookkeeping recorded here is filled in by equation analysis
(``yask_tpu.compiler.analysis``), mirroring how the reference updates halos
per stage during ``calc_halos`` (``Eqs.cpp:1614``).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from yask_tpu.utils.exceptions import YaskException
from yask_tpu.compiler.expr import IndexExpr, IndexType, VarPoint


class Var:
    """A compiler-side stencil variable (``yc_var``)."""

    def __init__(self, name: str, dims: Sequence[IndexExpr], solution=None,
                 is_scratch: bool = False):
        if not name.isidentifier():
            raise YaskException(f"invalid var name '{name}'")
        seen = set()
        step_seen = False
        for d in dims:
            if not isinstance(d, IndexExpr):
                raise YaskException(
                    f"var '{name}' dim {d!r} is not an index created by "
                    "new_step_index/new_domain_index/new_misc_index")
            if d.name in seen:
                raise YaskException(f"var '{name}' repeats dim '{d.name}'")
            seen.add(d.name)
            if d.type == IndexType.STEP:
                if step_seen:
                    raise YaskException(
                        f"var '{name}' has more than one step dim")
                step_seen = True
        if is_scratch and step_seen:
            raise YaskException(
                f"scratch var '{name}' may not use a step dim "
                "(reference rule, Eqs.cpp LHS checks)")
        self._name = name
        self._dims: Tuple[IndexExpr, ...] = tuple(dims)
        self._soln = solution
        self._is_scratch = is_scratch

        # Filled by analysis (calc_halos / calc_lifespans analogs):
        # halo per domain dim: {dim: (left>=0, right>=0)}
        self.halo: Dict[str, Tuple[int, int]] = {
            d.name: (0, 0) for d in self._dims if d.type == IndexType.DOMAIN}
        # range of misc indices accessed: {dim: (min, max)}
        self.misc_range: Dict[str, Tuple[int, int]] = {
            d.name: (0, 0) for d in self._dims if d.type == IndexType.MISC}
        # step offsets read/written: used for step_alloc
        self._step_alloc: Optional[int] = None  # user override
        self.step_offsets_used: List[int] = []
        # per-step-offset max |domain offset| among reads (for write-back)
        self.step_read_halo: Dict[int, int] = {}
        self.is_read = False
        self.is_written = False

    # ---- identity --------------------------------------------------------

    def __deepcopy__(self, memo):
        # Vars are identities (storage declarations): clone_ast of any
        # expression referencing one keeps pointing at the same var.
        return self

    def get_name(self) -> str:
        return self._name

    def get_solution(self):
        return self._soln

    def is_scratch(self) -> bool:
        return self._is_scratch

    # ---- dims ------------------------------------------------------------

    def get_num_dims(self) -> int:
        return len(self._dims)

    def get_dims(self) -> Tuple[IndexExpr, ...]:
        return self._dims

    def get_dim_names(self) -> List[str]:
        return [d.name for d in self._dims]

    def step_dim(self) -> Optional[IndexExpr]:
        for d in self._dims:
            if d.type == IndexType.STEP:
                return d
        return None

    def domain_dim_names(self) -> List[str]:
        return [d.name for d in self._dims if d.type == IndexType.DOMAIN]

    def misc_dim_names(self) -> List[str]:
        return [d.name for d in self._dims if d.type == IndexType.MISC]

    # ---- access ----------------------------------------------------------

    def __call__(self, *args) -> VarPoint:
        return VarPoint(self, args)

    # ---- halo / alloc bookkeeping ---------------------------------------

    def update_halo(self, dim: str, offset: int) -> None:
        """Grow the halo to cover a read at ``offset`` in ``dim``
        (reference ``Var::update_halo``)."""
        left, right = self.halo[dim]
        if offset < 0:
            left = max(left, -offset)
        else:
            right = max(right, offset)
        self.halo[dim] = (left, right)

    def update_misc_range(self, dim: str, val: int) -> None:
        lo, hi = self.misc_range[dim]
        self.misc_range[dim] = (min(lo, val), max(hi, val))

    def get_halo_sizes(self) -> Dict[str, Tuple[int, int]]:
        return dict(self.halo)

    def max_halo(self) -> int:
        return max((max(l, r) for l, r in self.halo.values()), default=0)

    # ---- step allocation -------------------------------------------------

    def set_step_alloc_size(self, n: int) -> None:
        """Override #step slots kept live (``yc_var::set_step_alloc_size``)."""
        if n < 1:
            raise YaskException("step_alloc must be >= 1")
        self._step_alloc = n

    set_alloc_size = set_step_alloc_size   # v2 name

    def set_dynamic_step_alloc(self, enable: bool) -> None:
        """Accepted for parity (``yc_var::set_dynamic_step_alloc``):
        XLA's static shapes make every ring allocation fixed at prepare
        time, so the flag records intent only."""
        self._dynamic_step_alloc = bool(enable)

    def is_dynamic_step_alloc(self) -> bool:
        return getattr(self, "_dynamic_step_alloc", False)

    def set_prefetch_dist(self, dist: int) -> None:
        """Accepted for parity (``yc_var::set_prefetch_dist``): software
        prefetch is subsumed by the Pallas input-DMA double buffering
        (pipeline_dmas), which streams the next tile while computing."""
        self._prefetch_dist = int(dist)

    def get_prefetch_dist(self) -> int:
        return getattr(self, "_prefetch_dist", 0)

    def min_step_alloc_size(self) -> int:
        """The ring depth this var's step accesses actually NEED,
        ignoring any manual :meth:`set_step_alloc_size` override: the
        span of step offsets used, *minus one* when the extreme read
        offset carries no spatial halo — then its slot doubles as the
        write target, point-wise-safely (the reference's write-back
        optimization; for 2nd-order-in-time stencils like iso3dfd this
        is 2 buffers instead of 3).  The static checker compares a
        manual override against this floor (RING-DEPTH rule)."""
        if self.step_dim() is None:
            return 1
        if not self.step_offsets_used:
            return 2
        hi, lo = max(self.step_offsets_used), min(self.step_offsets_used)
        span = hi - lo + 1
        if span >= 2 and self.is_written:
            # The write sits at the +1 end (forward stepping) or the -1 end
            # (reverse); the extreme *read* offset is the opposite end.
            extreme = lo if hi >= 1 else hi
            if self.step_read_halo.get(extreme, None) == 0:
                span -= 1
        return max(span, 1)

    def get_step_alloc_size(self) -> int:
        """#step slots kept (reference lifespan calc, ``Eqs.cpp:1912``):
        the manual override when set, else :meth:`min_step_alloc_size`."""
        if self._step_alloc is not None:
            return self._step_alloc
        return self.min_step_alloc_size()

    def __repr__(self):
        kind = "scratch " if self._is_scratch else ""
        return (f"<{kind}Var {self._name}"
                f"({', '.join(self.get_dim_names())})>")


# The reference exposes vars to users through `yc_var_proxy`; here the var is
# directly callable, so the proxy is just an alias.
yc_var = Var
