"""Debug/text printers for solutions and expressions.

Counterpart of the reference's printer framework and debug formats
(``src/compiler/lib/Print.cpp``: ``PseudoPrinter``, ``DOTPrinter``;
selected by target in ``Solution.cpp:241-259``). The ``py-api`` printer is
the TPU analog of the reference's generated-code output: a self-contained
Python module that rebuilds the solution through the public DSL API.
"""

from __future__ import annotations

from typing import Dict, List

from yask_tpu.compiler.expr import (
    AddExpr,
    AndExpr,
    CompExpr,
    ConstExpr,
    DivExpr,
    EqualsExpr,
    Expr,
    FirstIndexExpr,
    FuncExpr,
    IndexExpr,
    LastIndexExpr,
    ModExpr,
    MultExpr,
    NegExpr,
    NotExpr,
    OrExpr,
    SubExpr,
    VarPoint,
)


# ---------------------------------------------------------------------------
# expression formatting
# ---------------------------------------------------------------------------


def _fmt_const(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def format_expr(e: Expr) -> str:
    """Render an expression as infix text (the pseudo-printer's expression
    syntax: ``u(t+1, x, y)``, offsets shown inline)."""
    if isinstance(e, ConstExpr):
        return _fmt_const(e.value)
    if isinstance(e, IndexExpr):
        return e.name
    if isinstance(e, FirstIndexExpr):
        return f"FIRST_INDEX({e.dim.name})"
    if isinstance(e, LastIndexExpr):
        return f"LAST_INDEX({e.dim.name})"
    if isinstance(e, VarPoint):
        args = []
        for d in e.var.get_dims():
            ofs = e.offsets[d.name]
            if d.type.value == "misc":
                args.append(str(ofs))
            elif ofs == 0:
                args.append(d.name)
            elif ofs > 0:
                args.append(f"{d.name}+{ofs}")
            else:
                args.append(f"{d.name}{ofs}")
        return f"{e.var_name()}({', '.join(args)})"
    if isinstance(e, NegExpr):
        return f"(-{format_expr(e.arg)})"
    if isinstance(e, AddExpr):
        return "(" + " + ".join(format_expr(a) for a in e.args) + ")"
    if isinstance(e, MultExpr):
        return "(" + " * ".join(format_expr(a) for a in e.args) + ")"
    if isinstance(e, SubExpr):
        return f"({format_expr(e.lhs)} - {format_expr(e.rhs)})"
    if isinstance(e, DivExpr):
        return f"({format_expr(e.lhs)} / {format_expr(e.rhs)})"
    if isinstance(e, ModExpr):
        return f"({format_expr(e.lhs)} % {format_expr(e.rhs)})"
    if isinstance(e, FuncExpr):
        return f"{e.name}({', '.join(format_expr(a) for a in e.args)})"
    if isinstance(e, CompExpr):
        return f"({format_expr(e.lhs)} {e.op} {format_expr(e.rhs)})"
    if isinstance(e, AndExpr):
        return f"({format_expr(e.lhs)} && {format_expr(e.rhs)})"
    if isinstance(e, OrExpr):
        return f"({format_expr(e.lhs)} || {format_expr(e.rhs)})"
    if isinstance(e, NotExpr):
        return f"(!{format_expr(e.arg)})"
    if isinstance(e, EqualsExpr):
        s = f"{format_expr(e.lhs)} EQUALS {format_expr(e.rhs)}"
        if e.cond is not None:
            s += f" IF_DOMAIN {format_expr(e.cond)}"
        if e.step_cond is not None:
            s += f" IF_STEP {format_expr(e.step_cond)}"
        return s
    return f"<{type(e).__name__}>"


# ---------------------------------------------------------------------------
# pseudo printer
# ---------------------------------------------------------------------------


def print_pseudo(soln, long: bool = False) -> str:
    """Human-readable solution listing (reference ``PseudoPrinter``; the
    ``long`` variant additionally expands analysis results per part/stage)."""
    ana = soln.analyze()
    out: List[str] = []
    out.append(f"// Solution '{soln.get_name()}' "
               f"({soln.get_num_equations()} equation(s)).")
    out.append(f"// Step dim: {soln.step_dim_name() or '(none)'}; "
               f"domain dims: {', '.join(soln.domain_dim_names())}.")
    for v in soln.get_vars():
        kind = "scratch var" if v.is_scratch() else "var"
        halo = ", ".join(f"{d}:[-{l},+{r}]" for d, (l, r) in v.halo.items())
        out.append(f"{kind} {v.get_name()}({', '.join(v.get_dim_names())}); "
                   f"// halo {halo or 'n/a'}; "
                   f"step-alloc {v.get_step_alloc_size()}")
    for i, stage in enumerate(ana.stages):
        out.append(f"\n//// Stage {i}:")
        for part in stage.parts:
            out.append(f"// Part '{part.name}' "
                       f"({len(part.eqs)} equation(s)):")
            for eq in part.eqs:
                out.append(format_expr(eq) + ";")
    if long:
        out.append("\n//// Analysis detail:")
        out.append(f"// step direction: {ana.step_dir:+d}")
        for part in ana.parts:
            deps = ", ".join(p.name for p in part.deps) or "(none)"
            out.append(f"// part '{part.name}' depends on: {deps}")
        c = ana.counters
        out.append(f"// est. scalar FP ops/pt: {c.num_ops}; "
                   f"reads/pt: {c.num_reads}; writes/pt: {c.num_writes}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# DOT printer
# ---------------------------------------------------------------------------


def print_dot(soln, lite: bool = True) -> str:
    """Graphviz rendering of equation/var dependencies (reference
    ``DOTPrinter``). ``lite`` shows var-level edges only; the full form adds
    one node per equation."""
    ana = soln.analyze()
    out = ["digraph \"" + soln.get_name() + "\" {", "  rankdir=LR;"]
    for v in soln.get_vars():
        shape = "box" if not v.is_scratch() else "ellipse"
        out.append(f'  "{v.get_name()}" [shape={shape}];')
    if lite:
        seen = set()
        for eq in soln.get_equations():
            from yask_tpu.compiler.expr import count_points
            lhs_var = eq.lhs.var_name()
            for p in count_points(eq.rhs):
                edge = (p.var_name(), lhs_var)
                if edge not in seen:
                    seen.add(edge)
                    out.append(f'  "{edge[0]}" -> "{edge[1]}";')
    else:
        for i, eq in enumerate(soln.get_equations()):
            from yask_tpu.compiler.expr import count_points
            eq_node = f"eq{i}"
            label = format_expr(eq.lhs)
            out.append(f'  "{eq_node}" [shape=plaintext, label="{label}"];')
            out.append(f'  "{eq_node}" -> "{eq.lhs.var_name()}";')
            for p in count_points(eq.rhs):
                out.append(f'  "{p.var_name()}" -> "{eq_node}";')
    out.append("}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# POV-Ray printer
# ---------------------------------------------------------------------------


def print_povray(soln) -> str:
    """3-D rendering of the stencil's read pattern as POV-Ray boxes
    (reference ``POVRayPrinter``): one unit cube per distinct read offset
    of the first equation's RHS, colored per var."""
    from yask_tpu.compiler.expr import count_points
    soln.analyze()
    eqs = soln.get_equations()
    out: List[str] = [
        "#include \"colors.inc\"",
        f"// stencil '{soln.get_name()}' read pattern",
        "camera { location <12, 10, -16> look_at <0, 0, 0> }",
        "light_source { <20, 30, -25> color White }",
        "background { color White }",
    ]
    palette = ["Red", "Blue", "Green", "Orange", "Violet", "Cyan",
               "Magenta", "Yellow"]
    var_color: Dict[str, str] = {}
    seen = set()
    for eq in eqs:
        for p in count_points(eq.rhs):
            offs = p.domain_offsets()
            dims = list(offs.keys())[:3]
            coord = tuple(offs[d] for d in dims) + (0,) * (3 - len(dims))
            key = (p.var_name(), coord)
            if key in seen:
                continue
            seen.add(key)
            color = var_color.setdefault(
                p.var_name(), palette[len(var_color) % len(palette)])
            x, y, z = coord
            out.append(
                f"box {{ <{x - 0.4}, {y - 0.4}, {z - 0.4}>, "
                f"<{x + 0.4}, {y + 0.4}, {z + 0.4}> "
                f"texture {{ pigment {{ color {color} }} }} }}"
                f" // {p.var_name()}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# Python-module printer (the TPU "codegen output")
# ---------------------------------------------------------------------------


def _expr_to_py(e: Expr, var_names: Dict[str, str]) -> str:
    """Emit Python DSL source rebuilding ``e``."""
    if isinstance(e, ConstExpr):
        return _fmt_const(e.value)
    if isinstance(e, IndexExpr):
        return e.name
    if isinstance(e, FirstIndexExpr):
        return f"nfac.new_first_domain_index({e.dim.name})"
    if isinstance(e, LastIndexExpr):
        return f"nfac.new_last_domain_index({e.dim.name})"
    if isinstance(e, VarPoint):
        args = []
        for d in e.var.get_dims():
            ofs = e.offsets[d.name]
            if d.type.value == "misc":
                args.append(str(ofs))
            elif ofs == 0:
                args.append(d.name)
            else:
                args.append(f"{d.name}{ofs:+d}")
        return f"{var_names[e.var_name()]}({', '.join(args)})"
    if isinstance(e, NegExpr):
        return f"(-{_expr_to_py(e.arg, var_names)})"
    if isinstance(e, AddExpr):
        return "(" + " + ".join(_expr_to_py(a, var_names) for a in e.args) + ")"
    if isinstance(e, MultExpr):
        return "(" + " * ".join(_expr_to_py(a, var_names) for a in e.args) + ")"
    if isinstance(e, SubExpr):
        return (f"({_expr_to_py(e.lhs, var_names)} - "
                f"{_expr_to_py(e.rhs, var_names)})")
    if isinstance(e, DivExpr):
        return (f"({_expr_to_py(e.lhs, var_names)} / "
                f"{_expr_to_py(e.rhs, var_names)})")
    if isinstance(e, ModExpr):
        return (f"({_expr_to_py(e.lhs, var_names)} % "
                f"{_expr_to_py(e.rhs, var_names)})")
    if isinstance(e, FuncExpr):
        args = ", ".join(_expr_to_py(a, var_names) for a in e.args)
        return f"expr.FuncExpr('{e.name}', ({args},))"
    if isinstance(e, CompExpr):
        return (f"({_expr_to_py(e.lhs, var_names)} {e.op} "
                f"{_expr_to_py(e.rhs, var_names)})")
    if isinstance(e, AndExpr):
        return (f"({_expr_to_py(e.lhs, var_names)} & "
                f"{_expr_to_py(e.rhs, var_names)})")
    if isinstance(e, OrExpr):
        return (f"({_expr_to_py(e.lhs, var_names)} | "
                f"{_expr_to_py(e.rhs, var_names)})")
    if isinstance(e, NotExpr):
        return f"(~{_expr_to_py(e.arg, var_names)})"
    raise AssertionError(type(e))


def print_py_module(soln) -> str:
    """Emit a self-contained Python module that rebuilds this solution via
    the public DSL API and returns it from ``get_solution()`` — the TPU
    analog of the reference compiler emitting ``yask_stencil_code.hpp``
    (``YaskKernel.cpp:72-103``): an artifact the kernel runtime consumes."""
    soln.analyze()
    lines: List[str] = []
    a = lines.append
    a('"""Generated by yask_tpu — rebuilds stencil solution '
      f"'{soln.get_name()}'.\"\"\"")
    a("from yask_tpu.compiler import expr")
    a("from yask_tpu.compiler.solution import yc_factory")
    a("from yask_tpu.compiler.node_api import yc_node_factory")
    a("")
    a("")
    a("def get_solution():")
    a(f"    soln = yc_factory().new_solution({soln.get_name()!r})")
    a("    nfac = yc_node_factory()")
    idxs = soln.get_indices()
    for name, idx in idxs.items():
        a(f"    {name} = soln.new_{idx.type.value}_index({name!r})")
    var_names: Dict[str, str] = {}
    for v in soln.get_vars():
        py = f"v_{v.get_name()}"
        var_names[v.get_name()] = py
        dims = ", ".join(d.name for d in v.get_dims())
        maker = "new_scratch_var" if v.is_scratch() else "new_var"
        a(f"    {py} = soln.{maker}({v.get_name()!r}, [{dims}])")
    for eq in soln.get_equations():
        lhs = _expr_to_py(eq.lhs, var_names)
        rhs = _expr_to_py(eq.rhs, var_names)
        cond = _expr_to_py(eq.cond, var_names) if eq.cond is not None else "None"
        scond = (_expr_to_py(eq.step_cond, var_names)
                 if eq.step_cond is not None else "None")
        a(f"    soln.add_eq({lhs}, {rhs}, {cond}, {scond})")
    a("    return soln")
    return "\n".join(lines) + "\n"
